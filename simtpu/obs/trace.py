"""Low-overhead span tracer with Chrome-trace-event (Perfetto) export.

The tracer answers the question the phase timings cannot: WHERE inside a
phase the wall-clock went — which chunk dispatches, which AOT compiles on
which pool threads, which fault-sweep blocks, which checkpoint writes.
Every layer of the engine opens spans through the one context manager
here:

    from simtpu.obs import span
    with span("scan.chunk", pods=int(b - a)):
        ...

Design constraints (measured by `make bench-obs`):
- **Disabled = free.** `span()` returns one shared no-op singleton when
  tracing is off — no span object, no event, no lock; the only cost is
  the enabled-flag check (and the caller's kwargs, which are empty on
  the hot paths that matter).  The bench pins ~0% overhead off and <3%
  on, against a warm bulk placement.
- **Bounded memory.** Events land in a fixed-capacity ring buffer
  (default 65536); a long run overwrites its oldest spans instead of
  growing without bound.  The flight recorder (obs/flight.py) snapshots
  the last N on failure for exactly this reason.
- **Thread-safe.** The AOT precompile pool opens compile spans from
  worker threads concurrently with the dispatch loop's chunk spans; the
  ring index is bumped under one lock at span EXIT only (one lock
  acquisition per completed span, nothing on entry).

Export is the Chrome trace-event JSON object format — `{"traceEvents":
[...]}` with complete ("ph": "X") events — loadable directly in Perfetto
(https://ui.perfetto.dev) or chrome://tracing.  Timestamps are
microseconds from an arbitrary per-process origin, durations are
microseconds, `tid` is the Python thread ident (named via metadata
events).  `simtpu apply --trace FILE` writes one; SIMTPU_TRACE=1 arms
in-memory tracing (SIMTPU_TRACE=<path> also exports at process exit —
the hook tools/run_tests.py uses for its slowest-spans summary).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 65536

_LOCK = threading.Lock()
_ENABLED = False
_RING: List[Optional[tuple]] = []
_COUNT = 0  # total events ever recorded (ring index = _COUNT % capacity)
_DROPPED = 0  # events overwritten after wraparound
_T0 = time.perf_counter_ns()  # per-process trace origin
_TLS = threading.local()  # per-thread span depth (nesting attribute)

#: set by obs/profile.py while a jax.profiler capture is live: a callable
#: name -> context manager (jax.profiler.TraceAnnotation) entered by every
#: span so the device profile and the span trace share one vocabulary
_ANNOTATION_FACTORY = None


class _NoopSpan:
    """The shared disabled-path span: one instance for the whole process,
    allocation-free to enter/exit (the zero-overhead contract)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):  # noqa: ARG002 - signature parity with _Span
        return self


_NOOP = _NoopSpan()


class _Span:
    """One live span: records (name, start, duration, thread, depth,
    attrs) into the ring on exit."""

    __slots__ = ("name", "attrs", "_t0", "_depth", "_ann")

    def __init__(self, name: str, attrs: Optional[dict]):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs):
        """Attach/override attributes mid-span (e.g. bytes fetched, known
        only after the body ran)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)
        return self

    def __enter__(self):
        depth = getattr(_TLS, "depth", 0)
        _TLS.depth = depth + 1
        self._depth = depth
        ann = None
        factory = _ANNOTATION_FACTORY
        if factory is not None:
            try:
                ann = factory(self.name)
                ann.__enter__()
            except Exception:  # noqa: BLE001 - profiling must never break the run
                ann = None
        self._ann = ann
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter_ns()
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:  # noqa: BLE001
                pass
        _TLS.depth = self._depth
        global _COUNT, _DROPPED
        event = (
            self.name,
            (self._t0 - _T0) // 1000,  # ts, us
            max((t1 - self._t0) // 1000, 1),  # dur, us (Perfetto drops 0)
            threading.get_ident(),
            self._depth,
            self.attrs,
        )
        with _LOCK:
            if _ENABLED:  # disabled mid-span: drop, buffers already cleared
                cap = len(_RING)
                if _COUNT >= cap:
                    _DROPPED += 1
                _RING[_COUNT % cap] = event
                _COUNT += 1
        return False


def span(name: str, **attrs):
    """Open a span named `name` (a context manager).  With tracing off
    this is the shared no-op singleton — callers never pay for tracing
    they didn't enable.  Attributes must be JSON-serializable; hot-path
    callers should pass cheap scalars (pod counts, byte totals)."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, attrs or None)


def instant(name: str, **attrs) -> None:
    """Record a zero-duration point event (e.g. a wavefront rollback)."""
    if not _ENABLED:
        return
    global _COUNT, _DROPPED
    event = (
        name,
        (time.perf_counter_ns() - _T0) // 1000,
        0,
        threading.get_ident(),
        getattr(_TLS, "depth", 0),
        attrs or None,
    )
    with _LOCK:
        if _ENABLED:
            cap = len(_RING)
            if _COUNT >= cap:
                _DROPPED += 1
            _RING[_COUNT % cap] = event
            _COUNT += 1


def enabled() -> bool:
    return _ENABLED


def enable(capacity: int = DEFAULT_CAPACITY) -> None:
    """Arm the tracer with a fresh ring of `capacity` events (re-enabling
    clears prior events — one trace per arming)."""
    global _ENABLED, _RING, _COUNT, _DROPPED
    if capacity < 1:
        raise ValueError(f"trace capacity must be >= 1, got {capacity}")
    with _LOCK:
        _RING = [None] * capacity
        _COUNT = 0
        _DROPPED = 0
        _ENABLED = True


def disable() -> None:
    """Disarm and drop the buffered events."""
    global _ENABLED, _RING, _COUNT, _DROPPED
    with _LOCK:
        _ENABLED = False
        _RING = []
        _COUNT = 0
        _DROPPED = 0


def events() -> List[tuple]:
    """Chronological snapshot of the buffered events — oldest surviving
    first (wraparound drops the oldest).  Tuples of (name, ts_us, dur_us,
    tid, depth, attrs)."""
    with _LOCK:
        if not _RING:
            return []
        cap = len(_RING)
        if _COUNT <= cap:
            return [e for e in _RING[:_COUNT] if e is not None]
        head = _COUNT % cap
        return [e for e in _RING[head:] + _RING[:head] if e is not None]


def dropped() -> int:
    """Events overwritten by ring wraparound since enable()."""
    return _DROPPED


def to_chrome_trace(last: Optional[int] = None) -> Dict[str, object]:
    """The buffered spans as a Chrome trace-event JSON object (Perfetto
    loads it directly).  `last` keeps only the newest N events (the
    flight-recorder view)."""
    evs = events()
    if last is not None:
        evs = evs[-last:]
    pid = os.getpid()
    trace_events: List[dict] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "simtpu"},
        }
    ]
    tids = []
    for name, ts, dur, tid, depth, attrs in evs:
        args = {"depth": depth}
        if attrs:
            args.update(attrs)
        if dur == 0:
            trace_events.append(
                {
                    "name": name,
                    "ph": "i",
                    "s": "t",
                    "ts": ts,
                    "pid": pid,
                    "tid": tid,
                    "cat": "simtpu",
                    "args": args,
                }
            )
        else:
            trace_events.append(
                {
                    "name": name,
                    "ph": "X",
                    "ts": ts,
                    "dur": dur,
                    "pid": pid,
                    "tid": tid,
                    "cat": "simtpu",
                    "args": args,
                }
            )
        if tid not in tids:
            tids.append(tid)
    for i, tid in enumerate(tids):
        trace_events.append(
            {
                "name": "thread_name",
                "ph": "M",
                "pid": pid,
                "tid": tid,
                "args": {"name": "main" if i == 0 else f"thread-{i}"},
            }
        )
    return {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"dropped_events": _DROPPED},
    }


def export_trace(path: str, last: Optional[int] = None) -> str:
    """Write the Chrome trace JSON to `path` (parent dirs created) and
    return the path."""
    doc = to_chrome_trace(last=last)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def span_summary(top: int = 10) -> List[dict]:
    """Top-N span names by total wall-clock: [{"name", "count",
    "total_s", "max_s"}] — the run_tests / flight-recorder digest."""
    agg: Dict[str, List[float]] = {}
    for name, _, dur, _, _, _ in events():
        row = agg.setdefault(name, [0, 0.0, 0.0])
        row[0] += 1
        row[1] += dur / 1e6
        row[2] = max(row[2], dur / 1e6)
    rows = [
        {
            "name": name,
            "count": int(c),
            "total_s": round(tot, 6),
            "max_s": round(mx, 6),
        }
        for name, (c, tot, mx) in agg.items()
    ]
    rows.sort(key=lambda r: -r["total_s"])
    return rows[:top]


def init_from_env() -> None:
    """SIMTPU_TRACE activation, read once at `import simtpu`:

    - unset / "0"  — tracing stays off (the default; spans are no-ops)
    - "1"          — in-memory tracing on (consumers export explicitly)
    - anything else — treated as an output PATH: tracing on, and the
      buffered trace exports there at interpreter exit (atexit) — the
      hook tools/run_tests.py uses to collect per-module traces

    Capacity override: SIMTPU_TRACE_CAPACITY (events, default 65536)."""
    raw = os.environ.get("SIMTPU_TRACE", "")
    if raw in ("", "0"):
        return
    cap = int(os.environ.get("SIMTPU_TRACE_CAPACITY", DEFAULT_CAPACITY))
    enable(capacity=cap)
    if raw != "1":
        import atexit

        atexit.register(export_trace, raw)
