"""simtpu.obs — the unified observability layer (ISSUE 8).

One subsystem, four pieces, zero dependencies beyond the stdlib:

- `obs.trace`   — ring-buffer span tracer, Perfetto (Chrome trace-event)
  export; `span("name", **attrs)` is the one instrumentation primitive,
  compiled to a shared no-op when tracing is off.
- `obs.metrics` — the process-wide typed metrics registry every legacy
  counter family (fetch / state gauge / backoff / wavefront / jit-trace /
  audit) now lives in; legacy snapshot functions remain as alias views.
- `obs.profile` — `--profile DIR` jax.profiler capture whose
  TraceAnnotation names match the span vocabulary.
- `obs.flight`  — failure flight recorder: last-N spans + metrics
  snapshot + engine fingerprint dumped on exit 3/4/OOM-exhaustion.

Import cost matters: `simtpu/__init__.py` arms the tracer from
SIMTPU_TRACE at import, so this package must not import jax (obs.profile
defers it)."""

from .metrics import REGISTRY, SCHEMA_VERSION, MetricsRegistry
from .trace import (
    disable,
    enable,
    enabled,
    events,
    export_trace,
    init_from_env,
    instant,
    span,
    span_summary,
    to_chrome_trace,
)

__all__ = [
    "REGISTRY",
    "SCHEMA_VERSION",
    "MetricsRegistry",
    "disable",
    "enable",
    "enabled",
    "events",
    "export_trace",
    "init_from_env",
    "instant",
    "span",
    "span_summary",
    "to_chrome_trace",
]
