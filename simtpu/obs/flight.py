"""Failure flight recorder (ISSUE 8): a post-mortem bundle on bad exits.

When a plan ends on one of the structured failure exits — partial result
(deadline/SIGINT, exit 3), audit divergence (exit 4), or an OOM-backoff
that exhausted its halving budget and escaped — the CLI dumps ONE JSON
bundle capturing what the process knew at that moment:

- the last-N buffered spans (Chrome trace-event format, loadable in
  Perfetto like a full --trace file) and the span summary digest,
- a full metrics-registry snapshot (every counter family),
- the engine-config fingerprint of the run (the PlanResult.engine block
  when a plan exists, else the resolved CLI options),
- version/schema stamps and the triggering reason.

Location: "next to the checkpoint dir" — the parent directory of
--checkpoint DIR when one was given (the operator already looks there
for the durable-execution artifacts), else the working directory.
SIMTPU_FLIGHT_DIR overrides; SIMTPU_FLIGHT=0 disables dumping entirely.
Writes are atomic (tmp + rename, the durable/checkpoint.py discipline)
and total failures are swallowed into one warning: the flight recorder
must never turn a structured exit into a traceback.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, Optional

from .metrics import REGISTRY, SCHEMA_VERSION
from .trace import dropped, span_summary, to_chrome_trace

log = logging.getLogger("simtpu.obs")

#: how many of the newest spans ride the bundle (the ring may hold 64k)
FLIGHT_SPANS = 256

FLIGHT_FORMAT = "simtpu-flight-v1"


def flight_enabled() -> bool:
    return os.environ.get("SIMTPU_FLIGHT", "1") != "0"


def flight_dir(checkpoint: str = "") -> str:
    """Where bundles land: SIMTPU_FLIGHT_DIR > the checkpoint dir's
    parent > the working directory."""
    env = os.environ.get("SIMTPU_FLIGHT_DIR", "")
    if env:
        return env
    if checkpoint:
        parent = os.path.dirname(os.path.abspath(checkpoint.rstrip(os.sep)))
        return parent or "."
    return "."


def flight_bundle(
    reason: str,
    exit_code: int,
    engine: Optional[Dict[str, object]] = None,
    extra: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Assemble the bundle document (pure; `dump_flight` writes it)."""
    from .. import __version__

    doc: Dict[str, object] = {
        "format": FLIGHT_FORMAT,
        "version": __version__,
        "schema_version": SCHEMA_VERSION,
        "time_unix": time.time(),
        "pid": os.getpid(),
        "reason": reason,
        "exit_code": int(exit_code),
        "metrics": REGISTRY.snapshot(),
        "span_summary": span_summary(top=10),
        "spans": to_chrome_trace(last=FLIGHT_SPANS),
        "spans_dropped": dropped(),
        "engine": engine or {},
    }
    if extra:
        doc.update(extra)
    return doc


def dump_flight(
    reason: str,
    exit_code: int,
    checkpoint: str = "",
    engine: Optional[Dict[str, object]] = None,
    extra: Optional[Dict[str, object]] = None,
) -> Optional[str]:
    """Write one flight bundle and return its path (None when disabled or
    the write failed — the failure is a warning, never an exception)."""
    if not flight_enabled():
        return None
    try:
        out_dir = flight_dir(checkpoint)
        os.makedirs(out_dir, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S")
        path = os.path.join(
            out_dir, f"simtpu-flight-{stamp}-{os.getpid()}.json"
        )
        doc = flight_bundle(reason, exit_code, engine=engine, extra=extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        log.warning(
            "flight recorder: %s (exit %d) — bundle at %s",
            reason, exit_code, path,
        )
        return path
    except Exception as exc:  # noqa: BLE001 - never worsen a failing exit
        log.warning(
            "flight recorder failed (%s: %s); no bundle written",
            type(exc).__name__, exc,
        )
        return None
