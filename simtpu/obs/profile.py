"""JAX profiler integration (ISSUE 8): `--profile DIR` device traces whose
annotation vocabulary matches the span tracer's.

`jax.profiler.trace(DIR)` captures the XLA-level timeline (device kernels,
host callbacks, transfers) into a TensorBoard-loadable log dir.  On its
own that timeline names HLO modules, not simtpu phases; the bridge here
makes every `obs.span(...)` opened while a capture is live ALSO emit a
`jax.profiler.TraceAnnotation` with the same name, so the device profile
and the Perfetto span trace line up on one vocabulary ("scan.chunk",
"plan.probes", "aot.compile", ...).

Entry points:
- `profile_capture(dir)` — context manager: starts the jax profiler
  capture, arms the span tracer if it was off (annotations ride spans),
  installs the annotation bridge, and tears all of it down on exit.
  `dir=None/""` is a no-op nullcontext, so call sites stay unconditional.
- CLI: `simtpu apply/resilience/fuzz --profile DIR` (SIMTPU_PROFILE=DIR
  is the env equivalent — note this REPLACES the pre-ISSUE-8 meaning of
  SIMTPU_TRACE, which now arms the span tracer).

The import of jax is deferred into the context manager: `simtpu.obs` must
stay importable (and the tracer usable) in tooling that never touches
jax, e.g. tools/run_tests.py's trace aggregation.
"""

from __future__ import annotations

import contextlib
import logging

from . import trace as _trace

log = logging.getLogger("simtpu.obs")


@contextlib.contextmanager
def profile_capture(log_dir: str):
    """Capture a jax.profiler trace under `log_dir` for the body's
    duration, with span-named TraceAnnotations.  Empty/None dir = no-op.
    A profiler that fails to start (unsupported backend, dir not
    writable) logs ONE warning and runs the body unprofiled — profiling
    must never take the run down."""
    if not log_dir:
        yield False
        return
    try:
        import jax
    except Exception as exc:  # noqa: BLE001 - jax-free tooling contexts
        log.warning("--profile ignored (jax unavailable: %s)", exc)
        yield False
        return
    was_tracing = _trace.enabled()
    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception as exc:  # noqa: BLE001 - loud no-op, by contract
        log.warning(
            "jax profiler capture under %r failed to start (%s: %s); "
            "the run continues unprofiled",
            log_dir, type(exc).__name__, exc,
        )
    if started:
        if not was_tracing:
            # annotations ride spans — a profile without the span tracer
            # armed would capture an unannotated timeline
            _trace.enable()
        _trace._ANNOTATION_FACTORY = jax.profiler.TraceAnnotation
    try:
        yield started
    finally:
        if started:
            _trace._ANNOTATION_FACTORY = None
            try:
                jax.profiler.stop_trace()
            except Exception as exc:  # noqa: BLE001
                log.warning("jax profiler stop failed: %s", exc)
            if not was_tracing:
                _trace.disable()
