"""Open-Local storage kernels: LVM + exclusive-device fit, plan, and score.

Vectorized over nodes; the per-PVC loops are short static unrolls (a pod has
a handful of volume claims). Semantics mirror the vendored open-local algo:

- LVM named-VG fit and binpack placement of unnamed PVCs into the
  smallest-free VG that fits (`vendor/.../algo/common.go:59-144,511-560`)
- exclusive devices: per media class, PVCs ascending take the smallest free
  device with enough capacity (`common.go:290-345,394-446`)
- binpack scores: LVM = mean over used VGs of pod-usage/capacity × 10;
  device = mean over units of requested/capacity × 10 (`common.go:660-692,
  753-762`, MaxScore=10, binpack strategy default)
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

MAX_LOCAL_SCORE = 10.0
_BIG = 3.4e38  # finite stand-in for +inf (module-level jnp would init the backend at import)


def lvm_plan(
    vg_free: jnp.ndarray,  # [N, V] capacity - requested (current)
    vg_name_id: jnp.ndarray,  # [N, V] interned VG name, -1 pad
    sizes: jnp.ndarray,  # [L] pvc sizes, 0 = padding
    vg_ids: jnp.ndarray,  # [L] -1 unnamed, -2 missing VG, >=0 named
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (fits [N], alloc [N, V]) — the pod's LVM allocation per node."""
    n, v = vg_free.shape
    n_claims = sizes.shape[0]
    exists = vg_name_id >= 0
    has_any_vg = jnp.any(exists, axis=1)
    fits = jnp.ones(n, bool)
    alloc = jnp.zeros_like(vg_free)
    free = vg_free
    for i in range(n_claims):
        size, vid = sizes[i], vg_ids[i]
        active = size > 0
        named = vid >= 0
        # named path: the VG must exist on the node and have room
        slot_named = exists & (vg_name_id == vid)  # [N, V]
        has_named = jnp.any(slot_named, axis=1)
        # unnamed path: binpack — smallest free VG that still fits
        eligible = exists & (free >= size)
        key = jnp.where(eligible, free, _BIG)
        slot_binpack = jnp.zeros((n, v), bool).at[
            jnp.arange(n), jnp.argmin(key, axis=1)
        ].set(jnp.any(eligible, axis=1))
        slot = jnp.where(named, slot_named, slot_binpack)
        room = jnp.any(slot & (free >= size), axis=1)
        ok = jnp.where(
            named, has_named & room, jnp.any(eligible, axis=1)
        ) & (vid != -2) & has_any_vg
        take = slot & (free >= size)
        # named VG may match one slot only; guard double-count anyway
        upd = jnp.where(active & ok[:, None] & take, size, 0.0)
        alloc = alloc + upd
        free = free - upd
        fits = fits & jnp.where(active, ok, True)
    return fits, alloc


def device_plan(
    sdev_free: jnp.ndarray,  # [N, SD] bool — device exists and unallocated
    sdev_cap: jnp.ndarray,  # [N, SD]
    sdev_media: jnp.ndarray,  # [N, SD] media code (0 none)
    sizes: jnp.ndarray,  # [K] ascending per media class, 0 padding
    medias: jnp.ndarray,  # [K] media code per pvc
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (fits [N], take [N, SD] bool, tightness [N]) where tightness is
    Σ requested/allocated over assigned devices (for ScoreDevice)."""
    n, sd = sdev_cap.shape
    k = sizes.shape[0]
    fits = jnp.ones(n, bool)
    take = jnp.zeros((n, sd), bool)
    free = sdev_free
    tightness = jnp.zeros(n, jnp.float32)
    for i in range(k):
        size, media = sizes[i], medias[i]
        active = size > 0
        eligible = free & (sdev_media == media) & (sdev_cap >= size)
        key = jnp.where(eligible, sdev_cap, _BIG)
        choice = jnp.argmin(key, axis=1)  # smallest adequate device
        found = jnp.any(eligible, axis=1)
        sel = jnp.zeros((n, sd), bool).at[jnp.arange(n), choice].set(found)
        sel = sel & active
        take = take | sel
        free = free & ~sel
        cap_chosen = jnp.sum(jnp.where(sel, sdev_cap, 0.0), axis=1)
        tightness = tightness + jnp.where(
            found & active, size / jnp.maximum(cap_chosen, 1e-30), 0.0
        )
        fits = fits & jnp.where(active, found, True)
    return fits, take, tightness


def open_local_score(
    alloc: jnp.ndarray,  # [N, V] pod's LVM allocation (from lvm_plan)
    vg_cap: jnp.ndarray,  # [N, V]
    dev_tightness: jnp.ndarray,  # [N] Σ req/cap over assigned devices
    n_lvm: jnp.ndarray,  # scalar — number of LVM PVCs (for zero check)
    n_dev: jnp.ndarray,  # scalar — number of device PVCs
) -> jnp.ndarray:
    """LocalPlugin.Score raw value (`plugin/open-local.go:93-137`): ScoreLVM +
    ScoreDevice, each int-truncated in the reference; we keep floats."""
    used = alloc > 0
    per_vg = jnp.where(used, alloc / jnp.maximum(vg_cap, 1e-30), 0.0)
    vg_count = jnp.sum(used, axis=1)
    lvm_score = jnp.where(
        (n_lvm > 0) & (vg_count > 0),
        jnp.sum(per_vg, axis=1) / jnp.maximum(vg_count, 1) * MAX_LOCAL_SCORE,
        0.0,
    )
    dev_score = jnp.where(
        n_dev > 0, dev_tightness / jnp.maximum(n_dev, 1) * MAX_LOCAL_SCORE, 0.0
    )
    return lvm_score + dev_score
