"""GPU-share kernels: per-device GPU-memory fit and device assignment.

Mirrors `GpuNodeInfo.AllocateGpuId` (`vendor/github.com/alibaba/open-gpu-share/
pkg/cache/gpunodeinfo.go:231-291`):

- 1-GPU pods take the tightest-fitting device (min idle memory ≥ request,
  lowest index on ties — the Go loop uses strict `<`)
- multi-GPU pods greedily stack shares device-by-device in index order; one
  device may host several of the requested GPU shares
  (`gpunodeinfo.go:271-288` two-pointer walk)

plus the node-level total check from `GpuSharePlugin.Filter`
(`pkg/simulator/plugin/open-gpu-share.go:51-81`).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

_BIG = 3.4e38  # finite stand-in for +inf (module-level jnp would init the backend at import)


def gpu_plan(
    gpu_free: jnp.ndarray,  # [N, GD] free memory per device
    dev_exists: jnp.ndarray,  # [N, GD] bool
    gpu_total: jnp.ndarray,  # [N] node total GPU memory (static capacity)
    mem: jnp.ndarray,  # scalar — per-GPU memory request
    count: jnp.ndarray,  # scalar — number of GPU shares requested
    preset: jnp.ndarray = None,  # [GD] shares from an existing gpu-index anno
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (fits [N], shares [N, GD]) — shares = how many of the pod's GPU
    shares land on each device. Non-GPU pods fit everywhere with zero shares.

    A non-empty `preset` mirrors AllocateGpuId's annotation short-circuit
    (gpunodeinfo.go:247-253): the recorded assignment is honored verbatim
    without re-checking per-device memory.
    """
    n, gd = gpu_free.shape
    # Filter triggers on mem > 0 alone (open-gpu-share.go:53-57); a pod with
    # gpu-mem but no/zero gpu-count then fails AllocateGpuId on every node
    # (gpunodeinfo.go:236-240) — valid_req captures that.
    is_gpu_pod = mem > 0
    valid_req = count > 0

    free = jnp.where(dev_exists, gpu_free, -1.0)
    # capacity in shares per device
    per_dev = jnp.where(free >= mem, jnp.floor(free / jnp.maximum(mem, 1e-30)), 0.0)

    # multi-GPU greedy: fill devices in index order (two-pointer walk)
    cum = jnp.cumsum(per_dev, axis=1)
    prev = cum - per_dev
    greedy = jnp.clip(jnp.minimum(cum, count) - prev, 0.0, per_dev)

    # 1-GPU tightest fit: min free among devices that fit, lowest index tie
    fit1 = free >= mem
    key = jnp.where(fit1, free, _BIG)
    tight_idx = jnp.argmin(key, axis=1)
    tight = jnp.zeros((n, gd)).at[jnp.arange(n), tight_idx].set(
        jnp.where(jnp.any(fit1, axis=1), 1.0, 0.0)
    )

    shares = jnp.where(count == 1, tight, greedy)
    enough = jnp.sum(shares, axis=1) >= count
    node_total_ok = gpu_total >= mem  # Filter's node-level pre-check
    has_dev = jnp.any(dev_exists, axis=1)
    fits = jnp.where(is_gpu_pod, node_total_ok & has_dev & valid_req & enough, True)
    shares = jnp.where(is_gpu_pod & fits[:, None], shares, 0.0)
    if preset is not None:
        has_preset = jnp.sum(preset) > 0
        preset_fits = jnp.where(
            is_gpu_pod, node_total_ok & has_dev & valid_req, True
        )
        fits = jnp.where(has_preset, preset_fits, fits)
        shares = jnp.where(
            has_preset,
            jnp.where(
                (is_gpu_pod & preset_fits)[:, None],
                jnp.broadcast_to(preset, (n, gd)),
                0.0,
            ),
            shares,
        )
    return fits, shares
