"""Score kernels: each returns a float score vector over all nodes.

One kernel per score plugin active in the reference's profile — the default
algorithm provider (`vendor/.../algorithmprovider/registry.go:101-145`) plus
the Simon plugin (`pkg/simulator/plugin/simon.go:44-100`). Weights follow the
registry: LeastAllocated 1, BalancedAllocation 1, NodeAffinity 1,
TaintToleration 1, InterPodAffinity 1, Simon 1 (extension scores).

Normalization mirrors each plugin's NormalizeScore; scores are computed over
the full node axis but normalized over the feasible mask only, exactly like
`prioritizeNodes` running on the filtered list (`core/generic_scheduler.go:470`).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.tensorize import RES_CPU, RES_MEMORY

MAX_NODE_SCORE = 100.0


def minmax_normalize(score: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Min-max to [0, 100] over feasible nodes (SimonPlugin.NormalizeScore,
    `plugin/simon.go:76-100`; same default for NodeAffinity)."""
    big = jnp.float32(3.4e38)
    lo = jnp.min(jnp.where(mask, score, big))
    hi = jnp.max(jnp.where(mask, score, -big))
    rng = hi - lo
    return jnp.where(rng > 0, (score - lo) * MAX_NODE_SCORE / jnp.maximum(rng, 1e-30), 0.0)


def maxabs_normalize(score: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Scale by max |score| to [-100, 100] (InterPodAffinity NormalizeScore)."""
    m = jnp.max(jnp.where(mask, jnp.abs(score), 0.0))
    return jnp.where(m > 0, score * MAX_NODE_SCORE / jnp.maximum(m, 1e-30), 0.0)


def least_allocated(
    free: jnp.ndarray, alloc: jnp.ndarray, req: jnp.ndarray
) -> jnp.ndarray:
    """NodeResourcesLeastAllocated over cpu+memory
    (`plugins/noderesources/least_allocated.go`): mean of free-fraction × 100
    after placing the pod."""
    cols = jnp.array([RES_CPU, RES_MEMORY])
    fa = free[:, cols] - req[cols]  # [N, 2] free after placement
    al = alloc[:, cols]
    frac = jnp.where(al > 0, jnp.clip(fa, 0.0) / jnp.maximum(al, 1e-30), 0.0)
    return jnp.mean(frac, axis=-1) * MAX_NODE_SCORE


def balanced_allocation(
    free: jnp.ndarray, alloc: jnp.ndarray, req: jnp.ndarray
) -> jnp.ndarray:
    """NodeResourcesBalancedAllocation (`plugins/noderesources/
    balanced_allocation.go`, two-resource form): 100 - |cpuFrac - memFrac|·100."""
    cols = jnp.array([RES_CPU, RES_MEMORY])
    used_after = alloc[:, cols] - free[:, cols] + req[cols]
    frac = jnp.where(
        alloc[:, cols] > 0, used_after / jnp.maximum(alloc[:, cols], 1e-30), 1.0
    )
    return (1.0 - jnp.abs(frac[:, 0] - frac[:, 1])) * MAX_NODE_SCORE


def simon_share(alloc: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """Simon plugin raw score (`plugin/simon.go:44-67`): dominant share of the
    pod request against (static allocatable − request), per node, ×100.

    Uses the *static* allocatable, not remaining free — the fake-client node
    object never shrinks as pods bind, and the plugin reads it directly.
    """
    denom = alloc - req[None, :]  # [N, R]
    share = jnp.where(
        denom == 0,
        jnp.where(req[None, :] == 0, 0.0, 1.0),
        req[None, :] / jnp.where(denom == 0, 1.0, denom),
    )
    # only resources the node allocates participate; Go's `share > res` fold
    # starts at 0 so negatives never win
    share = jnp.where(alloc > 0, share, 0.0)
    return jnp.clip(jnp.max(share, axis=-1), 0.0) * MAX_NODE_SCORE


def taint_toleration_score(intolerable_cnt: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """TaintToleration score (`plugins/tainttoleration`): fewer intolerable
    PreferNoSchedule taints → higher, reverse-normalized to [0, 100]."""
    hi = jnp.max(jnp.where(mask, intolerable_cnt, 0.0))
    return jnp.where(
        hi > 0,
        MAX_NODE_SCORE * (1.0 - intolerable_cnt / jnp.maximum(hi, 1e-30)),
        MAX_NODE_SCORE,
    )


def spread_score_from_raw(raw: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """The inverse-min-max of `topology_spread_score` applied to an already
    summed [N] raw count vector — the single formula source shared by the
    [T, N] kernel and the wavefront verifier's incrementally carried raw."""
    big = jnp.float32(3.4e38)
    lo = jnp.min(jnp.where(mask, raw, big))
    hi = jnp.max(jnp.where(mask, raw, -big))
    rng = hi - lo
    return jnp.where(
        rng > 0, MAX_NODE_SCORE * (hi - raw) / jnp.maximum(rng, 1e-30), MAX_NODE_SCORE
    )


def topology_spread_score(
    cnt_at: jnp.ndarray,  # [T, N] matching placed pods at each node's domain
    soft_w: jnp.ndarray,  # [T] ScheduleAnyway constraint multiplicity
    mask: jnp.ndarray,  # [N] feasible nodes
) -> jnp.ndarray:
    """PodTopologySpread score (`plugins/podtopologyspread/scoring.go`,
    registry weight 2 applied by the caller): lower matching count in the
    node's domains → higher score, inverse-min-max to [0, 100]; nodes missing
    a topology key count 0 for that constraint."""
    return spread_score_from_raw(soft_w @ cnt_at, mask)


def selector_spread_compose(
    cnt_host: jnp.ndarray,  # [N] matching placed pods on each node
    cnt_zone: jnp.ndarray,  # [N] matching placed pods in each node's zone
    max_host,  # scalar — max of cnt_host over feasible nodes (0-floored)
    max_zone,  # scalar — max of cnt_zone over feasible nodes (0-floored)
    any_zone_terms,  # bool scalar — the pod has zone-key counting terms
) -> jnp.ndarray:
    """`selector_spread_score`'s normalization with the masked maxima
    precomputed — the wavefront verifier carries them as incrementally
    maintained scalars (max is order-free, so the carried value is
    bit-identical to the reduction)."""
    node_score = jnp.where(
        max_host > 0,
        MAX_NODE_SCORE * (max_host - cnt_host) / jnp.maximum(max_host, 1e-30),
        MAX_NODE_SCORE,
    )
    zone_score = jnp.where(
        max_zone > 0,
        MAX_NODE_SCORE * (max_zone - cnt_zone) / jnp.maximum(max_zone, 1e-30),
        MAX_NODE_SCORE,
    )
    have_zones = any_zone_terms & (max_zone > 0)
    zw = jnp.float32(2.0 / 3.0)
    return jnp.where(
        have_zones, (1.0 - zw) * node_score + zw * zone_score, node_score
    )


def selector_spread_from_counts(
    cnt_host: jnp.ndarray,  # [N] matching placed pods on each node
    cnt_zone: jnp.ndarray,  # [N] matching placed pods in each node's zone
    any_zone_terms,  # bool scalar — the pod has zone-key counting terms
    mask: jnp.ndarray,  # [N]
) -> jnp.ndarray:
    """`selector_spread_score`'s normalization on already summed host/zone
    count vectors (shared with the wavefront verifier's carried raws)."""
    return selector_spread_compose(
        cnt_host,
        cnt_zone,
        jnp.max(jnp.where(mask, cnt_host, 0.0)),
        jnp.max(jnp.where(mask, cnt_zone, 0.0)),
        any_zone_terms,
    )


def selector_spread_score(
    cnt_at: jnp.ndarray,  # [T, N] matching placed pods at each node's domain
    ss_host: jnp.ndarray,  # [T] hostname-key counting terms of the pod
    ss_zone: jnp.ndarray,  # [T] zone-key counting terms
    mask: jnp.ndarray,  # [N]
) -> jnp.ndarray:
    """SelectorSpread score (`plugins/selectorspread/selector_spread.go`):
    spread pods of the same service/controller across nodes, then zones with
    zoneWeighting=2/3 when zones exist."""
    return selector_spread_from_counts(
        ss_host.astype(jnp.float32) @ cnt_at,
        ss_zone.astype(jnp.float32) @ cnt_at,
        jnp.any(ss_zone),
        mask,
    )


def interpod_score(
    cnt_at: jnp.ndarray,  # [T, N] matching placed pods at each node's domain
    own_aff_at: jnp.ndarray,  # [T, N] placed owners of required affinity terms
    w_own_aff_at: jnp.ndarray,  # [T, N] summed preferred-affinity owner weights
    w_own_anti_at: jnp.ndarray,  # [T, N]
    s_match: jnp.ndarray,  # [T] incoming pod matches term
    w_aff_pref: jnp.ndarray,  # [T] incoming pod's preferred affinity weights
    w_anti_pref: jnp.ndarray,  # [T]
    hard_pod_affinity_weight: float = 1.0,
) -> jnp.ndarray:
    """InterPodAffinity score (`plugins/interpodaffinity/scoring.go`):

    + weight × matching placed pods in domain, for the incoming pod's
      preferred (anti-)affinity terms, and symmetrically
    + placed pods' preferred terms (and required affinity terms, scaled by
      HardPodAffinityWeight=1) that select the incoming pod.
    The [T, N] inputs are the engine's per-node count state (SchedState).
    Raw, un-normalized; caller applies maxabs_normalize.
    """
    incoming = (w_aff_pref - w_anti_pref) @ cnt_at
    symmetric = s_match.astype(jnp.float32) @ (
        w_own_aff_at - w_own_anti_at + hard_pod_affinity_weight * own_aff_at
    )
    return incoming + symmetric
