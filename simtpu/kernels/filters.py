"""Filter kernels: each returns a boolean feasibility mask over all nodes.

One kernel per vendored filter-plugin family (the checklist in SURVEY.md §2.2,
`vendor/.../scheduler/algorithmprovider/registry.go:75-145`). The reference
evaluates these per (pod, node) with 16 goroutines
(`core/generic_scheduler.go:271-341`); here the node axis is a vector lane and
one call covers every node at once.

Stateless filters (NodeUnschedulable, TaintToleration, NodeAffinity/selector,
NodeName pinning) are precomputed per pod-group in core/tensorize.py; the
kernels here are the ones that depend on mutable scan state.
"""

from __future__ import annotations

import jax.numpy as jnp

# Relative slack for float32 resource comparisons; the reference compares exact
# integer milli-quantities, so allow only rounding-level drift.
_RES_EPS = 1e-5


def resources_fit(free: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """NodeResourcesFit: every requested resource fits in the node's free
    allocatable (incl. the synthetic `pods` count resource).

    free: [N, R], req: [R] → mask [N].
    Mirrors `plugins/noderesources/fit.go` fitsRequest.
    """
    slack = _RES_EPS * jnp.maximum(jnp.abs(free), 1.0)
    return jnp.all(free + slack >= req, axis=-1)


def interpod_filter(
    cnt_match: jnp.ndarray,  # [T, D] placed pods matching term selector+ns
    cnt_own_anti: jnp.ndarray,  # [T, D] placed pods owning required anti term
    node_dom: jnp.ndarray,  # [K, N] global domain id per topo key (-1 absent)
    term_topo: jnp.ndarray,  # [T] topo-key index per term
    s_match: jnp.ndarray,  # [T] incoming pod matches term selector+ns
    a_aff: jnp.ndarray,  # [T] incoming pod requires affinity term t
    a_anti: jnp.ndarray,  # [T] incoming pod requires anti-affinity term t
) -> jnp.ndarray:
    """InterPodAffinity filter over all nodes.

    Mirrors `plugins/interpodaffinity/filtering.go`:
    - satisfyPodAffinity: every required affinity term must have ≥1 matching
      placed pod in the node's domain (node must carry the topology key); if no
      matching pod exists cluster-wide for any term and the pod matches its own
      terms, it may pass anywhere.
    - satisfyPodAntiAffinity: no required anti-affinity term of the incoming
      pod may have a matching placed pod in the node's domain.
    - satisfyExistingPodsAntiAffinity: no placed pod owning a required
      anti-affinity term that matches the incoming pod may share its domain.
    Returns mask [N].
    """
    t_count, _ = cnt_match.shape
    if t_count == 0:
        return jnp.ones(node_dom.shape[-1] if node_dom.ndim else 0, bool)

    dom_tn = node_dom[term_topo]  # [T, N] domain id of each node for each term's key
    valid = dom_tn >= 0
    safe = jnp.where(valid, dom_tn, 0)
    t_idx = jnp.arange(t_count)[:, None]
    match_at = jnp.where(valid, cnt_match[t_idx, safe], 0.0)  # [T, N]
    own_anti_at = jnp.where(valid, cnt_own_anti[t_idx, safe], 0.0)

    # anti-affinity: incoming pod's terms
    anti_violated = jnp.any(a_anti[:, None] & (match_at > 0), axis=0)  # [N]
    # symmetry: existing pods' anti terms that select the incoming pod
    sym_violated = jnp.any(s_match[:, None] & (own_anti_at > 0), axis=0)

    # affinity: every required term satisfied in-domain (key must exist)
    aff_term_ok = (~a_aff[:, None]) | (valid & (match_at > 0))  # [T, N]
    aff_ok = jnp.all(aff_term_ok, axis=0)
    # first-pod-in-series escape: no matching pod anywhere for any required
    # term AND the pod matches all its own terms AND node has all topo keys
    total_match = jnp.sum(jnp.where(a_aff, jnp.sum(cnt_match, axis=1), 0.0))
    self_ok = (
        (total_match == 0)
        & jnp.all(jnp.where(a_aff, s_match, True))
        & jnp.all((~a_aff[:, None]) | valid, axis=0)
    )
    aff_ok = aff_ok | (jnp.any(a_aff) & self_ok)

    return aff_ok & ~anti_violated & ~sym_violated
