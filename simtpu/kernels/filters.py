"""Filter kernels: each returns a boolean feasibility mask over all nodes.

One kernel per vendored filter-plugin family (the checklist in SURVEY.md §2.2,
`vendor/.../scheduler/algorithmprovider/registry.go:75-145`). The reference
evaluates these per (pod, node) with 16 goroutines
(`core/generic_scheduler.go:271-341`); here the node axis is a vector lane and
one call covers every node at once.

Stateless filters (NodeUnschedulable, TaintToleration, NodeAffinity/selector,
NodeName pinning) are precomputed per pod-group in core/tensorize.py; the
kernels here are the ones that depend on mutable scan state.
"""

from __future__ import annotations

import jax.numpy as jnp

# Relative slack for float32 resource comparisons; the reference compares exact
# integer milli-quantities, so allow only rounding-level drift.
_RES_EPS = 1e-5


def resources_fit(free: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """NodeResourcesFit: every requested resource fits in the node's free
    allocatable (incl. the synthetic `pods` count resource).

    free: [N, R], req: [R] → mask [N].
    Mirrors `plugins/noderesources/fit.go` fitsRequest.
    """
    slack = _RES_EPS * jnp.maximum(jnp.abs(free), 1.0)
    return jnp.all(free + slack >= req, axis=-1)


def ports_conflict_free(ports_used: jnp.ndarray, want: jnp.ndarray) -> jnp.ndarray:
    """NodePorts: no requested (protocol, hostPort) pair already in use on the
    node (`plugins/nodeports/node_ports.go` Filter).

    ports_used: [N, P] in-use counts, want: [P] bool → mask [N].
    """
    return ~jnp.any(want[None, :] & (ports_used > 0), axis=-1)


def volume_conflict_free(
    vols_any: jnp.ndarray,  # [N, W] users (rw or ro) of exclusive volume w
    vols_rw: jnp.ndarray,  # [N, W] read-write users of volume w
    want_rw: jnp.ndarray,  # [W] bool — pod mounts volume w read-write
    want_ro: jnp.ndarray,  # [W] bool — pod mounts volume w read-only
) -> jnp.ndarray:
    """VolumeRestrictions (`plugins/volumerestrictions/volume_restrictions.go`):
    a read-write mount conflicts with any existing user of the same volume; a
    read-only mount conflicts with an existing read-write user. Returns [N].
    """
    rw_conflict = jnp.any(want_rw[None, :] & (vols_any > 0), axis=-1)
    ro_conflict = jnp.any(want_ro[None, :] & (vols_rw > 0), axis=-1)
    return ~(rw_conflict | ro_conflict)


def attach_limits_ok(
    vols_any: jnp.ndarray,  # [N, W] users of volume w on node n
    want_att: jnp.ndarray,  # [W] bool — pod attaches volume w
    class_mask: jnp.ndarray,  # [C, W] bool — volume w belongs to attach class c
    limits: jnp.ndarray,  # [N, C] per-node attach limits
) -> jnp.ndarray:
    """NodeVolumeLimits (`plugins/nodevolumelimits/non_csi.go`): per class,
    unique volumes already attached to the node plus the pod's volumes not yet
    on the node must stay within the node's limit. A class the pod adds
    nothing to never filters — upstream returns early on zero new volumes, so
    an already-over-limit node (e.g. from forced `spec.nodeName` placements)
    still accepts volume-less pods. Returns [N].
    """
    present = (vols_any > 0).astype(jnp.float32)  # [N, W]
    cm = class_mask.astype(jnp.float32)  # [C, W]
    used = present @ cm.T  # [N, C] unique volumes on node per class
    new = ((1.0 - present) * want_att.astype(jnp.float32)[None, :]) @ cm.T
    return jnp.all((new == 0) | (used + new <= limits), axis=-1)


def topology_spread_filter(
    cnt_at: jnp.ndarray,  # [T, N] placed pods matching term t at node n's domain
    valid: jnp.ndarray,  # [T, N] node carries term t's topology key
    max_skew: jnp.ndarray,  # [T] maxSkew of the pod's DoNotSchedule constraints (0 = inactive)
    elig_nodes: jnp.ndarray,  # [N] nodes eligible for the pod (static mask ∩ valid)
) -> jnp.ndarray:
    """PodTopologySpread hard filter (`plugins/podtopologyspread/filtering.go`):
    placing on node n must keep `count(domain of n) + 1 - min count over
    eligible domains <= maxSkew` for every DoNotSchedule constraint; nodes
    missing the topology key are infeasible for that constraint.

    The eligible-domain minimum is taken over domains containing ≥1 node that
    passes the pod's static filters (upstream restricts to nodes passing
    nodeSelector/nodeAffinity; our static mask folds taints in as well — a
    strictly tighter, usually identical set); since every eligible domain
    surfaces its count at its eligible nodes, the per-node masked minimum of
    `cnt_at` equals the per-domain minimum. Counts are cluster-wide per
    domain rather than restricted to eligible nodes.
    """
    t_count, n = cnt_at.shape
    active = max_skew > 0
    if t_count == 0:
        return jnp.ones(n, bool)
    inf = jnp.float32(3.4e38)
    elig = valid & elig_nodes[None, :]
    min_cnt = jnp.min(jnp.where(elig, cnt_at, inf), axis=1)  # [T]
    min_cnt = jnp.where(min_cnt >= inf, 0.0, min_cnt)
    ok_tn = (~active[:, None]) | (
        valid & (cnt_at + 1.0 - min_cnt[:, None] <= max_skew[:, None])
    )
    return jnp.all(ok_tn, axis=0)


def interpod_filter(
    cnt_at: jnp.ndarray,  # [T, N] placed pods matching term t at node n's domain
    own_anti_at: jnp.ndarray,  # [T, N] placed owners of required anti term t
    valid: jnp.ndarray,  # [T, N] node carries term t's topology key
    cnt_total: jnp.ndarray,  # [T] cluster-wide matching count per term
    s_match: jnp.ndarray,  # [T] incoming pod matches term selector+ns
    a_aff: jnp.ndarray,  # [T] incoming pod requires affinity term t
    a_anti: jnp.ndarray,  # [T] incoming pod requires anti-affinity term t
) -> jnp.ndarray:
    """InterPodAffinity filter over all nodes.

    Mirrors `plugins/interpodaffinity/filtering.go`:
    - satisfyPodAffinity: every required affinity term must have ≥1 matching
      placed pod in the node's domain (node must carry the topology key); if no
      matching pod exists cluster-wide for any term and the pod matches its own
      terms, it may pass anywhere.
    - satisfyPodAntiAffinity: no required anti-affinity term of the incoming
      pod may have a matching placed pod in the node's domain.
    - satisfyExistingPodsAntiAffinity: no placed pod owning a required
      anti-affinity term that matches the incoming pod may share its domain.
    The [T, N] inputs are the engine's per-node count state. Returns mask [N].
    """
    t_count, n = cnt_at.shape
    if t_count == 0:
        return jnp.ones(n, bool)

    # anti-affinity: incoming pod's terms
    anti_violated = jnp.any(a_anti[:, None] & (cnt_at > 0), axis=0)  # [N]
    # symmetry: existing pods' anti terms that select the incoming pod
    sym_violated = jnp.any(s_match[:, None] & (own_anti_at > 0), axis=0)

    # affinity: every required term satisfied in-domain (key must exist)
    aff_term_ok = (~a_aff[:, None]) | (valid & (cnt_at > 0))  # [T, N]
    aff_ok = jnp.all(aff_term_ok, axis=0)
    # first-pod-in-series escape: no matching pod anywhere for any required
    # term AND the pod matches all its own terms AND node has all topo keys
    total_match = jnp.sum(jnp.where(a_aff, cnt_total, 0.0))
    self_ok = (
        (total_match == 0)
        & jnp.all(jnp.where(a_aff, s_match, True))
        & jnp.all((~a_aff[:, None]) | valid, axis=0)
    )
    aff_ok = aff_ok | (jnp.any(a_aff) & self_ok)

    return aff_ok & ~anti_violated & ~sym_violated
