"""Pallas TPU kernel: fused resource filter + resource scores in one HBM pass.

The per-pod hot loop reads `free[N, R]` and `alloc[N, R]` several times under
XLA — once for the NodeResourcesFit mask (`filters.resources_fit`), once each
for the NodeResourcesLeastAllocated / NodeResourcesBalancedAllocation scores
(`scores.least_allocated` / `scores.balanced_allocation`) and once for the
Simon dominant-share score (`scores.simon_share`, `pkg/simulator/plugin/
simon.go:44-67`). XLA usually fuses these into one loop already (SURVEY.md §7
flags Pallas as the escape hatch for when it doesn't); this kernel *guarantees*
the single pass: one tile-walk over the node axis computes all four outputs
from one VMEM residency of the inputs.

Layout is TPU-native: arrays come in **transposed** `[R, N]` form so the node
axis lies on the 128-wide vector lanes and the (small, padded-to-8) resource
axis on sublanes; all reductions are cheap sublane reductions. Use
`to_kernel_layout` to prepare inputs once per simulation.

On non-TPU backends the same kernel runs under `interpret=True`, so CPU tests
exercise the identical code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..core.tensorize import RES_CPU, RES_MEMORY
from .filters import _RES_EPS as _EPS
from .scores import MAX_NODE_SCORE

# float32 sublane granule; the resource axis is padded up to a multiple
_SUBLANE = 8
# default node-axis tile: 2048 f32 lanes ≈ 8 KiB per row-block in VMEM
_TILE_N = 2048


def _pad_to(x: jnp.ndarray, axis: int, multiple: int, value=0.0) -> jnp.ndarray:
    pad = (-x.shape[axis]) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def to_kernel_layout(free: jnp.ndarray, alloc: jnp.ndarray, tile_n: int = _TILE_N):
    """[N, R] host layout → padded [R8, Np] transposed kernel layout."""
    free_t = _pad_to(_pad_to(free.T, 0, _SUBLANE), 1, tile_n)
    alloc_t = _pad_to(_pad_to(alloc.T, 0, _SUBLANE), 1, tile_n)
    return free_t, alloc_t


def _kernel(req_ref, free_ref, alloc_ref, fit_ref, lb_ref, dom_ref, *, n_res):
    # All intermediates stay float32: Mosaic lowers bool vectors to i8 masks
    # and rejects the i8→i1 truncations that jnp.all / bool-valued selects
    # would emit, so predicates only ever appear as jnp.where conditions.
    free = free_ref[...]  # [R8, T]
    alloc = alloc_ref[...]
    req = req_ref[...]  # [R8, 1] broadcasts over lanes
    rows = jax.lax.broadcasted_iota(jnp.int32, free.shape, 0)
    act = jnp.where(rows < n_res, 1.0, 0.0)

    # NodeResourcesFit (filters.resources_fit): min over active rows of the
    # 0/1 fit indicator; pad rows forced to 1
    slack = _EPS * jnp.maximum(jnp.abs(free), 1.0)
    okf = jnp.where(free + slack >= req, 1.0, 0.0)
    fit = jnp.min(jnp.maximum(okf, 1.0 - act), axis=0)

    # NodeResourcesLeastAllocated over the cpu+memory rows (two separate
    # wheres: a bool-vector OR intermediate would hit Mosaic's i8→i1 limits)
    cpumem = jnp.where(rows == RES_CPU, 1.0, 0.0) + jnp.where(
        rows == RES_MEMORY, 1.0, 0.0
    )
    fa = jnp.clip(free - req, 0.0, None)
    lfrac = jnp.where(alloc > 0, fa / jnp.maximum(alloc, 1e-30), 0.0)
    least = jnp.sum(lfrac * cpumem, axis=0) * (MAX_NODE_SCORE / 2.0)

    # NodeResourcesBalancedAllocation (two-resource form)
    used_after = alloc - free + req
    ufrac = jnp.where(alloc > 0, used_after / jnp.maximum(alloc, 1e-30), 1.0)
    balanced = (1.0 - jnp.abs(ufrac[RES_CPU, :] - ufrac[RES_MEMORY, :])) * MAX_NODE_SCORE

    # Simon dominant share against static allocatable (scores.simon_share)
    denom = alloc - req
    share = jnp.where(
        denom == 0, jnp.where(req == 0, 0.0, 1.0), req / jnp.where(denom == 0, 1.0, denom)
    )
    share = jnp.where(alloc > 0, share * act, 0.0)
    dom = jnp.clip(jnp.max(share, axis=0), 0.0) * MAX_NODE_SCORE

    fit_ref[0, :] = fit
    lb_ref[0, :] = least + balanced
    dom_ref[0, :] = dom


@functools.partial(jax.jit, static_argnames=("n_res", "tile_n", "interpret"))
def fused_fit_score(
    free_t: jnp.ndarray,  # [R8, Np] transposed free (to_kernel_layout)
    alloc_t: jnp.ndarray,  # [R8, Np] transposed allocatable
    req: jnp.ndarray,  # [R] pod request
    n_res: int,
    tile_n: int = _TILE_N,
    interpret: bool = False,
):
    """One fused pass: (fit mask [Np], least+balanced score [Np], simon raw
    dominant-share score [Np]). Trailing pad columns report fit=False-safe
    values (alloc=0 ⇒ fit=True, scores 0/100) — callers slice [:N] or rely on
    the engine's static mask to exclude them.
    """
    r8, n = free_t.shape
    grid = (n // tile_n,)
    out = pl.pallas_call(
        functools.partial(_kernel, n_res=n_res),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, 1), lambda i: (0, 0)),  # req, replicated
            pl.BlockSpec((r8, tile_n), lambda i: (0, i)),
            pl.BlockSpec((r8, tile_n), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, tile_n), lambda i: (0, i)),
            pl.BlockSpec((1, tile_n), lambda i: (0, i)),
            pl.BlockSpec((1, tile_n), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
    )(_pad_to(req[:, None], 0, _SUBLANE), free_t, alloc_t)
    fit, lb, dom = out
    return fit[0] > 0.5, lb[0], dom[0]


def fused_fit_score_auto(free_t, alloc_t, req, n_res, tile_n: int = _TILE_N):
    """Backend-dispatching wrapper: compiled on TPU, interpreted elsewhere."""
    interpret = jax.default_backend() != "tpu"
    return fused_fit_score(free_t, alloc_t, req, n_res, tile_n, interpret)
