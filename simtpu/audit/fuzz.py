"""Differential fuzz harness + mutation-kill for the placement auditor.

Two modes, both seeded and deterministic (`simtpu fuzz`):

- **differential** (`run_differential`): generate gnarly spec/cluster
  cases (mixed hard/soft affinity + spread + selectors + tolerations +
  GPU + Open-Local storage + host-port collisions — `gen_case` draws the
  feature mix from the seed), place each one with the serial-exact
  baseline engine, then replay it across the engine-config matrix —
  speculative wavefront on/off × compact carried state on/off × GSPMD
  node sharding on/off (multi-device hosts) × injected-OOM chunk backoff
  — asserting BIT-IDENTICAL landing-node vectors and an audit-clean
  verdict on every config.  Every matrix cell is a documented
  bit-identity contract (docs/speculation.md, docs/memory.md,
  docs/robustness.md); the fuzzer is the runtime enforcement.
  A failing case auto-shrinks (drop workloads, halve replicas, halve
  nodes — greedily, while the failure reproduces) and lands as a minimal
  reproducer YAML under `--out`.

- **mutation-kill** (`run_mutation_kill`): corrupt ACCEPTED placements —
  move a pod to an invalid/full node, collide a host port, double-book a
  hard-anti domain, overfill a spread domain, strand a required-affinity
  pod, forge an illegal eviction — and assert the auditor flags every
  single one.  This is the auditor's own test harness: a corruption the
  audit misses is a hole in the certifier, surfaced as a failure here
  (and in `make bench-audit` / CI).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.objects import AppResource, PreemptedPod, ResourceTypes
from ..synth import make_deployment, make_node, synth_apps, synth_cluster
from .checker import audit_placement, audit_simulation, extras_from_log

OOM_MSG = "RESOURCE_EXHAUSTED: out of memory allocating (injected by fuzz)"


# ---------------------------------------------------------------------------
# Case generation
# ---------------------------------------------------------------------------


def gen_case(
    seed: int, n_nodes: int = 32, n_pods: int = 160
) -> Tuple[ResourceTypes, List[AppResource], Dict[str, object]]:
    """One seeded gnarly case: cluster + apps + the drawn feature mix."""
    rng = np.random.default_rng(seed)
    mix = {
        "zones": int(rng.integers(2, 6)),
        "taint_frac": float(rng.choice([0.0, 0.1, 0.3])),
        "gpu_frac": float(rng.choice([0.0, 0.2])),
        "storage_frac": float(rng.choice([0.0, 0.2])),
        "selector_frac": float(rng.choice([0.1, 0.4])),
        "toleration_frac": float(rng.choice([0.0, 0.2])),
        "anti_affinity_frac": float(rng.choice([0.2, 0.5])),
        "anti_affinity_hard_frac": float(rng.choice([0.3, 0.8])),
        "spread_frac": float(rng.choice([0.2, 0.5])),
        "spread_hard_frac": float(rng.choice([0.3, 0.8])),
        "affinity_frac": float(rng.choice([0.0, 0.3])),
        "ports": bool(rng.random() < 0.6),
    }
    cluster = synth_cluster(
        n_nodes,
        seed=seed,
        zones=mix["zones"],
        taint_frac=mix["taint_frac"],
        gpu_frac=mix["gpu_frac"],
        storage_frac=mix["storage_frac"],
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=mix["zones"],
        pods_per_deployment=max(4, n_pods // 12),
        selector_frac=mix["selector_frac"],
        toleration_frac=mix["toleration_frac"],
        anti_affinity_frac=mix["anti_affinity_frac"],
        anti_affinity_hard_frac=mix["anti_affinity_hard_frac"],
        spread_frac=mix["spread_frac"],
        spread_hard_frac=mix["spread_hard_frac"],
        gpu_frac=mix["gpu_frac"] * 0.5,
        storage_frac=mix["storage_frac"] * 0.5,
        affinity_frac=mix["affinity_frac"],
    )
    if mix["ports"]:
        # host-port collision pressure: more replicas wanting the same
        # (protocol, port) pair than... no — exactly at capacity, so the
        # engine must spread them one per node and a corrupted placement
        # (or a diverging config) trips the audit
        port_reps = int(rng.integers(2, min(8, n_nodes)))
        apps[0].resource.deployments.append(
            make_deployment(
                "porty", port_reps, 100, 128, host_port=int(rng.integers(7000, 9000))
            )
        )
    return cluster, apps, mix


# ---------------------------------------------------------------------------
# Engine-config matrix
# ---------------------------------------------------------------------------


class _OomFirst:
    """Wrap a dispatch callable so its first `n` multi-pod calls raise an
    injected RESOURCE_EXHAUSTED — driving the chunk-halving backoff
    (durable/backoff.py) inside a normal placement."""

    def __init__(self, real: Callable, n: int = 1):
        self.real = real
        self.left = n

    def __call__(self, statics, state, seg, *rest):
        width = int(np.asarray(seg[0]).shape[0])
        if self.left > 0 and width > 1:
            self.left -= 1
            raise RuntimeError(OOM_MSG)
        return self.real(statics, state, seg, *rest)


def engine_configs(include_shard: Optional[bool] = None) -> List[Dict]:
    """The matrix cells beyond the serial baseline.  `include_shard=None`
    auto-includes the GSPMD cell when >1 device is visible."""
    cells = [
        {"name": "wavefront", "speculate": True, "compact": False},
        {"name": "compact", "speculate": False, "compact": True},
        {"name": "wavefront+compact", "speculate": True, "compact": True},
        {"name": "oom-backoff", "speculate": False, "compact": False, "oom": 2},
    ]
    if include_shard is None:
        import jax

        include_shard = len(jax.devices()) > 1
    if include_shard:
        cells.insert(3, {"name": "sharded", "speculate": False,
                         "compact": False, "shard": True})
    return cells


def _place_with(cluster, apps, cfg: Dict):
    """Engine-level placement of one case under one matrix cell; returns
    the `PlacedCluster` (nodes vector + tensors + batch + engine)."""
    from ..engine.scan import Engine
    from ..faults.drain import place_cluster

    def factory(tz):
        if cfg.get("shard"):
            from ..parallel.mesh import planner_mesh
            from ..parallel.sharded import ShardedEngine

            mesh = planner_mesh()
            if mesh is None:
                raise RuntimeError("shard cell needs >1 visible device")
            eng = ShardedEngine(tz, mesh)
        else:
            eng = Engine(tz)
        eng.compact = bool(cfg.get("compact"))
        if cfg.get("oom"):
            eng._scan_call = _OomFirst(eng._scan_call, int(cfg["oom"]))
        return eng

    return place_cluster(
        cluster,
        apps,
        bulk=False,
        engine_factory=factory,
        speculate=bool(cfg.get("speculate")),
    )


@dataclass
class FuzzFailure:
    seed: int
    config: str
    kind: str  # "divergence" | "audit" | "error"
    detail: str
    reproducer: str = ""  # path of the shrunk YAML, when written


@dataclass
class FuzzResult:
    cases: int = 0
    configs_run: int = 0
    failures: List[FuzzFailure] = field(default_factory=list)
    audits_clean: int = 0

    @property
    def ok(self) -> bool:
        return not self.failures

    def counters(self) -> Dict[str, object]:
        return {
            "ok": self.ok,
            "cases": self.cases,
            "configs_run": self.configs_run,
            "audits_clean": self.audits_clean,
            "failures": [
                {"seed": f.seed, "config": f.config, "kind": f.kind,
                 "detail": f.detail, "reproducer": f.reproducer}
                for f in self.failures
            ],
        }


def _check_case(cluster, apps, cells) -> Optional[Tuple[str, str, str]]:
    """Run one case across the matrix.  Returns None when every config is
    bit-identical to the serial baseline and audit-clean, else
    (config, kind, detail)."""
    base = _place_with(cluster, apps, {"name": "serial"})
    rep = audit_placement(
        base.tensors, base.batch, base.nodes, extras_from_log(base)
    )
    if not rep.ok:
        return ("serial", "audit", rep.summary())
    base_nodes = np.asarray(base.nodes)
    for cfg in cells:
        try:
            pc = _place_with(cluster, apps, cfg)
        except Exception as exc:  # an engine config crashing IS a finding
            return (cfg["name"], "error", f"{type(exc).__name__}: {exc}")
        if not np.array_equal(np.asarray(pc.nodes), base_nodes):
            diff = np.flatnonzero(np.asarray(pc.nodes) != base_nodes)
            return (
                cfg["name"],
                "divergence",
                f"{len(diff)} divergent pod(s), first row {int(diff[0])}",
            )
        rep = audit_placement(
            pc.tensors, pc.batch, pc.nodes, extras_from_log(pc)
        )
        if not rep.ok:
            return (cfg["name"], "audit", rep.summary())
    return None


# ---------------------------------------------------------------------------
# Shrinking + reproducers
# ---------------------------------------------------------------------------


def _shrink(cluster, apps, cells, still_fails, rounds: int = 6):
    """Greedy structural shrink while the failure reproduces: drop
    deployments one at a time, halve replica counts, halve the node
    list."""
    import copy

    cur_c, cur_a = cluster, apps
    for _ in range(rounds):
        shrunk = False
        deps = cur_a[0].resource.deployments
        for i in range(len(deps) - 1, -1, -1):
            trial_a = copy.deepcopy(cur_a)
            del trial_a[0].resource.deployments[i]
            if not trial_a[0].resource.deployments:
                continue
            if still_fails(cur_c, trial_a, cells):
                cur_a, shrunk = trial_a, True
        trial_a = copy.deepcopy(cur_a)
        for d in trial_a[0].resource.deployments:
            d["spec"]["replicas"] = max(1, int(d["spec"].get("replicas", 1)) // 2)
        if still_fails(cur_c, trial_a, cells):
            cur_a, shrunk = trial_a, True
        if len(cur_c.nodes) > 2:
            trial_c = ResourceTypes(
                **{k: list(v) for k, v in vars(cur_c).items()}
            )
            trial_c.nodes = list(cur_c.nodes[: max(2, len(cur_c.nodes) // 2)])
            if still_fails(trial_c, cur_a, cells):
                cur_c, shrunk = trial_c, True
        if not shrunk:
            break
    return cur_c, cur_a


def write_reproducer(cluster, apps, path: str) -> str:
    """One multi-document YAML reproducing the case (nodes, storage
    classes, workloads) — re-runnable through `load_resources` +
    `simtpu fuzz --replay`."""
    import yaml

    docs: List[dict] = []
    docs.extend(cluster.nodes)
    docs.extend(cluster.storage_classes)
    for app in apps:
        docs.extend(app.resource.deployments)
        docs.extend(app.resource.pods)
        docs.extend(app.resource.daemon_sets)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        yaml.safe_dump_all(docs, f, sort_keys=False)
    return path


def load_reproducer(path: str):
    """Load a `write_reproducer` YAML back into (cluster, apps) — the
    `simtpu fuzz --replay` entry.  Nodes and StorageClasses form the
    cluster; every workload kind lands in one replay app."""
    from ..io.yaml_loader import load_resources

    res = load_resources(path)
    cluster = ResourceTypes(
        nodes=list(res.nodes), storage_classes=list(res.storage_classes)
    )
    work = ResourceTypes(
        pods=list(res.pods),
        deployments=list(res.deployments),
        replica_sets=list(res.replica_sets),
        replication_controllers=list(res.replication_controllers),
        stateful_sets=list(res.stateful_sets),
        daemon_sets=list(res.daemon_sets),
        jobs=list(res.jobs),
        cron_jobs=list(res.cron_jobs),
    )
    return cluster, [AppResource(name="replay", resource=work)]


def replay_case(
    path: str, include_shard: Optional[bool] = None
) -> Optional[Tuple[str, str, str]]:
    """Re-run one shrunk reproducer across the engine-config matrix;
    returns None when clean, else (config, kind, detail) — the same
    contract as `_check_case`."""
    cluster, apps = load_reproducer(path)
    return _check_case(cluster, apps, engine_configs(include_shard))


def run_differential(
    cases: int = 16,
    seed: int = 0,
    n_nodes: int = 32,
    n_pods: int = 160,
    out_dir: str = "",
    include_shard: Optional[bool] = None,
    progress: Optional[Callable[[str], None]] = None,
) -> FuzzResult:
    """The differential fuzz loop (docstring at module top)."""
    say = progress or (lambda s: None)
    cells = engine_configs(include_shard)
    result = FuzzResult(cases=cases)
    for c in range(cases):
        case_seed = seed + c * 1000
        cluster, apps, mix = gen_case(case_seed, n_nodes, n_pods)
        say(f"case {c + 1}/{cases} (seed {case_seed}): "
            + ", ".join(k for k, v in mix.items() if v))
        bad = _check_case(cluster, apps, cells)
        result.configs_run += 1 + len(cells)
        if bad is None:
            result.audits_clean += 1 + len(cells)
            continue
        config, kind, detail = bad
        failure = FuzzFailure(case_seed, config, kind, detail)
        if out_dir:
            say(f"  FAILURE ({kind} on {config}) — shrinking ...")

            def still_fails(cl, ap, cs):
                got = _check_case(cl, ap, cs)
                return got is not None and got[1] == kind

            s_cluster, s_apps = _shrink(cluster, apps, cells, still_fails)
            failure.reproducer = write_reproducer(
                s_cluster, s_apps,
                os.path.join(out_dir, f"fuzz_{case_seed}_{config}_{kind}.yaml"),
            )
        result.failures.append(failure)
    return result


# ---------------------------------------------------------------------------
# Mutation-kill
# ---------------------------------------------------------------------------


def _mutation_fixture(seed: int = 0, n_nodes: int = 16):
    """A placed problem guaranteeing every corruption class a target:
    headroom-light burst pods (overcommit), zone selectors (invalid node),
    hard hostname anti-affinity, hard zone spread, required zone
    self-affinity, and one host port per node."""
    cluster = synth_cluster(n_nodes, seed=seed, zones=4, taint_frac=0.0)
    res = ResourceTypes()
    res.deployments = [
        make_deployment("burst", 3 * n_nodes, 2000, 512),
        make_deployment("sel", 8, 250, 256,
                        node_selector={"topology.kubernetes.io/zone": "zone-0"}),
        make_deployment("anti", 6, 250, 256,
                        anti_affinity_topo="kubernetes.io/hostname",
                        anti_affinity_required=True),
        make_deployment("spread", 8, 250, 256,
                        spread_topo="topology.kubernetes.io/zone",
                        spread_hard=True),
        make_deployment("colo", 6, 250, 256,
                        affinity_topo="topology.kubernetes.io/zone"),
        make_deployment("porty", 4, 100, 128, host_port=8080),
    ]
    apps = [AppResource(name="mut", resource=res)]
    return cluster, apps


MUTATION_CLASSES = (
    "invalid-node",
    "overcommit",
    "affinity-break",
    "anti-affinity-break",
    "spread-break",
    "port-conflict",
    "illegal-eviction",
)


def _mutate_nodes(kind: str, tensors, batch, nodes: np.ndarray, rng):
    """Corrupt the landing-node vector for one engine-level mutation
    class; returns the corrupted copy, or None when the case lacks the
    feature (the fixture guarantees it never does)."""
    nodes = np.asarray(nodes).copy()
    group = np.asarray(batch.group)
    placed = (nodes >= 0) & ~np.asarray(batch.forced, bool)
    static = np.asarray(tensors.static_mask, bool)

    def rows_of(pred_g) -> np.ndarray:
        gs = np.flatnonzero(pred_g)
        return np.flatnonzero(placed & np.isin(group, gs))

    if kind == "invalid-node":
        for j in rng.permutation(np.flatnonzero(placed)):
            bad = np.flatnonzero(~static[group[j]])
            if len(bad):
                nodes[j] = int(rng.choice(bad))
                return nodes
        return None
    if kind == "overcommit":
        from ..core.tensorize import RES_CPU

        alloc = np.asarray(tensors.alloc)
        req = np.asarray(batch.req)
        target = int(np.argmin(alloc[:, RES_CPU]))
        total = 0.0
        moved = False
        for j in np.flatnonzero(placed):
            if not static[group[j], target]:
                continue
            nodes[j] = target
            total += float(req[j, RES_CPU])
            moved = True
            if total > alloc[target, RES_CPU] * 1.01:
                return nodes
        return nodes if moved and total > alloc[target, RES_CPU] else None
    if kind == "anti-affinity-break":
        a_anti = np.asarray(tensors.a_anti_req, bool)
        rows = rows_of(a_anti.any(axis=1))
        if len(rows) < 2:
            return None
        nodes[rows[1]] = nodes[rows[0]]
        return nodes
    if kind == "spread-break":
        sh = np.asarray(tensors.spread_hard)
        rows = rows_of((sh > 0).any(axis=1))
        if len(rows) < 3:
            return None
        nodes[rows] = nodes[rows[0]]
        return nodes
    if kind == "affinity-break":
        a_aff = np.asarray(tensors.a_aff_req, bool)
        for g in np.flatnonzero(a_aff.any(axis=1)):
            rows = np.flatnonzero(placed & (group == g))
            if len(rows) < 2:
                continue
            t = int(np.flatnonzero(a_aff[g])[0])
            dom = tensors.node_dom[int(tensors.term_topo_key[t])]
            have = set(int(d) for d in dom[nodes[rows]])
            other = np.flatnonzero(
                (dom >= 0) & ~np.isin(dom, list(have)) & static[g]
            )
            if len(other):
                nodes[rows[-1]] = int(other[0])
                return nodes
        return None
    if kind == "port-conflict":
        ports = np.asarray(tensors.ports, bool)
        rows = rows_of(ports.any(axis=1))
        if len(rows) < 2:
            return None
        nodes[rows[1]] = nodes[rows[0]]
        return nodes
    return None


def run_mutation_kill(
    seed: int = 0,
    per_class: int = 4,
    n_nodes: int = 16,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, object]:
    """Corrupt accepted placements across every MUTATION_CLASSES entry and
    count auditor detections.  The contract is 100% kill — asserted by
    tests/test_audit.py and `make bench-audit`."""
    from ..faults.drain import place_cluster

    say = progress or (lambda s: None)
    rng = np.random.default_rng(seed)
    cluster, apps = _mutation_fixture(seed, n_nodes)
    pc = place_cluster(cluster, apps, bulk=False)
    ext = extras_from_log(pc)
    base = audit_placement(pc.tensors, pc.batch, pc.nodes, ext)
    if not base.ok:
        raise AssertionError(
            f"mutation fixture must start audit-clean: {base.summary()}"
        )
    tried: Dict[str, int] = {}
    killed: Dict[str, int] = {}
    missed: List[str] = []
    for kind in MUTATION_CLASSES:
        if kind == "illegal-eviction":
            t, k = _run_eviction_mutations(seed, per_class, say)
            tried[kind], killed[kind] = t, k
            if k < t:
                missed.append(kind)
            continue
        tried[kind] = killed[kind] = 0
        for trial in range(per_class):
            mut = _mutate_nodes(
                kind, pc.tensors, pc.batch, pc.nodes,
                np.random.default_rng(seed + trial),
            )
            if mut is None:
                continue
            tried[kind] += 1
            rep = audit_placement(pc.tensors, pc.batch, mut, ext)
            if not rep.ok:
                killed[kind] += 1
            else:
                missed.append(f"{kind}#{trial}")
        say(f"mutation {kind}: {killed[kind]}/{tried[kind]} killed")
    # a class whose mutator never found a target is a FIXTURE hole, not a
    # pass — it must land in `missed` or the 100%-kill contract would
    # silently shrink to "100% of whatever happened to be tried"
    for kind in MUTATION_CLASSES:
        if not tried.get(kind):
            missed.append(f"{kind}#untried")
    total_t = sum(tried.values())
    total_k = sum(killed.values())
    return {
        "classes": len([k for k in tried if tried[k]]),
        "classes_total": len(MUTATION_CLASSES),
        "tried": total_t,
        "killed": total_k,
        "kill_rate": (total_k / total_t) if total_t else 1.0,
        "by_class": {k: f"{killed[k]}/{tried[k]}" for k in tried},
        "missed": missed,
    }


def _run_eviction_mutations(seed: int, per_class: int, say):
    """Preemption-legality mutations on a Simulator run that genuinely
    preempts: (a) forge a priority inversion on a reported eviction,
    (b) report an eviction whose victim is still placed."""
    from ..api import Simulator
    from ..core.objects import name_of, namespace_of
    from ..workloads.expand import get_valid_pods_exclude_daemonset

    cluster = ResourceTypes()
    # fixed small nodes so the filler genuinely saturates the cluster and
    # the high-priority app MUST preempt
    cluster.nodes = [
        make_node(f"ev-{i}", 16000, 32, {"kubernetes.io/hostname": f"ev-{i}"})
        for i in range(4)
    ]
    # fill with low-priority pods, then a high-priority app that must evict
    filler = ResourceTypes(
        deployments=[make_deployment("low", 30, 2000, 1024, priority=0)]
    )
    cluster.pods = get_valid_pods_exclude_daemonset(filler)
    apps = [
        AppResource(
            name="high",
            resource=ResourceTypes(
                deployments=[make_deployment("high", 6, 4000, 2048, priority=100)]
            ),
        )
    ]
    sim = Simulator()
    sim.run_cluster(cluster)
    for app in apps:
        sim.schedule_app(app)
    if not sim._preempted:
        raise AssertionError("eviction fixture produced no preemptions")
    base = audit_simulation(sim)
    if not base.ok:
        raise AssertionError(
            f"eviction fixture must start audit-clean: {base.summary()}"
        )
    tried = killed = 0
    # (a) priority inversion: claim the victim outranked its preemptor
    for pre in sim._preempted[:per_class]:
        tried += 1
        saved = pre.pod
        forged = {**saved, "spec": {**(saved.get("spec") or {}), "priority": 10_000}}
        pre.pod = forged
        rep = audit_simulation(sim)
        pre.pod = saved
        if not rep.ok and "preemption" in rep.by_class:
            killed += 1
    # (b) evicted-but-placed: report a still-placed pod as a victim
    victim = sim._scheduled[0]
    by = f"{namespace_of(sim._scheduled[-1])}/{name_of(sim._scheduled[-1])}"
    forged = PreemptedPod(pod=victim, preempted_by=by,
                          node=victim["spec"].get("nodeName", ""))
    sim._preempted.append(forged)
    tried += 1
    rep = audit_simulation(sim)
    sim._preempted.pop()
    if not rep.ok and "preemption" in rep.by_class:
        killed += 1
    say(f"mutation illegal-eviction: {killed}/{tried} killed")
    return tried, killed
