"""Trust-but-verify: independent placement auditing + differential fuzzing.

- `checker` — a second, engine-independent implementation of the
  feasibility semantics that certifies a finished placement against the
  raw tensorized inputs (`audit_placement` / `audit_simulation`),
  producing a structured `AuditReport`.  The planners run it auto-on over
  every accepted candidate (`--no-audit` opts out) and fall back to the
  serial-exact engine on failure (docs/robustness.md).
- `fuzz` — the seeded differential fuzz harness (`simtpu fuzz`): replay
  generated gnarly cases across the engine-config matrix asserting
  identical, audit-clean placements; shrink failures to minimal
  reproducer YAML; mutation-kill mode corrupts accepted placements and
  asserts the auditor flags 100% of them.
"""

from .checker import (
    AuditReport,
    Violation,
    audit_enabled,
    audit_placed_cluster,
    audit_placement,
    audit_simulation,
    divergence_diagnostic,
    extras_from_log,
    inject_divergence,
    inject_divergence_enabled,
)

__all__ = [
    "AuditReport",
    "Violation",
    "audit_enabled",
    "audit_placed_cluster",
    "audit_placement",
    "audit_simulation",
    "divergence_diagnostic",
    "extras_from_log",
    "inject_divergence",
    "inject_divergence_enabled",
]
