"""Independent placement auditor: trust-but-verify for emitted plans.

Every correctness guarantee the engines carry is a dev-time pin
(bit-identity tests for the wavefront/compact/sharded paths); at runtime
nothing certified that an emitted plan actually satisfies the constraints
it claims to.  This module is that certifier: a SECOND implementation of
the feasibility semantics that checks a finished placement against the
raw tensorized inputs (`core/tensorize.ClusterTensors` — shared data, not
shared code) without touching any engine scoring/placement kernel
(`engine/scan.py`, `kernels/`).  "Priority Matters" (PAPERS.md) frames
packing as explicit constraint predicates; that is exactly the shape
implemented here, and the ROADMAP's advisory-solver backend inherits it
as its accept/reject oracle.

What is certified (per placed pod, in placement order):

- node validity & pinning: the stateless filter verdict
  (`static_mask[g, n]`), VolumeBinding/Zone (`vol_mask[g, n]`), the
  candidate-cluster mask (`node_valid[n]`), DaemonSet `metadata.name`
  pins and `spec.nodeName` bindings;
- resource conservation: cpu/mem/pods/extended requests against the
  node's remaining allocatable AT THE POD'S STEP (a prefix sum over the
  placement order — forced `spec.nodeName` pods legitimately bypass fit,
  so end-state totals alone cannot distinguish a bug from a binding);
- Open-Local storage (VG space, exclusive-device double-takes), GPU-share
  device memory, host-port conflicts, exclusive-volume rw/ro conflicts,
  and per-class attach limits;
- required inter-pod affinity/anti-affinity (both directions, with the
  first-pod-in-series escape) and DoNotSchedule topology spread, each
  evaluated against the prefix state exactly as `interpod_filter` /
  `topology_spread_filter` define them — via different algorithms
  (per-term sorted-event prefix counts and the rank-threshold minimum,
  not the engine's carried count planes);
- preemption legality (Simulator runs): every eviction's victim is
  strictly lower priority than its preemptor, the preemptor is placed,
  and no victim is simultaneously reported evicted and still placed;
- all-or-nothing completeness when the caller claims it
  (`require_all=True`: an accepted capacity candidate strands nothing).

Two execution modes, pinned equal by tests/test_audit.py:

- the default routes the bulk per-pod×node work (validity gathers and
  every sequential conservation/conflict check) through ONE jitted pass
  (`_bulk_flags_jit`) — counts and comparisons only, no engine kernels;
- ``SIMTPU_AUDIT_JIT=0`` forces the pure-numpy reference path
  (`SIMTPU_NATIVE=0` style).  The order-dependent interpod/spread
  predicates always run host-side (sorted-event prefix algebra).

Violation reports carry witnesses (pod, node, constraint class, the
numbers that prove the violation); `AuditReport.counters()` is the
machine-readable summary the planners surface under ``engine.audit``.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..obs.metrics import REGISTRY
from ..obs.trace import span

# Conservation slack: the engines compare float32 `free + 1e-5·max(|free|,1)
# >= req` (kernels/filters.py _RES_EPS) and accumulate usage in f32; the
# audit accumulates in f64, so allow the engine's slack twice over plus an
# absolute term for f32 drift.  Real violations move by whole-pod requests,
# orders of magnitude above this.
_EPS_REL = 2e-5
_EPS_ABS = 1e-3

#: violations stored verbatim per report; everything beyond is counted only
MAX_VIOLATIONS = 64

# constraint classes (Violation.kind)
K_UNPLACED = "unplaced"
K_INVALID_NODE = "invalid-node"
K_OVERCOMMIT = "overcommit"
K_PORT = "port-conflict"
K_VOLUME = "volume-conflict"
K_ATTACH = "attach-limit"
K_STORAGE = "storage"
K_GPU = "gpu"
K_ANTI_AFFINITY = "anti-affinity"
K_AFFINITY = "affinity"
K_SPREAD = "spread"
K_PREEMPTION = "preemption"

#: bit positions in the bulk pass's per-entry flag word — host-side
#: witness extraction decodes these (order is part of the jit/numpy pin)
_BULK_BITS = (
    K_INVALID_NODE,
    K_OVERCOMMIT,
    K_PORT,
    K_VOLUME,
    K_ATTACH,
    K_STORAGE,
    K_GPU,
)


def audit_enabled() -> bool:
    """Global default for the planners' auto-audit: SIMTPU_AUDIT=0
    disables (1/unset = on); per-command `--no-audit` overrides."""
    return os.environ.get("SIMTPU_AUDIT", "1") != "0"


def audit_jit_enabled() -> bool:
    """SIMTPU_AUDIT_JIT=0 forces the pure-numpy reference path for the
    bulk checks (the `SIMTPU_NATIVE=0` pattern: same verdicts, pinned by
    tests, for debugging and hosts where jit is unwanted)."""
    return os.environ.get("SIMTPU_AUDIT_JIT", "1") != "0"


@dataclass
class Violation:
    """One certified constraint violation, with its witness numbers."""

    kind: str  # constraint class (K_* above)
    row: int  # batch row / log position of the offending pod (-1 n/a)
    pod: str = ""  # pod name when known
    node: int = -1  # landing node index (-1 n/a)
    node_name: str = ""
    witness: Dict[str, object] = field(default_factory=dict)

    def describe(self) -> str:
        wit = ", ".join(f"{k}={v}" for k, v in self.witness.items())
        where = self.node_name or (str(self.node) if self.node >= 0 else "-")
        who = self.pod or (f"row {self.row}" if self.row >= 0 else "-")
        return f"[{self.kind}] pod {who} on node {where}" + (
            f" ({wit})" if wit else ""
        )


@dataclass
class AuditReport:
    """Outcome of one audit pass."""

    ok: bool
    checked: int  # placed pods audited
    total: int = 0  # total violations (violations list is capped)
    violations: List[Violation] = field(default_factory=list)
    by_class: Dict[str, int] = field(default_factory=dict)
    wall_s: float = 0.0
    mode: str = "jit"  # "jit" | "numpy"

    def add(self, v: Violation) -> None:
        self.ok = False
        self.total += 1
        self.by_class[v.kind] = self.by_class.get(v.kind, 0) + 1
        if len(self.violations) < MAX_VIOLATIONS:
            self.violations.append(v)

    #: per-violation witness records carried into counters()/--json; the
    #: stored list is already capped at MAX_VIOLATIONS, this caps the doc
    DETAIL_CAP = 16

    def counters(self) -> Dict[str, object]:
        """Machine-readable summary (CLI --json `engine.audit`, bench).
        Dirty reports carry the first DETAIL_CAP witnessed violations
        verbatim — pod, node, constraint class, witness numbers — so the
        --json consumer sees WHAT failed, not only how many."""
        doc: Dict[str, object] = {
            "ok": self.ok,
            "checked": self.checked,
            "violations": self.total,
            "by_class": dict(self.by_class),
            "wall_s": round(self.wall_s, 4),
            "mode": self.mode,
        }
        if not self.ok:
            doc["detail"] = [
                {
                    "class": v.kind,
                    "pod": v.pod or f"row {v.row}",
                    "node": v.node_name or (str(v.node) if v.node >= 0 else ""),
                    "witness": {k: str(w) for k, w in v.witness.items()},
                }
                for v in self.violations[: self.DETAIL_CAP]
            ]
        return doc

    def summary(self) -> str:
        if self.ok:
            return f"audit: clean ({self.checked} placements certified)"
        by = ", ".join(f"{k}×{n}" for k, n in sorted(self.by_class.items()))
        return (
            f"audit: {self.total} violation(s) over {self.checked} "
            f"placements ({by})"
        )


# ---------------------------------------------------------------------------
# Entry assembly — the audit's own view of one finished placement
# ---------------------------------------------------------------------------


@dataclass
class _Entries:
    """Placed-pod arrays in PLACEMENT ORDER (the sequential checks replay
    prefixes over this order — batch order for engine-level placements,
    log order for Simulator runs whose log saw preemption surgery)."""

    g: np.ndarray  # [M] group
    n: np.ndarray  # [M] landing node
    req: np.ndarray  # [M, R] padded request rows
    forced: np.ndarray  # [M] bound via spec.nodeName (filters bypassed)
    pin: np.ndarray  # [M] required node (-1 unpinned, -2 nonexistent)
    lvm: np.ndarray  # [M, V] VG allocation
    sdev: np.ndarray  # [M, SD] exclusive-device takes
    gpu: np.ndarray  # [M, GD] device memory load (shares × mem)
    rows: np.ndarray  # [M] original batch row / log position (reporting)
    names: Optional[List[str]] = None  # pod names, parallel (reporting)


def _pad_req(req: np.ndarray, r: int) -> np.ndarray:
    if req.shape[1] < r:
        req = np.pad(req, ((0, 0), (0, r - req.shape[1])))
    return np.asarray(req, np.float64)


def _entries_from_batch(tensors, batch, nodes, ext) -> _Entries:
    nodes = np.asarray(nodes)
    placed = np.flatnonzero(nodes >= 0)
    r = tensors.alloc.shape[1]
    m = len(placed)
    v = tensors.ext.vg_cap.shape[1]
    sd = tensors.ext.sdev_cap.shape[1]
    gd = tensors.ext.gpu_dev_total.shape[1]
    if ext is not None:
        lvm = np.asarray(ext["lvm_alloc"], np.float64)[placed]
        sdev = np.asarray(ext["dev_take"], bool)[placed]
        gpu = (
            np.asarray(ext["gpu_shares"], np.float64)[placed]
            * np.asarray(batch.ext["gpu_mem"], np.float64)[placed, None]
        )
    else:
        lvm = np.zeros((m, v))
        sdev = np.zeros((m, sd), bool)
        gpu = np.zeros((m, gd))
    names = None
    if batch.pods:
        names = [
            (batch.pods[int(i)].get("metadata") or {}).get("name", "")
            for i in placed
        ]
    return _Entries(
        g=np.asarray(batch.group, np.int64)[placed],
        n=nodes[placed].astype(np.int64),
        req=_pad_req(np.asarray(batch.req, np.float64)[placed], r),
        forced=np.asarray(batch.forced, bool)[placed],
        pin=np.asarray(batch.pin, np.int64)[placed],
        lvm=lvm,
        sdev=sdev,
        gpu=gpu,
        rows=placed,
        names=names,
    )


# ---------------------------------------------------------------------------
# Segmented prefix algebra (numpy reference)
# ---------------------------------------------------------------------------


def _by_node_order(n: np.ndarray) -> np.ndarray:
    """Stable order grouping entries by node, placement order within."""
    return np.argsort(n, kind="stable")


def _prefix_within(order: np.ndarray, n: np.ndarray, cols: np.ndarray):
    """Exclusive per-node prefix sums of `cols` along placement order.

    Returns [M, C] in ORIGINAL entry order: row j holds the column sums of
    all earlier-placed entries on the same node."""
    m = len(order)
    out = np.zeros_like(cols, dtype=np.float64)
    if not m:
        return out
    c = np.asarray(cols, np.float64)[order]
    ns = n[order]
    cum = np.cumsum(c, axis=0)
    excl = cum - c
    seg_start = np.concatenate([[True], ns[1:] != ns[:-1]])
    first = np.maximum.accumulate(np.where(seg_start, np.arange(m), 0))
    out[order] = excl - excl[first]
    return out


# ---------------------------------------------------------------------------
# Bulk checks — jitted pass + numpy twin
# ---------------------------------------------------------------------------


def _bulk_flags_numpy(tensors, e: _Entries, node_valid: np.ndarray) -> np.ndarray:
    """Per-entry violation flag word (bits per _BULK_BITS), numpy path."""
    m = len(e.n)
    flags = np.zeros(m, np.int64)
    if not m:
        return flags
    ext = tensors.ext
    static = np.asarray(tensors.static_mask, bool)
    volm = np.asarray(tensors.vol_mask, bool)
    nv = np.asarray(node_valid, bool)

    # node validity & pinning (order-free)
    ok_static = static[e.g, e.n] & volm[e.g, e.n]
    pin_ok = np.where(e.pin >= 0, e.n == e.pin, e.pin > -2)
    bad = np.where(
        e.forced,
        ~((e.pin >= 0) & (e.n == np.maximum(e.pin, 0)) & nv[e.n]),
        ~(ok_static & pin_ok & nv[e.n]),
    )
    flags |= bad.astype(np.int64) << _BULK_BITS.index(K_INVALID_NODE)

    order = _by_node_order(e.n)
    soft = ~e.forced  # forced pods bypass every feasibility filter

    # resource conservation at each step
    used = _prefix_within(order, e.n, e.req)
    alloc = np.asarray(tensors.alloc, np.float64)[e.n]
    free = alloc - used
    slack = _EPS_REL * np.maximum(np.abs(free), 1.0) + _EPS_ABS
    over = soft & np.any(e.req > free + slack, axis=1)
    flags |= over.astype(np.int64) << _BULK_BITS.index(K_OVERCOMMIT)

    # host ports
    if tensors.n_ports:
        want = np.asarray(tensors.ports, bool)[e.g]
        cnt = _prefix_within(order, e.n, want.astype(np.float64))
        pv = soft & np.any(want & (cnt > 0), axis=1)
        flags |= pv.astype(np.int64) << _BULK_BITS.index(K_PORT)

    # exclusive volumes + attach limits
    if tensors.n_vols:
        rw = np.asarray(tensors.vol_rw, bool)[e.g]
        ro = np.asarray(tensors.vol_ro, bool)[e.g]
        att = np.asarray(tensors.vol_att, bool)[e.g]
        present = rw | ro | att
        cnt_any = _prefix_within(order, e.n, present.astype(np.float64))
        cnt_rw = _prefix_within(order, e.n, rw.astype(np.float64))
        vv = soft & (
            np.any(rw & (cnt_any > 0), axis=1)
            | np.any(ro & (cnt_rw > 0), axis=1)
        )
        flags |= vv.astype(np.int64) << _BULK_BITS.index(K_VOLUME)
        cm = np.asarray(tensors.vol_class_mask, np.float64)
        on_node = cnt_any > 0
        new = att & ~on_node
        used_c = on_node.astype(np.float64) @ cm.T
        new_c = new.astype(np.float64) @ cm.T
        limits = np.asarray(tensors.attach_limits, np.float64)[e.n]
        av = soft & np.any((new_c > 0) & (used_c + new_c > limits + 1e-9), axis=1)
        flags |= av.astype(np.int64) << _BULK_BITS.index(K_ATTACH)

    # Open-Local storage: VG space + exclusive-device double-takes
    if ext.vg_cap.shape[1] or ext.sdev_cap.shape[1]:
        sv = np.zeros(m, bool)
        if ext.vg_cap.shape[1]:
            avail0 = (ext.vg_cap - ext.vg_req0).astype(np.float64)[e.n]
            used_vg = _prefix_within(order, e.n, e.lvm)
            free_vg = avail0 - used_vg
            vg_slack = _EPS_REL * np.maximum(np.abs(free_vg), 1.0) + _EPS_ABS
            sv |= np.any(e.lvm > free_vg + vg_slack, axis=1)
        if ext.sdev_cap.shape[1]:
            free0 = ((ext.sdev_cap > 0) & ~ext.sdev_alloc0)[e.n]
            taken = _prefix_within(order, e.n, e.sdev.astype(np.float64)) > 0
            sv |= np.any(e.sdev & (~free0 | taken), axis=1)
        sv &= soft
        flags |= sv.astype(np.int64) << _BULK_BITS.index(K_STORAGE)

    # GPU-share device memory
    if ext.gpu_dev_total.shape[1]:
        total = ext.gpu_dev_total.astype(np.float64)[e.n]
        used_g = _prefix_within(order, e.n, e.gpu)
        free_g = total - used_g
        g_slack = _EPS_REL * np.maximum(np.abs(free_g), 1.0) + _EPS_ABS
        gv = soft & np.any(e.gpu > free_g + g_slack, axis=1)
        flags |= gv.astype(np.int64) << _BULK_BITS.index(K_GPU)
    return flags


_bulk_jit = None


def _get_bulk_jit():
    """The jitted twin of `_bulk_flags_numpy`, built lazily (importing jax
    only when the jit path actually runs)."""
    global _bulk_jit
    if _bulk_jit is not None:
        return _bulk_jit
    import jax
    import jax.numpy as jnp
    from jax import lax

    def prefix_within(order, n, cols):
        m = order.shape[0]
        c = cols[order]
        ns = n[order]
        cum = jnp.cumsum(c, axis=0)
        excl = cum - c
        seg_start = jnp.concatenate(
            [jnp.ones(1, bool), ns[1:] != ns[:-1]]
        )
        first = lax.cummax(jnp.where(seg_start, jnp.arange(m), 0))
        out = excl - excl[first]
        return jnp.zeros_like(cols).at[order].set(out)

    def bulk(
        alloc, static, volm, nv, ports, vol_rw, vol_ro, vol_att, cmask,
        limits, vg_avail0, sdev_free0, gpu_total,
        g, n, req, forced, pin, lvm, sdev, gpu,
    ):
        m = g.shape[0]
        flags = jnp.zeros(m, jnp.int32)
        ok_static = static[g, n] & volm[g, n]
        pin_ok = jnp.where(pin >= 0, n == pin, pin > -2)
        bad = jnp.where(
            forced,
            ~((pin >= 0) & (n == jnp.maximum(pin, 0)) & nv[n]),
            ~(ok_static & pin_ok & nv[n]),
        )
        flags |= bad.astype(jnp.int32) << _BULK_BITS.index(K_INVALID_NODE)
        # stable (node, position) order; int64 key — node·(m+1) overflows
        # int32 at planning scale
        key = n.astype(jnp.int64) * (m + 1) + jnp.arange(m)
        order = jnp.argsort(key)
        soft = ~forced

        used = prefix_within(order, n, req)
        free = alloc[n] - used
        slack = _EPS_REL * jnp.maximum(jnp.abs(free), 1.0) + _EPS_ABS
        over = soft & jnp.any(req > free + slack, axis=1)
        flags |= over.astype(jnp.int32) << _BULK_BITS.index(K_OVERCOMMIT)

        if ports.shape[1]:
            want = ports[g]
            cnt = prefix_within(order, n, want.astype(jnp.float64))
            pv = soft & jnp.any(want & (cnt > 0), axis=1)
            flags |= pv.astype(jnp.int32) << _BULK_BITS.index(K_PORT)

        if vol_rw.shape[1]:
            rw, ro, att = vol_rw[g], vol_ro[g], vol_att[g]
            present = rw | ro | att
            cnt_any = prefix_within(order, n, present.astype(jnp.float64))
            cnt_rw = prefix_within(order, n, rw.astype(jnp.float64))
            vv = soft & (
                jnp.any(rw & (cnt_any > 0), axis=1)
                | jnp.any(ro & (cnt_rw > 0), axis=1)
            )
            flags |= vv.astype(jnp.int32) << _BULK_BITS.index(K_VOLUME)
            on_node = cnt_any > 0
            new = att & ~on_node
            used_c = on_node.astype(jnp.float64) @ cmask.T
            new_c = new.astype(jnp.float64) @ cmask.T
            av = soft & jnp.any(
                (new_c > 0) & (used_c + new_c > limits[n] + 1e-9), axis=1
            )
            flags |= av.astype(jnp.int32) << _BULK_BITS.index(K_ATTACH)

        sv = jnp.zeros(m, bool)
        if vg_avail0.shape[1]:
            used_vg = prefix_within(order, n, lvm)
            free_vg = vg_avail0[n] - used_vg
            vg_slack = _EPS_REL * jnp.maximum(jnp.abs(free_vg), 1.0) + _EPS_ABS
            sv |= jnp.any(lvm > free_vg + vg_slack, axis=1)
        if sdev_free0.shape[1]:
            taken = prefix_within(order, n, sdev.astype(jnp.float64)) > 0
            sv |= jnp.any(sdev & (~sdev_free0[n] | taken), axis=1)
        flags |= (soft & sv).astype(jnp.int32) << _BULK_BITS.index(K_STORAGE)

        if gpu_total.shape[1]:
            used_g = prefix_within(order, n, gpu)
            free_g = gpu_total[n] - used_g
            g_slack = _EPS_REL * jnp.maximum(jnp.abs(free_g), 1.0) + _EPS_ABS
            gv = soft & jnp.any(gpu > free_g + g_slack, axis=1)
            flags |= gv.astype(jnp.int32) << _BULK_BITS.index(K_GPU)
        return flags

    _bulk_jit = jax.jit(
        bulk,
        static_argnames=(),
    )
    return _bulk_jit


def _bulk_flags_jax(tensors, e: _Entries, node_valid: np.ndarray) -> np.ndarray:
    from jax.experimental import enable_x64

    ext = tensors.ext
    fn = _get_bulk_jit()
    # x64 at trace time: the audit accumulates prefixes in f64 (like the
    # numpy twin) — verdict parity between the modes is a pinned contract
    with enable_x64():
        flags = fn(
            np.asarray(tensors.alloc, np.float64),
            np.asarray(tensors.static_mask, bool),
            np.asarray(tensors.vol_mask, bool),
            np.asarray(node_valid, bool),
            np.asarray(tensors.ports, bool),
            np.asarray(tensors.vol_rw, bool),
            np.asarray(tensors.vol_ro, bool),
            np.asarray(tensors.vol_att, bool),
            np.asarray(tensors.vol_class_mask, np.float64),
            np.asarray(tensors.attach_limits, np.float64),
            (ext.vg_cap - ext.vg_req0).astype(np.float64),
            np.asarray((ext.sdev_cap > 0) & ~ext.sdev_alloc0, bool),
            ext.gpu_dev_total.astype(np.float64),
            e.g.astype(np.int64),
            e.n.astype(np.int64),
            e.req,
            e.forced,
            e.pin.astype(np.int64),
            e.lvm,
            e.sdev,
            e.gpu,
        )
    return np.asarray(flags).astype(np.int64)


# ---------------------------------------------------------------------------
# Order-dependent interpod / spread checks (sorted-event prefix algebra)
# ---------------------------------------------------------------------------


def _term_events(tensors, e: _Entries, t: int, incid: np.ndarray):
    """(positions, domains) of entries carrying `incid` for term t, on
    nodes that carry the term's topology key (the engine only counts
    those — cnt_total semantics)."""
    k = int(tensors.term_topo_key[t])
    dom = np.asarray(tensors.node_dom[k], np.int64)
    d = dom[e.n]
    hit = incid[e.g] & (d >= 0)
    pos = np.flatnonzero(hit)
    return pos, d[pos], dom


def _count_before(ev_pos, ev_dom, q_pos, q_dom):
    """#events with domain == q_dom and position < q_pos, per query —
    one composite-key searchsorted (events are position-sorted within a
    domain after the stable composite sort)."""
    m_key = max(int(ev_pos.max(initial=0)), int(q_pos.max(initial=0))) + 2
    ev_key = np.sort(ev_dom.astype(np.int64) * m_key + ev_pos)
    lo = np.searchsorted(ev_key, q_dom.astype(np.int64) * m_key)
    hi = np.searchsorted(ev_key, q_dom.astype(np.int64) * m_key + q_pos)
    return hi - lo


def _interpod_spread_checks(
    tensors, e: _Entries, node_valid: np.ndarray, report: AuditReport
) -> None:
    """Required (anti-)affinity and DoNotSchedule spread, replayed over
    the placement order with the engine's exact predicate semantics
    (`kernels/filters.py interpod_filter` / `topology_spread_filter`)."""
    t_n = tensors.n_terms
    m = len(e.n)
    if not t_n or not m:
        return
    a_aff = np.asarray(tensors.a_aff_req, bool)
    a_anti = np.asarray(tensors.a_anti_req, bool)
    s_match = np.asarray(tensors.s_match, bool)
    sp_hard = np.asarray(tensors.spread_hard, np.float64)
    static = np.asarray(tensors.static_mask, bool)
    nv = np.asarray(node_valid, bool)
    soft_rows = np.flatnonzero(~e.forced)

    def _viol(kind, j, t, **wit):
        report.add(
            Violation(
                kind=kind,
                row=int(e.rows[j]),
                pod=e.names[j] if e.names else "",
                node=int(e.n[j]),
                node_name=tensors.node_names[int(e.n[j])],
                witness={"term": int(t), **wit},
            )
        )

    # ---- anti-affinity: own terms + the symmetric direction -------------
    anti_terms = np.flatnonzero(a_anti.any(axis=0))
    for t in anti_terms:
        ev_pos, ev_dom, dom = _term_events(tensors, e, t, s_match[:, t])
        own_pos, own_dom, _ = _term_events(tensors, e, t, a_anti[:, t])
        d_q = dom[e.n]
        # pods owning the term: no earlier matching pod in the domain
        q = soft_rows[a_anti[e.g[soft_rows], t] & (d_q[soft_rows] >= 0)]
        if len(q) and len(ev_pos):
            cnt = _count_before(ev_pos, ev_dom, q, d_q[q])
            for idx in np.flatnonzero(cnt > 0):
                _viol(
                    K_ANTI_AFFINITY, int(q[idx]), t,
                    matching_in_domain=int(cnt[idx]),
                )
        # pods MATCHING the term: no earlier owner in the domain
        q = soft_rows[s_match[e.g[soft_rows], t] & (d_q[soft_rows] >= 0)]
        if len(q) and len(own_pos):
            cnt = _count_before(own_pos, own_dom, q, d_q[q])
            for idx in np.flatnonzero(cnt > 0):
                _viol(
                    K_ANTI_AFFINITY, int(q[idx]), t,
                    owners_in_domain=int(cnt[idx]),
                )

    # ---- required affinity (with the first-pod-in-series escape) --------
    aff_groups = np.flatnonzero(a_aff.any(axis=1))
    if len(aff_groups):
        aff_terms = np.flatnonzero(a_aff.any(axis=0))
        events = {
            int(t): _term_events(tensors, e, t, s_match[:, t])
            for t in aff_terms
        }
        for j in soft_rows:
            g = int(e.g[j])
            terms = np.flatnonzero(a_aff[g])
            if not len(terms):
                continue
            sat = True
            total_before = 0
            missing = -1
            for t in terms:
                ev_pos, ev_dom, dom = events[int(t)]
                d_j = dom[e.n[j]]
                total_before += int(np.searchsorted(np.sort(ev_pos), j))
                if d_j < 0:
                    sat, missing = False, int(t)
                    continue
                cnt = _count_before(
                    ev_pos, ev_dom, np.array([j]), np.array([d_j])
                )[0]
                if cnt == 0:
                    sat, missing = False, int(t)
            if sat:
                continue
            # escape: no matching pod anywhere yet, pod matches its own
            # terms, and the node carries every topology key
            keys_ok = all(
                events[int(t)][2][e.n[j]] >= 0 for t in terms
            )
            self_ok = bool(np.all(s_match[g, terms]))
            if total_before == 0 and self_ok and keys_ok:
                continue
            _viol(K_AFFINITY, j, missing, matching_before=total_before)

    # ---- DoNotSchedule topology spread ----------------------------------
    hard_pairs = np.argwhere(sp_hard > 0)
    by_term: Dict[int, List[int]] = {}
    for g, t in hard_pairs:
        by_term.setdefault(int(t), []).append(int(g))
    for t, groups in by_term.items():
        ev_pos, ev_dom, dom = _term_events(tensors, e, t, s_match[:, t])
        d_q = dom[e.n]
        for g in groups:
            skew = float(sp_hard[g, t])
            q = soft_rows[(e.g[soft_rows] == g)]
            if not len(q):
                continue
            missing_key = q[d_q[q] < 0]
            for j in missing_key:
                _viol(K_SPREAD, j, t, reason="node lacks topology key")
            q = q[d_q[q] >= 0]
            if not len(q):
                continue
            # eligible domains: those containing >= 1 node passing the
            # pod's static filters (pinned pods audited per-pod below)
            elig_nodes = static[g] & nv
            cnt_q = _count_before(ev_pos, ev_dom, q, d_q[q])
            for idx, j in enumerate(q):
                pin = int(e.pin[j])
                en = elig_nodes
                if pin >= 0:
                    en = np.zeros_like(elig_nodes)
                    en[pin] = elig_nodes[pin]
                min_c = _min_over_eligible(dom, en, ev_pos, ev_dom, int(j))
                if cnt_q[idx] + 1.0 - min_c > skew + 1e-9:
                    _viol(
                        K_SPREAD, j, t,
                        count=int(cnt_q[idx]), min_eligible=int(min_c),
                        max_skew=int(skew),
                    )


def _min_over_eligible(
    dom: np.ndarray, elig_nodes: np.ndarray, ev_pos: np.ndarray,
    ev_dom: np.ndarray, before: int,
) -> int:
    """min over eligible domains of the matching-pod count strictly before
    placement position `before` — the rank-threshold formulation: the min
    reaches v+1 exactly when the LAST eligible domain gains its (v+1)-th
    event, so min(i) = #{v : t_v < i} with t_v the max over domains of the
    rank-v event position."""
    E = np.unique(dom[(dom >= 0) & elig_nodes])
    if not len(E):
        return 0
    in_e = np.isin(ev_dom, E) & (ev_pos < before)
    d_e, p_e = ev_dom[in_e], ev_pos[in_e]
    if not len(d_e):
        return 0
    per_dom = np.zeros(len(E), np.int64)
    comp = np.searchsorted(E, d_e)
    np.add.at(per_dom, comp, 1)
    c_star = int(per_dom.min())
    if c_star == 0:
        return 0
    order = np.lexsort((p_e, comp))
    comp_s, pos_s = comp[order], p_e[order]
    seg_start = np.concatenate([[True], comp_s[1:] != comp_s[:-1]])
    first = np.maximum.accumulate(
        np.where(seg_start, np.arange(len(comp_s)), 0)
    )
    rank = np.arange(len(comp_s)) - first
    t_v = np.zeros(c_star, np.int64)
    keep = rank < c_star
    np.maximum.at(t_v, rank[keep], pos_s[keep])
    return int(np.searchsorted(t_v, before, side="left"))


# ---------------------------------------------------------------------------
# Public entry points
# ---------------------------------------------------------------------------


def extras_from_log(pc) -> Dict[str, np.ndarray]:
    """Per-batch-row extras (`lvm_alloc`/`dev_take`/`gpu_shares`) rebuilt
    from a `PlacedCluster`'s engine ext log — the shape `audit_placement`
    consumes when the caller kept the log but not `place()`'s extras."""
    t = pc.tensors
    p = len(pc.nodes)
    ext = {
        "lvm_alloc": np.zeros((p, t.ext.vg_cap.shape[1])),
        "dev_take": np.zeros((p, t.ext.sdev_cap.shape[1]), bool),
        "gpu_shares": np.zeros((p, t.ext.gpu_dev_total.shape[1])),
    }
    rows = pc.log_row
    if len(rows):
        ext["lvm_alloc"][rows] = np.asarray(pc.engine.ext_log["vg_alloc"])
        ext["dev_take"][rows] = np.asarray(pc.engine.ext_log["sdev_take"])
        ext["gpu_shares"][rows] = np.asarray(pc.engine.ext_log["gpu_shares"])
    return ext


def audit_placement(
    tensors,
    batch,
    nodes,
    ext: Optional[dict] = None,
    node_valid: Optional[np.ndarray] = None,
    require_all: bool = False,
    expect_mask: Optional[np.ndarray] = None,
    entries: Optional[_Entries] = None,
    jit: Optional[bool] = None,
) -> AuditReport:
    """Audit one finished engine-level placement.

    `nodes` is the [P] landing-node vector `Engine.place` returned for
    `batch` (-1 = unplaced), `ext` the matching extras dict
    (`lvm_alloc`/`dev_take`/`gpu_shares`, per batch row).  `node_valid`
    is the candidate-cluster mask the placement ran under.  With
    `require_all`, every row of `expect_mask` (default: all rows) that is
    unplaced is a completeness violation — the all-or-nothing contract of
    an ACCEPTED capacity candidate.  `entries` substitutes a pre-built
    placement-order view (the Simulator path).  `jit=None` follows
    ``SIMTPU_AUDIT_JIT``.
    """
    t0 = time.perf_counter()
    n = tensors.alloc.shape[0]
    nv = (
        np.ones(n, bool)
        if node_valid is None
        else np.asarray(node_valid, bool)
    )
    use_jit = audit_jit_enabled() if jit is None else bool(jit)
    e = entries if entries is not None else _entries_from_batch(
        tensors, batch, nodes, ext
    )
    report = AuditReport(
        ok=True, checked=len(e.n), mode="jit" if use_jit else "numpy"
    )

    if require_all:
        nodes_a = np.asarray(nodes)
        exp = (
            np.ones(len(nodes_a), bool)
            if expect_mask is None
            else np.asarray(expect_mask, bool)
        )
        for j in np.flatnonzero((nodes_a < 0) & exp):
            name = ""
            if batch is not None and batch.pods:
                name = (batch.pods[int(j)].get("metadata") or {}).get("name", "")
            report.add(
                Violation(
                    kind=K_UNPLACED, row=int(j), pod=name,
                    witness={"claimed": "all-or-nothing"},
                )
            )

    with span("audit.pass", pods=int(len(e.n)), mode=report.mode):
        flags = (
            _bulk_flags_jax(tensors, e, nv)
            if use_jit
            else _bulk_flags_numpy(tensors, e, nv)
        )
        if flags.any():
            _decode_bulk(tensors, e, nv, flags, report)
        _interpod_spread_checks(tensors, e, nv, report)
    report.wall_s = time.perf_counter() - t0
    # registry mirror (obs/metrics.py): process-monotone audit telemetry
    # next to the other counter families, under `audit.total_*` — the
    # per-plan `audit.ok/checked/violations/wall_s/mode` names in the
    # --json metrics block are reserved for the SHIPPED candidate's
    # verdict (overlaid from PlanResult.audit in Applier.run), so the
    # aggregate counters must not collide with them: a collision would
    # leak one plan's verdict into the next plan's block and flip the
    # field's type between a scalar and a histogram dict under one
    # schema_version
    REGISTRY.counter("audit.total_passes").inc()
    REGISTRY.counter("audit.total_checked").inc(report.checked)
    REGISTRY.counter("audit.total_violations").inc(report.total)
    return report


def _decode_bulk(
    tensors, e: _Entries, nv: np.ndarray, flags: np.ndarray,
    report: AuditReport,
) -> None:
    """Turn bulk flag bits into witnessed Violations (host side; flagged
    rows are few, so the witness recomputation is per-row numpy)."""
    ext = tensors.ext
    order = _by_node_order(e.n)
    used = _prefix_within(order, e.n, e.req)
    for bit, kind in enumerate(_BULK_BITS):
        rows = np.flatnonzero((flags >> bit) & 1)
        for j in rows:
            wit: Dict[str, object] = {}
            node = int(e.n[j])
            if kind == K_INVALID_NODE:
                g = int(e.g[j])
                wit = {
                    "static_mask": bool(tensors.static_mask[g, node]),
                    "vol_mask": bool(tensors.vol_mask[g, node]),
                    "node_valid": bool(nv[node]),
                    "pin": int(e.pin[j]),
                    "forced": bool(e.forced[j]),
                }
            elif kind == K_OVERCOMMIT:
                alloc = np.asarray(tensors.alloc, np.float64)[node]
                free = alloc - used[j]
                r_bad = int(np.argmax(e.req[j] - free))
                wit = {
                    "resource": tensors.resource_names[r_bad],
                    "request": float(e.req[j, r_bad]),
                    "free_at_step": float(free[r_bad]),
                    "allocatable": float(alloc[r_bad]),
                }
            elif kind == K_GPU:
                wit = {"gpu_load": float(e.gpu[j].sum())}
            elif kind == K_STORAGE:
                wit = {"lvm": float(e.lvm[j].sum()), "sdev": int(e.sdev[j].sum())}
            report.add(
                Violation(
                    kind=kind,
                    row=int(e.rows[j]),
                    pod=e.names[j] if e.names else "",
                    node=node,
                    node_name=tensors.node_names[node],
                    witness=wit,
                )
            )


def audit_simulation(
    sim, jit: Optional[bool] = None, inject: bool = False
) -> AuditReport:
    """Audit a live `Simulator`'s full state: the engine placement log (in
    LOG order — preemption surgery reorders it) plus preemption legality
    over `sim._preempted`.  `inject` corrupts the audit's OWN view of the
    log (the SIMTPU_AUDIT_INJECT lever): the shipped result is untouched,
    but the audit fails as if the engine had diverged, driving the
    fallback path end-to-end."""
    from ..core.objects import name_of, namespace_of, pod_priority
    from ..core.tensorize import _group_of_pod

    eng = sim._engine
    tz = sim._tensorizer
    tensors = tz.freeze()
    r = tensors.alloc.shape[1]
    m = len(eng.placed_node)
    ext_log = eng.ext_log
    pins = np.full(m, -1, np.int64)
    names: List[str] = []
    for i, pod in enumerate(sim._scheduled):
        names.append(name_of(pod))
        if sim._placed_forced[i]:
            pins[i] = eng.placed_node[i]
            continue
        _, pin_name = _group_of_pod(pod)
        if pin_name is not None:
            pins[i] = tz.node_idx.get(pin_name, -2)
    gpu_mem = (
        np.asarray(ext_log["gpu_mem"], np.float64)
        if m
        else np.zeros(0)
    )
    e = _Entries(
        g=np.asarray(eng.placed_group, np.int64),
        n=np.asarray(eng.placed_node, np.int64),
        req=_pad_req(eng.log_req_matrix(r), r),
        forced=np.asarray(sim._placed_forced, bool),
        pin=pins,
        lvm=(
            np.asarray(ext_log["vg_alloc"], np.float64)
            if m
            else np.zeros((0, tensors.ext.vg_cap.shape[1]))
        ),
        sdev=(
            np.asarray(ext_log["sdev_take"], bool)
            if m
            else np.zeros((0, tensors.ext.sdev_cap.shape[1]), bool)
        ),
        gpu=(
            np.asarray(ext_log["gpu_shares"], np.float64) * gpu_mem[:, None]
            if m
            else np.zeros((0, tensors.ext.gpu_dev_total.shape[1]))
        ),
        rows=np.arange(m),
        names=names,
    )
    if inject and m:
        static = np.asarray(tensors.static_mask, bool)
        for j in np.flatnonzero(~e.forced):
            bad = np.flatnonzero(~static[int(e.g[j])])
            if len(bad):
                e.n[j] = int(bad[0])
                break
        else:
            if m > 1:
                e.n[:] = e.n[0]  # all-pass masks: force overcommit
    node_valid = eng.node_valid
    report = audit_placement(
        tensors, None, e.n, node_valid=node_valid, entries=e, jit=jit
    )

    # ---- preemption legality --------------------------------------------
    placed_by_key: Dict[str, List[int]] = {}
    for i, pod in enumerate(sim._scheduled):
        placed_by_key.setdefault(
            f"{namespace_of(pod)}/{name_of(pod)}", []
        ).append(i)
    for pre in sim._preempted:
        vkey = f"{namespace_of(pre.pod)}/{name_of(pre.pod)}"
        owners = placed_by_key.get(pre.preempted_by)
        vict_prio = pod_priority(pre.pod)
        if not owners:
            report.add(
                Violation(
                    kind=K_PREEMPTION, row=-1, pod=vkey,
                    witness={
                        "reason": "preemptor not placed",
                        "preemptor": pre.preempted_by,
                    },
                )
            )
            continue
        pre_prio = max(sim._placed_prio[i] for i in owners)
        if not vict_prio < pre_prio:
            report.add(
                Violation(
                    kind=K_PREEMPTION, row=-1, pod=vkey,
                    witness={
                        "reason": "victim not strictly lower priority",
                        "victim_priority": vict_prio,
                        "preemptor_priority": pre_prio,
                        "preemptor": pre.preempted_by,
                    },
                )
            )
        if vkey in placed_by_key:
            report.add(
                Violation(
                    kind=K_PREEMPTION, row=-1, pod=vkey,
                    witness={
                        "reason": "victim reported evicted but still placed",
                        "preemptor": pre.preempted_by,
                    },
                )
            )
    return report


def audit_placed_cluster(pc, progress=None, inject: bool = False):
    """Audit a `PlacedCluster`'s base placement (the fault sweep's
    drain-from state); on failure re-place through the serial exact scan
    and re-audit — the divergence-safe fallback at the sweep boundary.

    Returns `(pc, audit_doc, hard_failure_message_or_None)`: `pc` is the
    certified cluster (the fallback's when the original failed its
    audit), `audit_doc` the machine-readable record the CLI surfaces."""
    say = progress or (lambda s: None)
    tensors, batch = pc.tensors, pc.batch
    nodes = np.asarray(pc.nodes)
    nodes_aud = (
        inject_divergence(tensors, batch, nodes) if inject else nodes
    )
    rep = audit_placement(
        tensors, batch, nodes_aud, extras_from_log(pc),
        node_valid=pc.engine.node_valid,
    )
    if rep.ok:
        return pc, rep.counters(), None
    say(
        f"audit FAILED on the base placement ({rep.summary()}) — "
        "re-placing through the serial exact scan"
    )
    from ..engine.scan import Engine
    from ..faults.drain import PlacedCluster

    fb = Engine(pc.tz)
    fb.node_valid = pc.engine.node_valid
    fb.speculate = False
    fb.compact = False
    fb.sched_config = pc.engine.sched_config
    nodes_f, reasons_f, _ = fb.place(batch)
    pc_f = PlacedCluster(
        tz=pc.tz, tensors=tensors, batch=batch, engine=fb,
        nodes=nodes_f, reasons=reasons_f,
    )
    rep_f = audit_placement(
        tensors, batch, pc_f.nodes, extras_from_log(pc_f),
        node_valid=fb.node_valid,
    )
    audit_doc = {
        **rep.counters(),
        "fallback": True,
        "fallback_audit": rep_f.counters(),
        "divergence": divergence_diagnostic(
            tensors, batch, nodes_aud, pc_f.nodes, rep
        ),
    }
    if not rep_f.ok:
        return pc_f, audit_doc, (
            "audit failure: the base placement violates its claimed "
            "constraints and the serial-exact fallback did not certify "
            f"either ({rep_f.summary()})"
        )
    audit_doc["ok"] = True
    return pc_f, audit_doc, None


# ---------------------------------------------------------------------------
# Divergence diagnostics + test-lever injection
# ---------------------------------------------------------------------------


def divergence_diagnostic(
    tensors, batch, bad_nodes, serial_nodes, report: AuditReport,
    planes: Optional[List[str]] = None,
) -> Dict[str, object]:
    """The structured record of one caught divergence: the first pod whose
    audited placement differs from the serial-exact answer, the two
    landing nodes, the violation classes that tripped the audit, and
    (when the caller compared carries) the differing state planes."""
    bad = np.asarray(bad_nodes)
    good = np.asarray(serial_nodes)
    diff = np.flatnonzero(bad != good)
    first = int(diff[0]) if len(diff) else -1
    doc: Dict[str, object] = {
        "divergent_pods": int(len(diff)),
        "first_divergent_row": first,
        "violations": dict(report.by_class),
    }
    if first >= 0:
        if batch is not None and batch.pods:
            doc["first_divergent_pod"] = (
                (batch.pods[first].get("metadata") or {}).get("name", "")
            )
        bn, gn = int(bad[first]), int(good[first])
        doc["audited_node"] = (
            tensors.node_names[bn] if bn >= 0 else "<unplaced>"
        )
        doc["serial_node"] = (
            tensors.node_names[gn] if gn >= 0 else "<unplaced>"
        )
    if planes:
        doc["state_planes"] = list(planes)
    return doc


def inject_divergence_enabled() -> bool:
    """Test lever (docs/robustness.md): SIMTPU_AUDIT_INJECT=1 corrupts the
    PRIMARY engine's accepted placement right before its audit, so the
    audit-failure → serial-fallback → re-audit path runs end-to-end on
    demand.  Fallback runs are never injected."""
    return os.environ.get("SIMTPU_AUDIT_INJECT", "0") == "1"


def inject_divergence(tensors, batch, nodes: np.ndarray) -> np.ndarray:
    """Corrupt one placement: move the first non-forced placed pod onto a
    node its static mask rejects (or, when every node passes, onto the
    most loaded node to force overcommit)."""
    nodes = np.asarray(nodes).copy()
    forced = np.asarray(batch.forced, bool)
    static = np.asarray(tensors.static_mask, bool)
    for j in np.flatnonzero((nodes >= 0) & ~forced):
        g = int(batch.group[j])
        bad = np.flatnonzero(~static[g])
        if len(bad):
            nodes[j] = int(bad[0])
            return nodes
    # all-pass masks: stack every placed pod onto one node → overcommit
    placed = np.flatnonzero((nodes >= 0) & ~forced)
    if len(placed) > 1:
        nodes[placed] = nodes[placed[0]]
    return nodes
