"""Batched assignment relaxation: the solve backend's convex core.

The capacity question "do these pods fit on base + i clones?" is, after
tensorization, a transportation problem: interchangeable pods of one
(group, request) CLASS must be distributed over the nodes their group's
static/volume feasibility planes allow, without exceeding any node's
remaining allocatable vector (the synthetic `pods` resource folds the
max-pods cap in, simtpu/core/tensorize.py).  Dropping integrality gives a
convex feasibility problem per candidate count — and because candidates
differ ONLY in the `node_valid` membership mask (the same lever the
batched sweep vmaps over, simtpu/parallel/sweep.py), the whole capacity
search vmaps into one projected-gradient solve over the candidate axis.

Per candidate the kernel minimizes the overcommit penalty

    f(y) = 1/2 * sum_{n,r} relu( (y^T req)[n,r] - free[n,r] )^2

over the product of per-class simplices {y[c,:] >= 0 off-mask-zero,
sum_n y[c,n] = cnt[c]} by projected gradient with an exact sort-based
simplex projection.  The step size 1/sigma_max(req)^2 is the reciprocal
Lipschitz constant of grad f, computed host-side once per problem.

Verdicts are deliberately asymmetric in what they may be trusted for:

- residual <= RESIDUAL_TOL says the RELAXATION is (numerically) feasible
  — a necessary condition for any integral placement, so its first-True
  candidate is a sound LOWER BOUND once the candidate below it is
  certified infeasible;
- infeasibility is never concluded from non-convergence.  The planner
  fetches the boundary candidate's y and builds a weak-duality (Farkas)
  certificate host-side in float64: with prices lam = relu(load - free),
  any feasible assignment must satisfy

      sum_c cnt[c] * min_{n in feas(c)} (lam req_c)[n]  <=  sum lam*free

  so a strict violation PROVES no fractional (hence no integral)
  placement exists at that count.  f32 solver noise cannot fake the
  proof — the certificate is re-evaluated exactly, from scratch.

Shape discipline (satellite: the PR-1/PR-2 contract): every axis pads up
to a power of two before dispatch, so repeated solves across a capacity
sweep — and across plans of nearby sizes — reuse one compiled executable
per bucket.  The traced body bumps `compile.solve` (COMPILE_COUNT_KINDS)
once per distinct bucket, which is what the trace-budget test pins.
"""

from __future__ import annotations

import os
from functools import partial
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.scan import count_trace, fetch_outputs
from ..obs.trace import span

#: relaxed-feasibility acceptance: max scaled overcommit after the final
#: projection (capacities are scaled to ~1.0; integral thresholds are
#: sharp, so the rounding repair absorbs anything this small)
RESIDUAL_TOL = 1e-3

#: relative slack the float64 certificate must clear before infeasibility
#: is PROVEN — guards the f32→f64 recompute against degenerate lam ~ 0
CERT_MARGIN = 1e-9


def solver_iters() -> int:
    """Projected-gradient iteration budget (SIMTPU_SOLVER_ITERS, default
    400).  Static under jit — changing it recompiles, so it is read once
    per solve, not per candidate."""
    return int(os.environ.get("SIMTPU_SOLVER_ITERS", "400"))


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length() if n > 0 else 1


class RelaxProblem(NamedTuple):
    """Host-side problem statement, class-collapsed and capacity-scaled.

    Classes are equivalence classes of the FREE (un-pinned) pods under
    (request-row, feasibility-row): pods of one class are interchangeable
    for both feasibility and capacity, which shrinks the variable matrix
    from [P, N] to [C, N] — C tracks the number of DISTINCT pod shapes,
    not the pod count (uniform mixes collapse to a handful of rows no
    matter how many workloads they ship)."""

    cls_rows: List[np.ndarray]  # per class: batch row indices (free pods)
    cls_group: np.ndarray  # [C] i32 group of each class
    cnt: np.ndarray  # [C] f32 pod count per class
    req: np.ndarray  # [C, R] f32 scaled per-pod request
    req_raw: np.ndarray  # [C, R] f64 unscaled (rounding/certificate)
    feas: np.ndarray  # [C, N] bool static & volume feasibility
    fixed: np.ndarray  # [N, R] f32 scaled pinned/forced load
    fixed_raw: np.ndarray  # [N, R] f64 unscaled
    cap: np.ndarray  # [N, R] f32 scaled allocatable
    cap_raw: np.ndarray  # [N, R] f64 unscaled
    scale: np.ndarray  # [R] f64 per-resource scale divisor
    lr: float  # 1/L step size for the PGD kernel
    pinned_rows: np.ndarray  # [Q] batch rows with pin >= 0


def build_relax_problem(tensors, batch) -> RelaxProblem:
    """Collapse a tensorized capacity problem into the relaxation's
    class-level statement.  Pinned rows (DaemonSet clone pods and
    spec.nodeName pods) become fixed per-node load — the per-candidate
    membership mask gates them inside the kernel, which is exactly the
    phantom-pod semantics of the batched sweep."""
    pin = np.asarray(batch.pin)
    free = np.flatnonzero(pin < 0)
    pinned = np.flatnonzero(pin >= 0)

    n, r = tensors.alloc.shape
    req_all = np.asarray(batch.req, np.float64)
    if req_all.shape[1] < r:
        req_all = np.pad(req_all, ((0, 0), (0, r - req_all.shape[1])))

    fixed_raw = np.zeros((n, r), np.float64)
    if len(pinned):
        np.add.at(fixed_raw, pin[pinned], req_all[pinned])

    group = np.asarray(batch.group, np.int64)
    if len(free):
        key = np.concatenate(
            [group[free, None].astype(np.float64), req_all[free]], axis=1
        )
        uniq, inverse = np.unique(key, axis=0, return_inverse=True)
        c = uniq.shape[0]
        cls_rows = [free[np.flatnonzero(inverse == ci)] for ci in range(c)]
        cls_group = uniq[:, 0].astype(np.int32)
        req_raw = uniq[:, 1:]
        cnt = np.array([len(rows) for rows in cls_rows], np.float32)
    else:
        cls_rows, c = [], 0
        cls_group = np.zeros(0, np.int32)
        req_raw = np.zeros((0, r), np.float64)
        cnt = np.zeros(0, np.float32)

    static = np.asarray(tensors.static_mask, bool)
    vol = np.asarray(tensors.vol_mask, bool)
    if vol.shape[0] == 1 and static.shape[0] > 1:
        vol = np.broadcast_to(vol, static.shape)
    feas = (
        static[cls_group] & vol[cls_group]
        if c
        else np.zeros((0, n), bool)
    )

    if c > 1:
        # second collapse: distinct GROUPS with the same request AND the
        # same feasibility row are one class for the relaxation (pods are
        # interchangeable across them) — a uniform mix of many workloads
        # shrinks from C=#workloads to C=#distinct shapes, which is what
        # keeps the per-iteration [C, N] sort cheap at bench scale
        key2 = np.concatenate([req_raw, feas.astype(np.float64)], axis=1)
        uniq2, inv2 = np.unique(key2, axis=0, return_inverse=True)
        if uniq2.shape[0] < c:
            merged_rows = [
                np.sort(np.concatenate(
                    [cls_rows[ci] for ci in np.flatnonzero(inv2 == mi)]
                ))
                for mi in range(uniq2.shape[0])
            ]
            first = np.array(
                [int(np.flatnonzero(inv2 == mi)[0]) for mi in range(uniq2.shape[0])]
            )
            cls_rows = merged_rows
            cls_group = cls_group[first]
            req_raw = req_raw[first]
            feas = feas[first]
            cnt = np.array([len(rows) for rows in cls_rows], np.float32)
            c = uniq2.shape[0]

    cap_raw = np.asarray(tensors.alloc, np.float64)
    scale = np.maximum(cap_raw.max(axis=0), 1e-9)
    req = (req_raw / scale).astype(np.float32)
    sigma = float(np.linalg.norm(req, 2)) if req.size else 1.0
    lr = 0.9 / max(sigma * sigma, 1e-12)

    return RelaxProblem(
        cls_rows=cls_rows,
        cls_group=cls_group,
        cnt=cnt,
        req=req,
        req_raw=req_raw,
        feas=np.ascontiguousarray(feas),
        fixed=(fixed_raw / scale).astype(np.float32),
        fixed_raw=fixed_raw,
        cap=(cap_raw / scale).astype(np.float32),
        cap_raw=cap_raw,
        scale=scale,
        lr=lr,
        pinned_rows=pinned,
    )


def _project_rows(v, a, mask):
    """Exact Euclidean projection of each row of `v` onto the masked
    simplex {y >= 0, y*(~mask) = 0, sum y = a} (sort + threshold; the
    standard Held/Wolfe/Crowder construction, O(N log N) per row)."""
    neg = jnp.where(mask, v, -jnp.inf)
    u = jnp.flip(jnp.sort(neg, axis=1), axis=1)  # descending
    finite = jnp.isfinite(u)
    cs = jnp.cumsum(jnp.where(finite, u, 0.0), axis=1)
    k = jnp.arange(1, v.shape[1] + 1, dtype=v.dtype)[None, :]
    t = (cs - a[:, None]) / k
    cond = finite & (u > t)
    rho = jnp.maximum(jnp.sum(cond, axis=1) - 1, 0)
    tau = jnp.take_along_axis(t, rho[:, None], axis=1)
    y = jnp.maximum(v - tau, 0.0) * mask
    return jnp.where((a > 0)[:, None], y, 0.0)


@partial(jax.jit, static_argnums=(0,))
def _relax_kernel(iters, feas, req, cnt, fixed, cap, valid_s, lr):
    """vmapped projected-gradient feasibility solve over the candidate
    axis.  Returns (y [S, C, N], residual [S]): residual is the maximum
    scaled overcommit after the final projection (+inf when some class
    has demand but no feasible valid node — unsatisfiable outright)."""
    count_trace("solve")  # trace-time only: once per shape bucket

    def one(valid):
        f = feas & valid[None, :]
        free = jnp.maximum((cap - fixed) * valid[:, None], 0.0)
        nfeas = jnp.sum(f, axis=1)
        stuck = jnp.any((nfeas == 0) & (cnt > 0))
        y0 = jnp.where(f, (cnt / jnp.maximum(nfeas, 1))[:, None], 0.0)

        def body(_, y):
            load = jnp.einsum("cn,cr->nr", y, req)
            over = jnp.maximum(load - free, 0.0)
            grad = jnp.einsum("nr,cr->cn", over, req)
            return _project_rows(y - lr * grad, cnt, f)

        y = jax.lax.fori_loop(0, iters, body, y0)
        load = jnp.einsum("cn,cr->nr", y, req)
        over = jnp.maximum(load - free, 0.0)
        residual = jnp.where(stuck, jnp.inf, jnp.max(over, initial=0.0))
        return y, residual

    return jax.vmap(one)(valid_s)


class RelaxVerdicts(NamedTuple):
    residual: np.ndarray  # [S] f32 max scaled overcommit per candidate
    y_s: object  # device array [S, Cp, Np] (bucket-padded)
    c: int  # true class count (rows beyond are padding)
    n: int  # true node count (cols beyond are padding)
    bucket: tuple  # (S, C, N, R) padded shapes, for observability


def relax_candidates(
    prob: RelaxProblem, valid_s: np.ndarray, iters: Optional[int] = None
) -> RelaxVerdicts:
    """Solve every candidate membership mask in one bucketed dispatch."""
    iters = solver_iters() if iters is None else int(iters)
    c = len(prob.cnt)
    n = prob.cap.shape[0]
    s = valid_s.shape[0]
    r = prob.cap.shape[1]
    sp, cp, np_, rp = _pow2(s), _pow2(max(c, 1)), _pow2(n), _pow2(r)

    feas = np.zeros((cp, np_), bool)
    if c:
        feas[:c, :n] = prob.feas
    req = np.zeros((cp, rp), np.float32)
    if c:
        req[:c, :r] = prob.req
    cnt = np.zeros(cp, np.float32)
    cnt[:c] = prob.cnt
    fixed = np.zeros((np_, rp), np.float32)
    fixed[:n, :r] = prob.fixed
    cap = np.zeros((np_, rp), np.float32)
    cap[:n, :r] = prob.cap
    valid = np.zeros((sp, np_), bool)
    valid[:s, :n] = valid_s
    if sp > s:  # pad candidates by repeating the last mask (rows dropped)
        valid[s:, :n] = valid_s[-1]

    with span("solve.relax", candidates=int(s), bucket=f"{sp}x{cp}x{np_}x{rp}"):
        y_s, residual = _relax_kernel(
            iters,
            jnp.asarray(feas),
            jnp.asarray(req),
            jnp.asarray(cnt),
            jnp.asarray(fixed),
            jnp.asarray(cap),
            jnp.asarray(valid),
            np.float32(prob.lr),
        )
        residual = np.asarray(residual)[:s]
    return RelaxVerdicts(
        residual=residual, y_s=y_s, c=c, n=n, bucket=(sp, cp, np_, rp)
    )


def fetch_y(verdicts: RelaxVerdicts, s: int) -> np.ndarray:
    """Host copy of candidate s's fractional assignment, un-padded."""
    y = fetch_outputs(verdicts.y_s[s])
    return np.asarray(y, np.float64)[: verdicts.c, : verdicts.n]


def infeasibility_certificate(
    prob: RelaxProblem, y: np.ndarray, valid: np.ndarray
) -> bool:
    """Float64 weak-duality proof that NO fractional assignment exists for
    this membership mask.  Prices lam = relu(load - free) come from the
    solver's y, but the inequality is re-evaluated exactly — a true
    certificate, not a convergence heuristic.  Returns True iff
    infeasibility is PROVEN."""
    c, n = y.shape if y.size else (0, prob.cap_raw.shape[0])
    if c == 0:
        return False
    valid = np.asarray(valid, bool)
    feas = prob.feas & valid[None, :]
    if np.any((feas.sum(axis=1) == 0) & (prob.cnt > 0)):
        return True  # a class with demand and no feasible valid node
    free = np.maximum(
        (prob.cap_raw - prob.fixed_raw) * valid[:, None], 0.0
    ) / prob.scale
    # f64 re-evaluation in the scaled metric, from the f32 statement
    req = np.asarray(prob.req, np.float64)
    load = np.einsum("cn,cr->nr", y, req)
    lam = np.maximum(load - free, 0.0)
    if not lam.any():
        return False
    percost = np.einsum("nr,cr->cn", lam, req)
    mincost = np.where(feas, percost, np.inf).min(axis=1)
    lhs = float(np.sum(np.asarray(prob.cnt, np.float64) * mincost))
    rhs = float(np.sum(lam * free))
    return lhs > rhs * (1.0 + CERT_MARGIN) + 1e-12
