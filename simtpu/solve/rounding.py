"""Fractional assignment → integral candidate placement.

Host-side (numpy, float64) on purpose: rounding touches [C, N] count
matrices, not [P, N] planes, so it is cheap — and the auditor will judge
the result against the raw tensorized inputs anyway, so there is nothing
to gain from doing it on device and something to lose (f32 thresholds).

The rule, per class row of the relaxed solution y:

1. floor     — take m = floor(y) pods on each node;
2. remainder — hand the class's remaining cnt - sum(m) pods out one each
   to the feasible nodes with the LARGEST fractional mass, ties broken
   toward the lower node index (lexsort on (node_index, -frac)), which
   makes the rounding deterministic for the tie-broken-masses test;
3. repair    — greedy local repair in exact arithmetic: while any node's
   f64 load exceeds its capacity, move one pod from it to the first
   feasible node with room.  Bounded by 2·pods + 10 moves; exhausting
   the budget (or finding no legal move) fails the round, which the
   planner reports as a rejection — never a garbage placement.

Order-safety: requests are non-negative, so if the END state fits on
every node, every prefix of a batch-row-ordered placement fits too —
the rounded counts expand to a pod→node vector that passes the
auditor's conservation replay without any per-step search.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .relax import RelaxProblem


def round_candidate(
    prob: RelaxProblem, y: np.ndarray, valid: np.ndarray
) -> Tuple[Optional[np.ndarray], str]:
    """Round one candidate's fractional assignment to integral per-class
    node counts.  Returns (m [C, N] int64, "") on success or
    (None, reason) when no repair within budget produces a load that fits
    — the planner treats that as a rejected solve, not an infeasibility
    claim (only the certificate may claim infeasibility)."""
    c, n = y.shape if y.ndim == 2 else (0, prob.cap_raw.shape[0])
    valid = np.asarray(valid, bool)
    cnt = np.array([len(rows) for rows in prob.cls_rows], np.int64)
    if c == 0:
        return np.zeros((0, n), np.int64), ""

    feas = prob.feas & valid[None, :]
    y = np.where(feas, np.maximum(np.asarray(y, np.float64), 0.0), 0.0)
    m = np.floor(y + 1e-9).astype(np.int64)
    frac = y - m

    for ci in range(c):
        d = int(cnt[ci] - m[ci].sum())
        order = np.lexsort((np.arange(n), -frac[ci]))
        order = order[feas[ci][order]]
        if d > 0 and order.size == 0:
            return None, "no_feasible_node"
        k = 0
        while d > 0:  # hand out remainders, largest fraction first
            m[ci, order[k % order.size]] += 1
            d -= 1
            k += 1
        while d < 0:  # float overshoot: pull back smallest occupied mass
            occ = np.flatnonzero(m[ci] > 0)
            j = occ[np.argsort(frac[ci][occ], kind="stable")[0]]
            m[ci, j] -= 1
            d += 1

    req = prob.req_raw  # [C, R] f64, unscaled
    cap = prob.cap_raw * valid[:, None]
    load = np.einsum("cn,cr->nr", m.astype(np.float64), req)
    load += prob.fixed_raw * valid[:, None]
    tol = prob.scale * 1e-9

    moves, budget = 0, int(cnt.sum()) * 2 + 10
    while True:
        over = np.flatnonzero(np.any(load > cap + tol, axis=1))
        if over.size == 0:
            return m, ""
        if moves >= budget:
            return None, "repair_budget"
        nj = int(over[0])
        moved = False
        for ci in np.flatnonzero(m[:, nj] > 0):
            fits = np.all(load + req[ci][None, :] <= cap + tol, axis=1)
            targets = np.flatnonzero(feas[ci] & fits)
            targets = targets[targets != nj]
            if targets.size:
                t = int(targets[0])
                m[ci, nj] -= 1
                m[ci, t] += 1
                load[nj] -= req[ci]
                load[t] += req[ci]
                moves += 1
                moved = True
                break
        if not moved:
            reason = "overfull_fixed" if not m[:, nj].any() else "repair_stuck"
            return None, reason


def nodes_from_counts(
    prob: RelaxProblem, pin: np.ndarray, m: np.ndarray
) -> np.ndarray:
    """Expand per-class node counts to the engine's pod→node vector.
    Free rows of each class are filled in batch-row order against the
    class's nodes in ascending node order (deterministic; pods within a
    class are interchangeable).  Pinned rows keep their pin — the caller
    masks phantom clone rows to -1 afterwards."""
    pin = np.asarray(pin)
    nodes = np.full(pin.shape[0], -1, np.int32)
    if len(prob.pinned_rows):
        nodes[prob.pinned_rows] = pin[prob.pinned_rows].astype(np.int32)
    for ci, rows in enumerate(prob.cls_rows):
        nodes[rows] = np.repeat(
            np.arange(m.shape[1], dtype=np.int32), m[ci]
        )
    return nodes
