"""The solve backend's planning surface: propose, certify, or step aside.

`attempt_solve` is the whole advisory-mode contract in one function:

  eligibility gate -> vmapped relaxation over every candidate count ->
  float64 infeasibility certificate at the boundary -> host rounding ->
  occupancy caps -> independent audit (simtpu/audit) -> on a dirty audit,
  the serial exact engine re-places the candidate like wavefront rollback.

Nothing uncertified ever ships: an accepted answer is an audited integral
placement at a candidate count whose predecessor carries an infeasibility
proof, so it equals the exact search's minimum by construction.  Every
other outcome ("rejected", "infeasible", "ineligible") steps aside and
hands the exact planners a certified lower bound when one exists — the
relaxation's fractional verdicts warm-start the doubling+bisection even
when its rounded answer loses.

Counters ride the PR-8 registry under `solve.*` (attempts / accepted /
rejected / ineligible / infeasible / fallbacks), spans under
`solve.build` / `solve.relax` / `solve.round`, and the structured record
lands on `PlanResult.solve` (CLI `--json`: `engine.solve`).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import numpy as np

from ..obs.metrics import REGISTRY
from ..obs.trace import span
from .relax import (
    RESIDUAL_TOL,
    build_relax_problem,
    fetch_y,
    infeasibility_certificate,
    relax_candidates,
    solver_iters,
)
from .rounding import nodes_from_counts, round_candidate

#: the `solve.*` registry counter family (obs.metrics.family("solve", ...))
SOLVE_COUNT_KEYS = (
    "attempts", "accepted", "rejected", "ineligible", "infeasible",
    "fallbacks",
)


def solver_enabled() -> bool:
    """Global default for the planners' `solver=None`: SIMTPU_SOLVER=1
    turns the solve backend on; unset/0 = off (the exact engines answer
    alone).  Per-command `--solver/--no-solver` overrides."""
    return os.environ.get("SIMTPU_SOLVER", "0") == "1"


def _bump(key: str) -> None:
    REGISTRY.counter(f"solve.{key}").inc()


@dataclass
class SolveAttempt:
    """One consult of the solve backend, with everything a planner needs
    to either ship the answer or warm-start the exact search."""

    #: accepted | accepted_fallback | rejected | infeasible | ineligible
    status: str
    #: winning clone count (accepted states), else -1
    k: int = -1
    #: certified lower bound on the clone count: the exact search may
    #: skip every candidate below it (0 = no certificate — no claim)
    lower_bound: int = 0
    #: True when `lower_bound` carries the float64 infeasibility proof
    certified: bool = False
    #: accepted placement artifacts, `_materialize`-shaped
    nodes_arr: Optional[np.ndarray] = None
    reasons: Optional[np.ndarray] = None
    ext_log: Optional[dict] = None
    gpu_arr: Optional[np.ndarray] = None
    #: the auditor's verdict on the shipped placement (PlanResult.audit)
    audit_doc: Dict[str, object] = field(default_factory=dict)
    #: the structured record (PlanResult.solve / --json engine.solve)
    doc: Dict[str, object] = field(default_factory=dict)

    @property
    def accepted(self) -> bool:
        return self.status in ("accepted", "accepted_fallback")


def _ineligible_reason(batch) -> Optional[str]:
    """Specs the relaxation cannot faithfully model are gated out up
    front rather than rounded into audit-certain rejections: extended
    storage/GPU demand needs per-pod extras the solver does not
    construct, and a forced pod naming an unknown node can never place.
    Ports/affinity/spread are NOT gated — the solver tries and the
    auditor disposes (a dirty audit falls back, nothing ships wrong)."""
    ext = batch.ext
    if np.asarray(ext["lvm_size"]).any() or np.asarray(ext["dev_size"]).any():
        return "extended local-storage demand (lvm/device)"
    if (np.asarray(ext["gpu_mem"]) > 0).any() or (
        np.asarray(ext["gpu_count"]) > 0
    ).any():
        return "gpu-share demand"
    pin = np.asarray(batch.pin)
    forced = np.asarray(batch.forced, bool)
    if (forced & (pin < 0)).any():
        return "forced pod names an unknown node"
    return None


def _zero_extras(tensors, p: int) -> Dict[str, np.ndarray]:
    """Audit-shaped extras for a solver placement: eligibility guarantees
    zero extended demand, so the matching allocations are all zeros."""
    v = tensors.ext.vg_cap.shape[1]
    sd = tensors.ext.sdev_cap.shape[1]
    return {
        "lvm_alloc": np.zeros((p, v), np.float32),
        "dev_take": np.zeros((p, sd), bool),
        "gpu_shares": np.zeros(p, np.float32),
    }


def _solver_ext_log(tensors, batch, nodes_arr: np.ndarray) -> dict:
    """Placement-order ext_log for `_materialize` (zero extended
    allocations, same shape contract as Engine.ext_log)."""
    v = tensors.ext.vg_cap.shape[1]
    sd = tensors.ext.sdev_cap.shape[1]
    ok = np.flatnonzero(nodes_arr >= 0)
    return {
        "node": nodes_arr[ok].tolist(),
        "vg_alloc": list(np.zeros((len(ok), v), np.float32)),
        "sdev_take": list(np.zeros((len(ok), sd), bool)),
        "gpu_shares": list(np.zeros(len(ok), np.float32)),
        "gpu_mem": np.asarray(batch.ext["gpu_mem"])[ok].tolist(),
    }


def attempt_solve(
    tz,
    tensors,
    batch,
    all_nodes,
    n_base: int,
    max_new: int,
    sched_config=None,
    progress=None,
) -> SolveAttempt:
    """One full solver consult over candidates 0..max_new (inclusive —
    the planners' `max_new_nodes - 1` exclusive-bound convention).

    Accepts only what the auditor certifies at a count whose predecessor
    is PROVEN infeasible; everything else returns a non-accepted attempt
    whose `lower_bound`/`doc` the exact search consumes."""
    say = progress or (lambda s: None)
    _bump("attempts")
    t0 = time.perf_counter()
    doc: Dict[str, object] = {"enabled": True, "iters": solver_iters()}

    def finish(att: SolveAttempt) -> SolveAttempt:
        doc["status"] = att.status
        doc["wall_s"] = round(time.perf_counter() - t0, 4)
        if att.certified:
            doc["lower_bound"] = att.lower_bound
        att.doc = doc
        return att

    reason = _ineligible_reason(batch)
    if reason is not None:
        _bump("ineligible")
        doc["reason"] = reason
        return finish(SolveAttempt("ineligible"))

    with span("solve.build"):
        prob = build_relax_problem(tensors, batch)
    n_total = len(all_nodes)
    clone_idx = np.arange(n_total) - n_base
    cands = np.arange(max_new + 1)
    valid_s = (clone_idx[None, :] < cands[:, None]) | (clone_idx[None, :] < 0)
    doc["candidates"] = int(len(cands))

    verd = relax_candidates(prob, valid_s)
    finite = verd.residual[np.isfinite(verd.residual)]
    doc["residual"] = float(finite.min()) if len(finite) else None
    feasible = np.flatnonzero(verd.residual <= RESIDUAL_TOL)
    if len(feasible) == 0:
        # the relaxation converged nowhere — certify the LARGEST candidate
        # when possible, so the exact search knows the whole range is
        # hopeless (its run then exists only for rich diagnostics)
        _bump("infeasible")
        y_last = fetch_y(verd, max_new)
        certified = infeasibility_certificate(prob, y_last, valid_s[max_new])
        doc["reason"] = "no candidate count is relax-feasible"
        return finish(
            SolveAttempt(
                "infeasible",
                lower_bound=max_new + 1 if certified else 0,
                certified=certified,
            )
        )

    k = int(feasible[0])
    doc["k"] = k
    doc["residual"] = float(verd.residual[k])
    certified = k == 0
    if k > 0:
        # one boundary proof suffices: relax-feasibility is monotone in
        # the candidate count (candidate masks are nested), so k-1
        # infeasible => everything below k infeasible
        certified = infeasibility_certificate(
            prob, fetch_y(verd, k - 1), valid_s[k - 1]
        )
    doc["certified_lb"] = bool(certified)
    lb = k if certified else 0
    if not certified:
        # an uncertified k could overshoot the true minimum — never ship
        # a possibly-non-minimal count; hand the exact search the verdict
        _bump("rejected")
        doc["reason"] = "minimality not certified (duality gap)"
        return finish(SolveAttempt("rejected", k=k))

    with span("solve.round", k=k):
        m, why = round_candidate(prob, fetch_y(verd, k), valid_s[k])
    if m is None:
        _bump("rejected")
        doc["reason"] = f"rounding failed: {why}"
        return finish(
            SolveAttempt("rejected", k=k, lower_bound=lb, certified=True)
        )

    pin = np.asarray(batch.pin)
    nodes_arr = nodes_from_counts(prob, pin, m)
    phantom = (pin - n_base) >= k
    nodes_arr[phantom] = -1

    from ..plan.incremental import _caps_satisfied

    valid_k = np.asarray(valid_s[k], bool)
    ok, cap_reason = _caps_satisfied(
        tensors,
        np.asarray(batch.req)[nodes_arr >= 0].sum(axis=0),
        valid_k,
        vg_extra=0.0,
    )
    if not ok:
        # cap feasibility can be non-monotone (DaemonSet overhead,
        # plan/capacity.py) — the exact planners own that walk
        _bump("rejected")
        doc["reason"] = f"occupancy cap: {cap_reason.strip()}"
        return finish(
            SolveAttempt("rejected", k=k, lower_bound=lb, certified=True)
        )

    from ..audit.checker import (
        audit_placement,
        divergence_diagnostic,
        inject_divergence,
        inject_divergence_enabled,
    )

    extras = _zero_extras(tensors, len(pin))
    nodes_aud = nodes_arr
    if inject_divergence_enabled():
        nodes_aud = inject_divergence(tensors, batch, nodes_arr)
    rep = audit_placement(
        tensors, batch, nodes_aud, extras,
        node_valid=valid_k, require_all=True, expect_mask=~phantom,
    )
    audit_doc: Dict[str, object] = rep.counters()
    if rep.ok:
        _bump("accepted")
        say(f"solver: candidate {k} certified by the auditor")
        return finish(
            SolveAttempt(
                "accepted", k=k, lower_bound=lb, certified=True,
                nodes_arr=nodes_arr,
                reasons=np.zeros(len(pin), np.int32),
                ext_log=_solver_ext_log(tensors, batch, nodes_arr),
                gpu_arr=np.zeros(len(pin), np.float32),
                audit_doc=audit_doc,
            )
        )

    # audit-dirty: the wavefront-rollback shape — the serial exact engine
    # re-places candidate k, and only ITS certified answer may ship
    _bump("fallbacks")
    say(
        f"solver: audit FAILED on the rounded candidate ({rep.summary()}) "
        "— re-placing through the serial exact scan"
    )
    from ..engine.scan import Engine

    fb = Engine(tz)
    fb.node_valid = valid_k
    fb.speculate = False
    fb.compact = False
    fb.sched_config = sched_config
    nodes_f, reasons_f, extras_f = fb.place(batch)
    nodes_f = np.asarray(nodes_f)
    doc["fallback"] = True
    if ((nodes_f < 0) & ~phantom).any():
        # the exact engine cannot complete candidate k either (the
        # relaxation missed a constraint the engine enforces) — reject,
        # keeping the still-valid LP lower bound for the exact search
        _bump("rejected")
        doc["reason"] = "exact fallback could not place candidate k"
        return finish(
            SolveAttempt("rejected", k=k, lower_bound=lb, certified=True)
        )
    rep_f = audit_placement(
        tensors, batch, nodes_f, extras_f,
        node_valid=valid_k, require_all=True, expect_mask=~phantom,
    )
    audit_doc = {
        **rep.counters(),
        "fallback": True,
        "fallback_audit": rep_f.counters(),
        "divergence": divergence_diagnostic(
            tensors, batch, nodes_aud, nodes_f, rep
        ),
    }
    if not rep_f.ok:
        _bump("rejected")
        doc["reason"] = (
            f"fallback placement failed its audit too ({rep_f.summary()})"
        )
        att = SolveAttempt("rejected", k=k, lower_bound=lb, certified=True)
        att.audit_doc = audit_doc
        return finish(att)
    audit_doc["ok"] = True
    _bump("accepted")
    return finish(
        SolveAttempt(
            "accepted_fallback", k=k, lower_bound=lb, certified=True,
            nodes_arr=nodes_f,
            reasons=np.asarray(reasons_f),
            ext_log=fb.ext_log,
            gpu_arr=np.asarray(extras_f["gpu_shares"]),
            audit_doc=audit_doc,
        )
    )


def solve_capacity_plan(
    cluster,
    apps,
    new_node: dict,
    max_new_nodes: int,
    extended_resources=(),
    progress=None,
    sched_config=None,
):
    """Solver-backed capacity plan for the facade planner: one
    tensorization, one vmapped solve, one audit — no simulate() at all
    on the accepted path.

    Returns (PlanResult, attempt) when the solver's answer is certified,
    else (None, attempt) and the caller runs the exact search (using
    `attempt.lower_bound` as a warm start when certified)."""
    from ..parallel.sweep import assemble_planning_problem
    from ..plan.capacity import PlanResult
    from ..plan.incremental import _materialize

    say = progress or (lambda s: None)
    max_new = max(max_new_nodes - 1, 0)
    tz, all_nodes, n_base, ordered = assemble_planning_problem(
        cluster, apps, new_node, max_new, extended_resources
    )
    batch = tz.add_pods(ordered)
    tensors = tz.freeze()
    att = attempt_solve(
        tz, tensors, batch, all_nodes, n_base, max_new, sched_config, say
    )
    if not att.accepted:
        return None, att
    clone_of = np.asarray(batch.pin) - n_base
    result = _materialize(
        tz, all_nodes, n_base + att.k, batch, att.nodes_arr, att.reasons,
        clone_of, att.k, att.ext_log, att.gpu_arr,
    )
    plan = PlanResult(True, att.k, result, "Success!", {int(att.k): 0})
    plan.audit = att.audit_doc
    plan.solve = att.doc
    return plan, att


def solve_lower_bound(
    tensors, batch, n_base: int, n_total: int, max_new: int
) -> Tuple[int, Dict[str, object]]:
    """Relax-only certified lower bound on the clone count (0 = no
    claim).  Used by `plan_resilience`: the no-failure fit is necessary
    for survivability (failures only remove capacity), so an LP
    infeasibility proof at count j rules out every candidate <= j.  No
    rounding, no audit — this never ships a placement."""
    doc: Dict[str, object] = {"enabled": True, "mode": "lower_bound"}
    if _ineligible_reason(batch) is not None:
        doc["status"] = "ineligible"
        return 0, doc
    with span("solve.build"):
        prob = build_relax_problem(tensors, batch)
    clone_idx = np.arange(n_total) - n_base
    cands = np.arange(max_new + 1)
    valid_s = (clone_idx[None, :] < cands[:, None]) | (clone_idx[None, :] < 0)
    verd = relax_candidates(prob, valid_s)
    feasible = np.flatnonzero(verd.residual <= RESIDUAL_TOL)
    k = int(feasible[0]) if len(feasible) else max_new + 1
    doc["k"] = k
    if k == 0:
        doc["status"] = "trivial"
        return 0, doc
    boundary = min(k - 1, max_new)
    certified = infeasibility_certificate(
        prob, fetch_y(verd, boundary), valid_s[boundary]
    )
    doc["certified_lb"] = bool(certified)
    if not certified:
        doc["status"] = "uncertified"
        return 0, doc
    doc["status"] = "certified"
    doc["lower_bound"] = k
    return k, doc
