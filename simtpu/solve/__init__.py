"""Global-solver planning backend (ISSUE 19, docs/solver.md).

A second planning engine: pod x node placement lowered to a batched
assignment relaxation in pure JAX, vmapped over candidate node counts so
the entire capacity search collapses into ONE solve instead of
doubling+bisection over full placements.  Always advisory: the solver
proposes a candidate placement, the PR-7 auditor (simtpu/audit) disposes
— audit-dirty answers fall back to the serial exact engine exactly like
wavefront rollback, and nothing uncertified ever ships.
"""

from .planner import (  # noqa: F401
    SolveAttempt,
    attempt_solve,
    solve_capacity_plan,
    solve_lower_bound,
    solver_enabled,
)
from .relax import build_relax_problem, relax_candidates  # noqa: F401
from .rounding import nodes_from_counts, round_candidate  # noqa: F401
