"""HPA + cluster-pool autoscaler emulation for the replay loop.

Two scalers run on the periodic `EVT_AUTOSCALE` tick (interval from the
trace's `autoscale` block):

**HPA replica scaling.**  Every active elastic job carries a simulated
utilization signal — `usage` is the fraction of each replica's REQUEST
the replica actually consumes, a scalar or a `[[t_s, frac], ...]` step
function (diurnal shapes) — and the controller applies the standard HPA
formula `desired = ceil(current * usage / target_util)` clamped into
`[min, max]`.  Scale-ups admit reserve rows (the elastic expansion
pre-tensorized `max` replicas, so the vocabulary never grows mid-replay)
per-replica best-effort; scale-downs evict the youngest replicas through
the delta undo, releasing capacity for the pending queue.

**Template-node pool.**  `autoscale.pool` pre-provisions that many
clones of `autoscale.node` at tensorize time, DISABLED via the engine's
`node_valid` lever (the faults mask).  The tick arms one pool node per
interval while admission demand is visibly starved (a non-empty pending
queue), and disarms the highest empty pool node when utilization sits
below half the HPA target — capacity-planner-shaped grow/shrink without
ever re-tensorizing.

**Real node growth.**  `autoscale.grow_max` (default 0 — existing traces
are untouched) lets the tick keep scaling PAST the pre-provisioned pool:
once every pool node is armed and demand is still starved, one template
clone per interval joins through `Tensorizer.add_clone_nodes` and the
engine's append-only carry extension (`Engine.grow_nodes` /
`extend_state_nodes`), up to `grow_max` extra nodes.  The replay engine
runs in the grow layout for such traces; a `GrowRefused` template
disables further growth and is counted (`pool_grow_refused`).
"""

from __future__ import annotations

import math

import numpy as np


def usage_at(elastic: dict, t: float) -> float:
    """The job's simulated utilization-of-request at sim time `t`
    (scalar, or the last step of a `[[t_s, frac], ...]` breakpoint
    list at or before `t`; before the first breakpoint the first
    value holds)."""
    usage = elastic.get("usage", 0.6)
    if isinstance(usage, (int, float)):
        return float(usage)
    if not usage:
        return 0.6
    out = float(usage[0][1])
    for t_b, frac in usage:
        if float(t_b) <= t:
            out = float(frac)
        else:
            break
    return out


def desired_replicas(current: int, usage: float, target: float,
                     lo: int, hi: int) -> int:
    """The HPA formula: ceil(current * usage / target), clamped."""
    if current <= 0:
        current = max(lo, 1)
    want = math.ceil(current * usage / max(target, 1e-9))
    return max(lo, min(int(want), hi))


def autoscale_tick(rt, auto, t: float) -> bool:
    """One autoscaler evaluation on the replay runtime `rt`
    (timeline/replay.py `_Replay`).  Returns True when capacity was
    released (scale-down or pool-up), so the event loop runs its
    end-of-timestamp pending retry pass."""
    rt._bump("autoscale_checks")
    released = False

    # -- HPA replica scaling over the active elastic jobs ----------------
    for st in rt.jobs:
        if st.job.elastic is None or st.status not in ("active", "pending"):
            continue
        el = st.job.elastic
        current = st.placed_count
        if current <= 0:
            continue
        want = desired_replicas(
            current, usage_at(el, t), auto.target_util, el["min"], el["max"]
        )
        want = min(want, len(st.rows))
        if want > st.want:
            st.want = want
            placed = rt._try_admit_elastic(st, t)
            rt._bump("scale_up_pods", placed)
            if st.needs > 0 and st.status == "active":
                # the missing replicas wait like any pending job
                st.status = "pending"
        elif want < current:
            # evict the youngest replicas (highest rows) via the delta
            # undo; scale-to-zero is out of scope, so one replica stays
            want = max(want, 1)
            drop = current - want
            if drop <= 0:
                continue
            placed_rows = st.rows[st.placed]
            victims = placed_rows[-drop:]
            entries = np.flatnonzero(
                (rt.log_jid == st.jid) & np.isin(rt.log_row, victims)
            )
            # partial eviction of a run that stays alive: the job's
            # scheduled departure must remain valid (bump_epoch=False —
            # a bumped epoch would make the surviving replicas immortal)
            rt._evict_job(st, entries, bump_epoch=False)
            st.want = want
            rt._bump("scale_down_pods", int(drop))
            released = True
            if st.status == "pending" and st.needs <= 0:
                st.status = "active"

    # -- template-node pool ----------------------------------------------
    if rt.pool_rows or rt.grow_left > 0:
        pending = sum(
            st.needs
            for st in rt.jobs
            if st.status == "pending" and st.needs > 0
        )
        disabled = [i for i in rt.pool_rows if not rt.valid[i]]
        if pending > 0 and disabled:
            # arm ONE node per tick: grow at the autoscaler's cadence,
            # the way real cluster autoscalers rate-limit scale-out
            rt.valid[disabled[0]] = True
            rt.eng.node_valid = rt.valid.copy()
            rt._bump("pool_up")
            released = True
        elif pending > 0 and rt.grow_left > 0:
            # the pre-provisioned pool is exhausted: grow the node axis
            # for real (append-only clone + in-place carry extension),
            # still one node per tick
            released |= rt._grow_pool_node()
        elif pending == 0:
            cap = float(rt.alloc_cpu[rt.valid].sum())
            util = rt.used_cpu / cap if cap > 0 else 0.0
            if util < auto.target_util * 0.5:
                enabled = [i for i in rt.pool_rows if rt.valid[i]]
                if enabled:
                    log_nodes = np.asarray(rt.eng.placed_node, np.int64)
                    empty = [
                        i for i in enabled if not (log_nodes == i).any()
                    ]
                    if empty:
                        rt.valid[empty[-1]] = False
                        rt.eng.node_valid = rt.valid.copy()
                        rt._bump("pool_down")
    return released
