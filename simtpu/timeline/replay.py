"""Continuous-time, event-driven replay over the carried engine state.

The loop advances a single `Engine` through a time-ordered event stream
(timeline/events.py) WITHOUT re-placing from scratch: admissions are
ordinary `Engine.place` dispatches over the pre-tensorized batch (the
wavefront drafts a whole gang in one call), departures and evictions are
signed placement-log deltas through `engine/state.py`'s batch apply/undo
(`Engine.remove_placements` / `restore_placements`) — the same primitive
PR 4's drain/requeue rides — so the carried state rolls forward
incrementally across thousands of events.

On top of the loop:
- **gang admission** — all-or-nothing: a gang whose pods do not ALL
  place rolls its partial placement back (the wavefront's
  verify-and-rollback discipline at admission granularity); no emitted
  state ever shows a partial gang;
- **priority pending queue** — failed gangs wait with exponential
  retry/backoff and are re-attempted (priority-descending, arrival-order
  tie-break) at the end of any timestamp that released capacity
  (departure, node up, preemption, scale-down);
- **preemption on arrival** — an arriving gang may evict strictly
  lower-priority gangs (lowest priority first, youngest first); evicted
  gangs requeue, and a preemption that still cannot admit restores every
  victim bit-identically via the delta undo;
- **autoscaler emulation** (timeline/autoscale.py) — periodic HPA
  replica scaling off simulated utilization plus a pre-provisioned
  template-node pool armed through the same node_valid lever.

Determinism: events process in `(t, rank, seq)` order; same-timestamp
capacity changes settle before the end-of-timestamp retry pass (the rule
that makes the batched path's same-`t` departure coalescing
semantics-identical to the serial oracle).  `options.serial` is that
oracle: one event at a time, one pod per dispatch, wavefront off, dense
carry, state rebuilt from the placement log before every dispatch — the
batched path is pinned bit-identical against it (tests/test_timeline.py).
"""

from __future__ import annotations

import heapq
import time
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

from ..core.tensorize import Tensorizer, slice_batch
from ..durable.deadline import PlanInterrupted, RunControl
from ..engine.scan import Engine
from ..engine.state import build_state
from ..obs.metrics import REGISTRY
from ..obs.trace import instant, span
from ..workloads.expand import make_valid_node_by_node, seed_name_hashes
from ..workloads.validate import SpecError
from .events import (
    EVT_ARRIVE,
    EVT_AUTOSCALE,
    EVT_DEPART,
    EVT_NODE_DOWN,
    EVT_NODE_UP,
    EVT_RETRY,
    RANK_NAMES,
    Trace,
    expand_job_pods,
    initial_replicas,
)

#: admissions at time t schedule their departure at t + max(duration, this)
#: so a zero-duration job still departs strictly later than it arrived
#: (the event loop groups strictly by timestamp)
_MIN_DURATION_S = 1e-6

#: exported next to the instruments (obs/metrics.py `family` helper):
#: the timeline counter family the CLI/bench read
TIMELINE_KEYS = (
    "events", "arrivals", "departures", "admitted", "attempts",
    "gang_rollbacks", "retries", "preemptions", "preempted_pods",
    "node_down", "node_up", "cron_fires", "dropped_pods",
    "autoscale_checks", "scale_up_pods", "scale_down_pods",
    "pool_up", "pool_down", "pool_grow", "pool_grow_refused",
)


@dataclass
class ReplayOptions:
    """Knobs of one replay run."""

    serial: bool = False  # the one-event/one-pod-at-a-time oracle
    speculate: Optional[bool] = None  # wavefront (None = env default)
    compact: Optional[bool] = None  # compact carried state (None = default)
    preempt: bool = True  # preemption on gang arrival
    retry_backoff_s: float = 30.0  # base of the exponential backoff
    max_retries: int = 8  # per job; exhaustion drops the remainder
    extended_resources: tuple = ()
    sched_config: object = None
    audit: Optional[bool] = None  # end-state certification (None = env)
    control: Optional[RunControl] = None  # deadline/SIGINT token
    progress: Optional[Callable[[str], None]] = None


@dataclass
class _JobState:
    jid: int
    job: object  # TraceJob
    rows: np.ndarray  # all batch rows (elastic: max replicas)
    want: int  # current replica target (rows[:want] desired)
    placed: np.ndarray  # [len(rows)] bool
    status: str = "waiting"  # waiting|pending|active|departed|dropped
    arrive_t: float = 0.0
    attempts: int = 0
    admit_seq: int = -1  # monotone admission order (preemption tie-break)
    epoch: int = 0  # bumps on every eviction; stale departures skip
    full_at: Optional[float] = None  # first fully-placed instant

    @property
    def placed_count(self) -> int:
        return int(self.placed.sum())

    @property
    def needs(self) -> int:
        return self.want - self.placed_count


class TimelineResult:
    """Outcome of one replay: counters, the utilization/pending/preemption
    time series, the end-state handles the pinning tests and the auditor
    consume, and the partial-result contract fields."""

    def __init__(self):
        self.events = 0
        self.event_log: List[Tuple[float, str, str]] = []
        self.samples: List[Tuple[float, float, int, int]] = []
        self.pending_s: List[float] = []
        self.counts = {k: 0 for k in TIMELINE_KEYS}
        self.nodes: Optional[np.ndarray] = None  # [P] final landing (-1)
        self.tensors = None
        self.batch = None
        self.engine: Optional[Engine] = None
        self.node_valid: Optional[np.ndarray] = None
        self.audit: Optional[dict] = None
        self.partial = False
        self.message = ""
        self.still_pending = 0  # jobs not fully placed at the end
        self.timings = {}

    def end_state(self):
        """Dense end-of-replay SchedState (rebuilding from the log when
        the carry is dirty — the oracle leaves it so by design)."""
        eng = self.engine
        tensors = self.tensors
        if (
            eng.last_state is not None
            and not eng._state_dirty
            and eng._last_vocab == eng.state_vocab(tensors)
        ):
            return eng.carried_state()
        r = tensors.alloc.shape[1]
        return build_state(
            tensors,
            np.asarray(eng.placed_group, np.int32),
            np.asarray(eng.placed_node, np.int32),
            eng.log_req_matrix(r),
            eng.ext_log,
        )

    @property
    def pending_p50_s(self) -> float:
        if not self.pending_s:
            return 0.0
        return float(np.percentile(np.asarray(self.pending_s), 50))

    @property
    def pending_p90_s(self) -> float:
        if not self.pending_s:
            return 0.0
        return float(np.percentile(np.asarray(self.pending_s), 90))

    @property
    def util_avg(self) -> float:
        if not self.samples:
            return 0.0
        return float(np.mean([s[1] for s in self.samples]))

    def counters(self) -> dict:
        """Machine-readable summary (CLI --json, bench)."""
        out = dict(self.counts)
        out.update(
            events=self.events,
            placed_pods=int((np.asarray(self.nodes) >= 0).sum())
            if self.nodes is not None
            else 0,
            still_pending=self.still_pending,
            pending_p50_s=round(self.pending_p50_s, 3),
            pending_p90_s=round(self.pending_p90_s, 3),
            util_avg=round(self.util_avg, 4),
            partial=self.partial,
            events_per_s=round(self.timings.get("events_per_s", 0.0), 2),
        )
        return out


def _no_progress(msg: str) -> None:
    pass


def replay_trace(trace: Trace, options: Optional[ReplayOptions] = None) -> TimelineResult:
    """Replay one trace; see the module docstring for semantics."""
    options = options or ReplayOptions()
    rt = _Replay(trace, options)
    return rt.run()


class _Replay:
    def __init__(self, trace: Trace, opts: ReplayOptions):
        self.trace = trace
        self.opts = opts
        self.serial = bool(opts.serial)
        self._progress = opts.progress or _no_progress
        self.control = opts.control or RunControl()
        self._build_problem()
        self._build_heap()

    # -- problem assembly --------------------------------------------------

    def _build_problem(self) -> None:
        trace = self.trace
        # deterministic pod-name stream per trace: two replays (batched
        # and oracle) expand byte-identical pods
        seed_name_hashes(0x7133_1177 ^ int(trace.seed))
        pods_all: List[dict] = []
        self.jobs: List[_JobState] = []
        for job in sorted(trace.jobs, key=lambda j: j.seq):
            pods = expand_job_pods(job)
            if not pods:
                continue
            rows = np.arange(len(pods_all), len(pods_all) + len(pods),
                             dtype=np.int64)
            pods_all.extend(pods)
            want = min(initial_replicas(job), len(pods))
            self.jobs.append(
                _JobState(
                    jid=len(self.jobs),
                    job=job,
                    rows=rows,
                    want=want,
                    placed=np.zeros(len(pods), bool),
                )
            )
        cluster = trace.cluster
        nodes = list(cluster.nodes)
        if not nodes:
            raise SpecError("trace cluster has no nodes",
                            source=trace.source, field="trace.cluster")
        self.n_base = len(nodes)
        self.pool_rows: List[int] = []
        auto = trace.autoscale
        if auto is not None and auto.pool:
            for i in range(auto.pool):
                nodes.append(
                    make_valid_node_by_node(auto.node, f"timeline-pool-{i:04d}")
                )
                self.pool_rows.append(self.n_base + i)
        self.tz = Tensorizer(
            nodes,
            self.opts.extended_resources,
            getattr(cluster, "storage_classes", ()) or (),
            getattr(cluster, "services", ()) or (),
        )
        self.batch = self.tz.add_pods(pods_all)
        self.tensors = self.tz.freeze()
        self.node_idx = {
            str(node.get("metadata", {}).get("name", "")): i
            for i, node in enumerate(nodes)
        }
        for ev in trace.node_events:
            unknown = [n for n in ev.nodes if n not in self.node_idx]
            if unknown:
                raise SpecError(
                    f"unknown node(s) {unknown} (not in the trace cluster)",
                    source=trace.source,
                    field=f"node_events@{ev.t_s:g}s",
                )
        self.grow_left = int(auto.grow_max) if auto is not None else 0
        self._grown = 0
        eng = Engine(self.tz)
        eng.sched_config = self.opts.sched_config
        if self.serial:
            eng.speculate = False
            eng.compact = False
        else:
            if self.opts.speculate is not None:
                eng.speculate = bool(self.opts.speculate)
            if self.opts.compact is not None:
                eng.compact = bool(self.opts.compact)
        if self.grow_left > 0:
            # real node-axis growth is enabled for this trace: the carry
            # must live in the grow layout (dense, pow2-bucketed axes) so
            # scale-ups extend it in place instead of invalidating it
            eng.enable_grow()
        n = self.tensors.alloc.shape[0]
        self.valid = np.ones(n, bool)
        if self.pool_rows:
            self.valid[self.pool_rows] = False  # pool arms via node_up
        eng.node_valid = self.valid.copy()
        self.eng = eng
        # log mirrors: job id + batch row per engine log entry (the engine
        # log is the single source of placement truth; these map entries
        # back to jobs for departures/drains)
        self.log_jid = np.zeros(0, np.int64)
        self.log_row = np.zeros(0, np.int64)
        self.nodes_full = np.full(len(pods_all), -1, np.int64)
        # utilization bookkeeping (requested cpu vs valid allocatable)
        names = list(getattr(self.tensors, "resource_names", ()) or ())
        self.cpu_idx = names.index("cpu") if "cpu" in names else 0
        self.alloc_cpu = np.asarray(self.tensors.alloc[:, self.cpu_idx],
                                    np.float64)
        self.req_cpu = np.asarray(self.batch.req[:, self.cpu_idx], np.float64)
        self.used_cpu = 0.0
        self.res = TimelineResult()
        self.res.tensors = self.tensors
        self.res.batch = self.batch
        self.res.engine = eng
        self._admit_seq = 0

    def _build_heap(self) -> None:
        self.heap: List[tuple] = []
        self._seq = 0
        for st in self.jobs:
            self._push(st.job.t_s, EVT_ARRIVE, st.jid)
            if str(st.job.source).startswith("cron_jobs["):
                self._bump("cron_fires")
        for ev in self.trace.node_events:
            self._push(
                ev.t_s,
                EVT_NODE_DOWN if ev.kind == "down" else EVT_NODE_UP,
                ev,
            )
        auto = self.trace.autoscale
        if auto is not None:
            t = auto.interval_s
            while t <= self.trace.horizon_s:
                self._push(t, EVT_AUTOSCALE, None)
                t += auto.interval_s

    def _push(self, t: float, rank: int, payload) -> None:
        heapq.heappush(self.heap, (float(t), rank, self._seq, payload))
        self._seq += 1

    def _bump(self, key: str, n: int = 1) -> None:
        self.res.counts[key] += n
        REGISTRY.counter(f"timeline.{key}").inc(n)

    # -- engine plumbing ---------------------------------------------------

    def _place_rows(self, rows: np.ndarray, jid: int) -> np.ndarray:
        """Place `rows` through the engine, appending the log mirrors for
        the rows that landed.  The oracle dispatches one pod at a time
        with a from-log state rebuild before each dispatch; the batched
        path places the whole run in one call over the delta-advanced
        carry (wavefront-draftable — same group, contiguous)."""
        rows = np.asarray(rows, np.int64)
        if self.serial:
            out = np.empty(len(rows), np.int64)
            for k in range(len(rows)):
                self.eng._state_dirty = True  # force the from-log rebuild
                got, _, _ = self.eng.place(
                    slice_batch(self.batch, rows[k: k + 1])
                )
                out[k] = int(np.asarray(got)[0])
        else:
            got, _, _ = self.eng.place(slice_batch(self.batch, rows))
            out = np.asarray(got, np.int64)
        ok = rows[out >= 0]
        if len(ok):
            self.log_jid = np.concatenate(
                [self.log_jid, np.full(len(ok), jid, np.int64)]
            )
            self.log_row = np.concatenate([self.log_row, ok])
            self.used_cpu += float(self.req_cpu[ok].sum())
        return out

    def _remove_entries(self, indices: np.ndarray) -> dict:
        """Remove engine log entries (delta undo inside), keeping the
        mirrors and derived bookkeeping in lockstep."""
        idx = np.asarray(sorted(int(i) for i in indices), np.int64)
        rows = self.log_row[idx]
        saved = self.eng.remove_placements([int(i) for i in idx])
        keep = np.ones(len(self.log_jid), bool)
        keep[idx] = False
        removed = (idx, self.log_jid[idx].copy(), rows.copy())
        self.log_jid = self.log_jid[keep]
        self.log_row = self.log_row[keep]
        self.nodes_full[rows] = -1
        self.used_cpu -= float(self.req_cpu[rows].sum())
        for jid in np.unique(removed[1]):
            st = self.jobs[int(jid)]
            gone = rows[removed[1] == jid]
            pos = np.searchsorted(st.rows, gone)
            st.placed[pos] = False
        return {"saved": saved, "mirror": removed}

    def _restore_entries(self, token: dict) -> None:
        """Bit-identical inverse of `_remove_entries` (the preemption
        trial's rollback): delta re-apply plus mirror re-insertion."""
        saved = token["saved"]
        idx, jids, rows = token["mirror"]
        self.eng.restore_placements(saved)
        jid_list = list(self.log_jid)
        row_list = list(self.log_row)
        for i, j, r in zip(idx, jids, rows):
            jid_list.insert(int(i), int(j))
            row_list.insert(int(i), int(r))
        self.log_jid = np.asarray(jid_list, np.int64)
        self.log_row = np.asarray(row_list, np.int64)
        for (_, entry), r in zip(
            zip(saved["indices"], saved["entries"]), rows
        ):
            self.nodes_full[r] = entry[1]
        self.used_cpu += float(self.req_cpu[rows].sum())
        for jid in np.unique(jids):
            st = self.jobs[int(jid)]
            back = rows[jids == jid]
            pos = np.searchsorted(st.rows, back)
            st.placed[pos] = True

    def _evict_job(
        self,
        st: _JobState,
        entries: Optional[np.ndarray] = None,
        bump_epoch: bool = True,
    ) -> dict:
        """Evict a job's (subset of) placements.  `entries` are log
        indices (default: every entry of the job).  `bump_epoch` marks
        the job's scheduled departure stale — right for evictions that
        end the current run (the re-admission schedules a fresh one),
        wrong for partial evictions that leave the run alive (HPA
        scale-down), which pass False."""
        if entries is None:
            entries = np.flatnonzero(self.log_jid == st.jid)
        token = self._remove_entries(entries)
        if bump_epoch:
            st.epoch += 1  # any scheduled departure for the old run is stale
        return token

    # -- admission ---------------------------------------------------------

    def _mark_admitted(self, st: _JobState, t: float) -> None:
        st.status = "active"
        st.admit_seq = self._admit_seq
        self._admit_seq += 1
        if st.full_at is None:
            st.full_at = t
            self.res.pending_s.append(t - st.arrive_t)
            REGISTRY.histogram("timeline.pending_s").observe(t - st.arrive_t)
            dur = st.job.duration_s
            if dur is not None:
                self._push(
                    t + max(float(dur), _MIN_DURATION_S), EVT_DEPART,
                    (st.jid, st.epoch),
                )
        self._bump("admitted")

    def _try_admit_gang(self, st: _JobState, t: float) -> bool:
        """All-or-nothing: place the gang, roll back any partial."""
        rows = st.rows[: st.want]
        log_base = len(self.eng.placed_node)
        self._bump("attempts")
        with span("timeline.admit", job=st.job.name, pods=int(len(rows))):
            nodes = self._place_rows(rows, st.jid)
        if bool((nodes >= 0).all()):
            self.nodes_full[rows] = nodes
            st.placed[: st.want] = True
            self._mark_admitted(st, t)
            return True
        placed_ct = len(self.eng.placed_node) - log_base
        if placed_ct:
            # the partial gang never escapes this frame: undo the tail
            self._bump("gang_rollbacks")
            self._remove_entries(
                np.arange(log_base, log_base + placed_ct, dtype=np.int64)
            )
        return False

    def _try_admit_elastic(self, st: _JobState, t: float) -> int:
        """Per-replica best effort: place the missing rows, keep what
        lands.  Returns the newly-placed count."""
        missing = st.rows[: st.want][~st.placed[: st.want]]
        if not len(missing):
            return 0
        self._bump("attempts")
        with span("timeline.admit", job=st.job.name, pods=int(len(missing))):
            nodes = self._place_rows(missing, st.jid)
        ok = nodes >= 0
        landed = missing[ok]
        if len(landed):
            self.nodes_full[landed] = nodes[ok]
            pos = np.searchsorted(st.rows, landed)
            st.placed[pos] = True
        if st.placed[: st.want].all():
            self._mark_admitted(st, t)
        return int(len(landed))

    def _preempt_admit(self, st: _JobState, t: float) -> bool:
        """Evict strictly-lower-priority gangs (lowest priority first,
        youngest first) until the arrival admits; restore every victim
        via the delta undo when it never does."""
        evicted: List[Tuple[_JobState, dict]] = []
        admitted = False
        while True:
            cands = [
                v
                for v in self.jobs
                if v.status == "active" and v.jid != st.jid
                and v.job.priority < st.job.priority
            ]
            if not cands:
                break
            cands.sort(key=lambda v: (v.job.priority, -v.admit_seq))
            victim = cands[0]
            token = self._evict_job(victim)
            victim.status = "evicting"
            evicted.append((victim, token))
            if self._try_admit_gang(st, t):
                admitted = True
                break
        if not admitted:
            for victim, token in reversed(evicted):
                self._restore_entries(token)
                # the victim never actually left: un-stale its scheduled
                # departure by undoing the eviction's epoch bump (a
                # restored run IS the old run)
                victim.epoch -= 1
                victim.status = "active"
            return False
        self._bump("preemptions", len(evicted))
        for victim, token in evicted:
            pods = len(token["mirror"][0])
            self._bump("preempted_pods", pods)
            instant("timeline.preempt", victim=victim.job.name, pods=pods)
            victim.status = "pending"
            victim.full_at = None  # waits again; pending clock restarts
            victim.arrive_t = t
            self._push(t, EVT_RETRY, victim.jid)
        return True

    def _admit(self, st: _JobState, t: float, allow_preempt: bool) -> bool:
        """One admission opportunity; True when nothing remains pending."""
        if st.job.gang:
            if self._try_admit_gang(st, t):
                return True
            if allow_preempt and self.opts.preempt:
                if self._preempt_admit(st, t):
                    return True
            return False
        self._try_admit_elastic(st, t)
        return st.needs <= 0

    def _schedule_retry(self, st: _JobState, t: float) -> None:
        st.attempts += 1
        if st.attempts >= self.opts.max_retries:
            dropped = st.needs if not st.job.gang else st.want
            if st.job.gang:
                st.status = "dropped"
            else:
                # give up on the still-missing replicas only
                st.want = st.placed_count
                if st.want and st.full_at is None:
                    self._mark_admitted(st, t)
            self._bump("dropped_pods", int(dropped))
            return
        backoff = self.opts.retry_backoff_s * (2.0 ** (st.attempts - 1))
        self._push(t + backoff, EVT_RETRY, st.jid)

    def _retry_pending(self, t: float) -> None:
        """End-of-timestamp pass after released capacity: re-attempt every
        waiting job, priority-descending (arrival order breaking ties).
        Failures keep their scheduled backoff retries — this pass never
        burns an attempt."""
        pend = [
            st
            for st in self.jobs
            if st.status == "pending" and st.needs > 0
        ]
        pend.sort(key=lambda s: (-s.job.priority, s.jid))
        for st in pend:
            self._admit(st, t, allow_preempt=False)

    # -- event handlers ----------------------------------------------------

    def _handle_arrive(self, jid: int, t: float) -> None:
        st = self.jobs[jid]
        st.status = "pending"
        st.arrive_t = t
        self._bump("arrivals")
        if not self._admit(st, t, allow_preempt=True):
            self._schedule_retry(st, t)

    def _handle_retry(self, jid: int, t: float) -> None:
        st = self.jobs[jid]
        if st.status != "pending" or st.needs <= 0:
            return  # stale: admitted/departed/dropped meanwhile
        self._bump("retries")
        if not self._admit(st, t, allow_preempt=True):
            self._schedule_retry(st, t)

    def _handle_departs(self, departs: List[tuple], t: float) -> bool:
        """Process every departure at this timestamp.  The batched path
        coalesces them into ONE delta batch; the oracle removes job by
        job — bit-identical by the delta machinery's exactness."""
        live: List[_JobState] = []
        for jid, epoch in departs:
            st = self.jobs[jid]
            if st.status == "active" and st.epoch == epoch:
                live.append(st)
            elif st.status == "pending" and st.epoch == epoch:
                # departed while waiting: it leaves the queue
                st.status = "departed"
                self._bump("departures")
        if not live:
            return False
        with span("timeline.drain", jobs=int(len(live))):
            if self.serial:
                for st in live:
                    self._evict_job(st)
            else:
                jids = np.asarray([st.jid for st in live])
                entries = np.flatnonzero(np.isin(self.log_jid, jids))
                self._remove_entries(entries)
                for st in live:
                    st.epoch += 1
        for st in live:
            st.status = "departed"
            st.want = 0
            self._bump("departures")
        return True

    def _handle_node_event(self, ev, t: float, down: bool) -> bool:
        idxs = np.asarray([self.node_idx[n] for n in ev.nodes], np.int64)
        self._bump("node_down" if down else "node_up")
        if not down:
            self.valid[idxs] = True
            self.eng.node_valid = self.valid.copy()
            return True  # capacity released
        self.valid[idxs] = False
        self.eng.node_valid = self.valid.copy()
        # drain: gangs lose the whole gang (all-or-nothing holds under
        # failure too); elastic jobs lose only the dead replicas
        dead = np.zeros(self.tensors.alloc.shape[0], bool)
        dead[idxs] = True
        affected = np.flatnonzero(dead[np.asarray(self.eng.placed_node,
                                                  np.int64)])
        if not len(affected):
            return False
        jids = np.unique(self.log_jid[affected])
        with span("timeline.drain", jobs=int(len(jids)), node_down=True):
            for jid in jids:
                st = self.jobs[int(jid)]
                if st.job.gang:
                    self._evict_job(st)  # whole gang
                else:
                    entries = np.flatnonzero(
                        (self.log_jid == jid)
                        & dead[np.asarray(self.eng.placed_node, np.int64)]
                    )
                    self._evict_job(st, entries)
                st.status = "pending"
                st.full_at = None
                st.arrive_t = t
                self._push(t, EVT_RETRY, int(jid))
        return False  # capacity shrank; the retries ride their own events

    def _grow_pool_node(self) -> bool:
        """Grow the node axis for REAL — one template clone joins past the
        pre-provisioned pool via `Tensorizer.add_clone_nodes` and the
        engine's `grow_nodes` carry extension (no re-tensorize, no log
        rebuild).  Returns True when capacity was released; a `GrowRefused`
        template (vocabulary-class change) permanently disables further
        growth for this replay and is counted."""
        from ..core.tensorize import GrowRefused

        auto = self.trace.autoscale
        idx = self.tensors.alloc.shape[0]
        name = f"timeline-grow-{self._grown:04d}"
        node = make_valid_node_by_node(auto.node, name)
        try:
            self.tz.add_clone_nodes([node])
        except GrowRefused:
            self.grow_left = 0
            self._bump("pool_grow_refused")
            return False
        self._grown += 1
        self.grow_left -= 1
        # False means the term vocabulary moved under us (cannot happen
        # between ticks — no pods were added) — the next place() rebuilds
        # once from the log and the replay stays correct regardless
        self.eng.grow_nodes()
        self.tensors = self.tz.freeze()
        self.res.tensors = self.tensors
        self.valid = np.append(self.valid, True)
        self.eng.node_valid = self.valid.copy()
        self.alloc_cpu = np.append(
            self.alloc_cpu, float(self.tensors.alloc[idx, self.cpu_idx])
        )
        self.node_idx[name] = idx
        # grown nodes join the pool bookkeeping so the scale-down arm can
        # disarm them again once they sit empty
        self.pool_rows.append(idx)
        self._bump("pool_grow")
        return True

    def _sample(self, t: float) -> None:
        cap = float(self.alloc_cpu[self.valid].sum())
        util = self.used_cpu / cap if cap > 0 else 0.0
        placed = len(self.eng.placed_node)
        pending = sum(
            st.needs for st in self.jobs
            if st.status in ("pending", "active") and st.needs > 0
        )
        self.res.samples.append((t, util, placed, pending))
        REGISTRY.gauge("timeline.sim_clock_s").set(t)
        REGISTRY.gauge("timeline.util").set(round(util, 4))

    # -- the loop ----------------------------------------------------------

    def run(self) -> TimelineResult:
        res = self.res
        t0 = time.perf_counter()
        try:
            with span("timeline.replay", jobs=int(len(self.jobs)),
                      events=int(len(self.heap))):
                self._loop()
        except PlanInterrupted as exc:
            res.partial = True
            res.message = (
                f"replay interrupted ({exc.reason}): "
                f"{res.events} event(s) processed, sim clock at "
                f"{res.samples[-1][0] if res.samples else 0.0:g}s"
            )
        wall = time.perf_counter() - t0
        res.timings["wall_s"] = wall
        res.timings["events_per_s"] = res.events / wall if wall > 0 else 0.0
        res.nodes = self.nodes_full.copy()
        res.node_valid = self.valid.copy()
        res.still_pending = sum(
            1 for st in self.jobs
            if st.status == "pending" and st.needs > 0
        )
        self._audit(res)
        return res

    def _loop(self) -> None:
        auto = self.trace.autoscale
        while self.heap:
            self.control.check()  # deadline/SIGINT: cooperative partial
            t = self.heap[0][0]
            if t > self.trace.horizon_s:
                break
            released = False
            departs: List[tuple] = []
            while self.heap and self.heap[0][0] == t:
                _, rank, _, payload = heapq.heappop(self.heap)
                self.res.events += 1
                self._bump("events")
                if rank == EVT_DEPART:
                    departs.append(payload)
                    self.res.event_log.append(
                        (t, "depart", self.jobs[payload[0]].job.name)
                    )
                    continue
                if departs:
                    # capacity settles before anything else at this t
                    released |= self._handle_departs(departs, t)
                    departs = []
                if rank == EVT_ARRIVE:
                    self.res.event_log.append(
                        (t, "arrive", self.jobs[payload].job.name)
                    )
                    self._handle_arrive(payload, t)
                elif rank == EVT_RETRY:
                    self.res.event_log.append(
                        (t, "retry", self.jobs[payload].job.name)
                    )
                    self._handle_retry(payload, t)
                elif rank in (EVT_NODE_DOWN, EVT_NODE_UP):
                    self.res.event_log.append(
                        (t, RANK_NAMES[rank], ",".join(payload.nodes))
                    )
                    released |= self._handle_node_event(
                        payload, t, down=(rank == EVT_NODE_DOWN)
                    )
                elif rank == EVT_AUTOSCALE:
                    from .autoscale import autoscale_tick

                    self.res.event_log.append((t, "autoscale", ""))
                    with span("timeline.autoscale"):
                        released |= autoscale_tick(self, auto, t)
            if departs:
                released |= self._handle_departs(departs, t)
            if released:
                self._retry_pending(t)
            self._sample(t)

    # -- end-state certification ------------------------------------------

    def _audit(self, res: TimelineResult) -> None:
        from ..audit.checker import audit_enabled

        on = audit_enabled() if self.opts.audit is None else self.opts.audit
        if not on:
            return
        from ..audit.checker import audit_placement

        ext = {
            "lvm_alloc": np.zeros(
                (len(self.nodes_full), self.tensors.ext.vg_cap.shape[1])
            ),
            "dev_take": np.zeros(
                (len(self.nodes_full), self.tensors.ext.sdev_cap.shape[1]),
                bool,
            ),
            "gpu_shares": np.zeros(
                (len(self.nodes_full),
                 self.tensors.ext.gpu_dev_total.shape[1])
            ),
        }
        if len(self.log_row):
            rows = self.log_row
            ext["lvm_alloc"][rows] = np.asarray(self.eng.ext_log["vg_alloc"])
            ext["dev_take"][rows] = np.asarray(self.eng.ext_log["sdev_take"])
            ext["gpu_shares"][rows] = np.asarray(
                self.eng.ext_log["gpu_shares"]
            )
        report = audit_placement(
            self.tensors,
            self.batch,
            res.nodes,
            ext=ext,
            node_valid=self.valid,
        )
        res.audit = report.counters()
        if not report.ok:
            self._progress(
                f"timeline audit FAILED: {report.summary()}"
            )
