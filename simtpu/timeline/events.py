"""Trace model + event stream for `simtpu replay` (docs/timeline.md).

A TRACE is the replay engine's input: a cluster, a time-ordered stream of
workload arrivals (each with a duration and a priority), CronJob objects
whose real `spec.schedule` cron expressions generate firings, and node
up/down events from the faults scenario model's vocabulary.  Traces load
from a JSON file (`load_trace`) or assemble in memory
(`synth.make_trace` → `trace_from_doc`); malformed input raises the same
one-line `SpecError` diagnostics as manifest ingest, carrying the
offending event index (and the source line for syntax errors).

Determinism contract (the serial-oracle pinning rests on it):
- events sort by `(t, rank, seq)` where rank orders kinds within one
  timestamp — departures first (capacity settles), then node up, node
  down, arrivals, retries, autoscaler checks — and `seq` is the stable
  input order;
- cron firings enumerate through the SHARED parser
  (`workloads/cron.py`), epoch-anchored UTC, so the static expansion
  path and the replay agree on what a schedule means;
- pod-name suffixes draw from a stream seeded off the trace seed
  (`expand.seed_name_hashes`), so two replays of one trace expand
  identical pods.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import List, Optional

from .. import constants as C
from ..core.objects import ResourceTypes, name_of
from ..workloads.cron import cron_job_schedule, cron_job_suspended, fire_times
from ..workloads.expand import (
    generate_job_from_cron_job,
    make_valid_pod_by_pod,
    make_valid_pods_by_deployment,
    make_valid_pods_by_job,
    make_valid_pods_by_replica_set,
    make_valid_pods_by_replication_controller,
    make_valid_pods_by_stateful_set,
    spec_context,
)
from ..workloads.validate import SpecError

#: trace document version `load_trace` accepts
TRACE_VERSION = 1

# -- event ranks: the within-timestamp processing order ----------------------
# Capacity-releasing events settle before capacity-consuming ones at the
# same instant; pending-queue retries run at the END of each timestamp
# (after every event at that t), which is what makes the batched path's
# same-timestamp departure coalescing semantics-identical to the serial
# oracle's one-at-a-time processing.
EVT_DEPART = 0
EVT_NODE_UP = 1
EVT_NODE_DOWN = 2
EVT_ARRIVE = 3
EVT_RETRY = 4
EVT_AUTOSCALE = 5

RANK_NAMES = {
    EVT_DEPART: "depart",
    EVT_NODE_UP: "node_up",
    EVT_NODE_DOWN: "node_down",
    EVT_ARRIVE: "arrive",
    EVT_RETRY: "retry",
    EVT_AUTOSCALE: "autoscale",
}

#: workload-kind → pod expander, the trace-side mirror of
#: `expand.get_valid_pods_exclude_daemonset`'s table (DaemonSets are
#: cluster-shaped, not arrival-shaped, and deliberately absent)
_EXPANDERS = {
    "Pod": lambda w: [make_valid_pod_by_pod(w)],
    C.KIND_DEPLOYMENT: make_valid_pods_by_deployment,
    C.KIND_RS: make_valid_pods_by_replica_set,
    C.KIND_RC: make_valid_pods_by_replication_controller,
    C.KIND_STS: make_valid_pods_by_stateful_set,
    C.KIND_JOB: make_valid_pods_by_job,
}


@dataclass
class TraceJob:
    """One arriving workload: a gang (all-or-nothing) or an elastic
    (per-replica, HPA-scalable) pod group."""

    seq: int  # stable arrival order (tie-break within a timestamp)
    name: str
    t_s: float  # arrival time, seconds of sim clock
    duration_s: Optional[float]  # None = runs forever once admitted
    workload: dict  # Deployment / Job / ... manifest (single workload)
    priority: int = 0
    gang: bool = True
    #: {"min": int, "max": int, "usage": float | [[t_s, frac], ...]} —
    #: HPA-scalable; elastic jobs are per-replica (gang=False enforced)
    elastic: Optional[dict] = None
    source: str = ""  # provenance for diagnostics ("jobs[3]", "cron ...")


@dataclass
class NodeEvent:
    t_s: float
    kind: str  # "down" | "up"
    nodes: List[str]  # node names (the faults scenario vocabulary)


@dataclass
class AutoscaleSpec:
    """HPA + cluster-pool emulation knobs (timeline/autoscale.py)."""

    interval_s: float = 300.0
    target_util: float = 0.6  # HPA target utilization of requests
    pool: int = 0  # pre-provisioned template nodes the pool scaler arms
    node: Optional[dict] = None  # pool node template (required when pool>0)
    grow_max: int = 0  # extra clones grown PAST the pool (append-only
    #                    node-axis growth; 0 keeps the fixed-axis behavior)


@dataclass
class Trace:
    cluster: ResourceTypes
    jobs: List[TraceJob]
    node_events: List[NodeEvent] = field(default_factory=list)
    horizon_s: float = 86400.0
    seed: int = 0
    autoscale: Optional[AutoscaleSpec] = None
    source: str = "<in-memory>"


def _want(doc: dict, key: str, types, where: str, default="__required__"):
    """One validated field of a trace document — SpecError names the
    offending entry (`where` is e.g. `jobs[3]`) and the field."""
    if key not in doc:
        if default != "__required__":
            return default
        raise SpecError("missing required field", field=f"{where}.{key}")
    val = doc[key]
    if types is not None and not isinstance(val, types):
        raise SpecError(
            f"expected {'/'.join(t.__name__ for t in types)}, "
            f"got {type(val).__name__}",
            field=f"{where}.{key}",
        )
    return val


def _number(doc, key, where, default="__required__", minimum=None):
    v = _want(doc, key, (int, float), where, default)
    if v is not None and minimum is not None and v < minimum:
        raise SpecError(f"must be >= {minimum}", field=f"{where}.{key}")
    return v


def trace_from_doc(doc: dict, source: str = "<in-memory>") -> Trace:
    """Validate one trace document into a `Trace`, expanding CronJob
    firings into dated arrival jobs through the shared cron parser."""
    if not isinstance(doc, dict):
        raise SpecError("trace document must be a JSON object", source=source)
    try:
        version = _want(doc, "version", (int,), "trace", TRACE_VERSION)
        if version != TRACE_VERSION:
            raise SpecError(
                f"unsupported trace version {version} "
                f"(this build reads {TRACE_VERSION})",
                field="trace.version",
            )
        horizon = _number(doc, "horizon_s", "trace", 86400.0, minimum=1.0)
        seed = int(_number(doc, "seed", "trace", 0))
        # cron firings instantiate Job objects below, whose generated
        # name suffixes draw from the expansion name stream — seed it
        # here so two parses of one trace (the batched run and the
        # serial oracle) produce byte-identical firing workloads
        from ..workloads.expand import seed_name_hashes

        seed_name_hashes(0x7ACE_C0DE ^ seed)
        cluster = _cluster_from(doc.get("cluster"), source)

        jobs: List[TraceJob] = []
        for i, jd in enumerate(_want(doc, "jobs", (list,), "trace", [])):
            where = f"jobs[{i}]"
            if not isinstance(jd, dict):
                raise SpecError("event must be an object", field=where)
            jobs.append(_job_from(jd, len(jobs), where))

        for i, cd in enumerate(_want(doc, "cron_jobs", (list,), "trace", [])):
            where = f"cron_jobs[{i}]"
            if not isinstance(cd, dict):
                raise SpecError("entry must be an object", field=where)
            jobs.extend(_cron_arrivals(cd, horizon, len(jobs), where))

        node_events: List[NodeEvent] = []
        for i, nd in enumerate(
            _want(doc, "node_events", (list,), "trace", [])
        ):
            where = f"node_events[{i}]"
            if not isinstance(nd, dict):
                raise SpecError("event must be an object", field=where)
            t = float(_number(nd, "t_s", where, minimum=0.0))
            down = _want(nd, "down", (list,), where, None)
            up = _want(nd, "up", (list,), where, None)
            if (down is None) == (up is None):
                raise SpecError(
                    "exactly one of 'down'/'up' (a node-name list) required",
                    field=where,
                )
            kind = "down" if down is not None else "up"
            names = [str(x) for x in (down if down is not None else up)]
            if not names:
                raise SpecError("empty node list", field=f"{where}.{kind}")
            node_events.append(NodeEvent(t_s=t, kind=kind, nodes=names))

        autoscale = None
        ad = _want(doc, "autoscale", (dict,), "trace", None)
        if ad is not None:
            autoscale = AutoscaleSpec(
                interval_s=float(
                    _number(ad, "interval_s", "autoscale", 300.0, minimum=1.0)
                ),
                target_util=float(
                    _number(ad, "target_util", "autoscale", 0.6, minimum=0.01)
                ),
                pool=int(_number(ad, "pool", "autoscale", 0, minimum=0)),
                node=_want(ad, "node", (dict,), "autoscale", None),
                grow_max=int(
                    _number(ad, "grow_max", "autoscale", 0, minimum=0)
                ),
            )
            if (autoscale.pool or autoscale.grow_max) \
                    and autoscale.node is None:
                raise SpecError(
                    "autoscale.pool/grow_max > 0 requires autoscale.node "
                    "(the template the pool nodes clone)",
                    field="autoscale.pool",
                )
    except SpecError as exc:
        raise exc.attach(source=source)
    return Trace(
        cluster=cluster,
        jobs=jobs,
        node_events=node_events,
        horizon_s=float(horizon),
        seed=seed,
        autoscale=autoscale,
        source=source,
    )


def _cluster_from(cd, source: str) -> ResourceTypes:
    if not isinstance(cd, dict):
        raise SpecError(
            "trace.cluster required: {'nodes': [...]} or "
            "{'synth': {n_nodes, seed, ...}}",
            field="trace.cluster",
        )
    if "synth" in cd:
        from ..synth import synth_cluster

        params = cd["synth"]
        if not isinstance(params, dict) or "n_nodes" not in params:
            raise SpecError(
                "cluster.synth must be an object with n_nodes",
                field="trace.cluster.synth",
            )
        try:
            return synth_cluster(**{str(k): v for k, v in params.items()})
        except TypeError as exc:
            raise SpecError(str(exc), field="trace.cluster.synth")
    nodes = cd.get("nodes")
    if not isinstance(nodes, list) or not nodes:
        raise SpecError(
            "cluster.nodes must be a non-empty node list",
            field="trace.cluster.nodes",
        )
    res = ResourceTypes()
    res.nodes = list(nodes)
    scs = cd.get("storage_classes")
    if scs:
        res.storage_classes = list(scs)
    return res


def _job_from(jd: dict, seq: int, where: str) -> TraceJob:
    workload = _want(jd, "workload", (dict,), where)
    kind = workload.get("kind")
    if kind not in _EXPANDERS:
        raise SpecError(
            f"unsupported workload kind {kind!r} "
            f"(one of {sorted(_EXPANDERS)})",
            field=f"{where}.workload.kind",
        )
    t = float(_number(jd, "t_s", where, minimum=0.0))
    dur = _number(jd, "duration_s", where, None)
    if dur is not None:
        dur = float(dur)
        if dur <= 0:
            raise SpecError("must be > 0 (omit for forever)",
                            field=f"{where}.duration_s")
    gang = bool(_want(jd, "gang", (bool,), where, True))
    elastic = _want(jd, "elastic", (dict,), where, None)
    if elastic is not None:
        if gang and "gang" in jd:
            raise SpecError(
                "elastic jobs are per-replica (gang admission and HPA "
                "scaling are mutually exclusive)",
                field=f"{where}.gang",
            )
        gang = False
        lo = int(_number(elastic, "min", f"{where}.elastic", 1, minimum=0))
        hi = int(_number(elastic, "max", f"{where}.elastic", minimum=1))
        if hi < max(lo, 1):
            raise SpecError("max < min", field=f"{where}.elastic.max")
        usage = elastic.get("usage", 0.6)
        if not isinstance(usage, (int, float, list)):
            raise SpecError(
                "usage must be a fraction or [[t_s, fraction], ...]",
                field=f"{where}.elastic.usage",
            )
        elastic = {"min": lo, "max": hi, "usage": usage}
    return TraceJob(
        seq=seq,
        name=str(jd.get("name") or name_of(workload) or f"job-{seq}"),
        t_s=t,
        duration_s=dur,
        workload=workload,
        priority=int(_number(jd, "priority", where, 0)),
        gang=gang,
        elastic=elastic,
        source=where,
    )


def _cron_arrivals(
    cd: dict, horizon: float, seq0: int, where: str
) -> List[TraceJob]:
    """CronJob entry → one arrival job per firing of its real
    `spec.schedule` within `[0, horizon]` (shared parser; suspend and
    startingDeadlineSeconds honored; deadline-late fires admit at 0)."""
    cj = _want(cd, "cron_job", (dict,), where)
    if (cj.get("kind") or "CronJob") != C.KIND_CRON_JOB:
        raise SpecError(
            f"cron_job entry must be a CronJob, got {cj.get('kind')!r}",
            field=f"{where}.cron_job.kind",
        )
    dur = _number(cd, "duration_s", where, None)
    if dur is not None and float(dur) <= 0:
        raise SpecError("must be > 0 (omit for forever)",
                        field=f"{where}.duration_s")
    prio = int(_number(cd, "priority", where, 0))
    with spec_context(C.KIND_CRON_JOB, cj):
        if cron_job_suspended(cj):
            return []
        sched = cron_job_schedule(cj)
    deadline = (cj.get("spec") or {}).get("startingDeadlineSeconds")
    fires = fire_times(
        sched, 0.0, float(horizon),
        starting_deadline_s=float(deadline) if deadline is not None else None,
    )
    out = []
    for k, fire in enumerate(fires):
        with spec_context(C.KIND_CRON_JOB, cj):
            job = generate_job_from_cron_job(cj)
        out.append(
            TraceJob(
                seq=seq0 + k,
                name=name_of(job),
                # a deadline-late fire (< 0 on the sim clock) admits at
                # the window start, mirroring the controller's catch-up
                t_s=max(float(fire), 0.0),
                duration_s=float(dur) if dur is not None else None,
                workload=job,
                priority=prio,
                gang=True,
                elastic=None,
                source=f"{where}@{fire:g}s",
            )
        )
    return out


def load_trace(path: str) -> Trace:
    """Parse + validate one trace file.  Syntax errors carry the source
    line; semantic errors carry the offending event index — both as ONE
    actionable `SpecError` line (docs/robustness.md)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as exc:
        raise SpecError(f"cannot read trace file: {exc}", source=path)
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as exc:
        raise SpecError(
            f"malformed JSON: {exc.msg}", source=f"{path}:{exc.lineno}"
        )
    return trace_from_doc(doc, source=path)


def expand_job_pods(job: TraceJob) -> List[dict]:
    """The pods one arriving job schedules, through the SAME expansion
    path as static ingest (`workloads/expand.py`); elastic jobs expand
    their `max` replicas (rows beyond the initial target are the HPA
    scale-up reserve)."""
    workload = job.workload
    if job.elastic is not None:
        workload = dict(workload)
        workload["spec"] = dict(workload.get("spec") or {})
        field_name = "completions" if workload.get("kind") == C.KIND_JOB else "replicas"
        workload["spec"][field_name] = int(job.elastic["max"])
    with spec_context(workload.get("kind", "workload"), workload):
        return _EXPANDERS[workload["kind"]](workload)


def initial_replicas(job: TraceJob) -> int:
    """The replica count an arrival initially asks for (elastic jobs:
    spec replicas clamped into [min, max])."""
    spec = job.workload.get("spec") or {}
    want = spec.get("completions" if job.workload.get("kind") == C.KIND_JOB
                    else "replicas")
    want = 1 if want is None else int(want)
    if job.elastic is not None:
        want = max(job.elastic["min"], min(want, job.elastic["max"]))
        want = max(want, 1)
    return want
