"""`simtpu replay` — trace-driven continuous-time simulation engine.

Event model and trace loading live in `timeline/events.py`, the replay
loop (gang admission, pending queue, preemption, node events, the serial
oracle) in `timeline/replay.py`, the HPA/pool autoscaler emulation in
`timeline/autoscale.py`.  See docs/timeline.md.
"""

from .events import (
    AutoscaleSpec,
    NodeEvent,
    TRACE_VERSION,
    Trace,
    TraceJob,
    load_trace,
    trace_from_doc,
)
from .replay import (
    ReplayOptions,
    TIMELINE_KEYS,
    TimelineResult,
    replay_trace,
)

__all__ = [
    "AutoscaleSpec",
    "NodeEvent",
    "ReplayOptions",
    "TIMELINE_KEYS",
    "TRACE_VERSION",
    "TimelineResult",
    "Trace",
    "TraceJob",
    "load_trace",
    "replay_trace",
    "trace_from_doc",
]
