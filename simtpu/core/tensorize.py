"""Cluster + pod tensorization: k8s objects → dense arrays.

This module is the boundary between the host-side object world and the TPU
engine. It lowers the state the vendored scheduler keeps in caches and
informers (`vendor/.../scheduler/internal/cache/cache.go:57`, snapshot per
cycle) into:

- per-node resource arrays `alloc[N, R]`,
- per-topology-key domain ids `node_dom[K, N]`,
- a *pod group* axis G (pods with identical scheduling-relevant specs share a
  group), with a precomputed static feasibility mask `static_mask[G, N]`
  covering the stateless filter plugins (NodeUnschedulable, NodeName,
  TaintToleration, NodeAffinity/selector — `vendor/.../algorithmprovider/
  registry.go:75-145`), plus static per-group score terms, and
- an inter-pod affinity *term universe* T with the group↔term incidence
  matrices the scan-time InterPodAffinity kernels consume
  (`vendor/.../framework/plugins/interpodaffinity/filtering.go` semantics).

Node labels/taints never change during a simulation (nodes are pure data,
`SURVEY.md §4`), so everything that depends only on (pod spec, node spec) is
evaluated here once, vectorized over nodes in numpy; only state that evolves
with placements (free resources, topology counts, storage, GPU devices) lives
in the scan carry (engine/state.py).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import constants as C
from .extended import (
    ExtendedNodeArrays,
    StorageClassCatalog,
    pod_extended_demand,
    tensorize_node_storage,
)
from .match import (
    OP_DOES_NOT_EXIST,
    OP_EXISTS,
    OP_GT,
    OP_IN,
    OP_LT,
    OP_NOT_IN,
    match_label_selector,
    toleration_tolerates_taint,
)
from .objects import (
    ATTACH_CLASSES,
    labels_of,
    name_of,
    namespace_of,
    node_allocatable,
    node_images,
    node_prefer_avoid_pods,
    node_taints,
    node_unschedulable,
    pod_affinity,
    pod_attachable_volumes,
    pod_host_ports,
    pod_images,
    pod_node_name,
    pod_node_selector,
    pod_owner_kind,
    pod_pvc_names,
    pod_requests,
    pod_tolerations,
    pod_topology_spread_constraints,
    pod_volume_conflicts,
    csi_attach_limit_key,
    pv_attachable_source,
    pv_csi_source,
)
from .quantity import parse_quantity
from .vocab import Interner

# Canonical resource order; extended resources appended dynamically.
RES_CPU = 0
RES_MEMORY = 1
RES_PODS = 2
_BASE_RESOURCES = ("cpu", "memory", "pods")

# Synthetic taint for unschedulable nodes (NodeUnschedulable plugin semantics:
# `vendor/.../plugins/nodeunschedulable/node_unschedulable.go`).
_UNSCHEDULABLE_TAINT = {"key": "node.kubernetes.io/unschedulable", "effect": "NoSchedule"}

# Domain-count cap for the "small key" same-domain reduction route (zone /
# region / rack-sized keys); keys with more domains use the unique-per-node
# route (hostname) or the scatter fallback. Shared with engine/rounds.py.
DOM_SMALL = 64

# -- carried-state dtype policy (THE conversion boundary) -------------------
#
# Every count-like plane of the carried scheduling state (topology counts,
# interpod owner counts/weights, port and volume user counts) holds small
# integers by construction: placements bump them by ±1 or by integer k8s
# preference weights, so the values are exact in int32 AND in float32 (below
# 2^24).  The layout policy (docs/memory.md, "state layout" table) is:
#
#   carried/boundary form (engine/state.py CompactState): COUNT_DTYPE —
#     integer, the honest dtype; crossing a dispatch boundary or the wire in
#     this form costs no precision and keeps regrouped sums bit-stable.
#   in-kernel form (SchedState inside a dispatch): float32 — the one-hot
#     matmul row gathers (state.take_rows) and the scoring kernels are
#     float pipelines; int-valued f32 arithmetic on counts is exact, so the
#     f32 <-> COUNT_DTYPE casts at expand/compress are bit-clean round trips.
#   boolean planes (sdev_free, node_valid, every feasibility mask):
#     MASK_DTYPE end to end — never widened to float.
#
# This block is the single place the policy lives; engine/state.py imports
# these names rather than restating dtypes at each conversion site.
COUNT_DTYPE = np.int32
MASK_DTYPE = np.bool_


# ---------------------------------------------------------------------------
# Node-side vectorized label algebra
# ---------------------------------------------------------------------------


class NodeLabelIndex:
    """Boolean-column view of node labels for vectorized selector evaluation."""

    def __init__(self, nodes: Sequence[dict]):
        self.n = len(nodes)
        self.names = np.array([name_of(n) for n in nodes])
        # per key: an [N] int32 value-id array (-1 = key absent) plus the
        # value→id map. Storage is O(keys x N) — a dense bool column per
        # (key, value) pair would be O(N^2) through high-cardinality keys
        # like kubernetes.io/hostname.
        self._vid: Dict[str, np.ndarray] = {}
        self._vmap: Dict[str, Dict[str, int]] = {}
        self._val: Dict[str, np.ndarray] = {}  # raw values per key (for Gt/Lt)
        for i, node in enumerate(nodes):
            for k, v in labels_of(node).items():
                v = "" if v is None else str(v)
                vid = self._vid.get(k)
                if vid is None:
                    vid = self._vid[k] = np.full(self.n, -1, np.int32)
                    self._vmap[k] = {}
                    self._val[k] = np.full(self.n, "", object)
                vm = self._vmap[k]
                j = vm.get(v)
                if j is None:
                    j = vm[v] = len(vm)
                vid[i] = j
                self._val[k][i] = v

    def has_kv(self, key: str, value: str) -> np.ndarray:
        vid = self._vid.get(key)
        if vid is None:
            return np.zeros(self.n, bool)
        return vid == self._vmap[key].get(value, -2)

    def has_key(self, key: str) -> np.ndarray:
        vid = self._vid.get(key)
        return vid >= 0 if vid is not None else np.zeros(self.n, bool)

    def match_requirement(self, req: dict, field_names: Optional[np.ndarray] = None) -> np.ndarray:
        """Vectorized NodeSelectorRequirement over all nodes.

        field_names switches evaluation to matchFields (metadata.name).
        Semantics mirror match.match_requirement / apimachinery selector.go.
        """
        key = req.get("key", "")
        op = req.get("operator", "")
        vals = req.get("values") or []
        if field_names is not None:
            # only metadata.name is a legal field key
            if key != "metadata.name":
                return np.zeros(self.n, bool)
            present = np.ones(self.n, bool)
            member = np.isin(field_names, vals)
            if op == OP_IN:
                return member
            if op == OP_NOT_IN:
                return ~member
            if op == OP_EXISTS:
                return present
            if op == OP_DOES_NOT_EXIST:
                return ~present
            return np.zeros(self.n, bool)
        present = self.has_key(key)
        if op == OP_IN:
            out = np.zeros(self.n, bool)
            for v in vals:
                out |= self.has_kv(key, v)
            return out
        if op == OP_NOT_IN:
            out = np.zeros(self.n, bool)
            for v in vals:
                out |= self.has_kv(key, v)
            return ~out
        if op == OP_EXISTS:
            return present
        if op == OP_DOES_NOT_EXIST:
            return ~present
        if op in (OP_GT, OP_LT):
            if not vals:
                return np.zeros(self.n, bool)
            try:
                rhs = int(vals[0])
            except ValueError:
                return np.zeros(self.n, bool)
            out = np.zeros(self.n, bool)
            raw = self._val.get(key)
            if raw is None:
                return out
            for i in range(self.n):
                if present[i]:
                    try:
                        lhs = int(raw[i])
                    except (ValueError, TypeError):
                        continue
                    out[i] = lhs > rhs if op == OP_GT else lhs < rhs
            return out
        return np.zeros(self.n, bool)

    def match_term(self, term: dict) -> np.ndarray:
        """One NodeSelectorTerm over all nodes (AND of expressions+fields)."""
        exprs = term.get("matchExpressions") or []
        fields = term.get("matchFields") or []
        if not exprs and not fields:
            return np.zeros(self.n, bool)
        out = np.ones(self.n, bool)
        for req in exprs:
            out &= self.match_requirement(req)
        for req in fields:
            out &= self.match_requirement(req, field_names=self.names)
        return out


# ---------------------------------------------------------------------------
# Group signatures & pin extraction
# ---------------------------------------------------------------------------


def _canon(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def _extract_pin(node_affinity_required: Optional[dict]) -> Tuple[Optional[str], Optional[dict]]:
    """Detect a DaemonSet-style metadata.name pin common to every term.

    DaemonSet pods are pinned per node (`pkg/utils/utils.go:861-906`), which
    would otherwise explode the group axis to one group per node. If every
    required term carries the same single `metadata.name In [x]` matchFields
    requirement, return (x, affinity-with-fields-stripped).
    """
    if not node_affinity_required:
        return None, node_affinity_required
    terms = node_affinity_required.get("nodeSelectorTerms") or []
    if not terms:
        return None, node_affinity_required
    pin = None
    stripped_terms = []
    for term in terms:
        fields = term.get("matchFields") or []
        if (
            len(fields) != 1
            or fields[0].get("key") != "metadata.name"
            or fields[0].get("operator") != OP_IN
            or len(fields[0].get("values") or []) != 1
        ):
            return None, node_affinity_required
        value = fields[0]["values"][0]
        if pin is None:
            pin = value
        elif pin != value:
            return None, node_affinity_required
        t = {k: v for k, v in term.items() if k != "matchFields"}
        stripped_terms.append(t)
    # a term left empty after stripping was pure pin — its expression part is
    # vacuously true, and terms are OR'd, so the whole required clause reduces
    # to just the pin
    if any(not t.get("matchExpressions") for t in stripped_terms):
        return pin, None
    return pin, {"nodeSelectorTerms": stripped_terms}


@dataclass
class PodGroup:
    """One equivalence class of pods (identical scheduling-relevant spec)."""

    node_selector: dict
    affinity_required: Optional[dict]  # pin-stripped node affinity required
    affinity_preferred: list
    tolerations: list
    labels: Dict[str, str]
    namespace: str
    pod_affinity: dict  # podAffinity sub-dict
    pod_anti_affinity: dict
    host_ports: Tuple[Tuple[str, int], ...] = ()  # (protocol, hostPort)
    topology_spread: tuple = ()  # canonicalized topologySpreadConstraints
    owner_kind: str = ""  # controller ownerReference kind
    images: Tuple[str, ...] = ()  # container image names
    vol_rw: Tuple[str, ...] = ()  # exclusive volume keys (VolumeRestrictions)
    vol_ro: Tuple[str, ...] = ()  # read-only-shareable volume keys
    vol_att: tuple = ()  # inline attachable (key, class) pairs (NodeVolumeLimits)
    pvc_refs: Tuple[str, ...] = ()  # referenced claim names (VolumeBinding/Zone)

    def signature(self) -> str:
        return _canon(
            [
                self.node_selector,
                self.affinity_required,
                self.affinity_preferred,
                self.tolerations,
                sorted(self.labels.items()),
                self.namespace,
                self.pod_affinity,
                self.pod_anti_affinity,
                list(self.host_ports),
                list(self.topology_spread),
                self.owner_kind,
                sorted(self.images),
                list(self.vol_rw),
                list(self.vol_ro),
                [list(p) for p in self.vol_att],
                list(self.pvc_refs),
            ]
        )


def _group_of_pod(pod: dict) -> Tuple[PodGroup, Optional[str]]:
    aff = pod_affinity(pod)
    node_aff = aff.get("nodeAffinity") or {}
    pin, stripped_required = _extract_pin(
        node_aff.get("requiredDuringSchedulingIgnoredDuringExecution")
    )
    # NodePorts semantics collapse hostIP to the (protocol, port) pair: two
    # hostPorts conflict when IPs overlap and 0.0.0.0 (the default) overlaps
    # everything (`plugins/nodeports/node_ports.go`); distinct non-wildcard
    # IPs on the same port are rare enough to treat as conflicting.
    ports = tuple(
        sorted({(proto, port) for proto, _ip, port in pod_host_ports(pod)})
    )
    spread = tuple(
        _canon(c) for c in pod_topology_spread_constraints(pod)
    )
    vol_rw, vol_ro = pod_volume_conflicts(pod)
    return (
        PodGroup(
            node_selector=pod_node_selector(pod),
            affinity_required=stripped_required,
            affinity_preferred=node_aff.get("preferredDuringSchedulingIgnoredDuringExecution")
            or [],
            tolerations=pod_tolerations(pod),
            labels=labels_of(pod),
            namespace=namespace_of(pod),
            pod_affinity=aff.get("podAffinity") or {},
            pod_anti_affinity=aff.get("podAntiAffinity") or {},
            host_ports=ports,
            topology_spread=spread,
            owner_kind=pod_owner_kind(pod),
            images=tuple(pod_images(pod)),
            vol_rw=vol_rw,
            vol_ro=vol_ro,
            vol_att=tuple(pod_attachable_volumes(pod)),
            pvc_refs=tuple(sorted(set(pod_pvc_names(pod)))),
        ),
        pin,
    )


# ---------------------------------------------------------------------------
# Inter-pod affinity term universe
# ---------------------------------------------------------------------------


_UNPARSED = object()


@dataclass(frozen=True)
class Term:
    topology_key: str
    namespaces: Tuple[str, ...]
    selector_json: str  # canonical labelSelector

    @property
    def selector(self) -> dict:
        """Parsed labelSelector, cached on the instance — s_match refresh
        touches terms repeatedly and a per-call json.loads dominated it at
        scale. The cache lives and dies with the Term (no process-global
        growth); callers treat the returned dict as read-only. eq/hash use
        the declared fields only, so the cache slot does not affect
        interning."""
        got = getattr(self, "_parsed", _UNPARSED)
        if got is _UNPARSED:
            got = json.loads(self.selector_json)
            object.__setattr__(self, "_parsed", got)
        return got


def _terms_of(spec_terms: list, default_ns: str) -> List[Tuple[Term, float]]:
    """PodAffinityTerm list → [(Term, weight)] with weight 1 for required."""
    out = []
    for item in spec_terms or []:
        if "podAffinityTerm" in item:  # weighted form
            weight = float(item.get("weight", 0))
            term = item["podAffinityTerm"]
        else:
            weight = 1.0
            term = item
        ns = tuple(sorted(term.get("namespaces") or [default_ns]))
        sel = term.get("labelSelector")
        out.append(
            (
                Term(
                    topology_key=term.get("topologyKey", ""),
                    namespaces=ns,
                    selector_json=_canon(sel),
                ),
                weight,
            )
        )
    return out


class _RowTable:
    """Growing [G, N] plane with capacity doubling.

    Replaces per-group Python lists of [N] rows: freeze() used to np.stack
    ~2 GB of them at 1000 groups × 100k nodes (seconds per plane); here rows
    land in place and freeze() returns a zero-copy view. `append(None)`
    leaves the row at the fill value without touching memory — most planes
    (ImageLocality, preferred affinity, avoid penalties, volume masks) are
    all-fill for most groups.
    """

    def __init__(self, n: int, dtype, fill=0):
        self.n = n
        self.dtype = np.dtype(dtype)
        self.fill = fill
        self.rows = 0
        self.buf = self._alloc(16)

    def _alloc(self, cap: int) -> np.ndarray:
        if self.fill == 0 or self.fill is False:
            return np.zeros((cap, self.n), self.dtype)
        out = np.empty((cap, self.n), self.dtype)
        out.fill(self.fill)
        return out

    def append(self, row: Optional[np.ndarray]) -> None:
        if self.rows == self.buf.shape[0]:
            new = self._alloc(self.buf.shape[0] * 2)
            new[: self.rows] = self.buf
            self.buf = new
        if row is not None:
            self.buf[self.rows] = row
        self.rows += 1

    def view(self) -> np.ndarray:
        """[rows, N] zero-copy view. Later appends only write rows beyond it
        (or reallocate), so a frozen view's contents never change."""
        return self.buf[: self.rows]

    def __getitem__(self, i: int) -> np.ndarray:
        # bound-check against rows, not capacity: an index into the grown
        # tail would silently return a fill row and mask an off-by-one
        if not 0 <= i < self.rows:
            raise IndexError(i)
        return self.buf[i]

    def __len__(self) -> int:
        return self.rows


# ---------------------------------------------------------------------------
# The tensorized cluster
# ---------------------------------------------------------------------------


@dataclass
class ClusterTensors:
    """Everything static the engine needs, as numpy arrays (host-side)."""

    node_names: List[str]
    resource_names: List[str]
    alloc: np.ndarray  # [N, R] f32
    node_dom: np.ndarray  # [K, N] i32 global domain id, -1 when key absent
    n_domains: int
    topo_keys: List[str]
    # per-key same-domain reduction routing (engine/rounds.py): 1 = SMALL
    # (≤ DOM_SMALL domains; compact per-key ids in node_dom_small feed a
    # one-hot einsum), 2 = UNIQUE (every domain holds one node — zone sums
    # are the values themselves), 0 = fallback scatter
    key_kind: np.ndarray  # [K] i32
    node_dom_small: np.ndarray  # [K, N] i32 compact per-key id, -1 absent

    # group axis
    groups: List[PodGroup]
    static_mask: np.ndarray  # [G, N] bool — unschedulable+taints+affinity+selector
    node_pref_score: np.ndarray  # [G, N] f32 — NodeAffinity preferred raw score
    taint_intolerable: np.ndarray  # [G, N] f32 — count of intolerable PreferNoSchedule
    static_score: np.ndarray  # [G, N] f32 — ImageLocality

    # inter-pod term axis
    terms: List[Term]
    term_topo_key: np.ndarray  # [T] i32 index into topo_keys
    s_match: np.ndarray  # [G, T] bool — group's pods match term selector+ns
    a_aff_req: np.ndarray  # [G, T] bool
    a_anti_req: np.ndarray  # [G, T] bool
    w_aff_pref: np.ndarray  # [G, T] f32 (summed weights)
    w_anti_pref: np.ndarray  # [G, T] f32
    spread_hard: np.ndarray  # [G, T] f32 — maxSkew of DoNotSchedule constraints (0 = none)
    spread_soft: np.ndarray  # [G, T] f32 — count weight of ScheduleAnyway constraints
    ss_host: np.ndarray  # [G, T] bool — SelectorSpread hostname-key terms
    ss_zone: np.ndarray  # [G, T] bool — SelectorSpread zone-key terms

    # host-port axis (interned (protocol, hostPort) pairs)
    ports: np.ndarray = None  # [G, P] bool — group requests port p
    n_ports: int = 0

    avoid_pen: np.ndarray = None  # [G, N] f32 — NodePreferAvoidPods penalty

    # shared volume-identity axis (VolumeRestrictions + NodeVolumeLimits)
    vol_mask: np.ndarray = None  # [G, N] bool — VolumeBinding+VolumeZone feasibility
    vol_rw: np.ndarray = None  # [G, W] bool — group uses volume w read-write
    vol_ro: np.ndarray = None  # [G, W] bool — group uses volume w read-only
    vol_att: np.ndarray = None  # [G, W] bool — group attaches volume w
    vol_class_mask: np.ndarray = None  # [C, W] bool — volume w is attach class c
    attach_limits: np.ndarray = None  # [N, C] f32 per-node attach limits
    n_vols: int = 0

    # extended resources (Open-Local storage + GPU share)
    ext: ExtendedNodeArrays = field(repr=False, default=None)

    label_index: NodeLabelIndex = field(repr=False, default=None)

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    @property
    def n_groups(self) -> int:
        return len(self.groups)

    @property
    def n_terms(self) -> int:
        return len(self.terms)


@dataclass
class PodBatch:
    """Per-pod arrays for one schedulable batch, aligned with `pods`."""

    pods: List[dict]
    group: np.ndarray  # [P] i32
    req: np.ndarray  # [P, R] f32 (includes the synthetic `pods`=1 resource)
    pin: np.ndarray  # [P] i32 node index or -1
    forced: np.ndarray  # [P] bool — pre-assigned via spec.nodeName
    ext: dict = None  # stacked extended demand arrays (built by add_pods)


def slice_batch(batch: PodBatch, idx) -> PodBatch:
    """An index-selected view of a batch.  Engines consume only the arrays;
    `pods` follows (as references) when the source batch carries it, so
    drain/requeue consumers can still name the pods they report.  Shared by
    the incremental planner's completion probes and the fault subsystem's
    requeue batches."""
    idx = np.asarray(idx, np.int64)
    return PodBatch(
        pods=[batch.pods[int(i)] for i in idx] if batch.pods else [],
        group=batch.group[idx],
        req=batch.req[idx],
        pin=batch.pin[idx],
        forced=batch.forced[idx],
        ext={k: np.asarray(v)[idx] for k, v in batch.ext.items()},
    )


class GrowRefused(RuntimeError):
    """`Tensorizer.add_clone_nodes` cannot extend the node axis in place —
    the extension would change something the interned vocabularies already
    depend on (zone key, a reduction route, an ext plane width).  Raised
    BEFORE any mutation; callers fall back to a full re-tensorize."""


class Tensorizer:
    """Incremental tensorization: one instance per simulation.

    The group/term vocabularies grow as apps are scheduled in sequence
    (mirroring `sim.ScheduleApp` being called per app, `pkg/simulator/
    simulator.go:167-184`); node-side arrays are fixed at construction.
    """

    def __init__(
        self,
        nodes: Sequence[dict],
        extra_resources: Sequence[str] = (),
        storage_classes: Sequence[dict] = (),
        services: Sequence[dict] = (),
        pvcs: Sequence[dict] = (),
        pvs: Sequence[dict] = (),
    ):
        self.nodes = list(nodes)
        self.label_index = NodeLabelIndex(self.nodes)
        self.node_idx = {name: i for i, name in enumerate(self.label_index.names)}
        self.vg_names = Interner()
        self.ext = tensorize_node_storage(self.nodes, self.vg_names)
        self.catalog = StorageClassCatalog(storage_classes)
        self.services = list(services)
        # VolumeBinding/VolumeZone context: claims by (namespace, name), PVs
        # by name (`plugins/volumebinding`, `plugins/volumezone`)
        self.claim_map = {(namespace_of(c), name_of(c)): c for c in pvcs}
        self.pv_map = {name_of(pv): pv for pv in pvs}
        self._pv_mask_cache: Dict[str, np.ndarray] = {}  # PVs are immutable

        # resource vocabulary: base + everything any node allocates
        # (allocatable maps parse once and are reused by _attach_limits)
        self._alloc_maps = [node_allocatable(node) for node in self.nodes]
        self.resources = Interner()
        for r in _BASE_RESOURCES:
            self.resources.intern(r)
        for am in self._alloc_maps:
            for r in am:
                self.resources.intern(r)
        for r in extra_resources:
            self.resources.intern(r)

        n, r = len(self.nodes), len(self.resources)
        self.alloc = np.zeros((n, r), np.float32)
        for i, am in enumerate(self._alloc_maps):
            for rname, val in am.items():
                self.alloc[i, self.resources.intern(rname)] = val

        self.taints: List[List[dict]] = [list(node_taints(nd)) for nd in self.nodes]
        for i, node in enumerate(self.nodes):
            if node_unschedulable(node):
                self.taints[i] = self.taints[i] + [_UNSCHEDULABLE_TAINT]
        # distinct-taint incidence: clusters carry few distinct taints, so
        # per-group toleration checks run per *distinct taint* and fan out to
        # nodes through these masks instead of a Python loop over N nodes
        self._hard_taints: List[dict] = []  # NoSchedule / NoExecute
        self._pref_taints: List[dict] = []  # PreferNoSchedule
        hard_ids: Dict[str, int] = {}
        pref_ids: Dict[str, int] = {}
        hard_rows: List[np.ndarray] = []
        pref_rows: List[np.ndarray] = []
        for i, taints in enumerate(self.taints):
            for taint in taints:
                effect = taint.get("effect")
                if effect in ("NoSchedule", "NoExecute"):
                    ids, rows, bucket = hard_ids, hard_rows, self._hard_taints
                elif effect == "PreferNoSchedule":
                    ids, rows, bucket = pref_ids, pref_rows, self._pref_taints
                else:
                    continue
                key = _canon(taint)
                t = ids.get(key)
                if t is None:
                    t = ids[key] = len(bucket)
                    bucket.append(taint)
                    rows.append(np.zeros(n, bool))
                rows[t][i] = True
        self._hard_taint_incid = (
            np.stack(hard_rows) if hard_rows else np.zeros((0, n), bool)
        )
        self._pref_taint_incid = (
            np.stack(pref_rows) if pref_rows else np.zeros((0, n), bool)
        )

        # NodePreferAvoidPods: static per-node avoid flag (annotation)
        self.prefer_avoid = np.array(
            [node_prefer_avoid_pods(nd) for nd in self.nodes], bool
        )
        # ImageLocality: image name → (nodes having it [N] bool, sizeBytes)
        self.image_index: Dict[str, Tuple[np.ndarray, float]] = {}
        for i, node in enumerate(self.nodes):
            for img in node_images(node):
                size = float(img.get("sizeBytes") or 0)
                for nm in img.get("names") or []:
                    have, _ = self.image_index.setdefault(
                        nm, (np.zeros(n, bool), size)
                    )
                    have[i] = True
        # SelectorSpread zone key: modern label if any node carries it, else
        # the legacy beta key (`selectorspread` zone weighting, k8s 1.20)
        if self.label_index.has_key(C.LABEL_ZONE).any():
            self.zone_key = C.LABEL_ZONE
        elif self.label_index.has_key(C.LABEL_ZONE_BETA).any():
            self.zone_key = C.LABEL_ZONE_BETA
        else:
            self.zone_key = None

        # topology keys/domains and the term universe grow lazily
        self.topo_keys = Interner()
        self.domains = Interner()  # (key, value) pairs
        self._node_dom_rows: List[np.ndarray] = []  # [K][N]
        self._node_dom_small_rows: List[np.ndarray] = []  # [K][N] compact ids
        self._key_kinds: List[int] = []  # [K] reduction route per key
        self.term_interner = Interner()
        self.terms: List[Term] = []
        self._term_topo: List[int] = []
        # inverted term-selector index for s_match refresh: matchLabels-only
        # selectors register under ONE (key, value) pair, so a group's
        # candidate terms come from its own label pairs instead of a G×T scan
        self._term_sel_index: Dict[Tuple[str, str], List[int]] = {}
        self._term_general: List[int] = []  # terms needing full evaluation

        self.groups: List[PodGroup] = []
        self._group_ids: Dict[str, int] = {}
        self._smatch_done: List[int] = []  # per-group s_match term watermark
        self._static_mask = _RowTable(n, bool)
        self._vol_mask = _RowTable(n, bool, fill=True)
        self._node_pref = _RowTable(n, np.float32)
        self._taint_intol = _RowTable(n, np.float32)
        self._static_score = _RowTable(n, np.float32)
        self._avoid_pen = _RowTable(n, np.float32)
        # group×term incidence, grown row-wise (lists of dict[t]=val)
        self._s_match: List[Dict[int, bool]] = []
        self._a_aff: List[Dict[int, bool]] = []
        self._a_anti: List[Dict[int, bool]] = []
        self._w_aff: List[Dict[int, float]] = []
        self._w_anti: List[Dict[int, float]] = []
        self._spread_hard: List[Dict[int, float]] = []
        self._spread_soft: List[Dict[int, float]] = []
        self._ss_host: List[Dict[int, bool]] = []
        self._ss_zone: List[Dict[int, bool]] = []
        # host-port vocabulary ((protocol, port) pairs) and group rows
        self.ports = Interner()
        self._port_rows: List[Dict[int, bool]] = []
        # shared volume-identity vocabulary: VolumeRestrictions conflict keys
        # and NodeVolumeLimits attachable volumes intern into the same axis so
        # per-node presence (`vols_any`) counts each volume once
        self.vols = Interner()
        self._vol_rw_rows: List[Dict[int, bool]] = []
        self._vol_ro_rows: List[Dict[int, bool]] = []
        self._vol_att_rows: List[Dict[int, bool]] = []
        self._vol_class: Dict[int, int] = {}  # vol index → attach class
        # attach-limit class axis: the static in-tree classes plus one class
        # per CSI driver seen in a bound PV (csi.go per-driver limit keys);
        # CSI defaults to no limit — upstream enforces only a published limit
        self.attach_classes: List[tuple] = list(ATTACH_CLASSES)
        self._csi_class: Dict[str, int] = {}  # driver → class index
        # content fingerprint for the freeze() memo: every mutation today
        # grows a vocabulary (already part of the cache key), but any FUTURE
        # mutator that edits array contents in place (node allocatable, a
        # group row) MUST bump this counter or freeze() returns stale tensors
        self._content_version = 0

    # -- topology ----------------------------------------------------------

    def _intern_topo_key(self, key: str) -> int:
        k = self.topo_keys.get(key)
        if k >= 0:
            return k
        k = self.topo_keys.intern(key)
        li = self.label_index
        vid = li._vid.get(key)
        n = len(self.nodes)
        if vid is None:
            row = np.full(n, -1, np.int32)
            small = np.full(n, -1, np.int32)
            kind = 1  # vacuous small key: no domains at all
        else:
            # domain id per label-value id, then one vectorized gather (a
            # 100k-node Python loop per new topology key was measurable);
            # vid -1 (key absent) indexes the -1 sentinel slot
            vmap = li._vmap[key]
            dom_of = np.empty(len(vmap) + 1, np.int32)
            dom_of[-1] = -1
            for v, j in vmap.items():
                dom_of[j] = self.domains.intern((key, v))
            row = dom_of[vid]
            # same-domain reduction routing: the per-key value ids are
            # already compact [0, n_vals)
            if len(vmap) <= DOM_SMALL:
                kind, small = 1, vid.astype(np.int32)
            elif vid.max(initial=-1) >= 0 and np.all(
                np.bincount(vid[vid >= 0]) <= 1
            ):
                kind, small = 2, np.full(n, -1, np.int32)  # unique per node
            else:
                kind, small = 0, np.full(n, -1, np.int32)  # scatter fallback
        self._node_dom_rows.append(row)
        self._node_dom_small_rows.append(small)
        self._key_kinds.append(kind)
        return k

    def _intern_term(self, term: Term) -> int:
        t = self.term_interner.get(term)
        if t >= 0:
            return t
        t = self.term_interner.intern(term)
        self.terms.append(term)
        self._term_topo.append(self._intern_topo_key(term.topology_key))
        # register for the s_match candidate index: a matchLabels-only
        # selector is findable through any one of its pairs; everything else
        # (matchExpressions, empty selector) is evaluated for every group.
        # A nil selector never matches and registers nowhere.
        sel = term.selector
        ml = (sel or {}).get("matchLabels") if isinstance(sel, dict) else None
        me = (sel or {}).get("matchExpressions") if isinstance(sel, dict) else None
        if sel is not None:
            if ml and not me:
                k_, v_ = min(ml.items())
                self._term_sel_index.setdefault((k_, str(v_)), []).append(t)
            else:
                self._term_general.append(t)
        return t

    # -- groups ------------------------------------------------------------

    def _static_mask_for(self, g: PodGroup) -> np.ndarray:
        """Stateless filters vectorized over nodes: taints (NoSchedule/
        NoExecute + unschedulable), nodeSelector, required node affinity."""
        li = self.label_index
        mask = np.ones(li.n, bool)
        # TaintToleration + NodeUnschedulable: evaluate tolerations once per
        # distinct taint, fan out through the node-incidence matrix
        for t, taint in enumerate(self._hard_taints):
            if not any(toleration_tolerates_taint(tol, taint) for tol in g.tolerations):
                mask &= ~self._hard_taint_incid[t]
        # nodeSelector: every kv must be a node label
        for k, v in (g.node_selector or {}).items():
            mask &= li.has_kv(k, "" if v is None else str(v))
        # required node affinity: OR over terms
        if g.affinity_required is not None:
            terms = g.affinity_required.get("nodeSelectorTerms") or []
            any_term = np.zeros(li.n, bool)
            for term in terms:
                any_term |= li.match_term(term)
            mask &= any_term
        return mask

    # Zone/region label keys VolumeZone checks on bound PVs
    # (`plugins/volumezone/volume_zone.go` volumeZoneLabels); values are
    # "__"-joined sets (volumehelpers.LabelZonesToSet).
    _PV_TOPO_KEYS = (
        C.LABEL_ZONE_BETA,
        "failure-domain.beta.kubernetes.io/region",
        C.LABEL_ZONE,
        "topology.kubernetes.io/region",
    )

    def _pv_node_mask(self, pv: dict) -> np.ndarray:
        """Nodes a PV is reachable from: its nodeAffinity.required
        (volume_binding.go Filter → PVAssumeCache) AND its zone/region
        topology labels (volume_zone.go Filter). Cached per PV name."""
        cached = self._pv_mask_cache.get(name_of(pv))
        if cached is not None:
            return cached
        li = self.label_index
        mask = np.ones(li.n, bool)
        node_aff = ((pv.get("spec") or {}).get("nodeAffinity") or {}).get("required")
        if node_aff:
            any_term = np.zeros(li.n, bool)
            for term in node_aff.get("nodeSelectorTerms") or []:
                any_term |= li.match_term(term)
            mask &= any_term
        for key, raw in (labels_of(pv) or {}).items():
            if key not in self._PV_TOPO_KEYS:
                continue
            allowed = set(str(raw).split("__"))
            ok = np.zeros(li.n, bool)
            for zone in allowed:
                ok |= li.has_kv(key, zone)
            mask &= ok
        self._pv_mask_cache[name_of(pv)] = mask
        return mask

    def _volume_mask_for(self, g: PodGroup) -> Optional[np.ndarray]:
        """VolumeBinding + VolumeZone feasibility over nodes.
        Returns None (= unconstrained, the row table's all-True fill) for
        groups referencing no claims — the overwhelmingly common case.

        Mirrors `plugins/volumebinding/volume_binding.go` PreFilter/Filter and
        `plugins/volumezone/volume_zone.go`:
        - a referenced PVC that does not exist → unschedulable everywhere;
        - a bound PVC restricts nodes to the PV's nodeAffinity and zone/region
          topology labels;
        - an unbound PVC with a StorageClass needs the class to exist
          (dynamic provisioning is then assumed feasible on any node, both
          binding modes);
        - an unbound PVC without a StorageClass is statically provisioned:
          some unclaimed PV of sufficient capacity must exist, and the pod is
          restricted to nodes reachable by at least one such PV (the
          FindPodVolumes static-binding pass, approximated without
          access-mode matching);
        - claims of the Open-Local / yoda storage classes are excluded — they
          are scheduled by the storage kernels (`kernels/storage.py`) from the
          pod's local-storage annotation instead.
        """
        if not g.pvc_refs:
            return None
        li = self.label_index
        mask = np.ones(li.n, bool)
        open_local = set(C.SC_LVM) | set(C.SC_DEVICE_SSD) | set(C.SC_DEVICE_HDD)
        for claim in g.pvc_refs:
            pvc = self.claim_map.get((g.namespace, claim))
            if pvc is None:
                return np.zeros(li.n, bool)
            spec = pvc.get("spec") or {}
            sc_name = spec.get("storageClassName") or ""
            if sc_name in open_local:
                continue
            pv_name = spec.get("volumeName") or ""
            if pv_name:
                pv = self.pv_map.get(pv_name)
                if pv is None:
                    continue  # bound to a PV we weren't given: no constraint
                mask &= self._pv_node_mask(pv)
                continue
            # unbound: upstream findMatchingVolume takes a PV pre-bound to
            # this very claim first — claimRef naming the claim wins
            # regardless of class/capacity (IsVolumeBoundToClaim requires
            # exact namespace+name equality; an empty claimRef namespace
            # never matches)
            prebound = np.zeros(li.n, bool)
            has_prebound = False
            for pv in self.pv_map.values():
                ref = (pv.get("spec") or {}).get("claimRef") or {}
                if ref.get("name") == claim and ref.get("namespace") == g.namespace:
                    has_prebound = True
                    prebound |= self._pv_node_mask(pv)
            if has_prebound:
                mask &= prebound
            elif sc_name:
                if sc_name not in self.catalog:
                    # unbound, named class doesn't exist →
                    # UnschedulableAndUnresolvable
                    return np.zeros(li.n, bool)
            else:
                # static provisioning: any unclaimed classless PV with
                # enough capacity
                want = parse_quantity(
                    ((spec.get("resources") or {}).get("requests") or {}).get(
                        "storage", 0
                    )
                )
                candidates = np.zeros(li.n, bool)
                for pv in self.pv_map.values():
                    pv_spec = pv.get("spec") or {}
                    # class equality: a classless claim binds classless PVs only
                    if pv_spec.get("claimRef") or pv_spec.get("storageClassName"):
                        continue
                    cap = parse_quantity(
                        (pv_spec.get("capacity") or {}).get("storage", 0)
                    )
                    if cap >= want:
                        candidates |= self._pv_node_mask(pv)
                mask &= candidates
        return mask

    def _node_pref_for(self, g: PodGroup) -> Optional[np.ndarray]:
        """NodeAffinity preferred raw score (sum of matching term weights),
        mirroring `plugins/nodeaffinity` Score. None = all-zero."""
        if not g.affinity_preferred:
            return None
        score = np.zeros(self.label_index.n, np.float32)
        for item in g.affinity_preferred:
            w = float(item.get("weight", 0))
            pref = item.get("preference") or {}
            score += w * self.label_index.match_term(pref).astype(np.float32)
        return score

    def _taint_intol_for(self, g: PodGroup) -> Optional[np.ndarray]:
        """Count of PreferNoSchedule taints the group does not tolerate
        (`plugins/tainttoleration` Score). None = all-zero (no
        PreferNoSchedule taints in the cluster, or all tolerated)."""
        out = None
        for t, taint in enumerate(self._pref_taints):
            if not any(toleration_tolerates_taint(tol, taint) for tol in g.tolerations):
                if out is None:
                    out = np.zeros(self.label_index.n, np.float32)
                out += self._pref_taint_incid[t]
        return out

    # ImageLocality thresholds (`plugins/imagelocality/image_locality.go`)
    _IMG_MIN = 23 * 1024 * 1024
    _IMG_MAX = 1000 * 1024 * 1024

    def _static_score_for(self, g: PodGroup) -> Optional[np.ndarray]:
        """ImageLocality score, which depends only on (group, node specs)
        (`plugins/imagelocality/image_locality.go`; no NormalizeScore).
        None = all-zero (no group image resides on any node — sub-threshold
        sums score 0 anyway)."""
        n = self.label_index.n
        imgs = [im for im in set(g.images) if im in self.image_index]
        if not imgs or not n:
            return None
        # sum of node-resident image sizes scaled by spread
        sum_scores = np.zeros(n, np.float64)
        for img in imgs:
            have, size = self.image_index[img]
            spread = have.sum() / n
            sum_scores += np.where(have, size * spread, 0.0)
        img_score = np.clip(
            (sum_scores - self._IMG_MIN) * 100.0 / (self._IMG_MAX - self._IMG_MIN),
            0.0,
            100.0,
        )
        img_score[sum_scores < self._IMG_MIN] = 0.0
        return img_score.astype(np.float32)

    def _avoid_penalty_for(self, g: PodGroup) -> Optional[np.ndarray]:
        """NodePreferAvoidPods for RC/RS-owned pods: upstream adds
        weight·score = 10000·100 on non-avoid nodes and 0 on avoid nodes.
        Adding ~1e6 uniformly would erase sub-0.0625 deltas from the other
        plugins in float32, so keep the argmax-equivalent penalty form:
        0 baseline, -1e6 only on avoid-annotated nodes. None = all-zero."""
        if g.owner_kind in (C.KIND_RC, C.KIND_RS) and self.prefer_avoid.any():
            return -10000.0 * 100.0 * self.prefer_avoid.astype(np.float32)
        return None

    def _spread_selectors_for(self, g: PodGroup) -> List[dict]:
        """LabelSelectors the SelectorSpread score counts against: services
        selecting the group's pods, plus the controller's selector for
        RC/RS/STS-owned pods (`plugins/selectorspread/selector_spread.go`).
        Expanded pods inherit their owner's template labels verbatim
        (`workloads/expand.py`), so the full label set stands in for the
        owner's selector."""
        sels: List[dict] = []
        if g.owner_kind in (C.KIND_RC, C.KIND_RS, C.KIND_STS):
            if g.labels:
                sels.append({"matchLabels": dict(g.labels)})
        for svc in self.services:
            if namespace_of(svc) != g.namespace:
                continue
            raw = ((svc.get("spec") or {}).get("selector")) or {}
            if not raw:
                continue
            if all(g.labels.get(k) == str(v) for k, v in raw.items()):
                sels.append({"matchLabels": {k: str(v) for k, v in raw.items()}})
        return sels

    def _intern_group(self, g: PodGroup) -> int:
        sig = g.signature()
        gid = self._group_ids.get(sig)
        if gid is not None:
            return gid
        gid = len(self.groups)
        self._group_ids[sig] = gid
        self.groups.append(g)
        self._static_mask.append(self._static_mask_for(g))
        self._vol_mask.append(self._volume_mask_for(g))
        self._node_pref.append(self._node_pref_for(g))
        self._taint_intol.append(self._taint_intol_for(g))
        self._static_score.append(self._static_score_for(g))
        self._avoid_pen.append(self._avoid_penalty_for(g))

        # NodePorts: intern the group's (protocol, port) pairs
        prow: Dict[int, bool] = {}
        for pair in g.host_ports:
            prow[self.ports.intern(pair)] = True
        self._port_rows.append(prow)

        # VolumeRestrictions: intern the group's exclusive volume keys
        vrw: Dict[int, bool] = {}
        vro: Dict[int, bool] = {}
        for key in g.vol_rw:
            vrw[self.vols.intern(key)] = True
        for key in g.vol_ro:
            vro[self.vols.intern(key)] = True
        self._vol_rw_rows.append(vrw)
        self._vol_ro_rows.append(vro)

        # NodeVolumeLimits: attachable volumes, inline + resolved through
        # bound PVCs (`plugins/nodevolumelimits/non_csi.go`
        # filterAttachableVolumes); presence-per-node makes the count unique
        # per node like upstream, not per pod
        vatt: Dict[int, bool] = {}
        att_pairs = list(g.vol_att)
        for claim in g.pvc_refs:
            pvc = self.claim_map.get((g.namespace, claim))
            if pvc is None:
                continue
            pv = self.pv_map.get((pvc.get("spec") or {}).get("volumeName") or "")
            if pv is None:
                continue
            pair = pv_attachable_source(pv)
            if pair is not None:
                att_pairs.append(pair)
                continue
            csi = pv_csi_source(pv)
            if csi is not None:
                key, driver = csi
                cls = self._csi_class.get(driver)
                if cls is None:
                    cls = len(self.attach_classes)
                    self.attach_classes.append(
                        (csi_attach_limit_key(driver), np.inf)
                    )
                    self._csi_class[driver] = cls
                att_pairs.append((key, cls))
        for key, cls in set(att_pairs):
            w = self.vols.intern(key)
            vatt[w] = True
            self._vol_class[w] = cls
        self._vol_att_rows.append(vatt)

        # PodTopologySpread: one term per constraint; stricter maxSkew wins
        # on (key, selector) collisions
        sp_hard: Dict[int, float] = {}
        sp_soft: Dict[int, float] = {}
        for raw in g.topology_spread:
            c = json.loads(raw)
            term = Term(
                topology_key=c.get("topologyKey", ""),
                namespaces=(g.namespace,),
                selector_json=_canon(c.get("labelSelector")),
            )
            t = self._intern_term(term)
            if c.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule":
                skew = float(c.get("maxSkew", 1))
                sp_hard[t] = min(sp_hard.get(t, np.inf), skew)
            else:
                sp_soft[t] = sp_soft.get(t, 0.0) + 1.0
        self._spread_hard.append(sp_hard)
        self._spread_soft.append(sp_soft)

        # SelectorSpread: hostname + zone counting terms per spread selector
        ssh: Dict[int, bool] = {}
        ssz: Dict[int, bool] = {}
        for sel in self._spread_selectors_for(g):
            sel_json = _canon(sel)
            ssh[
                self._intern_term(
                    Term(C.LABEL_HOSTNAME, (g.namespace,), sel_json)
                )
            ] = True
            if self.zone_key is not None:
                ssz[
                    self._intern_term(
                        Term(self.zone_key, (g.namespace,), sel_json)
                    )
                ] = True
        self._ss_host.append(ssh)
        self._ss_zone.append(ssz)

        s_match: Dict[int, bool] = {}
        a_aff: Dict[int, bool] = {}
        a_anti: Dict[int, bool] = {}
        w_aff: Dict[int, float] = {}
        w_anti: Dict[int, float] = {}
        pa, paa = g.pod_affinity, g.pod_anti_affinity
        for term, _ in _terms_of(
            pa.get("requiredDuringSchedulingIgnoredDuringExecution"), g.namespace
        ):
            a_aff[self._intern_term(term)] = True
        for term, _ in _terms_of(
            paa.get("requiredDuringSchedulingIgnoredDuringExecution"), g.namespace
        ):
            a_anti[self._intern_term(term)] = True
        for term, w in _terms_of(
            pa.get("preferredDuringSchedulingIgnoredDuringExecution"), g.namespace
        ):
            t = self._intern_term(term)
            w_aff[t] = w_aff.get(t, 0.0) + w
        for term, w in _terms_of(
            paa.get("preferredDuringSchedulingIgnoredDuringExecution"), g.namespace
        ):
            t = self._intern_term(term)
            w_anti[t] = w_anti.get(t, 0.0) + w
        self._s_match.append(s_match)
        self._a_aff.append(a_aff)
        self._a_anti.append(a_anti)
        self._w_aff.append(w_aff)
        self._w_anti.append(w_anti)
        return gid

    def _attach_limits(self) -> np.ndarray:
        """[N, C] per-node attach limits: the published `attachable-volumes-*`
        allocatable, or the in-tree default when the key is absent (a
        published 0 stays 0 — upstream only falls back when unset).
        Columns are cached per class count: classes only append (new CSI
        drivers), and re-walking 100k parsed allocatable maps per freeze was
        measurable."""
        c_n = len(self.attach_classes)
        cached = getattr(self, "_attach_cache", None)
        if cached is not None and cached.shape[1] == c_n:
            return cached
        start = 0 if cached is None else cached.shape[1]
        out = np.zeros((len(self.nodes), c_n), np.float32)
        if cached is not None:
            out[:, :start] = cached
        for c in range(start, c_n):
            res, default = self.attach_classes[c]
            col = out[:, c]
            for i, am in enumerate(self._alloc_maps):
                col[i] = am.get(res, default)
        self._attach_cache = out
        return out

    def _refresh_s_match(self) -> None:
        """(Re)evaluate group-labels × term-selector incidence.

        Done once per batch build so terms interned by later apps see earlier
        groups too. Each group carries a watermark (terms already evaluated —
        the interners only append), only True entries are stored (readers
        .get() with a falsy default, and freeze()'s dense pass walks stored
        items), and candidates come from the inverted selector index rather
        than a full G×T scan (each candidate still gets its own
        match_label_selector call; only the selector parse is shared).
        """
        t_n = len(self.terms)
        while len(self._smatch_done) < len(self.groups):
            self._smatch_done.append(0)
        general = self._term_general
        idx = self._term_sel_index
        for gid, g in enumerate(self.groups):
            start = self._smatch_done[gid]
            if start >= t_n:
                continue
            labels, ns = g.labels, g.namespace
            # candidate terms: the general pool plus every indexed term
            # reachable through one of the group's own label pairs (the
            # index key is a necessary condition for a matchLabels match)
            cands = [t for t in general if t >= start]
            for k, v in labels.items():
                lst = idx.get((k, str(v)))
                if lst:
                    cands.extend(t for t in lst if t >= start)
            if not cands:
                self._smatch_done[gid] = t_n
                continue
            row = self._s_match[gid]
            for t in set(cands):
                term = self.terms[t]
                sel = term.selector
                if (
                    ns in term.namespaces
                    and sel is not None
                    and match_label_selector(sel, labels)
                ):
                    row[t] = True
            self._smatch_done[gid] = t_n

    # -- append-only node growth (warm-engine serving, ISSUE 20) -----------

    def add_clone_nodes(self, new_nodes: Sequence[dict]) -> None:
        """Append template-clone nodes to the node axis in place.

        The node-side arrays are "fixed at construction" above — this is the
        ONE sanctioned mutation, and it must leave the tensorizer
        indistinguishable from a from-scratch `Tensorizer(all_nodes)` fed the
        same pod sequence (modulo global domain-id numbering, which every
        consumer treats as opaque): the serve capacity fast path and the
        replay autoscaler grow a warm engine's node axis through it
        (`Engine.grow_nodes`) instead of re-tensorizing the cluster.

        Raises `GrowRefused` — without mutating any state — when the
        extension would change something the interned vocabularies already
        depend on: the SelectorSpread zone key, a topology key's same-domain
        reduction route (`key_kind`), or an extended-storage plane width.
        Callers fall back to a full re-tensorize; the refusal is a
        correctness guard, never an error.
        """
        new_nodes = list(new_nodes)
        if not new_nodes:
            return
        nodes = self.nodes + new_nodes
        n = len(nodes)
        li = NodeLabelIndex(nodes)

        # -- pass 1: validation only (no mutation before any refusal) ------
        if li.has_key(C.LABEL_ZONE).any():
            zone_key = C.LABEL_ZONE
        elif li.has_key(C.LABEL_ZONE_BETA).any():
            zone_key = C.LABEL_ZONE_BETA
        else:
            zone_key = None
        if zone_key != self.zone_key and any(
            self._ss_host[g] or self._ss_zone[g] for g in range(len(self.groups))
        ):
            # a from-scratch tensorize would have interned the spread
            # selectors' zone terms under the recomputed key
            raise GrowRefused(
                "SelectorSpread zone key would flip with the new nodes"
            )
        kinds = []
        for key in self.topo_keys.items():
            key = str(key)
            vid = li._vid.get(key)
            if vid is None:
                kinds.append(1)
                continue
            vmap = li._vmap[key]
            if len(vmap) <= DOM_SMALL:
                kinds.append(1)
            elif vid.max(initial=-1) >= 0 and np.all(
                np.bincount(vid[vid >= 0]) <= 1
            ):
                kinds.append(2)
            else:
                kinds.append(0)
        for k, kind in enumerate(kinds):
            if kind != self._key_kinds[k]:
                raise GrowRefused(
                    f"topology key {self.topo_keys.items()[k]!r} would "
                    f"change reduction route ({self._key_kinds[k]} -> {kind})"
                )
        from .extended import NodeStorage

        for node in new_nodes:
            s = NodeStorage.from_node(node)
            if s:
                if len(s.vgs) > self.ext.vg_cap.shape[1]:
                    raise GrowRefused("new node widens the VG plane")
                if len(s.devices) > self.ext.sdev_cap.shape[1]:
                    raise GrowRefused("new node widens the device plane")
            cap = ((node.get("status") or {}).get("capacity")) or {}
            if int(parse_quantity(cap.get(C.RES_GPU_COUNT))) > (
                self.ext.gpu_dev_total.shape[1]
            ):
                raise GrowRefused("new node widens the GPU device plane")

        # -- pass 2: extend ------------------------------------------------
        self.nodes = nodes
        self.label_index = li
        self.zone_key = zone_key
        for i, node in enumerate(new_nodes):
            self.node_idx[name_of(node)] = len(self.node_idx)
            self._alloc_maps.append(node_allocatable(node))
            for rname in self._alloc_maps[-1]:
                self.resources.intern(rname)
        r = len(self.resources)
        alloc = np.zeros((n, r), np.float32)
        alloc[: self.alloc.shape[0], : self.alloc.shape[1]] = self.alloc
        for i in range(len(self.nodes) - len(new_nodes), n):
            for rname, val in self._alloc_maps[i].items():
                alloc[i, self.resources.intern(rname)] = val
        self.alloc = alloc

        # extended storage/GPU planes: re-run over all nodes (interner is
        # idempotent, widths pinned equal by pass 1)
        self.ext = tensorize_node_storage(self.nodes, self.vg_names)

        # distinct-taint machinery: rebuild from scratch over all nodes —
        # first-seen node order keeps the old distinct-taint prefix stable
        for node in new_nodes:
            taints = list(node_taints(node))
            if node_unschedulable(node):
                taints = taints + [_UNSCHEDULABLE_TAINT]
            self.taints.append(taints)
        self._hard_taints = []
        self._pref_taints = []
        hard_ids: Dict[str, int] = {}
        pref_ids: Dict[str, int] = {}
        hard_rows: List[np.ndarray] = []
        pref_rows: List[np.ndarray] = []
        for i, taints in enumerate(self.taints):
            for taint in taints:
                effect = taint.get("effect")
                if effect in ("NoSchedule", "NoExecute"):
                    ids, rows, bucket = hard_ids, hard_rows, self._hard_taints
                elif effect == "PreferNoSchedule":
                    ids, rows, bucket = pref_ids, pref_rows, self._pref_taints
                else:
                    continue
                key = _canon(taint)
                t = ids.get(key)
                if t is None:
                    t = ids[key] = len(bucket)
                    bucket.append(taint)
                    rows.append(np.zeros(n, bool))
                rows[t][i] = True
        self._hard_taint_incid = (
            np.stack(hard_rows) if hard_rows else np.zeros((0, n), bool)
        )
        self._pref_taint_incid = (
            np.stack(pref_rows) if pref_rows else np.zeros((0, n), bool)
        )

        self.prefer_avoid = np.array(
            [node_prefer_avoid_pods(nd) for nd in self.nodes], bool
        )
        self.image_index = {}
        for i, node in enumerate(self.nodes):
            for img in node_images(node):
                size = float(img.get("sizeBytes") or 0)
                for nm in img.get("names") or []:
                    have, _ = self.image_index.setdefault(
                        nm, (np.zeros(n, bool), size)
                    )
                    have[i] = True

        # topology rows: recompute over all nodes. `vmap.items()` follows
        # first-seen node order, so old domain values re-intern to their
        # existing ids and only genuinely new values append (the numbering
        # still differs from from-scratch across MULTIPLE keys — by-key
        # instead of by-pod-sequence — which is fine: domain ids are opaque
        # scatter indices, and the grow carry is dense [T, N], never [Rt, D])
        self._node_dom_rows = []
        self._node_dom_small_rows = []
        for k, key in enumerate(self.topo_keys.items()):
            key = str(key)
            vid = li._vid.get(key)
            if vid is None:
                row = np.full(n, -1, np.int32)
                small = np.full(n, -1, np.int32)
            else:
                vmap = li._vmap[key]
                dom_of = np.empty(len(vmap) + 1, np.int32)
                dom_of[-1] = -1
                for v, j in vmap.items():
                    dom_of[j] = self.domains.intern((key, v))
                row = dom_of[vid]
                if kinds[k] == 1 and len(vmap):
                    small = vid.astype(np.int32)
                else:
                    small = np.full(n, -1, np.int32)
            self._node_dom_rows.append(row)
            self._node_dom_small_rows.append(small)
        self._key_kinds = kinds

        # group planes: recompute every row through the stored evaluators —
        # deterministic functions of (group, rebuilt node-side state), so the
        # old-node prefix is unchanged and the result matches from-scratch
        # (ImageLocality's spread fraction legitimately shifts with N for ALL
        # nodes; statics are re-derived from the next freeze() anyway)
        self._pv_mask_cache = {}
        self._static_mask = _RowTable(n, bool)
        self._vol_mask = _RowTable(n, bool, fill=True)
        self._node_pref = _RowTable(n, np.float32)
        self._taint_intol = _RowTable(n, np.float32)
        self._static_score = _RowTable(n, np.float32)
        self._avoid_pen = _RowTable(n, np.float32)
        for g in self.groups:
            self._static_mask.append(self._static_mask_for(g))
            self._vol_mask.append(self._volume_mask_for(g))
            self._node_pref.append(self._node_pref_for(g))
            self._taint_intol.append(self._taint_intol_for(g))
            self._static_score.append(self._static_score_for(g))
            self._avoid_pen.append(self._avoid_penalty_for(g))

        self._attach_cache = None
        self._content_version += 1

    # -- batches -----------------------------------------------------------

    @staticmethod
    def _pod_identity_key(pod: dict):
        """Identity-based key over every nested structure `_group_of_pod`,
        `pod_requests` and `pod_extended_demand` read, plus the scalar value
        fields. Shared by run detection (adjacent compare, together with
        labels/annotations dict equality) and `_pod_fingerprint` — a field
        added to one but not the other would silently mis-collapse runs.

        Workload expansion clones replicas from one normalized prototype
        (`workloads/expand.py` _clone_pod), so replicas *share* their nested
        spec objects — id() equality over those lets a batch of identical
        pods tensorize once. ids are stable for the duration of the call
        (the pods list keeps everything alive).
        """
        spec = pod.get("spec") or {}
        meta = pod.get("metadata") or {}
        return (
            id(spec.get("containers")),
            id(spec.get("initContainers")),
            id(spec.get("affinity")),
            id(spec.get("tolerations")),
            id(spec.get("nodeSelector")),
            id(spec.get("topologySpreadConstraints")),
            id(spec.get("volumes")),
            id(spec.get("overhead")),
            id(meta.get("ownerReferences")),
            meta.get("namespace") or "",
            spec.get("nodeName") or "",
        )

    @classmethod
    def _pod_fingerprint(cls, pod: dict):
        """The identity key plus order-insensitive label/annotation values —
        the cache key deduping non-adjacent identical pods."""
        meta = pod.get("metadata") or {}
        return cls._pod_identity_key(pod) + (
            tuple(sorted((meta.get("labels") or {}).items())),
            tuple(sorted((meta.get("annotations") or {}).items())),
        )

    def add_pods(self, pods: Sequence[dict]) -> PodBatch:
        """Intern a batch of pods, growing group/term vocabularies.

        Replica runs collapse: workload expansion clones replicas from one
        normalized prototype (`workloads/expand.py` _clone_pod), so
        consecutive replicas share their nested spec objects. Pass 1 detects
        run boundaries with identity/equality compares only; everything
        per-spec (grouping, requests, extended demand) then runs once per RUN
        and broadcasts over the run's slice — at million-pod batches the old
        per-pod path was the single largest host cost.
        """
        p = len(pods)
        group = np.zeros(p, np.int32)
        pin = np.full(p, -1, np.int32)
        forced = np.zeros(p, bool)

        # -- pass 1: adjacent-run detection (no hashing of value fields) ----
        starts: List[int] = []
        prev_key: object = None
        prev_labels = prev_annos = None
        identity_key = self._pod_identity_key
        for i, pod in enumerate(pods):
            meta = pod.get("metadata") or {}
            key = identity_key(pod)
            labels = meta.get("labels") or {}
            annos = meta.get("annotations") or {}
            if (
                not starts
                or key != prev_key
                or labels != prev_labels
                or annos != prev_annos
            ):
                starts.append(i)
                prev_key, prev_labels, prev_annos = key, labels, annos
        stops = starts[1:] + [p]

        # -- pass 2: one grouping/request/demand evaluation per run ---------
        # (the fingerprint cache still dedupes non-adjacent repeats)
        run_info: List[tuple] = []  # (start, stop, req_dict, demand)
        cache = {}
        for start, stop in zip(starts, stops):
            pod = pods[start]
            fp = self._pod_fingerprint(pod)
            hit = cache.get(fp)
            if hit is None:
                g, pin_name = _group_of_pod(pod)
                gid = self._intern_group(g)
                pin_v, forced_v = -1, False
                node_name = pod_node_name(pod)
                if node_name:
                    pin_v = self.node_idx.get(node_name, -1)
                    forced_v = True
                elif pin_name is not None:
                    # -2 = pinned to a node that does not exist →
                    # unschedulable everywhere (the NodeAffinity filter
                    # would match no node)
                    pin_v = self.node_idx.get(pin_name, -2)
                hit = (
                    gid,
                    pin_v,
                    forced_v,
                    pod_requests(pod),
                    pod_extended_demand(pod, self.catalog, self.vg_names),
                )
                cache[fp] = hit
            gid, pin_v, forced_v, r, demand = hit
            group[start:stop] = gid
            pin[start:stop] = pin_v
            forced[start:stop] = forced_v
            run_info.append((start, stop, r, demand))
        self._refresh_s_match()

        # -- request matrix: grow the vocabulary first, then one row per run
        for _, _, r, _ in run_info:
            for rname in r:
                if self.resources.get(rname) < 0:
                    # a resource no node allocates can never fit; grow the
                    # vocabulary so fit fails cleanly
                    self.resources.intern(rname)
                    self.alloc = np.pad(self.alloc, ((0, 0), (0, 1)))
        n_res = len(self.resources)
        req = np.zeros((p, n_res), np.float32)
        if p:
            req[:, RES_PODS] = 1.0
        row = np.zeros(n_res, np.float32)
        for start, stop, r, _ in run_info:
            row[:] = 0.0
            row[RES_PODS] = 1.0
            for rname, val in r.items():
                row[self.resources.get(rname)] = val
            req[start:stop] = row

        # -- extended demand arrays, filled per run ------------------------
        l_max = max([len(d.lvm_sizes) for _, _, _, d in run_info] + [1])
        k_max = max([len(d.dev_sizes) for _, _, _, d in run_info] + [1])
        gd = max(self.ext.gpu_dev_total.shape[1], 1)
        ext = {
            "lvm_size": np.zeros((p, l_max), np.float32),
            "lvm_vg": np.full((p, l_max), -1, np.int32),
            "dev_size": np.zeros((p, k_max), np.float32),
            "dev_media": np.zeros((p, k_max), np.int32),
            "gpu_mem": np.zeros(p, np.float32),
            "gpu_count": np.zeros(p, np.int32),
            "gpu_preset": np.zeros((p, gd), np.float32),
        }
        for start, stop, _, d in run_info:
            if d.lvm_sizes:
                ext["lvm_size"][start:stop, : len(d.lvm_sizes)] = d.lvm_sizes
                ext["lvm_vg"][start:stop, : len(d.lvm_vg_ids)] = d.lvm_vg_ids
            if d.dev_sizes:
                ext["dev_size"][start:stop, : len(d.dev_sizes)] = d.dev_sizes
                ext["dev_media"][start:stop, : len(d.dev_medias)] = d.dev_medias
            if d.gpu_mem:
                ext["gpu_mem"][start:stop] = d.gpu_mem
            if d.gpu_count:
                ext["gpu_count"][start:stop] = d.gpu_count
            for dev_id in d.gpu_preset:
                # device ids beyond the cluster's device table are silently
                # ignored, like the reference's guarded map lookup
                # (`gpunodeinfo.go:108-110`)
                if 0 <= dev_id < gd:
                    ext["gpu_preset"][start:stop, dev_id] += 1.0
        return PodBatch(
            pods=list(pods),
            group=group,
            req=req,
            pin=pin,
            forced=forced,
            ext=ext,
        )

    def freeze(self) -> ClusterTensors:
        """Materialize the dense arrays for the current vocabularies.

        Memoized on the vocabulary sizes: Engine.place freezes per batch,
        and re-stacking the [G, N] planes for an unchanged vocabulary costs
        seconds at 100k nodes (the frozen object also carries the memoized
        statics/compaction caches, so reuse preserves those too). Any growth
        in groups/terms/ports/vols/resources/attach classes — the only
        mutations add_pods can make — changes the key and rebuilds.
        """
        n, g_n, t_n = len(self.nodes), len(self.groups), len(self.terms)
        key = (
            n,
            g_n,
            t_n,
            len(self.ports),
            len(self.vols),
            len(self.resources),
            len(self.attach_classes),
            len(self.domains),
            self._content_version,
        )
        cached = getattr(self, "_freeze_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]

        def dense(rows: List[Dict[int, float]], dtype) -> np.ndarray:
            out = np.zeros((g_n, t_n), dtype)
            for gi, row in enumerate(rows):
                for t, v in row.items():
                    out[gi, t] = v
            return out

        node_dom = (
            np.stack(self._node_dom_rows) if self._node_dom_rows else np.zeros((0, n), np.int32)
        )
        node_dom_small = (
            np.stack(self._node_dom_small_rows)
            if self._node_dom_small_rows
            else np.zeros((0, n), np.int32)
        )
        key_kind = np.asarray(self._key_kinds, np.int32)
        p_n = len(self.ports)
        ports = np.zeros((g_n, p_n), bool)
        for gi, row in enumerate(self._port_rows):
            for p, v in row.items():
                ports[gi, p] = v
        w_n = len(self.vols)
        vol_rw = np.zeros((g_n, w_n), bool)
        vol_ro = np.zeros((g_n, w_n), bool)
        vol_att = np.zeros((g_n, w_n), bool)
        for gi, row in enumerate(self._vol_rw_rows):
            for w, v in row.items():
                vol_rw[gi, w] = v
        for gi, row in enumerate(self._vol_ro_rows):
            for w, v in row.items():
                vol_ro[gi, w] = v
        for gi, row in enumerate(self._vol_att_rows):
            for w, v in row.items():
                vol_att[gi, w] = v
        vol_class_mask = np.zeros((len(self.attach_classes), w_n), bool)
        for w, cls in self._vol_class.items():
            vol_class_mask[cls, w] = True
        tensors = ClusterTensors(
            node_names=list(self.label_index.names),
            resource_names=[str(r) for r in self.resources.items()],
            alloc=self.alloc.copy(),
            node_dom=node_dom,
            n_domains=max(len(self.domains), 1),
            topo_keys=[str(k) for k in self.topo_keys.items()],
            key_kind=key_kind,
            node_dom_small=node_dom_small,
            groups=list(self.groups),
            static_mask=self._static_mask.view(),
            node_pref_score=self._node_pref.view(),
            taint_intolerable=self._taint_intol.view(),
            static_score=self._static_score.view(),
            avoid_pen=self._avoid_pen.view(),
            terms=list(self.terms),
            term_topo_key=np.asarray(self._term_topo, np.int32),
            s_match=dense(self._s_match, bool),
            a_aff_req=dense(self._a_aff, bool),
            a_anti_req=dense(self._a_anti, bool),
            w_aff_pref=dense(self._w_aff, np.float32),
            w_anti_pref=dense(self._w_anti, np.float32),
            spread_hard=dense(self._spread_hard, np.float32),
            spread_soft=dense(self._spread_soft, np.float32),
            ss_host=dense(self._ss_host, bool),
            ss_zone=dense(self._ss_zone, bool),
            ports=ports,
            n_ports=p_n,
            vol_mask=self._vol_mask.view(),
            vol_rw=vol_rw,
            vol_ro=vol_ro,
            vol_att=vol_att,
            vol_class_mask=vol_class_mask,
            attach_limits=self._attach_limits(),
            n_vols=w_n,
            ext=self.ext,
            label_index=self.label_index,
        )
        self._freeze_cache = (key, tensors)
        return tensors
