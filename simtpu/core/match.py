"""Host-side label/selector/taint predicate evaluation.

These are the scalar (one pod × one node) forms of the scheduling predicates,
used where the reference also runs them host-side: DaemonSet expansion
(`pkg/utils/utils.go:388-395` via vendored `daemon.Predicates`,
`daemon_controller.go:1251-1257`) and planner diagnostics
(`pkg/apply/apply.go:215-231`). The batched forms over all nodes live in
simtpu.kernels and are built from the same semantics; test_kernels.py checks
scalar-vs-batched agreement.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .objects import (
    labels_of,
    name_of,
    node_taints,
    pod_affinity,
    pod_node_selector,
    pod_tolerations,
)

# NodeSelectorRequirement operators (k8s core/v1 types)
OP_IN = "In"
OP_NOT_IN = "NotIn"
OP_EXISTS = "Exists"
OP_DOES_NOT_EXIST = "DoesNotExist"
OP_GT = "Gt"
OP_LT = "Lt"


def match_requirement(values: Dict[str, str], req: dict) -> bool:
    """Evaluate one NodeSelectorRequirement against a key→value map.

    Semantics follow apimachinery labels.Requirement.Matches
    (`vendor/k8s.io/apimachinery/pkg/labels/selector.go:203-238`): NotIn
    matches when the key is absent; Gt/Lt require the key present.
    """
    key = req.get("key", "")
    op = req.get("operator", "")
    vals = req.get("values") or []
    present = key in values
    if op == OP_IN:
        return present and values[key] in vals
    if op == OP_NOT_IN:
        return not present or values[key] not in vals
    if op == OP_EXISTS:
        return present
    if op == OP_DOES_NOT_EXIST:
        return not present
    if op == OP_GT or op == OP_LT:
        if not present or not vals:
            return False
        try:
            lhs = int(values[key])
            rhs = int(vals[0])
        except ValueError:
            return False
        return lhs > rhs if op == OP_GT else lhs < rhs
    return False


def match_node_selector_term(term: dict, node: dict) -> bool:
    """One NodeSelectorTerm: AND of matchExpressions (over labels) and
    matchFields (over metadata.name)."""
    exprs = term.get("matchExpressions") or []
    fields = term.get("matchFields") or []
    if not exprs and not fields:
        return False  # empty term matches nothing (k8s semantics)
    node_labels = labels_of(node)
    for req in exprs:
        if not match_requirement(node_labels, req):
            return False
    field_map = {"metadata.name": name_of(node)}
    for req in fields:
        if not match_requirement(field_map, req):
            return False
    return True


def pod_matches_node_selector_and_affinity(pod: dict, node: dict) -> bool:
    """NodeSelector AND required node-affinity terms (OR across terms).

    Mirrors `pluginhelper.PodMatchesNodeSelectorAndAffinityTerms` used by both
    the NodeAffinity filter plugin and daemon.Predicates.
    """
    selector = pod_node_selector(pod)
    if selector:
        node_labels = labels_of(node)
        for k, v in selector.items():
            if node_labels.get(k) != v:
                return False
    node_affinity = (pod_affinity(pod)).get("nodeAffinity") or {}
    required = node_affinity.get("requiredDuringSchedulingIgnoredDuringExecution")
    if required is not None:
        terms = required.get("nodeSelectorTerms") or []
        if not any(match_node_selector_term(t, node) for t in terms):
            return False
    return True


def toleration_tolerates_taint(toleration: dict, taint: dict) -> bool:
    """Mirror of v1helper.TolerationsTolerateTaint single-pair check."""
    t_effect = toleration.get("effect", "")
    if t_effect and t_effect != taint.get("effect", ""):
        return False
    t_key = toleration.get("key", "")
    if t_key and t_key != taint.get("key", ""):
        return False
    op = toleration.get("operator") or "Equal"
    if op == "Exists":
        return True
    if op == "Equal":
        return toleration.get("value", "") == taint.get("value", "")
    return False


def tolerations_tolerate_taints(
    tolerations: List[dict], taints: List[dict], effects: Optional[List[str]] = None
) -> bool:
    """All taints (optionally restricted to given effects) must be tolerated."""
    for taint in taints:
        if effects is not None and taint.get("effect") not in effects:
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in tolerations):
            return False
    return True


def pod_tolerates_node_taints(pod: dict, node: dict, include_prefer: bool = False) -> bool:
    """TaintToleration filter: NoSchedule (+NoExecute) taints must be tolerated.

    The scheduler's filter ignores PreferNoSchedule (`tainttoleration` plugin);
    daemon.Predicates filters on NoSchedule+NoExecute the same way.
    """
    effects = ["NoSchedule", "NoExecute"]
    if include_prefer:
        effects.append("PreferNoSchedule")
    return tolerations_tolerate_taints(pod_tolerations(pod), node_taints(node), effects)


def node_should_run_pod(node: dict, pod: dict) -> bool:
    """Would a DaemonSet pod pinned to this node ever run here?

    Mirrors `utils.NodeShouldRunPod` (`pkg/utils/utils.go:388-395`) →
    daemon.Predicates (`daemon_controller.go:1251-1257`): node-name match,
    selector+affinity match, and NoSchedule/NoExecute taints tolerated.
    """
    from .objects import pod_node_name

    fits_node_name = not pod_node_name(pod) or pod_node_name(pod) == name_of(node)
    fits_affinity = pod_matches_node_selector_and_affinity(pod, node)
    fits_taints = pod_tolerates_node_taints(pod, node)
    return fits_node_name and fits_affinity and fits_taints


def match_label_selector(selector: dict, target_labels: Dict[str, str]) -> bool:
    """metav1.LabelSelector: matchLabels AND matchExpressions.

    A nil selector matches nothing; an empty selector matches everything
    (apimachinery LabelSelectorAsSelector semantics).
    """
    if selector is None:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if target_labels.get(k) != v:
            return False
    for req in selector.get("matchExpressions") or []:
        if req.get("operator") not in (OP_IN, OP_NOT_IN, OP_EXISTS, OP_DOES_NOT_EXIST):
            return False
        if not match_requirement(target_labels, req):
            return False
    return True
