"""Extended-resource tensorization: Open-Local storage and GPU-share devices.

Lowers the two annotation-based extended schedulers to arrays:

- Open-Local (`pkg/simulator/plugin/open-local.go`, vendored algo at
  `vendor/github.com/alibaba/open-local/pkg/scheduler/algorithm/algo/common.go`):
  node VGs/devices come from the `simon/node-local-storage` JSON annotation
  (`pkg/utils/utils.go:538-567`), pod demand from `simon/pod-local-storage`
  (`utils.go:593-651`), VG names / media types from StorageClass parameters
  (`vendor/.../open-local/pkg/utils/common.go:318-340`).
- GPU-share (`pkg/simulator/plugin/open-gpu-share.go`, vendored cache at
  `vendor/github.com/alibaba/open-gpu-share/pkg/cache/gpunodeinfo.go`): per-node
  devices each hold capacity/count GPU memory; pod demand comes from the
  `alibabacloud.com/gpu-mem` + `gpu-count` annotations
  (`vendor/.../open-gpu-share/pkg/utils/pod.go:57-98`).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import constants as C
from .objects import annotations_of, name_of
from .quantity import parse_quantity
from .vocab import Interner

MEDIA_NONE, MEDIA_SSD, MEDIA_HDD = 0, 1, 2
_MEDIA_CODE = {"ssd": MEDIA_SSD, "hdd": MEDIA_HDD}


def _parse_num(v) -> float:
    """Storage JSON writes numbers as strings ("capacity": "107374182400")."""
    if isinstance(v, str):
        return parse_quantity(v)
    return float(v or 0)


def _parse_bool(v) -> bool:
    if isinstance(v, str):
        return v.lower() == "true"
    return bool(v)


@dataclass
class NodeStorage:
    """Parsed `simon/node-local-storage` annotation (utils.go:538-541)."""

    vgs: List[dict]
    devices: List[dict]

    @classmethod
    def from_node(cls, node: dict) -> Optional["NodeStorage"]:
        raw = annotations_of(node).get(C.ANNO_NODE_LOCAL_STORAGE)
        if raw is None:
            return None
        data = json.loads(raw)
        return cls(vgs=data.get("vgs") or [], devices=data.get("devices") or [])


class StorageClassCatalog:
    """StorageClass name → parameters lookup (the informer the LocalPlugin
    consults, `pkg/simulator/plugin/open-local.go:29,71`)."""

    def __init__(self, storage_classes: Sequence[dict] = ()):
        self._params: Dict[str, dict] = {}
        for sc in storage_classes:
            self._params[name_of(sc)] = sc.get("parameters") or {}

    def __contains__(self, sc_name: str) -> bool:
        return sc_name in self._params

    def vg_name(self, sc_name: str) -> str:
        return self._params.get(sc_name, {}).get("vgName", "")

    def media_type(self, sc_name: str) -> str:
        return self._params.get(sc_name, {}).get("mediaType", "")


@dataclass
class ExtendedNodeArrays:
    """Per-node extended-resource capacity arrays (V/SD/GD = padded widths)."""

    vg_cap: np.ndarray  # [N, V] f32
    vg_req0: np.ndarray  # [N, V] f32 initial Requested from annotation
    vg_name_id: np.ndarray  # [N, V] i32 interned VG name, -1 pad
    vg_names: List[str]
    has_storage: np.ndarray  # [N] bool — node carries the storage annotation
    sdev_cap: np.ndarray  # [N, SD] f32 exclusive-device capacity
    sdev_media: np.ndarray  # [N, SD] i32 media code
    sdev_alloc0: np.ndarray  # [N, SD] bool initially allocated
    sdev_names: List[List[str]]  # per node, for reports
    gpu_dev_total: np.ndarray  # [N, GD] f32 per-device GPU memory
    gpu_total: np.ndarray  # [N] f32 node total GPU memory (capacity)


def tensorize_node_storage(
    nodes: Sequence[dict], vg_names: Optional[Interner] = None
) -> ExtendedNodeArrays:
    n = len(nodes)
    storages = [NodeStorage.from_node(node) for node in nodes]
    if vg_names is None:
        vg_names = Interner()
    v_max = max([len(s.vgs) for s in storages if s] + [0])
    sd_max = max([len(s.devices) for s in storages if s] + [0])

    vg_cap = np.zeros((n, max(v_max, 1)), np.float32)
    vg_req0 = np.zeros_like(vg_cap)
    vg_name_id = np.full((n, max(v_max, 1)), -1, np.int32)
    has_storage = np.zeros(n, bool)
    sdev_cap = np.zeros((n, max(sd_max, 1)), np.float32)
    sdev_media = np.zeros((n, max(sd_max, 1)), np.int32)
    sdev_alloc0 = np.zeros((n, max(sd_max, 1)), bool)
    sdev_names: List[List[str]] = []

    # GPU devices: capacity/count each (gpunodeinfo.go:34-41); totals read from
    # node *capacity* (utils/node.go:11-26)
    gpu_counts = []
    gpu_totals = []
    for node in nodes:
        cap = ((node.get("status") or {}).get("capacity")) or {}
        gpu_totals.append(parse_quantity(cap.get(C.RES_GPU_MEM)))
        gpu_counts.append(int(parse_quantity(cap.get(C.RES_GPU_COUNT))))
    gd_max = max(gpu_counts + [0])
    gpu_dev_total = np.zeros((n, max(gd_max, 1)), np.float32)

    for i, (node, s) in enumerate(zip(nodes, storages)):
        names = []
        if s is not None:
            has_storage[i] = True
            for j, vg in enumerate(s.vgs):
                vg_cap[i, j] = _parse_num(vg.get("capacity"))
                vg_req0[i, j] = _parse_num(vg.get("requested"))
                vg_name_id[i, j] = vg_names.intern(vg.get("name", ""))
            for j, dev in enumerate(s.devices):
                sdev_cap[i, j] = _parse_num(dev.get("capacity"))
                sdev_media[i, j] = _MEDIA_CODE.get(
                    str(dev.get("mediaType", "")).lower(), MEDIA_NONE
                )
                sdev_alloc0[i, j] = _parse_bool(dev.get("isAllocated"))
                names.append(dev.get("device") or dev.get("name") or f"dev-{j}")
        sdev_names.append(names)
        if gpu_counts[i] > 0:
            gpu_dev_total[i, : gpu_counts[i]] = gpu_totals[i] / gpu_counts[i]

    return ExtendedNodeArrays(
        vg_cap=vg_cap,
        vg_req0=vg_req0,
        vg_name_id=vg_name_id,
        vg_names=[str(x) for x in vg_names.items()],
        has_storage=has_storage,
        sdev_cap=sdev_cap,
        sdev_media=sdev_media,
        sdev_alloc0=sdev_alloc0,
        sdev_names=sdev_names,
        gpu_dev_total=gpu_dev_total,
        gpu_total=np.asarray(gpu_totals, np.float32),
    )


@dataclass
class PodExtendedDemand:
    """One pod's storage/GPU demand, host-side."""

    lvm_sizes: List[float]
    lvm_vg_ids: List[int]  # interned VG name id or -1 (binpack)
    dev_sizes: List[float]  # sorted ascending within each media class
    dev_medias: List[int]
    gpu_mem: float
    gpu_count: int
    gpu_preset: List[int]  # device ids from an existing gpu-index annotation


def pod_extended_demand(
    pod: dict, catalog: StorageClassCatalog, vg_names: Interner
) -> PodExtendedDemand:
    """Extract the pod's Open-Local PVC list (`pkg/utils/utils.go:608-651`)
    and GPU annotation demand (`open-gpu-share/pkg/utils/pod.go:83-98`)."""
    annos = annotations_of(pod)
    lvm_sizes: List[float] = []
    lvm_vg_ids: List[int] = []
    dev_pairs: List[Tuple[float, int]] = []
    raw = annos.get(C.ANNO_POD_LOCAL_STORAGE)
    if raw:
        try:
            volumes = (json.loads(raw) or {}).get("volumes") or []
        except json.JSONDecodeError:
            volumes = []
        for vol in volumes:
            sc = vol.get("scName", "")
            size = _parse_num(vol.get("size"))
            if vol.get("kind") == "LVM":
                vg = catalog.vg_name(sc)
                lvm_sizes.append(size)
                # -1 = unnamed (binpack); -2 = named VG that exists on no node
                # (NewNotSuchVGError → unfit everywhere, common.go:71-75)
                if not vg:
                    lvm_vg_ids.append(-1)
                else:
                    vid = vg_names.get(vg)
                    lvm_vg_ids.append(vid if vid >= 0 else -2)
            elif vol.get("kind") in ("SSD", "HDD"):
                media = _MEDIA_CODE.get(catalog.media_type(sc).lower(), MEDIA_NONE)
                if media != MEDIA_NONE:
                    # SC without a known mediaType is dropped by
                    # DividePVCAccordingToMediaType (common.go:247-259)
                    dev_pairs.append((size, media))
    # device PVCs are consumed smallest-first per media class
    # (CheckExclusiveResourceMeetsPVCSize sorts both sides, common.go:290-297),
    # SSD class first (ProcessDevicePVC, common.go:394-446)
    dev_pairs.sort(key=lambda p: (p[1] != MEDIA_SSD, p[0]))
    # named-VG PVCs are allocated before unnamed ones (DivideLVMPVCs split,
    # common.go:59-70 then :108-144); keep relative order within each class
    order = sorted(range(len(lvm_sizes)), key=lambda i: lvm_vg_ids[i] == -1)
    lvm_sizes = [lvm_sizes[i] for i in order]
    lvm_vg_ids = [lvm_vg_ids[i] for i in order]
    gpu_mem = parse_quantity(annos.get(C.ANNO_POD_GPU_MEM, 0))
    try:
        gpu_count = int(annos.get(C.ANNO_POD_GPU_COUNT, "0"))
    except ValueError:
        gpu_count = 0
    # an existing gpu-index annotation short-circuits device planning
    # (AllocateGpuId, gpunodeinfo.go:247-253) — e.g. running pods from a live
    # cluster snapshot keep their device assignment
    gpu_preset: List[int] = []
    raw_idx = annos.get(C.ANNO_POD_GPU_INDEX, "")
    if raw_idx:
        try:
            gpu_preset = [int(tok) for tok in raw_idx.split("-")]
        except ValueError:
            gpu_preset = []
    return PodExtendedDemand(
        lvm_sizes=lvm_sizes,
        lvm_vg_ids=lvm_vg_ids,
        dev_sizes=[p[0] for p in dev_pairs],
        dev_medias=[p[1] for p in dev_pairs],
        gpu_mem=gpu_mem,
        gpu_count=gpu_count,
        gpu_preset=gpu_preset,
    )


