"""Lightweight Kubernetes object model.

The reference links the full k8s API type tree (`pkg/simulator/core.go:29-43`
enumerates the 13 resource kinds it ingests). We are not a controller — objects
here are inert simulation inputs — so instead of typed structs we keep each
manifest as its raw dict and provide accessor helpers for the handful of fields
the scheduler semantics read. This keeps ingestion = `yaml.safe_load`, workload
expansion = dict surgery, and leaves the numeric heavy lifting to tensorize.py.
"""

from __future__ import annotations

import copy
import json
from dataclasses import dataclass, field
from typing import Dict, Iterator, List

from .quantity import parse_quantity

# Kind names shared with simtpu.constants (single canonical table there).
from ..constants import (  # noqa: F401
    KIND_CRON_JOB,
    KIND_DEPLOYMENT,
    KIND_DS,
    KIND_JOB,
    KIND_POD,
    KIND_RC,
    KIND_RS,
    KIND_STS,
)

KIND_SERVICE = "Service"
KIND_PVC = "PersistentVolumeClaim"
KIND_PV = "PersistentVolume"
KIND_PDB = "PodDisruptionBudget"
KIND_STORAGE_CLASS = "StorageClass"
KIND_NODE = "Node"

WORKLOAD_KINDS = (
    KIND_DEPLOYMENT,
    KIND_RS,
    KIND_RC,
    KIND_STS,
    KIND_DS,
    KIND_JOB,
    KIND_CRON_JOB,
)


def meta(obj: dict) -> dict:
    """Read-only view of metadata; use ensure_meta() when mutating."""
    return obj.get("metadata") or {}


def ensure_meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


def name_of(obj: dict) -> str:
    return meta(obj).get("name", "")


def namespace_of(obj: dict) -> str:
    return meta(obj).get("namespace") or "default"


def labels_of(obj: dict) -> Dict[str, str]:
    return meta(obj).get("labels") or {}


def annotations_of(obj: dict) -> Dict[str, str]:
    return meta(obj).get("annotations") or {}


def set_annotation(obj: dict, key: str, value: str) -> None:
    ensure_meta(obj).setdefault("annotations", {})[key] = value


def set_label(obj: dict, key: str, value: str) -> None:
    ensure_meta(obj).setdefault("labels", {})[key] = value


def nn_key(obj: dict) -> str:
    """namespace/name key used for identity maps."""
    return f"{namespace_of(obj)}/{name_of(obj)}"


def owner_references(obj: dict) -> List[dict]:
    return meta(obj).get("ownerReferences") or []


def deep_copy(obj: dict) -> dict:
    return copy.deepcopy(obj)


def shallow_pod_copy(pod: dict) -> dict:
    """A pod copy isolated exactly where the simulator mutates: top level,
    metadata (+labels/annotations), spec, status. Deep sub-structures
    (containers, volumes, affinity, ...) are shared read-only — at
    million-pod scale `copy.deepcopy` per placed pod (and again per
    `_result()` call) dominated the whole facade."""
    placed = dict(pod)
    meta = dict(pod.get("metadata") or {})
    if "annotations" in meta:
        meta["annotations"] = dict(meta["annotations"])
    if "labels" in meta:
        meta["labels"] = dict(meta["labels"])
    placed["metadata"] = meta
    placed["spec"] = dict(pod.get("spec") or {})
    placed["status"] = dict(pod.get("status") or {})
    return placed


# ---------------------------------------------------------------------------
# Pod helpers
# ---------------------------------------------------------------------------


def pod_spec(pod: dict) -> dict:
    """Read-only view of spec."""
    return pod.get("spec") or {}


def pod_node_name(pod: dict) -> str:
    return pod_spec(pod).get("nodeName") or ""


def pod_containers(pod: dict) -> List[dict]:
    return pod_spec(pod).get("containers") or []


def pod_init_containers(pod: dict) -> List[dict]:
    return pod_spec(pod).get("initContainers") or []


def _container_requests(container: dict) -> Dict[str, float]:
    res = (container.get("resources") or {}).get("requests") or {}
    # limits default requests when requests are absent (k8s defaulting)
    limits = (container.get("resources") or {}).get("limits") or {}
    out = {k: parse_quantity(v) for k, v in limits.items()}
    out.update({k: parse_quantity(v) for k, v in res.items()})
    return out


def pod_requests(pod: dict) -> Dict[str, float]:
    """Aggregate pod resource requests.

    Mirrors k8s resourcehelper.PodRequestsAndLimits (used at
    `pkg/simulator/plugin/simon.go:45`): sum of containers, elementwise max with
    each init container, plus pod overhead.
    """
    totals: Dict[str, float] = {}
    for c in pod_containers(pod):
        for k, v in _container_requests(c).items():
            totals[k] = totals.get(k, 0.0) + v
    for c in pod_init_containers(pod):
        for k, v in _container_requests(c).items():
            if v > totals.get(k, 0.0):
                totals[k] = v
    for k, v in (pod_spec(pod).get("overhead") or {}).items():
        totals[k] = totals.get(k, 0.0) + parse_quantity(v)
    # keep negatives so validation can reject malformed manifests
    return {k: v for k, v in totals.items() if v != 0}


def pod_host_ports(pod: dict) -> List[tuple]:
    """(protocol, hostIP, hostPort) triples, for the NodePorts filter."""
    out = []
    for c in pod_containers(pod):
        for p in c.get("ports") or []:
            hp = p.get("hostPort")
            if hp:
                out.append((p.get("protocol", "TCP"), p.get("hostIP", "0.0.0.0"), int(hp)))
    return out


def pod_topology_spread_constraints(pod: dict) -> List[dict]:
    """topologySpreadConstraints, for the PodTopologySpread plugin."""
    return pod_spec(pod).get("topologySpreadConstraints") or []


def pod_volumes(pod: dict) -> List[dict]:
    return pod_spec(pod).get("volumes") or []


def pod_pvc_names(pod: dict) -> List[str]:
    """Claim names referenced by the pod's volumes (VolumeBinding/VolumeZone
    inputs, `plugins/volumebinding/volume_binding.go` podHasPVCs)."""
    out = []
    for v in pod_volumes(pod):
        pvc = v.get("persistentVolumeClaim")
        if pvc and pvc.get("claimName"):
            out.append(pvc["claimName"])
    return out


# Volume-identity key builders — shared by pod_volume_conflicts
# (VolumeRestrictions) and _attachable_source (NodeVolumeLimits) so one
# interned identity serves both and per-node presence counts each volume once.


def _ebs_key(src: dict) -> str:
    return f"aws:{src['volumeID']}"


def _gce_key(src: dict) -> str:
    return f"gce:{src['pdName']}"


def _azure_key(src: dict) -> str:
    return f"azure:{src['diskName']}"


def _cinder_key(src: dict) -> str:
    return f"cinder:{src['volumeID']}"


def _iscsi_key(src: dict) -> str:
    # upstream conflicts on same IQN *and* same LUN (volume_restrictions.go
    # isVolumeConflict): both participate in the identity
    return f"iscsi:{src.get('iqn', '')}:lun{src.get('lun', 0)}"


def _rbd_key(src: dict) -> str:
    # upstream compares CephMonitors overlap + pool + image; monitor-set
    # equality stands in for overlap (distinct-but-overlapping monitor lists
    # are vanishingly rare in manifests)
    mons = ",".join(sorted(src.get("monitors") or []))
    pool = src.get("pool") or "rbd"
    return f"rbd:{mons}:{pool}/{src.get('image', '')}"


def pod_volume_conflicts(pod: dict) -> tuple:
    """(read_write_keys, read_only_keys) of exclusive volume identities.

    VolumeRestrictions semantics (`plugins/volumerestrictions/
    volume_restrictions.go` isVolumeConflict): two pods on one node may not
    share
    - an AWS EBS volume at all,
    - a GCE PD / ISCSI (IQN+LUN) / RBD (monitors+pool+image) unless both
      mount it read-only.
    A volume in the read_write list excludes any other user of the same key;
    one in the read_only list excludes only read-write users.
    """
    rw, ro = [], []
    for v in pod_volumes(pod):
        src = v.get("awsElasticBlockStore")
        if src and src.get("volumeID"):
            rw.append(_ebs_key(src))  # always-exclusive
            continue
        src = v.get("gcePersistentDisk")
        if src and src.get("pdName"):
            (ro if src.get("readOnly") else rw).append(_gce_key(src))
            continue
        src = v.get("iscsi")
        if src and src.get("iqn"):
            (ro if src.get("readOnly") else rw).append(_iscsi_key(src))
            continue
        src = v.get("rbd")
        if src and src.get("image"):
            (ro if src.get("readOnly") else rw).append(_rbd_key(src))
    return tuple(sorted(set(rw))), tuple(sorted(set(ro) - set(rw)))


#: NodeVolumeLimits classes, in the order of the engine's static attach-limit
#: columns: (allocatable resource name, default limit when unpublished).
#: Defaults mirror the in-tree values (`plugins/nodevolumelimits/non_csi.go`
#: DefaultMaxEBSVolumes / DefaultMaxGCEPDVolumes / DefaultMaxAzureDiskVolumes,
#: `pkg/volume/util/attach_limit.go` DefaultMaxCinderVolumes). CSI classes are
#: per-driver and appended dynamically by the Tensorizer
#: (`plugins/nodevolumelimits/csi.go` — `attachable-volumes-csi-<driver>`).
ATTACH_CLASSES = (
    ("attachable-volumes-aws-ebs", 39.0),
    ("attachable-volumes-gce-pd", 16.0),
    ("attachable-volumes-azure-disk", 16.0),
    ("attachable-volumes-cinder", 256.0),
)


def csi_attach_limit_key(driver: str) -> str:
    """Per-driver CSI limit resource name (`pkg/volume/util/attach_limit.go`
    GetCSIAttachLimitKey: `attachable-volumes-csi-` prefix, driver appended)."""
    return f"attachable-volumes-csi-{driver}"


def _attachable_source(src_holder: dict) -> tuple:
    """(volume-key, class-index) of an inline EBS/GCE/Azure/Cinder source,
    else None.

    Keys are shared with `pod_volume_conflicts` so one interned volume
    identity serves both VolumeRestrictions and NodeVolumeLimits.
    """
    src = src_holder.get("awsElasticBlockStore")
    if src and src.get("volumeID"):
        return _ebs_key(src), 0
    src = src_holder.get("gcePersistentDisk")
    if src and src.get("pdName"):
        return _gce_key(src), 1
    src = src_holder.get("azureDisk")
    if src and src.get("diskName"):
        return _azure_key(src), 2
    src = src_holder.get("cinder")
    if src and src.get("volumeID"):
        return _cinder_key(src), 3
    return None


def pod_attachable_volumes(pod: dict) -> List[tuple]:
    """Inline attachable volumes as unique (key, class-index) pairs
    (NodeVolumeLimits, `plugins/nodevolumelimits/non_csi.go`). PVC-backed
    volumes are resolved by the Tensorizer, which holds the PVC/PV maps."""
    out = []
    for v in pod_volumes(pod):
        pair = _attachable_source(v)
        if pair is not None:
            out.append(pair)
    return sorted(set(out))


def pv_attachable_source(pv: dict) -> tuple:
    """The PV's attachable (key, class-index), or None (non_csi.go
    filterAttachableVolumes resolves PVC → PV → volume source)."""
    return _attachable_source((pv.get("spec") or {}))


def pv_csi_source(pv: dict) -> tuple:
    """The PV's CSI (volume-key, driver-name), or None.

    CSILimits counts only PVC-backed CSI volumes, keyed by driver +
    volumeHandle (`plugins/nodevolumelimits/csi.go` filterAttachableVolumes /
    getCSIDriverInfo); each driver gets its own per-node limit class."""
    src = (pv.get("spec") or {}).get("csi")
    if src and src.get("driver") and src.get("volumeHandle"):
        return f"csi:{src['driver']}:{src['volumeHandle']}", str(src["driver"])
    return None


def pod_owner_kind(pod: dict) -> str:
    """Kind of the pod's controller owner reference ('' when unowned)."""
    for ref in (pod.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("kind"):
            return str(ref["kind"])
    return ""


def pod_images(pod: dict) -> List[str]:
    """Container image names, for the ImageLocality score."""
    return [c["image"] for c in pod_containers(pod) if c.get("image")]


#: Built-in priority classes (`k8s.io/api/scheduling/v1/types.go`); the
#: reference's ResourceTypes carries no PriorityClass objects
#: (`pkg/simulator/core.go:29-43`), so only these resolve by name.
_BUILTIN_PRIORITY_CLASSES = {
    "system-cluster-critical": 2000000000.0,
    "system-node-critical": 2000001000.0,
}


def pod_priority(pod: dict) -> float:
    """Effective scheduling priority: spec.priority, else the built-in
    priorityClassName value, else 0 (the admission-defaulted globalDefault)."""
    p = pod_spec(pod).get("priority")
    if p is not None:
        return float(p)
    name = pod_spec(pod).get("priorityClassName") or ""
    return _BUILTIN_PRIORITY_CLASSES.get(name, 0.0)


def pod_tolerations(pod: dict) -> List[dict]:
    return pod_spec(pod).get("tolerations") or []


def pod_node_selector(pod: dict) -> Dict[str, str]:
    return pod_spec(pod).get("nodeSelector") or {}


def pod_affinity(pod: dict) -> dict:
    return pod_spec(pod).get("affinity") or {}


# ---------------------------------------------------------------------------
# Node helpers
# ---------------------------------------------------------------------------


def node_allocatable(node: dict) -> Dict[str, float]:
    alloc = ((node.get("status") or {}).get("allocatable")) or {}
    return {k: parse_quantity(v) for k, v in alloc.items()}


def node_taints(node: dict) -> List[dict]:
    return (node.get("spec") or {}).get("taints") or []


def node_unschedulable(node: dict) -> bool:
    return bool((node.get("spec") or {}).get("unschedulable"))


def node_images(node: dict) -> List[dict]:
    """status.images ({names, sizeBytes} entries), for ImageLocality."""
    return (node.get("status") or {}).get("images") or []


#: scheduler.alpha.kubernetes.io/preferAvoidPods — consumed by the
#: NodePreferAvoidPods score plugin (weight 10000 in the default provider).
ANNO_PREFER_AVOID_PODS = "scheduler.alpha.kubernetes.io/preferAvoidPods"


def node_prefer_avoid_pods(node: dict) -> bool:
    """True when the node's preferAvoidPods annotation lists any entry.

    The upstream plugin matches entries against the pod's RC/RS controller
    signature (`plugins/nodepreferavoidpods/node_prefer_avoid_pods.go`); the
    simulation has no UIDs, so any entry avoids all RC/RS-owned pods.
    """
    raw = annotations_of(node).get(ANNO_PREFER_AVOID_PODS)
    if not raw:
        return False
    try:
        parsed = json.loads(raw)
    except (ValueError, TypeError):
        return False
    return bool((parsed or {}).get("preferAvoidPods"))


# ---------------------------------------------------------------------------
# ResourceTypes — the 13-kind container (pkg/simulator/core.go:29-43)
# ---------------------------------------------------------------------------

_KIND_TO_FIELD = {
    KIND_POD: "pods",
    KIND_DEPLOYMENT: "deployments",
    KIND_RS: "replica_sets",
    KIND_RC: "replication_controllers",
    KIND_STS: "stateful_sets",
    KIND_DS: "daemon_sets",
    KIND_JOB: "jobs",
    KIND_CRON_JOB: "cron_jobs",
    KIND_SERVICE: "services",
    KIND_PVC: "persistent_volume_claims",
    KIND_PV: "persistent_volumes",
    KIND_PDB: "pod_disruption_budgets",
    KIND_STORAGE_CLASS: "storage_classes",
    KIND_NODE: "nodes",
}


@dataclass
class ResourceTypes:
    """All simulation inputs, grouped by kind.

    Mirrors `simulator.ResourceTypes` (`pkg/simulator/core.go:29-43`).
    """

    nodes: List[dict] = field(default_factory=list)
    pods: List[dict] = field(default_factory=list)
    deployments: List[dict] = field(default_factory=list)
    replica_sets: List[dict] = field(default_factory=list)
    replication_controllers: List[dict] = field(default_factory=list)
    stateful_sets: List[dict] = field(default_factory=list)
    daemon_sets: List[dict] = field(default_factory=list)
    jobs: List[dict] = field(default_factory=list)
    cron_jobs: List[dict] = field(default_factory=list)
    services: List[dict] = field(default_factory=list)
    persistent_volume_claims: List[dict] = field(default_factory=list)
    persistent_volumes: List[dict] = field(default_factory=list)
    pod_disruption_budgets: List[dict] = field(default_factory=list)
    storage_classes: List[dict] = field(default_factory=list)

    def add(self, obj: dict) -> bool:
        """Type-switch an object into its bucket.

        Mirrors `simulator.GetObjectFromYamlContent`'s decode-and-switch
        (`pkg/simulator/utils.go:139-183`). Returns False for unrecognized kinds
        (the reference errors; callers decide).
        """
        kind = obj.get("kind")
        fld = _KIND_TO_FIELD.get(kind)
        if fld is None:
            return False
        getattr(self, fld).append(obj)
        return True

    def extend(self, other: "ResourceTypes") -> None:
        for fld in _KIND_TO_FIELD.values():
            getattr(self, fld).extend(getattr(other, fld))

    def workloads(self) -> Iterator[dict]:
        for fld in (
            "deployments",
            "replica_sets",
            "replication_controllers",
            "stateful_sets",
            "daemon_sets",
            "jobs",
            "cron_jobs",
        ):
            yield from getattr(self, fld)

    def __iter__(self) -> Iterator[dict]:
        for fld in _KIND_TO_FIELD.values():
            yield from getattr(self, fld)


@dataclass
class AppResource:
    """A named application bundle (`pkg/simulator/core.go:45-48`)."""

    name: str
    resource: ResourceTypes


@dataclass
class UnscheduledPod:
    """A pod the engine could not place, with the failing constraint.

    Mirrors `simulator.UnscheduledPod` (`pkg/simulator/core.go:56-59`), but the
    reason is recovered from the constraint masks (which kernel zeroed the row)
    instead of a PodCondition message.
    """

    pod: dict
    reason: str


@dataclass
class NodeStatus:
    """One node plus the pods placed on it (`pkg/simulator/core.go:105-108`)."""

    node: dict
    pods: List[dict]


@dataclass
class PreemptedPod:
    """A lower-priority pod evicted to make room for a preemptor.

    The reference inherits this behavior from the vendored scheduler's
    DefaultPreemption PostFilter (`vendor/.../plugins/defaultpreemption/`):
    victims are deleted from the fake cluster and never re-queued (they were
    fake-Running, not owned by live controllers), so the simulation surfaces
    them explicitly instead of silently dropping them.
    """

    pod: dict
    preempted_by: str  # "namespace/name" of the preemptor
    node: str  # node the victim was evicted from


@dataclass
class SimulateResult:
    """Result of one simulation (`pkg/simulator/core.go:56-62`)."""

    unscheduled_pods: List[UnscheduledPod]
    node_status: List[NodeStatus]
    preempted_pods: List[PreemptedPod] = field(default_factory=list)
    # independent placement audit (simtpu/audit AuditReport) when the
    # caller asked `simulate(audit=True)`; None = not audited
    audit: object = None
    # decision-observability record (simtpu/explain: failure breakdowns +
    # bottleneck analysis) when the caller asked `simulate(explain=...)`;
    # None = not explained (the zero-cost default)
    explain: object = None
