"""Kubernetes resource-quantity parsing.

The reference manipulates `resource.Quantity` objects throughout (e.g.
`pkg/algo/greed.go:20-31`, `pkg/simulator/plugin/simon.go:46-66`). We only ever
need quantities as scalars feeding dense arrays, so this module lowers the k8s
quantity grammar straight to floats (canonical unit: CPU in *cores*, everything
else in base units — bytes for memory/storage).

Grammar (mirrors apimachinery's resource.Quantity):
    <number><suffix>
    suffix ∈ {"", m, k, M, G, T, P, E, Ki, Mi, Gi, Ti, Pi, Ei, n, u}
"""

from __future__ import annotations

_SUFFIX = {
    "": 1.0,
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
    "Ki": 2.0**10,
    "Mi": 2.0**20,
    "Gi": 2.0**30,
    "Ti": 2.0**40,
    "Pi": 2.0**50,
    "Ei": 2.0**60,
}


# quantity strings repeat massively across pods/nodes (every replica shares
# its template's "100m"/"64Gi"); memoize with a bounded cache
_CACHE: dict = {}
_CACHE_MAX = 1 << 16


def parse_quantity(value) -> float:
    """Parse a k8s quantity ("1500m", "16Gi", 2, "32560Mi") to a float scalar."""
    if value is None:
        return 0.0
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        return 0.0
    hit = _CACHE.get(s)
    if hit is not None:
        return hit
    # exponent form like "1e3" is legal in the k8s grammar
    i = len(s)
    while i > 0 and not (s[i - 1].isdigit() or s[i - 1] == "."):
        i -= 1
    num, suffix = s[:i], s[i:]
    if suffix not in _SUFFIX:
        # maybe scientific notation ("12e6"): float() handles it, no suffix
        try:
            out = float(s)
        except ValueError as exc:
            raise ValueError(f"unparseable quantity {value!r}") from exc
    elif not num:
        raise ValueError(f"unparseable quantity {value!r}")
    else:
        out = float(num) * _SUFFIX[suffix]
    if len(_CACHE) >= _CACHE_MAX:
        _CACHE.clear()
    _CACHE[s] = out
    return out


def format_quantity(value: float, unit: str = "") -> str:
    """Render a float back into a human-readable quantity for reports.

    unit="cpu" renders millicores below 10 cores; unit="mem" renders Gi/Mi.
    """
    if unit == "cpu":
        if value == int(value) and value >= 10:
            return str(int(value))
        m = value * 1000
        if m == int(m):
            return f"{int(m)}m"
        return f"{m:.1f}m"
    if unit == "mem":
        for suf, mult in (("Ti", 2.0**40), ("Gi", 2.0**30), ("Mi", 2.0**20), ("Ki", 2.0**10)):
            if value >= mult:
                v = value / mult
                if v == int(v):
                    return f"{int(v)}{suf}"
                return f"{v:.2f}{suf}"
        return str(int(value))
    if value == int(value):
        return str(int(value))
    return f"{value:.3f}"
