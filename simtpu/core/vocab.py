"""String interning for ragged k8s metadata.

TPU kernels can't chew on label strings; every string-valued feature (label
key/value pairs, taint triples, topology domains, resource names) is interned
to a dense integer id at tensorization time. This replaces the reference's
map[string]string lookups inside the scheduler hot loop
(`vendor/.../core/generic_scheduler.go:271-341`).
"""

from __future__ import annotations

from typing import Dict, Hashable, List


class Interner:
    """Monotonic string→id mapping."""

    def __init__(self) -> None:
        self._ids: Dict[Hashable, int] = {}
        self._items: List[Hashable] = []

    def intern(self, item: Hashable) -> int:
        idx = self._ids.get(item)
        if idx is None:
            idx = len(self._items)
            self._ids[item] = idx
            self._items.append(item)
        return idx

    def get(self, item: Hashable) -> int:
        """-1 for unknown items (never allocates)."""
        return self._ids.get(item, -1)

    def __len__(self) -> int:
        return len(self._items)

    def __contains__(self, item: Hashable) -> bool:
        return item in self._ids

    def items(self) -> List[Hashable]:
        return list(self._items)

    def lookup(self, idx: int) -> Hashable:
        return self._items[idx]
