"""KubeSchedulerConfiguration consumption.

The reference accepts `--default-scheduler-config` and merges the file into
its in-memory scheduler profile before force-enabling the Simon/Open-Local/
Open-Gpu-Share plugins (`pkg/simulator/utils.go:212-289`). The practically
configurable surface of that file is the score-plugin set: which plugins run
and with what weight. This module lowers that surface onto the engine's
score-term weight vector (`scan.StaticArrays.score_w`).

Filter plugins are hard constraints in this engine and cannot be disabled
(matching the reference, which never disables filters either — it only
appends to them).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import yaml

# score-term order in StaticArrays.score_w — must match scan.schedule_step
TERM_LEAST = 0  # NodeResourcesLeastAllocated
TERM_BALANCED = 1  # NodeResourcesBalancedAllocation
TERM_SIMON = 2  # Simon (dominant share)
TERM_GPU = 3  # Open-Gpu-Share (same formula as Simon)
TERM_NODE_PREF = 4  # NodeAffinity (preferred)
TERM_TAINT = 5  # TaintToleration
TERM_IPA = 6  # InterPodAffinity
TERM_SPREAD_SOFT = 7  # PodTopologySpread (ScheduleAnyway)
TERM_SS = 8  # SelectorSpread
TERM_IMAGE = 9  # ImageLocality
TERM_OPEN_LOCAL = 10  # Open-Local binpack
TERM_AVOID = 11  # NodePreferAvoidPods (penalty form; registry weight folded in)
N_TERMS = 12

#: default-provider weights (`vendor/.../algorithmprovider/registry.go:101-145`
#: — PodTopologySpread carries weight 2; NodePreferAvoidPods' 10000 is folded
#: into its penalty term, so its weight here stays 1)
DEFAULT_WEIGHTS = np.array(
    [1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 2.0, 1.0, 1.0, 1.0, 1.0], np.float32
)
_PLUGIN_TO_TERM = {
    "NodeResourcesLeastAllocated": TERM_LEAST,
    "NodeResourcesBalancedAllocation": TERM_BALANCED,
    "Simon": TERM_SIMON,
    "Open-Gpu-Share": TERM_GPU,
    "NodeAffinity": TERM_NODE_PREF,
    "TaintToleration": TERM_TAINT,
    "InterPodAffinity": TERM_IPA,
    "PodTopologySpread": TERM_SPREAD_SOFT,
    "SelectorSpread": TERM_SS,
    "ImageLocality": TERM_IMAGE,
    "NodePreferAvoidPods": TERM_AVOID,
    "Open-Local": TERM_OPEN_LOCAL,
}


@dataclass
class SchedulerConfig:
    """Score-weight view of a KubeSchedulerConfiguration."""

    score_weights: np.ndarray = field(
        default_factory=lambda: DEFAULT_WEIGHTS.copy()
    )

    @classmethod
    def from_file(cls, path: str) -> "SchedulerConfig":
        """Parse profiles[0].plugins.score of a KubeSchedulerConfiguration.

        `enabled: [{name, weight}]` overrides that plugin's weight (defaulting
        to 1); `disabled: [{name}]` (or `{name: "*"}`) zeroes it.
        """
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        if "KubeSchedulerConfiguration" not in str(doc.get("kind", "")):
            raise ValueError(
                f"{path}: not a KubeSchedulerConfiguration (kind={doc.get('kind')!r})"
            )
        weights = DEFAULT_WEIGHTS.copy()
        profiles = doc.get("profiles") or []
        score = ((profiles[0].get("plugins") or {}).get("score") or {}) if profiles else {}
        for item in score.get("disabled") or []:
            name = (item or {}).get("name", "")
            if name == "*":
                weights[:] = 0.0
            elif name in _PLUGIN_TO_TERM:
                weights[_PLUGIN_TO_TERM[name]] = 0.0
        explicit = set()
        for item in score.get("enabled") or []:
            name = (item or {}).get("name", "")
            if name in _PLUGIN_TO_TERM:
                term = _PLUGIN_TO_TERM[name]
                weights[term] = float(item.get("weight", 1) or 1)
                explicit.add(term)
        # the reference force-enables its own plugins AFTER merging the file
        # (`pkg/simulator/utils.go:259-276`): Simon, Open-Gpu-Share and
        # Open-Local always run; an explicit weight override still applies
        for term in (TERM_SIMON, TERM_GPU, TERM_OPEN_LOCAL):
            if term not in explicit:
                weights[term] = DEFAULT_WEIGHTS[term]
        return cls(score_weights=weights)
