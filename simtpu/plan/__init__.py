"""Capacity planning: serial reference-faithful search (`capacity`),
incremental single-tensorization search (`incremental`), the batched
candidate sweep (`simtpu.parallel.sweep`), and N+k survivability planning
(`resilience`, riding the fault subsystem `simtpu.faults`)."""

from .capacity import (  # noqa: F401
    Applier,
    ApplierOptions,
    PlanResult,
    plan_capacity,
)
from .incremental import plan_capacity_incremental  # noqa: F401
from .resilience import ResiliencePlan, plan_resilience  # noqa: F401
