"""Capacity planning: serial reference-faithful search (`capacity`),
incremental single-tensorization search (`incremental`), and the batched
candidate sweep (`simtpu.parallel.sweep`)."""

from .capacity import (  # noqa: F401
    Applier,
    ApplierOptions,
    PlanResult,
    plan_capacity,
)
from .incremental import plan_capacity_incremental  # noqa: F401
