"""Incremental min-node-add capacity planning: one tensorization, one base
placement, cheap completion probes.

The reference re-simulates the ENTIRE cluster from scratch for every
candidate clone count (`pkg/apply/apply.go:183-233` builds a fresh simulator
per iteration) — at planning scale that re-pays workload expansion,
tensorization, compilation, and a full placement per probe. This module
exploits two structural facts:

1. Candidate clusters differ only in how many template clones are VALID.
   Tensorizing base + max clones ONCE and flipping a `node_valid` mask per
   candidate (`StaticArrays.node_valid`, the same lever the batched sweep
   vmaps over) reuses the frozen tensors, memoized device statics, and every
   compiled executable across all probes.

2. Feasibility probes only need to answer "do the pods that failed on the
   base cluster fit once i clones exist?". The base run's final engine state
   is snapshotted on device; probe(i) resumes from the snapshot, places the
   clone-pinned DaemonSet pods for clones < i plus the base failures in
   their original order, and checks nothing is left behind. This is the
   retry semantics of a REAL cluster — kube-scheduler moves unschedulable
   pods back through the queue when node-add events arrive; it re-places
   only them, never the whole cluster — while the reference's fresh-restart
   is an artifact of its simulator design.

Because greedy placement is order-path-dependent, a fresh run at the chosen
count can in principle differ from base+completion. `verify=True` (default)
re-runs the winning candidate as one fresh full placement over the same
tensorization/compiled code (reference-faithful semantics, one extra
placement of wall-clock); if the fresh run disagrees, the search continues
upward with fresh runs — correctness never rests on the incremental oracle.

Engine-level throughout: probes bypass the Simulator facade (no per-pod
Python bookkeeping) and the final SimulateResult materializes once at the
end. Preemption does not run inside probes — capacity planning asks whether
everything fits, and evicting lower-priority pods does not change cluster
capacity (the serial planner inherits preemption from `simulate()`; use it
when priority-eviction semantics matter).

Two cross-candidate performance levers ride on top (the ISSUE-1 tentpole):

- MESH SHARDING: with `mesh=`, base placement, completion probes, and the
  verify re-runs all execute with the node axis sharded over the mesh
  (`MaskedShardedRoundsEngine`) — the candidate mask composes with the
  sharding's dead-node pad mask and placements stay bit-identical to the
  single-device path.  The compiled mesh executables live in a mesh-wide
  cache (`parallel.sharded._SHARDED_JITS`), so the fresh engine each
  candidate gets does NOT re-jit.
- SHAPE BUCKETING: every engine of one plan shares a bulk-chunk shape
  registry; probe chunks snap UP into (segment count, round capacity,
  carried term rows) buckets the base run already compiled
  (`RoundsEngine.snap_shapes`), so the whole linear/binary probe sweep and
  the verify run reuse warm round-body executables instead of
  shape-specializing per candidate — and the shapes stay deterministic
  across processes, which is what lets the persistent compilation cache
  (`simtpu/cache.py`) collapse the cold path on accelerator backends.
  `PlanResult.compiles` records the per-phase jit-trace counts.

Serial-engine dispatches inside the plan (the rounds engines' serial
fallback segments — tiny runs, matrix leftovers) additionally ride the
speculative wavefront dispatcher (engine/scan.py, docs/speculation.md):
eligible same-group lean runs place through the batched
verify-and-rollback executable instead of the pod-at-a-time scan, with
bit-identical placements.  `speculate=` (None = the SIMTPU_WAVEFRONT
default) forces it per plan for A/B measurement.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import constants as C
from ..core.objects import (
    AppResource,
    NodeStatus,
    ResourceTypes,
    SimulateResult,
    UnscheduledPod,
    deep_copy,
    name_of,
    namespace_of,
)
from ..core.tensorize import slice_batch
from ..durable.deadline import PlanInterrupted
from ..engine.rounds import RoundsEngine
from ..engine.scan import REASON_TEXT
from ..engine.state import CompactState
from ..obs.trace import span
from .capacity import PlanResult, _env_cap, meet_resource_requests


class MaskedRoundsEngine(RoundsEngine):
    """Bulk rounds engine restricted to a candidate cluster: `node_valid`
    masks out clone nodes beyond the candidate's size (dead rows no pod can
    select, exactly like the sweep's vmapped membership masks).  The
    mesh-sharded counterpart is `parallel.sharded.MaskedShardedRoundsEngine`
    (same mask, composed before the shard padding)."""

    def __init__(self, tensorizer, node_valid: np.ndarray):
        super().__init__(tensorizer)
        self.node_valid = np.asarray(node_valid, bool)

    def _dispatch(self, statics, state, pods, flags):
        import jax.numpy as jnp

        statics = statics._replace(
            node_valid=statics.node_valid & jnp.asarray(self.node_valid)
        )
        return super()._dispatch(statics, state, pods, flags)


_state_copier = None


def _copy_state(state):
    """One-dispatch on-device copy of the scan carry (the engines donate
    their input state, so each probe consumes a copy of the snapshot).
    The jitted copier is module-cached — a fresh lambda per call would
    retrace every probe."""
    global _state_copier
    if _state_copier is None:
        import jax
        import jax.numpy as jnp

        _state_copier = jax.jit(
            lambda s: jax.tree_util.tree_map(jnp.copy, s)
        )
    return _state_copier(state)


def _vocab_of(tensors) -> tuple:
    """Engine.place's state-reuse key, for snapshot injection."""
    from ..engine.scan import Engine

    return Engine.state_vocab(tensors)


def _caps_satisfied(
    tensors, placed_req_sum: np.ndarray, node_valid: np.ndarray, vg_extra: float
) -> tuple:
    """MaxCPU/MaxMemory/MaxVG occupancy caps (`apply.go:580-666`), computed
    from the dense arrays instead of walking a million result pods. All caps
    at their default 100 are trivially satisfied (rates cannot exceed 100
    without overcommit, which the engines never do)."""
    max_cpu = _env_cap(C.ENV_MAX_CPU)
    max_mem = _env_cap(C.ENV_MAX_MEMORY)
    max_vg = _env_cap(C.ENV_MAX_VG)
    if max_cpu == 100 and max_mem == 100 and max_vg == 100:
        return True, ""
    from ..core.tensorize import RES_CPU, RES_MEMORY

    alloc = tensors.alloc[node_valid]
    total_cpu = float(alloc[:, RES_CPU].sum())
    total_mem = float(alloc[:, RES_MEMORY].sum())
    cpu_rate = int(placed_req_sum[RES_CPU] / total_cpu * 100) if total_cpu else 0
    mem_rate = int(placed_req_sum[RES_MEMORY] / total_mem * 100) if total_mem else 0
    if cpu_rate > max_cpu:
        return False, (
            f"the average occupancy rate({cpu_rate}%) of cpu goes beyond "
            f"the env setting({max_cpu}%)\n"
        )
    if mem_rate > max_mem:
        return False, (
            f"the average occupancy rate({mem_rate}%) of memory goes beyond "
            f"the env setting({max_mem}%)\n"
        )
    ext = tensors.ext
    vg_cap = float(ext.vg_cap[node_valid].sum())
    if vg_cap:
        vg_req = float(ext.vg_req0[node_valid].sum()) + vg_extra
        vg_rate = int(vg_req / vg_cap * 100)
        if vg_rate > max_vg:
            return False, (
                f"the average occupancy rate({vg_rate}%) of vg goes beyond "
                f"the env setting({max_vg}%)\n"
            )
    return True, ""


def plan_capacity_incremental(
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
    new_node: dict,
    max_new_nodes: int = C.MAX_NUM_NEW_NODE,
    extended_resources: Sequence[str] = (),
    progress=None,
    sched_config=None,
    corrected_ds_overhead: bool = False,
    verify: bool = True,
    materialize: bool = True,
    mesh=None,
    precompile: bool = False,
    pipeline=None,
    speculate=None,
    checkpoint=None,
    control=None,
    audit: Optional[bool] = None,
    explain: bool = False,
    solver: Optional[bool] = None,
) -> PlanResult:
    """Minimum clone count of `new_node` deploying everything, via the
    incremental probe strategy described in the module docstring.

    `solver` (None = the SIMTPU_SOLVER default, off) consults the global
    solve backend (simtpu/solve, docs/solver.md) right after the shared
    tensorization: one vmapped convex relaxation over every candidate
    count.  An audit-certified solver answer ships directly (no base
    placement, no probes); a rejected one floors the resource lower
    bound with the solver's certified LP bound and the probe search runs
    as usual — always advisory, the auditor disposes.

    `explain` (off by default; the off path adds zero device dispatches)
    attaches the decision-observability block (simtpu/explain) to
    terminal failure results: the per-stage breakdown of the failing
    candidate's unplaced pods against its carried state, plus the
    binding-constraint bottleneck with the template verdict — the plan
    then reports *what to buy*, not just *how many*.

    `audit` (None = the SIMTPU_AUDIT default, on) runs the independent
    placement auditor (simtpu/audit) over the accepted candidate's fresh
    verify placement.  On audit failure the plan is NOT shipped: the
    candidate re-places through the serial exact scan (wavefront off,
    dense carry), re-audits, and the result carries a divergence
    diagnostic under `PlanResult.audit` — graceful degradation instead of
    a silently wrong answer (docs/robustness.md).  Audit requires the
    default `verify=True` path (the unverified fast path is explicitly
    uncertified).

    Matches `plan_capacity`'s contract (candidates 0..max_new_nodes-1,
    occupancy caps, can-never-help diagnostics, PlanResult shape); the
    per-candidate oracle differs as documented. `PlanResult.timings` carries
    the phase breakdown (tensorize / base / probes / verify / materialize)
    and `PlanResult.compiles` the per-phase jit-trace counts (the shape-
    bucketed probe sweep is expected to trace the round body at most twice
    across every candidate size).

    With `mesh` (a jax.sharding.Mesh), every placement — base, completion
    probes, and the fresh verify re-runs — executes node-sharded over the
    mesh's "nodes" axis (`MaskedShardedRoundsEngine`); the candidate
    node_valid mask composes with the sharding's dead-node pad mask, so
    placements are bit-identical to the single-device path.

    With `precompile`, one shared AOT pipeline (engine/precompile.py)
    background-compiles every executable the base run will need as soon as
    tensorization fixes the shape buckets — and each probe/verify engine
    re-enumerates against its own batch, deduplicating through the shared
    registry (probe chunks snap into base buckets, so they mostly find the
    base executables).  Placements are bit-identical either way; the
    per-phase `compiles` counts then attribute background traces to
    whatever phase is active when they run (timings gain
    compile_wall/compile_serial).  An internally-created pipeline is shut
    down on EVERY exit (cancelling enumerated-but-undispatched compiles —
    a raised plan must not leave the process lingering at exit finishing
    unused work); pass `pipeline=` (an AotPipeline, implies precompile) to
    share one registry across several plans — the caller then owns its
    lifecycle.
    """
    own_pipeline = None
    if pipeline is None and precompile:
        from ..engine.precompile import AotPipeline

        pipeline = own_pipeline = AotPipeline()
    try:
        return _plan_capacity_incremental(
            cluster, apps, new_node, max_new_nodes, extended_resources,
            progress, sched_config, corrected_ds_overhead, verify,
            materialize, mesh, pipeline, speculate, checkpoint, control,
            audit, explain, solver,
        )
    except PlanInterrupted as exc:
        # deadline / SIGINT between candidates (docs/robustness.md): the
        # structured partial result — every completed candidate is
        # already checkpointed, so a later --resume loses nothing
        from ..durable.deadline import partial_message

        best = getattr(exc, "best_candidate", None)
        out = PlanResult(
            False,
            -1 if best is None else best,
            None,
            partial_message(exc.reason, best, checkpoint),
            getattr(exc, "probes", {}),
            partial=True,
        )
        out.timings = getattr(exc, "timings", {})
        out.compiles = getattr(exc, "compiles", {})
        return out
    finally:
        if own_pipeline is not None:
            own_pipeline.shutdown()


def _plan_capacity_incremental(
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
    new_node: dict,
    max_new_nodes: int,
    extended_resources: Sequence[str],
    progress,
    sched_config,
    corrected_ds_overhead: bool,
    verify: bool,
    materialize: bool,
    mesh,
    pipeline,
    speculate,
    checkpoint,
    control,
    audit=None,
    explain=False,
    solver=None,
) -> PlanResult:
    from ..audit.checker import audit_enabled
    from ..engine.scan import COMPILE_COUNT_KINDS, statics_from
    from ..obs.metrics import family as metrics_family
    from ..parallel.sweep import assemble_planning_problem
    from ..solve import solver_enabled

    def trace_counts() -> Dict[str, int]:
        # per-kind jit-trace counters off the obs registry (the ISSUE-8
        # alias views are gone; this is the direct read)
        return metrics_family("compile", COMPILE_COUNT_KINDS)

    # the auditor certifies the ACCEPTED candidate's fresh verify
    # placement; the explicitly-unverified verify=False path stays
    # uncertified by design
    audit_on = (audit_enabled() if audit is None else bool(audit)) and verify

    say = progress or (lambda s: None)
    timings: Dict[str, float] = {}
    compiles: Dict[str, Dict[str, int]] = {}
    probes: Dict[int, int] = {}
    # the global-solver consult's record + the priority-ignored flag,
    # attached to EVERY result this plan returns (finalize)
    solve_doc: Dict[str, object] = {}
    preempt_flag = [False]
    fail_msg = f"we have added {max_new_nodes} nodes but it still failed!!"
    # the best candidate any probe/verify found feasible so far — what an
    # interrupted plan reports as its partial answer
    best_candidate: List[Optional[int]] = [None]

    def check() -> None:
        """Deadline/SIGINT poll at the candidate boundary; the raised
        PlanInterrupted carries the search progress so the wrapper can
        assemble the partial PlanResult."""
        if control is None:
            return
        try:
            control.check()
        except PlanInterrupted as exc:
            exc.probes = dict(probes)
            exc.timings = dict(timings)
            exc.compiles = dict(compiles)
            exc.best_candidate = best_candidate[0]
            raise

    def mark_compiles(phase: str, before: dict) -> None:
        after = trace_counts()
        prev = compiles.get(phase, {})
        compiles[phase] = {
            k: prev.get(k, 0) + after.get(k, 0) - before.get(k, 0)
            for k in after
        }

    def finalize(out: PlanResult) -> PlanResult:
        if pipeline is not None:
            s = pipeline.stats()
            timings["compile_wall"] = s["compile_wall_s"]
            timings["compile_serial"] = s["compile_serial_s"]
        out.timings = timings
        out.compiles = compiles
        if solve_doc and not out.solve:
            out.solve = dict(solve_doc)
        out.preemption_ignored = preempt_flag[0]
        return out

    t0 = time.perf_counter()
    max_new = max(max_new_nodes - 1, 0)  # reference walks i in [0, max)
    if checkpoint is not None:
        # pin the pod-name suffix stream to the problem fingerprint: the
        # ONE expansion below then produces identical pods (names
        # included) in the interrupted and the resuming process, which is
        # what makes the recorded placement vectors replayable across
        # processes (durable.checkpoint.name_seed)
        from ..durable.checkpoint import name_seed
        from ..workloads.expand import seed_name_hashes

        seed_name_hashes(name_seed(checkpoint.fingerprint))
    with span("plan.tensorize"):
        tz, all_nodes, n_base, ordered = assemble_planning_problem(
            cluster, apps, new_node, max_new, extended_resources
        )
        batch = tz.add_pods(ordered)
        tensors = tz.freeze()
        statics_from(tensors, sched_config)  # transfer device statics once
        vocab = _vocab_of(tensors)
        pin = np.asarray(batch.pin)
        clone_of = pin - n_base  # >= 0 for clone-pinned (DaemonSet) pods
    timings["tensorize"] = time.perf_counter() - t0

    # -- loud no-preemption notice (docs/status.md): probes never evict.
    # Capacity planning asks whether everything FITS — priority-bearing
    # specs plan fine, but their eviction semantics are ignored, and
    # that must be visible at runtime, not only in the docs.
    from ..core.objects import pod_priority

    if any(pod_priority(p) != 0 for p in ordered):
        import sys

        preempt_flag[0] = True
        notice = (
            "simtpu: specs carry pod priorities, but the incremental "
            "planner never runs preemption — priority/eviction semantics "
            "are IGNORED (use --search binary/linear for simulate()'s "
            "preemption path)"
        )
        print(notice, file=sys.stderr)
        say(notice)

    # -- global-solver consult (simtpu/solve, docs/solver.md): one
    # vmapped relaxation over every candidate count, on the SAME
    # tensorization the probes would use.  Accepted => the plan ships
    # here (no base placement, no probes); rejected => its certified LP
    # bound floors the resource lower bound below.  Checkpointed runs
    # skip it — solver answers are not candidate records.
    lb_solve = 0
    solver_on = solver_enabled() if solver is None else bool(solver)
    if solver_on and checkpoint is None:
        from ..solve import attempt_solve

        check()
        c0 = trace_counts()
        t_s = time.perf_counter()
        with span("solve"):
            att = attempt_solve(
                tz, tensors, batch, all_nodes, n_base, max_new,
                sched_config, say,
            )
        timings["solve"] = time.perf_counter() - t_s
        mark_compiles("solve", c0)
        solve_doc.update(att.doc)
        if att.accepted:
            probes[att.k] = 0
            best_candidate[0] = att.k
            result = None
            if materialize:
                t1 = time.perf_counter()
                result = _materialize(
                    tz, all_nodes, n_base + att.k, batch, att.nodes_arr,
                    att.reasons, clone_of, att.k, att.ext_log, att.gpu_arr,
                )
                timings["materialize"] = time.perf_counter() - t1
            out = PlanResult(True, att.k, result, "Success!", probes)
            out.audit = att.audit_doc
            return finalize(out)
        if att.certified:
            lb_solve = att.lower_bound
            if lb_solve > 0:
                say(
                    f"solver: certified lower bound {lb_solve} — flooring "
                    "the probe search"
                )

    # one shape-bucket registry for every engine of this plan: probes snap
    # their bulk chunks into buckets the base run (or an earlier probe)
    # already compiled, so the whole candidate sweep stays on warm
    # executables (engine/rounds.py `_bulk_chunk`)
    shape_registry: Dict = {}
    # ... and one AOT pipeline (when the wrapper created or was handed
    # one): every engine enumerates its batch's executables into the same
    # background-compile registry, so the base run's compiles start before
    # its first dispatch and the probe/verify engines find them finished
    # (engine/precompile.py)

    def make_engine(node_valid: np.ndarray, plan_batch=None):
        if mesh is not None:
            from ..parallel.sharded import MaskedShardedRoundsEngine

            eng = MaskedShardedRoundsEngine(tz, mesh, node_valid)
        else:
            eng = MaskedRoundsEngine(tz, node_valid)
        eng.sched_config = sched_config
        eng.bulk_shapes = shape_registry
        eng.snap_shapes = True
        if speculate is not None:
            eng.speculate = bool(speculate)
        if pipeline is not None and plan_batch is not None:
            from ..engine.precompile import precompile_place

            precompile_place(eng, plan_batch, pipeline)
        return eng

    def valid_mask(i: int) -> np.ndarray:
        m = np.ones(len(all_nodes), bool)
        m[n_base + i :] = False
        return m

    def _fallback_engine(i: int):
        """The serial exact referee the audit falls back to: pod-at-a-time
        scan, wavefront off, dense carry (docs/robustness.md)."""
        from ..engine.scan import Engine

        fb = Engine(tz)
        fb.node_valid = valid_mask(i)
        fb.speculate = False
        fb.compact = False
        fb.sched_config = sched_config
        return fb

    def _plane_diff(a_eng, b_eng):
        """Which carried-state planes the two engines' logs disagree on —
        the divergence diagnostic's state witness (engine/state.py
        diff_state_planes; audit-readable from-log views, no carries
        touched)."""
        from ..engine.state import build_state, diff_state_planes

        def dense(e):
            return build_state(
                tensors,
                np.asarray(e.placed_group, np.int32),
                np.asarray(e.placed_node, np.int32),
                e.log_req_matrix(r_res),
                e.ext_log,
            )

        return diff_state_planes(dense(a_eng), dense(b_eng))

    def mk_explain(eng, ebatch, erows, enodes, ereasons, i, base_nodes=None):
        """Decision-observability block for a failing candidate
        (simtpu/explain): per-stage breakdown against the engine's
        carried state + the bottleneck analysis with the template
        verdict.  {} when --explain was not requested (the off path
        dispatches nothing).  A checkpoint-replayed candidate has no
        carried state — it explains with the bottleneck block alone, its
        free capacity rebuilt from EVERY visible placement: probe call
        sites hand in `base_nodes` because their `enodes`/`ebatch` cover
        only the unplaced-from-base slice, and free derived from that
        slice alone would overstate capacity and misname the binding
        resource."""
        if not explain or not len(erows):
            return {}
        from ..explain import build_explain_doc

        all_ds = list(cluster.daemon_sets)
        for app in apps:
            all_ds += app.resource.daemon_sets
        try:
            state = eng.carried_state()
        except ValueError:
            state = None
        free = None
        if state is None:
            used = np.zeros(tensors.alloc.shape, np.float32)
            enodes_np = np.asarray(enodes)
            ereq = np.asarray(ebatch.req, np.float32)
            if ereq.shape[1] < r_res:
                ereq = np.pad(ereq, ((0, 0), (0, r_res - ereq.shape[1])))
            placed = np.flatnonzero(enodes_np >= 0)
            np.add.at(used, enodes_np[placed], ereq[placed])
            if base_nodes is not None:
                base_np = np.asarray(base_nodes)
                bplaced = np.flatnonzero(base_np >= 0)
                np.add.at(used, base_np[bplaced], req_pad[bplaced])
            free = tensors.alloc - used
        return build_explain_doc(
            tensors, ebatch, erows, state, np.asarray(enodes),
            np.asarray(ereasons), node_valid=valid_mask(i),
            sched_config=sched_config, new_node=new_node,
            daemon_sets=all_ds, corrected_ds_overhead=corrected_ds_overhead,
            free=free,
        )

    r_res = tensors.alloc.shape[1]
    req_pad = batch.req
    if req_pad.shape[1] < r_res:
        req_pad = np.pad(req_pad, ((0, 0), (0, r_res - req_pad.shape[1])))

    def replay_engine(i, rows, nodes_arr, lvm, dev, gpu, with_state):
        """An engine equivalent to one that just completed the recorded
        run (checkpoint resume): placement log + ext_log rebuilt from the
        record's placement vectors, and — when the caller needs the carry
        (the base candidate, whose snapshot seeds every probe) — the
        carried state rebuilt from that log, which is bit-identical to
        the dispatched carry (the donated-state reuse guard's pinned
        contract).  `rows` maps record positions to batch rows (None =
        identity: a full fresh run)."""
        from ..engine.state import build_state

        eng = make_engine(valid_mask(i))
        ok = np.flatnonzero(nodes_arr >= 0)
        rows_ok = ok if rows is None else np.asarray(rows)[ok]
        eng.placed_group = np.asarray(batch.group)[rows_ok].tolist()
        eng.placed_node = nodes_arr[ok].tolist()
        eng.placed_req = list(req_pad[rows_ok])
        eng.ext_log = {
            "node": nodes_arr[ok].tolist(),
            "vg_alloc": list(lvm[ok]),
            "sdev_take": list(dev[ok]),
            "gpu_shares": list(gpu[ok]),
            "gpu_mem": np.asarray(batch.ext["gpu_mem"])[rows_ok].tolist(),
        }
        if with_state:
            dense = build_state(
                tensors,
                np.asarray(eng.placed_group, np.int32),
                np.asarray(eng.placed_node, np.int32),
                eng.log_req_matrix(r_res),
                eng.ext_log,
            )
            eng.last_state = eng._store_state(tensors, dense)
            eng._last_vocab = vocab
            eng._state_dirty = False
        return eng

    def fresh_run(i: int, phase: str = "verify"):
        """Full placement of every pod against base + i clones (the
        reference's per-candidate semantics, minus re-tensorization).
        With a checkpoint, a completed record for (phase, i) replays
        instead of dispatching — the resume path."""
        rec = checkpoint.get(phase, i) if checkpoint is not None else None
        phantom = clone_of >= i
        if rec is not None:
            nodes = np.asarray(rec["nodes"])
            reasons = np.asarray(rec["reasons"])
            lvm, dev, gpu = (
                np.asarray(rec["lvm"]),
                np.asarray(rec["dev"]),
                np.asarray(rec["gpu"]),
            )
            eng = replay_engine(
                i, None, nodes, lvm, dev, gpu, with_state=(phase == "base")
            )
            failed = (nodes < 0) & ~phantom
            probes[i] = int(failed.sum())
            return eng, nodes, reasons, failed, {
                "lvm_alloc": lvm, "dev_take": dev, "gpu_shares": gpu,
            }
        check()
        c0 = trace_counts()
        with span("plan.candidate", count=int(i), phase=phase):
            eng = make_engine(valid_mask(i), plan_batch=batch)
            nodes, reasons, extras = eng.place(batch)
        failed = (nodes < 0) & ~phantom
        probes[i] = int(failed.sum())
        mark_compiles(phase, c0)
        if checkpoint is not None:
            checkpoint.put(
                phase, i,
                nodes=nodes, reasons=reasons, lvm=extras["lvm_alloc"],
                dev=extras["dev_take"], gpu=extras["gpu_shares"],
            )
        return eng, nodes, reasons, failed, extras

    # -- base candidate: i = 0 -------------------------------------------
    t0 = time.perf_counter()
    say("add 0 node(s)")
    with span("plan.base"):
        base_eng, base_nodes_arr, base_reasons, base_failed, base_extras = (
            fresh_run(0, phase="base")
        )
    timings["base"] = time.perf_counter() - t0

    def finish(i, eng, nodes_arr, reasons, extras):
        ok, reason = _caps_satisfied(
            tensors,
            batch.req[nodes_arr >= 0].sum(axis=0),
            valid_mask(i),
            vg_extra=float(
                np.asarray(eng.ext_log["vg_alloc"]).sum()
                if len(eng.ext_log["vg_alloc"])
                else 0.0
            ),
        )
        if not ok:
            say(reason.rstrip("\n"))
            return None
        nodes_arr = np.asarray(nodes_arr)
        reasons = np.asarray(reasons)
        ext_log = eng.ext_log
        gpu_arr = extras["gpu_shares"]
        audit_doc: Dict[str, object] = {}
        if audit_on:
            from ..audit.checker import (
                audit_placement,
                divergence_diagnostic,
                inject_divergence,
                inject_divergence_enabled,
            )

            phantom = clone_of >= i
            nodes_aud = nodes_arr
            if inject_divergence_enabled():
                nodes_aud = inject_divergence(tensors, batch, nodes_arr)
            rep = audit_placement(
                tensors, batch, nodes_aud, extras,
                node_valid=valid_mask(i), require_all=True,
                expect_mask=~phantom,
            )
            audit_doc = rep.counters()
            if not rep.ok:
                # divergence-safe fallback (docs/robustness.md): do NOT
                # ship the uncertified plan — re-place through the serial
                # exact scan, re-audit, and report the divergence
                say(
                    f"audit FAILED on the accepted candidate "
                    f"({rep.summary()}) — re-placing through the serial "
                    "exact scan"
                )
                fb = _fallback_engine(i)
                nodes_f, reasons_f, extras_f = fb.place(batch)
                nodes_f = np.asarray(nodes_f)
                rep_f = audit_placement(
                    tensors, batch, nodes_f, extras_f,
                    node_valid=valid_mask(i), require_all=True,
                    expect_mask=~phantom,
                )
                audit_doc = {
                    **rep.counters(),
                    "fallback": True,
                    "fallback_audit": rep_f.counters(),
                    "divergence": divergence_diagnostic(
                        tensors, batch, nodes_aud, nodes_f, rep,
                        planes=_plane_diff(eng, fb),
                    ),
                }
                if not rep_f.ok:
                    out = PlanResult(
                        False, i, None,
                        "audit failure: the accepted candidate violates "
                        "its claimed constraints and the serial-exact "
                        f"fallback did not certify either ({rep_f.summary()})",
                        probes,
                    )
                    out.audit = audit_doc
                    return finalize(out)
                audit_doc["ok"] = True
                nodes_arr, reasons = nodes_f, np.asarray(reasons_f)
                ext_log, gpu_arr = fb.ext_log, extras_f["gpu_shares"]
        result = None
        if materialize:
            t1 = time.perf_counter()
            result = _materialize(
                tz, all_nodes, n_base + i, batch, nodes_arr, reasons,
                clone_of, i, ext_log, gpu_arr,
            )
            timings["materialize"] = time.perf_counter() - t1
        out = PlanResult(True, i, result, "Success!", probes)
        out.audit = audit_doc
        return finalize(out)

    if probes[0] == 0:
        best_candidate[0] = 0
        done = finish(0, base_eng, base_nodes_arr, base_reasons, base_extras)
        if done is not None:
            return done
        # caps failed at 0: more nodes lower the average rate — keep searching
    u0 = np.flatnonzero(base_failed)

    def diagnose(failed_idx) -> Optional[str]:
        """Adding template nodes can never help (`apply.go:213-231`)."""
        from ..core.match import node_should_run_pod

        all_ds = list(cluster.daemon_sets)
        for app in apps:
            all_ds += app.resource.daemon_sets
        for j in failed_idx[:64]:  # a handful suffices for the message
            pod = ordered[int(j)]
            if not node_should_run_pod(new_node, pod):
                return (
                    f"failed to schedule pod {namespace_of(pod)}/{name_of(pod)}: "
                    "the pod cannot be scheduled successfully by adding node: "
                    "pod does not fit new node affinity or taints"
                )
            if not meet_resource_requests(
                new_node, pod, all_ds, corrected=corrected_ds_overhead
            ):
                return (
                    f"failed to schedule pod {namespace_of(pod)}/{name_of(pod)}: "
                    "new node cannot meet resource requests of pod: the total "
                    "requested resource of daemonset pods in new node is too large"
                )
        return None

    msg = diagnose(u0)
    if msg:
        out = PlanResult(False, 0, None, msg, probes)
        out.explain = mk_explain(
            base_eng, batch, u0, base_nodes_arr, base_reasons, 0
        )
        return finalize(out)
    if max_new == 0:
        # no candidate beyond 0 exists (max_new_nodes <= 1, apply.go's
        # exclusive upper bound) — the base failure is terminal
        out = PlanResult(False, max_new_nodes, None, fail_msg, probes)
        out.explain = mk_explain(
            base_eng, batch, u0, base_nodes_arr, base_reasons, 0
        )
        return finalize(out)

    # -- snapshot + cheap probes ------------------------------------------
    t0 = time.perf_counter()
    # the snapshot is the base engine's carry AS STORED — under the compact
    # layout (engine/state.py CompactState) that is the domain-tabular
    # form, and the probes inject it VERBATIM: place()'s reuse branch
    # expands a compact carry without donating or mutating it and then
    # stores a fresh carry, so the shared snapshot stays intact across
    # probes.  A dense snapshot must be copied per probe — the reuse
    # branch hands it straight to a donating dispatch.
    snapshot = base_eng.last_state
    copy_snapshot = (
        (lambda: snapshot)
        if isinstance(snapshot, CompactState)
        else (lambda: _copy_state(snapshot))
    )

    def probe(i: int) -> tuple:
        """Completion probe: from the base snapshot, place the clone
        DaemonSet pods for clones < i plus every base failure, in original
        order. Feasible iff all of them place.  With a checkpoint, a
        completed record for ("probe", i) replays instead of dispatching
        (idx is deterministic given the — itself checkpointed — base)."""
        idx = np.flatnonzero(base_failed | ((clone_of >= 0) & (clone_of < i)))
        rec = checkpoint.get("probe", i) if checkpoint is not None else None
        if rec is not None:
            nodes = np.asarray(rec["nodes"])
            reasons = np.asarray(rec["reasons"])
            lvm, dev, gpu = (
                np.asarray(rec["lvm"]),
                np.asarray(rec["dev"]),
                np.asarray(rec["gpu"]),
            )
            eng = replay_engine(i, idx, nodes, lvm, dev, gpu, with_state=False)
            failed = nodes < 0
            probes[i] = int(failed.sum())
            return eng, idx, nodes, reasons, failed, gpu
        check()
        say(f"add {i} node(s)")
        c0 = trace_counts()
        with span("plan.candidate", count=int(i), phase="probes"):
            probe_batch = slice_batch(batch, idx)
            eng = make_engine(valid_mask(i), plan_batch=probe_batch)
            eng.last_state = copy_snapshot()
            eng._last_vocab = vocab
            eng._state_dirty = False
            nodes, reasons, extras = eng.place(probe_batch)
        failed = nodes < 0
        probes[i] = int(failed.sum())
        mark_compiles("probes", c0)
        if checkpoint is not None:
            checkpoint.put(
                "probe", i,
                nodes=nodes, reasons=reasons, lvm=extras["lvm_alloc"],
                dev=extras["dev_take"], gpu=extras["gpu_shares"],
            )
        return eng, idx, nodes, reasons, failed, extras["gpu_shares"]

    # resource lower bound: the base failures must at least FIT the added
    # template capacity, DS overhead aside — probes below it cannot succeed
    lb = 1
    if len(u0):
        demand = batch.req[u0].sum(axis=0)
        cap = tensors.alloc[n_base]
        with np.errstate(divide="ignore", invalid="ignore"):
            need = np.where(demand > 0, demand / np.maximum(cap, 1e-30), 0.0)
        need_max = float(need.max())
        if not math.isfinite(need_max) or need_max >= max_new_nodes:
            # a demanded resource the template lacks, or a bound beyond the
            # cap: a single terminal probe decides (and diagnoses) failure
            lb = max_new
        else:
            lb = max(1, int(math.ceil(need_max - 1e-9)))
    # the solver's certified LP bound floors the resource bound — LP
    # feasibility is necessary for ANY placement, so probes below it are
    # wasted dispatches (simtpu/solve, docs/solver.md)
    lb = max(lb, lb_solve)
    # doubling from the bound, then bisection on the open interval; when the
    # very first probe (the resource lower bound) is feasible, try bound-1
    # next — the bound is usually tight, making the whole search 2 probes
    hi = None
    first_cand = cand = min(max(lb, 1), max_new)
    lo = 0  # 0 is known infeasible (or cap-failed)
    while True:
        if cand <= lo:
            break
        eng_i, idx_i, nodes_i, reasons_i, failed_i, gpu_i = probe(cand)
        if probes[cand] == 0:
            hi, hi_run = cand, (eng_i, idx_i, nodes_i, gpu_i)
            if best_candidate[0] is None or cand < best_candidate[0]:
                best_candidate[0] = cand
        else:
            lo = max(lo, cand)
            msg = diagnose(idx_i[failed_i])
            if msg:
                out = PlanResult(False, cand, None, msg, probes)
                out.explain = mk_explain(
                    eng_i, slice_batch(batch, idx_i),
                    np.flatnonzero(failed_i), nodes_i, reasons_i, cand,
                    base_nodes=base_nodes_arr,
                )
                return finalize(out)
        if hi is None:
            if cand >= max_new:
                out = PlanResult(False, max_new_nodes, None, fail_msg, probes)
                out.explain = mk_explain(
                    eng_i, slice_batch(batch, idx_i),
                    np.flatnonzero(np.asarray(failed_i)), nodes_i,
                    reasons_i, cand, base_nodes=base_nodes_arr,
                )
                return finalize(out)
            cand = min(cand * 2, max_new)
        elif hi == first_cand and lo == 0 and hi - 1 > lo:
            cand = hi - 1  # tight-bound fast path
        elif hi - lo > 1:
            cand = (lo + hi) // 2
        else:
            break
    timings["probes"] = time.perf_counter() - t0

    # -- reference-faithful confirmation ----------------------------------
    if verify:
        t0 = time.perf_counter()
        i = hi
        while i < max_new_nodes:
            say(f"verify {i} node(s) with a fresh placement")
            eng_v, nodes_v, reasons_v, failed_v, extras_v = fresh_run(i)
            if probes[i] == 0:
                if best_candidate[0] is None or i < best_candidate[0]:
                    best_candidate[0] = i
                timings["verify"] = time.perf_counter() - t0
                done = finish(i, eng_v, nodes_v, reasons_v, extras_v)
                if done is not None:
                    return done
                i += 1  # caps failed: monotone in node count, walk upward
                continue
            msg = diagnose(np.flatnonzero(failed_v))
            if msg:
                out = PlanResult(False, i, None, msg, probes)
                out.explain = mk_explain(
                    eng_v, batch, np.flatnonzero(failed_v), nodes_v,
                    reasons_v, i,
                )
                return finalize(out)
            i += 1
        out = PlanResult(False, max_new_nodes, None, fail_msg, probes)
        out.explain = mk_explain(
            eng_v, batch, np.flatnonzero(failed_v), nodes_v, reasons_v,
            max_new_nodes - 1,
        )
        return finalize(out)

    # -- incremental result: base placements + winning probe -------------
    eng_w, idx_w, nodes_w, gpu_w = hi_run
    nodes_all = base_nodes_arr.copy()
    nodes_all[idx_w] = nodes_w
    gpu_all = np.asarray(base_extras["gpu_shares"]).copy()
    if len(idx_w):
        gpu_all[idx_w] = gpu_w
    reasons_all = base_reasons.copy()
    ext_log = {
        k: list(base_eng.ext_log[k]) + list(eng_w.ext_log[k])
        for k in base_eng.ext_log
    }
    ok, reason = _caps_satisfied(
        tensors,
        batch.req[nodes_all >= 0].sum(axis=0),
        valid_mask(hi),
        vg_extra=float(
            np.asarray(ext_log["vg_alloc"]).sum() if len(ext_log["vg_alloc"]) else 0.0
        ),
    )
    if not ok:
        # rare unverified path with caps configured: fall back to fresh
        # upward walk for exact reference cap semantics
        say(reason.rstrip("\n"))
        i = hi + 1
        while i < max_new_nodes:
            eng_v, nodes_v, reasons_v, failed_v, extras_v = fresh_run(i)
            if probes[i] == 0:
                done = finish(i, eng_v, nodes_v, reasons_v, extras_v)
                if done is not None:
                    return done
            i += 1
        return finalize(PlanResult(False, max_new_nodes, None, fail_msg, probes))
    result = None
    if materialize:
        t1 = time.perf_counter()
        result = _materialize(
            tz, all_nodes, n_base + hi, batch, nodes_all, reasons_all,
            clone_of, hi, ext_log, gpu_all,
        )
        timings["materialize"] = time.perf_counter() - t1
    return finalize(PlanResult(True, hi, result, "Success!", probes))


def _materialize(
    tz,
    all_nodes: List[dict],
    n_nodes: int,
    batch: PodBatch,
    nodes_arr: np.ndarray,
    reasons: np.ndarray,
    clone_of: np.ndarray,
    n_clones: int,
    ext_log: dict,
    gpu_shares_arr,
) -> SimulateResult:
    """Assemble the SimulateResult for the winning candidate from the
    engine-level placement vector (one pass, no per-probe Python cost)."""
    from ..api import record_placed_pod, write_extended_annotations

    node_objs = [deep_copy(n) for n in all_nodes[:n_nodes]]
    write_extended_annotations(tz.ext, ext_log, node_objs)
    names = [name_of(n) for n in node_objs]
    by_node: List[List[dict]] = [[] for _ in range(n_nodes)]
    unscheduled: List[UnscheduledPod] = []
    gpu_shares_arr = np.asarray(gpu_shares_arr)
    phantom = clone_of >= n_clones
    for j in np.flatnonzero((nodes_arr >= 0) & ~phantom):
        pod = batch.pods[int(j)]
        by_node[int(nodes_arr[j])].append(
            record_placed_pod(pod, names[int(nodes_arr[j])], gpu_shares_arr[j])
        )
    for j in np.flatnonzero((nodes_arr < 0) & ~phantom):
        pod = batch.pods[int(j)]
        msg = REASON_TEXT.get(int(reasons[j]), "unschedulable")
        unscheduled.append(
            UnscheduledPod(
                pod=pod,
                reason=(
                    f"failed to schedule pod ({namespace_of(pod)}/{name_of(pod)}): "
                    f"Unschedulable: 0/{n_nodes} nodes are available: {msg}"
                ),
            )
        )
    statuses = [
        NodeStatus(node=n, pods=by_node[i]) for i, n in enumerate(node_objs)
    ]
    return SimulateResult(
        unscheduled_pods=unscheduled, node_status=statuses, preempted_pods=[]
    )
