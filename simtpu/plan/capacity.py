"""Capacity planner: minimum node additions for a full deployment.

Mirrors `Applier.Run` (`pkg/apply/apply.go:88-245`): load apps + cluster +
new-node template, then find the smallest number of template-node clones that
lets every pod schedule, subject to the MaxCPU/MaxMemory/MaxVG average
utilization caps (`apply.go:580-666`), with "adding nodes can never help"
diagnostics (`apply.go:213-231` → `utils.NodeShouldRunPod`,
`utils.MeetResourceRequests`).

Search strategy: the reference walks i = 0,1,2,…,100 re-simulating from
scratch each time (`apply.go:183`, `MaxNumNewNode=100`). Feasibility is
monotone in the clone count (clones only add capacity), so the default here is
a doubling probe + binary search — O(log N) full simulations instead of O(N) —
with `search="linear"` available for reference-exact behavior.

Non-monotone caveat (pinned by tests/test_plan.py): SCHEDULABILITY is
monotone, but the MaxCPU/MaxMemory/MaxVG occupancy-cap verdict need not be —
with DaemonSet overhead, every clone adds `u` usage against `A` capacity, so
the average rate tends toward u/A and RISES with the clone count whenever it
starts below that ratio.  A feasible window like {k0..k1} can then be jumped
over by the doubling probe, where the reference's linear walk would land
inside it.  The binary search therefore falls back LOUDLY to the
reference-exact linear scan the moment any probe is rejected by the caps
alone (everything scheduled, rate over the cap); probes already known
unschedulable are skipped in the fallback (schedulability stays monotone).
With the caps at their default 100 the fallback can never trigger.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import constants as C
from ..api import simulate
from ..config import SimonConfig, validate_config
from ..core.match import node_should_run_pod
from ..core.objects import (
    AppResource,
    ResourceTypes,
    SimulateResult,
    name_of,
    namespace_of,
    pod_requests,
    set_label,
)
from ..core.quantity import parse_quantity
from ..durable.deadline import PlanInterrupted
from ..io.cluster import (
    create_cluster_resource_from_client,
    create_cluster_resource_from_cluster_config,
    match_and_set_local_storage_annotation_on_node,
)
from ..io.yaml_loader import get_objects_from_yaml_content, get_yaml_content_from_directory
from ..obs.metrics import REGISTRY, SCHEMA_VERSION
from ..obs.trace import span
from ..workloads.expand import make_valid_node_by_node, new_daemon_pod


@dataclass
class PlanResult:
    success: bool
    nodes_added: int
    result: Optional[SimulateResult]
    message: str = ""
    # per-candidate-count unscheduled totals, for transparency
    probes: Dict[int, int] = field(default_factory=dict)
    # per-phase wall-clock seconds (ingest, plan), the observability the
    # reference lacks (SURVEY.md §5: vendored metrics exist but are never
    # exported)
    timings: Dict[str, float] = field(default_factory=dict)
    # the engines that actually ran (search strategy, bulk placement,
    # node-shard count, and whether the choice was automatic): auto engine
    # selection can change results vs the reference-exact path (bulk
    # tie-breaks, incremental's no-preemption semantics), and a stderr-only
    # notice is invisible to scripted/CI consumers — this rides the result
    # and the CLI's --json output
    engine: Dict[str, object] = field(default_factory=dict)
    # per-phase jit-trace counts from the incremental planner (base /
    # probes / verify, each {"rounds": n, "scan": m}) — the compile
    # observability behind the shape-bucketed probe sweep and bench.py's
    # cold-path tracking
    compiles: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # True when the plan was interrupted (deadline / SIGINT) and this
    # result reports only the best candidate verified BEFORE the
    # interrupt (nodes_added = that candidate, or -1 when none) — the
    # structured partial-result contract (docs/robustness.md); rides the
    # CLI's --json as "partial"
    partial: bool = False
    # the independent placement audit of the shipped candidate
    # (simtpu/audit, docs/robustness.md): AuditReport.counters() plus —
    # when the primary engine's answer failed its audit and the
    # serial-exact fallback shipped instead — "fallback": true and a
    # "divergence" diagnostic (first divergent pod, differing state
    # planes).  {} = audit not run (--no-audit / SIMTPU_AUDIT=0);
    # rides --json under engine.audit and decides the audit exit code
    audit: Dict[str, object] = field(default_factory=dict)
    # the unified metrics block (ISSUE 8, obs/metrics.py): one flat
    # name → value dict of every counter family's delta over this plan
    # (gauges report their end-of-plan level).  The legacy engine-block
    # fields above are aliases built FROM these values — bit-equal by
    # construction, kept for one release; rides --json as "metrics"
    metrics: Dict[str, object] = field(default_factory=dict)
    # layout stamp for --json consumers (obs.metrics.SCHEMA_VERSION):
    # bumped whenever the metrics block or any stable field changes
    # shape — pin on this, not on key probing
    schema_version: int = SCHEMA_VERSION
    # decision-observability block (simtpu/explain, `--explain`): the
    # per-stage failure breakdown of the reported candidate's unplaced
    # pods + the binding-constraint bottleneck analysis ("what to buy").
    # {} = not requested (the zero-cost default); carries its own
    # "version" stamp (explain.EXPLAIN_VERSION); rides --json as
    # "explain" and the flight recorder's exit-3/4 bundles
    explain: Dict[str, object] = field(default_factory=dict)
    # the global-solver backend's record (simtpu/solve, docs/solver.md):
    # status (accepted / accepted_fallback / rejected / infeasible /
    # ineligible), the certified lower bound it handed the exact search,
    # and the audit/fallback trail when its answer shipped.  {} = solver
    # not consulted (--no-solver / SIMTPU_SOLVER unset); rides --json
    # under engine.solve
    solve: Dict[str, object] = field(default_factory=dict)
    # True when the incremental planner received priority/preemption-
    # bearing specs: probes never run preemption (capacity planning asks
    # whether everything fits), so priority semantics were IGNORED — the
    # loud runtime counterpart of the docs/status.md note; rides --json
    # under engine.preemption_ignored
    preemption_ignored: bool = False


def new_fake_nodes(template: dict, count: int) -> List[dict]:
    """Clone the template node `count` times as simon-%02d with the new-node
    label (`pkg/apply/apply.go:286-303`)."""
    nodes = []
    for i in range(count):
        hostname = f"{C.NEW_NODE_NAME_PREFIX}-{i:02d}"
        node = make_valid_node_by_node(template, hostname)
        set_label(node, C.LABEL_NEW_NODE, "")
        nodes.append(node)
    return nodes


def _env_cap(name: str) -> int:
    """0-100 percentage cap from env; out-of-range falls back to 100
    (`apply.go:580-610`)."""
    raw = os.environ.get(name, "")
    if not raw:
        return 100
    val = int(raw)
    return 100 if (val > 100 or val < 0) else val


def satisfy_resource_setting(result: SimulateResult) -> (bool, str):
    """Average cluster occupancy caps MaxCPU/MaxMemory/MaxVG
    (`apply.go:580-666`)."""
    import json

    max_cpu = _env_cap(C.ENV_MAX_CPU)
    max_mem = _env_cap(C.ENV_MAX_MEMORY)
    max_vg = _env_cap(C.ENV_MAX_VG)

    total = {"cpu": 0.0, "memory": 0.0}
    used = {"cpu": 0.0, "memory": 0.0}
    vg_cap = vg_req = 0.0
    for status in result.node_status:
        alloc = ((status.node.get("status") or {}).get("allocatable")) or {}
        total["cpu"] += parse_quantity(alloc.get("cpu"))
        total["memory"] += parse_quantity(alloc.get("memory"))
        for pod in status.pods:
            req = pod_requests(pod)
            used["cpu"] += req.get("cpu", 0.0)
            used["memory"] += req.get("memory", 0.0)
        anno = (status.node.get("metadata") or {}).get("annotations") or {}
        raw = anno.get(C.ANNO_NODE_LOCAL_STORAGE)
        if raw:
            storage = json.loads(raw)
            for vg in storage.get("vgs") or []:
                vg_cap += parse_quantity(vg.get("capacity"))
                vg_req += parse_quantity(vg.get("requested"))

    cpu_rate = int(used["cpu"] / total["cpu"] * 100) if total["cpu"] else 0
    mem_rate = int(used["memory"] / total["memory"] * 100) if total["memory"] else 0
    if cpu_rate > max_cpu:
        return False, (
            f"the average occupancy rate({cpu_rate}%) of cpu goes beyond "
            f"the env setting({max_cpu}%)\n"
        )
    if mem_rate > max_mem:
        return False, (
            f"the average occupancy rate({mem_rate}%) of memory goes beyond "
            f"the env setting({max_mem}%)\n"
        )
    if vg_cap:
        vg_rate = int(vg_req / vg_cap * 100)
        if vg_rate > max_vg:
            return False, (
                f"the average occupancy rate({vg_rate}%) of vg goes beyond "
                f"the env setting({max_vg}%)\n"
            )
    return True, ""


def meet_resource_requests(
    node: dict, pod: dict, daemon_sets: Sequence[dict], corrected: bool = False
) -> bool:
    """Could the new-node template EVER hold this pod, once its daemonsets are
    accounted for? (`pkg/utils/utils.go:768-818`).

    Reference quirk preserved by default: the probe daemon pod is pinned to a
    node named `simon` (`utils.go:777` passes NewNodeNamePrefix as the node
    name), so unless the template node is literally named "simon" the
    matchFields pin fails NodeShouldRunPod and daemonset overhead contributes
    nothing — a DS-heavy cluster under-provisions exactly like the reference.
    `corrected=True` pins the probe pod to the template node's own name so
    the overhead is actually accounted (opt-in via `--corrected-ds-overhead`).
    """
    import json

    probe_name = name_of(node) if corrected else C.NEW_NODE_NAME_PREFIX
    total_cpu = total_mem = 0.0
    for ds in daemon_sets:
        daemon_pod = new_daemon_pod(ds, probe_name)
        if node_should_run_pod(node, daemon_pod):
            req = pod_requests(daemon_pod)
            total_cpu += req.get("cpu", 0.0)
            total_mem += req.get("memory", 0.0)
    req = pod_requests(pod)
    total_cpu += req.get("cpu", 0.0)
    total_mem += req.get("memory", 0.0)
    alloc = ((node.get("status") or {}).get("allocatable")) or {}
    if total_cpu > parse_quantity(alloc.get("cpu")) or total_mem > parse_quantity(
        alloc.get("memory")
    ):
        return False
    # local storage: sum of LVM claims must fit the largest VG
    anno = (node.get("metadata") or {}).get("annotations") or {}
    raw = anno.get(C.ANNO_NODE_LOCAL_STORAGE)
    if not raw:
        return True
    storage = json.loads(raw)
    vg_max = max(
        [parse_quantity(vg.get("capacity")) for vg in storage.get("vgs") or []] or [0.0]
    )
    pod_anno = (pod.get("metadata") or {}).get("annotations") or {}
    pvc_raw = pod_anno.get(C.ANNO_POD_LOCAL_STORAGE)
    pvc_sum = 0.0
    if pvc_raw:
        for vol in (json.loads(pvc_raw) or {}).get("volumes") or []:
            if vol.get("kind") == "LVM":
                pvc_sum += parse_quantity(vol.get("size"))
    return pvc_sum <= vg_max


def plan_capacity(
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
    new_node: dict,
    max_new_nodes: int = C.MAX_NUM_NEW_NODE,
    extended_resources: Sequence[str] = (),
    search: str = "binary",
    progress: Optional[Callable[[str], None]] = None,
    bulk: bool = False,
    sched_config=None,
    corrected_ds_overhead: bool = False,
    precompile: bool = False,
    checkpoint=None,
    control=None,
    audit: Optional[bool] = None,
    explain: bool = False,
    solver: Optional[bool] = None,
) -> PlanResult:
    """Find the minimum clone count of `new_node` that deploys everything.

    `solver` (None = the SIMTPU_SOLVER default, off) consults the global
    solve backend (simtpu/solve, docs/solver.md) FIRST: one vmapped
    convex relaxation over every candidate count replaces the whole
    doubling+bisection when its rounded answer is audit-certified at a
    count whose predecessor carries an infeasibility proof.  Advisory
    mode throughout — a rejected/uncertified solve falls through to the
    exact search below, warm-started with the solver's certified lower
    bound when one exists; the answer is then bit-identical to the
    solver-off run.

    `explain` (off by default — the off path adds zero device
    dispatches) attaches the decision-observability block
    (simtpu/explain) to the result: every live candidate simulation
    computes the failure breakdown + bottleneck analysis of its
    unplaced pods, and the reported candidate's block rides
    `PlanResult.explain` — so an infeasible plan reports *what to buy*
    (binding resource, template-node hint), not just *how many*.
    Deliberate cost shape: any candidate can turn out terminal (the
    diagnose failures return straight from the probe that hit them) and
    the Simulator closes inside simulate(), so each failing candidate
    pays its own explain pass — one vmapped dispatch per 64 unplaced
    pods, small against the full simulation it rides; fully-placed
    candidates pay nothing.

    `audit` (None = the SIMTPU_AUDIT default, on) runs the independent
    placement auditor (simtpu/audit) inside every candidate simulation
    and gates the WINNER on its verdict: an audit-dirty winner is never
    shipped — the candidate re-simulates through the serial exact engines
    (bulk off, wavefront off, dense carry), re-audits, and the result
    carries the divergence diagnostic under `PlanResult.audit`
    (docs/robustness.md).

    Durable execution (docs/robustness.md): with `checkpoint` (a
    `durable.checkpoint.PlanCheckpoint`) every completed candidate's
    verdict persists, and a resumed plan replays recorded candidates
    instead of re-simulating them (the winning candidate re-simulates
    once to materialize its SimulateResult — deterministic, so the
    PlanResult is bit-identical to the uninterrupted run).  With
    `control` (a `durable.deadline.RunControl`) the deadline/SIGINT check
    runs before each candidate; an interrupt yields a partial PlanResult
    (`partial=True`) instead of a traceback."""
    from ..audit.checker import audit_enabled, inject_divergence_enabled
    from ..solve import solver_enabled

    say = progress or (lambda s: None)
    probes: Dict[int, int] = {}
    # -- global-solver consult (simtpu/solve): solver proposes, auditor
    # disposes.  An accepted attempt IS the plan (no simulate() at all);
    # anything else warm-starts the exact search below.  Checkpointed
    # runs skip the solver — its answers are not candidate records.
    solve_doc: Dict[str, object] = {}
    lb_hint = 0
    solver_on = solver_enabled() if solver is None else bool(solver)
    if solver_on and checkpoint is None:
        from ..solve import solve_capacity_plan

        with span("solve"):
            plan_s, att = solve_capacity_plan(
                cluster, apps, new_node, max_new_nodes,
                extended_resources, progress=say, sched_config=sched_config,
            )
        if plan_s is not None:
            return plan_s
        solve_doc = att.doc
        if att.certified and att.lower_bound > 0:
            lb_hint = min(att.lower_bound, max_new_nodes - 1)
            say(
                f"solver: certified lower bound {att.lower_bound} — "
                "warm-starting the exact search"
            )
    all_daemon_sets = list(cluster.daemon_sets)
    for app in apps:
        all_daemon_sets += app.resource.daemon_sets
    best_candidate: list = [None]  # lowest candidate found feasible
    last_result: list = [None]  # most recent live SimulateResult
    audit_on = audit_enabled() if audit is None else bool(audit)
    # decision observability (simtpu/explain): the template context folds
    # the can-another-node-ever-help verdict into the bottleneck block
    explain_opts = (
        {
            "new_node": new_node,
            "daemon_sets": all_daemon_sets,
            "corrected": corrected_ds_overhead,
        }
        if explain
        else False
    )

    def with_explain(out: PlanResult, result) -> PlanResult:
        out.explain = getattr(result, "explain", None) or {}
        return out

    def run(i: int, serial_exact: bool = False) -> SimulateResult:
        say(f"add {i} node(s)")
        with span("plan.candidate", count=int(i), serial_exact=serial_exact):
            return _run_candidate(i, serial_exact)

    def _run_candidate(i: int, serial_exact: bool) -> SimulateResult:
        trial = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        trial.nodes = list(cluster.nodes) + new_fake_nodes(new_node, i)
        if serial_exact:
            # the divergence-safe fallback's engines: pod-at-a-time scan,
            # wavefront off, dense carry (docs/robustness.md) — never the
            # engine config whose answer just failed its audit
            from ..engine.scan import Engine

            def factory(tz):
                eng = Engine(tz)
                eng.speculate = False
                eng.compact = False
                return eng

            return simulate(
                trial,
                apps,
                extended_resources=extended_resources,
                engine_factory=factory,
                sched_config=sched_config,
                audit=True,
                explain=explain_opts,
            )
        result = simulate(
            trial,
            apps,
            extended_resources=extended_resources,
            bulk=bulk,
            sched_config=sched_config,
            precompile=precompile,
            audit=audit_on,
            explain=explain_opts,
            _audit_inject=audit_on and inject_divergence_enabled(),
        )
        probes[i] = len(result.unscheduled_pods)
        last_result[0] = result
        return result

    def diagnose(result: SimulateResult) -> Optional[str]:
        """Return a message when adding template nodes can never help
        (`apply.go:213-231`)."""
        for unsched in result.unscheduled_pods:
            pod = unsched.pod
            if not node_should_run_pod(new_node, pod):
                return (
                    f"failed to schedule pod {namespace_of(pod)}/{name_of(pod)}: "
                    "the pod cannot be scheduled successfully by adding node: "
                    "pod does not fit new node affinity or taints"
                )
            if not meet_resource_requests(
                new_node, pod, all_daemon_sets, corrected=corrected_ds_overhead
            ):
                return (
                    f"failed to schedule pod {namespace_of(pod)}/{name_of(pod)}: "
                    "new node cannot meet resource requests of pod: the total "
                    "requested resource of daemonset pods in new node is too large"
                )
        return None

    cap_rejected = False  # a probe scheduled everything but missed a cap

    def feasible(result: SimulateResult) -> Tuple[bool, str]:
        """Candidate acceptance = everything scheduled AND occupancy caps
        hold. The reference treats a cap miss like infeasibility
        (`apply.go:199-207`); schedulability is monotone in the clone
        count, but the cap verdict need NOT be (DaemonSet overhead — see
        the module docstring), so a cap rejection is flagged and aborts
        the O(log N) search in favor of the reference's linear walk."""
        nonlocal cap_rejected
        if result.unscheduled_pods:
            return False, ""
        ok, reason = satisfy_resource_setting(result)
        if not ok:
            cap_rejected = True
            say(reason.rstrip("\n"))
        return ok, reason

    def evaluate(i: int, need_result: bool = False):
        """(feasible, unscheduled, diagnosis, result) for candidate i —
        replayed from the checkpoint record when one exists (resume
        path; result is then None), else one live simulation, recorded
        afterwards.  `need_result` forces the live run: the winning
        candidate materializes its SimulateResult, and determinism makes
        the re-run bit-identical to the recorded verdict's run."""
        nonlocal cap_rejected
        rec = None if checkpoint is None else checkpoint.get("cand", i)
        if rec is not None and not need_result:
            probes[i] = int(rec["unscheduled"])
            if bool(rec["cap_rejected"]):
                cap_rejected = True
            ok = bool(rec["feasible"])
            msg = str(rec["message"]) or None
            if ok and (best_candidate[0] is None or i < best_candidate[0]):
                best_candidate[0] = i
            return ok, probes[i], msg, None
        if control is not None:
            control.check()
        if checkpoint is not None:
            # pin the pod-name suffix stream per candidate so a resumed
            # run's live evaluations expand the exact pods the
            # uninterrupted run's would — including the replayed winner's
            # re-materialization (durable.checkpoint.name_seed)
            from ..durable.checkpoint import name_seed
            from ..workloads.expand import seed_name_hashes

            seed_name_hashes(name_seed(checkpoint.fingerprint, i))
        result = run(i)
        ok, _ = feasible(result)
        msg = diagnose(result) if result.unscheduled_pods else None
        if checkpoint is not None:
            # a cap rejection is per-candidate (fully scheduled, cap
            # missed) — exactly the records whose replay must re-trigger
            # the linear fallback on resume
            checkpoint.put(
                "cand", i,
                unscheduled=probes[i], feasible=ok,
                cap_rejected=(not ok) and not result.unscheduled_pods,
                message=msg or "",
            )
        if ok and (best_candidate[0] is None or i < best_candidate[0]):
            best_candidate[0] = i
        return ok, probes[i], msg, result

    def final_success(i: int, result) -> PlanResult:
        if result is None:  # checkpoint-replayed winner: materialize live
            _, _, _, result = evaluate(i, need_result=True)
        out = with_explain(PlanResult(True, i, result, "Success!", probes), result)
        rep = getattr(result, "audit", None)
        if not audit_on or rep is None:
            return out
        out.audit = rep.counters()
        if rep.ok:
            return out
        # divergence-safe fallback: the winner's audit failed — do NOT
        # ship it; re-simulate through the serial exact engines and
        # re-audit (docs/robustness.md)
        say(
            f"audit FAILED on the winning candidate ({rep.summary()}) — "
            "re-simulating through the serial exact engines"
        )
        fb = run(i, serial_exact=True)
        rep_f = fb.audit
        audit_doc = {
            **rep.counters(),
            "fallback": True,
            "fallback_audit": rep_f.counters(),
            "divergence": _result_divergence(result, fb, rep),
        }
        if not rep_f.ok or fb.unscheduled_pods:
            out = with_explain(
                PlanResult(
                    False, i, fb,
                    "audit failure: the winning candidate violates its claimed "
                    "constraints and the serial-exact fallback did not certify "
                    f"either ({rep_f.summary()})",
                    probes,
                ),
                fb,
            )
            out.audit = audit_doc
            return out
        audit_doc["ok"] = True
        out.result = fb
        out.explain = getattr(fb, "explain", None) or {}
        out.audit = audit_doc
        return out

    def _result_divergence(primary, fallback, report) -> Dict[str, object]:
        """Divergence record for two SimulateResults.  Pod-name suffixes
        are process-random across separate simulations, so the diagnostic
        compares per-node pod counts rather than names."""

        def by_node(res):
            return {name_of(s.node): len(s.pods) for s in res.node_status}

        pa, fb = by_node(primary), by_node(fallback)
        changed = sorted(n for n in pa if pa.get(n) != fb.get(n))
        return {
            "violations": dict(report.by_class),
            "nodes_changed": len(changed),
            "first_changed_node": changed[0] if changed else "",
        }

    def linear_from(start: int) -> PlanResult:
        """The reference-exact linear walk over [start, max_new_nodes);
        candidates already probed and found UNSCHEDULABLE are skipped
        (schedulability is monotone — more clones cannot unschedule
        them... fewer cannot schedule them), cap-rejected ones re-run."""
        for i in range(start, max_new_nodes):
            if i in probes and probes[i] > 0:
                continue  # known unschedulable
            ok, unsched, msg, result = evaluate(i)
            if ok:
                return final_success(i, result)
            if unsched and msg:
                res = result or last_result[0]
                return with_explain(
                    PlanResult(False, i, res, msg, probes), res
                )
        return with_explain(
            PlanResult(False, max_new_nodes, last_result[0], fail_msg, probes),
            last_result[0],
        )

    fail_msg = f"we have added {max_new_nodes} nodes but it still failed!!"

    def search_candidates() -> PlanResult:
        nonlocal cap_rejected
        if lb_hint < 1:
            ok, unsched, msg, result = evaluate(0)
            if ok:
                return final_success(0, result)
            if unsched and msg:
                res = result or last_result[0]
                return with_explain(PlanResult(False, 0, res, msg, probes), res)
        # else: the solver PROVED candidate 0 (and everything below
        # lb_hint) infeasible — skip straight to the bound

        # the reference's loop is `for i := 0; i < MaxNumNewNode; i++`
        # (apply.go:183) — the largest candidate ever tried is
        # max_new_nodes-1
        if search == "linear":
            return linear_from(max(1, lb_hint))

        def cap_fallback() -> PlanResult:
            """A cap rejection makes feasibility potentially non-monotone —
            bisection could skip the window the reference's walk would
            find.  Fall back loudly to the linear scan (pinned by
            tests/test_plan.py's DaemonSet-overhead adversary)."""
            import sys

            msg = (
                "simtpu: an occupancy cap rejected a fully-scheduled "
                "candidate; cap feasibility can be non-monotone in the "
                "clone count (DaemonSet overhead) — falling back to the "
                "reference's linear scan"
            )
            print(msg, file=sys.stderr)
            say(msg)
            return linear_from(1)

        # doubling probe then binary search (feasibility monotone in
        # clone count); a certified solver lower bound starts the
        # doubling at the bound instead of 1
        hi, hi_result = None, None
        probe = max(1, lb_hint)
        while probe < max_new_nodes:
            ok, unsched, msg, result = evaluate(probe)
            if cap_rejected:
                return cap_fallback()
            if ok:
                hi, hi_result = probe, result
                break
            if unsched and msg:
                res = result or last_result[0]
                return with_explain(
                    PlanResult(False, probe, res, msg, probes), res
                )
            probe *= 2
        if hi is None:
            probe = max_new_nodes - 1
            if probe in probes:  # already tried as the last doubling step
                return with_explain(
                    PlanResult(
                        False, max_new_nodes, last_result[0], fail_msg, probes
                    ),
                    last_result[0],
                )
            ok, unsched, msg, result = evaluate(probe)
            if cap_rejected:
                return cap_fallback()
            if not ok:
                res = result or last_result[0]
                return with_explain(
                    PlanResult(False, max_new_nodes, res, fail_msg, probes),
                    res,
                )
            hi, hi_result = probe, result
        # lowest infeasible known is hi//2 (probed by the doubling, or 0)
        # — unless the solver certified everything below lb_hint
        lo = max(hi // 2, lb_hint - 1)
        while hi - lo > 1:
            mid = (lo + hi) // 2
            ok, _, _, result = evaluate(mid)
            if cap_rejected:
                return cap_fallback()
            if ok:
                hi, hi_result = mid, result
            else:
                lo = mid
        return final_success(hi, hi_result)

    def _with_solve(out: PlanResult) -> PlanResult:
        # a rejected/uncertified solver consult still rides the result —
        # --json consumers see WHY the exact search answered
        if solve_doc and not out.solve:
            out.solve = dict(solve_doc)
        return out

    try:
        return _with_solve(search_candidates())
    except PlanInterrupted as exc:
        # deadline / SIGINT between candidates: the structured partial
        # result — every completed candidate is already checkpointed
        from ..durable.deadline import partial_message

        best = best_candidate[0]
        return _with_solve(
            PlanResult(
                False,
                -1 if best is None else best,
                None,
                partial_message(exc.reason, best, checkpoint),
                probes,
                partial=True,
            )
        )


@dataclass
class ApplierOptions:
    """CLI options (`pkg/apply/apply.go:32-38`).

    `search` / `bulk` default to None = scale-aware auto: the reference's
    `simon apply` is ONE command that is always its fastest
    (`pkg/apply/apply.go:88,183`), so `simtpu apply` picks the engines
    itself — serial scan + binary search at conformance scale, bulk rounds
    + incremental search once the problem is large enough that the serial
    floor would dominate (see `_resolve_engines`)."""

    simon_config: str = ""
    default_scheduler_config: str = ""
    use_greed: bool = False
    interactive: bool = False
    extended_resources: Sequence[str] = ()
    search: Optional[str] = None  # None = auto; binary | linear | incremental
    bulk: Optional[bool] = None  # None = auto; place replica runs bulk
    # None = auto: shard the incremental planner's node axis over the device
    # mesh when more than one accelerator device is visible (placements are
    # bit-identical to the single-device path; CPU backends stay unsharded
    # unless forced — virtual CPU "devices" share one host's FLOPs)
    shard: Optional[bool] = None
    # None = auto: AOT-precompile each run's jit executables on a
    # background thread pool as soon as the shapes are known, so the cold
    # `simtpu apply` path overlaps compilation with host work instead of
    # serializing compiles at first dispatch (engine/precompile.py).  Auto
    # is ON for accelerator backends only — on CPU the "device" computes on
    # the same host cores the compiles need, so backgrounding them is pure
    # contention (measured slower), the same reasoning as the persistent
    # cache's CPU gating.  Placements are bit-identical either way;
    # --precompile forces it anywhere, --no-precompile disables.
    precompile: Optional[bool] = None
    # account daemonset overhead on the template node in the can-ever-fit
    # diagnostic (off = faithful to the reference's NewNodeNamePrefix quirk)
    corrected_ds_overhead: bool = False
    # durable execution (docs/robustness.md): checkpoint directory for
    # per-candidate plan records ("" = no checkpointing), `resume` replays
    # a prior run's records from it (fingerprint-guarded), `deadline`
    # bounds the plan's wall-clock in seconds (None = none), and
    # `install_sigint` makes the first ^C a graceful interrupt (partial
    # result + flushed checkpoint) — the CLI sets it; library callers
    # keep their own signal handling
    checkpoint: str = ""
    resume: bool = False
    deadline: Optional[float] = None
    install_sigint: bool = False
    # None = auto (the SIMTPU_AUDIT default, on): run the independent
    # placement auditor over the accepted candidate and fall back to the
    # serial exact engines on failure; False = --no-audit
    audit: Optional[bool] = None
    # None = the SIMTPU_SOLVER default (off): consult the global solve
    # backend (simtpu/solve) before the exact search — advisory mode,
    # the auditor gates everything it proposes; --solver forces it on,
    # --no-solver off (docs/solver.md)
    solver: Optional[bool] = None
    # decision observability (simtpu/explain, --explain): attach failure
    # breakdowns + the bottleneck analysis to the plan.  Off = zero cost
    # (no explain import, no extra device dispatch)
    explain: bool = False
    # observability (ISSUE 8, docs/observability.md): `trace` = output
    # path for a Perfetto-loadable Chrome trace of the run's spans
    # ("" = no trace file; arming leaves the process tracer on so a
    # later flight-recorder dump still sees the spans); `profile` = log
    # dir for a jax.profiler capture of the plan phase with span-named
    # TraceAnnotations ("" = SIMTPU_PROFILE env, else off)
    trace: str = ""
    profile: str = ""


# Auto-engine thresholds: below both, the serial scan keeps its per-pod
# reference-exact tie-breaks and compiles fastest; above either, the bulk
# rounds engine (~600x the serial rate at 100k nodes, BENCH_r04) and the
# incremental planner win by minutes.  Declared pods, not expanded: the
# estimate runs before workload expansion.
AUTO_ENGINE_NODES = 1024
AUTO_ENGINE_PODS = 16384


def _declared_pod_estimate(cluster: ResourceTypes, apps: Sequence[AppResource]) -> int:
    """Cheap upper-ish estimate of the expanded pod count: declared replica
    counts plus one DaemonSet pod per node, without running expansion."""

    def one(res: ResourceTypes, n_nodes: int) -> int:
        total = len(res.pods)
        for w in res.deployments + res.replica_sets + res.replication_controllers + res.stateful_sets:
            spec = w.get("spec") or {}
            total += int(spec.get("replicas") or 1)
        for j in res.jobs:
            spec = j.get("spec") or {}
            total += int(spec.get("completions") or spec.get("parallelism") or 1)
        for cj in res.cron_jobs:
            total += 1
        total += len(res.daemon_sets) * n_nodes
        return total

    n = len(cluster.nodes)
    return one(cluster, n) + sum(one(a.resource, n) for a in apps)


def _resolve_engines(
    opts: ApplierOptions,
    cluster: ResourceTypes,
    apps: Sequence[AppResource],
) -> Tuple[str, bool, Optional[object]]:
    """Fill in auto (None) search/bulk/shard choices from the problem size
    (and device topology) and say so loudly on stderr — the user should
    never need to know the flags to get the fast path, but must be able to
    see (and override) what was picked.  Returns (search, bulk, mesh) where
    mesh is a node-sharding device mesh for the incremental planner or
    None."""
    import sys

    n_nodes = len(cluster.nodes)
    est_pods = _declared_pod_estimate(cluster, apps)
    large = n_nodes >= AUTO_ENGINE_NODES or est_pods >= AUTO_ENGINE_PODS
    search = opts.search if opts.search is not None else ("incremental" if large else "binary")
    bulk = opts.bulk if opts.bulk is not None else large
    if large and (opts.search is None or opts.bulk is None):
        print(
            f"simtpu: large problem ({n_nodes} nodes, ~{est_pods} declared "
            f"pods) — auto-selected {'bulk' if bulk else 'serial'} placement"
            f" + {search} search; pass --search binary/linear or --no-bulk "
            "for the serial reference-exact engines",
            file=sys.stderr,
        )
    mesh = None
    if search == "incremental" and opts.shard is not False:
        import jax

        devices = jax.devices()
        # auto: only real accelerator meshes (virtual CPU devices split one
        # host's FLOPs — sharding there is a test vehicle, not a speedup)
        want = opts.shard is True or (
            opts.shard is None
            and len(devices) > 1
            and jax.default_backend() != "cpu"
        )
        if want:
            from ..parallel.mesh import planner_mesh

            mesh = planner_mesh()  # None on single-device topologies
            if mesh is not None and opts.shard is None:
                print(
                    f"simtpu: sharding the incremental plan's node axis over "
                    f"{len(devices)} devices; pass --no-shard for "
                    "single-device execution",
                    file=sys.stderr,
                )
    if opts.shard is True and mesh is None:
        # an explicit --shard that cannot be honored must be LOUD — a CI
        # job forcing the sharded path would otherwise silently validate
        # the unsharded one (same contract as the auto-engine notice)
        why = (
            "the search strategy is not 'incremental'"
            if search != "incremental"
            else "only one device is visible"
        )
        print(
            f"simtpu: --shard ignored ({why}); the plan runs unsharded",
            file=sys.stderr,
        )
    return search, bulk, mesh


class Applier:
    """End-to-end capacity-planning run (`pkg/apply/apply.go:55-245`)."""

    def __init__(self, opts: ApplierOptions):
        self.opts = opts
        self.config = SimonConfig.from_file(opts.simon_config)
        validate_config(self.config, opts.default_scheduler_config)

    def load_apps(self) -> List[AppResource]:
        apps = []
        for info in self.config.app_list:
            if info.chart:
                from .. import chart as chart_mod

                content = chart_mod.process_chart(info.name, info.path)
            else:
                content = get_yaml_content_from_directory(info.path)
            apps.append(
                AppResource(name=info.name, resource=get_objects_from_yaml_content(content))
            )
        return apps

    def load_cluster(self) -> ResourceTypes:
        if self.config.cluster.kube_config:
            return create_cluster_resource_from_client(self.config.cluster.kube_config)
        return create_cluster_resource_from_cluster_config(self.config.cluster.custom_config)

    def _sched_config(self):
        """Parse --default-scheduler-config when given
        (`pkg/simulator/utils.go:281` loads the file the same way)."""
        if not self.opts.default_scheduler_config:
            return None
        from ..schedconfig import SchedulerConfig

        return SchedulerConfig.from_file(self.opts.default_scheduler_config)

    def load_new_node(self) -> dict:
        content = get_yaml_content_from_directory(self.config.new_node)
        resources = get_objects_from_yaml_content(content)
        if not resources.nodes:
            raise ValueError(f"the new node directory({self.config.new_node}) has no nodes")
        match_and_set_local_storage_annotation_on_node(resources.nodes, self.config.new_node)
        return resources.nodes[0]

    def run(
        self,
        select_apps: Optional[Callable[[List[str]], List[str]]] = None,
        progress: Optional[Callable[[str], None]] = None,
    ) -> PlanResult:
        import contextlib
        import os
        import time as _time

        from ..obs import trace as obs_trace
        from ..obs.profile import profile_capture

        # --trace FILE arms the span tracer for this run (a tracer armed
        # earlier — SIMTPU_TRACE — keeps its buffer; the export below
        # only adds this run's output file).  Deliberately NOT disabled
        # afterwards: a failing exit's flight recorder (obs/flight.py)
        # reads the same buffer after run() returns.
        if self.opts.trace and not obs_trace.enabled():
            obs_trace.enable()

        timings: Dict[str, float] = {}
        t0 = _time.perf_counter()
        # the ingest span brackets exactly the wall the "ingest" timing
        # reports (spans and --json phase timings must reconcile); the
        # interactive selection's human think-time sits between two spans
        # just as it sits outside both timed regions
        sp_ingest = span("ingest")
        sp_ingest.__enter__()
        try:
            apps = self.load_apps()
            if select_apps is not None:
                # human think-time must not count toward the ingest phase
                timings["ingest"] = _time.perf_counter() - t0
                sp_ingest.__exit__(None, None, None)
                chosen = set(select_apps([a.name for a in apps]))
                apps = [a for a in apps if a.name in chosen]
                t0 = _time.perf_counter()
                sp_ingest = span("ingest")
                sp_ingest.__enter__()
            cluster = self.load_cluster()
            new_node = self.load_new_node()
            timings["ingest"] = (
                timings.get("ingest", 0.0) + _time.perf_counter() - t0
            )
        finally:
            # a load failure must still close the span: a leaked span is
            # never recorded AND corrupts the thread's nesting depth for
            # every later span — exactly on the failing runs a trace or
            # flight bundle is read to explain
            sp_ingest.__exit__(None, None, None)

        import jax

        # --profile DIR (or SIMTPU_PROFILE=DIR) captures a jax.profiler
        # trace of the plan phase, with TraceAnnotations named after the
        # spans (obs/profile.py).  Note: before ISSUE 8 the profiler dir
        # rode SIMTPU_TRACE — that name now arms the span tracer instead.
        profile_dir = self.opts.profile or os.environ.get("SIMTPU_PROFILE", "")
        ctx = profile_capture(profile_dir) if profile_dir else contextlib.nullcontext()
        from ..engine.scan import (
            fused_cascade_enabled,
            wave_enabled,
            wave_heavy_enabled,
        )
        from ..engine.state import delta_direct_enabled

        search, bulk, mesh = _resolve_engines(self.opts, cluster, apps)
        metrics_before = REGISTRY.snapshot()

        # durable execution (docs/robustness.md): per-candidate checkpoint
        # records under --checkpoint DIR, fingerprint-guarded resume, and
        # a deadline/SIGINT control polled at candidate boundaries
        checkpoint = None
        control = None
        if self.opts.checkpoint:
            from ..durable.checkpoint import (
                PlanCheckpoint,
                file_digest,
                plan_fingerprint,
            )

            fingerprint = plan_fingerprint(
                cluster, apps, new_node,
                extra={
                    "search": search,
                    "bulk": bool(bulk),
                    "extended_resources": list(self.opts.extended_resources),
                    "corrected_ds_overhead": self.opts.corrected_ds_overhead,
                    # CONTENT digest: editing the sched-config between a
                    # kill and a --resume must refuse, same path or not
                    "sched_config": file_digest(
                        self.opts.default_scheduler_config
                    ),
                    "caps": [
                        _env_cap(C.ENV_MAX_CPU),
                        _env_cap(C.ENV_MAX_MEMORY),
                        _env_cap(C.ENV_MAX_VG),
                    ],
                },
            )
            checkpoint = PlanCheckpoint(
                self.opts.checkpoint, kind=search, fingerprint=fingerprint,
                resume=self.opts.resume,
            )
        elif self.opts.resume:
            raise ValueError("--resume requires --checkpoint DIR")
        if self.opts.deadline is not None or self.opts.install_sigint:
            from ..durable.deadline import RunControl

            control = RunControl(deadline=self.opts.deadline)
        # auto-ON for apply on accelerator backends: the one-shot CLI user
        # always pays the cold path, which is exactly what the background
        # AOT pipeline attacks.  CPU backends stay off under auto (the
        # compiles would contend with the placement compute for the same
        # host cores; ApplierOptions.precompile documents the measurement)
        # — an explicit --precompile forces it anywhere.
        precompile = self.opts.precompile is True or (
            self.opts.precompile is None and jax.default_backend() != "cpu"
        )
        t0 = _time.perf_counter()
        sig_ctx = (
            control.sigint()
            if control is not None and self.opts.install_sigint
            else contextlib.nullcontext()
        )
        with ctx, sig_ctx, span("plan", search=search):
            if search == "incremental":
                from .incremental import plan_capacity_incremental

                plan = plan_capacity_incremental(
                    cluster,
                    apps,
                    new_node,
                    extended_resources=self.opts.extended_resources,
                    progress=progress,
                    sched_config=self._sched_config(),
                    corrected_ds_overhead=self.opts.corrected_ds_overhead,
                    mesh=mesh,
                    precompile=precompile,
                    checkpoint=checkpoint,
                    control=control,
                    audit=self.opts.audit,
                    explain=self.opts.explain,
                    solver=self.opts.solver,
                )
            else:
                plan = plan_capacity(
                    cluster,
                    apps,
                    new_node,
                    extended_resources=self.opts.extended_resources,
                    search=search,
                    progress=progress,
                    bulk=bulk,
                    sched_config=self._sched_config(),
                    corrected_ds_overhead=self.opts.corrected_ds_overhead,
                    precompile=precompile,
                    checkpoint=checkpoint,
                    control=control,
                    audit=self.opts.audit,
                    explain=self.opts.explain,
                    solver=self.opts.solver,
                )
        timings["plan"] = _time.perf_counter() - t0
        plan.timings = timings
        # machine-readable record of what actually ran (ADVICE r5: the
        # stderr notice alone is invisible to scripted consumers —
        # "search"/"bulk" distinguish the non-reference-exact fast path)
        from ..parallel.mesh import NODE_AXIS

        # the unified metrics block (ISSUE 8): one registry delta over
        # the plan — counters subtract, gauges report their end-of-plan
        # level — plus the shipped candidate's audit verdict under the
        # audit.* names (the registry's audit counters aggregate EVERY
        # candidate's pass; the block reports the one that shipped, the
        # same record engine.audit carries)
        metrics = REGISTRY.delta_since(metrics_before)
        if plan.audit:
            for k in ("ok", "checked", "violations", "wall_s", "mode"):
                if k in plan.audit:
                    metrics[f"audit.{k}"] = plan.audit[k]
        plan.metrics = metrics
        # the legacy engine-block families below are ALIAS VIEWS of the
        # metrics block — same numbers re-grouped, bit-equal by
        # construction; kept for one release (pin on schema_version)
        plan.engine = {
            "search": search,
            "bulk": bool(bulk) if search != "incremental" else True,
            "shards": int(mesh.shape[NODE_AXIS]) if mesh is not None else 0,
            "precompile": precompile,
            "auto_search": self.opts.search is None,
            "auto_bulk": self.opts.bulk is None,
            "reference_exact": search == "linear" and not bulk,
            # the speculative wavefront dispatcher's telemetry over this
            # plan's serial-engine dispatches (docs/speculation.md):
            # placements are bit-identical with it on or off, so this is
            # pure observability — acceptance rate and rollback volume
            "speculate": wave_enabled(),
            # round-16 A/B switches, recorded so scripted consumers can
            # detect the non-reference-exact fast paths from --json alone
            # (ADVICE r5 #1): heavy wavefront drafting, the fused
            # filter/score cascade, and the direct compact-delta apply —
            # placements are bit-identical under every combination
            "wave_heavy": wave_heavy_enabled(),
            "fused_cascade": fused_cascade_enabled(),
            "delta_direct": {
                "enabled": delta_direct_enabled(),
                "applied": metrics.get("state.delta_direct", 0),
                "expand": metrics.get("state.expand", 0),
                "compress": metrics.get("state.compress", 0),
            },
            "wavefront": {
                k: metrics.get(f"wavefront.{k}", 0)
                for k in (
                    "wavefronts", "pods", "accepted", "rollbacks",
                    "rollback_pods", "draft_hard",
                )
            },
            # transfer + carried-state byte telemetry (ISSUE 5): blocking
            # device→host round-trips and bytes this plan paid, plus the
            # final engine carry's per-plane byte breakdown under the
            # active layout (compact = the domain-tabular carry,
            # SIMTPU_COMPACT A/B — placements are identical either way)
            "fetch": {
                "get": metrics.get("fetch.get", 0),
                "bytes": metrics.get("fetch.bytes", 0),
            },
            # OOM-backoff telemetry (docs/robustness.md): caught
            # RESOURCE_EXHAUSTED events, the sub-dispatches their halving
            # replays created, and the smallest chunk any replay
            # re-dispatched at ("chunk_min" is a process-lifetime floor,
            # not a delta — 0 = no backoff this process)
            "backoff": {
                "events": metrics.get("backoff.events", 0),
                "splits": metrics.get("backoff.splits", 0),
                "chunk_min": metrics.get("backoff.chunk_min", 0),
            },
            # `compact` is the gauge's own record of what the final carry
            # actually was — NOT the SIMTPU_COMPACT default, which an
            # engine attribute or a spec with no tabular keys can override
            # (kept out of `state_bytes` so the byte breakdown holds only
            # the carried/dense/per-plane numbers, not a duplicate flag)
            "compact": metrics.get("state.compact", False),
            "state_bytes": {
                "carried_bytes": metrics.get("state.carried_bytes", 0),
                "dense_bytes": metrics.get("state.dense_bytes", 0),
                "planes": metrics.get("state.planes", {}),
            },
            # the independent placement audit of the shipped candidate
            # (simtpu/audit): counters, plus fallback/divergence records
            # when the primary engine's answer failed certification.
            # {"enabled": False} = --no-audit / SIMTPU_AUDIT=0
            "audit": plan.audit if plan.audit else {"enabled": False},
            # the global-solver backend's record (simtpu/solve): which
            # engine ANSWERED — an accepted status means the vmapped
            # relaxation produced the shipped plan; rejected/ineligible
            # means the exact search did (with the solver's certified
            # lower bound when one existed).  {"enabled": False} =
            # solver not consulted (--no-solver / SIMTPU_SOLVER unset)
            "solve": plan.solve if plan.solve else {"enabled": False},
            # loud runtime flag (docs/status.md): the incremental
            # planner's probes never run preemption, and this plan's
            # specs carried pod priorities — they were ignored
            "preemption_ignored": bool(
                getattr(plan, "preemption_ignored", False)
            ),
        }
        if self.opts.trace:
            from ..obs.trace import export_trace

            path = export_trace(self.opts.trace)
            if progress is not None:
                progress(f"span trace written to {path} (load in Perfetto)")
        return plan
