"""N+k survivability planning: the smallest cluster that still fits after
k failures.

`plan_capacity` answers "min template clones so everything fits"
(`pkg/apply/apply.go:183-233` semantics); production capacity reviews ask
the harder question — "min clones so everything STILL fits after any
(or a p-quantile of) k-node outages".  This module wraps the fault
subsystem (simtpu/faults) in the same search scaffolding as
`plan/capacity.py`:

- ONE tensorization of base + max clones
  (`parallel.sweep.assemble_planning_problem`), candidate membership via
  `node_valid` masks, shared bulk-shape registry across candidates — the
  incremental planner's levers;
- per candidate i: one bulk base placement (`MaskedRoundsEngine`), then
  one batched fault sweep (`faults.sweep.sweep_scenarios`) over scenarios
  generated on candidate i's live nodes (failures may hit clones too —
  an added node is as mortal as a base node);
- a candidate is FEASIBLE when the base placement strands nothing and at
  least `quantile` of its scenarios fully re-place after drain + requeue;
- doubling probe + bisection over the candidate count (`search="binary"`,
  the default), with `search="linear"` for the reference-shaped upward
  walk.

Monotonicity caveat (the same assumption `plan_capacity` documents for
schedulability): survivability is capacity-monotone, but with SAMPLED
scenario sets (k >= 2 on large clusters) each candidate is judged on its
own deterministic sample (seeded per candidate), so bisection can in
principle disagree with the linear walk near the boundary by sampling
noise.  Scenario seeds derive as `seed + candidate`, making every run
reproducible; raise `samples` or use `search="linear"` when the boundary
matters to the pod.

Preemption does not run inside the sweep (the drain asks whether
everything fits, the capacity-planning contract); `faults.drain_simulator`
is the eviction-semantics path.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np

from .. import constants as C
from ..core.objects import AppResource, ResourceTypes
from ..durable.deadline import PlanInterrupted
from ..faults.drain import PlacedCluster
from ..faults.scenarios import generate_scenarios
from ..faults.sweep import SweepResult, sweep_scenarios
from ..obs.trace import span
from .incremental import MaskedRoundsEngine


@dataclass
class ResiliencePlan:
    """Outcome of one `plan_resilience` search."""

    success: bool
    nodes_added: int
    k: int
    quantile: float
    message: str = ""
    #: per-candidate {"scenarios": S, "survived": n, "base_unplaced": m}
    probes: Dict[int, Dict[str, int]] = field(default_factory=dict)
    #: the winning candidate's sweep (None when the search failed)
    sweep: Optional[SweepResult] = None
    timings: Dict[str, float] = field(default_factory=dict)
    #: True when the search was interrupted (deadline / SIGINT) and this
    #: plan reports only the best candidate verified so far — the
    #: structured partial-result contract (docs/robustness.md)
    partial: bool = False
    #: independent placement audit of the winning candidate's base
    #: placement (simtpu/audit): AuditReport.counters(), plus fallback/
    #: divergence records when the bulk engine's answer failed its audit
    #: and the serial-exact fallback shipped instead.  {} = not audited
    audit: Dict[str, object] = field(default_factory=dict)
    #: decision-observability block (simtpu/explain, `--explain`): for a
    #: failed search, the last failing candidate's failure breakdown (base
    #: placement strands) or the worst scenario's binding-constraint
    #: bottleneck — *what to buy*, not just *how many*.  {} = not requested
    explain: Dict[str, object] = field(default_factory=dict)
    #: the global-solver backend's relax-only lower-bound record
    #: (simtpu/solve `solve_lower_bound`): the no-failure LP bound the
    #: doubling started from.  {} = solver not consulted
    solve: Dict[str, object] = field(default_factory=dict)

    def counters(self) -> Dict[str, object]:
        """Machine-readable summary (CLI --json, bench)."""
        out = {
            "success": self.success,
            "nodes_added": self.nodes_added,
            "k": self.k,
            "quantile": self.quantile,
            "candidates_probed": len(self.probes),
            "plan_resilience_s": round(self.timings.get("total_s", 0.0), 2),
        }
        if self.partial:
            out["partial"] = True
        if self.audit:
            out["audit"] = dict(self.audit)
        if self.explain:
            out["explain"] = dict(self.explain)
        if self.solve:
            out["solve"] = dict(self.solve)
        if self.sweep is not None:
            out.update(self.sweep.counters())
        return out


def _diagnose_doomed(
    sweep: SweepResult, batch, new_node: dict, all_ds, corrected: bool
):
    """Scenarios no clone count can rescue: a stranded pod that cannot EVER
    run on the template (`apply.go:213-231` semantics — affinity/taints or
    template capacity net of DaemonSet overhead).  Returns (doomed scenario
    count, message for the first doomed pod)."""
    from ..core.match import node_should_run_pod
    from ..core.objects import name_of, namespace_of
    from .capacity import meet_resource_requests

    doomed, msg = 0, None
    for s in np.flatnonzero(~sweep.survived):
        rows = sweep.requeue_rows[s]
        stranded = rows[(rows >= 0) & (sweep.requeue_nodes[s] < 0)]
        for j in stranded[:16]:  # a handful decides the scenario
            pod = batch.pods[int(j)]
            why = None
            if not node_should_run_pod(new_node, pod):
                why = (
                    "the pod cannot be scheduled successfully by adding "
                    "node: pod does not fit new node affinity or taints"
                )
            elif not meet_resource_requests(
                new_node, pod, all_ds, corrected=corrected
            ):
                why = (
                    "new node cannot meet resource requests of pod: the "
                    "total requested resource of daemonset pods in new "
                    "node is too large"
                )
            if why is not None:
                doomed += 1
                if msg is None:
                    msg = (
                        f"scenario {sweep.scenarios.labels[int(s)]!r} cannot "
                        f"be survived by adding nodes: pod "
                        f"{namespace_of(pod)}/{name_of(pod)}: {why}"
                    )
                break
    return doomed, msg


def plan_resilience(
    cluster: ResourceTypes,
    apps: Sequence[AppResource] = (),
    new_node: Optional[dict] = None,
    k: int = 1,
    quantile: float = 1.0,
    spec: Optional[str] = None,
    samples: int = 256,
    seed: int = 0,
    max_new_nodes: int = C.MAX_NUM_NEW_NODE,
    extended_resources: Sequence[str] = (),
    search: str = "binary",
    progress=None,
    sched_config=None,
    mesh=None,
    pipeline=None,
    s_chunk: Optional[int] = None,
    corrected_ds_overhead: bool = False,
    checkpoint=None,
    control=None,
    audit: Optional[bool] = None,
    explain: bool = False,
    solver: Optional[bool] = None,
) -> ResiliencePlan:
    """Minimum clone count of `new_node` whose cluster still fully places
    every workload under the failure model.

    The failure model is `spec` (a `faults.parse_fault_spec` string) when
    given, else ``k=<k>`` — sampled/exhaustive k-node outages.  A candidate
    passes when its base placement strands nothing AND the surviving
    fraction of its scenario sweep is >= `quantile` (1.0 = every scenario).
    `new_node=None` assesses only the as-is cluster (candidate 0) and
    reports success/failure without searching.

    Durable execution (docs/robustness.md): with `checkpoint` (a
    `durable.checkpoint.PlanCheckpoint`) every completed candidate's sweep
    verdict persists, and a resumed search replays recorded candidates
    (the winner re-sweeps once to materialize its SweepResult —
    deterministic seeds make the replayed plan bit-identical).  With
    `control` (a `durable.deadline.RunControl`) the deadline/SIGINT poll
    runs before each candidate; an interrupt yields a partial
    ResiliencePlan (`partial=True`) instead of a traceback.

    `audit` (None = the SIMTPU_AUDIT default, on) certifies the WINNING
    candidate's base placement through the independent auditor
    (simtpu/audit).  An audit-dirty winner is never shipped: the base
    placement re-runs through the serial exact scan, re-audits, and the
    sweep re-runs over the certified placement, with the divergence
    diagnostic under `ResiliencePlan.audit` (docs/robustness.md).

    `solver` (None = the SIMTPU_SOLVER default, off) consults the solve
    backend's relax-only lower bound (simtpu/solve `solve_lower_bound`):
    the no-failure fit is necessary for survivability — failures only
    remove capacity — so a certified LP infeasibility proof at count j
    rules out every candidate <= j, and the doubling starts at the
    bound instead of 1 (docs/solver.md)."""
    from ..engine.scan import statics_from
    from ..parallel.sweep import assemble_planning_problem
    from ..solve import solve_lower_bound, solver_enabled

    say = progress or (lambda s: None)
    t_start = time.perf_counter()
    timings: Dict[str, float] = {}
    fault_spec = spec if spec is not None else f"k={k}"
    from ..faults.scenarios import parse_fault_spec

    # the reported k is the largest failure size the spec names (domain
    # outages fail whole label domains; their size is scenario-dependent)
    k = max(
        [t["k"] for t in parse_fault_spec(fault_spec) if t["kind"] == "k"],
        default=k,
    )
    max_new = max(max_new_nodes - 1, 0) if new_node is not None else 0
    template = new_node if new_node is not None else cluster.nodes[0]
    t0 = time.perf_counter()
    if checkpoint is not None:
        # pin the pod-name suffix stream to the problem fingerprint so the
        # one expansion below matches across the interrupted and resuming
        # processes (durable.checkpoint.name_seed; see plan/incremental.py)
        from ..durable.checkpoint import name_seed
        from ..workloads.expand import seed_name_hashes

        seed_name_hashes(name_seed(checkpoint.fingerprint))
    tz, all_nodes, n_base, ordered = assemble_planning_problem(
        cluster, apps, template, max_new, extended_resources
    )
    batch = tz.add_pods(ordered)
    tensors = tz.freeze()
    statics_from(tensors, sched_config)  # transfer device statics once
    pin = np.asarray(batch.pin)
    clone_of = pin - n_base  # >= 0 for clone-pinned (DaemonSet) pods
    timings["tensorize"] = time.perf_counter() - t0

    # relax-only solver consult: a certified no-failure LP bound floors
    # the candidate search (survivability requires the base fit)
    solve_doc: Dict[str, object] = {}
    lb_solve = 0
    solver_on = solver_enabled() if solver is None else bool(solver)
    if solver_on and new_node is not None and max_new > 0:
        t_s = time.perf_counter()
        lb_solve, solve_doc = solve_lower_bound(
            tensors, batch, n_base, len(all_nodes), max_new
        )
        solve_doc["wall_s"] = round(time.perf_counter() - t_s, 4)
        lb_solve = min(lb_solve, max_new)
        if lb_solve > 0:
            say(
                f"solver: certified no-failure lower bound {lb_solve} — "
                "starting the candidate search there"
            )

    # one bulk-shape registry across every candidate's engine, the
    # incremental planner's warm-executable lever
    shape_registry: Dict = {}
    probes: Dict[int, Dict[str, int]] = {}
    sweeps: Dict[int, SweepResult] = {}
    all_ds = list(cluster.daemon_sets)
    for app in apps:
        all_ds += app.resource.daemon_sets

    class _Doomed(Exception):
        """A failure scenario no clone count can rescue forces the
        quantile unreachable — abort the search with the diagnosis."""

    def valid_mask(i: int) -> np.ndarray:
        m = np.ones(len(all_nodes), bool)
        m[n_base + i :] = False
        return m

    best_candidate: list = [None]  # lowest candidate found surviving
    # the last candidate a live probe found FAILING — what a failed
    # search's --explain block describes (simtpu/explain)
    last_fail: Dict[str, object] = {}
    # artifacts of the best OK candidate's live base placement — what the
    # winner audit certifies (one slot: worse candidates are dropped)
    best_run: Dict[str, object] = {}
    from ..audit.checker import audit_enabled

    audit_on = audit_enabled() if audit is None else bool(audit)

    def probe(i: int, need_sweep: bool = False) -> bool:
        """Base placement + fault sweep for candidate i; True = survives.

        With a checkpoint, a completed record for ("resil", i) replays its
        verdict instead of dispatching (scenario seeds are `seed + i`, so
        the recorded sweep is the one a live run would produce);
        `need_sweep` forces the live run — the winning candidate
        materializes its SweepResult for the report."""
        rec_d = None if checkpoint is None else checkpoint.get("resil", i)
        if rec_d is not None and not need_sweep:
            rec = {
                "scenarios": int(rec_d["scenarios"]),
                "survived": int(rec_d["survived"]),
                "base_unplaced": int(rec_d["base_unplaced"]),
            }
            probes[i] = rec
            if bool(rec_d["doomed"]):
                raise _Doomed(str(rec_d["message"]))
            ok = bool(rec_d["ok"])
            if ok and (best_candidate[0] is None or i < best_candidate[0]):
                best_candidate[0] = i
            return ok
        if control is not None:
            control.check()
        say(f"resilience probe: {i} node(s) added, faults={fault_spec}")
        valid = valid_mask(i)
        if mesh is not None:
            from ..parallel.sharded import MaskedShardedRoundsEngine

            eng = MaskedShardedRoundsEngine(tz, mesh, valid)
        else:
            eng = MaskedRoundsEngine(tz, valid)
        eng.sched_config = sched_config
        eng.bulk_shapes = shape_registry
        eng.snap_shapes = True
        with span("plan.candidate", count=int(i), phase="resilience"):
            nodes, reasons, extras = eng.place(batch)
        nodes = np.asarray(nodes)
        phantom = clone_of >= i
        base_unplaced = int(((nodes < 0) & ~phantom).sum())
        rec = {"scenarios": 0, "survived": 0, "base_unplaced": base_unplaced}
        probes[i] = rec

        def record(ok: bool, doomed_msg: str = "") -> None:
            if checkpoint is not None:
                checkpoint.put(
                    "resil", i, ok=ok,
                    scenarios=rec["scenarios"], survived=rec["survived"],
                    base_unplaced=rec["base_unplaced"],
                    doomed=bool(doomed_msg), message=doomed_msg,
                )

        if base_unplaced:
            record(False)
            if explain:
                # retained ONLY under --explain: the engine pins its
                # carried device state alive for the rest of the search
                last_fail.update(
                    i=i, eng=eng, nodes=nodes, reasons=np.asarray(reasons)
                )
            return False
        pc = PlacedCluster(
            tz=tz, tensors=tensors, batch=batch, engine=eng,
            nodes=nodes, reasons=np.asarray(reasons),
        )
        scen = generate_scenarios(
            all_nodes, fault_spec, samples=samples, seed=seed + i, valid=valid
        )
        sweep = sweep_scenarios(
            pc, scen, s_chunk=s_chunk, mesh=mesh, pipeline=pipeline
        )
        sweeps[i] = sweep
        rec["scenarios"] = len(scen)
        rec["survived"] = int(sweep.survived.sum())
        ok = sweep.survival_rate >= quantile - 1e-12
        if not ok and new_node is not None:
            doomed, msg = _diagnose_doomed(
                sweep, batch, new_node, all_ds, corrected_ds_overhead
            )
            if doomed and (len(scen) - doomed) / len(scen) < quantile - 1e-12:
                record(False, doomed_msg=msg or "")
                raise _Doomed(msg)
        record(ok)
        if not ok and explain:
            # see above: only --explain pays the retained-engine memory
            last_fail.update(
                i=i, eng=eng, nodes=nodes, reasons=np.asarray(reasons)
            )
        # <= : the winner's finish() re-probe (checkpoint-replayed runs
        # materialize the sweep live) must also refresh the audit
        # artifacts, or a resumed plan would ship unaudited
        if ok and (best_candidate[0] is None or i <= best_candidate[0]):
            best_candidate[0] = i
            best_run.update(
                i=i, eng=eng, nodes=nodes, reasons=np.asarray(reasons),
                extras=extras,
            )
        return ok

    def _audit_winner(i: int):
        """Certify the winning candidate's base placement; on failure
        re-place through the serial exact scan, re-audit, and re-sweep
        over the certified placement (the divergence-safe fallback).
        Returns (audit_doc, hard_failure_message_or_None)."""
        from ..audit.checker import (
            audit_placement,
            divergence_diagnostic,
            inject_divergence,
            inject_divergence_enabled,
        )
        from ..engine.state import build_state, diff_state_planes

        eng = best_run["eng"]
        nodes = np.asarray(best_run["nodes"])
        phantom = clone_of >= i
        valid = valid_mask(i)
        nodes_aud = nodes
        if inject_divergence_enabled():
            nodes_aud = inject_divergence(tensors, batch, nodes)
        rep = audit_placement(
            tensors, batch, nodes_aud, best_run["extras"],
            node_valid=valid, require_all=True, expect_mask=~phantom,
        )
        if rep.ok:
            return rep.counters(), None
        say(
            f"audit FAILED on the winning candidate ({rep.summary()}) — "
            "re-placing through the serial exact scan"
        )
        from ..engine.scan import Engine

        fb = Engine(tz)
        fb.node_valid = valid
        fb.speculate = False
        fb.compact = False
        fb.sched_config = sched_config
        nodes_f, reasons_f, extras_f = fb.place(batch)
        nodes_f = np.asarray(nodes_f)
        rep_f = audit_placement(
            tensors, batch, nodes_f, extras_f,
            node_valid=valid, require_all=True, expect_mask=~phantom,
        )
        r = tensors.alloc.shape[1]

        def dense(e):
            return build_state(
                tensors,
                np.asarray(e.placed_group, np.int32),
                np.asarray(e.placed_node, np.int32),
                e.log_req_matrix(r),
                e.ext_log,
            )

        audit_doc = {
            **rep.counters(),
            "fallback": True,
            "fallback_audit": rep_f.counters(),
            "divergence": divergence_diagnostic(
                tensors, batch, nodes_aud, nodes_f, rep,
                planes=diff_state_planes(dense(eng), dense(fb)),
            ),
        }
        if not rep_f.ok:
            return audit_doc, (
                "audit failure: the winning candidate violates its claimed "
                "constraints and the serial-exact fallback did not certify "
                f"either ({rep_f.summary()})"
            )
        # certified fallback placement: the survivability verdict must
        # describe IT, so the winner's sweep re-runs over it
        audit_doc["ok"] = True
        pc = PlacedCluster(
            tz=tz, tensors=tensors, batch=batch, engine=fb,
            nodes=nodes_f, reasons=np.asarray(reasons_f),
        )
        scen = generate_scenarios(
            all_nodes, fault_spec, samples=samples, seed=seed + i, valid=valid
        )
        sweeps[i] = sweep_scenarios(
            pc, scen, s_chunk=s_chunk, mesh=mesh, pipeline=pipeline
        )
        rec = probes.get(i) or {}
        rec["survived"] = int(sweeps[i].survived.sum())
        if sweeps[i].survival_rate < quantile - 1e-12:
            return audit_doc, (
                "audit fallback: the serial-exact placement does not "
                "survive the failure model "
                f"({rec['survived']}/{len(scen)} scenarios place fully)"
            )
        return audit_doc, None

    def finish(i: int) -> ResiliencePlan:
        if (i not in sweeps or best_run.get("i") != i) and (
            audit_on or i not in sweeps
        ):
            # checkpoint-replayed winner (or artifacts dropped): one live
            # re-probe materializes its SweepResult and the audit
            # artifacts (deterministic — seeds are `seed + i`)
            probe(i, need_sweep=True)
        audit_doc: Dict[str, object] = {}
        if audit_on and best_run.get("i") == i:
            audit_doc, hard_fail = _audit_winner(i)
            if hard_fail is not None:
                timings["total_s"] = time.perf_counter() - t_start
                out = ResiliencePlan(
                    False, i, k, quantile, hard_fail,
                    probes=probes, sweep=sweeps.get(i), timings=timings,
                )
                out.audit = audit_doc
                out.solve = solve_doc
                return out
        timings["total_s"] = time.perf_counter() - t_start
        out = ResiliencePlan(
            True, i, k, quantile, "Success!",
            probes=probes, sweep=sweeps.get(i), timings=timings,
        )
        out.audit = audit_doc
        out.solve = solve_doc
        return out

    def interrupted(exc: PlanInterrupted) -> ResiliencePlan:
        # deadline / SIGINT between candidates: the structured partial
        # result — every completed candidate is already checkpointed
        from ..durable.deadline import partial_message

        best = best_candidate[0]
        msg = partial_message(
            exc.reason, best, checkpoint, what="resilience plan",
            none_note="no surviving candidate found yet",
        )
        timings["total_s"] = time.perf_counter() - t_start
        out = ResiliencePlan(
            False, -1 if best is None else best, k, quantile, msg,
            probes=probes, sweep=None, timings=timings, partial=True,
        )
        out.solve = solve_doc
        return out

    def mk_explain() -> Dict[str, object]:
        """The failed search's decision-observability block
        (simtpu/explain): when the last failing candidate's BASE placement
        stranded pods, the full per-stage breakdown + bottleneck; when its
        base placed clean but a scenario sweep failed, the worst
        scenario's binding-constraint bottleneck over its stranded set
        (free capacity = the drained surviving cluster)."""
        if not explain or not last_fail:
            return {}
        from ..explain import EXPLAIN_VERSION, bottleneck_analysis, build_explain_doc

        i = int(last_fail["i"])
        eng = last_fail["eng"]
        nodes = np.asarray(last_fail["nodes"])
        reasons_a = np.asarray(last_fail["reasons"])
        valid = valid_mask(i)
        phantom = clone_of >= i
        doc: Dict[str, object] = {"version": EXPLAIN_VERSION}
        unp = np.flatnonzero((nodes < 0) & ~phantom)
        if len(unp):
            try:
                state = eng.carried_state()
            except ValueError:
                state = None
            return build_explain_doc(
                tensors, batch, unp, state, nodes, reasons_a,
                node_valid=valid, sched_config=sched_config,
                new_node=new_node, daemon_sets=all_ds,
                corrected_ds_overhead=corrected_ds_overhead,
            )
        sweep = sweeps.get(i)
        if sweep is None:
            return doc
        s_idx = int(np.argmax(sweep.unplaced))
        rows_s = np.asarray(sweep.requeue_rows[s_idx])
        nodes_s = np.asarray(sweep.requeue_nodes[s_idx])
        reasons_s = np.asarray(sweep.requeue_reasons[s_idx])
        live = rows_s >= 0
        stranded = rows_s[live & (nodes_s < 0)]
        if not len(stranded):
            return doc
        # the drained cluster's final placement: requeued pods move to
        # their landing nodes, pods that died with a failed node vacate
        alive = valid & ~np.asarray(sweep.scenarios.masks[s_idx], bool)
        nodes_final = nodes.copy()
        nodes_final[rows_s[live]] = nodes_s[live]
        on_failed = (nodes_final >= 0) & ~alive[np.clip(nodes_final, 0, None)]
        nodes_final[on_failed] = -1
        reasons_full = np.zeros(len(nodes), np.int32)
        reasons_full[rows_s[live]] = reasons_s[live]
        doc["worst_scenario"] = sweep.scenarios.labels[s_idx]
        doc["bottleneck"] = bottleneck_analysis(
            tensors, batch, nodes_final, reasons_full, rows=stranded,
            node_valid=alive, new_node=new_node, daemon_sets=all_ds,
            corrected_ds_overhead=corrected_ds_overhead,
        )
        return doc

    def fail(msg: str) -> ResiliencePlan:
        timings["total_s"] = time.perf_counter() - t_start
        out = ResiliencePlan(
            False, max_new_nodes, k, quantile, msg, probes=probes,
            sweep=None, timings=timings,
        )
        out.explain = mk_explain()
        out.solve = solve_doc
        return out

    fail_msg = (
        f"we have added {max_new_nodes} nodes but the workloads still do "
        f"not survive {fault_spec} failures!!"
    )
    t0 = time.perf_counter()
    try:
        # a certified solver bound >= 1 proves candidate 0's base fit
        # impossible — its probe is a wasted placement
        if lb_solve < 1 and probe(0):
            timings["search"] = time.perf_counter() - t0
            return finish(0)
        if new_node is None:
            timings["search"] = time.perf_counter() - t0
            rec = probes[0]
            return fail(
                "cluster does not survive the failure model "
                f"({rec['survived']}/{rec['scenarios']} scenarios place fully, "
                f"{rec['base_unplaced']} pods unplaced before any failure)"
            )

        if search == "linear":
            for i in range(max(1, lb_solve), max_new + 1):
                if probe(i):
                    timings["search"] = time.perf_counter() - t0
                    return finish(i)
            timings["search"] = time.perf_counter() - t0
            return fail(fail_msg)

        # doubling probe then bisection (survivability capacity-monotone,
        # the plan_capacity scaffolding; see the module docstring's
        # sampling caveat)
        hi = None
        cand = max(1, lb_solve)
        while cand <= max_new:
            if probe(cand):
                hi = cand
                break
            cand *= 2
        if hi is None:
            if max_new >= 1 and max_new not in probes and probe(max_new):
                hi = max_new
            else:
                timings["search"] = time.perf_counter() - t0
                return fail(fail_msg)
        lo = max(
            [i for i in probes if i < hi and not _passed(probes[i], quantile)],
            default=max(0, lb_solve - 1),  # certified infeasible below
        )
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if probe(mid):
                hi = mid
            else:
                lo = mid
    except _Doomed as exc:
        timings["search"] = time.perf_counter() - t0
        return fail(str(exc))
    except PlanInterrupted as exc:
        timings["search"] = time.perf_counter() - t0
        return interrupted(exc)
    timings["search"] = time.perf_counter() - t0
    try:
        return finish(hi)
    except PlanInterrupted as exc:  # interrupt during the winner re-sweep
        return interrupted(exc)


def _passed(rec: Dict[str, int], quantile: float) -> bool:
    if rec["base_unplaced"] or not rec["scenarios"]:
        return False
    return rec["survived"] / rec["scenarios"] >= quantile - 1e-12
