"""Failure-scenario model: deterministic, seed-driven what-if outages.

The reference answers only static questions — "does this app list fit" and
"min nodes to fit" (`pkg/apply/apply.go:183-233`); it has no notion of a
node dying.  This module makes a failure scenario a first-class VALUE: a
boolean node mask (True = node failed), stackable into a `[S, N]` scenario
tensor that the batched sweep (faults/sweep.py) evaluates as one more
vmapped axis — the same move that turned the candidate-size loop into the
capacity sweep (parallel/sweep.py).

Three generators cover the outage families capacity reviews actually ask
about:

- `single_node_scenarios`: exhaustive one-node failures (the N+1 question);
- `k_node_scenarios`: k-node combinations — exhaustive while C(n, k) fits
  the sample budget, else sampled WITHOUT replacement from a seeded
  Generator (deterministic for a given (n, k, samples, seed));
- `domain_scenarios`: correlated outages keyed off node labels (zone, rack
  — `synth_cluster` stamps both), one scenario per distinct domain value.

Everything is host-side numpy; nothing here touches jax.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from .. import constants as C

#: spec shorthand → node-label key for domain outages
DOMAIN_KEYS = {
    "zone": C.LABEL_ZONE,
    "rack": C.LABEL_RACK,
    "host": C.LABEL_HOSTNAME,
}


@dataclass(frozen=True)
class ScenarioSet:
    """A batch of failure scenarios over one cluster.

    masks:  [S, N] bool — True marks a FAILED node in that scenario.  The
            complement of a scenario row is the surviving cluster's
            node_valid mask.
    labels: [S] human-readable scenario names ("node:node-000003",
            "k=2:17", "zone:zone-4").
    kind:   generator family ("single" | "k" | "domain" | "mixed").
    k:      failure size (nodes per scenario; max across rows for domain
            outages, whose domains need not be equal-sized).
    """

    masks: np.ndarray
    labels: tuple
    kind: str = "mixed"
    k: int = 1

    def __len__(self) -> int:
        return int(self.masks.shape[0])

    @property
    def n_nodes(self) -> int:
        return int(self.masks.shape[1])


def stack_scenarios(sets: Sequence[ScenarioSet]) -> ScenarioSet:
    """Concatenate scenario sets over one cluster into a single sweepable
    batch (the scenario axis is just rows — kinds may mix freely)."""
    sets = [s for s in sets if len(s)]
    if not sets:
        raise ValueError("no scenarios to stack")
    n = {s.n_nodes for s in sets}
    if len(n) != 1:
        raise ValueError(f"scenario sets span different clusters: {sorted(n)}")
    kinds = {s.kind for s in sets}
    return ScenarioSet(
        masks=np.concatenate([s.masks for s in sets], axis=0),
        labels=tuple(lbl for s in sets for lbl in s.labels),
        kind=kinds.pop() if len(kinds) == 1 else "mixed",
        k=max(s.k for s in sets),
    )


def _candidates(n_nodes: int, valid: Optional[np.ndarray]) -> np.ndarray:
    if valid is None:
        return np.arange(n_nodes)
    valid = np.asarray(valid, bool)
    if valid.shape != (n_nodes,):
        raise ValueError(f"valid mask shape {valid.shape} != ({n_nodes},)")
    return np.flatnonzero(valid)


def _node_name(nodes, i: int) -> str:
    if nodes is None:
        return f"node[{i}]"
    meta = nodes[i].get("metadata") or {}
    return meta.get("name") or f"node[{i}]"


def single_node_scenarios(
    n_nodes: int,
    nodes: Optional[List[dict]] = None,
    valid: Optional[np.ndarray] = None,
) -> ScenarioSet:
    """Exhaustive single-node failures over the (valid) nodes — the
    N+1 survivability question."""
    cand = _candidates(n_nodes, valid)
    masks = np.zeros((len(cand), n_nodes), bool)
    masks[np.arange(len(cand)), cand] = True
    labels = tuple(f"node:{_node_name(nodes, int(i))}" for i in cand)
    return ScenarioSet(masks=masks, labels=labels, kind="single", k=1)


def k_node_scenarios(
    n_nodes: int,
    k: int,
    samples: int = 256,
    seed: int = 0,
    valid: Optional[np.ndarray] = None,
) -> ScenarioSet:
    """k-node failure combinations: exhaustive while C(n, k) <= samples
    (lexicographic order), else `samples` DISTINCT combinations sampled from
    a seeded Generator — deterministic for a given (n, k, samples, seed),
    independent of process or platform."""
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    cand = _candidates(n_nodes, valid)
    if k > len(cand):
        raise ValueError(f"k={k} exceeds the {len(cand)} failable nodes")
    if k == 1:
        # exhaustive single-node failures regardless of the sample budget:
        # N scenarios is the floor any N+1 answer needs anyway
        return single_node_scenarios(n_nodes, valid=valid)
    total = math.comb(len(cand), k)
    if samples <= 0 or total <= samples:
        combos = [cand[list(c)] for c in itertools.combinations(range(len(cand)), k)]
    else:
        rng = np.random.default_rng(seed)
        seen, combos = set(), []
        # distinct k-subsets; the attempt cap bounds the (astronomically
        # unlikely) degenerate tail when samples approaches C(n, k)
        attempts = 0
        while len(combos) < samples and attempts < 50 * samples:
            attempts += 1
            pick = tuple(sorted(rng.choice(len(cand), size=k, replace=False).tolist()))
            if pick in seen:
                continue
            seen.add(pick)
            combos.append(cand[list(pick)])
    masks = np.zeros((len(combos), n_nodes), bool)
    for s, nodes_idx in enumerate(combos):
        masks[s, nodes_idx] = True
    labels = tuple(f"k={k}:{s}" for s in range(len(combos)))
    return ScenarioSet(masks=masks, labels=labels, kind="k", k=k)


def domain_scenarios(
    nodes: List[dict],
    label_key: str,
    valid: Optional[np.ndarray] = None,
) -> ScenarioSet:
    """One scenario per distinct value of `label_key` among the (valid)
    nodes: the whole failure domain goes down at once (zone outage, rack
    power loss).  Nodes without the label belong to no domain and never
    fail here."""
    n = len(nodes)
    cand = set(_candidates(n, valid).tolist())
    by_value: dict = {}
    for i, node in enumerate(nodes):
        if i not in cand:
            continue
        labels = (node.get("metadata") or {}).get("labels") or {}
        value = labels.get(label_key)
        if value is not None:
            by_value.setdefault(value, []).append(i)
    values = sorted(by_value)
    masks = np.zeros((len(values), n), bool)
    for s, value in enumerate(values):
        masks[s, by_value[value]] = True
    short = label_key.rsplit("/", 1)[-1]
    labels_out = tuple(f"{short}:{v}" for v in values)
    k = max((len(v) for v in by_value.values()), default=0)
    return ScenarioSet(masks=masks, labels=labels_out, kind="domain", k=k)


def parse_fault_spec(spec: str) -> List[dict]:
    """Parse the CLI fault spec: comma-separated terms of

    - ``k=<int>``            sampled (or exhaustive) k-node failures
    - ``k=<int>:<samples>``  ... with a per-term sample budget
    - ``zone`` / ``rack`` / ``host``   domain outages on the standard keys
    - ``label:<key>``        domain outages on an arbitrary node-label key

    e.g. ``--faults k=1,zone`` or ``--faults k=2:500,rack``.
    """
    terms = []
    for raw in (spec or "").split(","):
        token = raw.strip()
        if not token:
            continue
        if token.startswith("k="):
            body = token[2:]
            samples = None
            if ":" in body:
                body, samples_s = body.split(":", 1)
                samples = int(samples_s)
            k = int(body)
            if k < 1:
                raise ValueError(f"fault spec term {token!r}: k must be >= 1")
            terms.append({"kind": "k", "k": k, "samples": samples})
        elif token in DOMAIN_KEYS:
            terms.append({"kind": "domain", "key": DOMAIN_KEYS[token]})
        elif token.startswith("label:"):
            terms.append({"kind": "domain", "key": token[len("label:"):]})
        else:
            raise ValueError(
                f"unrecognized fault spec term {token!r} "
                "(expected k=<int>[:<samples>], zone, rack, host, or label:<key>)"
            )
    if not terms:
        raise ValueError("empty fault spec")
    return terms


def generate_scenarios(
    nodes: List[dict],
    spec: str = "k=1",
    samples: int = 256,
    seed: int = 0,
    valid: Optional[np.ndarray] = None,
) -> ScenarioSet:
    """Scenario set for a parsed fault spec over `nodes` (see
    `parse_fault_spec`).  `samples` is the default budget for k-terms that
    carry none of their own; `valid` restricts failures to live nodes (the
    resilience planner passes each candidate's membership mask)."""
    n = len(nodes)
    sets = []
    for term in parse_fault_spec(spec):
        if term["kind"] == "k":
            if term["k"] == 1:
                # exhaustive, with real node names in the labels
                sets.append(single_node_scenarios(n, nodes=nodes, valid=valid))
            else:
                sets.append(
                    k_node_scenarios(
                        n,
                        term["k"],
                        samples=term["samples"] if term["samples"] is not None else samples,
                        seed=seed,
                        valid=valid,
                    )
                )
        else:
            sets.append(domain_scenarios(nodes, term["key"], valid=valid))
    return stack_scenarios(sets)
