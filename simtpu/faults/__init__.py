"""Fault injection & resilience: batched failure-scenario sweeps.

The robustness-shaped subsystem the ROADMAP north star calls for: a
failure scenario is a boolean node mask, a drain is a batch of signed
placement-log deltas, a requeue is one more placement over the masked
cluster — and the whole scenario axis evaluates as one vmapped tensor
dimension (faults/sweep.py), the same batching move as the capacity sweep.
`plan.resilience.plan_resilience` wraps it in an N+k survivability search.
"""

from .drain import (
    DrainResult,
    PlacedCluster,
    drain_requeue,
    drain_simulator,
    place_cluster,
)
from .scenarios import (
    DOMAIN_KEYS,
    ScenarioSet,
    domain_scenarios,
    generate_scenarios,
    k_node_scenarios,
    parse_fault_spec,
    single_node_scenarios,
    stack_scenarios,
)
from .sweep import SweepResult, serial_replay, sweep_scenarios

__all__ = [
    "DOMAIN_KEYS",
    "DrainResult",
    "PlacedCluster",
    "ScenarioSet",
    "SweepResult",
    "domain_scenarios",
    "drain_requeue",
    "drain_simulator",
    "generate_scenarios",
    "k_node_scenarios",
    "parse_fault_spec",
    "place_cluster",
    "serial_replay",
    "single_node_scenarios",
    "stack_scenarios",
    "sweep_scenarios",
]
