"""Drain & requeue: evict the pods of failed nodes, re-place the backlog.

The functional analog of what a real cluster does when a node dies: the
node controller deletes the node's pods, their controllers recreate them,
and kube-scheduler places the recreations against the surviving nodes.
Here the placement log IS the cluster state, so a drain is a batch of
signed log deltas (`engine/state.py apply_placement_deltas` via
`Engine.remove_placements`) and the requeue is one more engine placement
over the masked cluster (`Engine.node_valid`).

Two entry points:

- `drain_requeue` (engine level): exact, restorable, and the serial
  oracle the batched sweep (faults/sweep.py) is pinned against.  Pods
  FORCED to a failed node (DaemonSet pods, spec.nodeName pins) die with
  the node — they are drained but not requeued, and never count as
  unplaced (their node no longer exists to run them).
- `drain_simulator` (facade level): requeues through
  `Simulator._schedule_pods`, so the evicted pods re-enter the FULL
  scheduling flow including DefaultPreemption retry semantics (api.py) —
  a high-priority evictee may push lower-priority pods off surviving
  nodes, exactly as a fresh submission would.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..core.objects import AppResource, ResourceTypes
from ..core.tensorize import PodBatch, slice_batch
from ..engine.scan import Engine


@dataclass
class PlacedCluster:
    """One placed problem the fault subsystem reasons about: the frozen
    tensors, the full pod batch, the engine holding the placement log, and
    the base placement vector.  `log_row[j]` maps engine log index j back
    to its batch row (the log appends placed pods in batch order)."""

    tz: object
    tensors: object
    batch: PodBatch
    engine: Engine
    nodes: np.ndarray  # [P] base landing node per batch row (-1 = unplaced)
    reasons: np.ndarray  # [P]

    def __post_init__(self):
        self.nodes = np.asarray(self.nodes)
        self.reasons = np.asarray(self.reasons)
        self.log_row = np.flatnonzero(self.nodes >= 0)
        self._dies = None

    @property
    def n_nodes(self) -> int:
        return self.tensors.alloc.shape[0]

    @property
    def dies_with_node(self) -> np.ndarray:
        """[P] rows that DIE with their node rather than requeue: pods
        forced via spec.nodeName, and DaemonSet-owned pods (the reference
        pins those per node through a matchFields affinity,
        workloads/expand.py — either way the pod has no other node to
        exist on, exactly as in a real cluster where the DS controller
        only recreates it when a node comes back)."""
        if self._dies is None:
            forced = np.asarray(self.batch.forced, bool)
            if self.batch.pods:
                daemon = np.fromiter(
                    (_is_daemon_pod(p) for p in self.batch.pods),
                    bool,
                    len(self.batch.pods),
                )
                self._dies = forced | daemon
            else:
                self._dies = forced.copy()
        return self._dies


def place_cluster(
    cluster: ResourceTypes,
    apps: Sequence[AppResource] = (),
    extended_resources: Sequence[str] = (),
    bulk: bool = True,
    sched_config=None,
    engine_factory=None,
    speculate=None,
) -> PlacedCluster:
    """Expand, tensorize and place the whole problem through ONE engine —
    the base placement every fault scenario drains from.  Pod order matches
    `simulate()` (cluster pods + DaemonSet expansion, then each app's
    sorted pods); preemption does not run here (the sweep's scenario axis
    asks whether everything fits, the same contract as the incremental
    planner — use `drain_simulator` when eviction semantics matter)."""
    from ..engine.rounds import RoundsEngine
    from ..parallel.sweep import assemble_planning_problem

    if not cluster.nodes:
        raise ValueError("cannot place against a cluster with no nodes")
    tz, _all_nodes, _n_base, ordered = assemble_planning_problem(
        cluster, apps, cluster.nodes[0], 0, extended_resources
    )
    batch = tz.add_pods(ordered)
    factory = engine_factory or (RoundsEngine if bulk else Engine)
    eng = factory(tz)
    eng.sched_config = sched_config
    if speculate is not None:
        eng.speculate = bool(speculate)
    nodes, reasons, _extras = eng.place(batch)
    return PlacedCluster(
        tz=tz, tensors=tz.freeze(), batch=batch, engine=eng,
        nodes=nodes, reasons=reasons,
    )


@dataclass
class DrainResult:
    """Outcome of one drain + requeue scenario."""

    fail_mask: np.ndarray  # [N] the scenario (True = node failed)
    evicted_rows: np.ndarray  # batch rows drained off failed nodes
    lost_rows: np.ndarray  # forced subset that dies with its node
    requeue_rows: np.ndarray  # rows requeued (evicted minus lost)
    requeue_nodes: np.ndarray  # landing node per requeue row (-1 = unplaced)
    requeue_reasons: np.ndarray  # failure codes parallel to requeue_nodes
    preempted: int = 0  # victims evicted by preemption (drain_simulator only)
    extra_unscheduled: int = 0  # facade-path pods unplaced even after preemption

    @property
    def unplaced_rows(self) -> np.ndarray:
        return self.requeue_rows[np.asarray(self.requeue_nodes) < 0]

    @property
    def unplaced(self) -> int:
        return int((np.asarray(self.requeue_nodes) < 0).sum()) + self.extra_unscheduled

    @property
    def survived(self) -> bool:
        return self.unplaced == 0


def drain_requeue(
    pc: PlacedCluster,
    fail_mask: np.ndarray,
    restore: bool = False,
) -> DrainResult:
    """Drain every pod placed on a failed node, then requeue the survivors'
    backlog (original placement order) against the masked cluster.

    With `restore=True` the engine is rolled back afterwards — requeue
    placements removed, victims restored via the batch-delta undo — so the
    next scenario drains from a bit-identical base (the serial-replay
    oracle the sweep tests are pinned against).  With `restore=False` the
    engine is left holding the post-failure cluster (node mask applied,
    backlog placed where it fits)."""
    eng = pc.engine
    n = pc.n_nodes
    fail = np.asarray(fail_mask, bool)
    if fail.shape != (n,):
        raise ValueError(f"fail_mask shape {fail.shape} != ({n},)")
    placed_log_nodes = np.asarray(eng.placed_node, np.int64)
    vict_log = np.flatnonzero(fail[placed_log_nodes])
    rows = pc.log_row[vict_log]
    dies = pc.dies_with_node[rows]
    # DaemonSet pods and spec.nodeName pins die with their node: drained
    # from the state, but with no other node to exist on they neither
    # requeue nor count as unplaced
    lost_rows = rows[dies]
    requeue_rows = rows[~dies]

    # an empty drain must not touch the log: remove_placements with no
    # entries would mark the carried state dirty (forcing a rebuild), and
    # the failure-free scenario is pinned as a strict no-op
    saved = (
        eng.remove_placements([int(i) for i in vict_log])
        if len(vict_log)
        else {"indices": [], "entries": []}
    )
    prev_valid = eng.node_valid
    eng.node_valid = (
        ~fail if prev_valid is None else np.asarray(prev_valid, bool) & ~fail
    )
    try:
        if len(requeue_rows):
            probe = slice_batch(pc.batch, requeue_rows)
            log_base = len(eng.placed_node)
            req_nodes, req_reasons, _extras = eng.place(probe)
            req_nodes = np.asarray(req_nodes)
            req_reasons = np.asarray(req_reasons)
        else:
            log_base = len(eng.placed_node)
            req_nodes = np.zeros(0, np.int64)
            req_reasons = np.zeros(0, np.int32)
        if restore:
            placed_cnt = int((req_nodes >= 0).sum())
            if placed_cnt:
                # permanent removal of the requeue entries (no undo token
                # kept): the restore below returns the log to the base
                eng.remove_placements(
                    list(range(log_base, log_base + placed_cnt))
                )
            if saved["indices"]:
                eng.restore_placements(saved)
    finally:
        if restore:
            eng.node_valid = prev_valid
    return DrainResult(
        fail_mask=fail,
        evicted_rows=rows,
        lost_rows=lost_rows,
        requeue_rows=requeue_rows,
        requeue_nodes=req_nodes,
        requeue_reasons=req_reasons,
    )


def _is_daemon_pod(pod: dict) -> bool:
    for ref in (pod.get("metadata") or {}).get("ownerReferences") or []:
        if ref.get("kind") == "DaemonSet":
            return True
    return False


def _unbind(pod: dict, gpu_assigned: bool) -> dict:
    """A requeue-able copy of a placed pod: binding and phase cleared.  The
    GPU device-index annotation `record_placed_pod` wrote at bind time is
    dropped when the engine log shows this placement consumed GPU shares —
    keeping it would act as a preset pin onto device indices of a node the
    pod may no longer land on.  (A pod whose index annotation predates the
    simulation is indistinguishable from an assigned one here; dropping is
    the safe default for drained pods and is documented in
    docs/resilience.md.)"""
    from .. import constants as C
    from ..core.objects import annotations_of, shallow_pod_copy

    p = shallow_pod_copy(pod)
    p["spec"].pop("nodeName", None)
    if "status" in p:
        p["status"] = dict(p["status"])
        p["status"].pop("phase", None)
    if gpu_assigned and C.ANNO_POD_GPU_INDEX in annotations_of(p):
        p["metadata"]["annotations"] = {
            k: v
            for k, v in annotations_of(p).items()
            if k != C.ANNO_POD_GPU_INDEX
        }
    return p


def drain_simulator(sim, fail_mask: np.ndarray) -> DrainResult:
    """Drain failed nodes on a live `Simulator` and requeue the evicted
    pods through the full facade flow — `Simulator._schedule_pods`
    including DefaultPreemption (api.py): a requeued pod may evict
    lower-priority pods on surviving nodes exactly as a fresh submission
    would, and requeue failures are recorded in the simulator's
    unscheduled list with real reason strings.

    The failure mask STAYS applied to the simulator's engine (the cluster
    has genuinely lost those nodes); `sim._result()` afterwards reflects
    the post-failure placement.  DaemonSet pods and spec.nodeName-bound
    pods on failed nodes die with their node (drained, not requeued)."""
    eng = sim._engine
    fail = np.asarray(fail_mask, bool)
    placed_log_nodes = np.asarray(eng.placed_node, np.int64)
    vict_log = [int(i) for i in np.flatnonzero(fail[placed_log_nodes])]
    gpu_mem_log = [float(eng.ext_log["gpu_mem"][i]) for i in vict_log]
    bound_log = [bool(sim._placed_forced[i]) for i in vict_log]
    saved = (
        eng.remove_placements(vict_log)
        if vict_log
        else {"indices": [], "entries": []}
    )
    victims = [sim._scheduled[i] for i in saved["indices"]]
    for i in reversed(saved["indices"]):
        del sim._scheduled[i]
        del sim._placed_prio[i]
        del sim._placed_forced[i]
    requeue, lost = [], 0
    for pod, gpu_mem, bound in zip(victims, gpu_mem_log, bound_log):
        if bound or _is_daemon_pod(pod):
            # same death rule as the engine oracle (drain_requeue): pods
            # statically bound via spec.nodeName die with their node too
            lost += 1
            continue
        requeue.append(_unbind(pod, gpu_assigned=gpu_mem > 0))
    prev_valid = eng.node_valid
    eng.node_valid = (
        ~fail if prev_valid is None else np.asarray(prev_valid, bool) & ~fail
    )
    before_unsched = len(sim._unscheduled)
    before_preempted = len(sim._preempted)
    sim._schedule_pods(requeue)
    return DrainResult(
        fail_mask=fail,
        evicted_rows=np.asarray(saved["indices"], np.int64),
        lost_rows=np.zeros(lost, np.int64),
        requeue_rows=np.arange(len(requeue), dtype=np.int64),
        requeue_nodes=np.zeros(0, np.int64),
        requeue_reasons=np.zeros(0, np.int32),
        preempted=len(sim._preempted) - before_preempted,
        extra_unscheduled=len(sim._unscheduled) - before_unsched,
    )
