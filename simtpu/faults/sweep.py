"""Batched failure-scenario sweep: thousands of what-if outages at once.

The serial oracle (faults/drain.py) answers one scenario with three engine
round-trips — drain deltas, a requeue placement, the undo.  At N scenarios
that is O(N) compiled dispatches, the same shape as the reference's serial
candidate loop before the capacity sweep (parallel/sweep.py) batched it.
Here the scenario axis becomes a tensor dimension:

1. per scenario, the DRAIN is a fixed-length batch of signed placement-log
   deltas (`engine/state.py placement_delta_step`, w = -1 real rows, 0
   padding) applied to the shared base state — the same arithmetic the
   serial path's `remove_placements` undo machinery runs, so drained
   states are bit-identical;
2. the REQUEUE is a fixed-length `schedule_step` scan of the scenario's
   evicted pods (original placement order) against the scenario-masked
   statics — the same kernels as the serial engine's dispatch;
3. `vmap` batches both over a `[S, N]` scenario-mask tensor, chunked so
   the vmapped carry stays within memory, and one compiled executable
   (`_fault_sweep`) serves every chunk.  With `mesh=`, the scenario axis
   shards over "sweep" and the node axis over "nodes", exactly like the
   capacity sweep.

Padding is trailing and inert: a padded delta row carries w = 0 (an exact
no-op through `placement_delta_step` — its sdev mask is zeroed so the
boolean-release branch is the identity), and a padded requeue row is an
unforced zero-request phantom whose state effects occur AFTER every real
pod of its scenario; outputs are masked back to the real counts host-side.

`sweep_scenarios` enumerates its executable into the AOT registry
(engine/precompile.py) under the scenario-batched signature when handed a
pipeline, so the compile overlaps the host-side scenario assembly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..durable.backoff import is_resource_exhausted, record_backoff
from ..engine.scan import (
    StepFlags,
    build_pod_arrays,
    count_trace,
    flags_from,
    schedule_step,
    statics_from,
)
from ..engine.state import build_state, placement_delta_step
from ..obs.trace import span
from .drain import PlacedCluster, drain_requeue
from .scenarios import ScenarioSet


@partial(jax.jit, static_argnums=(5,))
def _fault_sweep(statics, valid_s, state, entries_s, pods_s, flags=StepFlags()):
    """One chunk of scenarios: vmapped drain (delta scan) + requeue
    (schedule scan).  `state` is the shared base carry (broadcast, never
    donated); `valid_s [S, N]` is the SURVIVING-node mask per scenario."""
    count_trace("fault_sweep")

    def one(valid, entries, pods):
        drained, _ = jax.lax.scan(
            partial(placement_delta_step, statics), state, entries
        )
        st = statics._replace(node_valid=statics.node_valid & valid)
        _, outs = jax.lax.scan(
            partial(schedule_step, st, flags=flags), drained, pods
        )
        return outs[0], outs[1]  # landing nodes, failure reasons

    return jax.vmap(one)(valid_s, entries_s, pods_s)


def _pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def _state_bytes(state) -> int:
    # chunk sizing is deliberately keyed to the DENSE state's bytes even
    # under the compact carried layout: the vmapped drain/requeue kernels
    # consume the dense [·, N] expansion per scenario, so dense bytes are
    # what each scenario replica actually costs on device
    from ..engine.state import state_nbytes

    return sum(state_nbytes(state).values())


def _base_state(pc: PlacedCluster):
    """The base carry every scenario drains from.  `place_cluster` leaves
    the engine's carried state valid; a dirtied engine (log surgery without
    a following place) rebuilds from the log the way Engine.place would.
    The carry is read through `Engine.carried_state` — the engine may hold
    it domain-tabular (engine/state.py CompactState), and the vmapped
    drain/requeue kernels consume the dense expansion (one exact gather,
    never donating the engine's carry)."""
    eng = pc.engine
    tensors = pc.tensors
    if (
        eng.last_state is not None
        and not eng._state_dirty
        and eng._last_vocab == eng.state_vocab(tensors)
    ):
        return eng.carried_state()
    r = tensors.alloc.shape[1]
    return build_state(
        tensors,
        np.asarray(eng.placed_group, np.int32),
        np.asarray(eng.placed_node, np.int32),
        eng.log_req_matrix(r),
        eng.ext_log,
    )


@dataclass
class SweepResult:
    """Per-scenario outcomes of one batched sweep."""

    scenarios: ScenarioSet
    evicted: np.ndarray  # [S] pods drained off failed nodes
    lost: np.ndarray  # [S] forced pods that die with their node
    requeued: np.ndarray  # [S] requeue attempts (evicted - lost)
    unplaced: np.ndarray  # [S] requeued pods that found no surviving node
    requeue_rows: np.ndarray  # [S, Rq] batch rows (-1 padding)
    requeue_nodes: np.ndarray  # [S, Rq] landing nodes (-1 = unplaced)
    requeue_reasons: np.ndarray  # [S, Rq] failure codes
    timings: Dict[str, float] = field(default_factory=dict)

    @property
    def survived(self) -> np.ndarray:
        return self.unplaced == 0

    @property
    def survival_rate(self) -> float:
        s = len(self.scenarios)
        return float(self.survived.sum()) / s if s else 1.0

    def worst(self, top: int = 5) -> List[Tuple[str, int]]:
        """The `top` scenarios by unplaced-pod count (ties by index)."""
        order = np.argsort(-self.unplaced, kind="stable")[:top]
        return [
            (self.scenarios.labels[int(s)], int(self.unplaced[s]))
            for s in order
            if self.unplaced[s] > 0
        ]

    def critical_nodes(self, top: int = 10) -> List[Tuple[str, int]]:
        """For single-node scenarios: the nodes whose loss strands the most
        pods — the cluster's criticality ranking."""
        singles = [
            (self.scenarios.labels[s], int(self.unplaced[s]))
            for s in range(len(self.scenarios))
            if int(self.scenarios.masks[s].sum()) == 1
        ]
        singles.sort(key=lambda kv: -kv[1])
        return [(lbl.split(":", 1)[-1], n) for lbl, n in singles[:top] if n > 0]

    def counters(self) -> Dict[str, object]:
        """Machine-readable summary (CLI --json, bench)."""
        return {
            "scenarios": len(self.scenarios),
            "survived": int(self.survived.sum()),
            "survival_rate": round(self.survival_rate, 4),
            "evicted_total": int(self.evicted.sum()),
            "unplaced_max": int(self.unplaced.max()) if len(self.unplaced) else 0,
            "fault_scenarios_per_s": round(
                self.timings.get("scenarios_per_s", 0.0), 1
            ),
        }


def _chunk_default(state, n_scenarios: int) -> int:
    """Scenario rows per dispatch: bound the vmapped carry to ~256 MB of
    replicated state, clamped to [8, 128] and pow2 for shape stability."""
    per = max(_state_bytes(state), 1)
    budget = 256 << 20
    return int(min(128, max(8, _pow2(min(budget // per, n_scenarios) or 1))))


def sweep_scenarios(
    pc: PlacedCluster,
    scenarios: ScenarioSet,
    s_chunk: Optional[int] = None,
    mesh=None,
    pipeline=None,
) -> SweepResult:
    """Evaluate every scenario's drain + requeue in vmapped chunks.

    Produces, for each scenario, the identical unplaced-pod set as the
    serial replay (`drain_requeue(pc, mask, restore=True)`) — pinned by
    tests/test_faults.py.  The engine itself is never touched: the base
    state is read once and broadcast, so the sweep composes with any
    engine (bulk, masked, a resilience candidate's
    `MaskedRoundsEngine`)."""
    t0 = time.perf_counter()
    eng = pc.engine
    tensors = pc.tensors
    n = pc.n_nodes
    r = tensors.alloc.shape[1]
    if scenarios.n_nodes != n:
        raise ValueError(
            f"scenarios span {scenarios.n_nodes} nodes, cluster has {n}"
        )
    s_total = len(scenarios)
    flags = flags_from(tensors, pc.batch.ext)
    statics = statics_from(tensors, eng.sched_config)
    state = _base_state(pc)
    base_valid = (
        np.ones(n, bool)
        if eng.node_valid is None
        else np.asarray(eng.node_valid, bool)
    )

    # -- host-side scenario assembly --------------------------------------
    masks = np.asarray(scenarios.masks, bool)
    log_nodes = np.asarray(eng.placed_node, np.int32)
    log_rows = pc.log_row  # log index -> batch row
    dies = pc.dies_with_node  # DS pods / nodeName pins die with their node
    ev_lists = [np.flatnonzero(masks[s][log_nodes]) for s in range(s_total)]
    rq_lists = []
    lost = np.zeros(s_total, np.int64)
    for s, ev in enumerate(ev_lists):
        rows = log_rows[ev]
        f = dies[rows]
        lost[s] = int(f.sum())
        rq_lists.append(rows[~f])
    e_pad = _pow2(max((len(v) for v in ev_lists), default=0) or 1)
    r_pad = _pow2(max((len(v) for v in rq_lists), default=0) or 1)
    if s_chunk is None:
        s_chunk = _chunk_default(state, s_total)
    if mesh is not None:
        from ..parallel.mesh import SWEEP_AXIS

        s_chunk = max(s_chunk, mesh.shape[SWEEP_AXIS])
        s_chunk -= s_chunk % mesh.shape[SWEEP_AXIS]

    shardings = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import NODE_AXIS, SWEEP_AXIS
        from ..parallel.sharded import (
            pad_state,
            pad_statics,
            state_sharding,
            statics_sharding,
        )

        statics, pad = pad_statics(statics, mesh.shape[NODE_AXIS])
        state = pad_state(state, pad)
        statics = jax.device_put(statics, statics_sharding(mesh))
        state = jax.device_put(state, state_sharding(mesh))
        shardings = (
            NamedSharding(mesh, P(SWEEP_AXIS, NODE_AXIS)),
            NamedSharding(mesh, P(SWEEP_AXIS)),
            pad,
        )

    if pipeline is not None:
        # enumerate the scenario-batched signature into the AOT registry
        # BEFORE the (host-bound) per-chunk assembly below, so the compile
        # overlaps it (engine/precompile.py)
        _submit_sweep(
            pipeline, statics, state, flags, s_chunk, e_pad, r_pad, pc
        )

    # whole-log delta source columns, gathered per scenario
    m = len(log_nodes)
    log_group = np.asarray(eng.placed_group, np.int32)
    log_req = eng.log_req_matrix(r)
    ext = eng.ext_log
    log_vg = (
        np.asarray(ext["vg_alloc"], np.float32)
        if m
        else np.zeros((0, tensors.ext.vg_cap.shape[1]), np.float32)
    )
    log_sd = (
        np.asarray(ext["sdev_take"], bool)
        if m
        else np.zeros((0, tensors.ext.sdev_cap.shape[1]), bool)
    )
    log_gpu = (
        np.asarray(ext["gpu_shares"], np.float32)
        * np.asarray(ext["gpu_mem"], np.float32)[:, None]
        if m
        else np.zeros((0, tensors.ext.gpu_dev_total.shape[1]), np.float32)
    )
    _, pods_full = build_pod_arrays(pc.batch, r)

    def gather_block(s0: int, s1: int, sb: int):
        """Assemble one chunk's (valid, entries, pods, rq_idx) arrays,
        padding the scenario axis to `sb` with empty (failure-free)
        rows."""
        ev_idx = np.full((sb, e_pad), -1, np.int64)
        rq_idx = np.full((sb, r_pad), -1, np.int64)
        valid = np.ones((sb, n), bool) & base_valid[None, :]
        for j, s in enumerate(range(s0, s1)):
            ev = ev_lists[s]
            rq = rq_lists[s]
            ev_idx[j, : len(ev)] = ev
            rq_idx[j, : len(rq)] = rq
            valid[j] &= ~masks[s]
        ev_ok = ev_idx >= 0
        ev_safe = np.maximum(ev_idx, 0)
        entries = (
            np.where(ev_ok, log_group[ev_safe], 0).astype(np.int32),
            np.where(ev_ok, log_nodes[ev_safe], 0).astype(np.int32),
            np.where(ev_ok, -1.0, 0.0).astype(np.float32),
            log_req[ev_safe],
            log_vg[ev_safe],
            # padded rows MUST carry an all-False device mask: the w<0
            # release branch of placement_delta_step ORs it into the row
            log_sd[ev_safe] & ev_ok[..., None],
            log_gpu[ev_safe],
        )
        rq_ok = rq_idx >= 0
        rq_safe = np.maximum(rq_idx, 0)

        def pod_col(arr, fill=0):
            got = arr[rq_safe]
            mask = rq_ok.reshape(rq_ok.shape + (1,) * (got.ndim - 2))
            return np.where(mask, got, fill).astype(arr.dtype)

        pods = (
            pod_col(pods_full[0]),  # group
            pod_col(pods_full[1]),  # req
            pod_col(pods_full[2], fill=-1),  # pin: padding is unpinned
            pod_col(pods_full[3]),  # forced (False for padding)
        ) + tuple(pod_col(a) for a in pods_full[4:])
        if shardings is not None and shardings[2]:
            valid = np.pad(valid, ((0, 0), (0, shardings[2])))
        return valid, entries, pods, rq_idx

    timings = {"assemble_s": 0.0, "sweep_s": 0.0}
    rq_rows = np.full((s_total, r_pad), -1, np.int64)
    rq_nodes = np.full((s_total, r_pad), -1, np.int64)
    rq_reasons = np.zeros((s_total, r_pad), np.int32)
    t_sweep = 0.0
    backoff_events = 0
    # a sharded sweep cannot shrink a block below one scenario per shard
    min_block = 1
    if mesh is not None:
        from ..parallel.mesh import SWEEP_AXIS as _SW

        min_block = int(mesh.shape[_SW])
    # worklist of (s0, s1, block) scenario blocks: an OOM'd block halves
    # and replays (durable/backoff.py) — scenario rows are independent, so
    # any split is exact, and the pow2 halves keep the compiled-shape set
    # at most log2(s_chunk) larger
    blocks = [
        (s0, min(s0 + s_chunk, s_total), s_chunk)
        for s0 in range(0, s_total, s_chunk)
    ]
    while blocks:
        s0, s1, sb = blocks.pop(0)
        ta = time.perf_counter()
        valid, entries, pods, rq_idx = gather_block(s0, s1, sb)
        if shardings is not None:
            valid = jax.device_put(jnp.asarray(valid), shardings[0])
            entries = jax.device_put(entries, shardings[1])
            pods = jax.device_put(pods, shardings[1])
        timings["assemble_s"] += time.perf_counter() - ta
        td = time.perf_counter()
        args = (statics, valid, state, entries, pods)
        try:
            with span("fault.block", scenarios=int(s1 - s0), pad=int(sb)):
                if pipeline is not None:
                    nodes_b, reasons_b = pipeline.call(
                        "fault_sweep", (flags,), args,
                        lambda: _fault_sweep(*args, flags),
                    )
                else:
                    nodes_b, reasons_b = _fault_sweep(*args, flags)
                nodes_b = np.asarray(nodes_b)[: s1 - s0]
                reasons_b = np.asarray(reasons_b)[: s1 - s0]
        except Exception as exc:
            if not is_resource_exhausted(exc) or sb <= min_block:
                raise
            half = max(sb // 2, min_block)
            if mesh is not None:
                half -= half % min_block
                half = max(half, min_block)
            record_backoff(sb, half)
            backoff_events += 1
            t_sweep += time.perf_counter() - td
            # requeue [s0, s1) as blocks of AT MOST `half` scenarios each:
            # every sub-block's span must fit its pad `half` (an odd span,
            # or mesh rounding shrinking `half` below span/2, would
            # otherwise overflow gather_block's arrays)
            blocks[:0] = [
                (x, min(x + half, s1), half) for x in range(s0, s1, half)
            ]
            continue
        t_sweep += time.perf_counter() - td
        rq_rows[s0:s1] = rq_idx[: s1 - s0]
        rq_nodes[s0:s1] = np.where(rq_idx[: s1 - s0] >= 0, nodes_b, -1)
        rq_reasons[s0:s1] = np.where(rq_idx[: s1 - s0] >= 0, reasons_b, 0)
    timings["sweep_s"] = t_sweep
    if backoff_events:
        timings["backoff_events"] = float(backoff_events)
    timings["total_s"] = time.perf_counter() - t0
    timings["scenarios_per_s"] = s_total / t_sweep if t_sweep > 0 else 0.0

    evicted = np.asarray([len(v) for v in ev_lists], np.int64)
    requeued = np.asarray([len(v) for v in rq_lists], np.int64)
    unplaced = ((rq_nodes < 0) & (rq_rows >= 0)).sum(axis=1)
    return SweepResult(
        scenarios=scenarios,
        evicted=evicted,
        lost=lost,
        requeued=requeued,
        unplaced=unplaced.astype(np.int64),
        requeue_rows=rq_rows,
        requeue_nodes=rq_nodes,
        requeue_reasons=rq_reasons,
        timings=timings,
    )


def _submit_sweep(pipeline, statics, state, flags, s_chunk, e_pad, r_pad, pc):
    """Queue the scenario-batched executable's AOT compile (one signature
    per (chunk, pad) shape — every chunk of a sweep shares it)."""
    from ..engine.precompile import as_sds as _as_sds, sds as _sds

    n = int(np.asarray(statics.node_valid).shape[0])
    r = pc.tensors.alloc.shape[1]
    ext = pc.tensors.ext
    entries_sds = (
        _sds((s_chunk, e_pad), np.int32),
        _sds((s_chunk, e_pad), np.int32),
        _sds((s_chunk, e_pad), np.float32),
        _sds((s_chunk, e_pad, r), np.float32),
        _sds((s_chunk, e_pad, ext.vg_cap.shape[1]), np.float32),
        _sds((s_chunk, e_pad, ext.sdev_cap.shape[1]), bool),
        _sds((s_chunk, e_pad, ext.gpu_dev_total.shape[1]), np.float32),
    )
    _, pods_full = build_pod_arrays(pc.batch, r)
    pods_sds = tuple(
        _sds((s_chunk, r_pad) + a.shape[1:], a.dtype) for a in pods_full
    )
    args_sds = (
        _as_sds(statics),
        _sds((s_chunk, n), bool),
        _as_sds(state),
        entries_sds,
        pods_sds,
    )
    pipeline.submit("fault_sweep", (flags,), _fault_sweep, args_sds)


def serial_replay(
    pc: PlacedCluster,
    scenarios: ScenarioSet,
    limit: Optional[int] = None,
):
    """The serial oracle: drain + requeue + restore per scenario through
    the engine path (`faults/drain.py`).  Returns (unplaced counts,
    per-scenario unplaced batch-row sets) for the first `limit` scenarios —
    the floor the batched sweep is benchmarked (and pinned) against."""
    s_n = len(scenarios) if limit is None else min(limit, len(scenarios))
    counts = np.zeros(s_n, np.int64)
    row_sets = []
    for s in range(s_n):
        res = drain_requeue(pc, scenarios.masks[s], restore=True)
        counts[s] = res.unplaced
        row_sets.append(frozenset(int(x) for x in res.unplaced_rows))
    return counts, row_sets
