"""Tests for the pod-ordering queues (`pkg/algo` port: simtpu/algo.py)."""

from __future__ import annotations

from simtpu.algo import (
    affinity_sort,
    cluster_total_resources,
    greed_sort,
    pod_dominant_share,
    share,
    toleration_sort,
)

from .fixtures import (
    make_fake_node,
    make_fake_pod,
    with_pod_node_selector,
    with_pod_tolerations,
)


def test_share_edge_cases():
    # greed.go:69-83
    assert share(0, 0) == 0.0
    assert share(5, 0) == 1.0
    assert share(2, 8) == 0.25


def test_cluster_totals_and_dominant_share():
    nodes = [make_fake_node(f"n{i}", "10", "100Gi") for i in range(2)]
    total = cluster_total_resources(nodes)
    assert total["cpu"] == 20.0
    pod = make_fake_pod("p", "default", "5", "10Gi")
    # cpu share 5/20 = 0.25 dominates memory 10/200 = 0.05
    assert abs(pod_dominant_share(pod, total) - 0.25) < 1e-9


def test_greed_sort_descending_share_nodename_first():
    nodes = [make_fake_node("n0", "10", "100Gi")]
    small = make_fake_pod("small", "default", "1", "1Gi")
    big = make_fake_pod("big", "default", "8", "1Gi")
    pinned = make_fake_pod("pinned", "default", "1", "1Gi")
    pinned["spec"]["nodeName"] = "n0"
    order = [p["metadata"]["name"] for p in greed_sort([small, big, pinned], nodes)]
    assert order == ["pinned", "big", "small"]


def test_affinity_and_toleration_sorts():
    plain = make_fake_pod("plain", "default", "1", "1Gi")
    sel = make_fake_pod(
        "sel", "default", "1", "1Gi", with_pod_node_selector({"disk": "ssd"})
    )
    tol = make_fake_pod(
        "tol",
        "default",
        "1",
        "1Gi",
        with_pod_tolerations([{"key": "k", "operator": "Exists"}]),
    )
    assert [p["metadata"]["name"] for p in affinity_sort([plain, sel])] == [
        "sel",
        "plain",
    ]
    assert [p["metadata"]["name"] for p in toleration_sort([plain, tol])] == [
        "tol",
        "plain",
    ]
