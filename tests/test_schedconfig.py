"""Tests for KubeSchedulerConfiguration consumption (`simtpu/schedconfig.py`):
score-plugin weights/disables from the --default-scheduler-config file flow
into the engine's score-term weight vector and change placement.
"""

from __future__ import annotations

import pytest

from simtpu.api import simulate
from simtpu.core.objects import ResourceTypes
from simtpu.schedconfig import (
    DEFAULT_WEIGHTS,
    TERM_NODE_PREF,
    TERM_SPREAD_SOFT,
    SchedulerConfig,
)

from .fixtures import make_fake_node, make_fake_pod, with_node_labels


CONFIG_YAML = """
apiVersion: kubescheduler.config.k8s.io/v1beta1
kind: KubeSchedulerConfiguration
profiles:
  - plugins:
      score:
        disabled:
          - name: NodeResourcesBalancedAllocation
        enabled:
          - name: NodeAffinity
            weight: 50
          - name: PodTopologySpread
            weight: 7
"""


def test_from_file(tmp_path):
    p = tmp_path / "sched.yaml"
    p.write_text(CONFIG_YAML)
    cfg = SchedulerConfig.from_file(str(p))
    assert cfg.score_weights[TERM_NODE_PREF] == 50.0
    assert cfg.score_weights[TERM_SPREAD_SOFT] == 7.0
    assert cfg.score_weights[1] == 0.0  # balanced disabled
    assert cfg.score_weights[0] == DEFAULT_WEIGHTS[0]


def test_wildcard_disable_keeps_forced_plugins(tmp_path):
    # the reference force-enables Simon/Open-Gpu-Share/Open-Local AFTER
    # merging the user config (utils.go:259-276) — 'disabled: *' cannot
    # remove them
    from simtpu.schedconfig import TERM_GPU, TERM_OPEN_LOCAL, TERM_SIMON

    p = tmp_path / "sched.yaml"
    p.write_text(
        "kind: KubeSchedulerConfiguration\n"
        "profiles:\n"
        "  - plugins:\n"
        "      score:\n"
        "        disabled: [{name: '*'}]\n"
    )
    cfg = SchedulerConfig.from_file(str(p))
    assert cfg.score_weights[TERM_SIMON] == DEFAULT_WEIGHTS[TERM_SIMON]
    assert cfg.score_weights[TERM_GPU] == DEFAULT_WEIGHTS[TERM_GPU]
    assert cfg.score_weights[TERM_OPEN_LOCAL] == DEFAULT_WEIGHTS[TERM_OPEN_LOCAL]
    assert cfg.score_weights[0] == 0.0  # everything else really is off


def test_image_locality_and_prefer_avoid_are_separate_terms(tmp_path):
    from simtpu.schedconfig import TERM_AVOID, TERM_IMAGE

    p = tmp_path / "sched.yaml"
    p.write_text(
        "kind: KubeSchedulerConfiguration\n"
        "profiles:\n"
        "  - plugins:\n"
        "      score:\n"
        "        disabled: [{name: ImageLocality}]\n"
    )
    cfg = SchedulerConfig.from_file(str(p))
    assert cfg.score_weights[TERM_IMAGE] == 0.0
    assert cfg.score_weights[TERM_AVOID] == DEFAULT_WEIGHTS[TERM_AVOID]


def test_bad_kind_rejected(tmp_path):
    p = tmp_path / "bad.yaml"
    p.write_text("kind: Deployment\n")
    with pytest.raises(ValueError):
        SchedulerConfig.from_file(str(p))


def test_weights_change_placement():
    # n0 is busier (least-allocated favors n1) but strongly preferred by node
    # affinity: default weights pick n0; disabling the NodeAffinity score
    # flips the choice to the emptier n1
    nodes = [
        make_fake_node("n0", "16", "32Gi", with_node_labels({"tier": "gold"})),
        make_fake_node("n1", "16", "32Gi"),
    ]
    busy = make_fake_pod("busy", "default", "8", "16Gi")
    busy["spec"]["nodeName"] = "n0"
    pod = make_fake_pod("p", "default", "1", "1Gi")
    pod["spec"]["affinity"] = {
        "nodeAffinity": {
            "preferredDuringSchedulingIgnoredDuringExecution": [
                {
                    "weight": 100,
                    "preference": {
                        "matchExpressions": [
                            {"key": "tier", "operator": "In", "values": ["gold"]}
                        ]
                    },
                }
            ]
        }
    }

    def run(cfg):
        cluster = ResourceTypes(
            nodes=[dict(n) for n in nodes], pods=[dict(busy), dict(pod)]
        )
        result = simulate(cluster, [], sched_config=cfg)
        for status in result.node_status:
            for placed in status.pods:
                if placed["metadata"]["name"].startswith("p"):
                    return status.node["metadata"]["name"]
        return None

    assert run(None) == "n0"
    w = DEFAULT_WEIGHTS.copy()
    w[TERM_NODE_PREF] = 0.0
    assert run(SchedulerConfig(score_weights=w)) == "n1"
