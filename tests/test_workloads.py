"""Workload → pod expansion tests (controller-manager emulation parity)."""

import json
import os

import pytest

import simtpu.constants as C
from simtpu.core.objects import annotations_of, labels_of, name_of, owner_references
from simtpu.io.yaml_loader import load_resources
from simtpu.workloads.expand import (
    get_valid_pods_exclude_daemonset,
    make_valid_pods_by_daemonset,
    make_valid_pods_by_deployment,
    make_valid_pods_by_stateful_set,
    new_daemon_pod,
    seed_name_hashes,
)


@pytest.fixture(autouse=True)
def _seed():
    seed_name_hashes(0)


def _deploy(name="web", namespace="ns", replicas=3, labels=None):
    return {
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace, "labels": labels or {"app": name}},
        "spec": {
            "replicas": replicas,
            "template": {
                "spec": {
                    "containers": [
                        {"name": "c", "resources": {"requests": {"cpu": "1", "memory": "1Gi"}}}
                    ]
                }
            },
        },
    }


class TestDeployment:
    def test_replica_count_and_owner_chain(self):
        pods = make_valid_pods_by_deployment(_deploy(replicas=4))
        assert len(pods) == 4
        for pod in pods:
            refs = owner_references(pod)
            assert refs[0]["kind"] == "ReplicaSet"
            # RS name = deploy name + "-" + 10-char hash; pod name extends it
            rs_name = refs[0]["name"]
            assert rs_name.startswith("web-") and len(rs_name) == len("web-") + 10
            assert name_of(pod).startswith(rs_name + "-")
            assert annotations_of(pod)[C.ANNO_WORKLOAD_KIND] == "ReplicaSet"
            assert labels_of(pod)["app"] == "web"
            assert pod["spec"]["schedulerName"] == "default-scheduler"

    def test_default_replicas_is_one(self):
        d = _deploy()
        del d["spec"]["replicas"]
        assert len(make_valid_pods_by_deployment(d)) == 1


class TestStatefulSet:
    def test_ordinal_names_and_storage_annotation(self):
        sts = {
            "kind": "StatefulSet",
            "metadata": {"name": "db", "namespace": "ns"},
            "spec": {
                "replicas": 2,
                "template": {"spec": {"containers": [{"name": "c"}]}},
                "volumeClaimTemplates": [
                    {
                        "spec": {
                            "storageClassName": "yoda-lvm-default",
                            "resources": {"requests": {"storage": "10Gi"}},
                        }
                    },
                    {
                        "spec": {
                            "storageClassName": "yoda-device-hdd",
                            "resources": {"requests": {"storage": "100Gi"}},
                        }
                    },
                ],
            },
        }
        pods = make_valid_pods_by_stateful_set(sts)
        assert [name_of(p) for p in pods] == ["db-0", "db-1"]
        vols = json.loads(annotations_of(pods[0])[C.ANNO_POD_LOCAL_STORAGE])["volumes"]
        assert vols[0] == {"size": str(10 * 2**30), "kind": "LVM", "scName": "yoda-lvm-default"}
        assert vols[1]["kind"] == "HDD"


MASTER = {
    "kind": "Node",
    "metadata": {
        "name": "master-1",
        "labels": {"node-role.kubernetes.io/master": "", "beta.kubernetes.io/os": "linux"},
    },
    "spec": {"taints": [{"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}]},
    "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
}
WORKER = {
    "kind": "Node",
    "metadata": {
        "name": "worker-1",
        "labels": {"node-role.kubernetes.io/worker": "", "beta.kubernetes.io/os": "linux"},
    },
    "spec": {},
    "status": {"allocatable": {"cpu": "8", "memory": "16Gi", "pods": "110"}},
}


class TestDaemonSet:
    def _ds(self, selector=None, tolerations=None):
        spec = {"containers": [{"name": "c"}]}
        if selector:
            spec["nodeSelector"] = selector
        if tolerations:
            spec["tolerations"] = tolerations
        return {
            "kind": "DaemonSet",
            "metadata": {"name": "proxy", "namespace": "kube-system"},
            "spec": {"template": {"spec": spec}},
        }

    def test_pinned_per_matching_node(self):
        ds = self._ds(
            selector={"node-role.kubernetes.io/master": ""},
            tolerations=[{"operator": "Exists"}],
        )
        pods = make_valid_pods_by_daemonset(ds, [MASTER, WORKER])
        assert len(pods) == 1
        aff = pods[0]["spec"]["affinity"]["nodeAffinity"]
        term = aff["requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"][0]
        assert term["matchFields"] == [
            {"key": "metadata.name", "operator": "In", "values": ["master-1"]}
        ]

    def test_taint_blocks_untolerating_ds(self):
        ds = self._ds(selector={"beta.kubernetes.io/os": "linux"})
        pods = make_valid_pods_by_daemonset(ds, [MASTER, WORKER])
        assert [owner_references(p)[0]["name"] for p in pods] == ["proxy"]
        # only the untainted worker node runs it
        term = pods[0]["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"][0]
        assert term["matchFields"][0]["values"] == ["worker-1"]

    def test_existing_affinity_fields_replaced(self):
        ds = self._ds()
        ds["spec"]["template"]["spec"]["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {
                            "matchExpressions": [
                                {"key": "node-role.kubernetes.io/worker", "operator": "Exists"}
                            ]
                        }
                    ]
                }
            }
        }
        pod = new_daemon_pod(ds, "worker-1")
        term = pod["spec"]["affinity"]["nodeAffinity"][
            "requiredDuringSchedulingIgnoredDuringExecution"
        ]["nodeSelectorTerms"][0]
        # matchFields injected while matchExpressions kept (utils.go:898-903)
        assert term["matchFields"][0]["values"] == ["worker-1"]
        assert term["matchExpressions"][0]["key"] == "node-role.kubernetes.io/worker"


class TestFullExpansion:
    def test_simple_app_pod_census(self, example_dir):
        res = load_resources(os.path.join(example_dir, "application/simple"))
        pods = get_valid_pods_exclude_daemonset(res)
        # deploy(4) + rs-calico(2) + sts(4) + job(1) + bare pod(1) = 12 non-DS pods
        by_kind = {}
        for p in pods:
            kind = annotations_of(p).get(C.ANNO_WORKLOAD_KIND, "Pod")
            by_kind[kind] = by_kind.get(kind, 0) + 1
        assert by_kind["ReplicaSet"] == 4 + 2
        assert by_kind["StatefulSet"] == 4
        assert by_kind["Job"] == 1
        assert by_kind.get("Pod", 0) == 1


class TestUpstreamValidationRules:
    """The scheduling-relevant slice of upstream API validation
    (`pkg/utils/utils.go:516-529,654-668` → apis/core/validation): every
    malformed shape below would otherwise change placement semantics
    SILENTLY (a bad selector matches nothing, a bad operator no-matches,
    an unparseable quantity corrupts capacity)."""

    def _pod(self, **spec_extra):
        pod = {
            "metadata": {"name": "p", "namespace": "default"},
            "spec": {"containers": [{"name": "c"}]},
        }
        pod["spec"].update(spec_extra)
        return pod

    def test_valid_pod_passes(self):
        from simtpu.workloads.validate import validate_pod

        validate_pod(
            self._pod(
                nodeSelector={"topology.kubernetes.io/zone": "z1"},
                tolerations=[{"operator": "Exists", "effect": "NoSchedule"}],
                topologySpreadConstraints=[
                    {
                        "maxSkew": 1,
                        "topologyKey": "zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "x"}},
                    }
                ],
            )
        )

    @pytest.mark.parametrize(
        "mutate",
        [
            lambda p: p["metadata"].__setitem__("labels", {"app": "x" * 64}),
            lambda p: p["metadata"].__setitem__("labels", {"-bad": "v"}),
            lambda p: p["spec"].__setitem__("nodeSelector", {"k": "bad value!"}),
            lambda p: p["spec"].__setitem__(
                "tolerations", [{"operator": "Sometimes"}]
            ),
            lambda p: p["spec"].__setitem__(
                "tolerations", [{"operator": "Exists", "value": "v"}]
            ),
            lambda p: p["spec"].__setitem__(
                "tolerations", [{"operator": "Equal", "effect": "Eventually"}]
            ),
            lambda p: p["spec"].__setitem__(
                "topologySpreadConstraints",
                [{"maxSkew": 0, "topologyKey": "z", "whenUnsatisfiable": "DoNotSchedule"}],
            ),
            lambda p: p["spec"].__setitem__(
                "topologySpreadConstraints",
                [{"maxSkew": 1, "whenUnsatisfiable": "DoNotSchedule"}],
            ),
            lambda p: p["spec"].__setitem__(
                "topologySpreadConstraints",
                [{"maxSkew": 1, "topologyKey": "z", "whenUnsatisfiable": "Maybe"}],
            ),
            lambda p: p["spec"].__setitem__(
                "affinity",
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {"matchExpressions": [{"key": "k", "operator": "Near"}]}
                            ]
                        }
                    }
                },
            ),
            lambda p: p["spec"].__setitem__(
                "affinity",
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {"key": "k", "operator": "Gt", "values": ["x"]}
                                    ]
                                }
                            ]
                        }
                    }
                },
            ),
            lambda p: p["spec"].__setitem__(
                "affinity",
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {"labelSelector": {"matchLabels": {"app": "x"}}}
                        ]
                    }
                },
            ),
            lambda p: p["spec"]["containers"][0].__setitem__(
                "ports", [{"hostPort": 70000}]
            ),
            lambda p: p["spec"]["containers"][0].__setitem__(
                "ports", [{"hostPort": "web"}]
            ),
            lambda p: p["spec"]["containers"][0].__setitem__(
                "ports", [{"hostPort": 80, "protocol": "ICMP"}]
            ),
            lambda p: p["metadata"].__setitem__("labels", {"/app": "v"}),
            lambda p: p["spec"].__setitem__(
                "affinity",
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {"key": "-bad!", "operator": "Exists"}
                                    ]
                                }
                            ]
                        }
                    }
                },
            ),
            lambda p: p["spec"].__setitem__(
                "affinity",
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchFields": [
                                        {
                                            "key": "metadata.name",
                                            "operator": "NotIn",
                                            "values": ["n1"],
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                },
            ),
        ],
    )
    def test_malformed_pod_rejected(self, mutate):
        from simtpu.workloads.validate import ValidationError, validate_pod

        pod = self._pod()
        mutate(pod)
        with pytest.raises(ValidationError):
            validate_pod(pod)

    def test_malformed_node_quantities_rejected(self):
        from simtpu.workloads.validate import ValidationError, validate_node

        node = {"metadata": {"name": "n"}, "status": {"allocatable": {"cpu": "banana"}}}
        with pytest.raises(ValidationError):
            validate_node(node)
        node = {"metadata": {"name": "n"}, "status": {"capacity": {"cpu": "-2"}}}
        with pytest.raises(ValidationError):
            validate_node(node)

    def test_expansion_rejects_malformed_template(self):
        """The gate sits where the reference's is: expansion validates every
        generated pod, so a malformed workload template fails loudly."""
        from simtpu.core.objects import ResourceTypes
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset
        from simtpu.workloads.validate import ValidationError

        from .fixtures import make_fake_deployment

        dep = make_fake_deployment("d", "default", 2, "1", "1Gi")
        dep["spec"]["template"]["spec"]["tolerations"] = [{"operator": "Sometimes"}]
        res = ResourceTypes()
        res.deployments = [dep]
        with pytest.raises(ValidationError):
            get_valid_pods_exclude_daemonset(res)


class TestCronJob:
    """The shared cron parser (workloads/cron.py) + the static expansion's
    suspend/schedule fidelity (ISSUE 15 satellite)."""

    def _cron(self, schedule="*/15 * * * *", suspend=None, deadline=None):
        from .fixtures import make_fake_cron_job

        cj = make_fake_cron_job("tick", "ns", 1, "100m", "128Mi")
        cj["spec"]["schedule"] = schedule
        if suspend is not None:
            cj["spec"]["suspend"] = suspend
        if deadline is not None:
            cj["spec"]["startingDeadlineSeconds"] = deadline
        return cj

    def test_static_expansion_emits_one_job(self):
        from simtpu.workloads.expand import make_valid_pods_by_cron_job

        pods = make_valid_pods_by_cron_job(self._cron())
        assert len(pods) == 1
        kinds = annotations_of(pods[0])[C.ANNO_WORKLOAD_KIND]
        assert kinds == C.KIND_JOB

    def test_suspend_true_expands_to_nothing(self):
        """spec.suspend: true — the controller creates no Jobs while set;
        the static snapshot previously emitted one regardless."""
        from simtpu.workloads.expand import make_valid_pods_by_cron_job

        assert make_valid_pods_by_cron_job(self._cron(suspend=True)) == []
        # explicit false behaves like absent
        assert len(make_valid_pods_by_cron_job(self._cron(suspend=False))) == 1

    def test_malformed_schedule_is_one_line_spec_error(self):
        from simtpu.core.objects import ResourceTypes
        from simtpu.workloads.validate import SpecError

        res = ResourceTypes()
        res.cron_jobs = [self._cron(schedule="every 5 minutes")]
        with pytest.raises(SpecError) as exc:
            get_valid_pods_exclude_daemonset(res)
        msg = str(exc.value)
        assert "spec.schedule" in msg and "ns/tick" in msg
        assert "\n" not in msg

    @pytest.mark.parametrize(
        "expr",
        ["* * * *", "61 * * * *", "* 24 * * *", "*/0 * * * *",
         "5-1 * * * *", "a * * * *", ""],
    )
    def test_parser_rejects_bad_fields(self, expr):
        from simtpu.workloads.cron import parse_schedule
        from simtpu.workloads.validate import SpecError

        with pytest.raises(SpecError):
            parse_schedule(expr)

    def test_parser_fire_enumeration(self):
        from simtpu.workloads.cron import fire_times, parse_schedule

        # */15: four fires per hour, strictly-after-start semantics
        sched = parse_schedule("*/15 * * * *")
        fires = fire_times(sched, 0.0, 3600.0)
        assert fires == [900.0, 1800.0, 2700.0, 3600.0]
        # lists + ranges + steps
        sched = parse_schedule("5,35 1-3/2 * * *")
        fires = fire_times(sched, 0.0, 86400.0)
        assert fires == [
            1 * 3600 + 5 * 60, 1 * 3600 + 35 * 60,
            3 * 3600 + 5 * 60, 3 * 3600 + 35 * 60,
        ]
        # macros resolve through the same grammar
        assert fire_times(parse_schedule("@hourly"), 0.0, 7200.0) == [
            3600.0, 7200.0,
        ]

    def test_parser_dom_dow_or_rule(self):
        """Classic cron: when BOTH day fields are restricted, either
        matching fires.  Epoch day 0 (1970-01-01) is a Thursday."""
        from simtpu.workloads.cron import fire_times, parse_schedule

        # dom=2 OR dow=thu; window covers Thu Jan 1 .. Fri Jan 2
        sched = parse_schedule("0 0 2 * thu")
        fires = fire_times(sched, -1.0, 2 * 86400.0)
        assert fires == [0.0, 86400.0]  # Thu (dow) and the 2nd (dom)
        # dow restricted alone: Sundays only (Jan 4 1970)
        sched = parse_schedule("0 12 * * 0")
        fires = fire_times(sched, 0.0, 7 * 86400.0)
        assert fires == [3 * 86400 + 12 * 3600.0]

    def test_starting_deadline_window(self):
        """startingDeadlineSeconds reaches back before the window start:
        a fire missed by less than the deadline still surfaces (at its
        original schedule time), one missed by more does not."""
        from simtpu.workloads.cron import fire_times, parse_schedule

        sched = parse_schedule("0 * * * *")  # hourly on the hour
        # window opens 30 min past an hourly fire
        start = 3600.0 + 1800.0
        got = fire_times(sched, start, start + 3600.0, starting_deadline_s=2700.0)
        assert got[0] == 3600.0  # missed 30 min ago, within the 45-min deadline
        got = fire_times(sched, start, start + 3600.0, starting_deadline_s=600.0)
        assert got[0] == 7200.0  # 10-min deadline: the missed fire is gone

    def test_impossible_schedule_has_no_fires(self):
        from simtpu.workloads.cron import parse_schedule

        sched = parse_schedule("0 0 31 2 *")  # Feb 31st never exists
        assert sched.next_fire(0.0, limit_days=900) is None
