"""Cold-start pipeline tests (`simtpu/engine/precompile.py`): parallel AOT
precompilation races, bit-identical placements with the pipeline on/off,
loud fallback, and the stretch-group fetch coalescing of the bulk dispatch.
"""

from __future__ import annotations

import numpy as np

from simtpu.core.objects import ResourceTypes, set_label
from simtpu.core.tensorize import Tensorizer
from simtpu import constants as C
from simtpu.synth import make_deployment, make_node
from simtpu.workloads.expand import get_valid_pods_exclude_daemonset


def _mixed_pods():
    """A pod list whose runs alternate bulk KINDS: plain threshold rounds,
    matrix rounds (multi-GPU), and domain-quota rounds (DoNotSchedule
    spread) — at least three kind-stretches in one dispatch."""
    res = ResourceTypes()
    res.deployments = [
        make_deployment("plain-a", 24, 100, 128),
        make_deployment("gpu-multi", 24, 100, 128, gpu_mem_mib=1000, gpu_count=2),
        make_deployment("plain-b", 24, 100, 128),
        make_deployment(
            "spread", 24, 100, 128,
            spread_topo="topology.kubernetes.io/zone", spread_hard=True,
        ),
    ]
    pods = get_valid_pods_exclude_daemonset(res)
    for pod in pods:
        set_label(pod, C.LABEL_APP_NAME, "mix")
    return pods


def _nodes(n=8):
    return [
        make_node(
            f"node-{i:03d}", 8000, 32,
            {
                "kubernetes.io/hostname": f"node-{i:03d}",
                "topology.kubernetes.io/zone": f"zone-{i % 4}",
            },
            gpu=(4, 16000),
        )
        for i in range(n)
    ]


def _place(pods, precompile: bool, engine_cls=None, wait_first: bool = False):
    from simtpu.engine.rounds import RoundsEngine

    tz = Tensorizer(_nodes())
    batch = tz.add_pods(pods)
    eng = (engine_cls or RoundsEngine)(tz)
    pipe = None
    if precompile:
        from simtpu.engine.precompile import precompile_place

        pipe = precompile_place(eng, batch)
        if wait_first:
            pipe.wait_all()
    nodes, reasons, _ = eng.place(batch)
    return np.asarray(nodes), np.asarray(reasons), pipe


def test_bulk_placements_bit_identical_with_pipeline():
    """Acceptance pin: the pipeline changes when/where compilation happens,
    never what executes — nodes and reasons byte-equal on/off."""
    pods = _mixed_pods()
    n_off, r_off, _ = _place(pods, precompile=False)
    n_on, r_on, pipe = _place(pods, precompile=True)
    assert np.array_equal(n_off, n_on)
    assert np.array_equal(r_off, r_on)
    pipe.wait_all()
    s = pipe.stats()
    assert s["submitted"] > 0
    assert s["failures"] == 0, s
    assert s["hits"] > 0, s


def test_concurrent_precompile_one_executable_per_signature():
    """The race pin: place() starts while the background compiles are still
    in flight; every dispatch whose signature is enumerated must WAIT on
    the in-flight compile rather than compiling its own copy — observable
    as exactly one jit trace per distinct executable (trace counters bump
    once per trace, shared by the AOT lowering and the jit path)."""
    import jax

    from simtpu.engine.scan import COMPILE_COUNT_KINDS
    from simtpu.obs.metrics import family as metrics_family

    def trace_counts():
        return metrics_family("compile", COMPILE_COUNT_KINDS)

    jax.clear_caches()  # compile accounting must start cold
    pods = _mixed_pods()
    c0 = trace_counts()
    # eager dispatch against in-flight compiles (wait_first=False)
    n_on, r_on, pipe = _place(pods, precompile=True, wait_first=False)
    pipe.wait_all()
    s = pipe.stats()
    delta = {k: trace_counts()[k] - c0.get(k, 0) for k in trace_counts()}
    # one executable per signature: had a dispatch compiled its own copy
    # next to the background one, the trace count would exceed the number
    # of distinct submitted + missed signatures
    assert s["failures"] == 0, s
    assert s["misses"] == 0, s  # full-capacity scenario: no leftover probes
    assert delta["rounds"] + delta["scan"] == s["submitted"], (delta, s)
    # and the results are the no-pipeline results
    n_off, r_off, _ = _place(pods, precompile=False)
    assert np.array_equal(n_off, n_on)
    assert np.array_equal(r_off, r_on)


def test_serial_engine_pipeline_identical():
    from simtpu.engine.scan import Engine

    pods = _mixed_pods()[:200]
    n_off, r_off, _ = _place(pods, precompile=False, engine_cls=Engine)
    n_on, r_on, pipe = _place(pods, precompile=True, engine_cls=Engine)
    assert np.array_equal(n_off, n_on)
    assert np.array_equal(r_off, r_on)
    pipe.wait_all()
    assert pipe.stats()["failures"] == 0


def test_stretch_group_fetch_coalescing():
    """Consecutive bulk stretches of DIFFERENT kinds must share ONE
    blocking device→host fetch (the stretch-group coalescing): the mixed
    batch has >= 3 kind-stretches and no scan segments or leftovers, so
    the whole placement pays exactly one fetch."""
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.obs.metrics import family as metrics_family

    from simtpu.engine.scan import FETCH_KEYS

    def fetch_counts():
        return metrics_family("fetch", FETCH_KEYS)

    pods = _mixed_pods()
    tz = Tensorizer(_nodes())
    batch = tz.add_pods(pods)
    eng = RoundsEngine(tz)
    segments = eng._segments(batch, tz.freeze())
    kinds = [k for k, _, _ in segments]
    assert "scan" not in kinds
    assert len(set(kinds)) >= 3, kinds  # distinct bulk kinds interleave
    f0 = fetch_counts()["get"]
    nodes, _, _ = eng.place(batch)
    assert fetch_counts()["get"] - f0 == 1
    assert (np.asarray(nodes) >= 0).all()  # no leftovers in this scenario


def test_failed_compile_falls_back_loud(caplog):
    """A background compile failure must fall back to the jit path AND
    warn — never silently."""
    import logging

    from simtpu.engine.precompile import AotPipeline, _sds

    class _Boom:
        def lower(self, *args, **kwargs):
            raise RuntimeError("AOT lowering unsupported here")

    pipe = AotPipeline(workers=1)
    arg = np.zeros(3, np.float32)
    pipe.submit("boom", (), _Boom(), (_sds((3,), np.float32),))
    pipe.wait_all()
    with caplog.at_level(logging.WARNING, logger="simtpu.precompile"):
        out = pipe.call("boom", (), (arg,), lambda: "fell-back")
    assert out == "fell-back"
    assert pipe.stats()["failures"] == 1
    assert any("AOT precompile" in rec.message for rec in caplog.records)
    # second call falls back again but does not re-warn (loud once)
    n_warn = len(caplog.records)
    out = pipe.call("boom", (), (arg,), lambda: "fell-back-2")
    assert out == "fell-back-2"
    assert len(caplog.records) == n_warn
    pipe.shutdown()


def test_unknown_signature_misses_to_jit_path():
    from simtpu.engine.precompile import AotPipeline

    pipe = AotPipeline(workers=1)
    out = pipe.call("never-submitted", (), (np.zeros(2, np.float32),), lambda: 7)
    assert out == 7
    assert pipe.stats()["misses"] == 1
    pipe.shutdown()


def test_incremental_plan_precompile_identical():
    """plan_capacity_incremental(precompile=True) answers exactly what the
    un-pipelined plan answers (shared-registry probe/verify engines
    included)."""
    from simtpu.plan.incremental import plan_capacity_incremental
    from simtpu.workloads.expand import seed_name_hashes
    from simtpu.core.objects import AppResource

    cluster = ResourceTypes()
    cluster.nodes = [
        make_node(
            f"node-{i:03d}", 8000, 32, {"kubernetes.io/hostname": f"node-{i:03d}"}
        )
        for i in range(4)
    ]
    res = ResourceTypes()
    res.deployments = [make_deployment(f"dep-{j}", 30, 1000, 512) for j in range(2)]
    apps = [AppResource(name="a", resource=res)]
    template = make_node("tmpl", 16000, 64, {"kubernetes.io/hostname": "tmpl"})

    seed_name_hashes(5)
    base = plan_capacity_incremental(
        cluster, apps, template, max_new_nodes=40, precompile=False
    )
    seed_name_hashes(5)
    piped = plan_capacity_incremental(
        cluster, apps, template, max_new_nodes=40, precompile=True
    )
    assert base.success and piped.success
    assert piped.nodes_added == base.nodes_added
    assert "compile_wall" in piped.timings
    assert "compile_wall" not in base.timings


def test_fault_sweep_signature_failed_aot_falls_back_loud(caplog, monkeypatch):
    """The scenario-batched fault-sweep signature gets the same loud
    warn-and-fallback contract as the scan/bulk signatures (ISSUE 6
    satellite): a failed background compile of the "fault_sweep"
    executable warns ONCE, every chunk falls back to the plain jit, and
    the sweep's outcome is identical to the un-pipelined run."""
    import logging

    import simtpu.faults.sweep as sweep_mod
    from simtpu.engine.precompile import AotPipeline
    from simtpu.faults import generate_scenarios, place_cluster, sweep_scenarios
    from simtpu.synth import synth_apps, synth_cluster

    cluster = synth_cluster(8, seed=13, zones=2)
    apps = synth_apps(24, seed=14, zones=2, pods_per_deployment=8)
    pc = place_cluster(cluster, apps)
    scen = generate_scenarios(cluster.nodes, "k=1")
    base = sweep_scenarios(pc, scen, s_chunk=4)

    class _NoLower:
        """The compiled sweep entry point with AOT lowering broken: the
        background compile fails, the jit fallback still works."""

        def __init__(self, real):
            self.real = real

        def lower(self, *args, **kwargs):
            raise RuntimeError("AOT lowering rejected (injected)")

        def __call__(self, *args, **kwargs):
            return self.real(*args, **kwargs)

    monkeypatch.setattr(
        sweep_mod, "_fault_sweep", _NoLower(sweep_mod._fault_sweep)
    )
    pipe = AotPipeline(workers=1)
    try:
        with caplog.at_level(logging.WARNING, logger="simtpu.precompile"):
            out = sweep_scenarios(pc, scen, s_chunk=4, pipeline=pipe)
        assert pipe.stats()["failures"] >= 1
        warned = [
            rec for rec in caplog.records if "fault_sweep" in rec.message
        ]
        assert len(warned) == 1  # loud once, not per chunk
        assert np.array_equal(out.requeue_rows, base.requeue_rows)
        assert np.array_equal(out.requeue_nodes, base.requeue_nodes)
        assert np.array_equal(out.requeue_reasons, base.requeue_reasons)
    finally:
        pipe.shutdown()
