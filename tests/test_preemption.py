"""Tests for the DefaultPreemption analog (`simtpu/api.py _try_preempt`,
mirroring `vendor/.../plugins/defaultpreemption/default_preemption.go`).
"""

from __future__ import annotations

import os

import pytest

from simtpu.api import simulate

# wall-clock envelopes only fire on dedicated perf runs (advisor low, round
# 4): explicit opt-in, anything else keeps them off
_PERF_ASSERT = os.environ.get("SIMTPU_PERF_ASSERT", "").lower() in ("1", "true", "yes", "on")
from simtpu.core.objects import ResourceTypes  # noqa: E402

from .fixtures import (  # noqa: E402
    make_fake_node,
    make_fake_pod,
    with_node_labels,
    with_pod_affinity,
    with_pod_labels,
)


def _prio(pod, p):
    pod["spec"]["priority"] = p
    return pod


def _placements(result):
    out = {}
    for status in result.node_status:
        for pod in status.pods:
            out[pod["metadata"]["name"]] = status.node["metadata"]["name"]
    return out


def test_high_priority_pod_preempts_lower():
    node = make_fake_node("n0", "10", "16Gi")
    fillers = [
        _prio(make_fake_pod(f"low{i}", "default", "4", "1Gi"), 0) for i in range(2)
    ]
    vip = _prio(make_fake_pod("vip", "default", "6", "1Gi"), 1000)
    result = simulate(ResourceTypes(nodes=[node], pods=fillers + [vip]))
    placed = _placements(result)
    assert "vip" in placed
    assert len(result.preempted_pods) == 1
    assert result.preempted_pods[0].pod["metadata"]["name"].startswith("low")
    assert result.preempted_pods[0].preempted_by == "default/vip"
    assert result.preempted_pods[0].node == "n0"
    # one low pod survives: 4 + 6 = 10 cpu
    assert sum(1 for name in placed if name.startswith("low")) == 1
    assert not result.unscheduled_pods


def test_equal_priority_does_not_preempt():
    node = make_fake_node("n0", "10", "16Gi")
    fillers = [
        _prio(make_fake_pod(f"low{i}", "default", "4", "1Gi"), 10) for i in range(2)
    ]
    pod = _prio(make_fake_pod("late", "default", "6", "1Gi"), 10)
    result = simulate(ResourceTypes(nodes=[node], pods=fillers + [pod]))
    assert not result.preempted_pods
    assert len(result.unscheduled_pods) == 1
    assert result.unscheduled_pods[0].pod["metadata"]["name"] == "late"


def test_picks_node_with_lowest_victim_priority():
    # n0 carries a prio-50 pod, n1 a prio-5 pod; preemptor (prio 100) must
    # evict from n1 (lowest max victim priority)
    n0 = make_fake_node("n0", "4", "16Gi")
    n1 = make_fake_node("n1", "4", "16Gi")
    p0 = _prio(make_fake_pod("mid", "default", "4", "1Gi"), 50)
    p0["spec"]["nodeName"] = "n0"
    p1 = _prio(make_fake_pod("small", "default", "4", "1Gi"), 5)
    p1["spec"]["nodeName"] = "n1"
    vip = _prio(make_fake_pod("vip", "default", "3", "1Gi"), 100)
    result = simulate(ResourceTypes(nodes=[n0, n1], pods=[p0, p1, vip]))
    placed = _placements(result)
    assert placed.get("vip") == "n1"
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["small"]


def test_minimal_victim_set():
    # evicting ONE 2-cpu victim suffices for the 2-cpu preemptor; both lows
    # must not be evicted
    node = make_fake_node("n0", "8", "16Gi")
    fillers = [
        _prio(make_fake_pod(f"low{i}", "default", "2", "1Gi"), 0) for i in range(4)
    ]
    vip = _prio(make_fake_pod("vip", "default", "2", "1Gi"), 9)
    result = simulate(ResourceTypes(nodes=[node], pods=fillers + [vip]))
    assert len(result.preempted_pods) == 1
    assert not result.unscheduled_pods


def test_mid_batch_failure_keeps_bookkeeping_aligned():
    # the failing pod is NOT last in its batch: a pod placed after it in the
    # same batch must not skew the engine-log ↔ simulator bookkeeping
    node = make_fake_node("n0", "10", "16Gi")
    pods = [
        _prio(make_fake_pod("low0", "default", "4", "1Gi"), 0),
        _prio(make_fake_pod("low1", "default", "4", "1Gi"), 0),
        _prio(make_fake_pod("vip", "default", "6", "1Gi"), 1000),
        _prio(make_fake_pod("tiny", "default", "1", "1Gi"), 0),
    ]
    result = simulate(ResourceTypes(nodes=[node], pods=pods))
    placed = _placements(result)
    # low0+low1+tiny place first (9 cpu); vip preempts the minimal victim
    # set {tiny, low1} (latest lowest-priority placements) and lands
    assert "vip" in placed
    assert not result.unscheduled_pods
    names = {p.pod["metadata"]["name"] for p in result.preempted_pods}
    assert names == {"tiny", "low1"}
    assert set(placed) == {"low0", "vip"}


def test_wave_commit_never_rides_restored_victims():
    """Advisor finding (round 4): in a preemption wave, a pod committed
    before the first verify failure f may have verify-landed on a node that
    only had room because of f's evictions (the batched placement applies
    ALL wave evictions).  Restoring f's victims under it silently
    overcommits the node — impossible in the serial evict/retry/undo flow.

    Construction: preemptors A (10 cpu) and B (20 cpu) both fail and wave
    together.  A's proposal evicts fA on nA (lowest victim priority), B's
    evicts fB on nB.  With both evictions applied, the score pipeline sends
    A to the roomier nB; B then cannot fit and fails verify.  The buggy
    flow committed A on nB and restored fB beside it (30 cpu on a 20-cpu
    node).  The fixed flow demotes A, lets B's authoritative retry land on
    nB, and re-verifies A — converging to the serial-exact placement."""
    nA = make_fake_node("nA", "10", "16Gi")
    nB = make_fake_node("nB", "20", "32Gi")
    fA = _prio(make_fake_pod("fa", "default", "10", "1Gi"), 0)
    fA["spec"]["nodeName"] = "nA"
    fB = _prio(make_fake_pod("fb", "default", "20", "2Gi"), 1)
    fB["spec"]["nodeName"] = "nB"
    a = _prio(make_fake_pod("a", "default", "10", "1Gi"), 100)
    b = _prio(make_fake_pod("b", "default", "20", "2Gi"), 100)
    result = simulate(ResourceTypes(nodes=[nA, nB], pods=[fA, fB, a, b]))
    placed = _placements(result)
    # the serial flow places both preemptors, evicting both fillers
    assert placed.get("a") == "nA"
    assert placed.get("b") == "nB"
    assert not result.unscheduled_pods
    assert {p.pod["metadata"]["name"] for p in result.preempted_pods} == {"fa", "fb"}
    # the no-overcommit invariant the buggy flow violated: per-node summed
    # cpu requests within allocatable
    cap = {"nA": 10.0, "nB": 20.0}
    used: dict = {}
    for status in result.node_status:
        name = status.node["metadata"]["name"]
        for pod in status.pods:
            cpu = pod["spec"]["containers"][0]["resources"]["requests"]["cpu"]
            used[name] = used.get(name, 0.0) + float(cpu)
    for name, total in used.items():
        assert total <= cap[name] + 1e-9, (name, total)


def test_affinity_dependent_head_not_finalized():
    """ADVICE r5 #3 regression: a retried head whose verify success depends
    on another wave pod BEING placed (required positive affinity to it)
    must not be finalized by retry finality — the head verifies FIRST in
    its wave, so its fresh attempt never sees the anchor pod placed.

    Construction: both nodes are full of prio-0 fillers.  X (needs
    colocation with app=anchor on a hostname domain) and D (carries
    app=anchor) both fail on resources and wave together, X first.  X's
    verify keeps failing on inter-pod affinity until D lands; the old
    finality rule recorded X unscheduled on its second fresh failure.  With
    the exemption, X re-queues BEHIND D, D places, and X colocates."""
    n0 = make_fake_node(
        "n0", "10", "16Gi", with_node_labels({"kubernetes.io/hostname": "n0"})
    )
    n1 = make_fake_node(
        "n1", "10", "16Gi", with_node_labels({"kubernetes.io/hostname": "n1"})
    )
    f0 = _prio(make_fake_pod("f0", "default", "10", "1Gi"), 0)
    f0["spec"]["nodeName"] = "n0"
    f1 = _prio(make_fake_pod("f1", "default", "10", "1Gi"), 0)
    f1["spec"]["nodeName"] = "n1"
    x = _prio(
        make_fake_pod(
            "x", "default", "5", "1Gi",
            with_pod_affinity({
                "podAffinity": {
                    "requiredDuringSchedulingIgnoredDuringExecution": [
                        {
                            "labelSelector": {"matchLabels": {"app": "anchor"}},
                            "topologyKey": "kubernetes.io/hostname",
                        }
                    ]
                }
            }),
        ),
        100,
    )
    d = _prio(
        make_fake_pod(
            "d", "default", "5", "1Gi", with_pod_labels({"app": "anchor"})
        ),
        100,
    )
    result = simulate(ResourceTypes(nodes=[n0, n1], pods=[f0, f1, x, d]))
    placed = _placements(result)
    assert not result.unscheduled_pods, [
        u.reason for u in result.unscheduled_pods
    ]
    # the affinity actually binds: x shares d's node
    assert placed.get("x") == placed.get("d")


def test_preempts_port_holder():
    import copy

    node = make_fake_node("n0", "32", "64Gi")
    low = _prio(make_fake_pod("low", "default", "1", "1Gi"), 0)
    low["spec"]["containers"][0]["ports"] = [
        {"containerPort": 80, "hostPort": 80, "protocol": "TCP"}
    ]
    vip = _prio(copy.deepcopy(low), 100)
    vip["metadata"]["name"] = "vip"
    result = simulate(ResourceTypes(nodes=[node], pods=[low, vip]))
    placed = _placements(result)
    assert "vip" in placed
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["low"]
    assert not result.unscheduled_pods


def test_static_failures_never_preempt():
    node = make_fake_node("n0", "10", "16Gi")
    filler = _prio(make_fake_pod("low", "default", "9", "1Gi"), 0)
    vip = _prio(make_fake_pod("vip", "default", "1", "1Gi"), 1000)
    vip["spec"]["nodeSelector"] = {"nonexistent": "label"}
    result = simulate(ResourceTypes(nodes=[node], pods=[filler, vip]))
    assert not result.preempted_pods
    assert len(result.unscheduled_pods) == 1


def _with_labels(pod, labels):
    pod["metadata"]["labels"] = dict(labels)
    return pod


def _pdb(name, ns, match_labels, allowed=0):
    return {
        "apiVersion": "policy/v1beta1",
        "kind": "PodDisruptionBudget",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"selector": {"matchLabels": dict(match_labels)}},
        "status": {"disruptionsAllowed": allowed},
    }


def test_pdb_flips_the_chosen_victim_node():
    """pickOneNode criterion 1: a node whose victim set violates no PDB wins
    over an otherwise-identical node whose victim is PDB-covered
    (`default_preemption.go` pickOneNodeForPreemption + 
    filterPodsWithPDBViolation)."""
    n0 = make_fake_node("n0", "4", "16Gi")
    n1 = make_fake_node("n1", "4", "16Gi")
    covered = _with_labels(
        _prio(make_fake_pod("covered", "default", "4", "1Gi"), 0),
        {"app": "critical-db"},
    )
    covered["spec"]["nodeName"] = "n0"
    free = _prio(make_fake_pod("free", "default", "4", "1Gi"), 0)
    free["spec"]["nodeName"] = "n1"
    vip = _prio(make_fake_pod("vip", "default", "3", "1Gi"), 100)
    pdb = _pdb("db-pdb", "default", {"app": "critical-db"}, allowed=0)
    cluster = ResourceTypes(nodes=[n0, n1], pods=[covered, free, vip])
    cluster.pod_disruption_budgets = [pdb]
    result = simulate(cluster)
    placed = _placements(result)
    # without the PDB, the tie-break key is identical for both nodes and the
    # lowest node index (n0) would win; the PDB flips the choice to n1
    assert placed.get("vip") == "n1"
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["free"]
    assert placed.get("covered") == "n0"


def test_pdb_budget_permits_disruption():
    """A PDB with disruptionsAllowed >= victims does not penalize the node."""
    n0 = make_fake_node("n0", "4", "16Gi")
    n1 = make_fake_node("n1", "4", "16Gi")
    covered = _with_labels(
        _prio(make_fake_pod("covered", "default", "4", "1Gi"), 0),
        {"app": "web"},
    )
    covered["spec"]["nodeName"] = "n0"
    # n1's victim has HIGHER priority, so n0 wins on criterion 2 once its
    # budgeted PDB contributes zero violations
    pricey = _prio(make_fake_pod("pricey", "default", "4", "1Gi"), 50)
    pricey["spec"]["nodeName"] = "n1"
    vip = _prio(make_fake_pod("vip", "default", "3", "1Gi"), 100)
    cluster = ResourceTypes(nodes=[n0, n1], pods=[covered, pricey, vip])
    cluster.pod_disruption_budgets = [_pdb("web-pdb", "default", {"app": "web"}, allowed=1)]
    result = simulate(cluster)
    placed = _placements(result)
    assert placed.get("vip") == "n0"
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["covered"]


def test_pdb_prefers_uncovered_victim_within_node():
    """Victim greed keeps PDB-covered pods placed when an uncovered victim
    suffices (the reference reprieves violating victims preferentially)."""
    node = make_fake_node("n0", "6", "16Gi")
    covered = _with_labels(
        _prio(make_fake_pod("covered", "default", "2", "1Gi"), 0),
        {"app": "db"},
    )
    free = _prio(make_fake_pod("free", "default", "2", "1Gi"), 0)
    vip = _prio(make_fake_pod("vip", "default", "4", "1Gi"), 100)
    cluster = ResourceTypes(nodes=[node], pods=[covered, free, vip])
    cluster.pod_disruption_budgets = [_pdb("db-pdb", "default", {"app": "db"}, allowed=0)]
    result = simulate(cluster)
    placed = _placements(result)
    assert placed.get("vip") == "n0"
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["free"]
    assert placed.get("covered") == "n0"


def test_empty_pdb_selector_matches_nothing():
    """filterPodsWithPDBViolation: a PDB with a nil or empty selector
    matches nothing (unlike the general LabelSelector empty-matches-all)."""
    n0 = make_fake_node("n0", "4", "16Gi")
    n1 = make_fake_node("n1", "4", "16Gi")
    a = _with_labels(_prio(make_fake_pod("a", "default", "4", "1Gi"), 0), {"x": "1"})
    a["spec"]["nodeName"] = "n0"
    b = _with_labels(_prio(make_fake_pod("b", "default", "4", "1Gi"), 0), {"x": "2"})
    b["spec"]["nodeName"] = "n1"
    vip = _prio(make_fake_pod("vip", "default", "3", "1Gi"), 100)
    cluster = ResourceTypes(nodes=[n0, n1], pods=[a, b, vip])
    empty = {
        "apiVersion": "policy/v1beta1",
        "kind": "PodDisruptionBudget",
        "metadata": {"name": "catch-all", "namespace": "default"},
        "spec": {"selector": {}},
        "status": {"disruptionsAllowed": 0},
    }
    cluster.pod_disruption_budgets = [empty]
    result = simulate(cluster)
    placed = _placements(result)
    # no PDB matches: plain tie-break picks the lowest node index
    assert placed.get("vip") == "n0"
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["a"]


def test_pdb_with_budget_does_not_penalize_covered_victim():
    """Budget-aware reprieve split: a victim whose PDB still absorbs the
    eviction (disruptionsAllowed=1) is NON-violating and ranks purely by
    priority — the priority-0 covered pod is evicted, not the priority-50
    uncovered one."""
    node = make_fake_node("n0", "6", "16Gi")
    covered = _with_labels(
        _prio(make_fake_pod("covered", "default", "2", "1Gi"), 0),
        {"app": "web"},
    )
    pricey = _prio(make_fake_pod("pricey", "default", "2", "1Gi"), 50)
    vip = _prio(make_fake_pod("vip", "default", "4", "1Gi"), 100)
    cluster = ResourceTypes(nodes=[node], pods=[covered, pricey, vip])
    cluster.pod_disruption_budgets = [_pdb("web-pdb", "default", {"app": "web"}, allowed=1)]
    result = simulate(cluster)
    placed = _placements(result)
    assert placed.get("vip") == "n0"
    assert [p.pod["metadata"]["name"] for p in result.preempted_pods] == ["covered"]
    assert placed.get("pricey") == "n0"


@pytest.mark.slow
def test_preemption_at_100k_scale():
    """VERDICT r3 task 2: preemption at the scale round 2 actually asked for
    — a placement log of 100,000 pods and >= 1,000 forced preemptions.
    The wave machinery (api.py _preempt_failed_batch) makes this a handful
    of device dispatches: host-side victim proposals against one shared
    whole-log model, one batched eviction delta, one batched verify
    placement (the 1,100 preemptors are one run, so the verify itself is a
    bulk round). Semantics pinned exactly: every high-priority pod lands,
    each evicting precisely the two 1-cpu victims its 2-cpu request needs.

    Measured 2026-07-31 (CPU, shared host): 113 s end-to-end including the
    100k-pod initial bulk placement and jit compiles; docs/status.md keeps
    the number. The envelope below is deliberately loose for slow CI."""
    import time

    from simtpu.core.objects import AppResource, ResourceTypes
    from simtpu.synth import make_deployment, make_node

    n = 6250
    cluster = ResourceTypes()
    cluster.nodes = [
        make_node(
            f"node-{i:06d}",
            16000,
            64,
            {
                "topology.kubernetes.io/zone": f"zone-{i % 8}",
                "kubernetes.io/hostname": f"node-{i:06d}",
            },
        )
        for i in range(n)
    ]
    low = make_deployment("low", n * 16, 1000, 512)
    low["spec"]["template"]["spec"]["priority"] = 10
    high = make_deployment("high", 1100, 2000, 1024)
    high["spec"]["template"]["spec"]["priority"] = 1000
    res_low = ResourceTypes()
    res_low.deployments = [low]
    res_high = ResourceTypes()
    res_high.deployments = [high]
    apps = [
        AppResource(name="low", resource=res_low),
        AppResource(name="high", resource=res_high),
    ]
    from simtpu.workloads.expand import seed_name_hashes

    seed_name_hashes(1)
    t0 = time.perf_counter()
    out = simulate(cluster, apps, bulk=True)
    wall = time.perf_counter() - t0
    placed = sum(len(s.pods) for s in out.node_status)
    assert len(out.unscheduled_pods) == 0
    assert len(out.preempted_pods) == 2 * 1100
    assert placed == n * 16 - 2 * 1100 + 1100
    # wall-clock envelope only on dedicated perf runs (advisor low, round
    # 4): a loaded shared CI host can exceed it without anything being
    # wrong; functional runs still pin placement/preemption counts above
    if _PERF_ASSERT:
        assert wall < 420, f"100k-scale preemption too slow: {wall:.1f}s"


def test_preemption_at_scale():
    """VERDICT r2 task 5: hundreds of preemptions against a placement log of
    thousands of entries must run in seconds — the victim search is
    vectorized over the whole log (api.py) and evictions update the carried
    device state incrementally instead of rebuilding it (engine/scan.py).
    Semantics pinned: every high-priority pod lands, every eviction is
    recorded, and the displaced capacity matches exactly."""
    import time

    from simtpu.core.objects import AppResource, ResourceTypes
    from simtpu.synth import make_deployment, make_node

    n = 300
    cluster = ResourceTypes()
    cluster.nodes = [
        make_node(
            f"node-{i:06d}",
            4000,
            16,
            {
                "topology.kubernetes.io/zone": f"zone-{i % 4}",
                "kubernetes.io/hostname": f"node-{i:06d}",
            },
        )
        for i in range(n)
    ]
    low = make_deployment("low", n * 4, 1000, 512)
    low["spec"]["template"]["spec"]["priority"] = 10
    high = make_deployment("high", 250, 2000, 1024)
    high["spec"]["template"]["spec"]["priority"] = 1000
    res_low = ResourceTypes()
    res_low.deployments = [low]
    res_high = ResourceTypes()
    res_high.deployments = [high]
    apps = [
        AppResource(name="low", resource=res_low),
        AppResource(name="high", resource=res_high),
    ]
    from simtpu.workloads.expand import seed_name_hashes

    seed_name_hashes(1)
    t0 = time.perf_counter()
    out = simulate(cluster, apps, bulk=True)
    wall = time.perf_counter() - t0
    placed = sum(len(s.pods) for s in out.node_status)
    # every high-prio pod fits by evicting exactly two 1-cpu victims
    assert len(out.unscheduled_pods) == 0
    assert len(out.preempted_pods) == 2 * 250
    assert placed == n * 4 - 2 * 250 + 250
    # generous envelope: the pre-vectorization search alone took minutes
    if _PERF_ASSERT:
        assert wall < 120, f"preemption path too slow: {wall:.1f}s"


def test_wave_cap_abort_tags_failures_distinctly(caplog):
    """ADVICE r5 (`api.py` waves_left): when the termination cap trips, the
    still-pending preemptors are finalized with their ORIGINAL (stale)
    failure reason — the report must distinguish a cap abort from a genuine
    verify failure, and a warning must carry the remaining-pod count."""
    import logging

    from simtpu.api import PREEMPT_WAVE_CAP_NOTE, Simulator

    node = make_fake_node("n0", "10", "16Gi")
    fillers = [
        _prio(make_fake_pod(f"low{i}", "default", "4", "1Gi"), 0) for i in range(2)
    ]
    vip = _prio(make_fake_pod("vip", "default", "6", "1Gi"), 1000)

    sim = Simulator()
    sim.WAVE_CAP_SLACK = -100  # trip the cap on the first wave
    with caplog.at_level(logging.WARNING, logger="simtpu.api"):
        result = sim.run_cluster(
            ResourceTypes(nodes=[node], pods=fillers + [vip])
        )
    # the vip WOULD have preempted (test_high_priority_pod_preempts_lower);
    # the forced cap abort records it unscheduled with the distinct tag
    assert len(result.unscheduled_pods) == 1
    reason = result.unscheduled_pods[0].reason
    assert PREEMPT_WAVE_CAP_NOTE in reason
    assert "1 pod(s) unresolved" in reason
    assert any(
        "preemption wave cap exhausted with 1 pod(s)" in rec.getMessage()
        for rec in caplog.records
    )
    # the untagged path stays untagged
    sim2 = Simulator()
    result2 = sim2.run_cluster(
        ResourceTypes(nodes=[node], pods=fillers + [vip])
    )
    assert not result2.unscheduled_pods


def test_preemption_under_compact_rides_direct_delta(monkeypatch):
    """ISSUE 16 tentpole: with a compact carry, the batched eviction delta
    and every restore of a rejected wave ride the DIRECT compact apply —
    the expand -> apply -> recompress round trip never runs on the hot
    path (state.delta_direct > 0, state.expand/compress unchanged during
    the replay), and the full simulation outcome (placements, evictions,
    unscheduled set) is bit-identical to the SIMTPU_DELTA_DIRECT=0 path."""
    from simtpu.core.objects import AppResource
    from simtpu.obs.metrics import REGISTRY
    from simtpu.synth import make_deployment, make_node
    from simtpu.workloads.expand import seed_name_hashes

    def run():
        n = 24
        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"node-{i:06d}",
                4000,
                16,
                {
                    "topology.kubernetes.io/zone": f"zone-{i % 4}",
                    "kubernetes.io/hostname": f"node-{i:06d}",
                },
            )
            for i in range(n)
        ]
        # zone spread gives the problem tabular topology terms, so the
        # carry compresses; the capacity squeeze forces real preemptions
        low = make_deployment(
            "low", n * 4, 1000, 512, priority=10,
            spread_topo="topology.kubernetes.io/zone",
        )
        high = make_deployment(
            "high", 16, 2000, 1024, priority=1000,
            spread_topo="topology.kubernetes.io/zone",
        )
        res_low = ResourceTypes()
        res_low.deployments = [low]
        res_high = ResourceTypes()
        res_high.deployments = [high]
        apps = [
            AppResource(name="low", resource=res_low),
            AppResource(name="high", resource=res_high),
        ]
        seed_name_hashes(3)
        before = REGISTRY.snapshot()
        out = simulate(cluster, apps, bulk=True)
        after = REGISTRY.snapshot()
        delta = {
            k: after.get(k, 0) - before.get(k, 0)
            for k in ("state.delta_direct", "state.expand", "state.compress")
        }
        placements = tuple(sorted(_placements(out).items()))
        evicted = tuple(
            sorted(p.pod["metadata"]["name"] for p in out.preempted_pods)
        )
        unsched = tuple(
            sorted(p["metadata"]["name"] for p in out.unscheduled_pods)
        )
        return delta, placements, evicted, unsched

    monkeypatch.setenv("SIMTPU_DELTA_DIRECT", "1")
    d_direct, p_direct, e_direct, u_direct = run()
    assert e_direct, "scenario produced no preemptions — not exercising the path"
    assert d_direct["state.delta_direct"] > 0, d_direct

    monkeypatch.setenv("SIMTPU_DELTA_DIRECT", "0")
    d_ab, p_ab, e_ab, u_ab = run()
    assert d_ab["state.delta_direct"] == 0, d_ab
    # the placement dispatches themselves still expand/compress once per
    # round (the kernels run dense) — identically on both paths; every
    # EXTRA round trip in the A/B run is a delta replay the direct path
    # eliminated.  (tests/test_state_deltas.py pins the exact zero around
    # remove/restore in isolation.)
    extra = d_ab["state.expand"] - d_direct["state.expand"]
    assert extra >= d_direct["state.delta_direct"], (d_direct, d_ab)
    assert d_ab["state.compress"] - d_direct["state.compress"] == extra, (
        d_direct,
        d_ab,
    )
    assert p_direct == p_ab
    assert e_direct == e_ab
    assert u_direct == u_ab
