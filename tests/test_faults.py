"""Fault-injection subsystem tests (simtpu/faults, plan/resilience).

The load-bearing pin (ISSUE 4 acceptance): an exhaustive single-node
failure sweep through the batched scenario engine produces, for EVERY
scenario, the identical unplaced-pod set as the serial replay (drain the
node via the batch-delta API, rerun placement, undo).  Plus the satellite
properties: failure-free drains are strict no-ops, drained pods never
land on masked nodes (fuzzed over synth seeds), scenario generation is
deterministic, rack labels ride synth_cluster without disturbing
pre-existing RNG streams, and the sweep shards over the test mesh with
identical results.
"""

import numpy as np
import pytest

from simtpu import constants as C
from simtpu.faults import (
    domain_scenarios,
    drain_requeue,
    drain_simulator,
    generate_scenarios,
    k_node_scenarios,
    parse_fault_spec,
    place_cluster,
    serial_replay,
    single_node_scenarios,
    stack_scenarios,
    sweep_scenarios,
)
from simtpu.synth import make_node, synth_apps, synth_cluster


def _mixed_problem(node_seed=21, app_seed=22, n_nodes=10, n_pods=60):
    cluster = synth_cluster(
        n_nodes, seed=node_seed, zones=3, taint_frac=0.1,
        gpu_frac=0.3, storage_frac=0.3,
    )
    apps = synth_apps(
        n_pods, seed=app_seed, zones=3, pods_per_deployment=10,
        selector_frac=0.2, toleration_frac=0.1, anti_affinity_frac=0.3,
        gpu_frac=0.2, storage_frac=0.2,
    )
    return cluster, apps


@pytest.fixture(scope="module")
def placed():
    cluster, apps = _mixed_problem()
    return cluster, place_cluster(cluster, apps)


def _sweep_unplaced_sets(sw):
    out = []
    for s in range(len(sw.scenarios)):
        mask = (sw.requeue_rows[s] >= 0) & (sw.requeue_nodes[s] < 0)
        out.append(frozenset(int(x) for x in sw.requeue_rows[s][mask]))
    return out


class TestSweepSerialEquivalence:
    def test_exhaustive_single_node_matches_serial_replay(self, placed):
        """ISSUE 4 acceptance pin: batched sweep == serial replay on every
        single-node scenario — same unplaced-pod SETS, not just counts."""
        cluster, pc = placed
        scen = single_node_scenarios(pc.n_nodes, nodes=cluster.nodes)
        sw = sweep_scenarios(pc, scen)
        counts, sets = serial_replay(pc, scen)
        assert np.array_equal(sw.unplaced, counts)
        assert _sweep_unplaced_sets(sw) == sets
        # the sweep must have actually drained something somewhere
        assert sw.evicted.sum() > 0

    def test_domain_and_k2_scenarios_match_serial_replay(self, placed):
        cluster, pc = placed
        scen = stack_scenarios(
            [
                domain_scenarios(cluster.nodes, C.LABEL_ZONE),
                domain_scenarios(cluster.nodes, C.LABEL_RACK),
                k_node_scenarios(pc.n_nodes, 2, samples=12, seed=5),
            ]
        )
        sw = sweep_scenarios(pc, scen, s_chunk=8)
        counts, sets = serial_replay(pc, scen)
        assert np.array_equal(sw.unplaced, counts)
        assert _sweep_unplaced_sets(sw) == sets

    def test_sharded_sweep_identical(self, placed):
        """The mesh-sharded sweep (scenario axis over 'sweep', node axis
        over 'nodes') must not change one outcome."""
        from simtpu.parallel import make_mesh

        cluster, pc = placed
        scen = single_node_scenarios(pc.n_nodes, nodes=cluster.nodes)
        base = sweep_scenarios(pc, scen)
        mesh = make_mesh(sweep=2)  # 2-way scenario x 4-way node sharding
        sharded = sweep_scenarios(pc, scen, mesh=mesh, s_chunk=4)
        assert np.array_equal(base.unplaced, sharded.unplaced)
        assert np.array_equal(base.requeue_nodes, sharded.requeue_nodes)


class TestDrainProperties:
    @pytest.mark.parametrize("seed", [0, 23])
    def test_failure_free_drain_is_noop_and_masks_hold(self, seed):
        """Fuzz (ISSUE 4 satellite): an empty node mask drains nothing and
        leaves the engine log bit-identical; non-empty masks never see a
        drained pod reappear on a failed node, and restore=True returns
        the log to the base placement."""
        cluster, apps = _mixed_problem(
            node_seed=100 + seed, app_seed=200 + seed, n_nodes=8, n_pods=40
        )
        pc = place_cluster(cluster, apps)
        log_before = (
            list(pc.engine.placed_node),
            list(pc.engine.placed_group),
        )
        # failure-free scenario: strict no-op
        res = drain_requeue(pc, np.zeros(pc.n_nodes, bool), restore=True)
        assert len(res.evicted_rows) == 0 and res.unplaced == 0
        assert list(pc.engine.placed_node) == log_before[0]
        assert list(pc.engine.placed_group) == log_before[1]
        assert pc.engine.node_valid is None
        # and through the batched sweep: an all-False row survives trivially
        from simtpu.faults.scenarios import ScenarioSet

        empty = ScenarioSet(
            masks=np.zeros((1, pc.n_nodes), bool), labels=("none",)
        )
        sw = sweep_scenarios(pc, empty)
        assert sw.evicted[0] == 0 and sw.unplaced[0] == 0
        # non-empty masks: requeued placements avoid every failed node
        rng = np.random.default_rng(seed)
        for _ in range(3):
            mask = np.zeros(pc.n_nodes, bool)
            mask[rng.choice(pc.n_nodes, size=2, replace=False)] = True
            out = drain_requeue(pc, mask, restore=True)
            landed = out.requeue_nodes[out.requeue_nodes >= 0]
            assert not mask[landed].any(), "drained pod reappeared on a failed node"
            assert list(pc.engine.placed_node) == log_before[0]

    def test_restore_leaves_sweep_reproducible(self, placed):
        """After serial replays (drain+undo cycles) the batched sweep still
        reproduces its own results — the undo path restores the carried
        state the sweep reads."""
        cluster, pc = placed
        scen = single_node_scenarios(pc.n_nodes, nodes=cluster.nodes)
        first = sweep_scenarios(pc, scen)
        serial_replay(pc, scen, limit=3)
        second = sweep_scenarios(pc, scen)
        assert np.array_equal(first.unplaced, second.unplaced)
        assert np.array_equal(first.requeue_nodes, second.requeue_nodes)


class TestDrainSimulator:
    def test_preemption_honors_fault_mask(self):
        """Facade-level drain requeues through the full api.py flow; no pod
        of the final result sits on a failed node (including preemption
        landings), and DaemonSet pods die with the node."""
        from simtpu.api import Simulator
        from simtpu.core.objects import ResourceTypes, name_of
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

        from tests.fixtures import make_fake_pod, with_pod_node_name

        cluster, apps = _mixed_problem(n_nodes=6, n_pods=30)
        sim = Simulator()
        work = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        work.pods = get_valid_pods_exclude_daemonset(work)
        # statically bound pods die with their node like DaemonSet pods
        bound = [
            make_fake_pod(
                f"bound-{i}", "default", "100m", "64Mi",
                with_pod_node_name(f"node-{i:06d}"),
            )
            for i in range(6)
        ]
        work.pods += bound
        sim.run_cluster(work)
        for app in apps:
            sim.schedule_app(app)
        # fail the node hosting the most pods, so the drain is non-trivial
        counts = np.bincount(
            np.asarray(sim._engine.placed_node), minlength=len(cluster.nodes)
        )
        target = int(np.argmax(counts))
        mask = np.zeros(len(cluster.nodes), bool)
        mask[target] = True
        unsched_before = len(sim._unscheduled)
        res = drain_simulator(sim, mask)
        assert len(res.evicted_rows) > 0
        final = sim._result()
        failed_name = name_of(cluster.nodes[target])
        for status in final.node_status:
            if name_of(status.node) == failed_name:
                assert status.pods == [], "pods still on the failed node"
        # the bound pod of the failed node died with it: not re-placed
        # anywhere, not reported unschedulable
        bound_names = {f"bound-{target}"}
        placed_names = {
            name_of(p) for s in final.node_status for p in s.pods
        }
        assert not (bound_names & placed_names)
        assert all(
            name_of(u.pod) not in bound_names
            for u in sim._unscheduled[unsched_before:]
        )
        # the engine keeps the mask: later batches also avoid the node
        assert sim._engine.node_valid is not None
        assert not sim._engine.node_valid[target]


class TestScenarioModel:
    def test_k_scenarios_deterministic_and_distinct(self):
        a = k_node_scenarios(40, 2, samples=16, seed=3)
        b = k_node_scenarios(40, 2, samples=16, seed=3)
        assert np.array_equal(a.masks, b.masks)
        assert len(a) == 16
        assert len({m.tobytes() for m in a.masks}) == 16
        assert (a.masks.sum(axis=1) == 2).all()
        c = k_node_scenarios(40, 2, samples=16, seed=4)
        assert not np.array_equal(a.masks, c.masks)

    def test_k_exhaustive_when_budget_allows(self):
        s = k_node_scenarios(6, 2, samples=100, seed=0)
        assert len(s) == 15  # C(6, 2)

    def test_parse_spec(self):
        terms = parse_fault_spec("k=1,k=3:50,zone,label:foo/bar")
        assert terms[0] == {"kind": "k", "k": 1, "samples": None}
        assert terms[1] == {"kind": "k", "k": 3, "samples": 50}
        assert terms[2] == {"kind": "domain", "key": C.LABEL_ZONE}
        assert terms[3] == {"kind": "domain", "key": "foo/bar"}
        with pytest.raises(ValueError):
            parse_fault_spec("bogus")

    def test_domain_scenarios_cover_all_labeled_nodes(self):
        cluster = synth_cluster(12, seed=9, zones=3)
        zones = domain_scenarios(cluster.nodes, C.LABEL_ZONE)
        assert len(zones) == 3
        assert zones.masks.any(axis=0).all()  # every node is in some zone
        racks = domain_scenarios(cluster.nodes, C.LABEL_RACK)
        assert len(racks) >= 3
        # racks nest within zones: each rack mask stays inside one zone mask
        for rm in racks.masks:
            assert any((rm & ~zm).sum() == 0 for zm in zones.masks)

    def test_generate_valid_restriction(self):
        cluster = synth_cluster(8, seed=9, zones=2)
        valid = np.zeros(8, bool)
        valid[:5] = True
        scen = generate_scenarios(cluster.nodes, "k=1", valid=valid)
        assert len(scen) == 5
        assert not scen.masks[:, 5:].any()


class TestSynthRackSatellite:
    def test_rack_labels_present_and_stream_preserving(self):
        """Rack labels are stamped on every node, and their RNG draws are
        APPEND-ONLY: every other node field is identical with racks on or
        off (pre-existing seeds' streams — and the tests pinned to them —
        unchanged)."""
        with_racks = synth_cluster(20, seed=5, zones=4, taint_frac=0.3,
                                   gpu_frac=0.3, storage_frac=0.3)
        without = synth_cluster(20, seed=5, zones=4, taint_frac=0.3,
                                gpu_frac=0.3, storage_frac=0.3,
                                racks_per_zone=0)
        for a, b in zip(with_racks.nodes, without.nodes):
            labels_a = dict(a["metadata"]["labels"])
            rack = labels_a.pop(C.LABEL_RACK)
            assert rack.startswith(labels_a[C.LABEL_ZONE])
            assert labels_a == b["metadata"]["labels"]
            assert a["spec"] == b["spec"]
            assert a["status"] == b["status"]
            assert a["metadata"]["annotations"] == b["metadata"]["annotations"]


class TestPlanResilience:
    def test_plans_enough_nodes_to_survive_any_single_failure(self):
        """A cluster sized to just fit its pods needs extra nodes to
        survive k=1; the plan finds a count whose sweep fully survives."""
        from simtpu.plan.resilience import plan_resilience

        nodes = [
            make_node(
                f"n{i}", 8000, 32,
                {"kubernetes.io/hostname": f"n{i}",
                 "topology.kubernetes.io/zone": "zone-a"},
            )
            for i in range(4)
        ]
        from simtpu.core.objects import AppResource, ResourceTypes
        from simtpu.synth import make_deployment

        cluster = ResourceTypes()
        cluster.nodes = nodes
        res = ResourceTypes()
        res.deployments.append(make_deployment("web", 28, 1000, 256))
        apps = [AppResource(name="web", resource=res)]
        template = make_node(
            "tmpl", 8000, 32,
            {"kubernetes.io/hostname": "tmpl",
             "topology.kubernetes.io/zone": "zone-a"},
        )
        plan = plan_resilience(
            cluster, apps, template, k=1, max_new_nodes=8, seed=1
        )
        assert plan.success
        assert plan.nodes_added >= 1
        assert plan.sweep is not None and bool(plan.sweep.survived.all())
        # candidate 0 was probed and failed (28 pods fill 3 nodes' worth)
        assert plan.probes[0]["survived"] < plan.probes[0]["scenarios"]

    def test_assess_only_mode(self, placed):
        from simtpu.plan.resilience import plan_resilience

        cluster, _pc = placed
        apps = synth_apps(
            60, seed=22, zones=3, pods_per_deployment=10,
            selector_frac=0.2, toleration_frac=0.1, anti_affinity_frac=0.3,
            gpu_frac=0.2, storage_frac=0.2,
        )
        plan = plan_resilience(cluster, apps, None, k=1)
        assert plan.nodes_added in (0, C.MAX_NUM_NEW_NODE)
        assert 0 in plan.probes
        counters = plan.counters()
        assert "plan_resilience_s" in counters
