"""Byte-level transfer + carried-state telemetry (ISSUE 5): the
`fetch.*` round-trip/byte counters, the `state.*` per-plane carried-state
gauges (read off the obs registry — the legacy alias views are gone,
ISSUE 13), and their surfacing through `simtpu apply --json`'s engine
block — present and consistent under the SIMTPU_WAVEFRONT and
shard/no-shard A/Bs (the counters are observability, never behavior).
"""

from __future__ import annotations

import json

import numpy as np
import pytest

from simtpu.core.tensorize import Tensorizer
from simtpu.engine.rounds import RoundsEngine
from simtpu.engine.scan import FETCH_KEYS, Engine
from simtpu.engine.state import STATE_KEYS, CompactState, SchedState
from simtpu.obs.metrics import family as metrics_family


def fetch_counts():
    return metrics_family("fetch", FETCH_KEYS)


def state_gauge():
    return metrics_family("state", STATE_KEYS)
from simtpu.synth import make_node, synth_apps, synth_cluster
from simtpu.workloads.expand import get_valid_pods_exclude_daemonset


@pytest.fixture(scope="module")
def problem():
    cluster = synth_cluster(16, seed=61, zones=4, taint_frac=0.1)
    apps = synth_apps(
        48, seed=62, zones=4, pods_per_deployment=12,
        selector_frac=0.2, anti_affinity_frac=0.2, spread_frac=0.3,
    )
    pods = []
    for app in apps:
        pods.extend(get_valid_pods_exclude_daemonset(app.resource))
    return cluster, pods


def _place(cluster, pods, factory=RoundsEngine, speculate=False):
    tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    eng = factory(tz)
    if speculate:
        eng.speculate = True
    nodes, _, _ = eng.place(tz.add_pods(pods))
    return eng, nodes


class TestFetchCounters:
    @pytest.mark.parametrize("speculate", [False, True])
    def test_monotone_and_bytes_move(self, problem, speculate):
        """Every placement pays >= 1 blocking fetch and its payload bytes;
        both counters only ever grow — under the pod-at-a-time scan AND
        the speculative wavefront dispatcher (SIMTPU_WAVEFRONT A/B)."""
        cluster, pods = problem
        before = fetch_counts()
        assert set(before) == {"get", "bytes"}
        _, nodes = _place(cluster, pods, Engine, speculate=speculate)
        after = fetch_counts()
        assert after["get"] > before["get"]
        assert after["bytes"] > before["bytes"]
        # a placement's outputs are at least one int32 per pod (nodes +
        # reasons ride one batched fetch)
        assert after["bytes"] - before["bytes"] >= nodes.size * 4


class TestStateGauge:
    def test_gauge_tracks_last_store(self, problem):
        cluster, pods = problem
        eng, _ = _place(cluster, pods)
        g = state_gauge()
        assert g["carried_bytes"] > 0
        assert g["dense_bytes"] >= g["carried_bytes"]
        assert g["compact"] == isinstance(eng.last_state, CompactState)
        fields = (
            CompactState._fields if g["compact"] else SchedState._fields
        )
        assert set(g["planes"]) == set(fields)
        assert sum(g["planes"].values()) == g["carried_bytes"]

    def test_gauge_survives_compact_off(self, problem, monkeypatch):
        monkeypatch.setenv("SIMTPU_COMPACT", "0")
        cluster, pods = problem
        eng, _ = _place(cluster, pods)
        g = state_gauge()
        assert isinstance(eng.last_state, SchedState)
        assert g["compact"] is False
        assert g["carried_bytes"] == g["dense_bytes"] > 0


class TestApplyJsonEngineBlock:
    """plan.engine (the `simtpu apply --json` engine block) carries the
    fetch/state-byte telemetry, under both the sharded and unsharded
    planner (--shard/--no-shard A/B)."""

    def _applier(self, shard):
        from simtpu.plan import capacity as cap

        cluster = synth_cluster(6, seed=63, zones=3, taint_frac=0.0)
        apps = synth_apps(
            240, seed=64, zones=3, pods_per_deployment=40,
            selector_frac=0.0, toleration_frac=0.0, spread_frac=0.2,
        )
        template = make_node(
            "tmpl", 64000, 256,
            {"kubernetes.io/hostname": "tmpl",
             "topology.kubernetes.io/zone": "zone-plan"},
        )
        applier = cap.Applier.__new__(cap.Applier)
        applier.opts = cap.ApplierOptions(
            search="incremental", shard=shard, precompile=False
        )
        applier.load_apps = lambda: list(apps)
        applier.load_cluster = lambda: cluster
        applier.load_new_node = lambda: template
        return applier

    @pytest.mark.parametrize("shard", [False, True])
    def test_engine_block_fields(self, shard):
        plan = self._applier(shard).run()
        assert plan.success, plan.message
        eng = plan.engine
        assert set(eng["fetch"]) == {"get", "bytes"}
        assert eng["fetch"]["get"] > 0 and eng["fetch"]["bytes"] > 0
        assert isinstance(eng["compact"], bool)
        sb = eng["state_bytes"]
        assert sb["carried_bytes"] > 0
        assert sb["dense_bytes"] >= sb["carried_bytes"]
        assert sb["planes"]
        assert eng["shards"] == (0 if not shard else eng["shards"])
        if shard:
            assert eng["shards"] > 1

    def test_plan_json_serializes(self):
        """cli._plan_json must emit the telemetry verbatim as valid JSON
        (the --json contract scripted consumers read)."""
        from simtpu.cli import _plan_json

        plan = self._applier(False).run()
        doc = json.loads(_plan_json(plan))
        assert doc["engine"]["state_bytes"]["carried_bytes"] > 0
        assert doc["engine"]["fetch"]["get"] > 0
        assert "compact" in doc["engine"]


class TestShardAB:
    def test_sharded_vs_unsharded_plan_identical(self):
        """The telemetry A/B never changes answers: the sharded and
        unsharded planner agree on the plan (and both leave counters
        populated)."""
        t = TestApplyJsonEngineBlock()
        a = t._applier(False).run()
        b = t._applier(True).run()
        assert (a.success, a.nodes_added) == (b.success, b.nodes_added)
        assert np.array_equal(
            sorted(a.probes.items()), sorted(b.probes.items())
        )
