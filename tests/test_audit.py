"""Trust-but-verify tests (simtpu/audit, ISSUE 7).

The load-bearing pins:

- mutation-kill: the independent auditor detects 100% of seeded
  placement corruptions across every corruption class (invalid node,
  overcommit, affinity/anti-affinity/spread breaks, port conflicts,
  illegal evictions);
- mode parity: the jitted bulk pass and the pure-numpy reference path
  (SIMTPU_AUDIT_JIT=0 style) return identical verdicts AND identical
  violation classes, clean and dirty;
- audit-clean: every examples/ config and the fuzz seed corpus audit
  clean across the engine-config matrix;
- divergence-safe fallback: an injected engine divergence
  (SIMTPU_AUDIT_INJECT=1) makes every planner re-place through the
  serial exact scan, ship the CERTIFIED answer, and report the
  divergence diagnostic; the CLI maps it to the documented exit code 4;
- --no-audit / audit=False opts out ({} in PlanResult.audit);
- all-or-nothing completeness: `require_all` flags stranded rows.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from simtpu import AppResource, ResourceTypes
from simtpu.audit.checker import (
    audit_placement,
    divergence_diagnostic,
    extras_from_log,
    inject_divergence,
)
from simtpu.audit.fuzz import (
    MUTATION_CLASSES,
    _check_case,
    _mutate_nodes,
    _mutation_fixture,
    _shrink,
    engine_configs,
    gen_case,
    load_reproducer,
    run_differential,
    run_mutation_kill,
    write_reproducer,
)
from simtpu.faults.drain import place_cluster
from simtpu.plan.capacity import plan_capacity
from simtpu.plan.incremental import plan_capacity_incremental
from simtpu.plan.resilience import plan_resilience
from simtpu.synth import synth_cluster

from .fixtures import make_fake_deployment, make_fake_node

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_plan_problem():
    cluster = ResourceTypes()
    cluster.nodes = [make_fake_node("base-1", "4", "8Gi")]
    apps = [
        AppResource(
            name="app",
            resource=ResourceTypes(
                deployments=[
                    make_fake_deployment("web", "default", 7, "2", "4Gi")
                ]
            ),
        )
    ]
    template = make_fake_node("template", "4", "8Gi")
    return cluster, apps, template


class TestModeParity:
    """jit and numpy bulk passes are pinned to identical verdicts."""

    @pytest.mark.parametrize("seed", [0, 1000, 2000])
    def test_clean_and_mutated_verdicts_match(self, seed):
        cluster, apps, _mix = gen_case(seed, n_nodes=10, n_pods=40)
        pc = place_cluster(cluster, apps, bulk=False)
        ext = extras_from_log(pc)

        def both(nodes):
            r_jit = audit_placement(pc.tensors, pc.batch, nodes, ext, jit=True)
            r_np = audit_placement(pc.tensors, pc.batch, nodes, ext, jit=False)
            assert r_jit.ok == r_np.ok
            assert r_jit.by_class == r_np.by_class
            assert r_jit.total == r_np.total
            return r_jit

        assert both(pc.nodes).ok, "fuzz case must start audit-clean"
        # a corrupted placement must be dirty in BOTH modes, same classes
        bad = inject_divergence(pc.tensors, pc.batch, pc.nodes)
        rep = both(bad)
        assert not rep.ok

    def test_env_lever_selects_numpy(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_AUDIT_JIT", "0")
        cluster, apps, _ = gen_case(0, n_nodes=8, n_pods=24)
        pc = place_cluster(cluster, apps, bulk=False)
        rep = audit_placement(
            pc.tensors, pc.batch, pc.nodes, extras_from_log(pc)
        )
        assert rep.mode == "numpy"
        assert rep.ok


class TestMutationKill:
    def test_100_percent_kill_across_classes(self):
        counters = run_mutation_kill(seed=0, per_class=2, n_nodes=12)
        assert counters["classes"] == len(MUTATION_CLASSES) == 7
        assert counters["classes"] == counters["classes_total"]
        assert counters["kill_rate"] == 1.0, counters["by_class"]
        assert not counters["missed"]

    def test_untried_class_lands_in_missed(self, monkeypatch):
        """A corruption class whose mutator never finds a target is a
        fixture hole, not a pass — it must surface in `missed` so the
        100%-kill contract cannot silently shrink."""
        from simtpu.audit import fuzz as F

        real = F._mutate_nodes

        def skip_ports(kind, *a, **kw):
            if kind == "port-conflict":
                return None
            return real(kind, *a, **kw)

        monkeypatch.setattr(F, "_mutate_nodes", skip_ports)
        counters = run_mutation_kill(seed=0, per_class=1, n_nodes=12)
        assert counters["classes"] == counters["classes_total"] - 1
        assert "port-conflict#untried" in counters["missed"]

    def test_each_class_reports_its_own_violation(self):
        """Every engine-level mutation is not only caught but classified:
        the report's by_class names a constraint family matching the
        corruption (no 'caught for the wrong reason' false confidence)."""
        expect = {
            "invalid-node": {"invalid-node"},
            "overcommit": {"overcommit"},
            "affinity-break": {"affinity"},
            "anti-affinity-break": {"anti-affinity"},
            "spread-break": {"spread"},
            # stacking two port-holders on one node may also trip
            # overcommit; the port class must still be among the findings
            "port-conflict": {"port-conflict"},
        }
        cluster, apps = _mutation_fixture(0, 12)
        pc = place_cluster(cluster, apps, bulk=False)
        ext = extras_from_log(pc)
        rng = np.random.default_rng(0)
        for kind, classes in expect.items():
            mut = _mutate_nodes(kind, pc.tensors, pc.batch, pc.nodes, rng)
            assert mut is not None, f"fixture lacks a {kind} target"
            rep = audit_placement(pc.tensors, pc.batch, mut, ext)
            assert not rep.ok
            assert classes & set(rep.by_class), (kind, rep.by_class)

    def test_violations_carry_witnesses(self):
        cluster, apps = _mutation_fixture(0, 12)
        pc = place_cluster(cluster, apps, bulk=False)
        mut = _mutate_nodes(
            "overcommit", pc.tensors, pc.batch, pc.nodes,
            np.random.default_rng(0),
        )
        rep = audit_placement(
            pc.tensors, pc.batch, mut, extras_from_log(pc)
        )
        over = [v for v in rep.violations if v.kind == "overcommit"]
        assert over
        v = over[0]
        assert v.pod and v.node_name
        assert v.witness["request"] > v.witness["free_at_step"]
        doc = rep.counters()
        assert doc["detail"][0]["class"]
        assert doc["detail"][0]["witness"]


class TestCompleteness:
    def test_require_all_flags_stranded_rows(self):
        # one tiny node, far more pods than fit: the engine strands some
        cluster = synth_cluster(1, seed=0, zones=1)
        apps = [
            AppResource(
                name="big",
                resource=ResourceTypes(
                    deployments=[
                        make_fake_deployment("huge", "default", 40, "2", "4Gi")
                    ]
                ),
            )
        ]
        pc = place_cluster(cluster, apps, bulk=False)
        stranded = int((pc.nodes < 0).sum())
        assert stranded > 0
        rep = audit_placement(
            pc.tensors, pc.batch, pc.nodes, extras_from_log(pc),
            require_all=True,
        )
        assert not rep.ok
        assert rep.by_class.get("unplaced") == stranded
        # without the all-or-nothing claim the same placement is clean
        rep2 = audit_placement(
            pc.tensors, pc.batch, pc.nodes, extras_from_log(pc)
        )
        assert rep2.ok


class TestPlannerFallback:
    """SIMTPU_AUDIT_INJECT corrupts the audit's view of the primary
    engine's answer: every planner must catch it, re-place through the
    serial exact scan, ship the certified answer, and report the
    divergence."""

    def _assert_fallback_doc(self, doc):
        assert doc["fallback"] is True
        assert doc["violations"] >= 1
        assert doc["fallback_audit"]["ok"] is True
        assert doc["ok"] is True  # the SHIPPED answer is certified
        div = doc["divergence"]
        assert div["violations"]
        # the injection corrupts only the audit's VIEW — the primary and
        # fallback engines' real logs agree, so the state-plane witness
        # is rightly empty here (TestDivergenceDiagnostic pins the
        # non-empty case)
        assert div.get("state_planes", []) == []

    def test_serial_planner_ships_certified_fallback(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity(cluster, apps, template, 8)
        assert plan.success
        assert not plan.result.unscheduled_pods
        self._assert_fallback_doc(plan.audit)

    def test_incremental_planner_ships_certified_fallback(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity_incremental(cluster, apps, template, 8)
        assert plan.success
        self._assert_fallback_doc(plan.audit)
        assert plan.audit["divergence"]["first_divergent_row"] >= 0

    def test_incremental_matches_uninjected_plan(self, monkeypatch):
        cluster, apps, template = _small_plan_problem()
        clean = plan_capacity_incremental(cluster, apps, template, 8)
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        fb = plan_capacity_incremental(cluster, apps, template, 8)
        # the fallback's serial-exact answer IS the uninterrupted answer
        assert fb.nodes_added == clean.nodes_added
        assert clean.audit["ok"] and "fallback" not in clean.audit

    def test_resilience_planner_ships_certified_fallback(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_resilience(
            cluster, apps, template, k=1, max_new_nodes=10
        )
        assert plan.success
        self._assert_fallback_doc(plan.audit)
        # the survivability verdict describes the CERTIFIED placement:
        # the winner's sweep re-ran over the fallback
        assert plan.sweep is not None

    def test_resumed_resilience_plan_still_audited(self, tmp_path):
        """A checkpoint-resumed winner replays its sweep verdict from the
        record, but the AUDIT must still run over a live placement — the
        finish() re-probe refreshes the audit artifacts."""
        from simtpu.durable import PlanCheckpoint, plan_fingerprint

        cluster, apps, template = _small_plan_problem()
        fp = plan_fingerprint(cluster, apps, template, extra={"k": 1})
        ck = PlanCheckpoint(
            str(tmp_path / "ck"), kind="resilience", fingerprint=fp
        )
        p1 = plan_resilience(
            cluster, apps, template, k=1, max_new_nodes=10, checkpoint=ck
        )
        ck2 = PlanCheckpoint(
            str(tmp_path / "ck"), kind="resilience", fingerprint=fp,
            resume=True,
        )
        p2 = plan_resilience(
            cluster, apps, template, k=1, max_new_nodes=10, checkpoint=ck2
        )
        assert (p2.success, p2.nodes_added) == (p1.success, p1.nodes_added)
        assert p2.audit.get("ok") is True
        assert p2.audit["checked"] > 0

    def test_audit_false_opts_out(self):
        cluster, apps, template = _small_plan_problem()
        for plan in (
            plan_capacity(cluster, apps, template, 8, audit=False),
            plan_capacity_incremental(cluster, apps, template, 8, audit=False),
            plan_resilience(
                cluster, apps, template, k=1, max_new_nodes=10, audit=False
            ),
        ):
            assert plan.success
            assert plan.audit == {}

    def test_clean_audit_doc_rides_every_planner(self):
        cluster, apps, template = _small_plan_problem()
        for plan in (
            plan_capacity(cluster, apps, template, 8),
            plan_capacity_incremental(cluster, apps, template, 8),
            plan_resilience(cluster, apps, template, k=1, max_new_nodes=10),
        ):
            assert plan.success
            assert plan.audit["ok"] is True
            assert plan.audit["violations"] == 0
            assert plan.audit["checked"] > 0


class TestDivergenceDiagnostic:
    def test_diff_state_planes_names_differing_planes(self):
        from simtpu.engine.state import build_state, diff_state_planes

        cluster, apps, _ = gen_case(0, n_nodes=8, n_pods=24)
        pc = place_cluster(cluster, apps, bulk=False)
        eng = pc.engine
        r = pc.tensors.alloc.shape[1]
        groups = np.asarray(eng.placed_group, np.int32)
        nodes = np.asarray(eng.placed_node, np.int32)
        req = eng.log_req_matrix(r)
        a = build_state(pc.tensors, groups, nodes, req, eng.ext_log)
        assert diff_state_planes(a, a) == []
        moved = nodes.copy()
        moved[0] = (moved[0] + 1) % pc.n_nodes
        b = build_state(pc.tensors, groups, moved, req, eng.ext_log)
        diff = diff_state_planes(a, b)
        assert diff, "moving a pod must perturb at least one carried plane"
        assert any(p.startswith("free") for p in diff), diff

    def test_divergence_diagnostic_names_first_divergent_pod(self):
        cluster, apps, _ = gen_case(0, n_nodes=8, n_pods=24)
        pc = place_cluster(cluster, apps, bulk=False)
        bad = inject_divergence(pc.tensors, pc.batch, pc.nodes)
        rep = audit_placement(
            pc.tensors, pc.batch, bad, extras_from_log(pc)
        )
        doc = divergence_diagnostic(
            pc.tensors, pc.batch, bad, pc.nodes, rep, planes=["free"]
        )
        first = doc["first_divergent_row"]
        assert first >= 0
        assert doc["divergent_pods"] >= 1
        assert doc["audited_node"] != doc["serial_node"]
        assert doc["state_planes"] == ["free"]


class TestCLI:
    @pytest.fixture(autouse=True)
    def _chdir_repo(self, monkeypatch):
        monkeypatch.chdir(REPO)

    def test_apply_json_audit_clean_exit_0(self, capsys):
        from simtpu.cli import main

        rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        audit = doc["engine"]["audit"]
        assert audit["ok"] is True and audit["violations"] == 0

    @pytest.mark.parametrize(
        "config,extended",
        [
            ("examples/simtpu-gpushare-config.yaml", ["-e", "gpu"]),
            ("examples/simtpu-storage-config.yaml", ["-e", "open-local"]),
        ],
    )
    def test_every_example_audits_clean(self, config, extended, capsys):
        from simtpu.cli import main

        rc = main(["apply", "-f", config, "--json", *extended])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["engine"]["audit"]["ok"] is True

    def test_no_audit_flag(self, capsys):
        from simtpu.cli import main

        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--no-audit",
        ])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["engine"]["audit"] == {"enabled": False}

    def test_injected_divergence_exit_4_with_diagnostic(
        self, monkeypatch, capsys
    ):
        from simtpu.cli import EXIT_AUDIT, main

        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == EXIT_AUDIT == 4
        # the SHIPPED plan is the serial-exact fallback's certified one
        assert doc["success"] is True
        assert doc["unscheduled"] == 0
        audit = doc["engine"]["audit"]
        assert audit["fallback"] is True
        assert audit["fallback_audit"]["ok"] is True
        assert audit["divergence"]["violations"]
        assert audit["detail"], "witnessed violations ride the doc"

    def test_injected_divergence_table_mode(self, monkeypatch, capsys):
        from simtpu.cli import EXIT_AUDIT, main

        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        rc = main(["apply", "-f", "examples/simtpu-config.yaml"])
        out = capsys.readouterr().out
        assert rc == EXIT_AUDIT
        assert "PRIMARY ENGINE DIVERGED" in out
        assert "serial-exact fallback certified" in out

    def test_faults_sweep_hard_audit_failure_exit_4(
        self, monkeypatch, capsys
    ):
        """When neither the --faults sweep's base placement nor the
        serial-exact fallback certifies, the plan stays but the exit code
        is EXIT_AUDIT and the audit doc rides resilience.audit — never a
        silent exit 0 with the diagnostics lost."""
        import simtpu.audit.checker as checker
        from simtpu.cli import EXIT_AUDIT, main

        doc_in = {"ok": False, "violations": 1, "by_class": {"overcommit": 1}}

        def fake(pc, progress=None, inject=False):
            return pc, doc_in, "audit failure: nothing certified"

        monkeypatch.setattr(checker, "audit_placed_cluster", fake)
        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--faults", "k=1",
        ])
        out = capsys.readouterr()
        doc = json.loads(out.out)
        assert rc == EXIT_AUDIT
        assert doc["success"] is True  # the plan itself stands
        assert "nothing certified" in doc["resilience"]["error"]
        assert doc["resilience"]["audit"] == doc_in

    def test_resilience_assessment_audit_rides_json(self, capsys):
        from simtpu.cli import main

        main(["resilience", "-f", "examples/simtpu-config.yaml", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert doc["audit"]["ok"] is True

    def test_fuzz_mutation_kill_cli(self, capsys):
        from simtpu.cli import main

        rc = main(["fuzz", "--mutation-kill", "--per-class", "1", "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["ok"] is True and doc["kill_rate"] == 1.0


class TestFuzzHarness:
    def test_differential_clean_on_seed_corpus(self):
        result = run_differential(
            cases=1, seed=0, n_nodes=10, n_pods=32, include_shard=False
        )
        assert result.ok
        assert result.audits_clean == result.configs_run

    def test_engine_config_matrix_shape(self):
        cells = engine_configs(include_shard=True)
        names = {c["name"] for c in cells}
        assert {
            "wavefront", "compact", "wavefront+compact", "sharded",
            "oom-backoff",
        } <= names

    def test_reproducer_roundtrip(self, tmp_path):
        cluster, apps, _ = gen_case(0, n_nodes=8, n_pods=24)
        path = write_reproducer(cluster, apps, str(tmp_path / "repro.yaml"))
        r_cluster, r_apps = load_reproducer(path)
        assert len(r_cluster.nodes) == len(cluster.nodes)
        n_work = len(apps[0].resource.deployments)
        assert len(r_apps[0].resource.deployments) == n_work
        # the reloaded case places and audits exactly like the original
        bad = _check_case(r_cluster, r_apps, [])
        assert bad is None

    def test_shrink_minimizes_while_failing(self):
        cluster, apps, _ = gen_case(0, n_nodes=16, n_pods=64)
        n_deps = len(apps[0].resource.deployments)

        def always_fails(cl, ap, cells):
            return True  # everything "reproduces": shrink to the floor

        s_cluster, s_apps = _shrink(cluster, apps, [], always_fails)
        assert len(s_apps[0].resource.deployments) < n_deps
        assert len(s_cluster.nodes) <= max(2, len(cluster.nodes) // 2)
        assert len(s_apps[0].resource.deployments) >= 1
        assert all(
            d["spec"]["replicas"] >= 1
            for d in s_apps[0].resource.deployments
        )
