"""Randomized cross-engine equivalence (the scan-vs-parallel check SURVEY.md
§5 calls for in place of a race detector): on random synthetic problems, the
serial scan, bulk rounds, and sharded engines must agree on feasibility
outcomes, and no engine may overcommit any node.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu.api import simulate
from simtpu.core.quantity import parse_quantity
from simtpu.synth import synth_apps, synth_cluster
from simtpu.workloads.expand import seed_name_hashes


def _counts(result):
    return sorted(
        (s.node["metadata"]["name"], len(s.pods)) for s in result.node_status
    )


def _assert_no_overcommit(result):
    for status in result.node_status:
        alloc = status.node["status"]["allocatable"]
        for res in ("cpu", "memory"):
            cap = parse_quantity(alloc[res])
            used = 0.0
            for pod in status.pods:
                for c in pod["spec"]["containers"]:
                    used += parse_quantity(
                        ((c.get("resources") or {}).get("requests") or {}).get(res, 0)
                    )
            assert used <= cap * (1 + 1e-6), (
                f"{status.node['metadata']['name']} overcommitted {res}: "
                f"{used} > {cap}"
            )


@pytest.mark.parametrize("seed", [101, 202, 303, 404])
def test_scan_vs_bulk_equivalence(seed):
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(8, 40))
    n_pods = int(rng.integers(40, 220))
    cluster = synth_cluster(
        n_nodes, seed=seed, zones=int(rng.integers(1, 5)), taint_frac=0.15
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=int(rng.integers(5, 40)),
        selector_frac=0.25,
        toleration_frac=0.15,
        anti_affinity_frac=0.25,
    )
    seed_name_hashes(seed)
    serial = simulate(cluster, apps)
    seed_name_hashes(seed)
    bulk = simulate(cluster, apps, bulk=True)
    # feasibility equivalence: same number of pods placed and unplaced
    assert sum(len(s.pods) for s in serial.node_status) == sum(
        len(s.pods) for s in bulk.node_status
    )
    assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods)
    _assert_no_overcommit(serial)
    _assert_no_overcommit(bulk)
