"""Randomized cross-engine equivalence (the scan-vs-parallel check SURVEY.md
§5 calls for in place of a race detector): on random synthetic problems, the
serial scan, bulk rounds, and sharded engines must agree on feasibility
outcomes, and no engine may overcommit any node.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu.api import simulate
from simtpu.core.quantity import parse_quantity
from simtpu.synth import synth_apps, synth_cluster
from simtpu.workloads.expand import seed_name_hashes


def _counts(result):
    return sorted(
        (s.node["metadata"]["name"], len(s.pods)) for s in result.node_status
    )


def _assert_no_overcommit(result):
    for status in result.node_status:
        alloc = status.node["status"]["allocatable"]
        for res in ("cpu", "memory"):
            cap = parse_quantity(alloc[res])
            used = 0.0
            for pod in status.pods:
                for c in pod["spec"]["containers"]:
                    used += parse_quantity(
                        ((c.get("resources") or {}).get("requests") or {}).get(res, 0)
                    )
            assert used <= cap * (1 + 1e-6), (
                f"{status.node['metadata']['name']} overcommitted {res}: "
                f"{used} > {cap}"
            )


def _assert_no_storage_gpu_overcommit(result):
    import json

    for status in result.node_status:
        anno = status.node["metadata"].get("annotations") or {}
        raw = anno.get("simon/node-local-storage")
        if raw:
            st = json.loads(raw)
            for vg in st.get("vgs") or []:
                assert vg["requested"] <= vg["capacity"] + 1, (
                    f"{status.node['metadata']['name']} VG {vg['name']} "
                    f"overcommitted: {vg['requested']} > {vg['capacity']}"
                )
        raw = anno.get("simon/node-gpu-share")
        if raw:
            info = json.loads(raw)
            assert info["gpuUsedMemory"] <= info["gpuTotalMemory"], (
                f"{status.node['metadata']['name']} GPU overcommitted"
            )
            for dev in (info.get("devs") or {}).values():
                assert dev["gpuUsedMemory"] <= dev["gpuTotalMemory"]


@pytest.mark.parametrize(
    "seed",
    [11] + [pytest.param(s, marks=pytest.mark.slow) for s in (22, 33, 77, 123)],
)
def test_scan_vs_bulk_equivalence_extended_resources(seed):
    """VERDICT r1 task 2: storage/GPU-demanding runs must flow through the
    bulk rounds path (not the serial fallback) and still agree with the
    serial scan on feasibility, without overcommitting any VG or device.

    Placed-pod counts may differ by a bounded sliver (seeds 77/123 diverge by
    exactly one LVM pod): the bulk round distributes a run with round-start
    binpack scores, so under VG fragmentation its packing can strand — or
    save — a final pod relative to the serial order. The reference itself is
    nondeterministic here (selectHost breaks score ties randomly,
    `core/generic_scheduler.go:188-209`), so count-exactness beyond this band
    is not a property even two reference runs share. Hard feasibility
    (no overcommit anywhere) is asserted exactly for both engines."""
    from simtpu.engine.rounds import RoundsEngine

    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(10, 32))
    n_pods = int(rng.integers(60, 180))
    cluster = synth_cluster(
        n_nodes, seed=seed, zones=3, taint_frac=0.1, gpu_frac=0.5, storage_frac=0.5
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=int(rng.integers(12, 40)),
        selector_frac=0.1,
        anti_affinity_frac=0.2,
        gpu_frac=0.3,
        storage_frac=0.3,
    )
    bulk_ext_pods = []  # pods per bulk call whose run demands storage/GPU

    class SpyEngine(RoundsEngine):
        def _bulk_call(
            self, statics, state, seg_pods, ks, n_domains, k_cap, flags,
            quota=False, self_aff=False, ext_mats=False,
        ):
            lvm = np.asarray(seg_pods[4]).max(axis=1) > 0
            dev = np.asarray(seg_pods[6]).max(axis=1) > 0
            gpu = np.asarray(seg_pods[8]) > 0
            ks_h = np.asarray(ks)
            bulk_ext_pods.append(int(ks_h[lvm | dev | gpu].sum()))
            return super()._bulk_call(
                statics, state, seg_pods, ks, n_domains, k_cap, flags,
                quota, self_aff, ext_mats,
            )

    seed_name_hashes(seed)
    serial = simulate(cluster, apps)
    seed_name_hashes(seed)
    bulk = simulate(cluster, apps, engine_factory=SpyEngine)
    # the feature under test: storage/GPU-demanding runs themselves must go
    # through the bulk path, not merely coexist with bulk CPU runs
    assert sum(bulk_ext_pods) > 0, "no storage/GPU run engaged the bulk path"
    placed_serial = sum(len(s.pods) for s in serial.node_status)
    placed_bulk = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, placed_serial // 100)  # 1% fragmentation band (see docstring)
    assert abs(placed_serial - placed_bulk) <= tol, (placed_serial, placed_bulk)
    assert abs(len(serial.unscheduled_pods) - len(bulk.unscheduled_pods)) <= tol
    for res in (serial, bulk):
        _assert_no_overcommit(res)
        _assert_no_storage_gpu_overcommit(res)


def _assert_spread_satisfied(result):
    """Every placed pod's DoNotSchedule constraints hold on the FINAL
    placement: per (constraint, workload) the domain counts obey
    max <= min_over_eligible_domains + maxSkew, eligibility being the
    filter's static mask (nodes the pod could statically run on). The
    serial engine guarantees this inductively — each placement satisfies
    count+1-min <= skew at its time and the minimum only rises — so the
    bulk quota round must land inside the same envelope."""
    import json as _json
    from collections import defaultdict

    from simtpu.core.match import node_should_run_pod

    counts = defaultdict(lambda: defaultdict(int))  # ident -> dom -> n
    rep = {}  # ident -> (representative pod, key, skew)
    for st in result.node_status:
        labels = (st.node["metadata"].get("labels")) or {}
        for pod in st.pods:
            plabels = (pod["metadata"].get("labels")) or {}
            for c in (pod["spec"].get("topologySpreadConstraints")) or []:
                if c.get("whenUnsatisfiable", "DoNotSchedule") != "DoNotSchedule":
                    continue
                ml = ((c.get("labelSelector")) or {}).get("matchLabels") or {}
                if not ml or not all(plabels.get(k) == str(v) for k, v in ml.items()):
                    continue  # count only self-matching pods (synth's shape)
                key = c["topologyKey"]
                ident = (key, _json.dumps(sorted(ml.items())))
                rep[ident] = (pod, key, float(c.get("maxSkew", 1)))
                dom = labels.get(key)
                if dom is not None:
                    counts[ident][dom] += 1
    for ident, (pod, key, skew) in rep.items():
        # eligible domains: those containing >= 1 node the pod statically
        # fits (nodeSelector/affinity + taints) — the filter's min set
        elig = set()
        for st in result.node_status:
            if node_should_run_pod(st.node, pod):
                dom = ((st.node["metadata"].get("labels")) or {}).get(key)
                if dom is not None:
                    elig.add(dom)
        got = counts[ident]
        if not got or not elig:
            continue
        mx = max(got.values())
        mn = min(got.get(d, 0) for d in elig)
        assert mx - mn <= skew, (ident, dict(got), sorted(elig), skew)


def _assert_anti_satisfied(result):
    """No two pods of a required-self-anti workload share a topology domain."""
    from collections import defaultdict

    seen = defaultdict(set)  # (workload labels key, topo key) -> domains
    for st in result.node_status:
        labels = (st.node["metadata"].get("labels")) or {}
        for pod in st.pods:
            aff = ((pod["spec"].get("affinity")) or {}).get("podAntiAffinity") or {}
            for term in aff.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
                ml = ((term.get("labelSelector")) or {}).get("matchLabels") or {}
                plabels = (pod["metadata"].get("labels")) or {}
                if not all(plabels.get(k) == str(v) for k, v in ml.items()):
                    continue  # not self-matching: ignore
                key = term.get("topologyKey", "")
                dom = labels.get(key)
                if dom is None:
                    continue
                ident = (tuple(sorted(ml.items())), key)
                assert dom not in seen[ident], (ident, dom)
                seen[ident].add(dom)


@pytest.mark.parametrize(
    "seed",
    [7] + [pytest.param(s, marks=pytest.mark.slow) for s in (19, 55, 91)],
)
def test_scan_vs_bulk_hard_constraints(seed):
    """VERDICT r2 task 2: DoNotSchedule spread and required self-anti-affinity
    runs must ride the bulk path (domain-quota rounds), agree with the serial
    scan on placed counts within the documented band, and the FINAL bulk
    placement must satisfy every hard constraint exactly (feasibility-exact).

    The band exists because the quota round fills domains level/index-ordered
    while the serial scan picks nodes by score: the totals match per run
    (domain capacity consumption is order-invariant), but different node
    choices shift resource state for later runs — the same class of
    divergence the plain bulk round documents (the reference breaks score
    ties randomly, so exact counts are not reproducible reference-vs-
    reference either)."""
    from simtpu.engine.rounds import RoundsEngine

    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(9, 36))
    n_pods = int(rng.integers(60, 240))
    cluster = synth_cluster(
        n_nodes, seed=seed, zones=int(rng.integers(2, 5)), taint_frac=0.1
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=int(rng.integers(10, 40)),
        selector_frac=0.1,
        anti_affinity_frac=0.4,
        anti_affinity_hard_frac=0.6,
        spread_frac=0.5,
        spread_hard_frac=0.8,
    )
    quota_pods = []

    class SpyEngine(RoundsEngine):
        def _bulk_call(
            self, statics, state, seg_pods, ks, n_domains, k_cap, flags,
            quota=False, self_aff=False, ext_mats=False,
        ):
            if quota:
                quota_pods.append(int(np.asarray(ks).sum()))
            return super()._bulk_call(
                statics, state, seg_pods, ks, n_domains, k_cap, flags,
                quota, self_aff, ext_mats,
            )

    seed_name_hashes(seed)
    serial = simulate(cluster, apps)
    seed_name_hashes(seed)
    bulk = simulate(cluster, apps, engine_factory=SpyEngine)
    assert sum(quota_pods) > 0, "no hard-constrained run engaged the quota path"
    placed_serial = sum(len(s.pods) for s in serial.node_status)
    placed_bulk = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, placed_serial // 100)
    assert abs(placed_serial - placed_bulk) <= tol, (placed_serial, placed_bulk)
    for res in (serial, bulk):
        _assert_no_overcommit(res)
        _assert_spread_satisfied(res)
        _assert_anti_satisfied(res)


@pytest.mark.parametrize(
    "seed",
    [13] + [pytest.param(s, marks=pytest.mark.slow) for s in (29, 47, 88, 131)],
)
def test_scan_vs_bulk_matrix_extended(seed):
    """VERDICT r3 task 1: multi-GPU (gpu_count > 1) and multi-claim LVM runs
    must ride the MATRIX bulk rounds (ext_mats), not the serial fallback,
    agree with the serial scan within the documented band, and never
    overcommit a GPU device or VG. The multi-GPU intake/split is exact
    (consecutive pods take consecutive share-pool prefixes, mirroring the
    two-pointer greedy gpunodeinfo.go:271-288); multi-claim LVM reuses the
    round-start binpack plan, whose fragmentation drift the band covers.

    The band here is 5% (vs 1% for the plain ext fuzz): at a 70% multi
    fraction, runs that choose different (score-tied) nodes fragment the
    share pools and VG frees differently for every later run — by the 6th
    heavy run the pools can differ by several whole pods (observed: serial
    strands a late count=3 run at 0 where the round's state fits 5 — the
    round was STRICTLY better there). Single-run
    totals are pinned EXACT by test_multi_gpu_single_run_exact — the drift
    is purely cross-run state divergence, the same class the reference's
    random tie-break exhibits reference-vs-reference. The cluster is
    uniformly GPU+storage equipped so extended capacity is not hostage to
    WHERE the mix's plain CPU runs happen to land (serial packs them onto
    big nodes, the round spreads them — both legal, wildly different GPU
    starvation downstream)."""
    from simtpu.engine.rounds import RoundsEngine

    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(12, 36))
    n_pods = int(rng.integers(80, 220))
    cluster = synth_cluster(
        n_nodes, seed=seed, zones=3, taint_frac=0.1, gpu_frac=1.0, storage_frac=1.0
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=int(rng.integers(12, 40)),
        selector_frac=0.1,
        anti_affinity_frac=0.1,
        gpu_frac=0.35,
        gpu_multi_frac=0.7,
        storage_frac=0.35,
        storage_device_frac=0.0,
        lvm_multi_frac=0.7,
    )
    mats_pods = []

    class SpyEngine(RoundsEngine):
        def _bulk_call(
            self, statics, state, seg_pods, ks, n_domains, k_cap, flags,
            quota=False, self_aff=False, ext_mats=False,
        ):
            if ext_mats:
                mats_pods.append(int(np.asarray(ks).sum()))
            return super()._bulk_call(
                statics, state, seg_pods, ks, n_domains, k_cap, flags,
                quota, self_aff, ext_mats,
            )

    seed_name_hashes(seed)
    serial = simulate(cluster, apps)
    seed_name_hashes(seed)
    bulk = simulate(cluster, apps, engine_factory=SpyEngine)
    assert sum(mats_pods) > 0, "no multi-GPU/multi-claim run engaged the matrix path"
    placed_serial = sum(len(s.pods) for s in serial.node_status)
    placed_bulk = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, (placed_serial * 5) // 100)
    assert abs(placed_serial - placed_bulk) <= tol, (placed_serial, placed_bulk)
    for res in (serial, bulk):
        _assert_no_overcommit(res)
        _assert_no_storage_gpu_overcommit(res)


@pytest.mark.parametrize("count", [2, 3, 4])
def test_multi_gpu_single_run_exact(count):
    """A single multi-GPU run from a common state places EXACTLY the serial
    count: per-node intake floor(pool/count) with prefix share consumption
    reproduces the two-pointer greedy's totals bit-for-bit (the cross-run
    fuzz band exists only because node-choice divergence fragments state
    for LATER runs)."""
    from simtpu.synth import make_deployment, make_node
    from simtpu.core.objects import AppResource, ResourceTypes

    for seed in (1, 2, 3):
        rng = np.random.default_rng(seed)
        nodes = []
        for i in range(12):
            gd = int(rng.integers(1, 6))
            mem = int(rng.choice([8192, 16384, 24576]))
            nodes.append(
                make_node(
                    f"n-{i:02d}", 64000, 256,
                    {"kubernetes.io/hostname": f"n-{i:02d}"}, gpu=(gd, mem),
                )
            )
        cluster = ResourceTypes()
        cluster.nodes = nodes
        res = ResourceTypes()
        res.deployments.append(
            make_deployment("mg", 40, 250, 256, gpu_mem_mib=4096, gpu_count=count)
        )
        apps = [AppResource(name="a", resource=res)]
        seed_name_hashes(0)
        s = simulate(cluster, apps)
        seed_name_hashes(0)
        b = simulate(cluster, apps, bulk=True)
        ps = sum(len(st.pods) for st in s.node_status)
        pb = sum(len(st.pods) for st in b.node_status)
        assert ps == pb, (count, seed, ps, pb)
        _assert_no_storage_gpu_overcommit(b)


def _assert_colocated(result):
    """Every workload with a required self-affinity term keeps all its placed
    pods in domains holding a matching pod; with no pre-existing matchers
    (synth gives each deployment unique labels) that means ONE domain."""
    from collections import defaultdict

    doms = defaultdict(set)
    for st in result.node_status:
        labels = (st.node["metadata"].get("labels")) or {}
        for pod in st.pods:
            aff = ((pod["spec"].get("affinity")) or {}).get("podAffinity") or {}
            for term in aff.get("requiredDuringSchedulingIgnoredDuringExecution") or []:
                ml = ((term.get("labelSelector")) or {}).get("matchLabels") or {}
                plabels = (pod["metadata"].get("labels")) or {}
                if not ml or not all(plabels.get(k) == str(v) for k, v in ml.items()):
                    continue
                key = term.get("topologyKey", "")
                dom = labels.get(key)
                assert dom is not None, "self-affinity pod on a key-less node"
                doms[(tuple(sorted(ml.items())), key)].add(dom)
    for ident, ds in doms.items():
        assert len(ds) == 1, (ident, sorted(ds))


@pytest.mark.parametrize(
    "seed",
    [17] + [pytest.param(s, marks=pytest.mark.slow) for s in (41, 73, 109)],
)
def test_scan_vs_bulk_self_affinity(seed):
    """VERDICT r3 task 1: required colocate-with-self runs must ride the
    bulk path (self_aff rounds), stay within the equivalence band, and the
    final placement must keep each such workload inside one domain."""
    from simtpu.engine.rounds import RoundsEngine

    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(10, 36))
    n_pods = int(rng.integers(60, 200))
    cluster = synth_cluster(
        n_nodes, seed=seed, zones=int(rng.integers(2, 5)), taint_frac=0.1
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=int(rng.integers(10, 40)),
        selector_frac=0.15,
        anti_affinity_frac=0.1,
        affinity_frac=0.6,
    )
    aff_pods = []

    class SpyEngine(RoundsEngine):
        def _bulk_call(
            self, statics, state, seg_pods, ks, n_domains, k_cap, flags,
            quota=False, self_aff=False, ext_mats=False,
        ):
            if self_aff:
                aff_pods.append(int(np.asarray(ks).sum()))
            return super()._bulk_call(
                statics, state, seg_pods, ks, n_domains, k_cap, flags,
                quota, self_aff, ext_mats,
            )

    seed_name_hashes(seed)
    serial = simulate(cluster, apps)
    seed_name_hashes(seed)
    bulk = simulate(cluster, apps, engine_factory=SpyEngine)
    assert sum(aff_pods) > 0, "no self-affinity run engaged the bulk path"
    placed_serial = sum(len(s.pods) for s in serial.node_status)
    placed_bulk = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, placed_serial // 100)
    assert abs(placed_serial - placed_bulk) <= tol, (placed_serial, placed_bulk)
    for res in (serial, bulk):
        _assert_no_overcommit(res)
        _assert_colocated(res)


def test_scan_vs_bulk_preset_gpu_index():
    """Preset gpu-index runs ride the matrix bulk path with the annotation
    honored verbatim (AllocateGpuId short-circuit, gpunodeinfo.go:247-253):
    serial and bulk must agree exactly on placed counts and on every pod's
    device assignment."""
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.synth import make_deployment, make_node
    from simtpu.core.objects import AppResource, ResourceTypes

    nodes = [
        make_node(
            f"gpu-{i:03d}", 64000, 256,
            {"kubernetes.io/hostname": f"gpu-{i:03d}"},
            gpu=(4, 16384),
        )
        for i in range(6)
    ]
    cluster = ResourceTypes()
    cluster.nodes = nodes
    res = ResourceTypes()
    res.deployments.append(
        make_deployment(
            "preset", 24, 500, 512, gpu_mem_mib=4096, gpu_count=2,
            gpu_index="0-1",
        )
    )
    apps = [AppResource(name="preset-app", resource=res)]
    mats_pods = []

    class SpyEngine(RoundsEngine):
        def _bulk_call(
            self, statics, state, seg_pods, ks, n_domains, k_cap, flags,
            quota=False, self_aff=False, ext_mats=False,
        ):
            if ext_mats:
                mats_pods.append(int(np.asarray(ks).sum()))
            return super()._bulk_call(
                statics, state, seg_pods, ks, n_domains, k_cap, flags,
                quota, self_aff, ext_mats,
            )

    def gpu_indices(result):
        out = {}
        for st in result.node_status:
            for pod in st.pods:
                anno = (pod["metadata"].get("annotations")) or {}
                out[pod["metadata"]["name"]] = (
                    st.node["metadata"]["name"],
                    anno.get("alibabacloud.com/gpu-index"),
                )
        return out

    seed_name_hashes(1)
    serial = simulate(cluster, apps)
    seed_name_hashes(1)
    bulk = simulate(cluster, apps, engine_factory=SpyEngine)
    assert sum(mats_pods) > 0, "preset run did not engage the matrix path"
    assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods)
    si, bi = gpu_indices(serial), gpu_indices(bulk)
    assert set(si) == set(bi)
    for name in si:
        assert si[name][1] == bi[name][1] == "0-1", (name, si[name], bi[name])


@pytest.mark.parametrize(
    "seed",
    [101] + [pytest.param(s, marks=pytest.mark.slow) for s in (202, 303, 404)],
)
def test_scan_vs_bulk_equivalence(seed):
    rng = np.random.default_rng(seed)
    n_nodes = int(rng.integers(8, 40))
    n_pods = int(rng.integers(40, 220))
    cluster = synth_cluster(
        n_nodes, seed=seed, zones=int(rng.integers(1, 5)), taint_frac=0.15
    )
    apps = synth_apps(
        n_pods,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=int(rng.integers(5, 40)),
        selector_frac=0.25,
        toleration_frac=0.15,
        anti_affinity_frac=0.25,
    )
    seed_name_hashes(seed)
    serial = simulate(cluster, apps)
    seed_name_hashes(seed)
    bulk = simulate(cluster, apps, bulk=True)
    # feasibility equivalence: same number of pods placed and unplaced
    assert sum(len(s.pods) for s in serial.node_status) == sum(
        len(s.pods) for s in bulk.node_status
    )
    assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods)
    _assert_no_overcommit(serial)
    _assert_no_overcommit(bulk)


@pytest.mark.slow
def test_scan_vs_bulk_hard_mix_agreement():
    """Mid-scale pin of the bench's HARD mix (VERDICT r3 task 6): under the
    exact hard-point constraint fractions (DoNotSchedule spread + required
    anti-affinity riding the domain-quota rounds), serial and bulk agree on
    placed counts within the documented band and the final placement
    satisfies every hard constraint."""
    cluster = synth_cluster(400, seed=3, zones=16, taint_frac=0.1, storage_frac=0.3)
    apps = synth_apps(
        2000,
        seed=4,
        zones=16,
        pods_per_deployment=100,
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.2,
        anti_affinity_hard_frac=0.34,
        spread_frac=0.3,
        spread_hard_frac=0.5,
        storage_frac=0.2,
    )
    seed_name_hashes(42)
    serial = simulate(cluster, apps)
    seed_name_hashes(42)
    bulk = simulate(cluster, apps, bulk=True)
    ps = sum(len(s.pods) for s in serial.node_status)
    pb = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, ps // 100)
    assert abs(ps - pb) <= tol, (ps, pb)
    _assert_no_overcommit(bulk)
    _assert_spread_satisfied(bulk)
    _assert_anti_satisfied(bulk)


@pytest.mark.slow
def test_scan_vs_bulk_matrix_mix_agreement():
    """Mid-scale pin of the bench's MATRIX mix (round-4): the multi-GPU /
    multi-claim-LVM / self-affinity fractions the matrix-point times, at
    400 nodes x 2000 pods, within the heavy-mix band and with every
    colocation constraint satisfied."""
    cluster = synth_cluster(
        400, seed=3, zones=16, taint_frac=0.1, storage_frac=0.3, gpu_frac=0.4
    )
    apps = synth_apps(
        2000,
        seed=4,
        zones=16,
        pods_per_deployment=100,
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.2,
        spread_frac=0.3,
        gpu_frac=0.25,
        gpu_multi_frac=0.6,
        storage_frac=0.25,
        storage_device_frac=0.0,
        lvm_multi_frac=0.6,
        affinity_frac=0.15,
    )
    seed_name_hashes(42)
    serial = simulate(cluster, apps)
    seed_name_hashes(42)
    bulk = simulate(cluster, apps, bulk=True)
    ps = sum(len(s.pods) for s in serial.node_status)
    pb = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, (ps * 5) // 100)
    assert abs(ps - pb) <= tol, (ps, pb)
    _assert_no_overcommit(bulk)
    _assert_no_storage_gpu_overcommit(bulk)
    _assert_colocated(bulk)


def test_scan_vs_bulk_north_star_mix_agreement():
    """Mid-scale pin of the headline bench mix (VERDICT r2 weak #2): under
    the exact north-star constraint fractions, the serial scan and the bulk
    rounds engine agree on placed counts within the documented band, so the
    bench's bulk number measures the same placement the serial engine
    defines."""
    cluster = synth_cluster(400, seed=3, zones=16, taint_frac=0.1, storage_frac=0.3)
    apps = synth_apps(
        2000,
        seed=4,
        zones=16,
        pods_per_deployment=100,
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.2,
        spread_frac=0.3,
        storage_frac=0.2,
    )
    seed_name_hashes(42)
    serial = simulate(cluster, apps)
    seed_name_hashes(42)
    bulk = simulate(cluster, apps, bulk=True)
    ps = sum(len(s.pods) for s in serial.node_status)
    pb = sum(len(s.pods) for s in bulk.node_status)
    tol = max(1, ps // 100)
    assert abs(ps - pb) <= tol, (ps, pb)
    _assert_no_overcommit(bulk)
    _assert_no_storage_gpu_overcommit(bulk)
