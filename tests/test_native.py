"""Tests for the native C host-path accelerators (`simtpu/native/`): the
batched quantity parser must agree with the Python grammar on a corpus, and
the scatter kernels must match np.add.at. The library builds with g++ at
first use; if no toolchain exists the module reports unavailable and every
caller falls back — both paths are exercised here.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu import native
from simtpu.core.quantity import parse_quantity

CORPUS = [
    "100m", "1500m", "2", "0.5", "16Gi", "32560Mi", "64Ki", "1Ti", "2Pi",
    "1Ei", "3n", "7u", "12k", "5M", "9G", "2T", "1P", "1E", "1e3", "12e6",
    "1.5e2", "  8  ", "", None, 4, 2.5, "0", "0.001",
]

BAD = ["abc", "12xyz", "Gi", "1.2.3m"]


def test_native_builds():
    # the image ships g++ (Environment contract) — the library must build
    assert native.available()


def test_parse_corpus_matches_python():
    got = native.parse_quantities(CORPUS)
    want = np.array([parse_quantity(v) for v in CORPUS], np.float64)
    np.testing.assert_allclose(got, want, rtol=1e-12)


@pytest.mark.parametrize("bad", BAD)
def test_parse_bad_raises_both_paths(bad):
    with pytest.raises(ValueError):
        parse_quantity(bad)
    with pytest.raises(ValueError):
        native.parse_quantities([bad])


def test_scatter_add_rows_matches_numpy():
    rng = np.random.default_rng(0)
    dst = rng.random((50, 7)).astype(np.float32)
    want = dst.copy()
    idx = rng.integers(0, 50, 1000).astype(np.int32)
    src = rng.random((1000, 7)).astype(np.float32)
    assert native.scatter_add_rows(dst, idx, src)
    np.add.at(want, idx, src)
    np.testing.assert_allclose(dst, want, rtol=1e-5)


def test_scatter_add_flat_matches_numpy():
    rng = np.random.default_rng(1)
    dst = rng.random((30, 11)).astype(np.float32)
    want = dst.copy()
    rows = rng.integers(0, 30, 500)
    cols = rng.integers(0, 11, 500)
    vals = rng.random(500).astype(np.float32)
    assert native.scatter_add_flat(dst, rows * 11 + cols, vals)
    np.add.at(want, (rows, cols), vals)
    np.testing.assert_allclose(dst, want, rtol=1e-5)


def test_out_of_range_indices_skipped():
    dst = np.zeros((4, 2), np.float32)
    idx = np.array([-1, 0, 7], np.int32)
    src = np.ones((3, 2), np.float32)
    assert native.scatter_add_rows(dst, idx, src)
    assert dst.sum() == 2.0  # only row 0 landed
