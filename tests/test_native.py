"""Tests for the native C host-path accelerators (`simtpu/native/`): the
batched quantity parser must agree with the Python grammar on a corpus, and
the scatter kernels must match np.add.at. The library builds with g++ at
first use; if no toolchain exists the module reports unavailable and every
caller falls back — both paths are exercised here.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu import native
from simtpu.core.quantity import parse_quantity

CORPUS = [
    "100m", "1500m", "2", "0.5", "16Gi", "32560Mi", "64Ki", "1Ti", "2Pi",
    "1Ei", "3n", "7u", "12k", "5M", "9G", "2T", "1P", "1E", "1e3", "12e6",
    "1.5e2", "  8  ", "", None, 4, 2.5, "0", "0.001",
]

BAD = ["abc", "12xyz", "Gi", "1.2.3m"]


def test_native_builds():
    # the image ships g++ (Environment contract) — the library must build
    assert native.available()


def test_parse_corpus_matches_python():
    got = native.parse_quantities(CORPUS)
    want = np.array([parse_quantity(v) for v in CORPUS], np.float64)
    np.testing.assert_allclose(got, want, rtol=1e-12)


@pytest.mark.parametrize("bad", BAD)
def test_parse_bad_raises_both_paths(bad):
    with pytest.raises(ValueError):
        parse_quantity(bad)
    with pytest.raises(ValueError):
        native.parse_quantities([bad])


def test_scatter_add_rows_matches_numpy():
    rng = np.random.default_rng(0)
    dst = rng.random((50, 7)).astype(np.float32)
    want = dst.copy()
    idx = rng.integers(0, 50, 1000).astype(np.int32)
    src = rng.random((1000, 7)).astype(np.float32)
    assert native.scatter_add_rows(dst, idx, src)
    np.add.at(want, idx, src)
    np.testing.assert_allclose(dst, want, rtol=1e-5)


def test_scatter_add_flat_matches_numpy():
    rng = np.random.default_rng(1)
    dst = rng.random((30, 11)).astype(np.float32)
    want = dst.copy()
    rows = rng.integers(0, 30, 500)
    cols = rng.integers(0, 11, 500)
    vals = rng.random(500).astype(np.float32)
    assert native.scatter_add_flat(dst, rows * 11 + cols, vals)
    np.add.at(want, (rows, cols), vals)
    np.testing.assert_allclose(dst, want, rtol=1e-5)


def test_out_of_range_indices_skipped():
    dst = np.zeros((4, 2), np.float32)
    idx = np.array([-1, 0, 7], np.int32)
    src = np.ones((3, 2), np.float32)
    assert native.scatter_add_rows(dst, idx, src)
    assert dst.sum() == 2.0  # only row 0 landed


class TestEnvOverride:
    """SIMTPU_NATIVE=0 forces the pure-python/numpy fallbacks even when the
    library builds — and the fallbacks must be bit-identical to the native
    path (they back the SAME state rebuilds; a drift would silently change
    placements on toolchain-less hosts)."""

    def test_available_forced_off(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_NATIVE", "0")
        assert not native.available()
        monkeypatch.setenv("SIMTPU_NATIVE", "1")
        if not native.available():  # toolchain-less host: only the forced-
            pytest.skip("native toolchain unavailable")  # off half applies
        assert native.available()  # the override is live, not sticky

    def test_scatter_entry_points_decline(self, monkeypatch):
        """Under the override the scatter helpers return False (caller
        falls back) and leave dst untouched."""
        monkeypatch.setenv("SIMTPU_NATIVE", "0")
        dst = np.ones((4, 3), np.float32)
        before = dst.copy()
        assert not native.scatter_add_rows(
            dst, np.zeros(2, np.int32), np.ones((2, 3), np.float32)
        )
        assert not native.scatter_add_flat(
            dst, np.zeros(2, np.int64), np.ones(2, np.float32)
        )
        np.testing.assert_array_equal(dst, before)

    def test_parse_quantities_fallback_bit_identical(self, monkeypatch):
        if not native.available():
            pytest.skip("native toolchain unavailable — nothing to compare")
        want = native.parse_quantities(CORPUS)
        monkeypatch.setenv("SIMTPU_NATIVE", "0")
        got = native.parse_quantities(CORPUS)
        # bit-identical, not allclose: both paths implement one grammar
        np.testing.assert_array_equal(got, want)

    def test_state_rebuild_fallback_bit_identical(self, monkeypatch):
        """build_state (the scatter kernels' real consumer) produces
        bit-identical planes through the numpy fallback and the native
        path."""
        if not native.available():
            pytest.skip("native toolchain unavailable — nothing to compare")
        from simtpu.core.tensorize import Tensorizer
        from simtpu.engine.rounds import RoundsEngine
        from simtpu.synth import synth_apps, synth_cluster
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

        cluster = synth_cluster(
            10, seed=71, zones=3, taint_frac=0.1, storage_frac=0.3
        )
        apps = synth_apps(
            36, seed=72, zones=3, pods_per_deployment=9,
            selector_frac=0.2, anti_affinity_frac=0.3, spread_frac=0.3,
        )
        pods = []
        for app in apps:
            pods.extend(get_valid_pods_exclude_daemonset(app.resource))

        # place once natively to seed the placement log, then rebuild the
        # state from that log through both scatter paths
        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        eng = RoundsEngine(tz)
        eng.place(tz.add_pods(pods))
        from simtpu.engine.state import build_state

        tensors = eng.tensorizer.freeze()
        r = tensors.alloc.shape[1]

        def rebuild(env):
            monkeypatch.setenv("SIMTPU_NATIVE", env)
            return build_state(
                tensors,
                np.asarray(eng.placed_group, np.int32),
                np.asarray(eng.placed_node, np.int32),
                eng.log_req_matrix(r),
                eng.ext_log,
            )

        a, b = rebuild("1"), rebuild("0")
        for name in a._fields:
            want = np.asarray(getattr(a, name))
            got = np.asarray(getattr(b, name))
            assert got.dtype == want.dtype, name
            assert np.array_equal(got, want), (
                f"build_state plane {name} differs between the native and "
                "numpy scatter paths"
            )
