"""Test configuration.

Tests run on CPU with a virtual 8-device topology so multi-chip sharding
(`simtpu.parallel`) is exercised without TPU hardware, per the driver contract.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell presets axon/tpu
# Speculative wavefront dispatch is OFF for the general suite: placements
# are bit-identical either way (that IS the pinned contract), but every
# tiny test problem would otherwise compile its own wavefront executables
# on top of the scan/round bodies — a suite-wide compile tax that pushed
# the fast tier against its wall-clock budget.  tests/test_wavefront.py
# (and anything else that wants the dispatcher) sets Engine.speculate
# explicitly, which overrides this default.
os.environ.setdefault("SIMTPU_WAVEFRONT", "0")
# Flight-recorder bundles (obs/flight.py) default to the CWD when no
# checkpoint dir is involved — under pytest that is the repo root, which
# the exit-3/exit-4 CLI tests would litter with simtpu-flight-*.json.
# Point the default at a per-session temp dir; tests that assert on
# bundles override SIMTPU_FLIGHT_DIR themselves (monkeypatch wins).
import tempfile  # noqa: E402

os.environ.setdefault(
    "SIMTPU_FLIGHT_DIR", tempfile.mkdtemp(prefix="simtpu-flight-tests-")
)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# a sitecustomize may prepend an accelerator platform (e.g. "axon,cpu");
# tests must run on the 8-device virtual CPU topology regardless
jax.config.update("jax_platforms", "cpu")

# NOTE: the persistent compilation cache is deliberately NOT enabled here:
# tests run on the CPU backend, whose cached-executable loader can segfault
# on this host (see simtpu/cache.py) — enable_compilation_cache() itself
# refuses CPU backends for the same reason.

import pytest  # noqa: E402

REFERENCE_EXAMPLES = "/root/reference/example"


@pytest.fixture(scope="session")
def example_dir():
    if not os.path.isdir(REFERENCE_EXAMPLES):
        pytest.skip("reference example fixtures not available")
    return REFERENCE_EXAMPLES


@pytest.fixture(scope="module", autouse=True)
def _drop_xla_executables():
    """Release each module's compiled XLA:CPU executables.

    A single long pytest process accumulates hundreds of loaded CPU
    executables; past ~190 tests the host's XLA:CPU
    `backend_compile_and_load` starts segfaulting (the same toolchain
    fault class simtpu/cache.py works around).  Dropping the jit caches
    between modules keeps the resident-executable count bounded at the
    cost of cross-module recompiles.  `tools/run_tests.py` goes further
    (one subprocess per module) and is the canonical full-suite entry."""
    yield
    jax.clear_caches()
