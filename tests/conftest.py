"""Test configuration.

Tests run on CPU with a virtual 8-device topology so multi-chip sharding
(`simtpu.parallel`) is exercised without TPU hardware, per the driver contract.
Must run before jax is imported anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell presets axon/tpu
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# a sitecustomize may prepend an accelerator platform (e.g. "axon,cpu");
# tests must run on the 8-device virtual CPU topology regardless
jax.config.update("jax_platforms", "cpu")

from simtpu.cache import enable_compilation_cache  # noqa: E402

# reuse compiled engine bodies across test runs (the suite is
# compile-dominated; a warm cache roughly halves its wall-clock)
enable_compilation_cache()

import pytest  # noqa: E402

REFERENCE_EXAMPLES = "/root/reference/example"


@pytest.fixture(scope="session")
def example_dir():
    if not os.path.isdir(REFERENCE_EXAMPLES):
        pytest.skip("reference example fixtures not available")
    return REFERENCE_EXAMPLES
