"""Capacity-planner tests (`pkg/apply/apply.go` semantics)."""

import os

import pytest

import simtpu.constants as C
from simtpu import AppResource, ResourceTypes
from simtpu.plan.capacity import (
    meet_resource_requests,
    new_fake_nodes,
    plan_capacity,
)
from simtpu.workloads.expand import seed_name_hashes

from .fixtures import (
    make_fake_deployment,
    make_fake_node,
    make_fake_pod,
    with_node_labels,
    with_node_taints,
    with_pod_node_selector,
)


@pytest.fixture(autouse=True)
def _seed():
    seed_name_hashes(11)


def _small_cluster():
    cluster = ResourceTypes()
    cluster.nodes = [make_fake_node("base-1", "4", "8Gi")]
    return cluster


def _app(replicas, cpu="2", memory="4Gi"):
    res = ResourceTypes()
    res.deployments = [make_fake_deployment("web", "default", replicas, cpu, memory)]
    return AppResource(name="app", resource=res)


TEMPLATE = make_fake_node("template", "4", "8Gi")


class TestNewFakeNodes:
    def test_names_and_labels(self):
        nodes = new_fake_nodes(TEMPLATE, 3)
        assert [n["metadata"]["name"] for n in nodes] == ["simon-00", "simon-01", "simon-02"]
        for n in nodes:
            assert C.LABEL_NEW_NODE in n["metadata"]["labels"]
            assert n["metadata"]["labels"]["kubernetes.io/hostname"] == n["metadata"]["name"]


class TestPlanCapacity:
    @pytest.mark.parametrize("search", ["linear", "binary"])
    def test_min_nodes_found(self, search):
        # each node fits 1 pod (2cpu/4Gi out of 4cpu/8Gi, next pod won't fit
        # with another 2cpu... actually 2 pods of 2cpu fit in 4cpu; use 3cpu)
        cluster = _small_cluster()
        app = _app(replicas=4, cpu="3", memory="6Gi")
        plan = plan_capacity(cluster, [app], TEMPLATE, search=search)
        # 4 replicas à 3cpu → 1 per node → base holds 1, need 3 more
        assert plan.success
        assert plan.nodes_added == 3

    def test_zero_added_when_cluster_suffices(self):
        cluster = _small_cluster()
        plan = plan_capacity(cluster, [_app(1, "1", "1Gi")], TEMPLATE)
        assert plan.success and plan.nodes_added == 0

    def test_linear_and_binary_agree(self):
        cluster = _small_cluster()
        app = _app(replicas=7, cpu="3", memory="1Gi")
        lin = plan_capacity(cluster, [app], TEMPLATE, search="linear")
        binp = plan_capacity(cluster, [app], TEMPLATE, search="binary")
        assert lin.success and binp.success
        assert lin.nodes_added == binp.nodes_added

    def test_diagnose_affinity_never_fits(self):
        # pod demands a label the new-node template lacks → adding cannot help
        cluster = _small_cluster()
        res = ResourceTypes()
        res.pods = [
            make_fake_pod(
                "picky",
                "default",
                "1",
                "1Gi",
                with_pod_node_selector({"special": "yes"}),
            )
        ]
        plan = plan_capacity(cluster, [AppResource(name="a", resource=res)], TEMPLATE)
        assert not plan.success
        assert "does not fit new node affinity or taints" in plan.message

    def test_diagnose_pod_larger_than_template(self):
        cluster = _small_cluster()
        plan = plan_capacity(cluster, [_app(2, cpu="32", memory="1Gi")], TEMPLATE)
        assert not plan.success
        assert "cannot meet resource requests" in plan.message

    def test_tainted_template_diagnosed(self):
        template = make_fake_node(
            "template",
            "4",
            "8Gi",
            with_node_taints([{"key": "dedicated", "effect": "NoSchedule"}]),
        )
        cluster = _small_cluster()
        plan = plan_capacity(cluster, [_app(4, "3", "1Gi")], template)
        assert not plan.success
        assert "affinity or taints" in plan.message


class TestResourceSetting:
    def test_max_cpu_cap(self, monkeypatch):
        """A cap miss is not terminal: the reference prints the reason and
        keeps adding nodes until the average rate drops under the cap
        (`apply.go:199-207`)."""
        cluster = _small_cluster()
        app = _app(1, "3", "1Gi")  # 75% cpu on the single node
        monkeypatch.setenv(C.ENV_MAX_CPU, "50")
        plan = plan_capacity(cluster, [app], TEMPLATE)
        assert plan.success
        assert plan.nodes_added == 1  # 3cpu / 8cpu = 37% <= 50%
        monkeypatch.setenv(C.ENV_MAX_CPU, "90")
        plan = plan_capacity(cluster, [app], TEMPLATE)
        assert plan.success
        assert plan.nodes_added == 0

    def test_invalid_cap_falls_back_to_100(self, monkeypatch):
        monkeypatch.setenv(C.ENV_MAX_CPU, "250")
        cluster = _small_cluster()
        plan = plan_capacity(cluster, [_app(1, "3", "1Gi")], TEMPLATE)
        assert plan.success


class TestMeetResourceRequests:
    def test_daemonset_overhead_requires_simon_named_template(self):
        """Reference quirk: the probe daemon pod is pinned to a node named
        "simon" (utils.go:777), so DS overhead only counts when the template
        node is literally named simon."""
        from .fixtures import make_fake_daemon_set

        ds = make_fake_daemon_set("heavy-ds", "kube-system", "3", "1Gi")
        pod = make_fake_pod("p", "default", "2", "1Gi")
        # template named "template": pin mismatch → DS overhead ignored
        assert meet_resource_requests(TEMPLATE, pod, [ds])
        # template literally named "simon": 3 (ds) + 2 (pod) > 4 cpu
        simon_node = make_fake_node("simon", "4", "8Gi")
        assert not meet_resource_requests(simon_node, pod, [ds])
        light = make_fake_pod("p2", "default", "1", "1Gi")
        assert meet_resource_requests(simon_node, light, [ds])

    def test_corrected_mode_accounts_ds_overhead_on_any_template(self):
        """`corrected=True` pins the probe daemon pod to the template node's
        own name, so DS overhead counts regardless of the template's name —
        contrast with the reference-bug default above."""
        from .fixtures import make_fake_daemon_set

        ds = make_fake_daemon_set("heavy-ds", "kube-system", "3", "1Gi")
        pod = make_fake_pod("p", "default", "2", "1Gi")
        template = make_fake_node("worker-template", "4", "8Gi")
        # reference-bug default: overhead ignored, the probe passes
        assert meet_resource_requests(template, pod, [ds])
        # corrected: 3 (ds) + 2 (pod) > 4 cpu → can never fit
        assert not meet_resource_requests(template, pod, [ds], corrected=True)
        light = make_fake_pod("p2", "default", "1", "1Gi")
        assert meet_resource_requests(template, light, [ds], corrected=True)

    def test_corrected_flag_changes_plan_diagnostic(self):
        """End-to-end: a DS-heavy cluster where the default mode keeps adding
        nodes forever (pod alone fits the template) but the corrected mode
        diagnoses up front that adding nodes can never help."""
        from .fixtures import make_fake_daemon_set

        cluster = _small_cluster()
        # the DS fits every node alone (3 <= 4 cpu) but crowds out the app
        # pod: each added template clone schedules its DS pod first, leaving
        # 1 cpu for the 2-cpu app pod
        cluster.daemon_sets = [
            make_fake_daemon_set("heavy-ds", "kube-system", "3", "1Gi")
        ]
        app = _app(1, "2", "4Gi")  # 3 (ds) + 2 (pod) > 4 cpu template
        plan = plan_capacity(
            cluster, [app], TEMPLATE, max_new_nodes=4, corrected_ds_overhead=True
        )
        assert not plan.success
        assert "cannot meet resource requests" in plan.message
        # reference-bug default: the diagnostic never fires; the plan walks
        # to the cap and reports the max-iteration failure instead
        plan = plan_capacity(cluster, [app], TEMPLATE, max_new_nodes=4)
        assert not plan.success
        assert "cannot meet resource requests" not in plan.message


class TestIncrementalPlanner:
    """plan_capacity_incremental must agree with the serial planner on
    success and node count while paying tensorization once (VERDICT r2
    task 1 — the second half of the BASELINE metric)."""

    @pytest.mark.parametrize(
        "seed",
        [5] + [pytest.param(s, marks=pytest.mark.slow) for s in (21, 34)],
    )
    def test_matches_serial_planner(self, seed):

        from simtpu.plan.incremental import plan_capacity_incremental
        from simtpu.synth import make_node, synth_apps

        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"node-{i:06d}",
                8000,
                16,
                {
                    "topology.kubernetes.io/zone": f"zone-{i % 2}",
                    "kubernetes.io/hostname": f"node-{i:06d}",
                },
            )
            for i in range(3)
        ]
        apps = synth_apps(
            160,
            seed=seed + 1,
            zones=2,
            pods_per_deployment=20,
            selector_frac=0.0,
            anti_affinity_frac=0.2,
            spread_frac=0.4,
            spread_hard_frac=0.5,
        )
        template = make_node(
            "tmpl",
            16000,
            64,
            {
                "kubernetes.io/hostname": "tmpl",
                "topology.kubernetes.io/zone": "zone-0",
            },
        )
        seed_name_hashes(seed)
        serial = plan_capacity(cluster, apps, template, max_new_nodes=60)
        seed_name_hashes(seed)
        inc = plan_capacity_incremental(cluster, apps, template, max_new_nodes=60)
        seed_name_hashes(seed)
        inc_nv = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=60, verify=False
        )
        assert inc.success == serial.success
        assert inc_nv.success == serial.success
        if serial.success:
            assert inc.nodes_added == serial.nodes_added
            # the unverified oracle may differ from a fresh greedy trace in
            # principle; in practice these scenarios agree exactly
            assert abs(inc_nv.nodes_added - serial.nodes_added) <= 1
            for r in (inc, inc_nv):
                assert len(r.result.unscheduled_pods) == 0
                placed = sum(len(s.pods) for s in r.result.node_status)
                assert placed == sum(
                    len(s.pods) for s in serial.result.node_status
                )

    def test_never_help_diagnostic(self):
        from simtpu.plan.incremental import plan_capacity_incremental
        from simtpu.workloads.expand import seed_name_hashes as _snh

        cluster = _small_cluster()
        app = _app(6, "2", "4Gi")  # needs ~3 template nodes of capacity
        tainted = make_fake_node(
            "tmpl",
            "16",
            "64Gi",
            with_node_taints([{"key": "k", "value": "v", "effect": "NoSchedule"}]),
        )
        _snh(11)
        plan = plan_capacity_incremental(cluster, [app], tainted, max_new_nodes=8)
        assert not plan.success
        assert "does not fit new node affinity or taints" in plan.message

    def test_single_candidate_cap(self):
        """max_new_nodes=1 (exclusive upper bound: no candidate beyond 0)
        must fail cleanly, not crash in the lower-bound arithmetic."""
        from simtpu.plan.incremental import plan_capacity_incremental
        from simtpu.synth import make_deployment, make_node

        cluster = ResourceTypes()
        cluster.nodes = [make_node("n0", 2000, 4, {"kubernetes.io/hostname": "n0"})]
        dep = make_deployment("big", 8, 1000, 512)
        res = ResourceTypes()
        res.deployments = [dep]
        plan = plan_capacity_incremental(
            cluster,
            [AppResource(name="a", resource=res)],
            make_node("t", 2000, 4, {"kubernetes.io/hostname": "t"}),
            max_new_nodes=1,
        )
        assert not plan.success
        assert "still failed" in plan.message


class TestProbeCompileBudget:
    """Shape-bucketed probe compilation: the candidate probe sweep must not
    shape-specialize the bulk round body per candidate size.  The scenario
    strands a PARTIAL run (failure-suffix shorter than the full run), so the
    probes' natural pow2 shapes differ from the base run's — without the
    bucket snapping (`RoundsEngine.snap_shapes`) the sweep compiles a second
    round body; with it the probes and the verify re-run ride the base
    executables."""

    def _scenario(self):
        from simtpu.synth import make_deployment, make_node

        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"node-{i:06d}", 8000, 32, {"kubernetes.io/hostname": f"node-{i:06d}"}
            )
            for i in range(6)
        ]
        res = ResourceTypes()
        res.deployments = [
            make_deployment(f"dep-{j}", 40, 1000, 512) for j in range(3)
        ]
        template = make_node("tmpl", 16000, 64, {"kubernetes.io/hostname": "tmpl"})
        return cluster, [AppResource(name="a", resource=res)], template

    def test_probe_sweep_compiles_at_most_two_round_bodies(self):
        import jax

        from simtpu.plan.incremental import plan_capacity_incremental

        cluster, apps, template = self._scenario()
        seed_name_hashes(5)
        jax.clear_caches()  # compile accounting must start cold
        plan = plan_capacity_incremental(cluster, apps, template, max_new_nodes=60)
        assert plan.success
        assert len(plan.probes) >= 3  # base + at least two candidate sizes
        rounds = {
            phase: counts.get("rounds", 0)
            for phase, counts in plan.compiles.items()
        }
        # the acceptance pin: across every candidate size, the probe sweep
        # (and the verify fresh re-run) traces the round body at most twice
        assert rounds.get("probes", 0) + rounds.get("verify", 0) <= 2, plan.compiles
        # and with the bucket snapping the expected number is zero: every
        # probe chunk snaps into a bucket the base run already compiled
        assert rounds.get("probes", 0) == 0, plan.compiles
        assert rounds.get("verify", 0) == 0, plan.compiles

    def test_plan_reports_compile_accounting(self):
        from simtpu.plan.incremental import plan_capacity_incremental

        cluster, apps, template = self._scenario()
        seed_name_hashes(5)
        plan = plan_capacity_incremental(cluster, apps, template, max_new_nodes=60)
        assert {"base", "probes"} <= set(plan.compiles)
        for counts in plan.compiles.values():
            assert {"rounds", "scan"} <= set(counts)


class TestAutoEngines:
    """Scale-aware engine defaults (VERDICT r4 task 2): `simtpu apply` is one
    command that is always its fastest — serial/binary at conformance scale,
    bulk + incremental above the size thresholds, loudly and overridably
    (the one-engine UX of the reference's `pkg/apply/apply.go:88`)."""

    def test_small_problem_keeps_serial_engines(self, capsys):
        from simtpu.plan.capacity import ApplierOptions, _resolve_engines

        cluster = _small_cluster()
        search, bulk, mesh = _resolve_engines(ApplierOptions(), cluster, [_app(3)])
        assert (search, bulk, mesh) == ("binary", False, None)
        assert capsys.readouterr().err == ""

    def test_large_node_count_selects_fast_engines(self, capsys):
        from simtpu.plan.capacity import AUTO_ENGINE_NODES, ApplierOptions, _resolve_engines

        cluster = ResourceTypes()
        cluster.nodes = [
            make_fake_node(f"n{i}", "4", "8Gi") for i in range(AUTO_ENGINE_NODES)
        ]
        search, bulk, _ = _resolve_engines(ApplierOptions(), cluster, [_app(3)])
        assert (search, bulk) == ("incremental", True)
        assert "auto-selected" in capsys.readouterr().err

    def test_large_declared_pod_count_selects_fast_engines(self):
        from simtpu.plan.capacity import AUTO_ENGINE_PODS, ApplierOptions, _resolve_engines

        search, bulk, _ = _resolve_engines(
            ApplierOptions(), _small_cluster(), [_app(AUTO_ENGINE_PODS)]
        )
        assert (search, bulk) == ("incremental", True)

    def test_explicit_flags_override_auto(self, capsys):
        from simtpu.plan.capacity import AUTO_ENGINE_PODS, ApplierOptions, _resolve_engines

        opts = ApplierOptions(search="linear", bulk=False)
        search, bulk, mesh = _resolve_engines(opts, _small_cluster(), [_app(AUTO_ENGINE_PODS)])
        assert (search, bulk, mesh) == ("linear", False, None)
        assert capsys.readouterr().err == ""

    def test_auto_path_plans_documented_config(self, example_dir, monkeypatch):
        """End-to-end: with thresholds lowered so the demo qualifies as
        large, the auto-selected bulk + incremental engines must still plan
        the reference's documented simon-config successfully."""
        from simtpu.plan import capacity as cap

        monkeypatch.chdir(os.path.dirname(example_dir))
        monkeypatch.setattr(cap, "AUTO_ENGINE_NODES", 1)
        applier = cap.Applier(
            cap.ApplierOptions(
                simon_config=os.path.join(example_dir, "simon-config.yaml"),
                extended_resources=("open-local",),
            )
        )
        plan = applier.run()
        assert plan.success, plan.message
        assert not plan.result.unscheduled_pods


class TestPlannerPreemptionDivergence:
    """VERDICT r4 weak #7: the incremental planner runs NO preemption inside
    its probes (capacity planning asks whether everything fits; eviction
    does not add capacity), while the serial planner's per-candidate
    simulate() does.  For priority-laden workloads the two therefore answer
    DIFFERENT questions: the serial plan accepts a cluster where high-prio
    pods land by evicting victims (the victims simply vanish from the
    accounting, as in the reference's Simulate), the incremental plan sizes
    the cluster so everything fits WITHOUT eviction.  This test pins the
    divergence concretely so the band is known, not anecdotal."""

    def test_incremental_over_provisions_vs_serial_preemption(self):
        from simtpu.plan.incremental import plan_capacity_incremental

        cluster = ResourceTypes()
        cluster.nodes = [make_fake_node(f"n{i}", "4", "16Gi") for i in range(2)]

        def prio(p):
            def apply(d):
                d["spec"]["template"]["spec"]["priority"] = p
            return apply

        low = make_fake_deployment("low", "default", 4, "2", "1Gi", prio(0))
        high = make_fake_deployment("high", "default", 2, "2", "1Gi", prio(100))
        res_low = ResourceTypes()
        res_low.deployments = [low]
        res_high = ResourceTypes()
        res_high.deployments = [high]
        apps = [
            AppResource(name="low", resource=res_low),
            AppResource(name="high", resource=res_high),
        ]
        template = make_fake_node("tmpl", "4", "16Gi")

        seed_name_hashes(9)
        serial = plan_capacity(cluster, apps, template, max_new_nodes=8)
        seed_name_hashes(9)
        inc = plan_capacity_incremental(cluster, apps, template, max_new_nodes=8)

        assert serial.success and inc.success
        # serial: the two high-prio pods preempt two low-prio pods — zero
        # nodes added, two victims gone from the final cluster
        assert serial.nodes_added == 0
        assert len(serial.result.preempted_pods) == 2
        # incremental: no eviction, so one template node is added and every
        # pod (including the would-be victims) is genuinely placed
        assert inc.nodes_added == 1
        assert not inc.result.unscheduled_pods
        assert not inc.result.preempted_pods
        # the documented band: incremental >= serial, by exactly the
        # capacity the victims would have freed
        assert inc.nodes_added >= serial.nodes_added


class TestBinarySearchCapNonMonotone:
    """ISSUE 3 satellite: with DaemonSet overhead, the occupancy-cap
    verdict is NOT monotone in the clone count — every clone adds DS usage
    `u` against capacity `A`, so the average rate climbs toward u/A and a
    narrow feasible window can sit between "too few clones to schedule"
    and "too many clones for the cap".  The doubling probe jumps straight
    over such a window; the pinned behavior is a LOUD fallback to the
    reference's linear scan the moment a cap rejection is seen (module
    docstring of plan/capacity.py documents the choice)."""

    def _scenario(self):
        from .fixtures import (
            make_fake_daemon_set,
            with_template_node_selector,
        )

        cluster = ResourceTypes()
        # ample base capacity with zero usage keeps the initial rate low,
        # so the per-clone DS share (6/10) RAISES the average as clones
        # are added — the non-monotone direction
        cluster.nodes = [
            make_fake_node(f"base-{i}", "10", "100Gi") for i in range(10)
        ]
        # the DaemonSet and the workload both target the template pool
        # only (the base nodes exist purely as cap denominator)
        cluster.daemon_sets = [
            make_fake_daemon_set(
                "heavy-agent", "kube-system", "6", "1Gi",
                with_template_node_selector({"pool": "fresh"}),
            )
        ]
        res = ResourceTypes()
        res.deployments = [
            make_fake_deployment(
                "web", "default", 6, "2", "1Gi",
                with_template_node_selector({"pool": "fresh"}),
            )
        ]
        apps = [AppResource(name="web", resource=res)]
        template = make_fake_node(
            "tmpl", "10", "100Gi", with_node_labels({"pool": "fresh"})
        )
        # clones: 10 cores, 6 to the DS -> 2 workload pods each; k=3
        # schedules all 6.  cpu rate(k) = (6k + 12) / (100 + 10k):
        # k=3 -> 23% (inside the cap), k=4 -> 25%, k>=4 rejected by
        # MaxCPU=24 -- the feasible window is exactly {3}, and the
        # doubling probe (1, 2, 4, ...) never lands on it
        return cluster, apps, template

    def test_binary_falls_back_to_linear_answer(self, monkeypatch, capsys):
        cluster, apps, template = self._scenario()
        monkeypatch.setenv(C.ENV_MAX_CPU, "24")

        seed_name_hashes(11)
        linear = plan_capacity(
            cluster, apps, template, max_new_nodes=10, search="linear"
        )
        assert linear.success and linear.nodes_added == 3, linear.message

        seed_name_hashes(11)
        binary = plan_capacity(
            cluster, apps, template, max_new_nodes=10, search="binary"
        )
        err = capsys.readouterr().err
        assert binary.success, binary.message
        assert binary.nodes_added == linear.nodes_added == 3
        assert "falling back" in err  # the loud part of the contract
        # the window's upper neighbor really was cap-rejected (scheduled
        # but infeasible) — the trigger for the fallback
        assert binary.probes.get(4) == 0

    def test_caps_off_stays_on_bisection(self, monkeypatch, capsys):
        """Without caps the window degenerates to the monotone case: the
        bisection must find the same count as linear with no fallback."""
        cluster, apps, template = self._scenario()
        monkeypatch.delenv(C.ENV_MAX_CPU, raising=False)

        seed_name_hashes(11)
        linear = plan_capacity(
            cluster, apps, template, max_new_nodes=10, search="linear"
        )
        seed_name_hashes(11)
        binary = plan_capacity(
            cluster, apps, template, max_new_nodes=10, search="binary"
        )
        assert "falling back" not in capsys.readouterr().err
        assert binary.success and linear.success
        assert binary.nodes_added == linear.nodes_added == 3


class TestEngineBlockRound16:
    """ADVICE r5 #1 residue (ISSUE 16): the round-16 A/B switches — heavy
    wavefront drafting, the fused filter/score cascade, and the direct
    compact-delta apply — are recorded in the --json engine block next to
    the auto engine selection, so scripted consumers can detect every
    non-reference-exact fast path from the JSON alone."""

    def _plan(self):
        from simtpu.plan import capacity as cap
        from simtpu.synth import make_node, synth_apps, synth_cluster

        cluster = synth_cluster(6, seed=63, zones=3, taint_frac=0.0)
        apps = synth_apps(
            120, seed=64, zones=3, pods_per_deployment=40,
            selector_frac=0.0, toleration_frac=0.0, spread_frac=0.2,
        )
        template = make_node(
            "tmpl", 64000, 256,
            {"kubernetes.io/hostname": "tmpl",
             "topology.kubernetes.io/zone": "zone-plan"},
        )
        applier = cap.Applier.__new__(cap.Applier)
        applier.opts = cap.ApplierOptions(search="incremental", precompile=False)
        applier.load_apps = lambda: list(apps)
        applier.load_cluster = lambda: cluster
        applier.load_new_node = lambda: template
        return applier.run()

    def test_round16_switches_recorded_in_json(self):
        import json

        from simtpu.cli import _plan_json

        plan = self._plan()
        assert plan.success, plan.message
        doc = json.loads(_plan_json(plan))
        eng = doc["engine"]
        # the auto-selection record rides alongside the new switches
        assert {"search", "auto_search", "auto_bulk"} <= set(eng)
        assert eng["auto_search"] is False  # explicit search= above
        # round-16 switches: booleans mirroring the env A/B levers
        assert eng["wave_heavy"] is True
        assert eng["fused_cascade"] is True
        dd = eng["delta_direct"]
        assert dd["enabled"] is True
        for key in ("applied", "expand", "compress"):
            assert isinstance(dd[key], int) and dd[key] >= 0
        # the wavefront family carries the new hard-drafting counter
        assert "draft_hard" in eng["wavefront"]
        assert eng["wavefront"]["draft_hard"] >= 0

    def test_switch_state_follows_env(self, monkeypatch):
        import json

        from simtpu.cli import _plan_json

        monkeypatch.setenv("SIMTPU_WAVE_HEAVY", "0")
        monkeypatch.setenv("SIMTPU_FUSED_CASCADE", "0")
        monkeypatch.setenv("SIMTPU_DELTA_DIRECT", "0")
        doc = json.loads(_plan_json(self._plan()))
        eng = doc["engine"]
        assert eng["wave_heavy"] is False
        assert eng["fused_cascade"] is False
        assert eng["delta_direct"]["enabled"] is False
        assert eng["delta_direct"]["applied"] == 0
