"""`io/cluster.py`'s live-cluster snapshot path against a mocked kubernetes
client (the package is an optional dependency, so the fake is injected via
sys.modules): the static-pod filter, the deployment-owned-ReplicaSet and
cronjob-owned-Job skips, and the policy/batch API-group fallbacks — the one
public entry point that had no coverage (ISSUE 3 satellite).
"""

from __future__ import annotations

import sys
import types

import pytest

from simtpu.io.cluster import create_cluster_resource_from_client


class FakeApiException(Exception):
    def __init__(self, status):
        super().__init__(f"status={status}")
        self.status = status


def _listing(items):
    def method(self):
        return types.SimpleNamespace(items=list(items))

    return method


def _fixtures():
    mirror_pod = {
        "metadata": {
            "name": "kube-proxy-abc",
            "annotations": {"kubernetes.io/config.mirror": "deadbeef"},
        }
    }
    workload_pod = {"metadata": {"name": "web-123", "annotations": {}}}
    owned_rs = {
        "metadata": {
            "name": "web-rs",
            "ownerReferences": [{"kind": "Deployment", "name": "web"}],
        }
    }
    bare_rs = {"metadata": {"name": "standalone-rs"}}
    owned_job = {
        "metadata": {
            "name": "backup-123",
            "ownerReferences": [{"kind": "CronJob", "name": "backup"}],
        }
    }
    bare_job = {"metadata": {"name": "oneshot"}}
    return {
        "nodes": [{"metadata": {"name": "n0"}}, {"metadata": {"name": "n1"}}],
        "pods": [mirror_pod, workload_pod],
        "pdbs_v1": [{"metadata": {"name": "pdb-v1"}}],
        "pdbs_beta": [{"metadata": {"name": "pdb-beta"}}],
        "services": [{"metadata": {"name": "svc"}}],
        "storage_classes": [{"metadata": {"name": "sc"}}],
        "pvcs": [{"metadata": {"name": "pvc"}}],
        "rcs": [{"metadata": {"name": "rc"}}],
        "deployments": [{"metadata": {"name": "web"}}],
        "replica_sets": [owned_rs, bare_rs],
        "stateful_sets": [{"metadata": {"name": "db"}}],
        "daemon_sets": [{"metadata": {"name": "logger"}}],
        "jobs": [owned_job, bare_job],
        "cron_jobs_v1": [{"metadata": {"name": "cron-v1"}}],
        "cron_jobs_beta": [{"metadata": {"name": "cron-beta"}}],
    }


def _install_fake_kubernetes(
    monkeypatch,
    fx,
    pdb_v1_status=None,
    cron_v1_status=None,
    drop_policy_apis=(),
):
    """Builds the kubernetes/kubernetes.client/kubernetes.config module
    triple `create_cluster_resource_from_client` imports.  `*_status`
    makes the MODERN API raise an ApiException with that status (404 =
    'API not served', exercising the beta fallback)."""
    calls = {"kubeconfig": None}

    def _raise_or(items, status):
        if status is None:
            return _listing(items)

        def method(self):
            raise FakeApiException(status)

        return method

    core = type("CoreV1Api", (), {
        "list_node": _listing(fx["nodes"]),
        "list_pod_for_all_namespaces": _listing(fx["pods"]),
        "list_service_for_all_namespaces": _listing(fx["services"]),
        "list_persistent_volume_claim_for_all_namespaces": _listing(fx["pvcs"]),
        "list_replication_controller_for_all_namespaces": _listing(fx["rcs"]),
    })
    apps = type("AppsV1Api", (), {
        "list_deployment_for_all_namespaces": _listing(fx["deployments"]),
        "list_replica_set_for_all_namespaces": _listing(fx["replica_sets"]),
        "list_stateful_set_for_all_namespaces": _listing(fx["stateful_sets"]),
        "list_daemon_set_for_all_namespaces": _listing(fx["daemon_sets"]),
    })
    batch = type("BatchV1Api", (), {
        "list_job_for_all_namespaces": _listing(fx["jobs"]),
        "list_cron_job_for_all_namespaces": _raise_or(
            fx["cron_jobs_v1"], cron_v1_status
        ),
    })
    batch_beta = type("BatchV1beta1Api", (), {
        "list_cron_job_for_all_namespaces": _listing(fx["cron_jobs_beta"]),
    })
    storage = type("StorageV1Api", (), {
        "list_storage_class": _listing(fx["storage_classes"]),
    })
    policy_v1 = type("PolicyV1Api", (), {
        "list_pod_disruption_budget_for_all_namespaces": _raise_or(
            fx["pdbs_v1"], pdb_v1_status
        ),
    })
    policy_beta = type("PolicyV1beta1Api", (), {
        "list_pod_disruption_budget_for_all_namespaces": _listing(
            fx["pdbs_beta"]
        ),
    })
    api_client = type("ApiClient", (), {
        "sanitize_for_serialization": staticmethod(lambda obj: obj),
    })

    client_mod = types.ModuleType("kubernetes.client")
    for cls in (
        core, apps, batch, batch_beta, storage, policy_v1, policy_beta,
        api_client,
    ):
        if cls.__name__ not in drop_policy_apis:
            setattr(client_mod, cls.__name__, cls)
    exceptions_mod = types.ModuleType("kubernetes.client.exceptions")
    exceptions_mod.ApiException = FakeApiException
    client_mod.exceptions = exceptions_mod

    config_mod = types.ModuleType("kubernetes.config")

    def load_kube_config(config_file=None):
        calls["kubeconfig"] = config_file

    config_mod.load_kube_config = load_kube_config

    kube_mod = types.ModuleType("kubernetes")
    kube_mod.client = client_mod
    kube_mod.config = config_mod

    monkeypatch.setitem(sys.modules, "kubernetes", kube_mod)
    monkeypatch.setitem(sys.modules, "kubernetes.client", client_mod)
    monkeypatch.setitem(
        sys.modules, "kubernetes.client.exceptions", exceptions_mod
    )
    monkeypatch.setitem(sys.modules, "kubernetes.config", config_mod)
    return calls


def _names(objs):
    return [o["metadata"]["name"] for o in objs]


class TestCreateClusterResourceFromClient:
    def test_snapshot_filters_and_modern_apis(self, monkeypatch):
        fx = _fixtures()
        calls = _install_fake_kubernetes(monkeypatch, fx)
        res = create_cluster_resource_from_client("/tmp/kubeconfig")
        assert calls["kubeconfig"] == "/tmp/kubeconfig"
        assert _names(res.nodes) == ["n0", "n1"]
        # only static (mirror) pods survive — workload pods are regenerated
        # by the controller emulation
        assert _names(res.pods) == ["kube-proxy-abc"]
        # deployment-owned ReplicaSets are skipped (their Deployment is the
        # source of truth); standalone ones kept
        assert _names(res.replica_sets) == ["standalone-rs"]
        # cronjob-owned Jobs are skipped; standalone ones kept
        assert _names(res.jobs) == ["oneshot"]
        assert _names(res.pod_disruption_budgets) == ["pdb-v1"]
        assert _names(res.cron_jobs) == ["cron-v1"]
        assert _names(res.deployments) == ["web"]
        assert _names(res.services) == ["svc"]
        assert _names(res.storage_classes) == ["sc"]
        assert _names(res.persistent_volume_claims) == ["pvc"]
        assert _names(res.replication_controllers) == ["rc"]
        assert _names(res.stateful_sets) == ["db"]
        assert _names(res.daemon_sets) == ["logger"]

    def test_api_group_fallbacks_on_404(self, monkeypatch):
        """PDBs moved policy/v1beta1 → policy/v1 and CronJobs
        batch/v1beta1 → batch/v1 in k8s 1.25; a 404 (API not served) on
        the modern group must fall through to the beta group."""
        fx = _fixtures()
        _install_fake_kubernetes(
            monkeypatch, fx, pdb_v1_status=404, cron_v1_status=404
        )
        res = create_cluster_resource_from_client("/tmp/kubeconfig")
        assert _names(res.pod_disruption_budgets) == ["pdb-beta"]
        assert _names(res.cron_jobs) == ["cron-beta"]

    def test_non_404_errors_propagate(self, monkeypatch):
        """RBAC/network failures (403 here) must raise, not silently fall
        through to an older API group."""
        fx = _fixtures()
        _install_fake_kubernetes(monkeypatch, fx, pdb_v1_status=403)
        with pytest.raises(FakeApiException) as exc:
            create_cluster_resource_from_client("/tmp/kubeconfig")
        assert exc.value.status == 403

    def test_404_with_no_fallback_api_raises(self, monkeypatch):
        """Every candidate API group 404ing (or missing from the client)
        surfaces the last 404 instead of returning an empty list."""
        fx = _fixtures()
        _install_fake_kubernetes(
            monkeypatch, fx, pdb_v1_status=404,
            drop_policy_apis=("PolicyV1beta1Api",),
        )
        with pytest.raises(FakeApiException) as exc:
            create_cluster_resource_from_client("/tmp/kubeconfig")
        assert exc.value.status == 404
