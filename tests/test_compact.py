"""The compact carried-state layout (engine/state.py, ISSUE 5): the
between-dispatch carry stores kind-1 topology keys' count rows as domain
histograms with integer dtypes, and expands back to the dense in-kernel
SchedState through one gather.

Pinned here:
- compress → expand is a BIT-identical round trip on a really-placed state
  (the exactness the whole layout rests on);
- placements are bit-identical with the compact carry on vs off across the
  serial scan, the bulk rounds engine, the speculative wavefront, GSPMD
  sharding, the incremental planner, and the fault sweep (the acceptance
  A/B);
- the carried bytes shrink on a multi-domain problem (the gauge the bench's
  `state_bytes` reports);
- the donated-state reuse guard: a dispatch that fails AFTER donating the
  carry must not leave place() reusing a dead buffer — the retry rebuilds
  from the placement log and lands the exact same placements.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu.core.tensorize import Tensorizer
from simtpu.engine.rounds import RoundsEngine
from simtpu.engine.scan import Engine
from simtpu.engine.state import (
    CompactState,
    compact_spec,
    compress_state,
    ensure_dense,
    state_nbytes,
)
from simtpu.obs.metrics import family as metrics_family
from simtpu.synth import make_node, synth_apps, synth_cluster
from simtpu.workloads.expand import get_valid_pods_exclude_daemonset


def state_gauge():
    # registry-backed carried-state gauges (the alias view is gone)
    from simtpu.engine.state import STATE_KEYS

    return metrics_family("state", STATE_KEYS)


def _round_robin_pods(apps):
    """Expand apps to pods, round-robined across deployments so the FIRST
    half of the list already contains a pod of every group: the second
    `place()` batch then interns no new groups/terms, the vocabulary stays
    stable, and the carry-REUSE branch of Engine.place (expansion of the
    stored compact state) really runs — a front-half/back-half split would
    cut across deployments, grow the vocab, and silently route every
    second batch through the from-log rebuild instead.  (synth_apps emits
    one app object per pod; the "app" label is the group identity.)"""
    per_dep: dict = {}
    for a in apps:
        for p in get_valid_pods_exclude_daemonset(a.resource):
            lbl = ((p.get("metadata") or {}).get("labels") or {}).get("app")
            per_dep.setdefault(lbl, []).append(p)
    deps = list(per_dep.values())
    pods = []
    for i in range(max(len(ps) for ps in deps)):
        for ps in deps:
            if i < len(ps):
                pods.append(ps[i])
    assert len(pods) // 2 >= len(deps), "first half must cover every group"
    return pods


def _mixed_problem():
    """A small cluster + pod list exercising zone AND hostname topology keys
    (tabular and dense rows), extended resources, and hard constraints.
    > DOM_SMALL nodes, or the hostname key itself would count as
    small-domain and the dense row class would be empty."""
    cluster = synth_cluster(
        72, seed=41, zones=3, taint_frac=0.1, gpu_frac=0.3, storage_frac=0.4
    )
    apps = synth_apps(
        90,
        seed=42,
        zones=3,
        pods_per_deployment=15,
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.4,
        anti_affinity_hard_frac=0.5,
        spread_frac=0.3,
        spread_hard_frac=0.5,
        gpu_frac=0.2,
        storage_frac=0.2,
        affinity_frac=0.2,
    )
    return cluster, _round_robin_pods(apps)


@pytest.fixture(scope="module")
def problem():
    return _mixed_problem()


def _place_batches(factory, cluster, pods, compact, speculate=False):
    """Two place() calls through one engine (the second takes the carry
    reuse path — expansion of the stored compact state)."""
    tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    eng = factory(tz)
    eng.compact = compact
    if speculate:
        eng.speculate = True
    half = len(pods) // 2
    n1, r1, _ = eng.place(tz.add_pods(pods[:half]))
    b2 = tz.add_pods(pods[half:])
    # the reuse precondition: were the vocabulary to grow here, place()
    # would rebuild from the log and the carry-reuse path under test
    # (compact expansion of the stored state) would go dark
    assert eng.state_vocab(tz.freeze()) == eng._last_vocab
    n2, r2, _ = eng.place(b2)
    return eng, np.concatenate([n1, n2]), np.concatenate([r1, r2])


class TestRoundTrip:
    def test_compress_expand_bit_identical(self, problem):
        """The dense carry of a REAL placement survives compress → expand
        with every plane bit-identical (dtype included)."""
        import jax
        import jax.numpy as jnp

        cluster, pods = problem
        eng, _, _ = _place_batches(RoundsEngine, cluster, pods, compact=True)
        tensors = eng.tensorizer.freeze()
        spec = compact_spec(tensors)
        assert spec.enabled, "the mixed problem must have tabular keys"
        # both row classes must be populated, or the test is vacuous
        assert spec.dev.t_tab.shape[0] > 0
        assert spec.dev.t_dense.shape[0] > 0
        dense = eng.carried_state()
        copy = jax.tree_util.tree_map(jnp.copy, dense)
        again = ensure_dense(compress_state(spec.dev, copy), tensors)
        for name in dense._fields:
            want = np.asarray(getattr(dense, name))
            got = np.asarray(getattr(again, name))
            assert got.dtype == want.dtype, name
            assert np.array_equal(got, want), (
                f"plane {name} not bit-identical after compress/expand"
            )

    def test_carry_is_compact_and_integer(self, problem):
        cluster, pods = problem
        eng, _, _ = _place_batches(RoundsEngine, cluster, pods, compact=True)
        carry = eng.last_state
        assert isinstance(carry, CompactState)
        for name in ("cm_tab", "cm_dense", "cnt_total", "ports_used",
                     "vols_any", "vols_rw"):
            assert np.issubdtype(
                np.asarray(getattr(carry, name)).dtype, np.integer
            ), name
        assert np.asarray(carry.sdev_free).dtype == np.bool_


class TestPlacementAB:
    """Placements bit-identical with the compact carry on vs off."""

    @pytest.mark.parametrize("factory", [Engine, RoundsEngine])
    def test_engines(self, problem, factory):
        cluster, pods = problem
        _, n_on, r_on = _place_batches(factory, cluster, pods, compact=True)
        _, n_off, r_off = _place_batches(factory, cluster, pods, compact=False)
        assert np.array_equal(n_on, n_off)
        assert np.array_equal(r_on, r_off)

    def test_wavefront(self, problem):
        """The speculative wavefront dispatcher over a compact-carrying
        engine matches the dense-carrying pod-at-a-time scan."""
        cluster, pods = problem
        _, n_on, _ = _place_batches(
            Engine, cluster, pods, compact=True, speculate=True
        )
        _, n_off, _ = _place_batches(
            Engine, cluster, pods, compact=False, speculate=False
        )
        assert np.array_equal(n_on, n_off)

    def test_sharded(self, problem):
        from simtpu.parallel.mesh import make_mesh
        from simtpu.parallel.sharded import ShardedRoundsEngine

        cluster, pods = problem
        mesh = make_mesh(sweep=1)

        def run(compact):
            tz = Tensorizer(
                cluster.nodes, storage_classes=cluster.storage_classes
            )
            eng = ShardedRoundsEngine(tz, mesh)
            eng.compact = compact
            half = len(pods) // 2
            n1, _, _ = eng.place(tz.add_pods(pods[:half]))
            n2, _, _ = eng.place(tz.add_pods(pods[half:]))
            return np.concatenate([n1, n2])

        assert np.array_equal(run(True), run(False))

    def test_incremental_planner(self, monkeypatch):
        """The probe sweep copies and expands COMPACT snapshots; the plan
        answer must match the dense-carry run (nodes_added > 0 so probes
        really run)."""
        from simtpu.plan.incremental import plan_capacity_incremental

        cluster = synth_cluster(6, seed=13, zones=3, taint_frac=0.0)
        apps = synth_apps(
            400, seed=14, zones=3, pods_per_deployment=40,
            selector_frac=0.0, toleration_frac=0.0, anti_affinity_frac=0.1,
            spread_frac=0.3,
        )
        template = make_node(
            "tmpl", 64000, 256,
            {"kubernetes.io/hostname": "tmpl",
             "topology.kubernetes.io/zone": "zone-plan"},
        )
        got = {}
        for env in ("1", "0"):
            monkeypatch.setenv("SIMTPU_COMPACT", env)
            plan = plan_capacity_incremental(
                cluster, apps, template, max_new_nodes=24, materialize=False
            )
            got[env] = (plan.success, plan.nodes_added, dict(plan.probes))
        assert got["1"] == got["0"]
        assert got["1"][0] and got["1"][1] > 0, (
            "the scenario must require added nodes or the probe path is "
            f"untested: {got['1']}"
        )

    def test_fault_sweep(self, problem, monkeypatch):
        """The batched scenario sweep drains from the engine's carry —
        identical per-scenario outcomes whether that carry is compact or
        dense."""
        from simtpu.faults import (
            place_cluster,
            single_node_scenarios,
            sweep_scenarios,
        )

        cluster, _ = problem
        apps = synth_apps(
            60, seed=52, zones=3, pods_per_deployment=12,
            selector_frac=0.1, anti_affinity_frac=0.2, spread_frac=0.2,
        )
        ref = None
        for env in ("1", "0"):
            monkeypatch.setenv("SIMTPU_COMPACT", env)
            pc = place_cluster(cluster, apps)
            assert isinstance(
                pc.engine.last_state, CompactState
            ) == (env == "1")
            scen = single_node_scenarios(pc.n_nodes, nodes=cluster.nodes)
            sw = sweep_scenarios(pc, scen)
            if ref is None:
                ref = (sw.unplaced.copy(), sw.requeue_nodes.copy())
            else:
                assert np.array_equal(ref[0], sw.unplaced)
                assert np.array_equal(ref[1], sw.requeue_nodes)


class TestBytesShrink:
    def test_multi_domain_carry_smaller(self):
        """Zone-dominated constraints → the compact carry is measurably
        smaller than the dense one (the bench asserts >= 2x at its shape;
        at this tiny node count the fixed planes weigh more, so just pin a
        real reduction and the gauge plumbing)."""
        cluster = synth_cluster(120, seed=21, zones=4, taint_frac=0.0)
        apps = synth_apps(
            300, seed=22, zones=4, pods_per_deployment=30,
            selector_frac=0.1, anti_affinity_frac=0.0, spread_frac=0.8,
            affinity_frac=0.5,
        )
        pods = _round_robin_pods(apps)
        eng, _, _ = _place_batches(RoundsEngine, cluster, pods, compact=True)
        g = state_gauge()
        assert g["compact"] is True
        assert g["carried_bytes"] == sum(state_nbytes(eng.last_state).values())
        assert g["carried_bytes"] < g["dense_bytes"], g
        assert set(g["planes"]) == set(CompactState._fields)


class TestDonatedReuseGuard:
    """Engine.place's cache bookkeeping runs only after a successful
    dispatch: a dispatch that raises AFTER donating the carry must leave
    the engine rebuilding from the log — never re-validating (and reading)
    a donated buffer on the retry."""

    # two cases cover both engines AND both carry layouts (the dense case
    # is where the donated buffer itself would be re-read on a buggy
    # retry; the compact case pins the expand-before-donate ordering)
    @pytest.mark.parametrize(
        "factory,compact", [(Engine, False), (RoundsEngine, True)]
    )
    def test_failed_dispatch_then_retry(self, problem, factory, compact):
        cluster, pods = problem
        half = len(pods) // 2

        # oracle: the same two batches through an unsabotaged engine
        _, want_nodes, want_reasons = _place_batches(
            factory, cluster, pods, compact
        )

        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        eng = factory(tz)
        eng.compact = compact
        eng.place(tz.add_pods(pods[:half]))
        assert eng.last_state is not None and not eng._state_dirty

        real_dispatch = eng._dispatch

        def boom(statics, state, pod_arrays, flags):
            # run the REAL dispatch first so the carried state genuinely
            # gets donated/consumed, then fail before place() can store
            real_dispatch(statics, state, pod_arrays, flags)
            raise RuntimeError("injected post-donation failure")

        eng._dispatch = boom
        b2 = tz.add_pods(pods[half:])
        # vocab-stable second batch (round-robin pod order): the retry
        # below WOULD take the reuse branch — and re-read the donated
        # buffer — were the guard not disarming it
        assert eng.state_vocab(tz.freeze()) == eng._last_vocab
        with pytest.raises(RuntimeError, match="post-donation"):
            eng.place(b2)
        # the guard: the failed run left the reuse branch disarmed
        assert eng._state_dirty
        eng._dispatch = real_dispatch
        n2, r2, _ = eng.place(b2)  # must rebuild from the log and succeed
        assert np.array_equal(n2, want_nodes[half:])
        assert np.array_equal(r2, want_reasons[half:])
        # and the carry is live again for a further batch
        assert not eng._state_dirty
