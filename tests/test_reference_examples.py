"""Conformance over the reference's own `example/` corpus — the de-facto
acceptance suite (SURVEY.md §4; VERDICT r1 task 3).

Runs the two documented configs (`example/simon-config.yaml`,
`example/simon-gpushare-config.yaml`, `README.md:55-57`) end-to-end through
the Applier (path-rebased by chdir-ing into the reference checkout), plus
each app directory individually against the `demo_1` cluster, asserting the
reference's own result contract: plan success, zero unscheduled pods, and
every workload produced exactly its replica count of placed pods
(`check_result`, the `core_test.go:364-591` port).
"""

from __future__ import annotations

import os

import pytest

from simtpu import AppResource
from simtpu.core.objects import ResourceTypes
from simtpu.io.cluster import create_cluster_resource_from_cluster_config
from simtpu.io.yaml_loader import (
    get_objects_from_yaml_content,
    get_yaml_content_from_directory,
)
from simtpu.plan.capacity import Applier, ApplierOptions, plan_capacity
from simtpu.workloads.expand import seed_name_hashes

from .test_conformance import check_result

# derived from the example_dir fixture's path at use sites so the skip gate
# and the chdir target cannot drift apart


@pytest.fixture(autouse=True)
def _seed():
    seed_name_hashes(7)


def _final_cluster(cluster: ResourceTypes, plan) -> ResourceTypes:
    """The cluster as the successful plan left it: original resources with
    the node list replaced by the final node set (template clones included),
    so `check_result`'s per-node DaemonSet expectations match the expansion
    the simulation actually ran."""
    final = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
    final.nodes = [st.node for st in plan.result.node_status]
    return final


def _load_app(example_dir: str, name: str) -> AppResource:
    content = get_yaml_content_from_directory(
        os.path.join(example_dir, "application", name)
    )
    return AppResource(name=name, resource=get_objects_from_yaml_content(content))


def _new_node(example_dir: str, name: str) -> dict:
    from simtpu.io.cluster import match_and_set_local_storage_annotation_on_node

    path = os.path.join(example_dir, "newnode", name)
    content = get_yaml_content_from_directory(path)
    nodes = get_objects_from_yaml_content(content).nodes
    # the sibling <node>.json files carry the local-storage inventory
    # (Applier.load_new_node does the same, `pkg/apply/apply.go:128-134`)
    match_and_set_local_storage_annotation_on_node(nodes, path)
    return nodes[0]


class TestDocumentedConfigs:
    """The two runs the reference README documents, through the full Applier."""

    @pytest.mark.slow
    def test_simon_config_plans_all_apps(self, example_dir, monkeypatch):
        # config paths are relative to the reference checkout root
        monkeypatch.chdir(os.path.dirname(example_dir))
        applier = Applier(
            ApplierOptions(
                simon_config=os.path.join(example_dir, "simon-config.yaml"),
                extended_resources=("open-local",),
            )
        )
        apps = applier.load_apps()
        cluster = applier.load_cluster()
        plan = applier.run()
        assert plan.success, plan.message
        assert plan.message == "Success!"
        assert not plan.result.unscheduled_pods
        # the app list is the configured five, in order (yoda is the chart)
        assert [a.name for a in apps] == [
            "yoda",
            "simple",
            "complicated",
            "open_local",
            "more_pods",
        ]
        check_result(_final_cluster(cluster, plan), apps, plan.result)

    @pytest.mark.slow
    def test_gpushare_config_plans_all_apps(self, example_dir, monkeypatch):
        monkeypatch.chdir(os.path.dirname(example_dir))
        applier = Applier(
            ApplierOptions(
                simon_config=os.path.join(example_dir, "simon-gpushare-config.yaml"),
                extended_resources=("gpu",),
            )
        )
        apps = applier.load_apps()
        cluster = applier.load_cluster()
        plan = applier.run()
        assert plan.success, plan.message
        assert not plan.result.unscheduled_pods
        check_result(_final_cluster(cluster, plan), apps, plan.result)
        # every placed GPU pod carries a device assignment annotation
        # (GpuSharePlugin.Bind applies the pod copy with gpu-index,
        # open-gpu-share.go:221-241)
        gpu_pods = 0
        for st in plan.result.node_status:
            for pod in st.pods:
                anno = (pod.get("metadata") or {}).get("annotations") or {}
                if anno.get("alibabacloud.com/gpu-mem"):
                    gpu_pods += 1
                    assert anno.get("alibabacloud.com/gpu-index"), pod["metadata"][
                        "name"
                    ]
        assert gpu_pods > 0


class TestAppDirsAgainstDemo1:
    """Each non-chart app directory individually against the demo_1 cluster
    (+ the demo_1 template node when the 4 fixed nodes can't hold it)."""

    @pytest.mark.parametrize(
        "app_name", ["simple", "complicate", "more_pods", "open_local"]
    )
    def test_app_plans_exactly(self, example_dir, app_name):
        cluster = create_cluster_resource_from_cluster_config(
            os.path.join(example_dir, "cluster", "demo_1")
        )
        app = _load_app(example_dir, app_name)
        plan = plan_capacity(
            cluster,
            [app],
            _new_node(example_dir, "demo_1"),
            extended_resources=("open-local",),
        )
        assert plan.success, plan.message
        assert not plan.result.unscheduled_pods
        check_result(_final_cluster(cluster, plan), [app], plan.result)
