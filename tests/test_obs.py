"""The unified observability layer (ISSUE 8, docs/observability.md):

- span tracer: nesting, thread-safety under the AOT pool, ring-buffer
  wraparound, Perfetto (Chrome trace-event) export validity, and the
  zero-overhead no-op contract when disabled;
- metrics registry: typed instruments, snapshot/delta protocol, and the
  engine counter families (`fetch.*` / `compile.*` / `wavefront.*` /
  `backoff.*` / `state.*`) read directly off the registry across the
  wavefront/compact engine A/Bs (the one-release legacy alias views are
  gone — ISSUE 13 — and their removal is pinned here);
- flight recorder: a bundle lands on the injected exit-3 (deadline) and
  exit-4 (audit divergence) CLI paths, and SIMTPU_FLIGHT=0 disables it;
- CLI surface: `apply --trace` writes a valid trace whose span sums
  reconcile with the --json phase timings, the --json document carries
  `schema_version` + the `metrics` block with the legacy engine-block
  families as bit-equal aliases, and `simtpu version --json` reports the
  schema stamp.
"""

from __future__ import annotations

import glob
import json
import os
import threading

import numpy as np
import pytest

from simtpu.obs import trace as obs_trace
from simtpu.obs.metrics import REGISTRY, SCHEMA_VERSION, MetricsRegistry


@pytest.fixture
def tracer():
    """Fresh tracer for a test; restores the prior (disabled) state."""
    was = obs_trace.enabled()
    obs_trace.enable()
    yield obs_trace
    if not was:
        obs_trace.disable()


class TestSpanTracer:
    def test_nesting_depth_and_containment(self, tracer):
        with obs_trace.span("outer", phase="x"):
            with obs_trace.span("inner"):
                pass
        evs = {e[0]: e for e in obs_trace.events()}
        assert set(evs) == {"outer", "inner"}
        name, ts_o, dur_o, _, depth_o, attrs = evs["outer"]
        _, ts_i, dur_i, _, depth_i, _ = evs["inner"]
        assert depth_o == 0 and depth_i == 1
        assert attrs == {"phase": "x"}
        # the inner interval is contained in the outer one
        assert ts_o <= ts_i and ts_i + dur_i <= ts_o + dur_o

    def test_mid_span_attributes(self, tracer):
        with obs_trace.span("s", a=1) as sp:
            sp.set(b=2)
        ((_, _, _, _, _, attrs),) = obs_trace.events()
        assert attrs == {"a": 1, "b": 2}

    def test_thread_safety_many_threads(self, tracer):
        """Concurrent spans from worker threads lose no events and keep
        per-thread nesting depths (the AOT pool regime)."""
        n_threads, per_thread = 8, 50

        def work():
            for _ in range(per_thread):
                with obs_trace.span("t.outer"):
                    with obs_trace.span("t.inner"):
                        pass

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        evs = obs_trace.events()
        assert len(evs) == n_threads * per_thread * 2
        for name, _, _, _, depth, _ in evs:
            assert depth == (1 if name == "t.inner" else 0)
        # at least two distinct recording threads (idents are REUSED when
        # a thread exits before a later one starts, so == n_threads would
        # be flaky by scheduler luck)
        assert len({tid for _, _, _, tid, _, _ in evs}) >= 2

    def test_aot_pool_compile_spans(self, tracer):
        """The precompile pipeline's per-signature compile spans are
        recorded FROM the pool threads (engine/precompile.py)."""
        import jax
        import jax.numpy as jnp

        from simtpu.engine.precompile import AotPipeline, _sds

        pipe = AotPipeline(workers=2)
        try:
            fn = jax.jit(lambda x: x * 2)
            assert pipe.submit("obs_test", (), fn, (_sds((4,), jnp.int32),))
            pipe.wait_all(timeout=60)
        finally:
            pipe.shutdown()
        spans = [e for e in obs_trace.events() if e[0] == "aot.compile"]
        assert len(spans) == 1
        assert spans[0][5]["sig"] == "obs_test"
        assert spans[0][3] != threading.get_ident(), "span must be on a pool thread"

    def test_ring_wraparound_keeps_newest(self):
        obs_trace.enable(capacity=8)
        try:
            for i in range(20):
                with obs_trace.span(f"s{i}"):
                    pass
            evs = obs_trace.events()
            assert [e[0] for e in evs] == [f"s{i}" for i in range(12, 20)]
            assert obs_trace.dropped() == 12
            # timestamps stay chronological across the wrap
            ts = [e[1] for e in evs]
            assert ts == sorted(ts)
        finally:
            obs_trace.disable()

    def test_perfetto_export_valid(self, tracer, tmp_path):
        with obs_trace.span("a", pods=3):
            obs_trace.instant("mark", n=1)
        path = obs_trace.export_trace(str(tmp_path / "t.json"))
        with open(path) as f:
            doc = json.loads(f.read())
        events = doc["traceEvents"]
        assert isinstance(events, list) and events
        for ev in events:
            for key in ("name", "ph", "pid", "tid"):
                assert key in ev
        complete = [e for e in events if e["ph"] == "X"]
        assert len(complete) == 1 and complete[0]["name"] == "a"
        assert complete[0]["args"]["pods"] == 3
        assert isinstance(complete[0]["ts"], int)
        assert complete[0]["dur"] >= 1
        instants = [e for e in events if e["ph"] == "i"]
        assert len(instants) == 1 and instants[0]["name"] == "mark"
        # thread-name metadata rides along for the Perfetto lane labels
        assert any(e["ph"] == "M" and e["name"] == "thread_name" for e in events)

    def test_noop_mode_no_allocation_no_events(self):
        obs_trace.disable()
        # one shared singleton — no per-span object when disabled
        assert obs_trace.span("a") is obs_trace.span("b", x=1)
        with obs_trace.span("c") as sp:
            sp.set(y=2)  # signature parity: attribute sets are no-ops too
        obs_trace.instant("d")
        assert obs_trace.events() == []
        assert not obs_trace.enabled()

    def test_span_summary_orders_by_total(self, tracer):
        import time

        for _ in range(3):
            with obs_trace.span("fast"):
                pass
        with obs_trace.span("slow"):
            time.sleep(0.02)
        rows = obs_trace.span_summary(top=10)
        assert rows[0]["name"] == "slow"
        fast = next(r for r in rows if r["name"] == "fast")
        assert fast["count"] == 3


class TestMetricsRegistry:
    def test_instrument_semantics_and_delta(self):
        reg = MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(4)
        reg.gauge("g").set({"x": 1})
        reg.histogram("h").observe(2.0)
        reg.histogram("h").observe(6.0)
        snap = reg.snapshot()
        assert snap["c"] == 5
        assert snap["g"] == {"x": 1}
        assert snap["h"] == {"count": 2, "total": 8.0, "min": 2.0, "max": 6.0}
        before = snap
        reg.counter("c").inc(2)
        reg.gauge("g").set(7)
        reg.histogram("h").observe(1.0)
        delta = reg.delta_since(before)
        assert delta["c"] == 2  # counters are flows
        assert delta["g"] == 7  # gauges are levels
        assert delta["h"]["count"] == 1 and delta["h"]["total"] == 1.0

    def test_type_conflict_refuses(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_snapshot_never_aliases_live_dicts(self):
        reg = MetricsRegistry()
        reg.gauge("g").set({"a": 1})
        snap = reg.snapshot()
        snap["g"]["a"] = 99
        assert reg.snapshot()["g"] == {"a": 1}


@pytest.fixture(scope="module")
def problem():
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    cluster = synth_cluster(16, seed=71, zones=4, taint_frac=0.1)
    apps = synth_apps(
        48, seed=72, zones=4, pods_per_deployment=12,
        anti_affinity_frac=0.2, spread_frac=0.3,
    )
    pods = []
    for app in apps:
        pods.extend(get_valid_pods_exclude_daemonset(app.resource))
    return cluster, pods


class TestRegistryCounters:
    """The engine counter families read directly off the registry —
    across the wavefront and compact-carry engine A/Bs (the GSPMD shard
    A/B rides the same counters through tests/test_telemetry.py's
    sharded-plan cases).  The one-release legacy alias views
    (`fetch_counts` et al.) are gone; their absence is pinned so they
    cannot silently resurrect."""

    def test_legacy_alias_views_removed(self):
        import simtpu.durable.backoff as backoff_mod
        import simtpu.engine.scan as scan_mod
        import simtpu.engine.state as state_mod

        for mod, name in (
            (scan_mod, "fetch_counts"),
            (scan_mod, "trace_counts"),
            (scan_mod, "wave_counts"),
            (backoff_mod, "backoff_counts"),
            (state_mod, "state_gauge"),
        ):
            assert not hasattr(mod, name), (
                f"{mod.__name__}.{name} was removed in ISSUE 13 — read "
                "the obs registry instead"
            )

    @pytest.mark.parametrize("speculate", [False, True])
    @pytest.mark.parametrize("compact", [False, True])
    def test_registry_counters_after_placement(
        self, problem, speculate, compact
    ):
        from simtpu.core.tensorize import Tensorizer
        from simtpu.engine.scan import Engine
        from simtpu.obs.metrics import family

        cluster, pods = problem
        before = REGISTRY.snapshot()
        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        eng = Engine(tz)
        eng.speculate = speculate
        eng.compact = compact
        nodes, _, _ = eng.place(tz.add_pods(pods))

        from simtpu.durable.backoff import BACKOFF_KEYS
        from simtpu.engine.scan import FETCH_KEYS, WAVE_KEYS

        fetch = family("fetch", FETCH_KEYS)
        assert fetch["get"] > before.get("fetch.get", 0)
        assert fetch["bytes"] - before.get("fetch.bytes", 0) >= nodes.size * 4

        waves = family("wavefront", WAVE_KEYS)
        if speculate:
            assert waves["pods"] > before.get("wavefront.pods", 0)
        # accept/rollback accounting is complete: every drafted pod is
        # either accepted or rolled back
        assert waves["accepted"] + waves["rollback_pods"] == waves["pods"]

        gauge_bytes = REGISTRY.value("state.carried_bytes")
        planes = REGISTRY.value("state.planes", default={})
        assert gauge_bytes == sum(planes.values())

        back = family("backoff", BACKOFF_KEYS)
        assert back["events"] >= 0 and back["splits"] >= 2 * back["events"] - 1

    def test_compact_ab_same_placements_different_gauge(self, problem):
        from simtpu.core.tensorize import Tensorizer
        from simtpu.engine.rounds import RoundsEngine

        cluster, pods = problem
        results = {}
        for compact in (True, False):
            tz = Tensorizer(
                cluster.nodes, storage_classes=cluster.storage_classes
            )
            eng = RoundsEngine(tz)
            eng.compact = compact
            nodes, _, _ = eng.place(tz.add_pods(pods))
            results[compact] = (
                np.asarray(nodes),
                bool(REGISTRY.value("state.compact", default=False)),
            )
        assert np.array_equal(results[True][0], results[False][0])
        assert results[True][1] is True
        assert results[False][1] is False


class TestFlightRecorder:
    def test_bundle_document_shape(self, tmp_path, monkeypatch, tracer):
        monkeypatch.setenv("SIMTPU_FLIGHT_DIR", str(tmp_path))
        from simtpu.obs.flight import dump_flight

        with obs_trace.span("pre-crash"):
            pass
        path = dump_flight("test reason", 3, engine={"search": "binary"})
        assert path and os.path.isfile(path)
        doc = json.load(open(path))
        assert doc["format"] == "simtpu-flight-v1"
        assert doc["reason"] == "test reason"
        assert doc["exit_code"] == 3
        assert doc["schema_version"] == SCHEMA_VERSION
        assert doc["engine"] == {"search": "binary"}
        assert isinstance(doc["metrics"], dict)
        names = [
            e["name"] for e in doc["spans"]["traceEvents"] if e["ph"] == "X"
        ]
        assert "pre-crash" in names

    def test_flight_disabled_by_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("SIMTPU_FLIGHT_DIR", str(tmp_path))
        monkeypatch.setenv("SIMTPU_FLIGHT", "0")
        from simtpu.obs.flight import dump_flight

        assert dump_flight("r", 4) is None
        assert not glob.glob(str(tmp_path / "simtpu-flight-*.json"))

    def test_flight_lands_next_to_checkpoint_dir(
        self, tmp_path, monkeypatch
    ):
        monkeypatch.delenv("SIMTPU_FLIGHT_DIR", raising=False)
        from simtpu.obs.flight import dump_flight

        ck = tmp_path / "nested" / "ck"
        ck.mkdir(parents=True)
        path = dump_flight("r", 3, checkpoint=str(ck))
        assert os.path.dirname(path) == str(tmp_path / "nested")

    def test_cli_exit_3_dumps_bundle(self, tmp_path, monkeypatch, capsys):
        """--deadline 0 = injected partial exit (3): the flight bundle
        lands next to the checkpoint dir with the partial reason."""
        from simtpu.cli import EXIT_PARTIAL, main

        monkeypatch.setenv("SIMTPU_FLIGHT_DIR", str(tmp_path / "fl"))
        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--deadline", "0", "--checkpoint", str(tmp_path / "ck"),
        ])
        capsys.readouterr()
        assert rc == EXIT_PARTIAL
        (path,) = glob.glob(str(tmp_path / "fl" / "simtpu-flight-*.json"))
        doc = json.load(open(path))
        assert doc["exit_code"] == EXIT_PARTIAL
        assert "partial" in doc["reason"]
        assert isinstance(doc["metrics"], dict)

    @pytest.mark.slow
    def test_cli_exit_4_dumps_bundle(self, tmp_path, monkeypatch, capsys):
        """SIMTPU_AUDIT_INJECT=1 = injected audit divergence (exit 4):
        the bundle carries the engine block and the buffered spans."""
        from simtpu.cli import EXIT_AUDIT, main

        monkeypatch.setenv("SIMTPU_FLIGHT_DIR", str(tmp_path / "fl"))
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        obs_trace.enable()
        try:
            rc = main([
                "apply", "-f", "examples/simtpu-config.yaml", "--json",
            ])
        finally:
            obs_trace.disable()
        capsys.readouterr()
        assert rc == EXIT_AUDIT
        (path,) = glob.glob(str(tmp_path / "fl" / "simtpu-flight-*.json"))
        doc = json.load(open(path))
        assert doc["exit_code"] == EXIT_AUDIT
        assert "audit" in doc["reason"]
        assert doc["engine"]["audit"]["fallback"] is True
        assert [
            e for e in doc["spans"]["traceEvents"] if e["ph"] == "X"
        ], "armed tracer's spans must ride the bundle"


class TestCLIObs:
    def test_version_json_schema_stamp(self, capsys):
        from simtpu import __version__
        from simtpu.cli import main

        assert main(["version", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc == {
            "version": __version__, "schema_version": SCHEMA_VERSION,
        }

    def test_apply_trace_json_reconciles(self, tmp_path, capsys):
        """The ISSUE-8 acceptance run: one `apply --trace t.json --json`
        on the examples yields (a) a Perfetto-valid trace whose
        ingest/plan span wall-clock reconciles with the --json phase
        timings within 5%, and (b) a metrics block whose values the
        legacy engine-block families alias bit-equally."""
        from simtpu.cli import main

        tpath = str(tmp_path / "t.json")
        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--trace", tpath,
        ])
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out)
        assert doc["schema_version"] == SCHEMA_VERSION
        m, e = doc["metrics"], doc["engine"]

        # (b) every legacy counter family under the unified schema,
        # values bit-equal to the legacy engine-block fields
        assert e["fetch"] == {"get": m["fetch.get"], "bytes": m["fetch.bytes"]}
        assert e["backoff"] == {
            "events": m["backoff.events"],
            "splits": m["backoff.splits"],
            "chunk_min": m["backoff.chunk_min"],
        }
        assert e["wavefront"] == {
            k: m[f"wavefront.{k}"] for k in e["wavefront"]
        }
        assert e["compact"] == m["state.compact"]
        assert e["state_bytes"] == {
            "carried_bytes": m["state.carried_bytes"],
            "dense_bytes": m["state.dense_bytes"],
            "planes": m["state.planes"],
        }
        for k in ("ok", "checked", "violations", "wall_s", "mode"):
            assert m[f"audit.{k}"] == e["audit"][k]
        assert any(k.startswith("compile.") for k in m)

        # (a) Perfetto-valid trace whose phase spans reconcile with the
        # --json timings within 5%
        trace = json.load(open(tpath))
        complete = [x for x in trace["traceEvents"] if x["ph"] == "X"]
        assert complete
        sums = {}
        for x in complete:
            sums[x["name"]] = sums.get(x["name"], 0.0) + x["dur"] / 1e6
        for phase in ("ingest", "plan"):
            span_s, json_s = sums[phase], doc["timings"][phase]
            assert span_s == pytest.approx(json_s, rel=0.05), phase
        # the engine layers all reported in: dispatch chunks, audit
        names = set(sums)
        assert {"tensorize", "expand", "audit.pass"} <= names
        assert "scan.chunk" in names or "rounds.chunk" in names

    def test_simulate_trace_kwarg_exports(self, tmp_path, problem):
        from simtpu.api import simulate
        from simtpu.core.objects import ResourceTypes

        cluster, pods = problem
        trial = ResourceTypes(**{k: list(v) for k, v in vars(cluster).items()})
        trial.pods = list(pods[:24])
        tpath = str(tmp_path / "sim.json")
        # an earlier CLI --trace run leaves the process tracer armed (by
        # design — flight-recorder visibility); this test is about the
        # own-tracer path, so start from the disabled state
        obs_trace.disable()
        simulate(trial, trace=tpath)
        assert not obs_trace.enabled(), "simulate() must disarm its own tracer"
        doc = json.load(open(tpath))
        names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
        assert {"tensorize", "expand", "schedule.cluster"} <= names
