"""Direct coverage for engine/state.py's batch apply/undo of placement
deltas: `apply_placement_deltas` with w = -1 then w = +1 over the same
entries must restore the carry BIT-identically — every plane, including
the topology count planes and the compacted per-key interpod histograms.
(Previously exercised only indirectly through the wavefront tests; the
fault subsystem's scenario drains ride the same arithmetic, ISSUE 4.)
"""

import numpy as np
import pytest

from simtpu.engine.scan import statics_from
from simtpu.engine.state import apply_placement_deltas, pack_delta_entries
from simtpu.faults import place_cluster
from simtpu.synth import synth_apps, synth_cluster


@pytest.fixture(scope="module")
def placed():
    cluster = synth_cluster(
        9, seed=41, zones=3, taint_frac=0.1, gpu_frac=0.3, storage_frac=0.4
    )
    apps = synth_apps(
        48,
        seed=42,
        zones=3,
        pods_per_deployment=8,
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.4,
        anti_affinity_hard_frac=0.5,
        spread_frac=0.3,
        spread_hard_frac=0.5,
        gpu_frac=0.2,
        storage_frac=0.2,
        affinity_frac=0.1,
    )
    return place_cluster(cluster, apps)


def _entries_of(eng, indices):
    """Saved-record tuples in Engine.remove_placements' layout, without
    touching the log."""
    ext = eng.ext_log
    return [
        (
            eng.placed_group[i],
            eng.placed_node[i],
            eng.placed_req[i],
            ext["node"][i],
            ext["vg_alloc"][i],
            ext["sdev_take"][i],
            ext["gpu_shares"][i],
            ext["gpu_mem"][i],
        )
        for i in indices
    ]


class TestApplyUndoRoundTrip:
    def test_apply_then_undo_bit_identical(self, placed):
        """evict (w=-1) then restore (w=+1) over the same entries returns
        every SchedState field bit-identically."""
        import jax
        import jax.numpy as jnp

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        r = tensors.alloc.shape[1]
        ext = tensors.ext
        base = eng.carried_state()  # dense view of the (compact) carry
        assert base is not None and not eng._state_dirty
        # a mixed batch: every 3rd entry, which spans groups/nodes/extended
        indices = list(range(0, len(eng.placed_node), 3))
        assert len(indices) >= 8
        entries = _entries_of(eng, indices)

        def packed(sign):
            return pack_delta_entries(
                entries,
                r,
                ext.vg_cap.shape[1],
                ext.sdev_cap.shape[1],
                ext.gpu_dev_total.shape[1],
                sign,
            )

        copy = jax.tree_util.tree_map(jnp.copy, base)
        evicted = apply_placement_deltas(statics, copy, packed(-1.0))
        # the eviction must actually change the state
        assert not np.array_equal(
            np.asarray(evicted.free), np.asarray(base.free)
        )
        restored = apply_placement_deltas(statics, evicted, packed(+1.0))
        for name in base._fields:
            got = np.asarray(getattr(restored, name))
            want = np.asarray(getattr(base, name))
            assert got.dtype == want.dtype, name
            assert np.array_equal(got, want), (
                f"state field {name} not bit-identical after apply+undo "
                f"(max delta {np.max(np.abs(got.astype(np.float64) - want.astype(np.float64)))})"
            )

    def test_count_planes_and_histograms_change_under_apply(self, placed):
        """The eviction delta visibly updates the topology count planes and
        the compacted interpod ('own') histograms — the round-trip above
        is not vacuous for them."""
        import jax
        import jax.numpy as jnp

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        r = tensors.alloc.shape[1]
        ext = tensors.ext
        base = eng.carried_state()
        entries = _entries_of(eng, range(len(eng.placed_node)))
        packed = pack_delta_entries(
            entries,
            r,
            ext.vg_cap.shape[1],
            ext.sdev_cap.shape[1],
            ext.gpu_dev_total.shape[1],
            -1.0,
        )
        copy = jax.tree_util.tree_map(jnp.copy, base)
        evicted = apply_placement_deltas(statics, copy, packed)
        # evicting the WHOLE log zeroes every count plane
        for name in ("cnt_match", "cnt_total", "cnt_own_anti", "cnt_own_aff"):
            before = np.asarray(getattr(base, name))
            after = np.asarray(getattr(evicted, name))
            if before.size and before.any():
                assert not np.array_equal(after, before), name
            assert np.allclose(after, 0.0, atol=1e-5), (
                f"{name} not zeroed by a full-log eviction"
            )

    def test_padding_rows_are_noops(self, placed):
        """w = 0 padding rows leave the state bit-identical (pack_delta_
        entries pads to pow2; the fault sweep pads every scenario)."""
        import jax
        import jax.numpy as jnp

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        r = tensors.alloc.shape[1]
        ext = tensors.ext
        base = eng.carried_state()
        packed = pack_delta_entries(
            [],
            r,
            ext.vg_cap.shape[1],
            ext.sdev_cap.shape[1],
            ext.gpu_dev_total.shape[1],
            -1.0,
            pad_to=16,
        )
        copy = jax.tree_util.tree_map(jnp.copy, base)
        out = apply_placement_deltas(statics, copy, packed)
        for name in base._fields:
            assert np.array_equal(
                np.asarray(getattr(out, name)), np.asarray(getattr(base, name))
            ), name


class TestInterleavedApplyUndo:
    """Out-of-stack-order apply/undo (ISSUE 15): departures in a timeline
    undo event i after later events j > i applied — the delta-advanced
    carry must stay bit-identical to a state REBUILT from the equivalent
    log, for the dense carry and the compact (domain-tabular) carry."""

    def _packed(self, placed, entries, sign):
        tensors = placed.tensors
        ext = tensors.ext
        return pack_delta_entries(
            entries,
            tensors.alloc.shape[1],
            ext.vg_cap.shape[1],
            ext.sdev_cap.shape[1],
            ext.gpu_dev_total.shape[1],
            sign,
        )

    def _rebuilt(self, placed, keep_mask):
        """build_state over the placement log restricted to `keep_mask`
        entries — the from-scratch oracle of any delta sequence whose net
        effect removes the masked-out entries."""
        import numpy as np

        from simtpu.engine.state import build_state

        eng = placed.engine
        tensors = placed.tensors
        keep = np.flatnonzero(keep_mask)
        r = tensors.alloc.shape[1]
        req = eng.log_req_matrix(r)[keep]
        ext = {
            k: [eng.ext_log[k][int(i)] for i in keep] for k in eng.ext_log
        }
        return build_state(
            tensors,
            np.asarray(eng.placed_group, np.int32)[keep],
            np.asarray(eng.placed_node, np.int32)[keep],
            req,
            ext,
        )

    def _assert_states_equal(self, got, want, label):
        import numpy as np

        for name in want._fields:
            g = np.asarray(getattr(got, name))
            w = np.asarray(getattr(want, name))
            assert g.dtype == w.dtype, (label, name)
            assert np.array_equal(g, w), (
                f"{label}: plane {name} not bit-identical "
                f"(max delta "
                f"{np.max(np.abs(g.astype(np.float64) - w.astype(np.float64)))})"
            )

    def test_undo_i_after_apply_j_matches_rebuild(self, placed):
        """apply -A, apply -B (disjoint, B after A), undo +A — the state
        must equal a rebuild from the log minus B, dense AND compact."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from simtpu.engine.state import (
            compact_spec,
            compress_state,
            node_dom_small_for,
        )

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        m = len(eng.placed_node)
        a_idx = list(range(0, m, 4))          # "event i"
        b_idx = list(range(1, m, 4))          # "event j > i", disjoint
        assert len(a_idx) >= 4 and len(b_idx) >= 4
        base = eng.carried_state()
        state = jax.tree_util.tree_map(jnp.copy, base)
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, a_idx), -1.0)
        )
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, b_idx), -1.0)
        )
        # out-of-stack-order undo: A comes back while B stays evicted
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, a_idx), +1.0)
        )
        keep = np.ones(m, bool)
        keep[b_idx] = False
        want = self._rebuilt(placed, keep)
        self._assert_states_equal(state, want, "dense carry")
        spec = compact_spec(tensors)
        if spec.enabled:
            nds = node_dom_small_for(tensors, tensors.alloc.shape[0])
            got_c = compress_state(spec.dev, state)
            want_c = compress_state(spec.dev, want)
            self._assert_states_equal(got_c, want_c, "compact carry")
            # and the compact round trip loses nothing: the delta-advanced
            # state is still in the domain-constant class compression
            # assumes (what the timeline's carried compact state rides on)
            from simtpu.engine.state import expand_state

            back = expand_state(spec.dev, got_c, nds)
            self._assert_states_equal(back, want, "compact round trip")

    def test_full_out_of_order_round_trip(self, placed):
        """apply -A, apply -B, undo +A, undo +B returns to base
        bit-identically (the stack-order test's interleaved sibling)."""
        import jax
        import jax.numpy as jnp

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        m = len(eng.placed_node)
        a_idx = list(range(0, m, 3))
        b_idx = list(range(1, m, 3))
        base = eng.carried_state()
        state = jax.tree_util.tree_map(jnp.copy, base)
        for idx, sign in ((a_idx, -1.0), (b_idx, -1.0),
                          (a_idx, +1.0), (b_idx, +1.0)):
            state = apply_placement_deltas(
                statics, state, self._packed(placed, _entries_of(eng, idx), sign)
            )
        self._assert_states_equal(state, base, "out-of-order round trip")

    def test_interleaved_apply_after_undo(self, placed):
        """undo (depart) then APPLY the same entries again (a re-admission
        landing on identical nodes) interleaved with another departure —
        the timeline's node-down/requeue shape — equals the rebuild."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        m = len(eng.placed_node)
        a_idx = list(range(0, m, 5))
        b_idx = list(range(2, m, 5))
        base = eng.carried_state()
        state = jax.tree_util.tree_map(jnp.copy, base)
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, a_idx), -1.0)
        )
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, b_idx), -1.0)
        )
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, a_idx), +1.0)
        )
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, b_idx), +1.0)
        )
        state = apply_placement_deltas(
            statics, state, self._packed(placed, _entries_of(eng, b_idx), -1.0)
        )
        keep = np.ones(m, bool)
        keep[b_idx] = False
        want = self._rebuilt(placed, keep)
        self._assert_states_equal(state, want, "re-admission interleave")


def _pack(placed, entries, sign):
    tensors = placed.tensors
    ext = tensors.ext
    return pack_delta_entries(
        entries,
        tensors.alloc.shape[1],
        ext.vg_cap.shape[1],
        ext.sdev_cap.shape[1],
        ext.gpu_dev_total.shape[1],
        sign,
    )


def _rebuilt_from_log(placed, keep_mask):
    import numpy as np

    from simtpu.engine.state import build_state

    eng = placed.engine
    tensors = placed.tensors
    keep = np.flatnonzero(keep_mask)
    r = tensors.alloc.shape[1]
    req = eng.log_req_matrix(r)[keep]
    ext = {k: [eng.ext_log[k][int(i)] for i in keep] for k in eng.ext_log}
    return build_state(
        tensors,
        np.asarray(eng.placed_group, np.int32)[keep],
        np.asarray(eng.placed_node, np.int32)[keep],
        req,
        ext,
    )


@pytest.fixture(scope="module")
def placed_wide():
    """> DOM_SMALL (64) nodes with hostname-keyed anti-affinity terms: the
    hostname topology key has one value PER NODE, so its rows compress as
    kind-2 DENSE rows — the fixture that exercises compact_delta_step's
    dense-row branch (the 9-node fixture above is all-tabular)."""
    cluster = synth_cluster(
        80, seed=61, zones=4, taint_frac=0.0, gpu_frac=0.2, storage_frac=0.3
    )
    apps = synth_apps(
        40,
        seed=62,
        zones=4,
        pods_per_deployment=6,
        selector_frac=0.1,
        anti_affinity_frac=0.6,
        anti_affinity_hard_frac=0.4,
        spread_frac=0.4,
        spread_hard_frac=0.5,
        gpu_frac=0.1,
        storage_frac=0.2,
    )
    return place_cluster(cluster, apps)


class TestDirectCompactDelta:
    """ISSUE 16 tentpole: packed placement deltas applied DIRECTLY to the
    compact carry (per-domain scatter into the [Rt, D] tabular histograms,
    plain row updates for the dense rows) must be bit-identical to the
    expand -> apply_placement_deltas -> recompress round trip AND to a
    from-scratch build_state rebuild.  Preemption evictions/restores,
    timeline departures and fault drains all replay this arithmetic."""

    def _assert_equal(self, got, want, label):
        for name in want._fields:
            g = np.asarray(getattr(got, name))
            w = np.asarray(getattr(want, name))
            assert g.dtype == w.dtype, (label, name)
            assert np.array_equal(g, w), (
                f"{label}: compact plane {name} not bit-identical "
                f"(max delta "
                f"{np.max(np.abs(g.astype(np.float64) - w.astype(np.float64)))})"
            )

    def _run_interleave(self, placed, expect_dense):
        """-A, -B, +A out of stack order, then +B, -B re-admission churn:
        direct compact apply vs the dense round-trip oracle at every step."""
        import jax
        import jax.numpy as jnp

        from simtpu.engine.state import (
            apply_placement_deltas_compact,
            compact_delta_spec,
            compact_spec,
            compress_state,
            expand_state,
            node_dom_for,
            node_dom_small_for,
        )

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        spec = compact_spec(tensors)
        assert spec.enabled, "fixture must be compact-eligible"
        n = tensors.alloc.shape[0]
        ndom = node_dom_for(tensors, n)
        nds = node_dom_small_for(tensors, n)
        dspec = compact_delta_spec(tensors)
        base = eng.carried_state()
        direct = compress_state(spec.dev, base)
        if expect_dense:
            assert int(direct.cm_dense.shape[0]) > 0, (
                "wide fixture grew no dense compact rows — the dense "
                "branch of compact_delta_step is not exercised"
            )
        else:
            assert int(direct.cm_dense.shape[0]) == 0
        dense = jax.tree_util.tree_map(jnp.copy, base)
        m = len(eng.placed_node)
        a_idx = list(range(0, m, 4))
        b_idx = list(range(1, m, 4))
        assert len(a_idx) >= 4 and len(b_idx) >= 4
        seq = (
            (a_idx, -1.0),
            (b_idx, -1.0),
            (a_idx, +1.0),  # out-of-stack-order undo
            (b_idx, +1.0),  # re-admission on identical nodes
            (b_idx, -1.0),
        )
        for step, (idx, sign) in enumerate(seq):
            packed = _pack(placed, _entries_of(eng, idx), sign)
            direct = apply_placement_deltas_compact(
                statics, dspec, ndom, nds, direct, packed
            )
            dense = apply_placement_deltas(statics, dense, packed)
            self._assert_equal(
                direct, compress_state(spec.dev, dense), f"step {step}"
            )
        keep = np.ones(m, bool)
        keep[b_idx] = False
        want = _rebuilt_from_log(placed, keep)
        self._assert_equal(
            direct, compress_state(spec.dev, want), "vs build_state rebuild"
        )
        # the direct-advanced compact state expands to the exact dense
        # rebuild: no information was lost to the scatter shortcut
        back = expand_state(spec.dev, direct, nds)
        self._assert_equal(back, want, "expansion of direct carry")

    def test_direct_interleave_tabular(self, placed):
        """9-node fixture: every compact row is tabular ([Rt, D] scatter)."""
        self._run_interleave(placed, expect_dense=False)

    def test_direct_interleave_dense_rows(self, placed_wide):
        """80-node fixture: hostname-keyed terms ride the dense-row branch."""
        self._run_interleave(placed_wide, expect_dense=True)

    def test_direct_is_non_donating(self, placed):
        """plan/incremental.py shares one compact snapshot across probes:
        the direct apply must NOT donate/overwrite its input buffers."""
        import jax
        import jax.numpy as jnp

        from simtpu.engine.state import (
            apply_placement_deltas_compact,
            compact_delta_spec,
            compact_spec,
            compress_state,
            node_dom_for,
            node_dom_small_for,
        )

        eng = placed.engine
        tensors = placed.tensors
        statics = statics_from(tensors, eng.sched_config)
        spec = compact_spec(tensors)
        n = tensors.alloc.shape[0]
        cstate = compress_state(spec.dev, eng.carried_state())
        before = jax.tree_util.tree_map(
            lambda a: np.asarray(a).copy(), cstate
        )
        packed = _pack(
            placed, _entries_of(eng, range(0, len(eng.placed_node), 3)), -1.0
        )
        out = apply_placement_deltas_compact(
            statics,
            compact_delta_spec(tensors),
            node_dom_for(tensors, n),
            node_dom_small_for(tensors, n),
            cstate,
            packed,
        )
        assert not np.array_equal(np.asarray(out.free), before.free)
        self._assert_equal(cstate, before, "input snapshot after apply")

    def test_engine_preemption_path_skips_expand_recompress(
        self, placed, monkeypatch
    ):
        """Engine.remove_placements/restore_placements on a compact carry:
        the direct path fires (state.delta_direct +2), expand/recompress
        stay untouched, and the compact carry returns bit-identically —
        then the SIMTPU_DELTA_DIRECT=0 round trip reproduces the same
        carry, pinning the A/B bit-identity at the engine level."""
        import jax

        from simtpu.engine.state import CompactState
        from simtpu.obs.metrics import REGISTRY

        eng = placed.engine
        base = eng.last_state
        if not isinstance(base, CompactState):
            pytest.skip("engine carry not compact under this config")
        base_np = jax.tree_util.tree_map(lambda a: np.asarray(a).copy(), base)
        idx = list(range(0, len(eng.placed_node), 3))

        def churn():
            saved = eng.remove_placements(idx)
            mid = jax.tree_util.tree_map(
                lambda a: np.asarray(a).copy(), eng.last_state
            )
            eng.restore_placements(saved)
            return mid

        monkeypatch.setenv("SIMTPU_DELTA_DIRECT", "1")
        snap0 = REGISTRY.snapshot()
        mid_direct = churn()
        snap1 = REGISTRY.snapshot()
        assert isinstance(eng.last_state, CompactState)
        assert snap1.get("state.delta_direct", 0) - snap0.get(
            "state.delta_direct", 0
        ) == 2
        for name in ("state.expand", "state.compress"):
            assert snap1.get(name, 0) == snap0.get(name, 0), (
                f"{name} bumped on the direct preemption hot path"
            )
        self._assert_equal(eng.last_state, base, "direct carry round trip")

        monkeypatch.setenv("SIMTPU_DELTA_DIRECT", "0")
        snap2 = REGISTRY.snapshot()
        mid_ab = churn()
        snap3 = REGISTRY.snapshot()
        assert snap3.get("state.delta_direct", 0) == snap2.get(
            "state.delta_direct", 0
        )
        assert snap3.get("state.compress", 0) - snap2.get(
            "state.compress", 0
        ) == 2
        self._assert_equal(eng.last_state, base, "round-trip carry")
        for name in base._fields:
            assert np.array_equal(
                getattr(mid_direct, name), getattr(mid_ab, name)
            ), f"mid-eviction carry differs between paths: {name}"
        # the log and carry are back to the fixture's original state for
        # the tests that share this module-scoped fixture
        self._assert_equal(eng.last_state, base_np, "fixture restored")
