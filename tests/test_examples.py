"""Golden end-to-end runs over the examples/ corpus through the real CLI —
the analog of the reference's `example/` acceptance fixtures (SURVEY.md §4).
Each config must plan successfully and print the report tables.
"""

from __future__ import annotations

import os

import pytest

from simtpu.cli import main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chdir_repo(monkeypatch):
    # config paths are relative to the repository root
    monkeypatch.chdir(REPO)


def test_demo_config_plans_successfully(capsys):
    rc = main(["apply", "-f", "examples/simtpu-config.yaml"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Success!" in out
    # report tables show the demo nodes
    for node in ("ctrl-0", "worker-a-0", "worker-a-1", "worker-b-0"):
        assert node in out
    # the chart-mode app rendered and scheduled (3 queue-broker pods)
    assert out.count("queue-broker") >= 3


def test_gpushare_config_plans_successfully(capsys):
    rc = main(
        ["apply", "-f", "examples/simtpu-gpushare-config.yaml", "-e", "gpu"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "Success!" in out
    assert "gpu-node-0" in out
    # placement-count + device-assignment assertions (VERDICT r1 task 9):
    # all 8 "infer" replicas appear in the Pod -> Node Map with a concrete
    # GPU device index (the gpu-index annotation feeds the GPU IDX column)
    import re

    idx_rows = re.findall(
        r"\|\s*infer-\S+\s*\|[^|]+\|[^|]+\|[^|]+\|[^|]+\|\s*(\S+)\s*\|", out
    )
    assert len(idx_rows) == 8, out
    assert all(idx.isdigit() for idx in idx_rows), idx_rows


def test_storage_config_plans_successfully(capsys):
    rc = main(
        ["apply", "-f", "examples/simtpu-storage-config.yaml", "-e", "open-local"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "Success!" in out
    # both "db" StatefulSet replicas land, and their LVM claims show in the
    # storage report: pool-0 requested = 2 x 20Gi on a 200Gi VG
    assert out.count("default/db-") == 2
    assert "40Gi(20%)" in out


def test_gen_doc(tmp_path, capsys):
    rc = main(["gen-doc", "--output", str(tmp_path)])
    assert rc == 0
    doc = (tmp_path / "simtpu.md").read_text()
    assert "apply" in doc and "gen-doc" in doc


def test_version(capsys):
    assert main(["version"]) == 0
    assert "simtpu version" in capsys.readouterr().out


def test_apply_engine_flags_plumb_through(capsys, monkeypatch):
    """The tri-state engine flags must reach the Applier intact: absent →
    None (auto), --bulk → True, --no-bulk → False, --search passes its
    choice, --shard/--no-shard likewise — and the auto path stays silent
    at conformance scale.  Only the first (default) case runs the plan
    (as --json, pinning the machine-readable engine record ADVICE r5
    asked for); the flag-override cases stop at the spy so the fast tier
    doesn't pay several full applies."""
    import json as _json

    import simtpu.plan.capacity as cap

    seen = {}
    orig = cap._resolve_engines
    full = True

    def spy(opts, cluster, apps):
        seen["search"], seen["bulk"], seen["shard"] = (
            opts.search, opts.bulk, opts.shard,
        )
        seen["precompile"] = opts.precompile
        if not full:
            # ValueError is cmd_apply's clean-exit path (rc=1)
            raise ValueError("flag-plumb probe stop")
        return orig(opts, cluster, apps)

    monkeypatch.setattr(cap, "_resolve_engines", spy)

    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--json"])
    assert rc == 0
    assert (seen["search"], seen["bulk"], seen["shard"]) == (None, None, None)
    assert seen["precompile"] is None  # tri-state: absent = auto (ON)
    captured = capsys.readouterr()
    assert "auto-selected" not in captured.err
    # stdout must be EXACTLY the JSON document (progress goes to stderr),
    # so `simtpu apply --json | jq .` works
    doc = _json.loads(captured.out.strip())
    assert doc["success"] is True
    # the engine record rides the OUTPUT (not stderr): scripted consumers
    # can detect the non-reference-exact fast path from here
    assert doc["engine"]["search"] in ("binary", "linear", "incremental")
    assert {"auto_search", "auto_bulk", "shards"} <= set(doc["engine"])
    assert doc["engine"]["auto_search"] is True
    # the precompile resolution is recorded in the machine-readable engine
    # block; auto is OFF here because the test env pins the CPU backend
    # (accelerator backends auto-enable it)
    assert doc["engine"]["precompile"] is False

    full = False
    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--no-bulk", "--search", "linear"])
    assert rc == 1
    assert (seen["search"], seen["bulk"]) == ("linear", False)

    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--bulk"])
    assert rc == 1
    assert seen["bulk"] is True

    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--no-precompile"])
    assert rc == 1
    assert seen["precompile"] is False

    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--precompile"])
    assert rc == 1
    assert seen["precompile"] is True

    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--shard"])
    assert rc == 1
    assert seen["shard"] is True

    rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--no-shard"])
    assert rc == 1
    assert seen["shard"] is False
