"""Tests for the second wave of in-tree plugin kernels: NodePorts,
PodTopologySpread (filter + score), SelectorSpread, ImageLocality and
NodePreferAvoidPods — the remaining rows of the SURVEY.md §2.2 plugin
checklist (`vendor/.../algorithmprovider/registry.go:75-145`).
"""

from __future__ import annotations

import json

from simtpu.api import simulate
from simtpu.core.objects import ResourceTypes

from .fixtures import (
    make_fake_node,
    make_fake_pod,
    make_fake_replica_set,
    with_node_labels,
    with_pod_labels,
)


def _cluster(nodes, **kw):
    return ResourceTypes(nodes=nodes, **kw)


def _placements(result):
    out = {}
    for status in result.node_status:
        for pod in status.pods:
            out[pod["metadata"]["name"]] = status.node["metadata"]["name"]
    return out


def with_host_port(port, protocol="TCP"):
    def opt(pod):
        c = pod["spec"]["containers"][0]
        c.setdefault("ports", []).append(
            {"containerPort": port, "hostPort": port, "protocol": protocol}
        )

    return opt


def with_spread_constraint(max_skew, key, when, match_labels):
    def opt(pod):
        pod["spec"].setdefault("topologySpreadConstraints", []).append(
            {
                "maxSkew": max_skew,
                "topologyKey": key,
                "whenUnsatisfiable": when,
                "labelSelector": {"matchLabels": match_labels},
            }
        )

    return opt


class TestNodePorts:
    def test_conflicting_host_ports_spread_then_fail(self):
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(2)]
        pods = [
            make_fake_pod(f"p{i}", "default", "1", "1Gi", with_host_port(8080))
            for i in range(3)
        ]
        result = simulate(_cluster(nodes, pods=pods))
        # two pods land on distinct nodes, the third has no port-free node
        placed = _placements(result)
        assert len(placed) == 2
        assert len(set(placed.values())) == 2
        assert len(result.unscheduled_pods) == 1
        assert "ports" in result.unscheduled_pods[0].reason

    def test_different_ports_coexist(self):
        nodes = [make_fake_node("n0", "32", "64Gi")]
        pods = [
            make_fake_pod("p0", "default", "1", "1Gi", with_host_port(8080)),
            make_fake_pod("p1", "default", "1", "1Gi", with_host_port(8081)),
            # same port number but UDP does not conflict with TCP
            make_fake_pod("p2", "default", "1", "1Gi", with_host_port(8080, "UDP")),
        ]
        result = simulate(_cluster(nodes, pods=pods))
        assert not result.unscheduled_pods

    def test_no_host_port_unaffected(self):
        nodes = [make_fake_node("n0", "32", "64Gi")]
        pods = [make_fake_pod(f"p{i}", "default", "1", "1Gi") for i in range(5)]
        result = simulate(_cluster(nodes, pods=pods))
        assert not result.unscheduled_pods


class TestPodTopologySpread:
    ZONE = "topology.kubernetes.io/zone"

    def _zoned_nodes(self, per_zone=2, zones=("a", "b")):
        nodes = []
        for z in zones:
            for i in range(per_zone):
                nodes.append(
                    make_fake_node(
                        f"n-{z}{i}",
                        "32",
                        "64Gi",
                        with_node_labels({self.ZONE: z, "kubernetes.io/hostname": f"n-{z}{i}"}),
                    )
                )
        return nodes

    def test_hard_constraint_balances_zones(self):
        nodes = self._zoned_nodes()
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_pod_labels({"app": "web"}),
                with_spread_constraint(1, self.ZONE, "DoNotSchedule", {"app": "web"}),
            )
            for i in range(4)
        ]
        result = simulate(_cluster(nodes, pods=pods))
        assert not result.unscheduled_pods
        zone_counts = {"a": 0, "b": 0}
        for status in result.node_status:
            z = status.node["metadata"]["labels"][self.ZONE]
            zone_counts[z] += len(status.pods)
        assert abs(zone_counts["a"] - zone_counts["b"]) <= 1

    def test_hard_constraint_fails_when_skew_unavoidable(self):
        # one zone has capacity for pods, the other zone's node is full
        nodes = self._zoned_nodes(per_zone=1)
        full = make_fake_pod("filler", "default", "31.5", "1Gi")
        full["spec"]["nodeName"] = "n-b0"
        spread = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_pod_labels({"app": "api"}),
                with_spread_constraint(1, self.ZONE, "DoNotSchedule", {"app": "api"}),
            )
            for i in range(3)
        ]
        result = simulate(_cluster(nodes, pods=[full] + spread))
        # p0 → zone a; p1 must go to zone b (skew) but b is full → fails;
        # p2 likewise: only one spread pod can ever place
        placed = [
            p
            for s in result.node_status
            for p in s.pods
            if p["metadata"]["name"].startswith("p")
        ]
        assert len(placed) == 1
        assert any(
            "topology spread" in u.reason for u in result.unscheduled_pods
        )

    def test_soft_constraint_spreads_without_blocking(self):
        nodes = self._zoned_nodes(per_zone=1)
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_pod_labels({"app": "soft"}),
                with_spread_constraint(1, self.ZONE, "ScheduleAnyway", {"app": "soft"}),
            )
            for i in range(4)
        ]
        result = simulate(_cluster(nodes, pods=pods))
        assert not result.unscheduled_pods
        counts = [len(s.pods) for s in result.node_status]
        assert max(counts) - min(counts) <= 1  # alternated a/b/a/b


class TestSelectorSpread:
    def test_rs_pods_spread_across_nodes(self):
        # identical nodes, no anti-affinity: SelectorSpread alone must spread
        # the replica set's pods instead of stacking them on one node
        nodes = [
            make_fake_node(
                f"n{i}",
                "32",
                "64Gi",
                with_node_labels({"kubernetes.io/hostname": f"n{i}"}),
            )
            for i in range(3)
        ]
        rs = make_fake_replica_set("web", "default", 3, "1", "1Gi")
        rs["spec"]["template"]["metadata"] = {"labels": {"app": "web"}}
        rs["spec"]["selector"] = {"matchLabels": {"app": "web"}}
        result = simulate(_cluster(nodes, replica_sets=[rs]))
        assert not result.unscheduled_pods
        counts = sorted(len(s.pods) for s in result.node_status)
        assert counts == [1, 1, 1]

    def test_service_pods_spread(self):
        nodes = [
            make_fake_node(
                f"n{i}",
                "32",
                "64Gi",
                with_node_labels({"kubernetes.io/hostname": f"n{i}"}),
            )
            for i in range(2)
        ]
        svc = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": "svc", "namespace": "default"},
            "spec": {"selector": {"app": "svc-app"}},
        }
        pods = [
            make_fake_pod(
                f"p{i}", "default", "1", "1Gi", with_pod_labels({"app": "svc-app"})
            )
            for i in range(2)
        ]
        result = simulate(_cluster(nodes, pods=pods, services=[svc]))
        assert not result.unscheduled_pods
        assert sorted(len(s.pods) for s in result.node_status) == [1, 1]


class TestImageLocality:
    def test_prefers_node_with_image(self):
        n0 = make_fake_node("n0", "32", "64Gi")
        n1 = make_fake_node("n1", "32", "64Gi")
        n1["status"]["images"] = [
            {"names": ["bigimage:v1"], "sizeBytes": 800 * 1024 * 1024}
        ]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["containers"][0]["image"] = "bigimage:v1"
        result = simulate(_cluster([n0, n1], pods=[pod]))
        assert _placements(result)["p0"] == "n1"

    def test_small_image_below_threshold_ignored(self):
        n0 = make_fake_node("n0", "32", "64Gi")
        n1 = make_fake_node("n1", "32", "64Gi")
        n1["status"]["images"] = [
            {"names": ["tiny:v1"], "sizeBytes": 1 * 1024 * 1024}
        ]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["containers"][0]["image"] = "tiny:v1"
        result = simulate(_cluster([n0, n1], pods=[pod]))
        # 0.5 MiB of spread-scaled size is under the 23 MiB threshold:
        # ImageLocality contributes nothing, first node wins the tie
        assert _placements(result)["p0"] == "n0"


class TestNodePreferAvoidPods:
    ANNO = "scheduler.alpha.kubernetes.io/preferAvoidPods"

    def _avoid_node(self, name):
        node = make_fake_node(name, "32", "64Gi")
        node["metadata"]["annotations"][self.ANNO] = json.dumps(
            {
                "preferAvoidPods": [
                    {
                        "podSignature": {
                            "podController": {"kind": "ReplicationController"}
                        },
                        "reason": "some reason",
                    }
                ]
            }
        )
        return node

    def test_rs_pod_avoids_annotated_node(self):
        avoid = self._avoid_node("n0")
        normal = make_fake_node("n1", "32", "64Gi")
        rs = make_fake_replica_set("web", "default", 1, "1", "1Gi")
        rs["spec"]["template"]["metadata"] = {"labels": {"app": "web"}}
        rs["spec"]["selector"] = {"matchLabels": {"app": "web"}}
        result = simulate(_cluster([avoid, normal], replica_sets=[rs]))
        placed = _placements(result)
        assert len(placed) == 1
        assert set(placed.values()) == {"n1"}

    def test_bare_pod_not_affected(self):
        avoid = self._avoid_node("n0")
        normal = make_fake_node("n1", "32", "64Gi")
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        result = simulate(_cluster([avoid, normal], pods=[pod]))
        # plugin only applies to RC/RS-owned pods; bare pod ties → first node
        assert _placements(result)["p0"] == "n0"
