"""Concurrent `simulate()` thread-safety (ISSUE 14 satellite).

The serve daemon's thread model stands on these pins: multiple threads
driving simulations against the shared process-global state — the jit
caches and AOT registry (engine/precompile.py), a shared shape-bucket
registry (`RoundsEngine.bulk_shapes`), and the metrics REGISTRY
(obs/metrics.py) — must produce placements bit-identical to serial runs
and corrupt no counters.

Pod NAMES are excluded from the bit-identity claim here, deliberately:
generated name suffixes draw from one process-global stream
(workloads/expand.py), so concurrent expansions interleave draws.  Names
never feed a kernel — placements are name-independent — and the serve
daemon serializes expansion under its request seed (batching._EXPAND_LOCK)
precisely so SERVED answers are reproducible to the name.  The canonical
comparison below is {node -> sorted pod base names}, suffixes stripped.
"""

from __future__ import annotations

import threading

import numpy as np

from simtpu import AppResource, ResourceTypes
from simtpu.api import simulate
from simtpu.obs.metrics import REGISTRY

from .fixtures import make_fake_deployment, make_fake_node

N_THREADS = 4


def _problem(tag: str = ""):
    cluster = ResourceTypes()
    cluster.nodes = [
        make_fake_node(f"node-{i}", "8", "16Gi") for i in range(6)
    ]
    apps = [
        AppResource(
            name=f"app{tag}",
            resource=ResourceTypes(
                deployments=[
                    make_fake_deployment(f"web{tag}", "default", 9, "1", "2Gi"),
                    make_fake_deployment(f"db{tag}", "default", 4, "2", "3Gi"),
                ]
            ),
        )
    ]
    return cluster, apps


def _canonical(result):
    """{node: sorted pod BASE names} — the name-suffix-independent view.
    A Deployment pod is named <dep>-<rs hash>-<pod hash> (both hashes
    drawn from the process-global stream, workloads/expand.py), so the
    base is everything before the first '-' (the fixture names carry
    none)."""
    return {
        s.node["metadata"]["name"]: sorted(
            p["metadata"]["name"].split("-", 1)[0] for p in s.pods
        )
        for s in result.node_status
    }


def _run_threads(fn, n=N_THREADS):
    """Run fn(i) on n threads; re-raise the first worker exception."""
    results = [None] * n
    errors = []

    def wrap(i):
        try:
            results[i] = fn(i)
        except BaseException as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)

    threads = [
        threading.Thread(target=wrap, args=(i,)) for i in range(n)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


class TestConcurrentSimulate:
    def test_same_problem_bit_identical_vs_serial(self):
        cluster, apps = _problem()
        serial = _canonical(simulate(cluster, apps))
        outs = _run_threads(lambda i: _canonical(simulate(cluster, apps)))
        for got in outs:
            assert got == serial

    def test_distinct_problems_each_match_their_serial_run(self):
        problems = [_problem(tag=str(i)) for i in range(N_THREADS)]
        serial = [
            _canonical(simulate(c, a)) for c, a in problems
        ]
        outs = _run_threads(
            lambda i: _canonical(simulate(*problems[i]))
        )
        assert outs == serial

    def test_concurrent_with_shared_aot_registry(self):
        """precompile=True runs the AOT pipeline's background compile
        pool under each simulation — pool threads and dispatch threads
        hammer the signature registry together."""
        cluster, apps = _problem(tag="aot")
        serial = _canonical(simulate(cluster, apps, precompile=True))
        outs = _run_threads(
            lambda i: _canonical(simulate(cluster, apps, precompile=True))
        )
        for got in outs:
            assert got == serial

    def test_concurrent_bulk_engines_share_shape_registry(self):
        """One shape-bucket registry across concurrently-placing bulk
        engines (the PR 1 sharing the serve sessions lean on): identical
        placement vectors vs the serial run."""
        from simtpu.engine.rounds import RoundsEngine
        from simtpu.faults import place_cluster

        cluster, apps = _problem(tag="bulk")
        shared: dict = {}

        def factory(tz):
            eng = RoundsEngine(tz)
            eng.bulk_shapes = shared
            eng.snap_shapes = True
            return eng

        base = place_cluster(cluster, apps, engine_factory=factory)
        base_nodes = np.asarray(base.nodes)
        outs = _run_threads(
            lambda i: np.asarray(
                place_cluster(cluster, apps, engine_factory=factory).nodes
            )
        )
        for nodes in outs:
            assert np.array_equal(nodes, base_nodes)


class TestRegistryUnderConcurrency:
    def test_counter_increments_are_exact(self):
        c = REGISTRY.counter("test.concurrency.counter")
        before = c.value
        per_thread, threads = 5000, 8
        _run_threads(
            lambda i: [c.inc() for _ in range(per_thread)], n=threads
        )
        assert c.value == before + per_thread * threads

    def test_histogram_counts_are_exact(self):
        h = REGISTRY.histogram("test.concurrency.hist")
        before = h.count
        per_thread, threads = 2000, 8
        _run_threads(
            lambda i: [h.observe(float(i)) for _ in range(per_thread)],
            n=threads,
        )
        assert h.count == before + per_thread * threads
        assert h.min == 0.0 and h.max == float(threads - 1)

    def test_fetch_counter_no_lost_increments(self):
        """fetch.get is bumped from every dispatch thread; K concurrent
        runs of a warmed problem must account for exactly K times one
        run's fetches."""
        cluster, apps = _problem(tag="fetch")
        simulate(cluster, apps)  # warm every executable first
        before = REGISTRY.snapshot()
        simulate(cluster, apps)
        one = REGISTRY.delta_since(before).get("fetch.get", 0)
        assert one > 0
        before = REGISTRY.snapshot()
        _run_threads(lambda i: simulate(cluster, apps))
        total = REGISTRY.delta_since(before).get("fetch.get", 0)
        assert total == N_THREADS * one
