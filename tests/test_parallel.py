"""Multi-chip layer tests: sharded engine equivalence, batched sweep parity.

Run on the 8-device virtual CPU mesh (conftest.py) — the same path the
driver's `dryrun_multichip` validates.
"""

import numpy as np
import pytest

from simtpu.api import simulate
from simtpu.core.objects import AppResource, ResourceTypes
from simtpu.parallel import (
    ShardedEngine,
    make_mesh,
    plan_capacity_batched,
    sweep_feasibility,
)
from simtpu.plan.capacity import plan_capacity
from simtpu.synth import make_node, synth_apps, synth_cluster
from simtpu.workloads.expand import seed_name_hashes


@pytest.fixture(scope="module")
def scenario():
    cluster = synth_cluster(
        11, seed=21, zones=3, taint_frac=0.2, gpu_frac=0.3, storage_frac=0.3
    )
    apps = synth_apps(
        40,
        seed=22,
        zones=3,
        pods_per_deployment=8,
        selector_frac=0.3,
        toleration_frac=0.2,
        anti_affinity_frac=0.4,
        gpu_frac=0.2,
        storage_frac=0.2,
    )
    return cluster, apps


def _placements(result):
    out = {}
    for status in result.node_status:
        for pod in status.pods:
            meta = pod["metadata"]
            out[(meta.get("namespace"), meta["name"])] = pod["spec"]["nodeName"]
    return out


class TestShardedEngine:
    def test_identical_to_unsharded(self, scenario):
        """Dead-node padding + GSPMD sharding must not change one placement."""
        cluster, apps = scenario
        ext = ("open-local", "gpu")
        seed_name_hashes(0)
        base = simulate(cluster, apps, extended_resources=ext)
        mesh = make_mesh(sweep=1)  # 8-way node sharding; 11 nodes pad to 16
        seed_name_hashes(0)
        sharded = simulate(
            cluster,
            apps,
            extended_resources=ext,
            engine_factory=lambda t: ShardedEngine(t, mesh),
        )
        assert _placements(base) == _placements(sharded)
        assert len(base.unscheduled_pods) == len(sharded.unscheduled_pods)

    def test_sweep_axis_mesh(self, scenario):
        cluster, apps = scenario
        mesh = make_mesh(sweep=2)  # 2 x 4 mesh
        seed_name_hashes(0)
        result = simulate(
            cluster, apps, engine_factory=lambda t: ShardedEngine(t, mesh)
        )
        seed_name_hashes(0)
        base = simulate(cluster, apps)
        assert _placements(base) == _placements(result)


class TestShardedRoundsEngine:
    def test_identical_to_unsharded_bulk(self):
        """Bulk rounds under GSPMD must match the unsharded rounds engine."""
        from simtpu.parallel import ShardedRoundsEngine

        cluster = synth_cluster(13, seed=31, zones=3, taint_frac=0.2)
        apps = synth_apps(
            60,
            seed=32,
            zones=3,
            pods_per_deployment=20,
            selector_frac=0.3,
            toleration_frac=0.2,
            anti_affinity_frac=0.2,
        )
        seed_name_hashes(0)
        base = simulate(cluster, apps, bulk=True)
        mesh = make_mesh(sweep=1)
        seed_name_hashes(0)
        sharded = simulate(
            cluster,
            apps,
            engine_factory=lambda t: ShardedRoundsEngine(t, mesh),
        )
        assert _placements(base) == _placements(sharded)
        assert len(base.unscheduled_pods) == len(sharded.unscheduled_pods)


class TestShardedMatrixRounds:
    # heaviest single cell in the module; fuzz-smoke's GSPMD column
    # re-proves the identity in CI, so it rides the slow tier
    @pytest.mark.slow
    def test_matrix_mix_identical_under_gspmd_small(self):
        """Fast-tier sibling of the slow matrix test: the same round
        variants (multi-GPU, multi-claim LVM, preset gpu-index, required
        colocate-with-self) under GSPMD at a tiny shape, so CI exercises
        every variant on every run (ISSUE 3 satellite)."""
        from simtpu.parallel import ShardedRoundsEngine
        from simtpu.synth import make_deployment

        cluster = synth_cluster(
            10, seed=51, zones=2, taint_frac=0.1, gpu_frac=0.6, storage_frac=0.5
        )
        apps = synth_apps(
            30,
            seed=52,
            zones=2,
            pods_per_deployment=10,
            selector_frac=0.2,
            anti_affinity_frac=0.2,
            gpu_frac=0.4,
            gpu_multi_frac=0.6,
            storage_frac=0.4,
            lvm_multi_frac=0.6,
            affinity_frac=0.3,
        )
        preset = ResourceTypes()
        preset.deployments = [
            make_deployment("preset", 4, 250, 256, gpu_mem_mib=4096, gpu_index="0-1")
        ]
        apps = list(apps) + [AppResource(name="preset", resource=preset)]
        ext = ("open-local", "gpu")
        seed_name_hashes(0)
        base = simulate(cluster, apps, bulk=True, extended_resources=ext)
        mesh = make_mesh(sweep=1)
        seed_name_hashes(0)
        sharded = simulate(
            cluster,
            apps,
            extended_resources=ext,
            engine_factory=lambda t: ShardedRoundsEngine(t, mesh),
        )
        assert _placements(base) == _placements(sharded)
        assert len(base.unscheduled_pods) == len(sharded.unscheduled_pods)

    @pytest.mark.slow
    def test_matrix_mix_identical_under_gspmd(self):
        """Round-4 MATRIX / self-affinity round variants under GSPMD
        (VERDICT r4 weak #2): multi-GPU pods, multi-claim LVM pods, preset
        gpu-index pods, and required colocate-with-self pods must place
        identically when the node axis is sharded over the mesh."""
        from simtpu.parallel import ShardedRoundsEngine
        from simtpu.synth import make_deployment

        cluster = synth_cluster(
            13, seed=51, zones=3, taint_frac=0.1, gpu_frac=0.5, storage_frac=0.4
        )
        apps = synth_apps(
            80,
            seed=52,
            zones=3,
            pods_per_deployment=10,
            selector_frac=0.2,
            anti_affinity_frac=0.2,
            gpu_frac=0.3,
            gpu_multi_frac=0.6,
            storage_frac=0.3,
            lvm_multi_frac=0.6,
            affinity_frac=0.3,
        )
        # one preset-gpu-index deployment: the round-4 verbatim-honor path
        preset = ResourceTypes()
        preset.deployments = [
            make_deployment("preset", 4, 250, 256, gpu_mem_mib=4096, gpu_index="0-1")
        ]
        apps = list(apps) + [AppResource(name="preset", resource=preset)]
        ext = ("open-local", "gpu")
        seed_name_hashes(0)
        base = simulate(cluster, apps, bulk=True, extended_resources=ext)
        mesh = make_mesh(sweep=1)
        seed_name_hashes(0)
        sharded = simulate(
            cluster,
            apps,
            extended_resources=ext,
            engine_factory=lambda t: ShardedRoundsEngine(t, mesh),
        )
        assert _placements(base) == _placements(sharded)
        assert len(base.unscheduled_pods) == len(sharded.unscheduled_pods)


class TestShardedIncrementalPlanner:
    """The flagship min-node-add workflow node-sharded over the mesh: base
    placement, completion probes, and the fresh verify re-runs all execute
    under GSPMD (`MaskedShardedRoundsEngine`), with the candidate
    `node_valid` mask composed with the sharding's dead-node pad mask.
    The gate: chosen count AND placement set bit-identical to the
    single-device incremental planner."""

    def _scenario(self):
        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"node-{i:06d}",
                8000,
                16,
                {
                    "topology.kubernetes.io/zone": f"zone-{i % 2}",
                    "kubernetes.io/hostname": f"node-{i:06d}",
                },
            )
            for i in range(3)
        ]
        apps = synth_apps(
            160,
            seed=6,
            zones=2,
            pods_per_deployment=20,
            selector_frac=0.0,
            anti_affinity_frac=0.2,
            spread_frac=0.4,
            spread_hard_frac=0.5,
        )
        template = make_node(
            "tmpl",
            16000,
            64,
            {
                "kubernetes.io/hostname": "tmpl",
                "topology.kubernetes.io/zone": "zone-0",
            },
        )
        return cluster, apps, template

    def test_sharded_plan_matches_single_device(self):
        from simtpu.plan.incremental import plan_capacity_incremental

        cluster, apps, template = self._scenario()
        seed_name_hashes(5)
        single = plan_capacity_incremental(cluster, apps, template, max_new_nodes=60)
        mesh = make_mesh(sweep=1)  # 8-way node sharding
        seed_name_hashes(5)
        sharded = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=60, mesh=mesh
        )
        assert sharded.success == single.success
        assert sharded.nodes_added == single.nodes_added
        assert sharded.probes == single.probes
        assert _placements(sharded.result) == _placements(single.result)
        assert len(sharded.result.unscheduled_pods) == len(
            single.result.unscheduled_pods
        )

    def test_sharded_probe_sweep_reuses_executables(self):
        """Per-probe engine instances must NOT re-jit the mesh executables:
        the compiled-callable cache is mesh-wide, so the probe sweep and
        the verify run trace at most two round bodies (the same budget the
        single-device sweep is pinned to)."""
        import jax

        from simtpu.plan.incremental import plan_capacity_incremental

        cluster, apps, template = self._scenario()
        mesh = make_mesh(sweep=1)
        seed_name_hashes(5)
        jax.clear_caches()
        plan = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=60, mesh=mesh
        )
        assert plan.success
        rounds = {
            phase: counts.get("rounds", 0)
            for phase, counts in plan.compiles.items()
        }
        assert rounds.get("probes", 0) + rounds.get("verify", 0) <= 2, plan.compiles


class TestBatchedSweep:
    # tier-1 keeps the host-vs-mesh sweep pin below; the vmapped-vs-
    # serial-planner identity duplicates test_faults' serial-oracle
    # pins and rides the slow tier
    @pytest.mark.slow
    def test_matches_serial_planner(self, scenario):
        """The one-shot vmapped sweep must find the same minimum node count
        as the reference-shaped serial search."""
        cluster, apps = scenario
        template = make_node(
            "tmpl", 64000, 256, {"kubernetes.io/hostname": "tmpl"}
        )
        serial = plan_capacity(cluster, apps, template, max_new_nodes=20)
        batched = plan_capacity_batched(cluster, apps, template, max_new_nodes=20)
        assert batched.success == serial.success
        assert batched.nodes_added == serial.nodes_added

    def test_feasibility_monotone(self, scenario):
        cluster, apps = scenario
        template = make_node(
            "tmpl", 64000, 256, {"kubernetes.io/hostname": "tmpl"}
        )
        failures, n_base, _ = sweep_feasibility(
            cluster, apps, template, candidates=range(6)
        )
        assert n_base == len(cluster.nodes)
        assert np.all(np.diff(failures) <= 0)

    def test_sweep_on_mesh_matches_host(self, scenario):
        cluster, apps = scenario
        template = make_node(
            "tmpl", 64000, 256, {"kubernetes.io/hostname": "tmpl"}
        )
        host, _, _ = sweep_feasibility(cluster, apps, template, candidates=range(5))
        mesh = make_mesh(sweep=1)
        meshed, _, _ = sweep_feasibility(
            cluster, apps, template, candidates=range(5), mesh=mesh
        )
        assert np.array_equal(host, meshed)


class TestGraftEntry:
    def test_entry_compiles(self):
        import importlib.util
        import jax

        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "/root/repo/__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        fn, args = mod.entry()
        out = jax.jit(fn)(*args)
        jax.block_until_ready(out)

    @pytest.mark.slow
    def test_dryrun_multichip(self):
        # slow tier: the single-chip dryrun above keeps the graft entry
        # covered on every run; the 8-dev variant rides `make test-all`.
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "/root/repo/__graft_entry__.py"
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.dryrun_multichip(8)


class TestShardedChunkedRounds:
    def test_chunked_rows_identical_under_gspmd_small(self):
        """Fast-tier sibling of the slow chunked-rows test: the
        ROW_BUDGET row-carry path under GSPMD at a tiny shape (ISSUE 3
        satellite)."""
        from simtpu.engine.rounds import RoundsEngine
        from simtpu.parallel import ShardedRoundsEngine

        cluster = synth_cluster(12, seed=41, zones=2, taint_frac=0.1)
        apps = synth_apps(
            36,
            seed=42,
            zones=2,
            pods_per_deployment=9,
            selector_frac=0.2,
            anti_affinity_frac=0.3,
            spread_frac=0.4,
        )

        class ChunkedBase(RoundsEngine):
            ROW_BUDGET = 4

        seed_name_hashes(3)
        base = simulate(cluster, apps, engine_factory=ChunkedBase)

        mesh = make_mesh(sweep=1)

        class Chunked(ShardedRoundsEngine):
            ROW_BUDGET = 4

        seed_name_hashes(3)
        sharded = simulate(
            cluster, apps, engine_factory=lambda t: Chunked(t, mesh)
        )
        assert _placements(base) == _placements(sharded)
        assert len(base.unscheduled_pods) == len(sharded.unscheduled_pods)

    @pytest.mark.slow
    def test_chunked_rows_identical_under_gspmd(self):
        """The chunked row-carry path (ROW_BUDGET) must also be placement-
        identical when the node axis is sharded over the mesh."""
        from simtpu.engine.rounds import RoundsEngine
        from simtpu.parallel import ShardedRoundsEngine

        cluster = synth_cluster(16, seed=41, zones=3, taint_frac=0.1)
        apps = synth_apps(
            96,
            seed=42,
            zones=3,
            pods_per_deployment=12,
            selector_frac=0.2,
            anti_affinity_frac=0.3,
            spread_frac=0.4,
        )
        seed_name_hashes(3)
        base = simulate(cluster, apps, engine_factory=RoundsEngine)

        mesh = make_mesh(sweep=1)

        class Chunked(ShardedRoundsEngine):
            ROW_BUDGET = 4

        seed_name_hashes(3)
        sharded = simulate(
            cluster, apps, engine_factory=lambda t: Chunked(t, mesh)
        )
        assert _placements(base) == _placements(sharded)
        assert len(base.unscheduled_pods) == len(sharded.unscheduled_pods)
