"""Tests for the bulk rounds engine (`simtpu/engine/rounds.py`), verified
against the serial scan (SURVEY.md §2.3: "greedy parallel rounds ...
verified against scan"): identical feasibility outcomes, zero constraint
violations in the final state, and serial fallback for interacting pods.
"""

from __future__ import annotations

import numpy as np

import simtpu.constants as C
from simtpu.api import simulate
from simtpu.core.objects import AppResource, ResourceTypes
from simtpu.engine.rounds import RoundsEngine
from simtpu.synth import synth_apps, synth_cluster

from .fixtures import (
    make_fake_deployment,
    make_fake_node,
    make_fake_pod,
    with_template_affinity,
)


def _placements(result):
    out = {}
    for status in result.node_status:
        for pod in status.pods:
            out[pod["metadata"]["name"]] = status.node["metadata"]["name"]
    return out


def _per_node_counts(result):
    return {
        s.node["metadata"]["name"]: len(s.pods) for s in result.node_status
    }


class TestBulkEquivalence:
    def test_all_placed_matches_scan(self):
        cluster = synth_cluster(40, seed=11, zones=4, taint_frac=0.1)
        apps = synth_apps(
            300,
            seed=12,
            zones=4,
            pods_per_deployment=50,
            selector_frac=0.2,
            toleration_frac=0.1,
            anti_affinity_frac=0.0,
        )
        serial = simulate(cluster, apps)
        bulk = simulate(cluster, apps, bulk=True)
        assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods) == 0
        assert sum(len(s.pods) for s in serial.node_status) == sum(
            len(s.pods) for s in bulk.node_status
        )

    def test_capacity_exhaustion_matches_scan(self):
        # 4 nodes x 8 pod slots; 50 requested -> exactly 18 unscheduled on
        # both engines, with a resource failure reason
        nodes = [make_fake_node(f"n{i}", "8", "16Gi") for i in range(4)]
        dep = make_fake_deployment("big", "default", 50, "1", "2Gi")
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        serial = simulate(cluster, apps)
        bulk = simulate(cluster, apps, bulk=True)
        assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods) == 18
        assert "resources" in bulk.unscheduled_pods[0].reason

    def test_no_overcommit(self):
        cluster = synth_cluster(16, seed=3, zones=2)
        apps = synth_apps(400, seed=4, zones=2, pods_per_deployment=100)
        bulk = simulate(cluster, apps, bulk=True)
        from simtpu.core.quantity import parse_quantity

        for status in bulk.node_status:
            cpu = parse_quantity(status.node["status"]["allocatable"]["cpu"])
            used = 0.0
            for pod in status.pods:
                for c in pod["spec"]["containers"]:
                    used += parse_quantity(
                        ((c.get("resources") or {}).get("requests") or {}).get(
                            "cpu", 0
                        )
                    )
            assert used <= cpu + 1e-6

    def test_spreading_quality_preserved(self):
        # 100 identical 1-cpu pods over 10 idle 32-cpu nodes: the
        # least-allocated slope must distribute them evenly, like serial
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(10)]
        dep = make_fake_deployment("spread", "default", 100, "1", "1Gi")
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        bulk = simulate(cluster, apps, bulk=True)
        counts = _per_node_counts(bulk)
        assert sum(counts.values()) == 100
        assert max(counts.values()) == min(counts.values()) == 10

    def test_anti_affinity_groups_fall_back_to_scan(self):
        # required anti-affinity on own labels -> serial path; at most one
        # pod per hostname domain
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(12)]
        dep = make_fake_deployment(
            "anti",
            "default",
            12,
            "1",
            "1Gi",
            with_template_affinity(
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {
                                    "matchLabels": {"simtpu-app": "anti"}
                                },
                                "topologyKey": C.LABEL_HOSTNAME,
                            }
                        ]
                    }
                }
            ),
        )
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        bulk = simulate(cluster, apps, bulk=True)
        counts = _per_node_counts(bulk)
        assert not bulk.unscheduled_pods
        assert max(counts.values()) == 1 and sum(counts.values()) == 12

    def test_host_port_run_capped_at_one_per_node(self):
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(3)]
        dep = make_fake_deployment("ported", "default", 10, "1", "1Gi")
        dep["spec"]["template"]["spec"]["containers"][0]["ports"] = [
            {"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}
        ]
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        for flag in (False, True):
            result = simulate(cluster, apps, bulk=flag)
            counts = _per_node_counts(result)
            assert max(counts.values(), default=0) == 1
            assert len(result.unscheduled_pods) == 7

    def test_mixed_batch_segments_interleave_correctly(self):
        # bare pod + big deployment + bare pod: segment order must respect
        # submission order so the trailing pod sees the deployment's usage
        nodes = [make_fake_node("n0", "10", "100Gi")]
        dep = make_fake_deployment("filler", "default", 9, "1", "1Gi")
        pre = make_fake_pod("pre", "default", "1", "1Gi")
        cluster = ResourceTypes(nodes=nodes, pods=[pre])
        apps = [
            AppResource(name="a", resource=ResourceTypes(deployments=[dep])),
            AppResource(
                name="b",
                resource=ResourceTypes(
                    pods=[make_fake_pod("post", "default", "1", "1Gi")]
                ),
            ),
        ]
        bulk = simulate(cluster, apps, bulk=True)
        # 10 cpu total: pre(1) + 9 filler = full; "post" must fail
        assert len(bulk.unscheduled_pods) == 1
        assert bulk.unscheduled_pods[0].pod["metadata"]["name"].startswith("post")


def test_chunked_rows_equivalent_to_whole_plane(monkeypatch):
    """Forcing a tiny ROW_BUDGET must not change placements: chunked bulk
    calls carry only each chunk's cnt-plane rows and scatter them back."""
    import numpy as np

    from simtpu import simulate
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import seed_name_hashes

    cluster = synth_cluster(24, seed=5, zones=3, taint_frac=0.1)
    apps = synth_apps(
        160,
        seed=6,
        zones=3,
        pods_per_deployment=16,
        selector_frac=0.2,
        anti_affinity_frac=0.3,
        spread_frac=0.3,
    )
    seed_name_hashes(5)
    whole = simulate(cluster, apps, engine_factory=RoundsEngine)

    class Chunked(RoundsEngine):
        ROW_BUDGET = 4

    chunk_counts = []
    orig = Chunked._chunk_runs

    def spy(self, run, batch, tensors, max_segs=None):
        out = list(orig(self, run, batch, tensors, max_segs))
        chunk_counts.append(len(out))
        return iter(out)

    monkeypatch.setattr(Chunked, "_chunk_runs", spy)
    seed_name_hashes(5)
    chunked = simulate(cluster, apps, engine_factory=Chunked)
    assert sum(chunk_counts) > 1, "the chunked path never engaged"

    def placements(res):
        return {
            p["metadata"]["name"]: st.node["metadata"]["name"]
            for st in res.node_status
            for p in st.pods
        }

    assert placements(whole) == placements(chunked)
    assert len(whole.unscheduled_pods) == len(chunked.unscheduled_pods)


def test_chunked_serial_scan_identical_to_monolithic(monkeypatch):
    """The chunked + term-row-sliced serial scan (scan.run_scan_chunked,
    VERDICT r4 task 5) must be placement-identical to one monolithic scan:
    force tiny chunks and a tiny row budget so both the pow2 chunk split
    and the count-plane slicing engage on a many-group problem, and compare
    against the same run with chunking/slicing effectively disabled."""
    from simtpu.engine import scan as scan_mod
    from simtpu.workloads.expand import seed_name_hashes

    cluster = synth_cluster(20, seed=15, zones=3, taint_frac=0.1)
    # 2-pod deployments → ~100 groups → a term vocabulary big enough that
    # a 8-row budget genuinely slices
    apps = synth_apps(
        200,
        seed=16,
        zones=3,
        pods_per_deployment=2,
        selector_frac=0.2,
        anti_affinity_frac=0.4,
        spread_frac=0.5,
    )

    def placements(res):
        return {
            p["metadata"]["name"]: st.node["metadata"]["name"]
            for st in res.node_status
            for p in st.pods
        }

    monkeypatch.setattr(scan_mod, "_SCAN_CHUNK", 1 << 30)
    monkeypatch.setattr(scan_mod, "_SCAN_ROW_BUDGET", 0)
    seed_name_hashes(7)
    mono = simulate(cluster, apps)

    monkeypatch.setattr(scan_mod, "_SCAN_CHUNK", 32)
    monkeypatch.setattr(scan_mod, "_SCAN_ROW_BUDGET", 8)
    seed_name_hashes(7)
    chunked = simulate(cluster, apps)

    assert placements(mono) == placements(chunked)
    assert len(mono.unscheduled_pods) == len(chunked.unscheduled_pods)


class TestBatchedLeftoverProbes:
    """Control-flow of the batched leftover probe machinery: one scan probes
    every exhausted run; a mid-batch placement truncates the batch, reverts
    any later placements through the eviction delta, and re-probes them."""

    def _engine(self):
        from simtpu.engine.rounds import RoundsEngine

        eng = RoundsEngine.__new__(RoundsEngine)
        return eng

    def test_mid_batch_placement_reverts_and_reprobes(self, monkeypatch):
        import numpy as np

        from simtpu.engine import scan as scan_mod

        eng = self._engine()
        p = 8
        r, v, sd, gd = 2, 1, 1, 1
        pods = (
            np.arange(p, dtype=np.int32),          # group
            np.ones((p, r), np.float32),           # req
            np.full(p, -1, np.int32),              # pin
            np.zeros(p, bool),                     # forced
            np.zeros((p, v), np.float32),          # lvm_size
            np.full((p, v), -1, np.int32),         # lvm_vg
            np.zeros((p, sd), np.float32),         # dev_size
            np.zeros((p, sd), np.int32),           # dev_media
            np.full(p, 2.0, np.float32),           # gpu_mem
            np.ones(p, np.int32),                  # gpu_count
            np.zeros((p, gd), np.float32),         # gpu_preset
        )
        leftovers = [(0, 3), (3, 5), (5, 8)]
        batches = []
        # batch 1: run0 fails, run1 places, run2 ALSO places (must revert);
        # batch 2 (re-probe of run2): fails
        script = [
            (np.array([-1, 4, 6]), np.array([2, 0, 0])),
            (np.array([-1]), np.array([5])),
        ]

        def fake_segment_idx(statics, state, pods_, idx, flags):
            nodes_s, reasons_s = script[len(batches)]
            batches.append(list(idx))
            k = len(idx)
            return state, (
                nodes_s,
                reasons_s,
                np.zeros((k, v), np.float32),
                np.zeros((k, sd), bool),
                np.full((k, gd), 1.0, np.float32),
            )

        walked = []

        def fake_segment(statics, state, pods_, a, b, flags):
            walked.append((a, b))
            k = b - a
            return state, (
                np.full(k, 7, np.int32),
                np.zeros(k, np.int32),
                np.zeros((k, v), np.float32),
                np.zeros((k, sd), bool),
                np.zeros((k, gd), np.float32),
            )

        deltas = []

        def fake_delta(statics, state, entries):
            deltas.append(entries)
            return state

        monkeypatch.setattr(eng, "_run_scan_segment_idx", fake_segment_idx)
        monkeypatch.setattr(eng, "_run_scan_segment", fake_segment)
        monkeypatch.setattr(scan_mod, "_apply_log_delta", fake_delta)

        nodes = np.full(p, -9, np.int32)
        reasons = np.zeros(p, np.int32)
        lvm = np.zeros((p, v), np.float32)
        dev = np.zeros((p, sd), bool)
        gpu = np.zeros((p, gd), np.float32)
        eng._probe_leftovers(
            None, "state", pods, leftovers, None, nodes, reasons, lvm, dev, gpu
        )
        # run0 stamped failed with its probe reason
        assert list(nodes[0:3]) == [-1, -1, -1] and list(reasons[0:3]) == [2, 2, 2]
        # run1's probe placed on node 4; remainder walked serially to node 7
        assert nodes[3] == 4 and list(nodes[4:5]) == [7]
        assert walked == [(4, 5)]
        # run2's premature placement was reverted (one delta with w=-1 and
        # the gpu row scaled by gpu_mem), then re-probed and stamped failed
        assert len(deltas) == 1
        g_a, n_a, w_a, req_a, vg_a, sd_a, gp_a = deltas[0]
        assert w_a[0] == -1.0 and n_a[0] == 6 and g_a[0] == 5
        assert gp_a[0, 0] == 2.0  # shares(1.0) * gpu_mem(2.0)
        assert batches == [[0, 3, 5], [5]]
        assert list(nodes[5:8]) == [-1, -1, -1] and list(reasons[5:8]) == [5, 5, 5]
