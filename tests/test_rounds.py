"""Tests for the bulk rounds engine (`simtpu/engine/rounds.py`), verified
against the serial scan (SURVEY.md §2.3: "greedy parallel rounds ...
verified against scan"): identical feasibility outcomes, zero constraint
violations in the final state, and serial fallback for interacting pods.
"""

from __future__ import annotations

import numpy as np

import simtpu.constants as C
from simtpu.api import simulate
from simtpu.core.objects import AppResource, ResourceTypes, set_label
from simtpu.engine.rounds import RoundsEngine
from simtpu.synth import synth_apps, synth_cluster

from .fixtures import (
    make_fake_deployment,
    make_fake_node,
    make_fake_pod,
    with_template_affinity,
)


def _placements(result):
    out = {}
    for status in result.node_status:
        for pod in status.pods:
            out[pod["metadata"]["name"]] = status.node["metadata"]["name"]
    return out


def _per_node_counts(result):
    return {
        s.node["metadata"]["name"]: len(s.pods) for s in result.node_status
    }


class TestBulkEquivalence:
    def test_all_placed_matches_scan(self):
        cluster = synth_cluster(40, seed=11, zones=4, taint_frac=0.1)
        apps = synth_apps(
            300,
            seed=12,
            zones=4,
            pods_per_deployment=50,
            selector_frac=0.2,
            toleration_frac=0.1,
            anti_affinity_frac=0.0,
        )
        serial = simulate(cluster, apps)
        bulk = simulate(cluster, apps, bulk=True)
        assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods) == 0
        assert sum(len(s.pods) for s in serial.node_status) == sum(
            len(s.pods) for s in bulk.node_status
        )

    def test_capacity_exhaustion_matches_scan(self):
        # 4 nodes x 8 pod slots; 50 requested -> exactly 18 unscheduled on
        # both engines, with a resource failure reason
        nodes = [make_fake_node(f"n{i}", "8", "16Gi") for i in range(4)]
        dep = make_fake_deployment("big", "default", 50, "1", "2Gi")
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        serial = simulate(cluster, apps)
        bulk = simulate(cluster, apps, bulk=True)
        assert len(serial.unscheduled_pods) == len(bulk.unscheduled_pods) == 18
        assert "resources" in bulk.unscheduled_pods[0].reason

    def test_no_overcommit(self):
        cluster = synth_cluster(16, seed=3, zones=2)
        apps = synth_apps(400, seed=4, zones=2, pods_per_deployment=100)
        bulk = simulate(cluster, apps, bulk=True)
        from simtpu.core.quantity import parse_quantity

        for status in bulk.node_status:
            cpu = parse_quantity(status.node["status"]["allocatable"]["cpu"])
            used = 0.0
            for pod in status.pods:
                for c in pod["spec"]["containers"]:
                    used += parse_quantity(
                        ((c.get("resources") or {}).get("requests") or {}).get(
                            "cpu", 0
                        )
                    )
            assert used <= cpu + 1e-6

    def test_spreading_quality_preserved(self):
        # 100 identical 1-cpu pods over 10 idle 32-cpu nodes: the
        # least-allocated slope must distribute them evenly, like serial
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(10)]
        dep = make_fake_deployment("spread", "default", 100, "1", "1Gi")
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        bulk = simulate(cluster, apps, bulk=True)
        counts = _per_node_counts(bulk)
        assert sum(counts.values()) == 100
        assert max(counts.values()) == min(counts.values()) == 10

    def test_anti_affinity_groups_fall_back_to_scan(self):
        # required anti-affinity on own labels -> serial path; at most one
        # pod per hostname domain
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(12)]
        dep = make_fake_deployment(
            "anti",
            "default",
            12,
            "1",
            "1Gi",
            with_template_affinity(
                {
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {
                                    "matchLabels": {"simtpu-app": "anti"}
                                },
                                "topologyKey": C.LABEL_HOSTNAME,
                            }
                        ]
                    }
                }
            ),
        )
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        bulk = simulate(cluster, apps, bulk=True)
        counts = _per_node_counts(bulk)
        assert not bulk.unscheduled_pods
        assert max(counts.values()) == 1 and sum(counts.values()) == 12

    def test_host_port_run_capped_at_one_per_node(self):
        nodes = [make_fake_node(f"n{i}", "32", "64Gi") for i in range(3)]
        dep = make_fake_deployment("ported", "default", 10, "1", "1Gi")
        dep["spec"]["template"]["spec"]["containers"][0]["ports"] = [
            {"containerPort": 8080, "hostPort": 8080, "protocol": "TCP"}
        ]
        cluster = ResourceTypes(nodes=nodes)
        apps = [AppResource(name="a", resource=ResourceTypes(deployments=[dep]))]
        for flag in (False, True):
            result = simulate(cluster, apps, bulk=flag)
            counts = _per_node_counts(result)
            assert max(counts.values(), default=0) == 1
            assert len(result.unscheduled_pods) == 7

    def test_mixed_batch_segments_interleave_correctly(self):
        # bare pod + big deployment + bare pod: segment order must respect
        # submission order so the trailing pod sees the deployment's usage
        nodes = [make_fake_node("n0", "10", "100Gi")]
        dep = make_fake_deployment("filler", "default", 9, "1", "1Gi")
        pre = make_fake_pod("pre", "default", "1", "1Gi")
        cluster = ResourceTypes(nodes=nodes, pods=[pre])
        apps = [
            AppResource(name="a", resource=ResourceTypes(deployments=[dep])),
            AppResource(
                name="b",
                resource=ResourceTypes(
                    pods=[make_fake_pod("post", "default", "1", "1Gi")]
                ),
            ),
        ]
        bulk = simulate(cluster, apps, bulk=True)
        # 10 cpu total: pre(1) + 9 filler = full; "post" must fail
        assert len(bulk.unscheduled_pods) == 1
        assert bulk.unscheduled_pods[0].pod["metadata"]["name"].startswith("post")


def test_chunked_rows_equivalent_to_whole_plane(monkeypatch):
    """Forcing a tiny ROW_BUDGET must not change placements: chunked bulk
    calls carry only each chunk's cnt-plane rows and scatter them back."""
    import numpy as np

    from simtpu import simulate
    from simtpu.engine.rounds import RoundsEngine
    from simtpu.synth import synth_apps, synth_cluster
    from simtpu.workloads.expand import seed_name_hashes

    cluster = synth_cluster(24, seed=5, zones=3, taint_frac=0.1)
    apps = synth_apps(
        160,
        seed=6,
        zones=3,
        pods_per_deployment=16,
        selector_frac=0.2,
        anti_affinity_frac=0.3,
        spread_frac=0.3,
    )
    seed_name_hashes(5)
    whole = simulate(cluster, apps, engine_factory=RoundsEngine)

    class Chunked(RoundsEngine):
        ROW_BUDGET = 4

    chunk_counts = []
    orig = Chunked._chunk_runs

    def spy(self, run, batch, tensors):
        out = list(orig(self, run, batch, tensors))
        chunk_counts.append(len(out))
        return iter(out)

    monkeypatch.setattr(Chunked, "_chunk_runs", spy)
    seed_name_hashes(5)
    chunked = simulate(cluster, apps, engine_factory=Chunked)
    assert sum(chunk_counts) > 1, "the chunked path never engaged"

    def placements(res):
        return {
            p["metadata"]["name"]: st.node["metadata"]["name"]
            for st in res.node_status
            for p in st.pods
        }

    assert placements(whole) == placements(chunked)
    assert len(whole.unscheduled_pods) == len(chunked.unscheduled_pods)
