"""Tests for the volume plugin family — the last filter rows of the
SURVEY.md §2.2 in-tree checklist (`vendor/.../algorithmprovider/
registry.go:75-145`): VolumeRestrictions, NodeVolumeLimits, VolumeBinding and
VolumeZone.

Note the reference's pod normalization converts PVC volumes to hostPath
(`pkg/utils/utils.go` MakeValidPod, mirrored in workloads/expand.py), so the
PVC-driven plugins only act on pods fed to the engine without normalization —
exactly as in the reference, where they are registered but inert for
normalized pods. Inline volume sources (EBS/GCE-PD/ISCSI/RBD/AzureDisk)
survive normalization and exercise VolumeRestrictions + NodeVolumeLimits
through the full `simulate()` path.
"""

from __future__ import annotations


from simtpu.api import simulate
from simtpu.core.objects import ResourceTypes
from simtpu.core.tensorize import Tensorizer

from .fixtures import make_fake_node, make_fake_pod, with_node_labels


def _placements(result):
    out = {}
    for status in result.node_status:
        for pod in status.pods:
            out[pod["metadata"]["name"]] = status.node["metadata"]["name"]
    return out


def with_volume(vol):
    def opt(pod):
        pod["spec"].setdefault("volumes", []).append(vol)

    return opt


def with_allocatable(res, value):
    def opt(node):
        node["status"]["allocatable"][res] = value
        node["status"]["capacity"][res] = value

    return opt


class TestVolumeRestrictions:
    def test_rw_gce_pd_excludes_second_user(self):
        nodes = [make_fake_node(f"n{i}", "8", "16Gi") for i in range(2)]
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume({"name": "d", "gcePersistentDisk": {"pdName": "disk-a"}}),
            )
            for i in range(3)
        ]
        result = simulate(ResourceTypes(nodes=nodes, pods=pods), [])
        placed = _placements(result)
        # only two nodes → the third rw user of disk-a cannot schedule
        assert len(placed) == 2
        assert len(set(placed.values())) == 2
        assert len(result.unscheduled_pods) == 1
        assert "volume" in result.unscheduled_pods[0].reason

    def test_ro_gce_pd_shares_a_node(self):
        nodes = [make_fake_node("n0", "8", "16Gi")]
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume(
                    {
                        "name": "d",
                        "gcePersistentDisk": {"pdName": "disk-a", "readOnly": True},
                    }
                ),
            )
            for i in range(3)
        ]
        result = simulate(ResourceTypes(nodes=nodes, pods=pods), [])
        assert len(_placements(result)) == 3
        assert not result.unscheduled_pods

    def test_ro_blocked_by_rw_user(self):
        nodes = [make_fake_node("n0", "8", "16Gi")]
        rw = make_fake_pod(
            "rw",
            "default",
            "1",
            "1Gi",
            with_volume({"name": "d", "gcePersistentDisk": {"pdName": "disk-a"}}),
        )
        ro = make_fake_pod(
            "ro",
            "default",
            "1",
            "1Gi",
            with_volume(
                {
                    "name": "d",
                    "gcePersistentDisk": {"pdName": "disk-a", "readOnly": True},
                }
            ),
        )
        result = simulate(ResourceTypes(nodes=nodes, pods=[rw, ro]), [])
        assert len(_placements(result)) == 1
        assert len(result.unscheduled_pods) == 1

    def test_aws_ebs_always_exclusive(self):
        nodes = [make_fake_node("n0", "8", "16Gi")]
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume(
                    {
                        "name": "d",
                        "awsElasticBlockStore": {
                            "volumeID": "vol-1",
                            "readOnly": True,  # readOnly does NOT share EBS
                        },
                    }
                ),
            )
            for i in range(2)
        ]
        result = simulate(ResourceTypes(nodes=nodes, pods=pods), [])
        assert len(_placements(result)) == 1
        assert len(result.unscheduled_pods) == 1

    def test_distinct_disks_no_conflict(self):
        nodes = [make_fake_node("n0", "8", "16Gi")]
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume(
                    {"name": "d", "gcePersistentDisk": {"pdName": f"disk-{i}"}}
                ),
            )
            for i in range(2)
        ]
        result = simulate(ResourceTypes(nodes=nodes, pods=pods), [])
        assert len(_placements(result)) == 2


class TestNodeVolumeLimits:
    def test_published_limit_enforced(self):
        node = make_fake_node(
            "n0", "32", "64Gi", with_allocatable("attachable-volumes-aws-ebs", "2")
        )
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume(
                    {"name": "d", "awsElasticBlockStore": {"volumeID": f"vol-{i}"}}
                ),
            )
            for i in range(3)
        ]
        result = simulate(ResourceTypes(nodes=[node], pods=pods), [])
        assert len(_placements(result)) == 2
        assert len(result.unscheduled_pods) == 1
        assert "max volume count" in result.unscheduled_pods[0].reason

    def test_shared_volume_counted_once_per_node(self):
        # upstream counts *unique* volumes per node: two read-only users of
        # one GCE PD consume a single attach slot
        node = make_fake_node(
            "n0", "32", "64Gi", with_allocatable("attachable-volumes-gce-pd", "1")
        )
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume(
                    {
                        "name": "d",
                        "gcePersistentDisk": {"pdName": "disk-a", "readOnly": True},
                    }
                ),
            )
            for i in range(2)
        ]
        result = simulate(ResourceTypes(nodes=[node], pods=pods), [])
        assert len(_placements(result)) == 2
        assert not result.unscheduled_pods

    def test_published_zero_limit_respected(self):
        # a node explicitly publishing 0 permits no attachments — the in-tree
        # default must not override it
        node = make_fake_node(
            "n0", "32", "64Gi", with_allocatable("attachable-volumes-aws-ebs", "0")
        )
        pod = make_fake_pod(
            "p0",
            "default",
            "1",
            "1Gi",
            with_volume({"name": "d", "awsElasticBlockStore": {"volumeID": "vol-1"}}),
        )
        result = simulate(ResourceTypes(nodes=[node], pods=[pod]), [])
        assert not _placements(result)
        assert len(result.unscheduled_pods) == 1

    def test_pvc_backed_ebs_counts_against_limit(self):
        # NodeVolumeLimits resolves PVC → PV → source (non_csi.go); feed the
        # tensorizer unnormalized pods with EBS-backed PVs
        node = make_fake_node(
            "n0", "32", "64Gi", with_allocatable("attachable-volumes-aws-ebs", "1")
        )
        pvs = [
            {
                "kind": "PersistentVolume",
                "metadata": {"name": f"pv-{i}"},
                "spec": {"awsElasticBlockStore": {"volumeID": f"vol-{i}"}},
            }
            for i in range(2)
        ]
        pvcs = [_pvc(f"claim-{i}", volume_name=f"pv-{i}") for i in range(2)]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["volumes"] = [
            {"name": f"v{i}", "persistentVolumeClaim": {"claimName": f"claim-{i}"}}
            for i in range(2)
        ]
        tz = Tensorizer([node], pvcs=pvcs, pvs=pvs)
        batch = tz.add_pods([pod])
        tensors = tz.freeze()
        g = batch.group[0]
        assert tensors.vol_att[g].sum() == 2
        assert tensors.attach_limits[0, 0] == 1.0

    def test_default_limit_when_unpublished(self):
        # GCE default limit is 16: a pod carrying 2 distinct PDs still fits a
        # node that publishes no attach limit at all
        node = make_fake_node("n0", "32", "64Gi")
        pod = make_fake_pod(
            "p0",
            "default",
            "1",
            "1Gi",
            with_volume({"name": "a", "gcePersistentDisk": {"pdName": "d-a"}}),
            with_volume({"name": "b", "gcePersistentDisk": {"pdName": "d-b"}}),
        )
        result = simulate(ResourceTypes(nodes=[node], pods=[pod]), [])
        assert len(_placements(result)) == 1
        assert not result.unscheduled_pods

    def test_cinder_published_limit_enforced(self):
        # CinderLimits (`nodevolumelimits/non_csi.go` cinderVolumeFilter):
        # inline cinder volumes count against attachable-volumes-cinder
        node = make_fake_node(
            "n0", "32", "64Gi", with_allocatable("attachable-volumes-cinder", "1")
        )
        pods = [
            make_fake_pod(
                f"p{i}",
                "default",
                "1",
                "1Gi",
                with_volume({"name": "d", "cinder": {"volumeID": f"cv-{i}"}}),
            )
            for i in range(2)
        ]
        result = simulate(ResourceTypes(nodes=[node], pods=pods), [])
        assert len(_placements(result)) == 1
        assert len(result.unscheduled_pods) == 1
        assert "max volume count" in result.unscheduled_pods[0].reason

    def test_cinder_default_limit_when_unpublished(self):
        # DefaultMaxCinderVolumes = 256 (`pkg/volume/util/attach_limit.go`)
        node = make_fake_node("n0", "32", "64Gi")
        pod = make_fake_pod(
            "p0",
            "default",
            "1",
            "1Gi",
            with_volume({"name": "a", "cinder": {"volumeID": "cv-a"}}),
        )
        result = simulate(ResourceTypes(nodes=[node], pods=[pod]), [])
        assert len(_placements(result)) == 1

    def test_csi_per_driver_limit_enforced(self):
        # CSILimits (`nodevolumelimits/csi.go`): PVC-backed CSI volumes count
        # against the per-driver `attachable-volumes-csi-<driver>` allocatable
        node = make_fake_node(
            "n0",
            "32",
            "64Gi",
            with_allocatable("attachable-volumes-csi-ebs.csi.aws.com", "1"),
        )
        pvs = [
            {
                "kind": "PersistentVolume",
                "metadata": {"name": f"pv-{i}"},
                "spec": {
                    "csi": {
                        "driver": "ebs.csi.aws.com",
                        "volumeHandle": f"vol-{i}",
                    }
                },
            }
            for i in range(2)
        ]
        pvcs = [_pvc(f"claim-{i}", volume_name=f"pv-{i}") for i in range(2)]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["volumes"] = [
            {"name": f"v{i}", "persistentVolumeClaim": {"claimName": f"claim-{i}"}}
            for i in range(2)
        ]
        tz = Tensorizer([node], pvcs=pvcs, pvs=pvs)
        batch = tz.add_pods([pod])
        tensors = tz.freeze()
        g = batch.group[0]
        assert tensors.vol_att[g].sum() == 2
        # the dynamic CSI class was appended after the 4 static classes
        csi_cls = tz._csi_class["ebs.csi.aws.com"]
        assert csi_cls == 4
        assert tensors.attach_limits[0, csi_cls] == 1.0
        from simtpu.engine.scan import FAIL_ATTACH, Engine

        nodes_out, reasons, _ = Engine(tz).place(batch)
        assert nodes_out[0] == -1 and int(reasons[0]) == FAIL_ATTACH

    def test_csi_unpublished_limit_is_unbounded(self):
        # upstream enforces a CSI limit only when the node publishes one (via
        # CSINode); an unpublished driver key imposes no cap
        node = make_fake_node("n0", "32", "64Gi")
        pvs = [
            {
                "kind": "PersistentVolume",
                "metadata": {"name": f"pv-{i}"},
                "spec": {
                    "csi": {"driver": "pd.csi.storage.gke.io", "volumeHandle": f"h-{i}"}
                },
            }
            for i in range(3)
        ]
        pvcs = [_pvc(f"claim-{i}", volume_name=f"pv-{i}") for i in range(3)]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["volumes"] = [
            {"name": f"v{i}", "persistentVolumeClaim": {"claimName": f"claim-{i}"}}
            for i in range(3)
        ]
        tz = Tensorizer([node], pvcs=pvcs, pvs=pvs)
        batch = tz.add_pods([pod])
        from simtpu.engine.scan import Engine

        nodes_out, _, _ = Engine(tz).place(batch)
        assert nodes_out[0] == 0

    def test_csi_drivers_have_independent_classes(self):
        # one driver's saturation must not block another driver's volumes
        node = make_fake_node(
            "n0",
            "32",
            "64Gi",
            with_allocatable("attachable-volumes-csi-a.example.com", "0"),
            with_allocatable("attachable-volumes-csi-b.example.com", "1"),
        )
        pvs = [
            {
                "kind": "PersistentVolume",
                "metadata": {"name": "pv-b"},
                "spec": {
                    "csi": {"driver": "b.example.com", "volumeHandle": "h-b"}
                },
            }
        ]
        pvcs = [_pvc("claim-b", volume_name="pv-b")]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["volumes"] = [
            {"name": "v", "persistentVolumeClaim": {"claimName": "claim-b"}}
        ]
        tz = Tensorizer([node], pvcs=pvcs, pvs=pvs)
        batch = tz.add_pods([pod])
        from simtpu.engine.scan import Engine

        nodes_out, _, _ = Engine(tz).place(batch)
        assert nodes_out[0] == 0


def _raw_pod_with_pvc(name, claim):
    """A pod dict fed straight to the Tensorizer (no normalization)."""
    pod = make_fake_pod(name, "default", "1", "1Gi")
    pod["spec"]["volumes"] = [
        {"name": "data", "persistentVolumeClaim": {"claimName": claim}}
    ]
    return pod


def _pvc(name, sc=None, volume_name=None):
    spec = {}
    if sc is not None:
        spec["storageClassName"] = sc
    if volume_name is not None:
        spec["volumeName"] = volume_name
    return {
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


class TestVolumeBindingAndZone:
    def _mask(self, nodes, pod, pvcs=(), pvs=(), scs=()):
        tz = Tensorizer(nodes, storage_classes=scs, pvcs=pvcs, pvs=pvs)
        batch = tz.add_pods([pod])
        tensors = tz.freeze()
        return tensors.vol_mask[batch.group[0]]

    def test_missing_pvc_unschedulable(self):
        nodes = [make_fake_node("n0", "8", "16Gi")]
        mask = self._mask(nodes, _raw_pod_with_pvc("p0", "nope"))
        assert not mask.any()

    def test_unbound_pvc_needs_storage_class(self):
        nodes = [make_fake_node("n0", "8", "16Gi")]
        sc = {"kind": "StorageClass", "metadata": {"name": "standard"}}
        ok = self._mask(
            nodes,
            _raw_pod_with_pvc("p0", "claim"),
            pvcs=[_pvc("claim", sc="standard")],
            scs=[sc],
        )
        missing = self._mask(
            nodes,
            _raw_pod_with_pvc("p1", "claim"),
            pvcs=[_pvc("claim", sc="standard")],
        )
        assert ok.all()
        assert not missing.any()

    def test_bound_pv_zone_restricts_nodes(self):
        nodes = [
            make_fake_node(
                "n0", "8", "16Gi", with_node_labels({"topology.kubernetes.io/zone": "z1"})
            ),
            make_fake_node(
                "n1", "8", "16Gi", with_node_labels({"topology.kubernetes.io/zone": "z2"})
            ),
        ]
        pv = {
            "kind": "PersistentVolume",
            "metadata": {
                "name": "pv-a",
                "labels": {"topology.kubernetes.io/zone": "z2"},
            },
            "spec": {},
        }
        mask = self._mask(
            nodes,
            _raw_pod_with_pvc("p0", "claim"),
            pvcs=[_pvc("claim", volume_name="pv-a")],
            pvs=[pv],
        )
        assert list(mask) == [False, True]

    def test_bound_pv_node_affinity(self):
        nodes = [
            make_fake_node("n0", "8", "16Gi", with_node_labels({"disk": "ssd"})),
            make_fake_node("n1", "8", "16Gi"),
        ]
        pv = {
            "kind": "PersistentVolume",
            "metadata": {"name": "pv-a"},
            "spec": {
                "nodeAffinity": {
                    "required": {
                        "nodeSelectorTerms": [
                            {
                                "matchExpressions": [
                                    {"key": "disk", "operator": "In", "values": ["ssd"]}
                                ]
                            }
                        ]
                    }
                }
            },
        }
        mask = self._mask(
            nodes,
            _raw_pod_with_pvc("p0", "claim"),
            pvcs=[_pvc("claim", volume_name="pv-a")],
            pvs=[pv],
        )
        assert list(mask) == [True, False]

    def test_static_provisioning_binds_to_available_pv(self):
        # PVC with no storageClassName: an unclaimed PV of sufficient capacity
        # makes the pod schedulable, restricted to that PV's reachable nodes
        nodes = [
            make_fake_node(
                "n0", "8", "16Gi", with_node_labels({"topology.kubernetes.io/zone": "z1"})
            ),
            make_fake_node(
                "n1", "8", "16Gi", with_node_labels({"topology.kubernetes.io/zone": "z2"})
            ),
        ]
        pvc = _pvc("claim")
        pvc["spec"]["resources"] = {"requests": {"storage": "10Gi"}}
        pv = {
            "kind": "PersistentVolume",
            "metadata": {
                "name": "pv-a",
                "labels": {"topology.kubernetes.io/zone": "z1"},
            },
            "spec": {"capacity": {"storage": "20Gi"}},
        }
        mask = self._mask(
            nodes, _raw_pod_with_pvc("p0", "claim"), pvcs=[pvc], pvs=[pv]
        )
        assert list(mask) == [True, False]
        # a too-small PV leaves the claim unbindable
        pv_small = dict(pv, spec={"capacity": {"storage": "1Gi"}})
        mask = self._mask(
            nodes, _raw_pod_with_pvc("p1", "claim"), pvcs=[pvc], pvs=[pv_small]
        )
        assert not mask.any()

    def test_prebound_pv_claimref_matches_claim(self):
        # a PV pre-bound via claimRef to the querying claim (PVC.volumeName
        # still empty) must bind — upstream findMatchingVolume prefers exactly
        # such PVs — and restricts the pod to that PV's reachable nodes
        nodes = [
            make_fake_node(
                "n0", "8", "16Gi", with_node_labels({"topology.kubernetes.io/zone": "z1"})
            ),
            make_fake_node(
                "n1", "8", "16Gi", with_node_labels({"topology.kubernetes.io/zone": "z2"})
            ),
        ]
        pvc = _pvc("claim")
        pvc["spec"]["resources"] = {"requests": {"storage": "10Gi"}}
        pv = {
            "kind": "PersistentVolume",
            "metadata": {
                "name": "pv-a",
                "labels": {"topology.kubernetes.io/zone": "z2"},
            },
            "spec": {
                # pre-bound, and smaller than the request: claimRef match
                # wins regardless of capacity
                "capacity": {"storage": "1Gi"},
                "claimRef": {"namespace": "default", "name": "claim"},
            },
        }
        mask = self._mask(
            nodes, _raw_pod_with_pvc("p0", "claim"), pvcs=[pvc], pvs=[pv]
        )
        assert list(mask) == [False, True]
        # a claimRef naming a DIFFERENT claim still excludes the PV
        pv_other = {
            "kind": "PersistentVolume",
            "metadata": {"name": "pv-b"},
            "spec": {
                "capacity": {"storage": "20Gi"},
                "claimRef": {"namespace": "default", "name": "other"},
            },
        }
        mask = self._mask(
            nodes, _raw_pod_with_pvc("p1", "claim"), pvcs=[pvc], pvs=[pv_other]
        )
        assert not mask.any()

    def test_open_local_claims_skip_volume_binding(self):
        # open-local SCs are scheduled by the storage kernels; the static
        # volume mask must not reject them even without PV objects
        nodes = [make_fake_node("n0", "8", "16Gi")]
        mask = self._mask(
            nodes,
            _raw_pod_with_pvc("p0", "claim"),
            pvcs=[_pvc("claim", sc="open-local-lvm")],
        )
        assert mask.all()
