"""`simtpu replay` — the trace-driven continuous-time engine (ISSUE 15).

The load-bearing pin: the batched replay path (one dispatch per gang,
delta-advanced carried state, coalesced same-timestamp departures,
compact carry, wavefront drafting) is BIT-IDENTICAL to the serial
one-event-at-a-time oracle (one pod per dispatch, dense carry, state
rebuilt from the placement log before every dispatch) — end-state
planes, placement log, final landing vectors, unplaced sets, event
timestamps, samples — on seeded traces covering gang rollback,
preemption-on-arrival, CronJob firings, and node down/up events.
"""

import json

import numpy as np
import pytest

from simtpu.engine.state import diff_state_planes
from simtpu.synth import make_deployment, make_node, make_trace
from simtpu.timeline import (
    ReplayOptions,
    load_trace,
    replay_trace,
    trace_from_doc,
)
from simtpu.workloads.validate import SpecError


def _assert_pinned(batched, serial):
    """Every acceptance surface of the batched-vs-oracle pin."""
    assert batched.event_log == serial.event_log
    assert np.array_equal(batched.nodes, serial.nodes)
    assert list(batched.engine.placed_node) == list(serial.engine.placed_node)
    assert list(batched.engine.placed_group) == list(serial.engine.placed_group)
    diffs = diff_state_planes(batched.end_state(), serial.end_state())
    assert not diffs, f"end-state planes differ: {diffs}"
    assert batched.samples == serial.samples
    assert batched.counts == serial.counts
    # unplaced sets: the rows that never (or no longer) hold a placement
    assert set(np.flatnonzero(batched.nodes < 0)) == set(
        np.flatnonzero(serial.nodes < 0)
    )


@pytest.fixture(scope="module")
def pressured():
    """A pressured seeded trace (tiny cluster, big gangs): gang
    rollbacks, retries, drops, cron firings, node down/up — replayed
    batched (wavefront ON) and through the serial oracle."""
    doc = make_trace(
        6, 180, seed=7, days=0.15, mean_gang=10, cron_jobs=2,
        node_event_frac=0.4, duration_mean_s=2500.0,
        priority_weights=(0.5, 0.3, 0.2),
    )
    batched = replay_trace(trace_from_doc(doc), ReplayOptions(speculate=True))
    serial = replay_trace(trace_from_doc(doc), ReplayOptions(serial=True))
    return doc, batched, serial


class TestOraclePinning:
    def test_batched_bit_identical_to_serial_oracle(self, pressured):
        _, batched, serial = pressured
        _assert_pinned(batched, serial)

    def test_trace_actually_exercises_the_hard_paths(self, pressured):
        """The pin above is not vacuous: rollbacks, retries, drops, cron
        firings and node events all fired."""
        _, batched, _ = pressured
        c = batched.counts
        assert c["gang_rollbacks"] > 0
        assert c["retries"] > 0
        assert c["cron_fires"] > 0
        assert c["node_down"] > 0 and c["node_up"] > 0
        assert c["departures"] > 0

    def test_sim_clock_monotone_and_samples_shaped(self, pressured):
        _, batched, _ = pressured
        ts = [s[0] for s in batched.samples]
        assert ts == sorted(ts)
        ev_ts = [t for t, _, _ in batched.event_log]
        assert ev_ts == sorted(ev_ts)
        for _, util, placed, pending in batched.samples:
            assert 0.0 <= util <= 1.0 + 1e-9
            assert placed >= 0 and pending >= 0

    def test_auditor_certifies_end_state(self, pressured):
        _, batched, serial = pressured
        assert batched.audit and batched.audit["ok"]
        assert serial.audit and serial.audit["ok"]

    def test_no_partial_gang_in_end_state(self, pressured):
        """All-or-nothing: every gang is fully placed or fully absent."""
        _, batched, _ = pressured
        # reconstruct per-job row slices the way the replay did
        from simtpu.timeline.events import expand_job_pods
        from simtpu.timeline.replay import _Replay  # noqa: F401 (shape doc)

        tr = trace_from_doc(pressured[0])
        base = 0
        from simtpu.workloads.expand import seed_name_hashes

        seed_name_hashes(0x7133_1177 ^ tr.seed)
        for job in sorted(tr.jobs, key=lambda j: j.seq):
            pods = expand_job_pods(job)
            if not pods:
                continue
            rows = np.arange(base, base + len(pods))
            base += len(pods)
            if not job.gang:
                continue
            placed = int((batched.nodes[rows] >= 0).sum())
            assert placed in (0, len(rows)), (
                f"partial gang visible in the end state: {job.name} "
                f"({placed}/{len(rows)})"
            )
        assert base == len(batched.nodes)

    def test_compact_carry_ab_identical(self, pressured):
        """SIMTPU_COMPACT-equivalent A/B inside the batched path."""
        doc, batched, _ = pressured
        dense = replay_trace(
            trace_from_doc(doc), ReplayOptions(speculate=True, compact=False)
        )
        _assert_pinned(batched, dense)


class TestPreemption:
    def _doc(self):
        nodes = [make_node(f"n-{i}", 4000, 16) for i in range(3)]

        def job(name, t, size, prio, dur=None, cpu=1800):
            j = {
                "name": name, "t_s": t, "priority": prio,
                "workload": make_deployment(name, size, cpu, 1024,
                                            priority=prio),
            }
            if dur:
                j["duration_s"] = dur
            return j

        return {
            "version": 1, "seed": 1, "horizon_s": 4000.0,
            "cluster": {"nodes": nodes},
            "jobs": [
                job("low-a", 1.0, 3, 0), job("low-b", 2.0, 3, 0),
                job("high", 100.0, 4, 100, dur=500.0),
                job("mid", 120.0, 2, 50),
            ],
        }

    def test_preemption_on_arrival_pinned(self):
        doc = self._doc()
        batched = replay_trace(trace_from_doc(doc), ReplayOptions())
        serial = replay_trace(trace_from_doc(doc), ReplayOptions(serial=True))
        _assert_pinned(batched, serial)
        assert batched.counts["preemptions"] >= 1
        assert batched.counts["preempted_pods"] >= 3
        assert batched.audit["ok"]

    def test_preemption_off_keeps_victims(self):
        doc = self._doc()
        res = replay_trace(trace_from_doc(doc), ReplayOptions(preempt=False))
        assert res.counts["preemptions"] == 0

    def test_failed_preemption_restores_victims(self):
        """An arrival too big to EVER fit must leave the evicted victims
        restored bit-identically (the delta-undo restore path)."""
        doc = self._doc()
        # the giant gang cannot fit even on an empty cluster
        doc["jobs"].append({
            "name": "giant", "t_s": 50.0, "priority": 1000,
            "workload": make_deployment("giant", 30, 1800, 1024,
                                        priority=1000),
        })
        base = replay_trace(trace_from_doc(self._doc()), ReplayOptions())
        res = replay_trace(trace_from_doc(doc), ReplayOptions())
        serial = replay_trace(trace_from_doc(doc), ReplayOptions(serial=True))
        _assert_pinned(res, serial)
        assert res.counts["preemptions"] == base.counts["preemptions"]
        assert res.audit["ok"]


class TestAutoscale:
    def _doc(self):
        nodes = [make_node(f"n-{i}", 8000, 32) for i in range(2)]
        return {
            "version": 1, "seed": 5, "horizon_s": 20000.0,
            "cluster": {"nodes": nodes},
            "jobs": [
                {"name": "web", "t_s": 10.0,
                 "workload": make_deployment("web", 2, 1000, 512),
                 "elastic": {"min": 1, "max": 8,
                             "usage": [[0.0, 0.5], [3000.0, 0.95],
                                       [12000.0, 0.2]]}},
                {"name": "filler", "t_s": 5.0, "priority": 0,
                 "duration_s": 18000.0,
                 "workload": make_deployment("filler", 10, 1200, 1024)},
            ],
            "autoscale": {"interval_s": 1000.0, "target_util": 0.6,
                          "pool": 2, "node": make_node("tmpl", 8000, 32)},
        }

    def test_hpa_and_pool_pinned(self):
        doc = self._doc()
        batched = replay_trace(trace_from_doc(doc), ReplayOptions())
        serial = replay_trace(trace_from_doc(doc), ReplayOptions(serial=True))
        _assert_pinned(batched, serial)
        c = batched.counts
        assert c["autoscale_checks"] > 0
        assert c["scale_up_pods"] > 0, "HPA never scaled up"
        assert c["scale_down_pods"] > 0, "HPA never scaled down"
        assert c["pool_up"] >= 1, "pool node never armed"
        assert c["pool_down"] >= 1, "pool node never disarmed"
        assert batched.audit["ok"]

    def test_pool_nodes_invisible_until_armed(self):
        """Before any pool_up, nothing may land on a pool node."""
        doc = self._doc()
        doc.pop("autoscale")
        base = replay_trace(trace_from_doc(doc), ReplayOptions())
        assert base.counts["pool_up"] == 0
        n_base = 2
        landed = np.asarray(base.engine.placed_node)
        assert (landed < n_base).all()


class TestCronFidelity:
    def _cron_doc(self, schedule="0 * * * *", suspend=False, horizon=7200.0):
        cj = {
            "apiVersion": "batch/v1", "kind": "CronJob",
            "metadata": {"name": "tick", "namespace": "t"},
            "spec": {
                "schedule": schedule, "suspend": suspend,
                "jobTemplate": {"spec": {
                    "completions": 2,
                    "template": {"spec": {"containers": [
                        {"name": "c", "resources":
                         {"requests": {"cpu": "100m", "memory": "64Mi"}}}
                    ]}},
                }},
            },
        }
        return {
            "version": 1, "seed": 0, "horizon_s": horizon,
            "cluster": {"nodes": [make_node("n-0", 8000, 32)]},
            "jobs": [],
            "cron_jobs": [{"cron_job": cj, "duration_s": 600.0}],
        }

    def test_firings_follow_the_schedule(self):
        tr = trace_from_doc(self._cron_doc())
        assert [j.t_s for j in tr.jobs] == [3600.0, 7200.0]
        res = replay_trace(tr, ReplayOptions())
        assert res.counts["cron_fires"] == 2
        assert res.counts["arrivals"] == 2
        # each firing runs 600s then departs
        assert res.counts["departures"] == 1  # the 7200 firing outlives horizon

    def test_suspended_cron_never_fires(self):
        tr = trace_from_doc(self._cron_doc(suspend=True))
        assert tr.jobs == []

    def test_malformed_schedule_is_one_line(self):
        doc = self._cron_doc(schedule="whenever")
        with pytest.raises(SpecError) as exc:
            trace_from_doc(doc, source="trace.json")
        msg = str(exc.value)
        assert "trace.json" in msg and "spec.schedule" in msg
        assert "\n" not in msg


class TestTraceDiagnostics:
    def _minimal(self):
        return {
            "version": 1, "horizon_s": 1000.0,
            "cluster": {"nodes": [make_node("n-0", 4000, 16)]},
            "jobs": [{"name": "a", "t_s": 1.0,
                      "workload": make_deployment("a", 1, 100, 128)}],
        }

    def test_load_trace_round_trip(self, tmp_path):
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(self._minimal()))
        tr = load_trace(str(path))
        assert len(tr.jobs) == 1 and tr.horizon_s == 1000.0
        res = replay_trace(tr, ReplayOptions())
        assert res.counts["admitted"] == 1

    def test_syntax_error_names_the_line(self, tmp_path):
        path = tmp_path / "bad.json"
        path.write_text('{\n "version": 1,\n "jobs": [}\n}')
        with pytest.raises(SpecError) as exc:
            load_trace(str(path))
        msg = str(exc.value)
        assert f"{path}:3" in msg and "\n" not in msg

    @pytest.mark.parametrize(
        "mutate, needle",
        [
            (lambda d: d["jobs"][0].pop("t_s"), "jobs[0].t_s"),
            (lambda d: d["jobs"][0].update(t_s=-5), "jobs[0].t_s"),
            (lambda d: d["jobs"][0].update(duration_s=0), "jobs[0].duration_s"),
            (lambda d: d["jobs"][0]["workload"].update(kind="DaemonSet"),
             "jobs[0].workload.kind"),
            (lambda d: d.update(version=99), "trace.version"),
            (lambda d: d.update(node_events=[{"t_s": 1.0}]), "node_events[0]"),
            (lambda d: d.update(
                node_events=[{"t_s": 1.0, "down": ["nope"]}]), "nope"),
            (lambda d: d["jobs"][0].update(
                gang=True, elastic={"min": 1, "max": 2}), "jobs[0].gang"),
            (lambda d: d["jobs"][0].update(
                elastic={"min": 3, "max": 2}), "elastic.max"),
        ],
    )
    def test_semantic_errors_carry_the_event_index(self, mutate, needle):
        doc = self._minimal()
        mutate(doc)
        with pytest.raises(SpecError) as exc:
            tr = trace_from_doc(doc, source="t.json")
            replay_trace(tr, ReplayOptions())  # node-name check is at build
        msg = str(exc.value)
        assert needle in msg, msg
        assert "\n" not in msg


class TestPartialResult:
    def test_deadline_yields_cooperative_partial(self):
        from simtpu.durable.deadline import RunControl

        doc = make_trace(4, 60, seed=3, days=0.1, mean_gang=5, cron_jobs=0)
        control = RunControl(deadline=0.0)  # expires at the first check
        res = replay_trace(
            trace_from_doc(doc), ReplayOptions(control=control, audit=False)
        )
        assert res.partial
        assert "interrupted" in res.message and "deadline" in res.message
        assert res.counters()["partial"] is True

    def test_interrupt_mid_stream_keeps_prefix(self):
        from simtpu.durable.deadline import RunControl

        doc = make_trace(4, 60, seed=3, days=0.1, mean_gang=5, cron_jobs=0)
        full = replay_trace(trace_from_doc(doc), ReplayOptions(audit=False))
        assert full.events > 4

        class _TripWire(RunControl):
            def __init__(self, after):
                super().__init__()
                self.left = after

            def check(self):
                self.left -= 1
                if self.left < 0:
                    self.trigger("SIGINT")
                super().check()

        res = replay_trace(
            trace_from_doc(doc),
            ReplayOptions(control=_TripWire(3), audit=False),
        )
        assert res.partial and 0 < res.events < full.events
        # the processed prefix is the full run's prefix (cooperative stop,
        # no torn state)
        assert res.event_log == full.event_log[: len(res.event_log)]


class TestMetrics:
    def test_timeline_counters_on_registry(self):
        from simtpu.obs.metrics import REGISTRY, family
        from simtpu.timeline.replay import TIMELINE_KEYS

        before = REGISTRY.snapshot("timeline.")
        doc = make_trace(4, 40, seed=11, days=0.05, mean_gang=4, cron_jobs=1)
        res = replay_trace(trace_from_doc(doc), ReplayOptions(audit=False))
        after = family("timeline", TIMELINE_KEYS)
        for key in ("events", "arrivals", "admitted", "attempts"):
            assert after[key] - before.get(f"timeline.{key}", 0) == \
                res.counts[key]
            assert res.counts[key] > 0
        assert REGISTRY.value("timeline.sim_clock_s") >= 0


class TestMakeTrace:
    def test_deterministic(self):
        a = make_trace(8, 100, seed=9, days=0.2)
        b = make_trace(8, 100, seed=9, days=0.2)
        assert a == b

    def test_append_only_rng_draws(self):
        """Enabling knobs that draw AFTER the arrival stream (node
        events, autoscale, cron count) must not perturb the jobs an
        existing seed already pinned."""
        base = make_trace(8, 100, seed=9, days=0.2, cron_jobs=0)
        with_nodes = make_trace(8, 100, seed=9, days=0.2, cron_jobs=0,
                                node_event_frac=0.25)
        with_pool = make_trace(8, 100, seed=9, days=0.2, cron_jobs=0,
                               autoscale_pool=2)
        assert with_nodes["jobs"] == base["jobs"]
        assert with_pool["jobs"] == base["jobs"]
        assert with_nodes["node_events"] and not base["node_events"]
        assert "autoscale" in with_pool

    def test_doc_is_json_serializable_and_loadable(self, tmp_path):
        doc = make_trace(6, 50, seed=2, days=0.1, cron_jobs=1,
                         elastic_frac=0.3, node_event_frac=0.2,
                         autoscale_pool=1)
        path = tmp_path / "t.json"
        path.write_text(json.dumps(doc))
        tr = load_trace(str(path))
        assert len(tr.jobs) > 0
        assert tr.autoscale is not None and tr.autoscale.pool == 1


class TestReplayCLI:
    """`simtpu replay` surface: exit codes, --json contract, one-line
    trace diagnostics (the docs/robustness.md code table's replay row)."""

    def _write_trace(self, tmp_path, doc):
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(doc))
        return str(path)

    def _tiny_doc(self):
        return make_trace(4, 30, seed=2, days=0.05, mean_gang=4,
                          cron_jobs=0)

    def test_replay_json_success(self, tmp_path, capsys):
        from simtpu.cli import main

        rc = main(["replay", self._write_trace(tmp_path, self._tiny_doc()),
                   "--json"])
        out = capsys.readouterr().out.strip().splitlines()[-1]
        doc = json.loads(out)
        assert rc == 0
        assert doc["success"] and doc["events"] > 0
        assert doc["audit"]["ok"]
        assert "events_per_s" in doc and "pending_p50_s" in doc

    def test_replay_check_mode(self, tmp_path, capsys):
        from simtpu.cli import main

        rc = main(["replay", self._write_trace(tmp_path, self._tiny_doc()),
                   "--json", "--check"])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 0 and doc["check"] is True

    def test_malformed_trace_one_line_exit_1(self, tmp_path, capsys):
        from simtpu.cli import main

        path = tmp_path / "bad.json"
        path.write_text('{"version": 1,\n "jobs": [}\n}')
        rc = main(["replay", str(path), "--json"])
        captured = capsys.readouterr()
        assert rc == 1
        doc = json.loads(captured.out.strip().splitlines()[-1])
        assert doc["success"] is False
        assert f"{path}:2" in doc["message"]
        assert "\n" not in doc["message"]
        # and the semantic-error shape names the event index
        bad = self._tiny_doc()
        bad["jobs"][1].pop("t_s")
        rc = main(["replay", self._write_trace(tmp_path, bad), "--json"])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 1 and "jobs[1].t_s" in doc["message"]

    def test_deadline_partial_exit_3(self, tmp_path, capsys, monkeypatch):
        from simtpu.cli import EXIT_PARTIAL, main

        monkeypatch.setenv("SIMTPU_FLIGHT_DIR", str(tmp_path))
        rc = main(["replay", self._write_trace(tmp_path, self._tiny_doc()),
                   "--json", "--deadline", "0"])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == EXIT_PARTIAL
        assert doc["partial"] is True and not doc["success"]
        assert "interrupted" in doc["message"]

    def test_missing_input_exit_1(self, capsys):
        from simtpu.cli import main

        rc = main(["replay", "--json"])
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == 1 and "success" in doc and not doc["success"]

    def test_no_timeline_import_on_other_commands(self):
        """The replay-off cost is provably zero: `simtpu version` (and
        apply's import closure) never import simtpu.timeline — same
        subprocess pin as the serve daemon."""
        import subprocess
        import sys as _sys

        code = (
            "import sys\n"
            "from simtpu.cli import main\n"
            "main(['version'])\n"
            "assert not any(m.startswith('simtpu.timeline') "
            "for m in sys.modules), sorted(m for m in sys.modules "
            "if m.startswith('simtpu.timeline'))\n"
        )
        proc = subprocess.run(
            [_sys.executable, "-c", code],
            capture_output=True, text=True, cwd="/root/repo",
            env={**__import__('os').environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0, proc.stderr


class TestReviewRegressions:
    """Pins for the round-15 review findings: eviction-epoch lifecycle
    (failed-preemption restore, HPA scale-down) and the --check control."""

    def test_restored_victim_still_departs(self):
        """A victim evicted in a FAILED preemption trial and restored
        must keep its scheduled departure (the restore un-stales the
        epoch) — previously it became immortal and held capacity to the
        horizon."""
        nodes = [make_node(f"n-{i}", 4000, 16) for i in range(3)]
        doc = {
            "version": 1, "seed": 1, "horizon_s": 6000.0,
            "cluster": {"nodes": nodes},
            "jobs": [
                {"name": "low", "t_s": 1.0, "priority": 0,
                 "duration_s": 500.0,
                 "workload": make_deployment("low", 6, 1800, 1024,
                                             priority=0)},
                # too big to EVER fit: the trial evicts low, fails,
                # restores it
                {"name": "giant", "t_s": 50.0, "priority": 100,
                 "workload": make_deployment("giant", 30, 1800, 1024,
                                             priority=100)},
            ],
        }
        res = replay_trace(trace_from_doc(doc), ReplayOptions(audit=False))
        serial = replay_trace(
            trace_from_doc(doc), ReplayOptions(serial=True, audit=False)
        )
        _assert_pinned(res, serial)
        assert res.counts["preemptions"] == 0  # the trial failed
        assert res.counts["departures"] == 1, (
            "restored victim never departed (stale-epoch leak)"
        )
        # low departed at ~501; nothing holds capacity at the horizon
        low_rows = np.arange(0, 6)
        assert (res.nodes[low_rows] < 0).all()

    def test_scaled_down_elastic_job_still_departs(self):
        """HPA scale-down partially evicts a run that stays alive: the
        job's departure must remain scheduled (bump_epoch=False) —
        previously the surviving replicas became immortal."""
        nodes = [make_node(f"n-{i}", 8000, 32) for i in range(2)]
        doc = {
            "version": 1, "seed": 5, "horizon_s": 20000.0,
            "cluster": {"nodes": nodes},
            "jobs": [
                {"name": "web", "t_s": 10.0, "duration_s": 8000.0,
                 "workload": make_deployment("web", 4, 1000, 512),
                 "elastic": {"min": 1, "max": 8,
                             "usage": [[0.0, 0.9], [3000.0, 0.2]]}},
            ],
            "autoscale": {"interval_s": 1000.0, "target_util": 0.6},
        }
        res = replay_trace(trace_from_doc(doc), ReplayOptions(audit=False))
        serial = replay_trace(
            trace_from_doc(doc), ReplayOptions(serial=True, audit=False)
        )
        _assert_pinned(res, serial)
        assert res.counts["scale_down_pods"] > 0
        assert res.counts["departures"] == 1, (
            "scaled-down job never departed (stale-epoch leak)"
        )
        assert (res.nodes < 0).all()

    def test_cron_deadline_catches_up_at_most_one_fire(self):
        """startingDeadlineSeconds reaching over several missed runs
        catches up only the MOST RECENT one (controller semantics) —
        previously every missed fire in the window was injected."""
        from simtpu.workloads.cron import fire_times, parse_schedule

        sched = parse_schedule("0 * * * *")  # hourly
        got = fire_times(sched, 5400.0, 9000.0,
                         starting_deadline_s=7200.0)
        # missed fires 0 and 3600 are both within the deadline; only
        # 3600 (the latest) surfaces, then the regular window fires
        assert got == [3600.0, 7200.0]
        # and without a deadline the window stays half-open
        assert fire_times(sched, 3600.0, 7200.0) == [7200.0]

    def test_check_deadline_mid_oracle_is_partial_not_divergence(
        self, tmp_path, capsys, monkeypatch
    ):
        """--check whose deadline expires during the ORACLE re-replay
        exits 3 (cooperative partial), not 4 (false divergence)."""
        from simtpu.cli import EXIT_PARTIAL, main
        import simtpu.cli as cli_mod

        monkeypatch.setenv("SIMTPU_FLIGHT_DIR", str(tmp_path))
        doc = make_trace(4, 40, seed=2, days=0.05, mean_gang=4,
                         cron_jobs=0)
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(doc))

        real = cli_mod.RunControl if hasattr(cli_mod, "RunControl") else None
        assert real is None  # cli imports RunControl lazily per command

        from simtpu.durable.deadline import RunControl

        calls = {"n": 0}
        orig_init = RunControl.__init__

        def fake_init(self, deadline=None):
            calls["n"] += 1
            # first control (batched run): no deadline; second (--check
            # oracle): already expired
            orig_init(self, deadline=-1.0 if calls["n"] == 2 else None)

        monkeypatch.setattr(RunControl, "__init__", fake_init)
        rc = main(["replay", str(path), "--json", "--check"])
        doc_out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rc == EXIT_PARTIAL, doc_out
        assert doc_out["partial"] is True
        assert "check" not in doc_out  # no verdict from a truncated oracle
        assert "--check" in doc_out["message"]
