"""Global-solver planning backend tests (simtpu/solve, ISSUE 19).

The load-bearing pins:

- exact-minimum parity: on a feasible mix `plan_capacity(..., solver=True)`
  ships the SAME certified minimum node count as the exact
  doubling+bisection, and the auditor certifies the shipped placement;
- proof-or-step-aside: an infeasible-by-construction spec makes the
  solver report a PROVEN infeasibility (never a rounded garbage
  placement), and the exact search still owns the final verdict;
- deterministic rounding: tie-broken fractional masses always round
  toward the lower node index, and the repair loop moves load off
  overfull nodes in exact arithmetic;
- audit-dirty fallback: SIMTPU_AUDIT_INJECT=1 corrupts the audit's view
  of the solver's rounded answer — the serial exact engine re-places the
  candidate, only ITS certified answer ships, and the --json engine
  block records `accepted_fallback` (the wavefront-rollback shape);
- trace budget: the vmapped solve rides the pow2 shape buckets — a
  capacity sweep traces the kernel once per bucket, not per plan
  (`compile.solve`, same contract as TestProbeCompileBudget);
- preemption honesty: priority-bearing specs through the incremental
  planner raise the loud IGNORED notice and set
  `PlanResult.preemption_ignored` (satellite 1).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from simtpu import AppResource, ResourceTypes
from simtpu.plan.capacity import plan_capacity
from simtpu.plan.incremental import plan_capacity_incremental
from simtpu.plan.resilience import plan_resilience
from simtpu.solve.relax import (
    RESIDUAL_TOL,
    RelaxProblem,
    build_relax_problem,
    infeasibility_certificate,
    relax_candidates,
)
from simtpu.solve.rounding import round_candidate
from simtpu.workloads.expand import seed_name_hashes

from .fixtures import make_fake_deployment, make_fake_node

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _seed():
    seed_name_hashes(11)


def _small_plan_problem(replicas=7, cpu="2", memory="4Gi"):
    """1×(4cpu,8Gi) base + N×(2cpu,4Gi) pods + (4cpu,8Gi) template —
    the same shape tests/test_audit.py pins (min clones = 3 at N=7)."""
    cluster = ResourceTypes()
    cluster.nodes = [make_fake_node("base-1", "4", "8Gi")]
    apps = [
        AppResource(
            name="app",
            resource=ResourceTypes(
                deployments=[
                    make_fake_deployment("web", "default", replicas, cpu, memory)
                ]
            ),
        )
    ]
    template = make_fake_node("template", "4", "8Gi")
    return cluster, apps, template


def _assembled(cluster, apps, template, max_new=7):
    from simtpu.parallel.sweep import assemble_planning_problem

    tz, all_nodes, n_base, ordered = assemble_planning_problem(
        cluster, apps, template, max_new, ()
    )
    batch = tz.add_pods(ordered)
    tensors = tz.freeze()
    clone_idx = np.arange(len(all_nodes)) - n_base
    cands = np.arange(max_new + 1)
    valid_s = (clone_idx[None, :] < cands[:, None]) | (clone_idx[None, :] < 0)
    return tensors, batch, valid_s


class TestRelaxCore:
    def test_vmapped_relaxation_finds_the_exact_minimum(self):
        """One dispatch answers every candidate count: first
        relax-feasible index == the exact search's minimum (3), and the
        boundary candidate below it carries a float64 infeasibility
        proof."""
        tensors, batch, valid_s = _assembled(*_small_plan_problem())
        prob = build_relax_problem(tensors, batch)
        verd = relax_candidates(prob, valid_s)
        feasible = np.flatnonzero(verd.residual <= RESIDUAL_TOL)
        assert feasible.size and int(feasible[0]) == 3
        from simtpu.solve.relax import fetch_y

        assert infeasibility_certificate(prob, fetch_y(verd, 2), valid_s[2])
        # and the proof does NOT fire on the feasible side
        assert not infeasibility_certificate(
            prob, fetch_y(verd, 3), valid_s[3]
        )

    def test_infeasible_spec_is_proven_not_rounded(self):
        """A pod larger than every node: the solver must report a PROVEN
        infeasibility over the whole candidate range — no placement, no
        rounded garbage — and the exact search still renders the final
        (failing) verdict."""
        cluster, apps, template = _small_plan_problem(replicas=2, cpu="16")
        plan = plan_capacity(cluster, apps, template, 4, solver=True)
        assert not plan.success
        assert plan.solve["status"] == "infeasible"
        assert plan.solve["lower_bound"] == 4  # beyond the whole range
        assert "k" not in plan.solve  # nothing was ever rounded


def _toy_problem(cap, feas, cnt=3.0, req=1.0):
    """Single-class single-resource RelaxProblem for rounding tests."""
    cap = np.asarray(cap, np.float64).reshape(-1, 1)
    n = cap.shape[0]
    scale = np.maximum(cap.max(axis=0), 1e-9)
    return RelaxProblem(
        cls_rows=[np.arange(int(cnt))],
        cls_group=np.zeros(1, np.int32),
        cnt=np.array([cnt], np.float32),
        req=np.array([[req]], np.float32) / scale.astype(np.float32),
        req_raw=np.array([[req]], np.float64),
        feas=np.asarray(feas, bool).reshape(1, n),
        fixed=np.zeros((n, 1), np.float32),
        fixed_raw=np.zeros((n, 1), np.float64),
        cap=(cap / scale).astype(np.float32),
        cap_raw=cap,
        scale=scale,
        lr=0.1,
        pinned_rows=np.zeros(0, np.int64),
    )


class TestRounding:
    def test_tied_fractional_masses_round_toward_lower_index(self):
        """y = [1.5, 1.5] over two identical nodes, 3 pods: the single
        remainder lands on node 0 — deterministically, every time."""
        prob = _toy_problem([4.0, 4.0], [True, True])
        valid = np.ones(2, bool)
        y = np.array([[1.5, 1.5]])
        results = [round_candidate(prob, y, valid) for _ in range(5)]
        for m, why in results:
            assert why == ""
            assert m.tolist() == [[2, 1]]

    def test_reversed_tie_still_prefers_lower_index(self):
        prob = _toy_problem([4.0, 4.0, 4.0], [True, True, True])
        y = np.array([[0.5, 1.0, 1.5]])  # fracs 0.5, 0.0, 0.5 after floor
        m, why = round_candidate(prob, y, np.ones(3, bool))
        assert why == ""
        # remainder 1 → tie between node 0 and node 2 at frac 0.5 → node 0
        assert m.tolist() == [[1, 1, 1]]

    def test_repair_moves_load_off_overfull_nodes(self):
        """floor lands 3 pods on a 2-capacity node: the exact-arithmetic
        repair relocates the overflow instead of shipping it."""
        prob = _toy_problem([2.0, 4.0], [True, True])
        m, why = round_candidate(
            prob, np.array([[3.0, 0.0]]), np.ones(2, bool)
        )
        assert why == ""
        assert m.tolist() == [[2, 1]]

    def test_repair_failure_is_a_reason_never_garbage(self):
        """Total demand exceeds total capacity: rounding must FAIL with a
        reason (the planner rejects) — it may not return an overfull m."""
        prob = _toy_problem([2.0], [True])  # 3 pods, capacity 2
        m, why = round_candidate(prob, np.array([[3.0]]), np.ones(1, bool))
        assert m is None and why in ("repair_budget", "repair_stuck")


class TestSolverPlanners:
    def test_facade_solver_matches_exact_search(self):
        cluster, apps, template = _small_plan_problem()
        exact = plan_capacity(cluster, apps, template, 8)
        cluster, apps, template = _small_plan_problem()
        solved = plan_capacity(cluster, apps, template, 8, solver=True)
        assert solved.success and exact.success
        assert solved.nodes_added == exact.nodes_added == 3
        assert solved.solve["status"] == "accepted"
        assert solved.solve["certified_lb"] is True
        assert solved.audit["ok"] is True
        # the accepted path never ran the probe search
        assert solved.probes == {3: 0}

    def test_incremental_solver_matches_exact_search(self):
        cluster, apps, template = _small_plan_problem()
        exact = plan_capacity_incremental(cluster, apps, template, 8)
        cluster, apps, template = _small_plan_problem()
        solved = plan_capacity_incremental(
            cluster, apps, template, 8, solver=True
        )
        assert solved.success and exact.success
        assert solved.nodes_added == exact.nodes_added
        assert solved.solve["status"] == "accepted"
        assert solved.audit["ok"] is True

    def test_solver_off_is_bit_identical_and_unrecorded(self):
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity(cluster, apps, template, 8, solver=False)
        assert plan.success and plan.solve == {}

    def test_env_default_consults_the_solver(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_SOLVER", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity(cluster, apps, template, 8)
        assert plan.solve.get("enabled") is True

    def test_no_solver_overrides_the_env_default(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_SOLVER", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity(cluster, apps, template, 8, solver=False)
        assert plan.solve == {}

    def test_resilience_lower_bound_warm_start(self):
        """plan_resilience never ships a solver placement — it consumes
        the relax-only certified lower bound (the no-failure fit is
        necessary for survivability) and must land on the exact search's
        answer."""
        cluster, apps, template = _small_plan_problem()
        exact = plan_resilience(cluster, apps, template, k=1, max_new_nodes=10)
        cluster, apps, template = _small_plan_problem()
        solved = plan_resilience(
            cluster, apps, template, k=1, max_new_nodes=10, solver=True
        )
        assert solved.success and exact.success
        assert solved.nodes_added == exact.nodes_added
        assert solved.solve["mode"] == "lower_bound"
        assert solved.solve["status"] == "certified"
        assert solved.solve["lower_bound"] <= solved.nodes_added


class TestAuditInjectFallback:
    """SIMTPU_AUDIT_INJECT corrupts the audit's view of the SOLVER's
    rounded answer: the serial exact engine must re-place the candidate
    and only its certified answer may ship (mirrors
    test_audit.TestPlannerFallback for the new backend)."""

    def _assert_fallback(self, plan):
        assert plan.success
        assert plan.solve["status"] == "accepted_fallback"
        assert plan.solve["fallback"] is True
        doc = plan.audit
        assert doc["fallback"] is True
        assert doc["violations"] >= 1
        assert doc["fallback_audit"]["ok"] is True
        assert doc["ok"] is True  # the SHIPPED answer is certified

    def test_facade_solver_falls_back_to_exact(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity(cluster, apps, template, 8, solver=True)
        self._assert_fallback(plan)
        assert plan.nodes_added == 3  # the certified count still ships
        assert not plan.result.unscheduled_pods

    def test_incremental_solver_falls_back_to_exact(self, monkeypatch):
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity_incremental(
            cluster, apps, template, 8, solver=True
        )
        self._assert_fallback(plan)
        assert plan.nodes_added == 3

    def test_fallback_matches_uninjected_answer(self, monkeypatch):
        cluster, apps, template = _small_plan_problem()
        clean = plan_capacity(cluster, apps, template, 8, solver=True)
        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        cluster, apps, template = _small_plan_problem()
        dirty = plan_capacity(cluster, apps, template, 8, solver=True)
        assert dirty.nodes_added == clean.nodes_added
        assert clean.solve["status"] == "accepted"
        assert dirty.solve["status"] == "accepted_fallback"


class TestSolveCompileBudget:
    """Satellite 2: the vmapped solve rides the pow2 shape buckets — a
    second plan in the same bucket reuses the compiled kernel, so the
    `compile.solve` trace count stays bounded across a capacity sweep
    (the TestProbeCompileBudget contract, extended to the new kind)."""

    def test_same_bucket_plans_trace_the_kernel_once(self):
        # max_new_nodes=17 puts the candidate axis in a pow2 bucket no
        # other test touches, so compile accounting starts cold WITHOUT
        # jax.clear_caches() (which would force every later module to
        # re-trace the engine kernels).
        cluster, apps, template = _small_plan_problem()
        p1 = plan_capacity_incremental(
            cluster, apps, template, 17, solver=True
        )
        first = p1.compiles.get("solve", {}).get("solve", 0)
        assert first >= 1  # the cold run traced the kernel
        # replicas=6 pads into the same pow2 buckets as replicas=7
        cluster, apps, template = _small_plan_problem(replicas=6)
        p2 = plan_capacity_incremental(
            cluster, apps, template, 17, solver=True
        )
        assert p2.success
        assert p2.compiles.get("solve", {}).get("solve", 0) == 0, p2.compiles

    def test_solve_rides_compile_count_kinds(self):
        from simtpu.engine.scan import COMPILE_COUNT_KINDS

        assert "solve" in COMPILE_COUNT_KINDS


class TestPreemptionWarning:
    """Satellite 1: priority-bearing specs through the incremental
    planner (which never runs preemption) raise a loud notice and set
    the machine-readable flag; clean specs stay silent."""

    def _priority_problem(self):
        cluster, apps, template = _small_plan_problem()
        dep = apps[0].resource.deployments[0]
        dep["spec"]["template"]["spec"]["priority"] = 100
        return cluster, apps, template

    def test_priority_specs_raise_the_ignored_notice(self, capsys):
        cluster, apps, template = self._priority_problem()
        plan = plan_capacity_incremental(cluster, apps, template, 8)
        assert plan.success
        assert plan.preemption_ignored is True
        assert "IGNORED" in capsys.readouterr().err

    def test_clean_specs_stay_silent(self, capsys):
        cluster, apps, template = _small_plan_problem()
        plan = plan_capacity_incremental(cluster, apps, template, 8)
        assert plan.preemption_ignored is False
        assert "IGNORED" not in capsys.readouterr().err

    # the --json ride-along for this flag is pinned inside
    # TestCLI.test_no_solver_flag_records_not_consulted (one CLI run
    # covers both engine-block fields).


class TestCLI:
    @pytest.fixture(autouse=True)
    def _chdir_repo(self, monkeypatch):
        monkeypatch.chdir(REPO)

    def test_apply_solver_json_records_the_backend(self, capsys):
        from simtpu.cli import main

        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--solver",
        ])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        solve = doc["engine"]["solve"]
        assert solve["status"] == "accepted"
        assert solve["certified_lb"] is True
        assert doc["engine"]["audit"]["ok"] is True

    def test_no_solver_flag_records_not_consulted(self, capsys):
        from simtpu.cli import main

        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--no-solver",
        ])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert doc["engine"]["solve"] == {"enabled": False}
        # satellite 1 ride-along: clean specs keep the honesty flag down
        assert doc["engine"]["preemption_ignored"] is False

    def test_injected_divergence_solver_fallback_exit_4(
        self, monkeypatch, capsys
    ):
        """The --json evidence for the audit-dirty fallback: the engine
        block names the backend that ANSWERED (accepted_fallback), the
        shipped plan is certified, and the exit code is the documented
        audit-divergence code."""
        from simtpu.cli import EXIT_AUDIT, main

        monkeypatch.setenv("SIMTPU_AUDIT_INJECT", "1")
        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--solver",
        ])
        doc = json.loads(capsys.readouterr().out)
        assert rc == EXIT_AUDIT == 4
        assert doc["success"] is True
        solve = doc["engine"]["solve"]
        assert solve["status"] == "accepted_fallback"
        audit = doc["engine"]["audit"]
        assert audit["fallback"] is True
        assert audit["fallback_audit"]["ok"] is True
