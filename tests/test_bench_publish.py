"""BASELINE.json `published` block provenance (REVIEW r13).

`bench.py publish_multihost` is the ONLY writer of the published block:
it recomputes every derived field from the measured primitives (vs_target
by the one documented formula, pods_per_s/end_to_end_s re-derived, no
warm number from a single run) and rejects records missing any measured
key. The committed MULTIHOST_r13.json raw record must reproduce the
committed BASELINE.json published block EXACTLY through that code path —
the published headline number is never hand-entered.
"""

from __future__ import annotations

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def _record(**overrides):
    rec = {
        "metric": bench._NORTH_STAR_METRIC,
        "value": 12.0,
        "unit": "s",
        "measured_at": "2026-08-04T00:00:00+00:00",
        "backend": "cpu",
        "devices": 8,
        "engine": "ShardedRoundsEngine (GSPMD, node axis over 8-device mesh)",
        "constraints": bench._NORTH_CONSTRAINTS,
        "affinity": True,
        "spread": True,
        "trajectory": {
            "expand_tensorize_s": 2.0,
            "place_cold_s": 12.0,
            "placed": 999_900,
            "unplaced": 100,
            "runs": 1,
        },
        "metrics": {"fetch.get": 2},
    }
    rec.update(overrides)
    return rec


def _scratch_baseline(tmp_path, published=None):
    path = tmp_path / "BASELINE.json"
    path.write_text(json.dumps({"metric": "x", "published": published or {}}))
    return str(path)


def test_derived_fields_recomputed(tmp_path):
    """vs_target follows round(60/value, 2) — the same formula main()
    publishes for the north-star point — and pods_per_s/end_to_end_s are
    re-derived from the measured primitives, not copied through."""
    path = _scratch_baseline(tmp_path)
    rec = _record()
    # stale/wrong derived fields in the record must not survive
    rec["trajectory"]["pods_per_s"] = 1.0
    rec["trajectory"]["end_to_end_s"] = 999.0
    pub = bench.publish_multihost(rec, path)
    assert pub["vs_target"] == round(60.0 / 12.0, 2) == 5.0
    assert pub["trajectory"]["pods_per_s"] == round(1_000_000 / 12.0, 1)
    assert pub["trajectory"]["end_to_end_s"] == 14.0
    assert pub["source"] == "bench.py multihost_point (publish_multihost)"
    on_disk = json.loads(open(path).read())
    assert on_disk["published"] == pub
    assert on_disk["metric"] == "x"  # the rest of BASELINE.json untouched


def test_single_run_publishes_no_warm_number(tmp_path):
    """A runs==1 record is a cold measurement only — a place_warm_s that
    merely duplicates the cold wall is dropped, never published."""
    rec = _record()
    rec["trajectory"]["place_warm_s"] = 12.0
    pub = bench.publish_multihost(rec, _scratch_baseline(tmp_path))
    assert "place_warm_s" not in pub["trajectory"]
    rec2 = _record()
    rec2["trajectory"]["runs"] = 2
    rec2["trajectory"]["place_warm_s"] = 11.0
    pub2 = bench.publish_multihost(rec2, _scratch_baseline(tmp_path))
    assert pub2["trajectory"]["place_warm_s"] == 11.0


@pytest.mark.parametrize(
    "breakage",
    [
        "drop_metrics",
        "drop_measured_at",
        "drop_placed",
        "wrong_metric",
        "smoke_shape",
        "pod_total_mismatch",
        "zero_value",
    ],
)
def test_hand_assembled_records_rejected(tmp_path, breakage):
    """publish_multihost refuses records missing measured primitives, any
    metric but the north-star one (the <60 s target vs_target measures is
    DEFINED at 100k x 1M — a smoke-shape run must never overwrite the
    headline block), and pod accounting that contradicts that shape."""
    rec = _record()
    if breakage == "drop_metrics":
        del rec["metrics"]
    elif breakage == "drop_measured_at":
        del rec["measured_at"]
    elif breakage == "drop_placed":
        del rec["trajectory"]["placed"]
    elif breakage == "wrong_metric":
        rec["metric"] = "north_star_place_1m_pods_100k_nodes"
    elif breakage == "smoke_shape":
        rec["metric"] = "multihost_place_1k_pods_200_nodes"
    elif breakage == "pod_total_mismatch":
        rec["trajectory"]["placed"] = 900
    elif breakage == "zero_value":
        rec["value"] = 0.0
    with pytest.raises(ValueError):
        bench.publish_multihost(rec, _scratch_baseline(tmp_path))


def test_count_tag_never_degrades():
    """Metric-name shape tags stay exact — no sub-1k shape collapses to a
    colliding '0k'."""
    assert bench._count_tag(1_000_000) == "1m"
    assert bench._count_tag(100_000) == "100k"
    assert bench._count_tag(1_000) == "1k"
    assert bench._count_tag(200) == "200"
    assert bench._count_tag(1_500) == "1500"
    assert (
        f"multihost_place_{bench._count_tag(1_000_000)}_pods_"
        f"{bench._count_tag(100_000)}_nodes" == bench._NORTH_STAR_METRIC
    )


def test_committed_record_reproduces_committed_published_block(tmp_path):
    """THE provenance pin: republishing the committed MULTIHOST_r13.json
    through the committed code path must reproduce the repo's
    BASELINE.json published block exactly (and the whole file
    byte-for-byte once the block is swapped in)."""
    repo_baseline = os.path.join(REPO, "BASELINE.json")
    record_path = os.path.join(REPO, "MULTIHOST_r13.json")
    committed = json.loads(open(repo_baseline).read())
    if not committed.get("published"):
        pytest.skip("no published block yet")
    scratch = tmp_path / "BASELINE.json"
    scratch.write_text(
        json.dumps({k: v for k, v in committed.items() if k != "published"}
                   | {"published": {}})
    )
    record = json.loads(open(record_path).read())
    pub = bench.publish_multihost(record, str(scratch))
    assert pub == committed["published"]
    assert json.loads(scratch.read_text()) == committed
