"""Extended-resource conformance: Open-Local storage + GPU-share.

Exercises the same golden fixtures the reference documents
(`example/simon-gpushare-config.yaml`, `example/application/open_local`) plus
kernel-level unit checks of the vendored algorithms' semantics."""

import json
import os

import pytest

import simtpu.constants as C
from simtpu import AppResource, ResourceTypes, simulate
from simtpu.core.objects import annotations_of, name_of
from simtpu.core.quantity import parse_quantity
from simtpu.io.cluster import create_cluster_resource_from_cluster_config
from simtpu.io.yaml_loader import load_resources
from simtpu.workloads.expand import seed_name_hashes

from .fixtures import (
    make_fake_node,
    make_fake_pod,
    with_node_allocatable,
    with_node_labels,
    with_node_local_storage,
    with_pod_annotations,
)

GI = 2**30


@pytest.fixture(autouse=True)
def _seed():
    seed_name_hashes(3)


def _placements(result):
    out = {}
    for st in result.node_status:
        for pod in st.pods:
            out[name_of(pod)] = (name_of(st.node), pod)
    return out


class TestGpuShareFixtures:
    def test_pai_gpu_app_places_with_device_assignments(self, example_dir):
        cluster = create_cluster_resource_from_cluster_config(
            os.path.join(example_dir, "cluster/gpushare")
        )
        app = AppResource(
            name="pai_gpu",
            resource=load_resources(os.path.join(example_dir, "application/gpushare")),
        )
        result = simulate(cluster, [app], extended_resources=["gpu"])
        # 2 nodes × 2 GPUs × 16280Mi per device; demand: 1×1024Mi, 1×10240Mi(×2 GPUs),
        # 6×10240Mi + (pod-01 unknown) — every gpu pod that fits must carry gpu-index
        per_device = {}
        for pname, (node, pod) in _placements(result).items():
            annos = annotations_of(pod)
            mem = parse_quantity(annos.get(C.ANNO_POD_GPU_MEM, 0))
            if mem > 0 and annos.get(C.ANNO_POD_GPU_COUNT, "0") != "0":
                idx = annos.get(C.ANNO_POD_GPU_INDEX)
                assert idx is not None, f"{pname} placed without gpu-index"
                for dev in idx.split("-"):
                    key = (node, int(dev))
                    per_device[key] = per_device.get(key, 0) + mem
        # per-device capacity is totalMem/count = 16280Mi
        cap = parse_quantity("32560Mi") / 2
        for key, used in per_device.items():
            assert used <= cap + 1, f"device {key} over capacity: {used}"
        assert per_device, "no GPU pods were placed"

    def test_multi_gpu_pod_stacks_onto_devices(self):
        node = make_fake_node(
            "g0",
            "64",
            "256Gi",
            with_node_labels({"kubernetes.io/hostname": "g0"}),
            with_node_allocatable(
                {"alibabacloud.com/gpu-mem": "32Gi", "alibabacloud.com/gpu-count": "2"}
            ),
        )
        # 4 GPU shares of 8Gi each; devices hold 16Gi → 2 shares per device
        pod = make_fake_pod(
            "multi",
            "default",
            "1",
            "1Gi",
            with_pod_annotations(
                {C.ANNO_POD_GPU_MEM: "8Gi", C.ANNO_POD_GPU_COUNT: "4"}
            ),
        )
        cluster = ResourceTypes()
        cluster.nodes = [node]
        res = ResourceTypes()
        res.pods = [pod]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert not result.unscheduled_pods
        _, placed = _placements(result)["multi"]
        assert annotations_of(placed)[C.ANNO_POD_GPU_INDEX] == "0-0-1-1"

    def test_tightest_fit_single_gpu(self):
        node = make_fake_node(
            "g0",
            "64",
            "256Gi",
            with_node_allocatable(
                {"alibabacloud.com/gpu-mem": "32Gi", "alibabacloud.com/gpu-count": "2"}
            ),
        )
        cluster = ResourceTypes()
        cluster.nodes = [node]

        def gpu_pod(name, mem):
            return make_fake_pod(
                name,
                "default",
                "100m",
                "128Mi",
                with_pod_annotations({C.ANNO_POD_GPU_MEM: mem, C.ANNO_POD_GPU_COUNT: "1"}),
            )

        res = ResourceTypes()
        # first pod takes 12Gi on dev 0; second (3Gi) should tightest-fit onto
        # dev 0 (4Gi idle < 16Gi idle on dev 1)
        res.pods = [gpu_pod("big", "12Gi"), gpu_pod("small", "3Gi")]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert not result.unscheduled_pods
        placements = _placements(result)
        assert annotations_of(placements["big"][1])[C.ANNO_POD_GPU_INDEX] == "0"
        assert annotations_of(placements["small"][1])[C.ANNO_POD_GPU_INDEX] == "0"

    def test_preexisting_gpu_index_annotation_is_honored(self):
        # a running pod from a snapshot keeps its recorded device assignment
        # (AllocateGpuId short-circuit) and its usage blocks later pods
        node = make_fake_node(
            "g0",
            "64",
            "256Gi",
            with_node_allocatable(
                {"alibabacloud.com/gpu-mem": "32Gi", "alibabacloud.com/gpu-count": "2"}
            ),
        )
        running = make_fake_pod(
            "running",
            "default",
            "1",
            "1Gi",
            with_pod_annotations(
                {
                    C.ANNO_POD_GPU_MEM: "6Gi",
                    C.ANNO_POD_GPU_COUNT: "2",
                    C.ANNO_POD_GPU_INDEX: "0-1",
                }
            ),
        )
        running["spec"]["nodeName"] = "g0"
        cluster = ResourceTypes()
        cluster.nodes = [node]
        cluster.pods = [running]
        # with 6Gi used on EACH device (10Gi idle each), a 12Gi pod can't fit;
        # a greedy re-plan would have stacked both shares on dev 0 and left
        # dev 1 free at 16Gi
        res = ResourceTypes()
        res.pods = [
            make_fake_pod(
                "newpod",
                "default",
                "1",
                "1Gi",
                with_pod_annotations({C.ANNO_POD_GPU_MEM: "12Gi", C.ANNO_POD_GPU_COUNT: "1"}),
            )
        ]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert len(result.unscheduled_pods) == 1
        _, placed = _placements(result)["running"]
        assert annotations_of(placed)[C.ANNO_POD_GPU_INDEX] == "0-1"

    def test_gpu_mem_without_count_is_unschedulable(self):
        # GpuSharePlugin.Filter triggers on gpu-mem alone; AllocateGpuId then
        # fails for reqGpuNum<=0 → unschedulable everywhere
        node = make_fake_node(
            "g0",
            "64",
            "256Gi",
            with_node_allocatable(
                {"alibabacloud.com/gpu-mem": "32Gi", "alibabacloud.com/gpu-count": "2"}
            ),
        )
        cluster = ResourceTypes()
        cluster.nodes = [node]
        res = ResourceTypes()
        res.pods = [
            make_fake_pod(
                "no-count",
                "default",
                "100m",
                "128Mi",
                with_pod_annotations({C.ANNO_POD_GPU_MEM: "8Gi"}),
            )
        ]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert len(result.unscheduled_pods) == 1

    def test_gpu_pod_unschedulable_without_gpu_nodes(self):
        cluster = ResourceTypes()
        cluster.nodes = [make_fake_node("plain", "8", "16Gi")]
        res = ResourceTypes()
        res.pods = [
            make_fake_pod(
                "gp",
                "default",
                "100m",
                "128Mi",
                with_pod_annotations({C.ANNO_POD_GPU_MEM: "1Gi", C.ANNO_POD_GPU_COUNT: "1"}),
            )
        ]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert len(result.unscheduled_pods) == 1
        assert "GPU" in result.unscheduled_pods[0].reason


STORAGE = {
    "vgs": [
        {"name": "pool0", "capacity": 100 * GI},
        {"name": "pool1", "capacity": 200 * GI},
    ],
    "devices": [
        {
            "name": "/dev/vdd",
            "device": "/dev/vdd",
            "capacity": 100 * GI,
            "isAllocated": False,
            "mediaType": "hdd",
        },
        {
            "name": "/dev/vde",
            "device": "/dev/vde",
            "capacity": 50 * GI,
            "isAllocated": False,
            "mediaType": "ssd",
        },
    ],
}


def _sc(name, media=None, vg=None):
    params = {}
    if media:
        params["mediaType"] = media
    if vg:
        params["vgName"] = vg
    return {
        "apiVersion": "storage.k8s.io/v1",
        "kind": "StorageClass",
        "metadata": {"name": name},
        "parameters": params,
    }


def _storage_pod(name, volumes):
    return make_fake_pod(
        name,
        "default",
        "100m",
        "128Mi",
        with_pod_annotations({C.ANNO_POD_LOCAL_STORAGE: json.dumps({"volumes": volumes})}),
    )


class TestOpenLocal:
    def _cluster(self):
        cluster = ResourceTypes()
        cluster.nodes = [
            make_fake_node("s0", "8", "16Gi", with_node_local_storage(STORAGE)),
            make_fake_node("plain", "8", "16Gi"),
        ]
        cluster.storage_classes = [
            _sc("open-local-lvm"),
            _sc("open-local-device-hdd", media="hdd"),
            _sc("open-local-device-ssd", media="ssd"),
        ]
        return cluster

    def test_lvm_binpack_picks_smallest_fitting_vg(self):
        cluster = self._cluster()
        res = ResourceTypes()
        res.pods = [
            _storage_pod(
                "lvm-pod",
                [{"size": str(60 * GI), "kind": "LVM", "scName": "open-local-lvm"}],
            )
        ]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert not result.unscheduled_pods
        node_name, _ = _placements(result)["lvm-pod"]
        assert node_name == "s0"
        status = {name_of(st.node): st.node for st in result.node_status}
        storage = json.loads(
            annotations_of(status["s0"])[C.ANNO_NODE_LOCAL_STORAGE]
        )
        # 60Gi binpacks into pool0 (100Gi free < 200Gi free)
        by_name = {vg["name"]: vg for vg in storage["vgs"]}
        assert int(by_name["pool0"]["requested"]) == 60 * GI
        assert int(by_name["pool1"]["requested"]) == 0

    def test_device_exclusive_allocation(self):
        cluster = self._cluster()
        res = ResourceTypes()
        res.pods = [
            _storage_pod(
                "dev-pod-1",
                [{"size": str(30 * GI), "kind": "HDD", "scName": "open-local-device-hdd"}],
            ),
            _storage_pod(
                "dev-pod-2",
                [{"size": str(30 * GI), "kind": "HDD", "scName": "open-local-device-hdd"}],
            ),
        ]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        # only one hdd device exists → second pod unschedulable
        assert len(result.unscheduled_pods) == 1
        assert "storage" in result.unscheduled_pods[0].reason
        status = {name_of(st.node): st.node for st in result.node_status}
        storage = json.loads(annotations_of(status["s0"])[C.ANNO_NODE_LOCAL_STORAGE])
        hdd = [d for d in storage["devices"] if d["mediaType"] == "hdd"][0]
        assert hdd["isAllocated"] is True

    def test_storage_pod_avoids_storageless_node(self):
        cluster = self._cluster()
        res = ResourceTypes()
        res.pods = [
            _storage_pod(
                "p",
                [{"size": str(10 * GI), "kind": "LVM", "scName": "open-local-lvm"}],
            )
        ]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert not result.unscheduled_pods
        assert _placements(result)["p"][0] == "s0"

    def test_open_local_app_fixture(self, example_dir):
        cluster = create_cluster_resource_from_cluster_config(
            os.path.join(example_dir, "cluster/demo_1")
        )
        app = AppResource(
            name="open_local",
            resource=load_resources(os.path.join(example_dir, "application/open_local")),
        )
        result = simulate(cluster, [app], extended_resources=["open-local"])
        # nginx-lvm: 4 replicas each wanting 10Gi+40Gi LVM and a 100Gi HDD
        # device; only master-1 (tainted, no toleration) and worker-1 carry
        # storage with ONE hdd device each → exactly 1 replica fits (worker-1)
        failed = [name_of(u.pod) for u in result.unscheduled_pods]
        assert len(failed) == 3, (failed, [u.reason for u in result.unscheduled_pods])
        placed = [
            (p, n)
            for p, (n, _) in _placements(result).items()
            if p.startswith("nginx-lvm")
        ]
        assert placed == [(placed[0][0], "worker-1")]
