"""Speculative wavefront scan (engine/scan.py, docs/speculation.md): the
batched verify-and-rollback dispatcher must place BIT-IDENTICALLY to the
pod-at-a-time scan on every constraint mix — including the quota (hard
spread/anti), matrix (multi-GPU/multi-LVM), and preemption-free priority
variants — and under GSPMD node sharding; the accept/rollback telemetry must
account for every wavefront pod; and a forced conflict must roll back and
still reproduce the serial answer.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu import constants as C
from simtpu.core.objects import set_label
from simtpu.core.tensorize import Tensorizer
from simtpu.engine.scan import WAVE_KEYS, Engine
from simtpu.obs.metrics import family as metrics_family


def wave_counts():
    # registry-backed speculation counters (the alias view is gone)
    return metrics_family("wavefront", WAVE_KEYS)
from simtpu.synth import make_deployment, make_node, synth_apps, synth_cluster
from simtpu.workloads.expand import (
    get_valid_pods_exclude_daemonset,
    seed_name_hashes,
)


def _expand(apps):
    pods = []
    for app in apps:
        expanded = get_valid_pods_exclude_daemonset(app.resource)
        for pod in expanded:
            set_label(pod, C.LABEL_APP_NAME, app.name)
        pods.extend(expanded)
    return pods


def _mix_problem(mix: str, seed: int):
    """A small problem whose pod sequence is dominated by same-group runs
    (24-replica deployments) under the named constraint mix."""
    hard = mix == "hard"
    matrix = mix == "matrix"
    cluster = synth_cluster(
        24, seed=seed, zones=3, taint_frac=0.1,
        storage_frac=0.4, gpu_frac=0.5 if matrix else 0.0,
    )
    apps = synth_apps(
        240,
        seed=seed + 1,
        zones=3,
        pods_per_deployment=24,
        selector_frac=0.2,
        toleration_frac=0.1,
        anti_affinity_frac=0.25,
        anti_affinity_hard_frac=0.4 if hard else 0.0,
        spread_frac=0.3,
        spread_hard_frac=0.5 if hard else 0.0,
        gpu_frac=0.25 if matrix else 0.0,
        gpu_multi_frac=0.5 if matrix else 0.0,
        storage_frac=0.25,
        storage_device_frac=0.0 if matrix else 0.3,
        lvm_multi_frac=0.5 if matrix else 0.0,
        affinity_frac=0.15 if matrix else 0.0,
    )
    if mix == "priority":
        # preemption-free priority spread: distinct priorities per
        # deployment, ample capacity (nothing is ever evicted — priority
        # only orders the queue)
        for i, app in enumerate(apps):
            for dep in app.resource.deployments:
                dep["spec"]["template"]["spec"]["priority"] = (i % 4) * 100
    return cluster, apps


def _place(cluster, apps, speculate, engine_cls=Engine, **engine_kw):
    seed_name_hashes(0)
    pods = _expand(apps)
    tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    batch = tz.add_pods(pods)
    eng = engine_cls(tz, **engine_kw)
    eng.speculate = speculate
    nodes, reasons, extras = eng.place(batch)
    return nodes, reasons, extras


def _assert_identical(a, b):
    nodes_a, reasons_a, extras_a = a
    nodes_b, reasons_b, extras_b = b
    assert np.array_equal(nodes_a, nodes_b)
    assert np.array_equal(reasons_a, reasons_b)
    for key in extras_a:
        assert np.array_equal(
            np.asarray(extras_a[key]), np.asarray(extras_b[key])
        ), key


MIXES = ("north", "hard", "matrix", "priority")


class TestWavefrontBitIdentity:
    # the matrix mix is the heavyweight cell; CI's fuzz-smoke matrix
    # covers wavefront bit-identity on every push, so it rides the
    # slow tier to keep tier-1 inside its wall budget
    @pytest.mark.parametrize(
        "mix",
        [pytest.param(m, marks=pytest.mark.slow) if m == "matrix" else m
         for m in MIXES])
    def test_identical_to_pod_at_a_time(self, mix):
        """The headline guarantee: wavefront placements (nodes, reasons,
        extended-resource allocations) are bit-identical to the serial
        scan on every mix, and the wavefront path actually engaged."""
        cluster, apps = _mix_problem(mix, seed=7)
        base = _place(cluster, apps, speculate=False)
        before = wave_counts()
        wave = _place(cluster, apps, speculate=True)
        after = wave_counts()
        _assert_identical(base, wave)
        assert after["pods"] > before["pods"], "no wavefront engaged"

    @pytest.mark.slow
    @pytest.mark.parametrize("mix", MIXES)
    @pytest.mark.parametrize("seed", [21, 33])
    def test_identical_more_seeds(self, mix, seed):
        cluster, apps = _mix_problem(mix, seed=seed)
        base = _place(cluster, apps, speculate=False)
        wave = _place(cluster, apps, speculate=True)
        _assert_identical(base, wave)

    @pytest.mark.slow
    def test_identical_under_sliced_chunk_contexts(self):
        """Forced tiny chunk/row budgets exercise the group- and term-row-
        sliced statics contexts the wavefront dispatch composes with."""
        from simtpu.engine.scan import (
            build_pod_arrays,
            default_wave_call,
            flags_from,
            run_scan_chunked,
            statics_from,
        )
        from simtpu.engine.state import build_state

        cluster, apps = _mix_problem("north", seed=11)
        seed_name_hashes(0)
        pods = _expand(apps)
        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        batch = tz.add_pods(pods)
        tensors = tz.freeze()
        statics = statics_from(tensors)
        flags = flags_from(tensors, batch.ext)
        r = tensors.alloc.shape[1]
        _, pod_arrays = build_pod_arrays(batch, r)
        groups = np.asarray(batch.group)

        def fresh():
            return build_state(
                tensors, np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros((0, r), np.float32), None,
            )

        _, base = run_scan_chunked(
            statics, fresh(), pod_arrays, flags, tensors, groups,
            chunk=32, row_budget=4,
        )
        _, wave = run_scan_chunked(
            statics, fresh(), pod_arrays, flags, tensors, groups,
            chunk=32, row_budget=4, wave_call=default_wave_call,
        )
        for a, b in zip(base, wave):
            assert np.array_equal(a, b)


class TestWavefrontSharded:
    def test_identical_under_gspmd(self):
        """--shard equivalence: the mesh-compiled wavefront must place
        identically to the unsharded serial scan (dead-node padding plus
        the sharded reduced carries)."""
        from simtpu.parallel import ShardedEngine, make_mesh

        cluster, apps = _mix_problem("north", seed=9)
        base = _place(cluster, apps, speculate=False)
        mesh = make_mesh(sweep=1)
        before = wave_counts()
        sharded = _place(
            cluster, apps, speculate=True,
            engine_cls=ShardedEngine, mesh=mesh,
        )
        after = wave_counts()
        _assert_identical(base, sharded)
        assert after["pods"] > before["pods"], "sharded wavefronts not engaged"


class TestWavefrontRollback:
    def _conflict_problem(self):
        """Three identical nodes and one 12-replica run sized so the
        speculative wavefront-start answer (every pod on the argmax node)
        diverges immediately — the serial engine spreads — and nodes fill
        up mid-run, flipping the fit mask (the lean verifier's rollback
        trigger)."""
        from simtpu.core.objects import AppResource, ResourceTypes

        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"n-{i}", 4000, 8,
                {"kubernetes.io/hostname": f"n-{i}",
                 "topology.kubernetes.io/zone": "z0"},
            )
            for i in range(3)
        ]
        res = ResourceTypes()
        res.deployments.append(make_deployment("burst", 12, 1000, 1024))
        return cluster, [AppResource(name="burst", resource=res)]

    def test_forced_conflict_rolls_back_to_serial_answer(self):
        cluster, apps = self._conflict_problem()
        base = _place(cluster, apps, speculate=False)
        before = wave_counts()
        wave = _place(cluster, apps, speculate=True)
        after = wave_counts()
        diff = {k: after[k] - before[k] for k in after}
        # 12 pods on 4-slot nodes: serial spreads while speculation drafts
        # one node — divergences must be detected and the rolled-back pods
        # replayed to the exact serial answer
        assert diff["pods"] == 12
        assert diff["rollbacks"] >= 1
        assert diff["rollback_pods"] >= 1
        _assert_identical(base, wave)
        # capacity is exactly 12 pods; everything must have placed
        assert int((wave[0] >= 0).sum()) == 12

    def test_overflow_tail_reasons_exact(self):
        """A run that exhausts the cluster mid-wavefront: the unplaced
        tail's failure reasons must match the serial scan exactly (the
        verifier's fail-code cascade)."""
        from simtpu.core.objects import AppResource, ResourceTypes

        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"n-{i}", 4000, 8,
                {"kubernetes.io/hostname": f"n-{i}",
                 "topology.kubernetes.io/zone": "z0"},
            )
            for i in range(2)
        ]
        res = ResourceTypes()
        res.deployments.append(make_deployment("over", 12, 1000, 1024))
        apps = [AppResource(name="over", resource=res)]
        base = _place(cluster, apps, speculate=False)
        wave = _place(cluster, apps, speculate=True)
        _assert_identical(base, wave)
        assert int((wave[0] < 0).sum()) == 4  # 8 slots, 12 pods
        from simtpu.engine.scan import FAIL_RESOURCES

        assert set(np.asarray(wave[1])[np.asarray(wave[0]) < 0]) == {
            FAIL_RESOURCES
        }

    def test_interpod_blocked_tail_reason_exact(self):
        """A lean run emptied by EXISTING pods' required anti-affinity
        (sym_violated — the run owns no terms of its own) must report the
        serial scan's FAIL_INTERPOD, not a later cascade stage: the lean
        verifier's fail cascade keeps the interpod mask out of the spread
        stage (regression — it used to fold m_nofit in and report
        FAIL_SPREAD)."""
        from simtpu.core.objects import AppResource, ResourceTypes

        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"n-{i}", 64000, 64,
                {"kubernetes.io/hostname": f"n-{i}",
                 "topology.kubernetes.io/zone": "z0"},
            )
            for i in range(4)
        ]
        # a placed group owning required anti-affinity that selects the
        # lean run's label — every node's domain then rejects the run
        blocker = make_deployment("blk", 4, 250, 1)
        blocker["spec"]["template"]["spec"]["affinity"] = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": "web"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
        res_b = ResourceTypes()
        res_b.deployments.append(blocker)
        res_w = ResourceTypes()
        res_w.deployments.append(make_deployment("web", 8, 250, 1))
        apps = [
            AppResource(name="blk", resource=res_b),
            AppResource(name="web", resource=res_w),
        ]
        base = _place(cluster, apps, speculate=False)
        wave = _place(cluster, apps, speculate=True)
        _assert_identical(base, wave)
        from simtpu.engine.scan import FAIL_INTERPOD

        unplaced = np.asarray(wave[0]) < 0
        assert unplaced.sum() == 8  # the whole web run is blocked
        assert set(np.asarray(wave[1])[unplaced]) == {FAIL_INTERPOD}

    def test_counters_account_for_every_wavefront_pod(self):
        cluster, apps = _mix_problem("north", seed=13)
        before = wave_counts()
        _place(cluster, apps, speculate=True)
        after = wave_counts()
        diff = {k: after[k] - before[k] for k in after}
        assert diff["pods"] > 0
        assert diff["accepted"] + diff["rollback_pods"] == diff["pods"]
        assert diff["rollbacks"] <= diff["wavefronts"]


class TestWavefrontPrecompile:
    def test_aot_registry_serves_wavefronts(self):
        """precompile_place must enumerate the wavefront signatures so the
        first dispatch finds them in the registry (hits > 0) — with
        placements identical to the plain-jit path."""
        from simtpu.engine.precompile import precompile_place

        cluster, apps = _mix_problem("north", seed=17)
        base = _place(cluster, apps, speculate=True)

        seed_name_hashes(0)
        pods = _expand(apps)
        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        batch = tz.add_pods(pods)
        eng = Engine(tz)
        eng.speculate = True
        pipe = precompile_place(eng, batch)
        try:
            nodes, reasons, extras = eng.place(batch)
            pipe.wait_all()
            stats = pipe.stats()
        finally:
            pipe.shutdown()
        _assert_identical(base, (nodes, reasons, extras))
        assert stats["hits"] > 0
        assert stats["failures"] == 0


@pytest.mark.slow
class TestFullScaleSpotCheck:
    def test_north_star_stretch_exact_vs_bulk(self):
        """VERDICT r5 next-round #5: one sampled ~10k-pod stretch of the
        north-star mix at 100k nodes through the (wavefront) exact scan,
        cross-checked against the bulk engine within the documented
        divergence classes (placed-count band — the bulk round's
        round-boundary packing may strand or save a sliver relative to
        the serial order; see tests/test_fuzz.py)."""
        import os

        n_nodes = int(os.environ.get("SIMTPU_SPOTCHECK_NODES", 100_000))
        n_pods = int(os.environ.get("SIMTPU_SPOTCHECK_PODS", 10_000))
        cluster = synth_cluster(
            n_nodes, seed=3, zones=16, taint_frac=0.1, storage_frac=0.3
        )
        apps = synth_apps(
            n_pods,
            seed=4,
            zones=16,
            pods_per_deployment=1000,
            selector_frac=0.2,
            toleration_frac=0.1,
            anti_affinity_frac=0.2,
            spread_frac=0.3,
            storage_frac=0.2,
            storage_device_frac=0.3,
        )
        from simtpu.engine.rounds import RoundsEngine

        seed_name_hashes(0)
        pods = _expand(apps)
        tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        batch = tz.add_pods(pods)

        eng = Engine(tz)
        eng.speculate = True
        before = wave_counts()
        nodes_exact, reasons_exact, _ = eng.place(batch)
        after = wave_counts()
        assert after["pods"] - before["pods"] > n_pods // 2, (
            "the stretch should be wavefront-dominated"
        )

        seed_name_hashes(0)
        tz2 = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
        batch2 = tz2.add_pods(pods)
        bulk = RoundsEngine(tz2)
        nodes_bulk, reasons_bulk, _ = bulk.place(batch2)

        placed_exact = int((nodes_exact >= 0).sum())
        placed_bulk = int((nodes_bulk >= 0).sum())
        tol = max(1, placed_exact // 100)  # the fuzz suite's 1% band
        assert abs(placed_exact - placed_bulk) <= tol, (
            placed_exact, placed_bulk,
        )
        # node-capacity feasibility of the exact placement: no node
        # oversubscribed (placements respect the serial fit semantics)
        tensors = tz.freeze()
        r = tensors.alloc.shape[1]
        used = np.zeros_like(tensors.alloc, dtype=np.float64)
        req = np.asarray(batch.req, np.float64)
        if req.shape[1] < r:
            req = np.pad(req, ((0, 0), (0, r - req.shape[1])))
        ok = nodes_exact >= 0
        np.add.at(used, nodes_exact[ok], req[ok, :r])
        assert (used <= tensors.alloc * (1 + 1e-5) + 1e-6).all()


class TestHeavyDrafting:
    """ISSUE 16 tentpole: storage/GPU/ports/volume pods — excluded from
    drafting entirely before SIMTPU_WAVE_HEAVY — now ride the HARD
    verifier with the extra resource stages (ports conflicts, LVM/device
    allocation, GPU-share fitting) recomputed inside the verify scan.
    Placements stay bit-identical to the serial scan, the accept rate on
    those previously-skipped pods is > 0, and the result audits clean."""

    def _all_heavy_problem(self):
        """Every pod carries a heavy feature (LVM, exclusive device, GPU
        share, or hostPort): with SIMTPU_WAVE_HEAVY=0 the wavefront drafts
        NOTHING here, so any accept under heavy=1 is attributable to the
        new path."""
        from simtpu.core.objects import AppResource, ResourceTypes

        cluster = synth_cluster(
            24, seed=17, zones=3, taint_frac=0.0,
            gpu_frac=0.6, storage_frac=0.6,
        )
        res = ResourceTypes()
        res.deployments = [
            make_deployment("lvmy", 24, 500, 256, lvm_gib=5),
            make_deployment("gpuey", 24, 500, 256, gpu_mem_mib=1024),
            make_deployment("devy", 12, 300, 256, device_gib=10),
            make_deployment("porty", 16, 100, 128, host_port=8080),
        ]
        return cluster, [AppResource(name="heavy", resource=res)]

    def test_all_heavy_mix_accepts_where_legacy_skips(self, monkeypatch):
        cluster, apps = self._all_heavy_problem()
        serial = _place(cluster, apps, speculate=False)

        monkeypatch.setenv("SIMTPU_WAVE_HEAVY", "0")
        before = wave_counts()
        legacy = _place(cluster, apps, speculate=True)
        mid = wave_counts()
        _assert_identical(serial, legacy)
        assert mid["pods"] == before["pods"], (
            "legacy mask drafted a heavy pod — the A/B control is broken"
        )

        monkeypatch.setenv("SIMTPU_WAVE_HEAVY", "1")
        wave = _place(cluster, apps, speculate=True)
        after = wave_counts()
        _assert_identical(serial, wave)
        drafted = after["pods"] - mid["pods"]
        accepted = after["accepted"] - mid["accepted"]
        hard = after["draft_hard"] - mid["draft_hard"]
        assert drafted > 0, "no heavy pod was drafted"
        assert hard > 0, "heavy pods must ride the hard verifier"
        assert accepted > 0, (
            f"wavefront_accept_rate is 0 on the all-heavy mix "
            f"({drafted} drafted)"
        )
        rate = accepted / drafted
        assert 0 < rate <= 1

    # one gnarly seed stays in tier-1; the other two ride the slow tier
    # (fuzz-smoke sweeps the full seeded corpus in CI regardless)
    @pytest.mark.parametrize(
        "seed",
        [5,
         pytest.param(7, marks=pytest.mark.slow),
         pytest.param(12, marks=pytest.mark.slow)])
    def test_fuzz_gnarly_mixes_identical_and_audit_clean(self, seed):
        """Seeded gnarly storage/GPU/ports mixes (audit/fuzz.gen_case —
        seed 7 draws all three): wavefront == serial bit-identically, the
        hard-drafting path engages, and the placement audits clean."""
        from simtpu.audit.checker import audit_placement, extras_from_log
        from simtpu.audit.fuzz import gen_case
        from simtpu.faults import place_cluster

        cluster, apps, mix = gen_case(seed, n_nodes=16, n_pods=96)
        assert mix["gpu_frac"] or mix["storage_frac"] or mix["ports"]
        serial = place_cluster(cluster, apps, bulk=False, speculate=False)
        before = wave_counts()
        wave = place_cluster(cluster, apps, bulk=False, speculate=True)
        after = wave_counts()
        assert np.array_equal(serial.nodes, wave.nodes)
        assert after["pods"] > before["pods"], "no wavefront engaged"
        assert after["draft_hard"] > before["draft_hard"], (
            "gnarly mix never engaged the hard verifier"
        )
        rep = audit_placement(
            wave.tensors, wave.batch, wave.nodes, extras_from_log(wave)
        )
        assert rep.ok, rep
