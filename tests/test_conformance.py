"""Conformance test — port of the reference's TestSimulate scenario.

Rebuilds the exact cluster and app from `pkg/simulator/core_test.go:32-362`
(4 nodes: 3 tainted masters + 1 worker; static pods; metrics-server deployment
with master affinity + zone anti-affinity; 3 daemonsets; "simple" app with
deployment/daemonset/job/bare-pod/statefulset/replicaset) and asserts the same
result contract as `checkResult` (`core_test.go:364-591`): zero unscheduled
pods, and every workload produced exactly its expected number of placed pods
(daemonset expectations recomputed per node via NodeShouldRunPod).
"""

from collections import defaultdict

import pytest

import simtpu.constants as C
from simtpu import AppResource, ResourceTypes, simulate
from simtpu.core.match import node_should_run_pod
from simtpu.core.objects import annotations_of, name_of, namespace_of
from simtpu.workloads.expand import new_daemon_pod, seed_name_hashes

from .fixtures import (
    make_fake_daemon_set,
    make_fake_deployment,
    make_fake_job,
    make_fake_node,
    make_fake_pod,
    make_fake_replica_set,
    make_fake_stateful_set,
    with_node_labels,
    with_node_local_storage,
    with_node_taints,
    with_pod_node_name,
    with_pod_node_selector,
    with_pod_tolerations,
    with_template_affinity,
    with_template_node_selector,
    with_template_tolerations,
)

MASTER_TAINT = [{"key": "node-role.kubernetes.io/master", "effect": "NoSchedule"}]
MASTER_TOLERATION = [
    {"key": "node-role.kubernetes.io/master", "operator": "Exists", "effect": "NoSchedule"}
]
LOCAL_STORAGE = {
    "vgs": [
        {"name": "yoda-pool0", "capacity": 107374182400},
        {"name": "yoda-pool1", "capacity": 107374182400},
    ],
    "devices": [
        {
            "name": "/dev/vdd",
            "device": "/dev/vdd",
            "capacity": 107374182400,
            "isAllocated": False,
            "mediaType": "hdd",
        }
    ],
}


def _node_labels(name, role):
    return {
        "beta.kubernetes.io/arch": "amd64",
        "beta.kubernetes.io/os": "linux",
        "kubernetes.io/arch": "amd64",
        "kubernetes.io/hostname": name,
        "kubernetes.io/os": "linux",
        f"node-role.kubernetes.io/{role}": "",
    }


def build_cluster() -> ResourceTypes:
    cluster = ResourceTypes()
    cluster.nodes = [
        make_fake_node(
            "master-1",
            "8",
            "16Gi",
            with_node_labels(_node_labels("master-1", "master")),
            with_node_taints(MASTER_TAINT),
            with_node_local_storage(LOCAL_STORAGE),
        ),
        make_fake_node(
            "master-2", "8", "16Gi", with_node_labels(_node_labels("master-2", "master"))
        ),
        make_fake_node(
            "master-3", "8", "16Gi", with_node_labels(_node_labels("master-3", "master"))
        ),
        make_fake_node(
            "worker-1",
            "8",
            "16Gi",
            with_node_labels(_node_labels("worker-1", "worker")),
            with_node_local_storage(LOCAL_STORAGE),
        ),
    ]
    cluster.pods = [
        make_fake_pod("etcd-master-1", "kube-system", "", "", with_pod_node_name("master-1")),
        make_fake_pod(
            "kube-apiserver-master-1", "kube-system", "250m", "", with_pod_node_name("master-1")
        ),
        make_fake_pod(
            "kube-controller-manager-master-1",
            "kube-system",
            "200m",
            "",
            with_pod_node_name("master-1"),
        ),
        make_fake_pod(
            "kube-scheduler-master-1", "kube-system", "100m", "", with_pod_node_name("master-1")
        ),
    ]
    cluster.deployments = [
        make_fake_deployment(
            "metrics-server",
            "kube-system",
            1,
            "1",
            "500Mi",
            with_template_affinity(
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {
                                            "key": "node-role.kubernetes.io/master",
                                            "operator": "Exists",
                                        }
                                    ]
                                }
                            ]
                        }
                    },
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {
                                    "matchLabels": {"k8s-app": "metrics-server"}
                                },
                                "topologyKey": "failure-domain.beta.kubernetes.io/zone",
                            }
                        ]
                    },
                }
            ),
        )
    ]
    cluster.daemon_sets = [
        make_fake_daemon_set(
            "kube-proxy-master",
            "kube-system",
            "",
            "",
            with_template_tolerations([{"operator": "Exists"}]),
            with_template_node_selector({"node-role.kubernetes.io/master": ""}),
        ),
        make_fake_daemon_set(
            "kube-proxy-worker",
            "kube-system",
            "",
            "",
            with_template_tolerations([{"operator": "Exists"}]),
            with_template_node_selector({"node-role.kubernetes.io/worker": ""}),
        ),
        make_fake_daemon_set(
            "coredns",
            "kube-system",
            "100m",
            "70Mi",
            with_template_affinity(
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {
                                            "key": "node-role.kubernetes.io/master",
                                            "operator": "Exists",
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
            ),
            with_template_tolerations(
                [{"effect": "NoSchedule", "key": "node-role.kubernetes.io/master"}]
            ),
            with_template_node_selector({"beta.kubernetes.io/os": "linux"}),
        ),
    ]
    return cluster


def build_simple_app() -> AppResource:
    res = ResourceTypes()
    res.deployments = [
        make_fake_deployment(
            "busybox-deploy",
            "simple",
            4,
            "1500m",
            "1Gi",
            with_template_tolerations(
                [
                    {
                        "effect": "NoSchedule",
                        "key": "node-role.kubernetes.io/master",
                        "operator": "Exists",
                    }
                ]
            ),
        )
    ]
    res.daemon_sets = [
        make_fake_daemon_set(
            "busybox-ds",
            "simple",
            "500m",
            "512Mi",
            with_template_node_selector({"beta.kubernetes.io/os": "linux"}),
            with_template_affinity(
                {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {
                                    "matchExpressions": [
                                        {
                                            "key": "node-role.kubernetes.io/master",
                                            "operator": "DoesNotExist",
                                        }
                                    ]
                                }
                            ]
                        }
                    }
                }
            ),
        )
    ]
    res.jobs = [make_fake_job("pi", "default", 1, "100m", "100Mi")]
    res.pods = [
        make_fake_pod(
            "single-pod",
            "simple",
            "100m",
            "100Mi",
            with_pod_node_selector({"node-role.kubernetes.io/master": ""}),
            with_pod_tolerations(
                [
                    {
                        "effect": "NoSchedule",
                        "key": "node-role.kubernetes.io/master",
                        "operator": "Exists",
                    }
                ]
            ),
        )
    ]
    res.stateful_sets = [
        make_fake_stateful_set(
            "busybox-sts",
            "simple",
            4,
            "1",
            "512Mi",
            with_template_tolerations(
                [
                    {
                        "effect": "NoSchedule",
                        "key": "node-role.kubernetes.io/master",
                        "operator": "Exists",
                    }
                ]
            ),
            with_template_affinity(
                {
                    "podAntiAffinity": {
                        "preferredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "weight": 100,
                                "podAffinityTerm": {
                                    "labelSelector": {
                                        "matchExpressions": [
                                            {
                                                "key": "app",
                                                "operator": "In",
                                                "values": ["busybox-sts"],
                                            }
                                        ]
                                    },
                                    "topologyKey": "kubernetes.io/hostname",
                                },
                            }
                        ]
                    }
                }
            ),
        )
    ]
    res.replica_sets = [
        make_fake_replica_set(
            "calico-kube-controllers",
            "kube-system",
            2,
            "",
            "",
            with_template_tolerations(
                [
                    {"effect": "NoSchedule", "operator": "Exists"},
                    {"key": "CriticalAddonsOnly", "operator": "Exists"},
                    {"effect": "NoExecute", "operator": "Exists"},
                ]
            ),
        )
    ]
    return AppResource(name="simple", resource=res)


def check_result(cluster, apps, result, expect_failed=0):
    """Port of checkResult (`core_test.go:364-591`)."""
    assert len(result.unscheduled_pods) == expect_failed, [
        u.reason for u in result.unscheduled_pods
    ]

    all_pods = [p for st in result.node_status for p in st.pods]
    all_pods += [u.pod for u in result.unscheduled_pods]

    expected = {}
    actual = defaultdict(int)

    def workloads(field, kind, count_of):
        items = list(getattr(cluster, field))
        for app in apps:
            items += getattr(app.resource, field)
        for item in items:
            key = (name_of(item), namespace_of(item), kind)
            expected[key] = count_of(item)
            actual[key] = 0

    workloads("deployments", "Deployment", lambda d: d["spec"].get("replicas", 1))
    workloads("replica_sets", "ReplicaSet", lambda r: r["spec"].get("replicas", 1))
    workloads("stateful_sets", "StatefulSet", lambda s: s["spec"].get("replicas", 1))
    workloads("jobs", "Job", lambda j: j["spec"].get("completions", 1))
    workloads(
        "cron_jobs",
        "CronJob",
        lambda c: c["spec"]["jobTemplate"]["spec"].get("completions", 1),
    )

    nodes = list(cluster.nodes)
    ds_items = list(cluster.daemon_sets)
    for app in apps:
        ds_items += app.resource.daemon_sets
    for ds in ds_items:
        key = (name_of(ds), namespace_of(ds), "DaemonSet")
        expected[key] = sum(
            1 for node in nodes if node_should_run_pod(node, new_daemon_pod(ds, name_of(node)))
        )
        actual[key] = 0

    individual = len(cluster.pods) + sum(len(a.resource.pods) for a in apps)
    got_individual = 0

    for pod in all_pods:
        refs = (pod.get("metadata") or {}).get("ownerReferences") or []
        if not refs:
            got_individual += 1
            continue
        ref = refs[0]
        ns = namespace_of(pod)
        kind, rname = ref["kind"], ref["name"]
        if kind == "ReplicaSet":
            if (rname, ns, "ReplicaSet") in expected:
                actual[(rname, ns, "ReplicaSet")] += 1
            else:  # deployment-owned: strip the hash suffix
                dname = rname.rsplit("-", 1)[0]
                actual[(dname, ns, "Deployment")] += 1
        elif kind == "Job":
            if (rname, ns, "Job") in expected:
                actual[(rname, ns, "Job")] += 1
            else:
                cname = rname.rsplit("-", 1)[0]
                actual[(cname, ns, "CronJob")] += 1
        elif kind in ("StatefulSet", "DaemonSet"):
            actual[(rname, ns, kind)] += 1

    assert dict(actual) == expected
    assert got_individual == individual


@pytest.fixture(autouse=True)
def _seed():
    seed_name_hashes(7)


class TestSimulate:
    def test_simple_scenario(self):
        cluster = build_cluster()
        apps = [build_simple_app()]
        result = simulate(cluster, apps)
        check_result(cluster, apps, result, expect_failed=0)

    def test_pod_placements_respect_constraints(self):
        cluster = build_cluster()
        apps = [build_simple_app()]
        result = simulate(cluster, apps)
        placements = {}
        for st in result.node_status:
            for pod in st.pods:
                placements[name_of(pod)] = name_of(st.node)
        # single-pod has a master nodeSelector + toleration
        assert placements["single-pod"].startswith("master")
        for st in result.node_status:
            for pod in st.pods:
                # busybox-ds is pinned off masters by its DoesNotExist affinity
                if annotations_of(pod).get(C.ANNO_WORKLOAD_NAME) == "busybox-ds":
                    assert name_of(st.node) == "worker-1"
                # pi has no toleration → never on the tainted master-1
                if annotations_of(pod).get(C.ANNO_WORKLOAD_NAME) == "pi":
                    assert name_of(st.node) != "master-1"

    def test_sts_preferred_anti_affinity_spreads(self):
        """A labeled STS with preferred hostname anti-affinity should spread
        its replicas across distinct nodes when capacity allows."""
        cluster = ResourceTypes()
        cluster.nodes = [
            make_fake_node(f"n{i}", "8", "16Gi", with_node_labels(_node_labels(f"n{i}", "worker")))
            for i in range(4)
        ]
        sts = make_fake_stateful_set("web", "default", 4, "500m", "256Mi")
        sts["metadata"]["labels"] = {"app": "web"}
        sts["spec"]["template"]["spec"]["affinity"] = {
            "podAntiAffinity": {
                "preferredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "weight": 100,
                        "podAffinityTerm": {
                            "labelSelector": {"matchLabels": {"app": "web"}},
                            "topologyKey": "kubernetes.io/hostname",
                        },
                    }
                ]
            }
        }
        res = ResourceTypes()
        res.stateful_sets = [sts]
        result = simulate(cluster, [AppResource(name="sts", resource=res)])
        assert not result.unscheduled_pods
        nodes_used = {
            name_of(st.node) for st in result.node_status for p in st.pods
        }
        assert len(nodes_used) == 4

    def test_required_anti_affinity_blocks_colocation(self):
        cluster = ResourceTypes()
        cluster.nodes = [
            make_fake_node(f"n{i}", "8", "16Gi", with_node_labels(_node_labels(f"n{i}", "worker")))
            for i in range(2)
        ]
        deploy = make_fake_deployment("web", "default", 3, "100m", "100Mi")
        deploy["metadata"]["labels"] = {"app": "web"}
        deploy["spec"]["template"]["spec"]["affinity"] = {
            "podAntiAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"app": "web"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
        res = ResourceTypes()
        res.deployments = [deploy]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        # 2 nodes, 3 replicas mutually exclusive per hostname → 1 fails
        assert len(result.unscheduled_pods) == 1
        assert "anti-affinity" in result.unscheduled_pods[0].reason

    def test_pin_to_nonexistent_node_is_unschedulable(self):
        cluster = ResourceTypes()
        cluster.nodes = [make_fake_node("n0", "8", "16Gi")]
        res = ResourceTypes()
        pod = make_fake_pod("ghost-pinned", "default", "100m", "100Mi")
        pod["spec"]["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {
                            "matchFields": [
                                {
                                    "key": "metadata.name",
                                    "operator": "In",
                                    "values": ["ghost-node"],
                                }
                            ]
                        }
                    ]
                }
            }
        }
        res.pods = [pod]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert len(result.unscheduled_pods) == 1

    def test_pure_pin_term_does_not_tighten_sibling_terms(self):
        # OR semantics: a second term that is pure pin makes the pin alone
        # sufficient, regardless of the first term's expressions
        cluster = ResourceTypes()
        cluster.nodes = [make_fake_node("n1", "8", "16Gi")]
        res = ResourceTypes()
        pod = make_fake_pod("orpin", "default", "100m", "100Mi")
        pod["spec"]["affinity"] = {
            "nodeAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {
                            "matchExpressions": [
                                {"key": "nonexistent-label", "operator": "Exists"}
                            ],
                            "matchFields": [
                                {
                                    "key": "metadata.name",
                                    "operator": "In",
                                    "values": ["n1"],
                                }
                            ],
                        },
                        {
                            "matchFields": [
                                {
                                    "key": "metadata.name",
                                    "operator": "In",
                                    "values": ["n1"],
                                }
                            ]
                        },
                    ]
                }
            }
        }
        res.pods = [pod]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert not result.unscheduled_pods

    def test_required_affinity_colocates(self):
        cluster = ResourceTypes()
        cluster.nodes = [
            make_fake_node(f"n{i}", "8", "16Gi", with_node_labels(_node_labels(f"n{i}", "worker")))
            for i in range(3)
        ]
        backend = make_fake_deployment("backend", "default", 1, "100m", "100Mi")
        backend["metadata"]["labels"] = {"tier": "backend"}
        frontend = make_fake_deployment("frontend", "default", 2, "100m", "100Mi")
        frontend["metadata"]["labels"] = {"tier": "frontend"}
        frontend["spec"]["template"]["spec"]["affinity"] = {
            "podAffinity": {
                "requiredDuringSchedulingIgnoredDuringExecution": [
                    {
                        "labelSelector": {"matchLabels": {"tier": "backend"}},
                        "topologyKey": "kubernetes.io/hostname",
                    }
                ]
            }
        }
        res = ResourceTypes()
        res.deployments = [backend, frontend]
        result = simulate(cluster, [AppResource(name="app", resource=res)])
        assert not result.unscheduled_pods
        placements = {}
        for st in result.node_status:
            for pod in st.pods:
                placements[name_of(pod)] = name_of(st.node)
        backend_nodes = {
            n for p, n in placements.items() if p.startswith("backend")
        }
        frontend_nodes = {
            n for p, n in placements.items() if p.startswith("frontend")
        }
        assert frontend_nodes == backend_nodes


class TestSchedulerNameGating:
    """Only `default-scheduler` pods enter the simulation — the reference's
    pod informer filters on SchedulerName (`pkg/simulator/simulator.go:100-104`),
    so a foreign-scheduler pod is neither placed nor reported unschedulable."""

    def test_foreign_scheduler_pod_excluded(self):
        from .fixtures import make_fake_node, make_fake_pod

        nodes = [make_fake_node("n0", "8", "16Gi")]
        ours = make_fake_pod("ours", "default", "1", "1Gi")
        foreign = make_fake_pod("foreign", "default", "1", "1Gi")
        foreign["spec"]["schedulerName"] = "volcano"
        result = simulate(
            ResourceTypes(nodes=nodes, pods=[ours, foreign]), []
        )
        placed = {name_of(p) for st in result.node_status for p in st.pods}
        assert placed == {"ours"}
        assert not result.unscheduled_pods

    def test_bound_foreign_pod_still_occupies_capacity(self):
        # a pod already bound via spec.nodeName consumes node resources
        # regardless of schedulerName — the reference creates bound pods in
        # the fake cluster unconditionally; only the event handler is filtered
        from .fixtures import make_fake_node, make_fake_pod

        nodes = [make_fake_node("n0", "8", "16Gi")]
        bound = make_fake_pod("bound", "default", "6", "1Gi")
        bound["spec"]["schedulerName"] = "volcano"
        bound["spec"]["nodeName"] = "n0"
        big = make_fake_pod("big", "default", "6", "1Gi")
        result = simulate(ResourceTypes(nodes=nodes, pods=[bound, big]), [])
        placed = {name_of(p) for st in result.node_status for p in st.pods}
        assert "bound" in placed
        # only 2 CPU remain after the bound pod — "big" must fail
        assert [name_of(u.pod) for u in result.unscheduled_pods] == ["big"]

    def test_empty_scheduler_name_defaults_to_ours(self):
        from .fixtures import make_fake_node, make_fake_pod

        nodes = [make_fake_node("n0", "8", "16Gi")]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["schedulerName"] = ""
        result = simulate(ResourceTypes(nodes=nodes, pods=[pod]), [])
        placed = {name_of(p) for st in result.node_status for p in st.pods}
        assert placed == {"p0"}

    def test_null_scheduler_name_defaults_to_ours(self):
        # YAML `schedulerName: null` unmarshals to "" in Go — treated as ours
        from .fixtures import make_fake_node, make_fake_pod

        nodes = [make_fake_node("n0", "8", "16Gi")]
        pod = make_fake_pod("p0", "default", "1", "1Gi")
        pod["spec"]["schedulerName"] = None
        result = simulate(ResourceTypes(nodes=nodes, pods=[pod]), [])
        placed = {name_of(p) for st in result.node_status for p in st.pods}
        assert placed == {"p0"}


def test_state_reuse_rebuilds_when_term_becomes_interpod():
    """A second batch can mark an ALREADY-interned term as interpod-used
    (same topologyKey/namespace/selector in a required podAntiAffinity);
    n_terms is unchanged but the compacted own planes reshape, so the carried
    state must be rebuilt, not reused."""
    from simtpu.core.tensorize import Tensorizer
    from simtpu.engine.scan import Engine
    from .fixtures import make_fake_node, make_fake_pod, with_node_labels

    nodes = [
        make_fake_node(
            f"n{i}",
            "8",
            "16Gi",
            with_node_labels({"topology.kubernetes.io/zone": f"z{i}"}),
        )
        for i in range(2)
    ]
    tz = Tensorizer(nodes)
    eng = Engine(tz)

    spread_pod = make_fake_pod("sp", "default", "1", "1Gi")
    spread_pod["metadata"]["labels"] = {"app": "web"}
    spread_pod["spec"]["topologySpreadConstraints"] = [
        {
            "maxSkew": 1,
            "topologyKey": "topology.kubernetes.io/zone",
            "whenUnsatisfiable": "ScheduleAnyway",
            "labelSelector": {"matchLabels": {"app": "web"}},
        }
    ]
    nodes_out, _, _ = eng.place(tz.add_pods([spread_pod]))
    assert nodes_out[0] >= 0

    anti_pod = make_fake_pod("ap", "default", "1", "1Gi")
    anti_pod["metadata"]["labels"] = {"app": "web"}
    anti_pod["spec"]["affinity"] = {
        "podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [
                {
                    "labelSelector": {"matchLabels": {"app": "web"}},
                    "topologyKey": "topology.kubernetes.io/zone",
                }
            ]
        }
    }
    nodes_out, _, _ = eng.place(tz.add_pods([anti_pod]))
    # the anti pod must land in the OTHER zone (the spread pod's zone is
    # excluded by its own required anti-affinity against app=web)
    assert nodes_out[0] >= 0
