"""Durable-execution tests (simtpu/durable, ISSUE 6).

The load-bearing pins:

- kill/resume: a plan interrupted mid-bisection and resumed from its
  checkpoint yields a PlanResult — placements, node count, message —
  bit-identical to the uninterrupted checkpointed run, while actually
  replaying records (fewer live simulations), for BOTH the serial and the
  incremental planner;
- OOM backoff: an injected RESOURCE_EXHAUSTED on the first N dispatches
  triggers chunk-halving replays that converge to bit-identical
  placements on the serial scan, the bulk rounds engine, and the fault
  sweep, with the events recorded in the `backoff.*` registry counters;
- deadline/SIGINT: the run exits with a structured `partial=True` result
  and a flushed checkpoint — never an unhandled traceback — and the CLI
  maps it to the documented exit code 3;
- a config/cluster fingerprint mismatch refuses to resume, loudly;
- structured ingest diagnostics: a malformed spec surfaces as ONE
  actionable SpecError line naming the source file, workload, and field
  path instead of a raw ValueError mid-tensorize.
"""

from __future__ import annotations

import json
import os
import signal

import numpy as np
import pytest

from simtpu import AppResource, ResourceTypes
from simtpu.obs.metrics import family as _metrics_family


def backoff_counts():
    # registry-backed backoff counters (the alias view is gone)
    from simtpu.durable.backoff import BACKOFF_KEYS

    return _metrics_family("backoff", BACKOFF_KEYS)


from simtpu.durable import (
    CheckpointMismatch,
    PlanCheckpoint,
    PlanInterrupted,
    RunControl,
    plan_fingerprint,
)
from simtpu.plan.capacity import plan_capacity
from simtpu.plan.incremental import plan_capacity_incremental
from simtpu.synth import make_node, synth_apps, synth_cluster

from .fixtures import make_fake_deployment, make_fake_node

OOM_MSG = "RESOURCE_EXHAUSTED: out of memory allocating (injected)"


def _small_problem():
    """One undersized base node + an app needing ~3 template clones: the
    binary search runs a real doubling + bisection (candidates 0, 1, 2,
    4, 3) — enough boundaries to interrupt between."""
    cluster = ResourceTypes()
    cluster.nodes = [make_fake_node("base-1", "4", "8Gi")]
    apps = [
        AppResource(
            name="app",
            resource=ResourceTypes(
                deployments=[
                    make_fake_deployment("web", "default", 7, "2", "4Gi")
                ]
            ),
        )
    ]
    template = make_fake_node("template", "4", "8Gi")
    return cluster, apps, template


def _placements(plan):
    """Canonical {node: sorted pod names} view of a PlanResult — pod
    names INCLUDED: checkpointed runs pin the suffix stream, so resumed
    results must match to the name."""
    return {
        s.node["metadata"]["name"]: sorted(
            p["metadata"]["name"] for p in s.pods
        )
        for s in plan.result.node_status
    }


class _Budget(RunControl):
    """RunControl that interrupts after `n` candidate-boundary checks —
    the deterministic stand-in for a kill mid-bisection."""

    def __init__(self, n: int):
        super().__init__()
        self.n = n

    def check(self) -> None:
        self.n -= 1
        if self.n < 0:
            raise PlanInterrupted("test budget")
        super().check()


class TestKillResume:
    def test_serial_kill_mid_bisection_resume_bit_identical(
        self, tmp_path, monkeypatch
    ):
        cluster, apps, template = _small_problem()
        fp = plan_fingerprint(cluster, apps, template, extra={})

        sims = [0]
        import simtpu.plan.capacity as cap

        real_sim = cap.simulate

        def counting_sim(*a, **kw):
            sims[0] += 1
            return real_sim(*a, **kw)

        monkeypatch.setattr(cap, "simulate", counting_sim)

        # uninterrupted checkpointed run — the reference answer
        ck_a = PlanCheckpoint(str(tmp_path / "a"), kind="binary", fingerprint=fp)
        full = plan_capacity(cluster, apps, template, checkpoint=ck_a)
        assert full.success and not full.partial
        sims_full = sims[0]

        # killed mid-bisection: interrupt after two completed candidates
        ck_b = PlanCheckpoint(str(tmp_path / "b"), kind="binary", fingerprint=fp)
        part = plan_capacity(
            cluster, apps, template, checkpoint=ck_b, control=_Budget(2)
        )
        assert part.partial and not part.success
        assert "interrupted" in part.message
        assert len(ck_b) == 2  # exactly the completed candidates persisted
        assert os.path.isfile(tmp_path / "b" / "manifest.json")

        # resume: recorded candidates replay, the rest run live
        sims[0] = 0
        ck_r = PlanCheckpoint(
            str(tmp_path / "b"), kind="binary", fingerprint=fp, resume=True
        )
        resumed = plan_capacity(cluster, apps, template, checkpoint=ck_r)
        assert sims[0] < sims_full  # replay really skipped simulations

        assert resumed.success and not resumed.partial
        assert resumed.nodes_added == full.nodes_added
        assert resumed.message == full.message
        assert resumed.probes == full.probes
        assert _placements(resumed) == _placements(full)
        assert [
            u.pod["metadata"]["name"] for u in resumed.result.unscheduled_pods
        ] == [u.pod["metadata"]["name"] for u in full.result.unscheduled_pods]

    def test_incremental_kill_resume_bit_identical(self, tmp_path):
        cluster = ResourceTypes()
        cluster.nodes = [
            make_node(
                f"node-{i}",
                8000,
                16,
                {
                    "topology.kubernetes.io/zone": f"zone-{i % 2}",
                    "kubernetes.io/hostname": f"node-{i}",
                },
            )
            for i in range(3)
        ]
        apps = synth_apps(
            60, seed=7, zones=2, pods_per_deployment=10,
            anti_affinity_frac=0.2, spread_frac=0.3,
        )
        template = make_node(
            "tmpl", 16000, 64,
            {"kubernetes.io/hostname": "tmpl",
             "topology.kubernetes.io/zone": "zone-0"},
        )
        fp = plan_fingerprint(cluster, apps, template, extra={})

        ck_a = PlanCheckpoint(
            str(tmp_path / "a"), kind="incremental", fingerprint=fp
        )
        full = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=30, checkpoint=ck_a
        )
        assert full.success and not full.partial

        # kill after the base + one probe completed
        ck_b = PlanCheckpoint(
            str(tmp_path / "b"), kind="incremental", fingerprint=fp
        )
        part = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=30,
            checkpoint=ck_b, control=_Budget(2),
        )
        assert part.partial and not part.success
        assert len(ck_b) >= 1

        ck_r = PlanCheckpoint(
            str(tmp_path / "b"), kind="incremental", fingerprint=fp,
            resume=True,
        )
        resumed = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=30, checkpoint=ck_r
        )
        assert resumed.success
        assert resumed.nodes_added == full.nodes_added
        assert resumed.probes == full.probes
        assert _placements(resumed) == _placements(full)

    def test_fingerprint_mismatch_refuses(self, tmp_path):
        cluster, apps, template = _small_problem()
        fp = plan_fingerprint(cluster, apps, template, extra={})
        PlanCheckpoint(str(tmp_path), kind="binary", fingerprint=fp).put(
            "cand", 0, feasible=False, unscheduled=3, cap_rejected=False,
            message="",
        )
        # a different problem (one more replica) → different fingerprint
        cluster2, apps2, template2 = _small_problem()
        apps2[0].resource.deployments[0]["spec"]["replicas"] = 9
        fp2 = plan_fingerprint(cluster2, apps2, template2, extra={})
        assert fp2 != fp
        with pytest.raises(CheckpointMismatch, match="fingerprint"):
            PlanCheckpoint(
                str(tmp_path), kind="binary", fingerprint=fp2, resume=True
            )
        # same problem, different planner kind → refuses too
        with pytest.raises(CheckpointMismatch, match="planner"):
            PlanCheckpoint(
                str(tmp_path), kind="incremental", fingerprint=fp, resume=True
            )

    def test_fingerprint_ignores_source_stamp(self, tmp_path):
        """The fingerprint identifies the PROBLEM, not the path to it:
        the YAML loader's per-object source-file stamp must not split
        otherwise-identical problems (relative vs absolute -f paths)."""
        from simtpu.workloads.expand import SOURCE_KEY

        cluster, apps, template = _small_problem()
        bare = plan_fingerprint(cluster, apps, template, extra={})
        for node in cluster.nodes:
            node[SOURCE_KEY] = "/some/abs/path/cluster.yaml"
        for dep in apps[0].resource.deployments:
            dep[SOURCE_KEY] = "relative/app.yaml"
        stamped = plan_fingerprint(cluster, apps, template, extra={})
        assert stamped == bare

    def test_file_digest_tracks_content(self, tmp_path):
        """Fingerprint extras hash config CONTENT: editing the file
        between a kill and a --resume changes the digest even though the
        path is unchanged."""
        from simtpu.durable.checkpoint import file_digest

        assert file_digest("") == ""
        assert file_digest(None) == ""
        p = tmp_path / "sched.yaml"
        p.write_text("weights: {a: 1}\n")
        d1 = file_digest(str(p))
        p.write_text("weights: {a: 2}\n")
        assert file_digest(str(p)) != d1

    def test_resume_without_manifest_refuses(self, tmp_path):
        with pytest.raises(CheckpointMismatch, match="no checkpoint"):
            PlanCheckpoint(
                str(tmp_path / "void"), kind="binary", fingerprint="x",
                resume=True,
            )

    def test_version_mismatch_refuses(self, tmp_path):
        ck = PlanCheckpoint(str(tmp_path), kind="binary", fingerprint="f")
        ck.put("cand", 0, feasible=True, unscheduled=0, cap_rejected=False,
               message="")
        man = json.loads((tmp_path / "manifest.json").read_text())
        man["version"] = 999
        (tmp_path / "manifest.json").write_text(json.dumps(man))
        with pytest.raises(CheckpointMismatch, match="v999"):
            PlanCheckpoint(
                str(tmp_path), kind="binary", fingerprint="f", resume=True
            )


def _engine_problem(n_nodes=24, n_pods=48):
    cluster = synth_cluster(n_nodes, seed=31, zones=3, gpu_frac=0.2,
                            storage_frac=0.2)
    apps = synth_apps(
        n_pods, seed=32, zones=3, pods_per_deployment=8,
        anti_affinity_frac=0.2, spread_frac=0.3, gpu_frac=0.1,
        storage_frac=0.1,
    )
    from simtpu.workloads.expand import get_valid_pods_exclude_daemonset

    pods = []
    for a in apps:
        pods.extend(get_valid_pods_exclude_daemonset(a.resource))
    return cluster, pods


def _place(engine_cls, cluster, pods):
    from simtpu.core.tensorize import Tensorizer

    tz = Tensorizer(cluster.nodes, storage_classes=cluster.storage_classes)
    eng = engine_cls(tz)
    # pin the dispatch path under test: wavefront speculation routes lean
    # runs through _wave_call, which would starve the injected _scan_call
    eng.speculate = False
    nodes, reasons, _ = eng.place(tz.add_pods(pods))
    return np.asarray(nodes), np.asarray(reasons)


class _FailFirst:
    """Wrap a dispatch callable: the first `n` calls raise an injected
    RESOURCE_EXHAUSTED (before the real dispatch runs — the launch-setup
    failure shape, donated buffers intact), later calls pass through."""

    def __init__(self, real, n):
        self.real = real
        self.n = n
        self.calls = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls <= self.n:
            raise RuntimeError(OOM_MSG)
        return self.real(*args, **kwargs)


class TestBackoff:
    def test_scan_backoff_bit_identical(self, monkeypatch):
        from simtpu.engine.scan import Engine

        cluster, pods = _engine_problem()
        clean_nodes, clean_reasons = _place(Engine, cluster, pods)

        fake = _FailFirst(Engine._scan_call, 2)
        monkeypatch.setattr(
            Engine, "_scan_call", lambda self, *a: fake(self, *a)
        )
        before = backoff_counts()
        oom_nodes, oom_reasons = _place(Engine, cluster, pods)
        after = backoff_counts()

        assert fake.calls > 2  # the replays really re-dispatched
        assert after["events"] - before["events"] >= 1
        assert after["splits"] - before["splits"] >= 2
        assert after["chunk_min"] >= 1
        assert np.array_equal(oom_nodes, clean_nodes)
        assert np.array_equal(oom_reasons, clean_reasons)

    def test_rounds_backoff_bit_identical(self, monkeypatch):
        from simtpu.engine.rounds import RoundsEngine

        cluster, pods = _engine_problem()
        clean_nodes, clean_reasons = _place(RoundsEngine, cluster, pods)

        fake = _FailFirst(RoundsEngine._dispatch_bulk_chunk, 1)
        monkeypatch.setattr(
            RoundsEngine,
            "_dispatch_bulk_chunk",
            lambda self, *a: fake(self, *a),
        )
        before = backoff_counts()
        oom_nodes, oom_reasons = _place(RoundsEngine, cluster, pods)
        after = backoff_counts()

        assert fake.calls > 1
        assert after["events"] - before["events"] >= 1
        assert np.array_equal(oom_nodes, clean_nodes)
        assert np.array_equal(oom_reasons, clean_reasons)

    # s_chunk=5 is the odd-span regression: the halving must requeue
    # blocks whose SPAN fits the pad (a naive head/tail split would
    # overflow gather_block's arrays and crash the recovery path)
    @pytest.mark.parametrize("s_chunk", [8, 5])
    def test_sweep_backoff_identical_and_counted(self, monkeypatch, s_chunk):
        from simtpu.faults import (
            generate_scenarios,
            place_cluster,
            sweep_scenarios,
        )

        cluster = synth_cluster(10, seed=21, zones=3)
        apps = synth_apps(40, seed=22, zones=3, pods_per_deployment=10)
        pc = place_cluster(cluster, apps)
        scen = generate_scenarios(cluster.nodes, "k=1")
        clean = sweep_scenarios(pc, scen, s_chunk=s_chunk)

        import simtpu.faults.sweep as sweep_mod

        fake = _FailFirst(sweep_mod._fault_sweep, 1)
        monkeypatch.setattr(sweep_mod, "_fault_sweep", fake)
        before = backoff_counts()
        oom = sweep_scenarios(pc, scen, s_chunk=s_chunk)
        after = backoff_counts()

        assert fake.calls > 1
        assert after["events"] - before["events"] >= 1
        assert oom.timings.get("backoff_events", 0) >= 1
        assert np.array_equal(oom.requeue_rows, clean.requeue_rows)
        assert np.array_equal(oom.requeue_nodes, clean.requeue_nodes)
        assert np.array_equal(oom.requeue_reasons, clean.requeue_reasons)

    def test_non_oom_error_propagates(self, monkeypatch):
        """Backoff must catch ONLY allocator failures — an unrelated
        dispatch error still surfaces."""
        from simtpu.engine.scan import Engine

        cluster, pods = _engine_problem(n_nodes=8, n_pods=16)

        def boom(self, *a, **kw):
            raise RuntimeError("unrelated kernel failure")

        monkeypatch.setattr(Engine, "_scan_call", boom)
        with pytest.raises(RuntimeError, match="unrelated"):
            _place(Engine, cluster, pods)

    def test_single_pod_oom_propagates(self, monkeypatch):
        """A segment that cannot shrink (one pod) propagates the
        allocator failure instead of looping."""
        from simtpu.engine.scan import Engine

        cluster, pods = _engine_problem(n_nodes=8, n_pods=16)

        def always_oom(self, *a, **kw):
            raise RuntimeError(OOM_MSG)

        monkeypatch.setattr(Engine, "_scan_call", always_oom)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            _place(Engine, cluster, pods)


class TestDeadlineInterrupt:
    def test_deadline_zero_yields_partial(self, tmp_path):
        cluster, apps, template = _small_problem()
        fp = plan_fingerprint(cluster, apps, template, extra={})
        ck = PlanCheckpoint(str(tmp_path), kind="binary", fingerprint=fp)
        plan = plan_capacity(
            cluster, apps, template,
            checkpoint=ck, control=RunControl(deadline=0.0),
        )
        assert plan.partial and not plan.success
        assert plan.nodes_added == -1  # nothing verified yet
        assert "deadline" in plan.message
        assert os.path.isfile(tmp_path / "manifest.json")  # flushed

    def test_interrupt_after_feasible_reports_best(self):
        """An interrupt AFTER a feasible candidate completed reports that
        candidate as the structured partial answer."""
        cluster, apps, template = _small_problem()
        # enough budget for 0 (fail), 1 (fail), 2 (fail), 4 (feasible);
        # the interrupt lands mid-bisection
        plan = plan_capacity(cluster, apps, template, control=_Budget(4))
        assert plan.partial and not plan.success
        assert plan.nodes_added == 4
        assert "best candidate so far: 4" in plan.message

    def test_sigint_flags_control_then_kills(self):
        ctrl = RunControl()
        prev = signal.getsignal(signal.SIGINT)
        with ctrl.sigint():
            os.kill(os.getpid(), signal.SIGINT)
            # delivered synchronously on the main thread: the handler
            # flagged the control instead of raising KeyboardInterrupt
            assert ctrl.interrupted == "SIGINT"
            with pytest.raises(PlanInterrupted, match="SIGINT"):
                ctrl.check()
            # second ^C = the default KeyboardInterrupt (stuck-run escape)
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGINT)
        # handler restored on exit
        assert signal.getsignal(signal.SIGINT) == prev

    def test_incremental_deadline_partial(self):
        cluster, apps, template = _small_problem()
        plan = plan_capacity_incremental(
            cluster, apps, template, max_new_nodes=8,
            control=RunControl(deadline=0.0),
        )
        assert plan.partial and not plan.success
        assert "deadline" in plan.message


class TestCLIDurable:
    def test_apply_deadline_json_partial_exit_3(self, tmp_path, capsys):
        from simtpu.cli import EXIT_PARTIAL, main

        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml", "--json",
            "--deadline", "0", "--checkpoint", str(tmp_path / "ck"),
        ])
        out = capsys.readouterr().out
        doc = json.loads(out)
        assert rc == EXIT_PARTIAL
        assert doc["partial"] is True
        assert doc["success"] is False
        # backoff telemetry rides the engine block on every run
        assert doc["engine"]["backoff"]["events"] >= 0
        # the final checkpoint flushed before exit
        assert os.path.isfile(tmp_path / "ck" / "manifest.json")

    def test_resume_without_checkpoint_dir_one_line(self, capsys):
        from simtpu.cli import main

        rc = main(["apply", "-f", "examples/simtpu-config.yaml", "--resume"])
        err = capsys.readouterr().err
        assert rc == 1
        assert "--resume requires --checkpoint" in err
        assert "Traceback" not in err

    def test_resume_mismatch_one_line(self, tmp_path, capsys):
        from simtpu.cli import main

        ck = tmp_path / "ck"
        # a manifest from a DIFFERENT problem
        PlanCheckpoint(str(ck), kind="binary", fingerprint="deadbeef")
        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml",
            "--checkpoint", str(ck), "--resume",
        ])
        err = capsys.readouterr().err
        assert rc == 1
        assert "refusing to resume" in err
        assert "Traceback" not in err


class TestCheckpointErrors:
    """Unusable checkpoint paths/records fail UP FRONT as one actionable
    `CheckpointError` line — never a mid-plan OSError/zipfile traceback
    (ISSUE 7 satellite)."""

    def test_checkpoint_path_is_a_file_refuses(self, tmp_path):
        from simtpu.durable import CheckpointError

        f = tmp_path / "not-a-dir"
        f.write_text("x")
        with pytest.raises(CheckpointError, match="not a directory"):
            PlanCheckpoint(str(f), kind="binary", fingerprint="fp")

    def test_checkpoint_path_uncreatable_refuses(self):
        from simtpu.durable import CheckpointError

        with pytest.raises(CheckpointError, match="cannot create"):
            PlanCheckpoint(
                "/dev/null/sub", kind="binary", fingerprint="fp"
            )

    def test_resume_empty_manifest_refuses(self, tmp_path):
        from simtpu.durable import CheckpointError

        ck = tmp_path / "ck"
        ck.mkdir()
        (ck / "manifest.json").write_text("")
        with pytest.raises(CheckpointError, match="empty or corrupt"):
            PlanCheckpoint(
                str(ck), kind="binary", fingerprint="fp", resume=True
            )
        # the message is one line, actionable
        try:
            PlanCheckpoint(
                str(ck), kind="binary", fingerprint="fp", resume=True
            )
        except CheckpointError as exc:
            assert "\n" not in str(exc)
            assert "re-run" in str(exc)

    def test_resume_corrupt_record_refuses(self, tmp_path):
        from simtpu.durable import CheckpointError

        ck = tmp_path / "ck"
        wr = PlanCheckpoint(str(ck), kind="binary", fingerprint="fp")
        wr.put("cand", 0, verdict=np.asarray(1))
        # truncate the record to garbage
        rec = ck / "rec_cand_0.npz"
        rec.write_bytes(b"not a zip")
        rd = PlanCheckpoint(
            str(ck), kind="binary", fingerprint="fp", resume=True
        )
        with pytest.raises(CheckpointError, match="empty or corrupt"):
            rd.get("cand", 0)

    def test_cli_checkpoint_file_path_one_line(self, tmp_path, capsys):
        from simtpu.cli import main

        f = tmp_path / "not-a-dir"
        f.write_text("x")
        rc = main([
            "apply", "-f", "examples/simtpu-config.yaml",
            "--checkpoint", str(f),
        ])
        err = capsys.readouterr().err
        assert rc == 1
        assert "not a directory" in err
        assert "Traceback" not in err


class TestFingerprintStrictness:
    """`plan_resilience --resume` with a changed fault model must refuse:
    the sweep verdict records are a function of --fault-seed /
    --fault-samples, so replaying them under different sampling would
    certify a DIFFERENT failure model (ISSUE 7 satellite).  The CLI pins
    spec/samples/seed/quantile into the fingerprint `extra`; these tests
    mirror that construction."""

    def _fp(self, samples, seed, spec="k=1", quantile=1.0):
        cluster, apps, template = _small_problem()
        return plan_fingerprint(
            cluster, apps, template,
            extra={
                "spec": spec,
                "quantile": quantile,
                "samples": samples,
                "seed": seed,
                "max_new_nodes": 8,
                "extended_resources": [],
                "sched_config": "",
            },
        )

    def test_changed_fault_seed_refuses(self, tmp_path):
        ck = tmp_path / "ck"
        PlanCheckpoint(
            str(ck), kind="resilience", fingerprint=self._fp(256, 0)
        )
        with pytest.raises(CheckpointMismatch, match="fingerprint"):
            PlanCheckpoint(
                str(ck), kind="resilience",
                fingerprint=self._fp(256, 1), resume=True,
            )

    def test_changed_fault_samples_refuses(self, tmp_path):
        ck = tmp_path / "ck"
        PlanCheckpoint(
            str(ck), kind="resilience", fingerprint=self._fp(256, 0)
        )
        with pytest.raises(CheckpointMismatch, match="fingerprint"):
            PlanCheckpoint(
                str(ck), kind="resilience",
                fingerprint=self._fp(500, 0), resume=True,
            )

    def test_same_fault_model_resumes(self, tmp_path):
        ck = tmp_path / "ck"
        PlanCheckpoint(
            str(ck), kind="resilience", fingerprint=self._fp(256, 0)
        )
        PlanCheckpoint(
            str(ck), kind="resilience",
            fingerprint=self._fp(256, 0), resume=True,
        )

    def test_cli_resilience_changed_seed_refuses(self, tmp_path, capsys):
        """End-to-end: the resilience CLI's fingerprint really carries the
        fault model — a --resume with a different --seed refuses."""
        from simtpu.cli import main

        ck = tmp_path / "ck"
        args = [
            "resilience", "-f", "examples/simtpu-config.yaml", "--plan",
            "--max-new-nodes", "2", "--checkpoint", str(ck),
        ]
        main(args)  # survivable or not, records + manifest land
        rc = main(args + ["--resume", "--seed", "7"])
        err = capsys.readouterr().err
        assert rc == 1
        assert "refusing to resume" in err
        assert "Traceback" not in err


class TestDuplicateNames:
    """Duplicate workload names within one ingest are a validate-time
    `SpecError` naming BOTH source files; random-suffix collisions on
    GENERATED pod names re-draw deterministically instead of rejecting
    (a birthday certainty at million-pod scale, not a user error)
    (ISSUE 7 satellite)."""

    def test_duplicate_deployments_name_both_files(self):
        from simtpu.workloads.expand import (
            SOURCE_KEY,
            get_valid_pods_exclude_daemonset,
        )
        from simtpu.workloads.validate import SpecError

        res = ResourceTypes()
        d1 = make_fake_deployment("foo", "default", 2, "1", "1Gi")
        d1[SOURCE_KEY] = "apps/a.yaml"
        d2 = make_fake_deployment("foo", "default", 3, "1", "1Gi")
        d2[SOURCE_KEY] = "apps/b.yaml"
        res.deployments = [d1, d2]
        with pytest.raises(SpecError) as ei:
            get_valid_pods_exclude_daemonset(res)
        msg = str(ei.value)
        assert "apps/a.yaml" in msg and "apps/b.yaml" in msg
        assert "duplicate Deployment" in msg
        assert "\n" not in msg

    def test_duplicate_bare_pods_refused(self):
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset
        from simtpu.workloads.validate import SpecError

        from .fixtures import make_fake_pod

        res = ResourceTypes()
        res.pods = [
            make_fake_pod("p", "default", "1", "1Gi"),
            make_fake_pod("p", "default", "1", "1Gi"),
        ]
        with pytest.raises(SpecError, match="duplicate Pod"):
            get_valid_pods_exclude_daemonset(res)

    def test_sts_ordinal_collision_refused_not_redrawn(self):
        """STS ordinal pods CARRY metadata.generateName but are named
        `{name}-{ordinal}` deterministically — a collision with one is a
        spec bug to refuse, never a silent re-draw (renaming would break
        the ordinal identity its volume claims were computed against)."""
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset
        from simtpu.workloads.validate import SpecError

        from .fixtures import make_fake_pod, make_fake_stateful_set

        res = ResourceTypes()
        res.pods = [make_fake_pod("web-0", "default", "1", "1Gi")]
        res.stateful_sets = [
            make_fake_stateful_set("web", "default", 1, "1", "1Gi")
        ]
        with pytest.raises(SpecError, match="pod name collides"):
            get_valid_pods_exclude_daemonset(res)

    def test_generated_collision_redraws_unique(self, monkeypatch):
        from simtpu.workloads import expand

        # force the pod-suffix stream to collide: the first two 5-digit
        # draws are identical, then unique — the expander must re-draw
        # the second pod's name rather than raise or shadow
        draws = iter(["aaaaa", "aaaaa", "bbbbb", "ccccc", "ddddd"])

        def fake_suffix(digits):
            if digits == expand.C.POD_HASH_DIGITS:
                return next(draws)
            return "f" * digits  # workload suffix: one per deployment

        monkeypatch.setattr(expand, "_hash_suffix", fake_suffix)
        res = ResourceTypes()
        res.deployments = [
            make_fake_deployment("web", "default", 3, "1", "1Gi")
        ]
        pods = expand.get_valid_pods_exclude_daemonset(res)
        names = [p["metadata"]["name"] for p in pods]
        assert len(names) == len(set(names)) == 3
        assert sorted(n.rsplit("-", 1)[1] for n in names) == [
            "aaaaa", "bbbbb", "ccccc"
        ]


class TestSpecDiagnostics:
    def test_bad_quantity_reports_field_path(self):
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset
        from simtpu.workloads.validate import SpecError, ValidationError

        res = ResourceTypes()
        dep = make_fake_deployment("web", "default", 2, "2", "4Gi")
        dep["spec"]["template"]["spec"]["containers"][0]["resources"][
            "requests"
        ]["cpu"] = "2xyz"
        res.deployments = [dep]
        with pytest.raises(SpecError) as ei:
            get_valid_pods_exclude_daemonset(res)
        err = ei.value
        assert isinstance(err, ValidationError)  # back-compat: callers
        assert err.kind == "Deployment"
        assert err.name == "default/web"
        assert err.field == "spec.containers[0].resources.requests.cpu"
        assert "2xyz" in err.reason
        assert "\n" not in str(err)  # one line, actionable

    def test_negative_quantity_reports_field_path(self):
        from simtpu.workloads.validate import SpecError, validate_pod

        from .fixtures import make_fake_pod

        pod = make_fake_pod("p", "default", "2", "4Gi")
        pod["spec"]["containers"][0]["resources"]["requests"]["memory"] = (
            "-1Gi"
        )
        with pytest.raises(SpecError) as ei:
            validate_pod(pod)
        assert ei.value.field == "spec.containers[0].resources.requests.memory"

    def test_yaml_source_rides_into_the_error(self, tmp_path):
        from simtpu.io.yaml_loader import (
            get_objects_from_yaml_content,
            get_yaml_content_from_directory,
        )
        from simtpu.workloads.expand import get_valid_pods_exclude_daemonset
        from simtpu.workloads.validate import SpecError

        bad = tmp_path / "web.yaml"
        bad.write_text(
            "apiVersion: apps/v1\n"
            "kind: Deployment\n"
            "metadata: {name: web, namespace: default}\n"
            "spec:\n"
            "  replicas: 1\n"
            "  template:\n"
            "    spec:\n"
            "      containers:\n"
            "        - name: c\n"
            "          image: nginx\n"
            "          resources: {requests: {cpu: 1stone}}\n"
        )
        docs = get_yaml_content_from_directory(str(tmp_path))
        resources = get_objects_from_yaml_content(docs)
        with pytest.raises(SpecError) as ei:
            get_valid_pods_exclude_daemonset(resources)
        msg = str(ei.value)
        assert str(bad) in msg
        assert "Deployment default/web" in msg
        assert "1stone" in msg
        assert "\n" not in msg

    def test_source_key_stripped_from_pods(self, tmp_path):
        from simtpu.io.yaml_loader import (
            get_objects_from_yaml_content,
            get_yaml_content_from_directory,
        )
        from simtpu.workloads.expand import (
            SOURCE_KEY,
            get_valid_pods_exclude_daemonset,
        )

        ok = tmp_path / "ok.yaml"
        ok.write_text(
            "apiVersion: apps/v1\n"
            "kind: Deployment\n"
            "metadata: {name: web, namespace: default}\n"
            "spec:\n"
            "  replicas: 2\n"
            "  template:\n"
            "    spec:\n"
            "      containers:\n"
            "        - name: c\n"
            "          image: nginx\n"
            "          resources: {requests: {cpu: 1}}\n"
        )
        docs = get_yaml_content_from_directory(str(tmp_path))
        resources = get_objects_from_yaml_content(docs)
        pods = get_valid_pods_exclude_daemonset(resources)
        assert len(pods) == 2
        assert all(SOURCE_KEY not in p for p in pods)


class TestSigterm:
    """SIGTERM gets the same first-signal grace as ^C (ISSUE 14
    satellite): daemons, `timeout(1)`, and CI runners send SIGTERM where
    a human sends SIGINT — it must yield the cooperative partial (exit
    3), not kill the process with no checkpoint and no flight bundle."""

    def test_sigterm_flags_control_then_kills(self):
        ctrl = RunControl()
        prev_term = signal.getsignal(signal.SIGTERM)
        prev_int = signal.getsignal(signal.SIGINT)
        with ctrl.sigint():
            os.kill(os.getpid(), signal.SIGTERM)
            # delivered synchronously on the main thread: flagged, not dead
            assert ctrl.interrupted == "SIGTERM"
            with pytest.raises(PlanInterrupted, match="SIGTERM"):
                ctrl.check()
            # second delivery (either signal) = hard stop
            with pytest.raises(KeyboardInterrupt):
                os.kill(os.getpid(), signal.SIGTERM)
        # BOTH handlers restored on exit
        assert signal.getsignal(signal.SIGTERM) == prev_term
        assert signal.getsignal(signal.SIGINT) == prev_int

    def test_sigterm_mid_plan_yields_partial(self):
        """The one-shot CLI path end to end minus the process boundary:
        a SIGTERM delivered mid-search produces the structured partial
        result (the same PlanInterrupted -> partial -> exit-3 flow the
        deadline tests pin)."""
        cluster, apps, template = _small_problem()
        control = RunControl()
        fired = {"n": 0}

        def progress(msg):
            fired["n"] += 1
            if fired["n"] == 2:  # after the first candidate completed
                os.kill(os.getpid(), signal.SIGTERM)

        with control.sigint():
            plan = plan_capacity(
                cluster, apps, template, control=control, progress=progress
            )
        assert plan.partial and not plan.success
        assert "SIGTERM" in plan.message


class TestCheckpointTransientRetry:
    """Transient filesystem errors on the checkpoint write path get ONE
    jittered retry; ENOSPC stays an immediate loud failure (ISSUE 14
    satellite, durable/checkpoint.py `_retry_transient`)."""

    @staticmethod
    def _fail_replace(monkeypatch, errnos):
        """Make os.replace raise OSError(errnos[i]) on call i, delegating
        once the list is exhausted; returns the call recorder."""
        real = os.replace
        calls = {"n": 0}

        def fake(src, dst):
            i = calls["n"]
            calls["n"] += 1
            if i < len(errnos) and errnos[i] is not None:
                raise OSError(errnos[i], os.strerror(errnos[i]), dst)
            return real(src, dst)

        monkeypatch.setattr(os, "replace", fake)
        return calls

    def test_eintr_retries_once_then_succeeds(self, tmp_path, monkeypatch):
        import errno

        ck = PlanCheckpoint(str(tmp_path / "ck"), kind="binary", fingerprint="fp")
        calls = self._fail_replace(monkeypatch, [errno.EINTR])
        ck.put("cand", 0, verdict=np.asarray(1))
        # failed attempt + retry + manifest rewrite
        assert calls["n"] == 3
        monkeypatch.undo()
        rd = PlanCheckpoint(
            str(tmp_path / "ck"), kind="binary", fingerprint="fp", resume=True
        )
        assert int(rd.get("cand", 0)["verdict"]) == 1

    def test_rename_race_enoent_retries_once(self, tmp_path, monkeypatch):
        import errno

        ck = PlanCheckpoint(str(tmp_path / "ck"), kind="binary", fingerprint="fp")
        calls = self._fail_replace(monkeypatch, [errno.ENOENT])
        ck.put("cand", 1, verdict=np.asarray(7))
        assert calls["n"] == 3
        monkeypatch.undo()
        rd = PlanCheckpoint(
            str(tmp_path / "ck"), kind="binary", fingerprint="fp", resume=True
        )
        assert int(rd.get("cand", 1)["verdict"]) == 7

    def test_enospc_immediate_loud_no_retry(self, tmp_path, monkeypatch):
        import errno

        from simtpu.durable import CheckpointError

        ck = PlanCheckpoint(str(tmp_path / "ck"), kind="binary", fingerprint="fp")
        calls = self._fail_replace(
            monkeypatch, [errno.ENOSPC] * 10
        )
        with pytest.raises(CheckpointError, match="[Nn]o space left"):
            ck.put("cand", 0, verdict=np.asarray(1))
        # exactly ONE attempt: a full disk never retries
        assert calls["n"] == 1

    def test_persistent_transient_surfaces_one_line(self, tmp_path, monkeypatch):
        import errno

        from simtpu.durable import CheckpointError

        ck = PlanCheckpoint(str(tmp_path / "ck"), kind="binary", fingerprint="fp")
        calls = self._fail_replace(
            monkeypatch, [errno.EINTR] * 10
        )
        with pytest.raises(CheckpointError, match="failed twice"):
            ck.put("cand", 0, verdict=np.asarray(1))
        assert calls["n"] == 2  # one retry, then the loud line
        err_line = None
        try:
            ck.put("cand", 0, verdict=np.asarray(1))
        except CheckpointError as exc:
            err_line = str(exc)
        assert err_line is not None and "\n" not in err_line

    def test_non_transient_oserror_propagates_untouched(
        self, tmp_path, monkeypatch
    ):
        import errno

        ck = PlanCheckpoint(str(tmp_path / "ck"), kind="binary", fingerprint="fp")
        calls = self._fail_replace(monkeypatch, [errno.EACCES])
        with pytest.raises(OSError) as ei:
            ck.put("cand", 0, verdict=np.asarray(1))
        assert ei.value.errno == errno.EACCES
        assert calls["n"] == 1  # no retry for non-transient classes
