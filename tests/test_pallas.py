"""Equivalence tests for the fused Pallas resource kernel
(`simtpu/kernels/pallas_fused.py`) against the reference jnp kernels it fuses
(resources_fit + least_allocated + balanced_allocation + simon_share). Runs
under `interpret=True` on the CPU test topology — the same kernel body that
compiles on TPU.
"""

from __future__ import annotations

import numpy as np
import pytest

from simtpu.kernels.filters import resources_fit
from simtpu.kernels.pallas_fused import (
    fused_fit_score,
    to_kernel_layout,
)
from simtpu.kernels.scores import (
    balanced_allocation,
    least_allocated,
    simon_share,
)


def _random_problem(n, r, seed):
    rng = np.random.default_rng(seed)
    alloc = rng.uniform(0.0, 64.0, (n, r)).astype(np.float32)
    alloc[rng.uniform(size=(n, r)) < 0.1] = 0.0  # some unallocated resources
    free = (alloc * rng.uniform(0.0, 1.0, (n, r))).astype(np.float32)
    req = rng.uniform(0.0, 8.0, r).astype(np.float32)
    req[rng.uniform(size=r) < 0.3] = 0.0
    return free, alloc, req


@pytest.mark.parametrize("n,r", [(96, 3), (1000, 7), (2048, 2)])
def test_fused_matches_reference_kernels(n, r):
    free, alloc, req = _random_problem(n, r, seed=n + r)
    tile = 512
    free_t, alloc_t = to_kernel_layout(free, alloc, tile_n=tile)
    fit, lb, dom = fused_fit_score(free_t, alloc_t, req, n_res=r, tile_n=tile, interpret=True)
    fit, lb, dom = np.asarray(fit)[:n], np.asarray(lb)[:n], np.asarray(dom)[:n]

    want_fit = np.asarray(resources_fit(free, req))
    want_lb = np.asarray(least_allocated(free, alloc, req) + balanced_allocation(free, alloc, req))
    want_dom = np.asarray(simon_share(alloc, req))

    np.testing.assert_array_equal(fit, want_fit)
    np.testing.assert_allclose(lb, want_lb, rtol=1e-5, atol=1e-4)
    np.testing.assert_allclose(dom, want_dom, rtol=1e-5, atol=1e-4)


def test_pad_columns_are_inert():
    # pad columns have alloc=0/free=0/req broadcast: fit must come back True
    # there only if req==0 — either way the engine's static mask excludes them
    free, alloc, req = _random_problem(100, 4, seed=9)
    req[:] = np.maximum(req, 0.5)  # nonzero request
    free_t, alloc_t = to_kernel_layout(free, alloc, tile_n=512)
    fit, _, _ = fused_fit_score(free_t, alloc_t, req, n_res=4, tile_n=512, interpret=True)
    assert not np.asarray(fit)[100:].any()
