"""Multi-process execution of the sharded engines (VERDICT r4 task 4; the
bulk-rounds variant is VERDICT r5 task 1's missing coverage).

`simtpu.parallel.mesh.initialize_multihost` is the DCN/multi-host analog of
the reference's in-process parallelism (SURVEY.md §2.3/§5): jax.distributed
wires N processes into one global device mesh.  Real TPU pods give each
process its own chips; here every process brings 4 virtual CPU devices, so
2 processes form an 8-device global mesh — the same shape the single-process
tests shard over.  The gate: a 2-process run must produce placements
IDENTICAL to the single-process sharded run (which is itself pinned to the
unsharded engine by test_parallel.py) — for BOTH the serial-equivalent
`ShardedEngine` and the bulk `ShardedRoundsEngine` (the engine behind the
mesh-sharded incremental planner).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

import pytest

from simtpu.api import simulate
from simtpu.parallel import ShardedEngine, ShardedRoundsEngine, make_mesh
from simtpu.synth import synth_apps, synth_cluster
from simtpu.workloads.expand import seed_name_hashes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tools", "multihost_worker.py")

ENGINES = {"scan": ShardedEngine, "rounds": ShardedRoundsEngine}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_reference(engine: str):
    cluster = synth_cluster(
        11, seed=21, zones=3, taint_frac=0.2, gpu_frac=0.3, storage_frac=0.3
    )
    apps = synth_apps(
        40,
        seed=22,
        zones=3,
        pods_per_deployment=8,
        selector_frac=0.3,
        toleration_frac=0.2,
        anti_affinity_frac=0.4,
        gpu_frac=0.2,
        storage_frac=0.2,
    )
    seed_name_hashes(0)
    mesh = make_mesh(sweep=1)
    engine_cls = ENGINES[engine]
    result = simulate(
        cluster,
        apps,
        extended_resources=("open-local", "gpu"),
        engine_factory=lambda t: engine_cls(t, mesh),
    )
    placements = {}
    for status in result.node_status:
        for pod in status.pods:
            meta = pod["metadata"]
            placements[f"{meta.get('namespace')}/{meta['name']}"] = pod["spec"][
                "nodeName"
            ]
    return placements, len(result.unscheduled_pods)


def _run_two_process(tmp_path, engine: str):
    out = tmp_path / f"multihost-{engine}.json"
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count (4 each)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port), str(out), engine],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    logs = []
    for p in procs:
        stdout, _ = p.communicate(timeout=600)
        logs.append(stdout)
    if any(p.returncode != 0 for p in procs) and any(
        "Multiprocess computations aren't implemented on the CPU backend" in log
        for log in logs
    ):
        # environment capability, not a product bug: this jax build's CPU
        # backend cannot run cross-process collectives at all (the
        # single-process mesh path is pinned by test_parallel.py); real
        # TPU/GPU pods are the intended multihost substrate
        pytest.skip("jax CPU backend lacks multiprocess collectives")
    assert all(p.returncode == 0 for p in procs), "\n---\n".join(logs)
    return json.loads(out.read_text())


@pytest.mark.slow
@pytest.mark.parametrize("engine", ["scan", "rounds"])
def test_two_process_run_matches_single_process(tmp_path, engine):
    """2 local processes x 4 virtual CPU devices == one 8-device mesh; the
    distributed placement must equal the single-process sharded one, for
    the serial-equivalent AND the bulk-rounds sharded engines."""
    data = _run_two_process(tmp_path, engine)
    assert data["process_count"] == 2
    assert data["global_devices"] == 8
    assert data["engine"] == engine
    placements, unscheduled = _single_process_reference(engine)
    assert data["placements"] == placements
    assert data["unscheduled"] == unscheduled
