"""Persistent-compilation-cache gating tests (`simtpu/cache.py`): the cache
must stay OFF on the CPU backend (the documented XLA:CPU deserialize
segfault), honor the env kill-switch, and say so on stderr either way —
cold-path triage must never have to guess whether the cache was silently
disabled.
"""

from __future__ import annotations

import simtpu.cache as cache_mod


def test_cpu_backend_leaves_cache_off(capsys, monkeypatch):
    # the test process runs on the CPU backend (conftest pins it), so the
    # accelerator-only gate must refuse without touching jax.config
    monkeypatch.delenv("SIMTPU_COMPILATION_CACHE", raising=False)
    called = []

    import jax

    monkeypatch.setattr(jax.config, "update", lambda *a: called.append(a))
    assert cache_mod.enable_compilation_cache() is None
    assert called == []  # never partially configured
    err = capsys.readouterr().err
    assert "persistent compilation cache off" in err
    assert "CPU backend" in err


def test_env_kill_switch_wins(capsys, monkeypatch):
    monkeypatch.setenv("SIMTPU_COMPILATION_CACHE", "off")
    assert cache_mod.enable_compilation_cache() is None
    err = capsys.readouterr().err
    assert "persistent compilation cache off" in err
    assert "SIMTPU_COMPILATION_CACHE=off" in err


def test_accelerator_backend_enables(tmp_path, capsys, monkeypatch):
    """With a non-CPU backend the cache configures and returns its dir (the
    jax.config writes are captured, not applied — this process IS on CPU)."""
    import jax

    monkeypatch.delenv("SIMTPU_COMPILATION_CACHE", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    updates = {}
    monkeypatch.setattr(
        jax.config, "update", lambda k, v: updates.__setitem__(k, v)
    )
    out = cache_mod.enable_compilation_cache(str(tmp_path / "xla"))
    assert out == str(tmp_path / "xla")
    assert updates["jax_compilation_cache_dir"] == out
    assert updates["jax_persistent_cache_min_compile_time_secs"] == 0.5
    assert "persistent compilation cache off" not in capsys.readouterr().err
